// Zoo — the runtime registry/singleton: owns the actors and the
// transport, routes messages, registers tables, answers barrier.
// Capability parity with include/multiverso/zoo.h (SURVEY.md §2.2, §3.1).
//
// Placement note (TPU-native design): the TPU data plane is XLA
// collectives over ICI/DCN (the Python/JAX layer); this native runtime is
// the HOST control/parity plane — a real actor pipeline with a real TCP
// transport (net.h).  With no machine file it runs the reference's
// Role::ALL single-process degenerate mode; with `-machine_file=F
// -rank=N` it becomes N cooperating processes: tables shard across the
// server roles (arrays by contiguous chunk, matrices by row block), the
// worker stubs partition requests per shard owner, and rank 0's
// controller answers the barrier — the reference's §3.1–§3.3 call stacks
// across OS processes.
#pragma once

#include <atomic>
#include <memory>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "mvtpu/actor.h"
#include "mvtpu/mutex.h"
#include "mvtpu/net.h"
#include "mvtpu/table.h"

namespace mvtpu {

class Waiter;

// Defined in c_api.cc: drops un-waited MV_GetAsync* tickets.  Zoo::Stop
// calls it before clearing the table registry the tickets point into.
void CApiReclaimAsyncGets();

class Zoo {
 public:
  static Zoo* Get();

  // argc/argv parsed through configure; spawns actors (+ transport when a
  // machine file names more than one process); idempotent.
  bool Start(int argc, const char* const* argv);
  void Stop();
  bool started() const { return started_.load(); }

  int rank() const { return rank_; }
  int size() const { return size_; }
  // Active wire engine name ("tcp" | "epoll" | "mpi" | "uring"), or
  // "local" when this is a single process with no transport
  // (docs/transport.md).  This is the EFFECTIVE engine: when
  // `-net_engine=uring` was requested but the kernel cannot run it,
  // Start degrades to epoll and this reports "epoll" (the health
  // report's `engine_requested`/`engine_fallback` fields record the
  // downgrade).
  const char* net_engine() const;
  // Anonymous serve-tier fan-in counters — nonzero only on the epoll
  // engine, the one that accepts non-rank client connections.
  Net::FanInStats FanIn() const;
  // Role bitmasks (reference Role enum): 1 = worker, 2 = server.
  // Static (machine-file) mode gives every rank both roles; dynamic
  // registration (-controller_endpoint/-role) can create worker-only or
  // server-only processes — tables shard across SERVER ranks only.
  static constexpr int kRoleWorker = 1;
  static constexpr int kRoleServer = 2;
  int num_workers() const { return static_cast<int>(worker_ranks_.size()); }
  int num_servers() const { return static_cast<int>(server_ranks_.size()); }
  // Index among the worker/server ranks, or -1 when this rank lacks the
  // role (matches the reference's worker_id/server_id semantics).
  int worker_id() const { return IndexIn(worker_ranks_, rank_); }
  int server_id() const { return IndexIn(server_ranks_, rank_); }
  // shard index -> global rank translation for the table layer.  With
  // replication armed this consults the VERSIONED ROUTING TABLE
  // (docs/replication.md): promotion/join bump the routing epoch and
  // re-point shards, so every request minted after the flip routes to
  // the live owner — the pre-replication behavior (server_ranks_[idx])
  // is the epoch-0 route.
  int server_rank(int idx) const;
  // Inverse over the ORIGINAL (registration-time) shard assignment —
  // the fallback attribution for replies carrying no shard hint.
  int server_index(int rank) const { return IndexIn(server_ranks_, rank); }

  // ---- shard replication + failover (docs/replication.md) ------------
  // Monotonic fleet routing epoch (0 = the registration-time route).
  int64_t RoutingEpoch() const {
    return routing_epoch_.load(std::memory_order_acquire);
  }
  std::vector<int> RouteOwners() const;
  std::vector<int> RouteBackups() const;
  // The shard index this rank BACKS (chained: server j backs shard
  // j-1 mod n), or -1 when replication is off / this rank backs none.
  int BackupShard() const;
  // The serving table instance for an inbound data-plane message: this
  // rank's own shard unless the message's shard hint names the shard
  // this rank backs (hedged backup reads pre-promotion, all traffic
  // post-promotion).
  ServerTable* RoutedServerTable(const Message& msg);
  ServerTable* backup_table(int32_t id);
  // Forward an applied add to the shard's backup rank (ReplForward).
  // Sync mode parks `*reply` (the client's prepared ReplyAdd) until
  // the backup's ReplAck and returns true — the caller must NOT send
  // it; async mode stalls at `-repl_lag_max` outstanding forwards.
  bool ForwardAddToBackup(const Message& req, MessagePtr* reply);
  void OnReplForward(MessagePtr msg);   // backup side, server actor
  void OnReplAck(MessagePtr msg);       // primary side, transport thread
  void OnShardSnapshot(MessagePtr msg); // both sides, server actor
  void OnRoutingEpoch(MessagePtr msg);  // transport thread, max-merge
  // Promote this rank's backup shard into serving for every shard
  // `dead_rank` owns; bumps + broadcasts the routing epoch.  Returns
  // the number of shards promoted (0 = this rank backs none of them).
  int PromoteFor(int dead_rank);
  // Elastic join: become shard `shard_idx`'s backup — create backup
  // tables from the registration specs, announce (epoch flip), then
  // pull whole-shard catch-up snapshots; deltas stream in behind the
  // snapshot on the same connection (FIFO).  Blocking; idempotent
  // (chaos re-runs re-pull the snapshots).
  bool JoinAsBackup(int shard_idx);
  std::string OpsReplicationJson();  // the "replication" OpsQuery kind

  // Blocks until every rank arrived; false when `-barrier_timeout_ms`
  // (default: infinite) expired or the barrier authority is unreachable.
  // On timeout the error names the unresponsive rank(s): rank 0 lists
  // the ranks that never announced arrival; other ranks name rank 0
  // (the authority whose release never came).
  bool Barrier();

  // ---- heartbeat / lease (docs/fault_tolerance.md) --------------------
  // With `-heartbeat_ms > 0` and size > 1, every non-zero rank sends a
  // Heartbeat to rank 0 each interval; rank 0's lease loop marks a peer
  // dead after `-heartbeat_timeout_ms` of silence (default 5 intervals),
  // logging the rank and counting Dashboard `hb.missed` — the job
  // LEARNS about the corpse instead of discovering it by hanging.
  void OnHeartbeat(int src_rank);      // controller actor inbound
  int DeadPeerCount();                 // rank 0: currently-expired leases
  std::vector<int> DeadPeers();

  // SSP (bounded staleness, SURVEY.md §2.9-bis): advance this worker's
  // clock and announce it to every server shard (async, FIFO behind this
  // clock's adds).  With `-staleness=s`, a server holds a worker's Get
  // while that worker is more than s ticks ahead of the slowest worker —
  // s=0 degenerates to per-clock rendezvous on read (BSP reads without
  // a full barrier); jobs that never Clock() are unaffected.
  void Clock();
  int64_t clock() const { return clock_; }
  // Server side: true = the get was parked until the SSP bound allows it
  // (the caller's handler must return without serving).
  bool MaybeHoldGet(MessagePtr& msg);
  void OnClockTick(int src_rank, int64_t clock);

  // ---- introspection plane (docs/observability.md, mvtpu/ops.h) ------
  // This rank's health verdict / per-table stats as JSON (the "health" /
  // "tables" sections of an OpsQuery report).
  std::string OpsHealthJson();
  std::string OpsTablesJson();
  // Workload plane (docs/observability.md): per-table hot-key top-K,
  // bucket-load skew ratio, observed staleness, and update-health
  // sentinels — the "hotkeys" OpsQuery kind / MV_HotKeys payload.
  // id >= 0 restricts to one table.
  std::string OpsHotKeysJson(int32_t id = -1);
  // Delivery-audit plane (docs/observability.md "audit plane"): per
  // table, the worker-side acked-add ledger (sent/acked per shard
  // stream) and the server-side delivery book (per-origin applied
  // watermark, dup/reorder/gap anomalies, pending out-of-order ranges)
  // plus per-bucket content checksums — the "audit" OpsQuery kind.
  std::string OpsAuditJson();
  // Capacity plane (docs/observability.md "capacity plane"): host proc
  // stats, arena/write-queue/registered byte gauges, and per-table
  // resident bytes per bucket + the bounded load-history ring — the
  // "capacity" OpsQuery kind, and tools/mvplan.py's input shape.
  std::string OpsCapacityJson();
  // Exact byte-accounting resync over every table shard (primary AND
  // backup) — the re-arm hook behind MV_SetCapacityTracking(1): drift
  // from disarmed inserts heals the moment tracking turns back on.
  void RecomputeCapacityAll();
  // Run a fleet-scope aggregation SYNCHRONOUSLY from this rank (the
  // same bounded fan-out an inbound fleet OpsQuery triggers) — the
  // engine-agnostic entry point: on the blocking tcp engine, where no
  // anonymous scraper can connect, a rank can still assemble the fleet
  // view itself.  Single-process fleets report just this rank.
  std::string FleetReport(const std::string& kind);
  // OpsQuery routing (transport reader / reactor threads — NEVER the
  // actor mailbox, so a wedged server still answers its scrape).  Local
  // scope replies inline; fleet scope (version == 1) fans out to every
  // peer on a bounded detached thread (-ops_fleet_timeout_ms, capped by
  // -ops_inflight_max) and merges, marking silent ranks.
  void HandleOpsQuery(MessagePtr msg);
  void OnOpsReply(MessagePtr msg);   // fleet fan-out responses

  // ---- serve backpressure (docs/serving.md) ---------------------------
  // Current server-actor mailbox backlog (the inflight gauge MV_Serve-
  // QueueDepth exposes); 0 when the runtime is down.
  int ServeQueueDepth();
  // With `-server_inflight_max=N` > 0: when the backlog still queued
  // behind the request being processed reaches N, answer `msg` with a
  // retryable ReplyBusy (no table work) and return true.  Gets and
  // version probes only — adds are never shed ("no lost adds").
  bool ShedIfOverloaded(MessagePtr& msg);
  // Tail plane (docs/serving.md "tail"): true when `msg` is a read
  // that was hedge-cancelled or is past its propagated deadline — the
  // caller drops it at dequeue (counted serve.hedge.cancelled /
  // serve.deadline.shed; an anonymous client's reactor admission slots
  // settle through the transport).  Reads only — never call for adds.
  bool DropServeRead(MessagePtr& msg);

  // Deliver to a LOCAL actor's mailbox.
  void SendTo(const std::string& actor_name, MessagePtr msg);

  // Deliver to msg->dst's `actor_name` actor — local mailbox when dst is
  // this rank (or unset), the TCP transport otherwise (the Communicator
  // routing of SURVEY.md §2.6; inbound routing is RouteInbound).
  void Deliver(const std::string& actor_name, MessagePtr msg);

  int64_t NextMsgId() { return next_msg_id_.fetch_add(1); }

  // ---- table registry -------------------------------------------------
  int32_t RegisterArrayTable(int64_t size);
  int32_t RegisterMatrixTable(int64_t rows, int64_t cols);
  int32_t RegisterSparseMatrixTable(int64_t rows, int64_t cols);

 private:
  template <typename WorkerT>
  int32_t RegisterMatrixTableImpl(int64_t rows, int64_t cols);

  // Registration-time shape record: backup shards (chained at
  // registration or created by a live JoinAsBackup) are built from the
  // same spec with the PRIMARY's shard index, so ShardOf ranges agree.
  struct TableSpec {
    enum Kind { kArray, kMatrix, kSparseMatrix, kKV };
    Kind kind;
    int64_t rows = 0, cols = 0;
  };
  std::unique_ptr<ServerTable> MakeShard(const TableSpec& spec, int sid,
                                         int nservers);
  // Append the spec + (when replication is armed) the chained backup
  // instance for one newly registered table.  Caller holds tables_mu_.
  void RegisterBackupShard(const TableSpec& spec) REQUIRES(tables_mu_);

 public:
  int32_t RegisterKVTable();
  ServerTable* server_table(int32_t id);
  WorkerTable* worker_table(int32_t id);
  ArrayWorkerTable* array_worker(int32_t id);
  MatrixWorkerTable* matrix_worker(int32_t id);
  KVWorkerTable* kv_worker(int32_t id);

  UpdaterType updater_type() const { return updater_type_; }

  // ---- barrier plumbing (internal) ------------------------------------
  // Arrive/release messages carry a per-rank ROUND number (msg_id):
  // after a timed-out round k, a late round-k release must not free the
  // retry's round-k+1 waiter.  round = -1 forces the release (local
  // failure paths that already latched barrier_failed_).
  void OnBarrierArrive(int src_rank, int64_t round);
  void OnBarrierRelease(int64_t round = -1);
  void OnFlushReply(int64_t msg_id);    // per-server flush ack

 private:
  Zoo() = default;

  static int IndexIn(const std::vector<int>& v, int rank) {
    for (size_t i = 0; i < v.size(); ++i)
      if (v[i] == rank) return static_cast<int>(i);
    return -1;
  }

  void SetRoles(const std::vector<int>& roles);

  // Blocking: one RequestFlush per remote server shard, acked when that
  // server drained every earlier message on the same connection.
  // Always drains the add-aggregation buffers first (the flush marker
  // must ride behind the adds it certifies).
  bool FlushPipelines();

 public:
  // Drain every worker table's add-aggregation buffer onto the wire
  // (docs/wire_compression.md).  Called by FlushPipelines/Clock/Stop
  // and the MV_FlushAdds C API.
  void FlushWorkerAdds();

 private:

  void RouteInbound(Message&& m);       // transport reader threads

  // Atomic, not GUARDED_BY(mu_): started() is the C-API fast-path gate
  // (RequireStarted) and must not contend with Start/Stop.  It doubles
  // as the Stop latch — the first Stop flips it under mu_ and later
  // Stops return without touching the half-torn-down actors.
  std::atomic<bool> started_{false};
  Mutex mu_;              // lifecycle (Start/Stop) + actor pointers
  Mutex tables_mu_;       // table registry — actors query it mid-Stop, so
                          // it must never be held across a thread join
  std::atomic<int64_t> next_msg_id_{0};
  UpdaterType updater_type_ = UpdaterType::kDefault;

  // Phase-stable state (rank_, size_, role rank lists, net_,
  // updater_type_): written once during Start and cleared by the one
  // Stop that wins the started_ latch, both under mu_; every other
  // reader runs between Start and Stop where the values are immutable.
  // Deliberately NOT GUARDED_BY(mu_) — the hot paths (Deliver, shard
  // math, barrier fan-out) read them lock-free, and net_->Send must not
  // run under mu_ anyway.  The analyze build checks the mutex-guarded
  // state below; this block's discipline is the started_ protocol.
  int rank_ = 0;
  int size_ = 1;
  std::vector<int> worker_ranks_{0};   // ranks holding the worker role
  std::vector<int> server_ranks_{0};   // ranks holding the server role
  std::unique_ptr<Net> net_;  // TcpNet or MpiNet, per -net_type
  // Engine-degradation record (health plane): what `-net_engine` asked
  // for and whether Start had to fall back (uring probe failure →
  // epoll).  Set once in Start, read by OpsHealthJson.
  std::string engine_requested_;
  bool engine_fallback_ = false;

  std::unique_ptr<Actor> worker_actor_ GUARDED_BY(mu_);
  std::unique_ptr<Actor> server_actor_ GUARDED_BY(mu_);
  std::unique_ptr<Actor> controller_actor_ GUARDED_BY(mu_);

  std::vector<std::unique_ptr<ServerTable>> server_tables_
      GUARDED_BY(tables_mu_);
  std::vector<std::unique_ptr<WorkerTable>> worker_tables_
      GUARDED_BY(tables_mu_);

  // Barrier state: one outstanding barrier per rank; rank 0 tracks
  // arrivals PER RANK (a retry after an abandoned round must not double
  // count toward the quorum).  barrier_failed_ latches transport
  // failures so Barrier() reports them instead of a false release.
  // barrier_round_ is this rank's current round; barrier_rounds_ is the
  // rank-0 authority's record of each rank's latest announced round
  // (echoed in the release so stale releases are droppable).
  Mutex barrier_mu_;
  std::shared_ptr<Waiter> barrier_waiter_ GUARDED_BY(barrier_mu_);
  std::vector<bool> barrier_arrived_ GUARDED_BY(barrier_mu_);
  bool barrier_failed_ GUARDED_BY(barrier_mu_) = false;
  int64_t barrier_round_ GUARDED_BY(barrier_mu_) = 0;
  std::vector<int64_t> barrier_rounds_ GUARDED_BY(barrier_mu_);

  // SSP state: this rank's worker clock; server-side per-rank clock
  // vector + the gets parked until the staleness bound admits them.
  // Parks carry a deadline (rpc_timeout_ms at park time): a dead
  // straggler whose clock never advances must not grow held_gets_
  // without bound, so every park/tick event purges expired entries and
  // fails them fast with ReplyError (the caller sees rc=-3).
  std::atomic<int64_t> clock_{0};
  Mutex ssp_mu_;
  std::vector<int64_t> worker_clocks_ GUARDED_BY(ssp_mu_);
  std::vector<std::pair<int64_t, MessagePtr>> held_gets_
      GUARDED_BY(ssp_mu_);  // (deadline_ms, parked get)
  // Moves expired parks out for fail-fast replies.
  void PurgeExpiredHeldLocked(std::vector<MessagePtr>* expired)
      REQUIRES(ssp_mu_);
  void FailHeldGets(std::vector<MessagePtr> expired);
  bool HeldBySspLocked(int src) REQUIRES(ssp_mu_);  // admission predicate

  // Outstanding pipeline flushes (msg_id → waiter); acks notify under
  // flush_mu_ so a timed-out flush cannot race its waiter's teardown.
  Mutex flush_mu_;
  // mvlint: MV018-exempt(one waiter per outstanding FlushPipelines
  // round — bounded by caller concurrency, acks/timeouts drain it)
  std::unordered_map<int64_t, std::shared_ptr<Waiter>> flush_pending_
      GUARDED_BY(flush_mu_);

  // Fleet-scope OpsQuery state: msg_id -> collected per-rank payloads.
  // Fan-out threads are detached but counted (ops_inflight_); Stop
  // drains the counter bounded before tearing the transport down.
  struct OpsPending;
  void FleetOpsThread(int64_t id, Message query);
  // The shared fan-out+merge body of FleetOpsThread and FleetReport:
  // sends local-scope sub-queries under `id`, waits out the bounded
  // deadline, merges (rank labels / JSON ranks map, silent + dead
  // ranks explicit) and returns the report text.
  std::string FleetCollect(const std::string& kind, int64_t trace_id,
                           int64_t id);
  Mutex ops_mu_;
  // mvlint: MV018-exempt(bounded by -ops_inflight_max concurrent fleet
  // queries; the deadline wait erases each entry)
  std::unordered_map<int64_t, std::shared_ptr<OpsPending>> ops_pending_
      GUARDED_BY(ops_mu_);
  std::atomic<int> ops_inflight_{0};
  // Shed-storm detector (-shed_storm_threshold): consecutive sheds.
  std::atomic<long long> shed_streak_{0};
  std::atomic<bool> shed_storm_latched_{false};

  // Heartbeat/lease state.  The loop thread is started by Start (when
  // enabled) and joined by the Stop latch winner before actors die.
  // SYMMETRIC (docs/replication.md): every rank renews to every peer
  // and every rank scans its own lease table — a backup can trigger
  // promotion even when the corpse is rank 0 itself.
  void HeartbeatLoop();
  std::thread hb_thread_;
  std::atomic<bool> hb_running_{false};
  Mutex hb_mu_;
  std::vector<int64_t> hb_last_seen_ GUARDED_BY(hb_mu_);  // ms, all ranks
  std::vector<bool> hb_dead_ GUARDED_BY(hb_mu_);

  // ---- shard replication + failover state (docs/replication.md) ------
  // Versioned routing table: shard idx -> serving rank / backup rank.
  // Initialized from server_ranks_ at Start (epoch 0); promotion and
  // elastic joins mutate it under route_mu_ and broadcast the new map
  // tagged with the bumped epoch (receivers max-merge).
  std::atomic<int64_t> routing_epoch_{0};
  mutable Mutex route_mu_;
  std::vector<int> route_owner_ GUARDED_BY(route_mu_);
  std::vector<int> route_backup_ GUARDED_BY(route_mu_);
  int backup_shard_ GUARDED_BY(route_mu_) = -1;  // shard this rank backs
  std::vector<bool> promoted_ GUARDED_BY(route_mu_);  // by shard idx
  // Backup shard instances, parallel to server_tables_ (nullptr when
  // this rank backs nothing / the table predates a join).
  std::vector<std::unique_ptr<ServerTable>> backup_tables_
      GUARDED_BY(tables_mu_);
  std::vector<TableSpec> table_specs_ GUARDED_BY(tables_mu_);
  // Sync replication: client acks parked until the backup's ReplAck
  // (fwd msg_id -> prepared ReplyAdd), deadline-bounded so a dying
  // backup degrades to async acking instead of wedging clients.
  Mutex repl_mu_;
  struct ParkedAck {
    int64_t deadline_ms;
    MessagePtr reply;
  };
  // mvlint: MV018-exempt(deadline-bounded: ReleaseParkedAcks sweeps
  // expired parks every lease tick; outstanding count rides repl stats)
  std::unordered_map<int64_t, ParkedAck> parked_acks_ GUARDED_BY(repl_mu_);
  std::atomic<long long> repl_outstanding_{0};
  // Catch-up rendezvous: ShardSnapshot request msg_id -> waiter.
  // mvlint: MV018-exempt(one waiter per in-flight catch-up pull —
  // bounded by shard count, drained on reply/timeout)
  std::unordered_map<int64_t, std::shared_ptr<Waiter>> snapshot_pending_
      GUARDED_BY(repl_mu_);
  // Collision-free epoch allocation: epochs advance in strides of
  // kEpochStride with the bumping rank in the low bits, so two ranks
  // reacting to the same failure concurrently (a promotion here, a
  // backup-drop there) can never mint EQUAL epochs that then reject
  // each other's broadcast — the ordering is total and rank-salted.
  static constexpr int64_t kEpochStride = 1024;
  int64_t NextEpochLocked() REQUIRES(route_mu_) {
    int64_t e = (routing_epoch_.load(std::memory_order_relaxed) /
                     kEpochStride +
                 1) *
                    kEpochStride +
                rank_;
    routing_epoch_.store(e, std::memory_order_release);
    return e;
  }
  // Broadcast the current route map under `epoch` to every peer.
  void BroadcastRoutingEpoch(int64_t epoch, const std::vector<int>& owners,
                             const std::vector<int>& backups);
  // Drop serve-layer caches on a route flip (the epoch's clock-boundary
  // analog): snapshot under tables_mu_, invalidate outside it.
  void InvalidateWorkerCaches();
  // Release parked sync acks whose deadline passed (or all of them,
  // when the backup's lease expired) — the client must not wedge on a
  // dead backup; replication degrades, it never blocks the primary.
  void ReleaseParkedAcks(bool all);
  // Lease-expiry reaction: promote if the corpse owned our backed
  // shard; stop forwarding to it if it was our backup.
  void OnPeerDead(int rank);
};

}  // namespace mvtpu
