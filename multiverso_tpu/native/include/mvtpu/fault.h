// Fault — deterministic fault-injection hooks for the wire plane.
// The chaos suite (tests/test_fault.py, docs/fault_tolerance.md) scripts
// transport failures through this seam instead of hoping for real ones:
// drop / delay / duplicate a logical send, or fail individual write
// attempts so the retry/backoff path in TcpNet::Send is exercised on
// demand.  Configured through the C API (MV_SetFault*) or environment
// (MVTPU_FAULT_SEED, MVTPU_FAULT_{DROP,DELAY,DUP,FAIL_SEND},
// MVTPU_FAULT_DELAY_MS), deterministic under a seed.  Disabled (the
// default) the hooks are one relaxed atomic load — no behavior change,
// no counters.
#pragma once

#include <cstdint>

namespace mvtpu {

class Fault {
 public:
  enum class Action { kNone, kDrop, kDelay, kDuplicate };

  // Fast-path gate: false means every hook below is a no-op.
  static bool Enabled();

  // Consult once per LOGICAL message about to ship.  kDelay also fills
  // *delay_ms.  The caller owns acting on the verdict (and counting it
  // in the Dashboard at the site, so counter names stay with the code
  // they describe).
  static Action OnSend(int64_t* delay_ms);

  // Consult once per WRITE ATTEMPT: true = simulate a wire failure
  // (the caller treats it exactly like a failed ::send), which is what
  // drives the retry-then-succeed chaos scenario.
  static bool FailSendAttempt();

  // Consult once per server-side APPLY (ProcessGet/ProcessAdd): the
  // returned milliseconds (0 = none) are slept INSIDE the apply stage,
  // so the latency-attribution plane (docs/observability.md) can prove
  // it pins a seeded slowdown on `lat.stage.apply` rather than the
  // wire — the latdoctor acceptance scenario.  kind "apply_delay";
  // the shared "delay_ms" knob sets the length.
  static int64_t ApplyDelayMs();

  // Consult once per server-side RequestAdd: true = SILENTLY discard
  // the delivered add before it is applied or booked — the seeded
  // "real loss" the delivery-audit plane (docs/observability.md
  // "audit plane") must detect as an audit_gap; retry cannot absorb it
  // because the wire delivery succeeded.  kind "discard_apply".
  static bool DiscardApply();

  // kind: drop | delay | dup | fail_send (probability per op in [0,1]);
  // delay_ms sets the injected delay length.  Returns 0, -1 on unknown
  // kind / bad rate.
  static int Set(const char* kind, double rate);
  // Deterministic alternative to a probability: fire on exactly the
  // next n matching ops, then stop.  Same kinds as Set.
  static int SetBudget(const char* kind, long long n);
  static void SetSeed(uint64_t seed);
  static void Clear();  // back to fully disabled
};

}  // namespace mvtpu
