// Capacity plane — fleet-wide memory & bytes accounting
// (docs/observability.md, "capacity plane").
//
// ROADMAP item 2 (load-aware placement + live migration) needs a data
// substrate before anything can move: per-bucket resident BYTES and a
// load RATE CURVE, not just lifetime op totals.  This module is that
// substrate:
//
//  - an arm latch (`-capacity_enabled`, MV_SetCapacityTracking) in the
//    workload::Armed() tradition: disarmed, every hot-path accounting
//    hook is one relaxed atomic load;
//  - a process-wide named byte-gauge registry: subsystems that hold
//    bytes outside the table shards (HostArena, epoll write queues,
//    worker replica side tables, serve caches via the Python mirror)
//    register a callback and the "capacity" ops report enumerates them;
//  - /proc/self process stats (RSS, VmHWM, open fds, uptime) for the
//    host-level rows of the health + capacity reports;
//  - a bounded per-table load HISTORY ring (kHistoryWindows == the
//    metrics.py HISTORY_SNAPSHOTS discipline): each capacity scrape at
//    least `-capacity_history_ms` after the last appends one window of
//    (ts, gets, adds, bytes, per-bucket load), so a single scrape
//    yields per-bucket RATES — the advisor's (bytes x load rate) input
//    — instead of forcing every consumer to diff two scrapes.
#pragma once

#include <cstdint>
#include <functional>
#include <string>
#include <vector>

namespace mvtpu {
namespace capacity {

// Process-global arm switch (the `-capacity_enabled` flag, latched by
// Zoo::Start; MV_SetCapacityTracking toggles live).  Disarmed, every
// incremental hot-path hook is this one relaxed load.  Construction /
// snapshot-load walks are NOT gated — they are one-time full
// recomputes, and re-arming resyncs via ServerTable::RecomputeCapacity
// so counters never stay stale once tracking is on.
bool Armed();
void Arm(bool on);

// Per-entry overhead charged for one KV hash-map entry beside its key
// and value bytes (node + bucket amortization).  Part of the
// byte-accounting CONTRACT: ground-truth walks use the same constant,
// so "within 10%" in the acceptance gate measures the incremental
// bookkeeping, not allocator trivia.
constexpr int64_t kKVEntryOverhead = 64;

// ---- named byte gauges ------------------------------------------------
// A gauge is a callback returning CURRENT bytes held; registration is
// idempotent by name (latest wins — subsystems re-register across
// restarts).  Callbacks run at scrape time on the ops thread and must
// be cheap and lock-light.
using GaugeFn = std::function<long long()>;
void RegisterGauge(const std::string& name, GaugeFn fn);
void UnregisterGauge(const std::string& name);
// {"name":bytes,...} over every registered gauge.
std::string GaugesJson();

// ---- /proc/self process stats ----------------------------------------
struct ProcStats {
  long long rss_bytes = -1;     // VmRSS
  long long vm_hwm_bytes = -1;  // peak resident (VmHWM)
  long long open_fds = -1;      // entries in /proc/self/fd
  double uptime_s = 0.0;        // since this module loaded
};
ProcStats Proc();
std::string ProcJson();  // {"rss_bytes":..,"vm_hwm_bytes":..,...}

// ---- per-table load history ring --------------------------------------
// Bounded at kHistoryWindows windows per table (the HISTORY_SNAPSHOTS
// discipline); kLoadBuckets mirrors ServerTable::kVersionBuckets (the
// table layer static_asserts the two agree).
constexpr int kHistoryWindows = 64;
constexpr int kLoadBuckets = 64;

// True when at least `-capacity_history_ms` passed since the last
// recorded window (one shared clock for every table: a scrape records
// all tables or none, so windows align across tables).  Latches the
// new timestamp when due.
bool HistoryDue();
// Append one window for `table_id` (called per table when HistoryDue).
void RecordHistory(int32_t table_id, int64_t gets, int64_t adds,
                   int64_t bytes, const int64_t* bucket_load);
// JSON for one table:
//   {"windows":n,"span_ms":t,"get_rate":r,"add_rate":r,"bytes_rate":r,
//    "bucket_rate":[64 per-second rates],
//    "curve":[{"ts_ms":..,"gets":..,"adds":..,"bytes":..},...]}
// Rates are (newest - oldest) / span over the ring; absent (rate
// fields = null-free zero-window object) with fewer than two windows —
// consumers render '-' rather than a fake 0 (the mvtop discipline).
std::string HistoryJson(int32_t table_id);
// Drop every ring + the shared clock (test isolation / re-arm).
void ResetHistory();

}  // namespace capacity
}  // namespace mvtpu
