// Transport — the pluggable wire seam (docs/transport.md).
//
// The reference selects its transport (MPI vs ZMQ) behind one
// NetInterface (include/multiverso/net.h, SURVEY.md §2.17-2.18); this
// header is that seam grown one axis further: besides the WIRE (TCP vs
// MPI) the runtime now also picks the READINESS MODEL.  `-net_engine`
// chooses between
//
//   tcp    — TcpNet (net.h): blocking sockets, one reader thread per
//            accepted connection.  Simple, fine for a fixed rank fleet.
//   epoll  — EpollNet (epoll_net.h): an event-driven reactor (one epoll
//            loop, optionally `-net_threads` shards) driving
//            non-blocking sockets through per-connection read/write
//            state machines.  Scales to thousands of connections and is
//            the only engine that accepts ANONYMOUS (non-rank) serve
//            clients.  The default for TCP fleets.
//   mpi    — MpiNet (mpi_net.h): the literal MPI wire; rank/size come
//            from MPI itself, so it keeps its own Init shape.
//   uring  — UringNet (uring_net.h): the io_uring proactor — completion-
//            driven I/O, receive buffers registered with the kernel over
//            HostArena slabs, multishot accept for the anonymous tier,
//            zero-copy send completions.  Same message semantics as
//            epoll; zoo.cc degrades to epoll (with a logged reason and
//            an `effective_engine` health field) when the kernel lacks
//            io_uring.
#pragma once

#include <cstdint>
#include <functional>
#include <memory>
#include <string>
#include <vector>

#include "mvtpu/message.h"

namespace mvtpu {

// What the Zoo needs from a transport.
class Net {
 public:
  using InboundFn = std::function<void(Message&&)>;

  virtual ~Net() = default;

  // Serialize + ship to the peer; false on a dead/unreachable rank.
  virtual bool Send(int dst_rank, const Message& msg) = 0;
  virtual void Stop() = 0;
  virtual int rank() const = 0;
  virtual int size() const = 0;
  virtual const char* engine() const = 0;

  // Anonymous serve-tier fan-in counters (docs/transport.md): clients
  // are connections that carry no rank identity — only the epoll engine
  // accepts them; every other engine reports zeros.
  struct FanInStats {
    long long accepted_total = 0;  // anonymous connections ever accepted
    long long active_clients = 0;  // currently connected
    long long client_shed = 0;     // requests answered ReplyBusy by the
                                   // per-client admission gate
  };
  virtual FanInStats FanIn() const { return {}; }

  // Settle one per-client admission slot for an anonymous client whose
  // request was DROPPED server-side (deadline-expired or hedge-
  // cancelled read: no reply will ever route back to release it).
  // No-op on engines without anonymous clients.
  virtual void SettleClient(int client_rank) { (void)client_rank; }

  // Capacity plane (docs/observability.md): total bytes currently
  // parked on this engine's outbound write queues.  Only the epoll
  // engine queues frames (blocking engines hold none); the capacity
  // report's `net.writeq_bytes` gauge reads this.
  virtual long long QueuedBytes() const { return 0; }

  // Capacity plane (docs/observability.md): bytes currently held in
  // receive-side arenas — per-connection reassembly slabs on the epoll
  // engine, the registered buffer pool + heap fallback slabs on the
  // uring engine.  The `net.rx_arena_bytes` gauge reads this; blocking
  // engines buffer on the stack and report zero.
  virtual long long RxArenaBytes() const { return 0; }
};

namespace transport {

// Anonymous clients have no endpoint to connect back to, so the reactor
// assigns each accepted non-rank connection a PSEUDO-RANK at/above this
// base and routes Send(pseudo_rank) back over the accepted socket.
// Real ranks are always far below it, so routing stays a range check.
inline constexpr int kClientRankBase = 1 << 20;

inline bool IsClientRank(int r) { return r >= kClientRankBase; }

}  // namespace transport

// Machine-file/registration transports share one Init shape: endpoints
// are rank-indexed "host:port" strings, `rank` is this process's index,
// and every decoded inbound message is handed to `fn` (from reader or
// reactor threads).  MpiNet is NOT one of these — it derives rank/size
// from MPI itself.
class RankTransport : public Net {
 public:
  virtual bool Init(const std::vector<std::string>& endpoints, int rank,
                    InboundFn fn, int64_t connect_retry_ms = 15000) = 0;
};

// `-net_engine` factory ("tcp" | "epoll" | "uring"); nullptr on an
// unknown name.  "uring" requires uring::Probe() (uring_net.h) — the
// zoo checks it first and degrades to epoll with a logged reason.
std::unique_ptr<RankTransport> MakeRankTransport(const std::string& engine);

}  // namespace mvtpu
