// Waiter — counted latch used to block Get/Add until replies arrive.
// Capability parity with include/multiverso/util/waiter.h (SURVEY.md §2.23).
#pragma once

#include <chrono>
#include <condition_variable>
#include <mutex>

namespace mvtpu {

class Waiter {
 public:
  explicit Waiter(int count = 1) : count_(count) {}

  void Wait() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return count_ <= 0; });
  }

  // Deadline wait: true when the count reached zero, false on timeout.
  // timeout_ms <= 0 means wait forever (the reference's only mode).
  bool WaitFor(int64_t timeout_ms) {
    if (timeout_ms <= 0) {
      Wait();
      return true;
    }
    std::unique_lock<std::mutex> lk(mu_);
    return cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                        [&] { return count_ <= 0; });
  }

  void Notify() {
    // notify_all runs WHILE holding mu_: Waiters are stack-allocated by
    // their waiting caller (RoundTrip, Barrier), so notifying after the
    // unlock would race the waiter observing count_<=0, returning, and
    // destroying this object mid-notify (use-after-free).
    std::lock_guard<std::mutex> lk(mu_);
    --count_;
    cv_.notify_all();
  }

  void Reset(int count) {
    std::lock_guard<std::mutex> lk(mu_);
    count_ = count;
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  int count_;
};

}  // namespace mvtpu
