// Waiter — counted latch used to block Get/Add until replies arrive.
// Capability parity with include/multiverso/util/waiter.h (SURVEY.md §2.23).
#pragma once

#include <chrono>
#include <cstdint>

#include "mvtpu/mutex.h"

namespace mvtpu {

class Waiter {
 public:
  explicit Waiter(int count = 1) : count_(count) {}

  void Wait() {
    MutexLock lk(mu_);
    while (count_ > 0) cv_.Wait(mu_);
  }

  // Deadline wait: true when the count reached zero, false on timeout.
  // timeout_ms <= 0 means wait forever (the reference's only mode).
  bool WaitFor(int64_t timeout_ms) {
    if (timeout_ms <= 0) {
      Wait();
      return true;
    }
    auto deadline = std::chrono::system_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    MutexLock lk(mu_);
    while (count_ > 0) {
      if (!cv_.WaitUntil(mu_, deadline)) return count_ <= 0;
    }
    return true;
  }

  void Notify() {
    // notify_all runs WHILE holding mu_: the waiting caller (RoundTrip,
    // Barrier) may drop its reference right after observing count_<=0,
    // so notifying after the unlock could run on a destroyed object.
    // Waiters are heap-allocated (shared_ptr) by every caller: TSan's
    // mutex shadow state is flushed on free, whereas a stack slot
    // reused by the next call's waiter resurrects the old mutex
    // identity in gcc-10's libtsan.
    MutexLock lk(mu_);
    --count_;
    cv_.NotifyAll();
  }

  void Reset(int count) {
    MutexLock lk(mu_);
    count_ = count;
  }

 private:
  Mutex mu_;
  CondVar cv_;
  int count_ GUARDED_BY(mu_);
};

}  // namespace mvtpu
