// Tail-at-scale serve-tier QoS plane (docs/serving.md "tail").
//
// Three mechanisms, all behind version-tolerant wire stamps:
//
// 1. **Per-tenant weighted admission** — anonymous serve clients declare
//    a tenant class (a QosStamp in the wire header; the class id is a
//    POSITIONAL index into `-qos_classes`, e.g. "bulk:1,gold:8"), and
//    the epoll reactor's admission path becomes weighted deficit-round-
//    robin over per-class inflight budgets: each class owns
//    `cap * weight / sum(weights)` of the `-qos_inflight_max` read
//    slots outright, and spare capacity is borrowed in weight
//    proportion via per-class deficit credit.  A bulk herd at its cap
//    answers ReplyBusy at the reactor while gold reads keep flowing;
//    adds and flushes are never shed.  Per-class admit/shed counters
//    land in the Dashboard (serve.qos.{admit,shed}.<class>) and thus
//    the "metrics" ops kind.
//
// 2. **Deadline propagation** — requests carry their remaining deadline
//    budget (QosStamp::budget_ns, stamped from the caller's timeout);
//    the receiver converts it to a local monotonic deadline at frame
//    receipt (wire time corrected via the PR 11 per-peer clock-offset
//    estimate when one exists), and the reactor + server actor drop an
//    already-expired read at dequeue (serve.deadline.shed[.<class>])
//    instead of burning an apply slot on an answer nobody is waiting
//    for.  Adds are never deadline-shed.
//
// 3. **Hedge-cancel registry** — a hedged read's loser is cancelled
//    with a fire-and-forget RequestCancel token consumed AT THE
//    REACTOR (it overtakes the mailbox FIFO the loser is parked in);
//    the actor drops a cancelled read at dequeue
//    (serve.hedge.cancelled).
//
// Disarmed (`-qos_inflight_max=0`, no stamps on the wire), every hook
// below is a relaxed load or a no-op — the <1% fast-path bar.
#pragma once

#include <cstdint>
#include <string>

#include "mvtpu/message.h"

namespace mvtpu {
namespace qos {

// (Re)latch the class table + budgets from the flags (-qos_classes,
// -qos_inflight_max, -qos_class, -wire_deadline).  Called by Zoo::Start
// so per-process flag choices win; safe to call again (test isolation —
// counters reset).
void Configure();
// Drop counters + inflight + cancel registry (test isolation).
void Reset();

int NumClasses();
// Positional class id for a name in -qos_classes; -1 when unknown.
int ClassId(const std::string& name);
// Name for a class id ("?" when out of range).
std::string ClassName(int klass);

// Weighted deficit-round-robin admission over per-class inflight read
// budgets.  True (and the slot held) when admitted; false = shed with
// ReplyBusy.  Always true when -qos_inflight_max <= 0 (disabled).
// Counts serve.qos.admit.<class> / serve.qos.shed.<class>.
bool TryAdmit(int klass);
// Settle one admitted read slot (reply sent, or the read was dropped
// at dequeue).  Floors at zero per class.
void Release(int klass);

// ---- deadline propagation --------------------------------------------
// Worker-side: stamp the request's class (-qos_class) and remaining
// budget (from -rpc_timeout_ms) behind msgflag::kHasQos.  No-op when
// -wire_deadline=false or the timeout is unbounded.
void StampRequest(Message* m);
// Receiver-side (transport recv path, right after latency::StampRecv):
// convert the wire budget into a local monotonic deadline in
// m->qos_deadline_ns, correcting for wire time via the per-peer clock
// offset when the timing trail + an offset estimate exist.
void AdoptDeadline(Message* m);
// True when the message's adopted deadline has passed — the caller
// drops the read and must Release() its admission slot if it held one.
// Counts serve.deadline.shed and serve.deadline.shed.<class>.
bool ShedExpired(const Message& m);
// Deadline sheds observed so far (the mvtop/latdoctor surface).
long long DeadlineSheds();

// ---- hedge-cancel registry -------------------------------------------
// Note a fire-and-forget cancel token for (src, msg_id); bounded ring —
// the oldest token is evicted past capacity.
void NoteCancel(int32_t src, int64_t msg_id);
// Consume a token: true exactly once per noted (src, msg_id).
bool Cancelled(int32_t src, int64_t msg_id);

// {"classes":[{name,weight,budget,inflight,admits,sheds,
//   deadline_sheds}...],"inflight_max":N,"deadline_shed":N,
//  "cancels_noted":N,"cancelled":N} — the "latency" ops kind's "qos"
// section (mvtop --qos renders it).
std::string Json();

}  // namespace qos
}  // namespace mvtpu
