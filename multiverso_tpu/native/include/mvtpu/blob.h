// Blob — ref-counted byte buffer with typed views.
// Capability parity with the reference's include/multiverso/blob.h
// (SURVEY.md §2.4): the unit of message payload. Implemented fresh on
// shared_ptr instead of a hand-rolled refcount.
#pragma once

#include <cstddef>
#include <cstring>
#include <memory>
#include <vector>

namespace mvtpu {

class Blob {
 public:
  Blob() = default;
  explicit Blob(size_t size) : data_(std::make_shared<std::vector<char>>(size)) {}
  Blob(const void* src, size_t size)
      : data_(std::make_shared<std::vector<char>>(size)) {
    std::memcpy(data_->data(), src, size);
  }

  // Zero-copy view into a shared slab (the receive-arena path,
  // docs/transport.md): shares ownership of `owner` but exposes only
  // [off, off+len).  The slab cannot be freed or overwritten while any
  // view is alive — the arena checks use_count() before reusing it —
  // so a view is as safe as an owning Blob, without the copy.
  static Blob View(std::shared_ptr<std::vector<char>> owner, size_t off,
                   size_t len) {
    Blob b;
    b.data_ = std::move(owner);
    b.off_ = off;
    b.len_ = len;
    b.is_view_ = true;
    return b;
  }

  // Borrowed EXTERNAL memory (the host-bridge send path,
  // docs/host_bridge.md): a non-owning window over caller-owned bytes —
  // a HostArena buffer — with a release hook.  `keepalive`'s deleter
  // fires when the last shallow copy of this blob dies (the message was
  // sent / locally processed and destroyed), which is how the arena
  // learns the wire is done with the buffer.  The bytes must stay alive
  // and UNCHANGED until then; the arena defers recycling to make the
  // caller's Release() unconditionally safe.  Paths that must mutate or
  // outlive the payload (codec encode, aggregation) never borrow — they
  // produce fresh owning blobs (copy-on-conflict).
  static Blob Borrow(const void* ptr, size_t len,
                     std::shared_ptr<void> keepalive) {
    Blob b;
    b.ext_ = static_cast<const char*>(ptr);
    b.len_ = len;
    b.keepalive_ = std::move(keepalive);
    return b;
  }
  bool borrowed() const { return ext_ != nullptr; }

  size_t size() const {
    if (ext_) return len_;
    return is_view_ ? len_ : (data_ ? data_->size() : 0);
  }
  char* data() {
    if (ext_) return const_cast<char*>(ext_);
    return data_ ? data_->data() + (is_view_ ? off_ : 0) : nullptr;
  }
  const char* data() const {
    if (ext_) return ext_;
    return data_ ? data_->data() + (is_view_ ? off_ : 0) : nullptr;
  }

  template <typename T>
  T* As() { return reinterpret_cast<T*>(data()); }
  template <typename T>
  const T* As() const { return reinterpret_cast<const T*>(data()); }
  template <typename T>
  size_t count() const { return size() / sizeof(T); }

  // Shallow copy shares the buffer (the reference Blob's refcount
  // semantics); CopyFrom deep-copies (views and borrows flatten to
  // owning blobs — the borrow's keepalive drops here).
  void CopyFrom(const Blob& other) {
    data_ = std::make_shared<std::vector<char>>(
        other.data(), other.data() + other.size());
    off_ = 0;
    len_ = 0;
    is_view_ = false;
    ext_ = nullptr;
    keepalive_.reset();
  }

 private:
  std::shared_ptr<std::vector<char>> data_;
  size_t off_ = 0;   // view window (is_view_ only)
  size_t len_ = 0;   // view / borrow length
  bool is_view_ = false;
  const char* ext_ = nullptr;        // borrowed external base (or null)
  std::shared_ptr<void> keepalive_;  // borrow release hook (host_arena.h)
};

}  // namespace mvtpu
