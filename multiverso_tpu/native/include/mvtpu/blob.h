// Blob — ref-counted byte buffer with typed views.
// Capability parity with the reference's include/multiverso/blob.h
// (SURVEY.md §2.4): the unit of message payload. Implemented fresh on
// shared_ptr instead of a hand-rolled refcount.
#pragma once

#include <cstddef>
#include <cstring>
#include <memory>
#include <vector>

namespace mvtpu {

class Blob {
 public:
  Blob() = default;
  explicit Blob(size_t size) : data_(std::make_shared<std::vector<char>>(size)) {}
  Blob(const void* src, size_t size)
      : data_(std::make_shared<std::vector<char>>(size)) {
    std::memcpy(data_->data(), src, size);
  }

  size_t size() const { return data_ ? data_->size() : 0; }
  char* data() { return data_ ? data_->data() : nullptr; }
  const char* data() const { return data_ ? data_->data() : nullptr; }

  template <typename T>
  T* As() { return reinterpret_cast<T*>(data()); }
  template <typename T>
  const T* As() const { return reinterpret_cast<const T*>(data()); }
  template <typename T>
  size_t count() const { return size() / sizeof(T); }

  // Shallow copy shares the buffer (the reference Blob's refcount
  // semantics); CopyFrom deep-copies.
  void CopyFrom(const Blob& other) {
    data_ = std::make_shared<std::vector<char>>(
        other.data(), other.data() + other.size());
  }

 private:
  std::shared_ptr<std::vector<char>> data_;
};

}  // namespace mvtpu
