// Workload sketches — bounded-memory hot-key accounting for the server
// hot path (docs/observability.md, "workload plane").
//
// Multiverso's native workloads (word embedding, LightLDA, recommender
// serving) are huge sparse tables under heavily skewed access.  The
// systems plane (PRs 3/7) can say how LONG an apply took; nothing said
// WHICH keys were hot.  These two classic sketches answer that in O(1)
// per touched key with memory bounded by construction:
//
//  - SpaceSaving (Metwally et al. 2005): top-K heavy hitters.  K
//    counters; an unmonitored key evicts the minimum counter and
//    inherits its count as `error` — every true heavy hitter with
//    frequency > N/K is guaranteed to be monitored, and
//    count - error <= true <= count.
//  - CountMin (Cormode & Muthukrishnan 2005): depth x width counter
//    array, per-row hashes; Estimate() = min over rows.  Never
//    underestimates; overestimates by at most eps * N with probability
//    1 - delta for width = e/eps, depth = ln(1/delta).  Answers "how
//    hot is ARBITRARY key k", including keys SpaceSaving evicted.
//
// HotKeyTracker combines both per server table, armed by the
// `-hotkey_enabled` flag (mirrored into one process-global atomic so a
// disarmed ProcessGet/ProcessAdd pays exactly one relaxed load).
#pragma once

#include <atomic>
#include <cstdint>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

#include "mvtpu/mutex.h"

namespace mvtpu {
namespace workload {

// Process-global arm switch (the `-hotkey_enabled` flag, latched by
// Zoo::Start and togglable at runtime via MV_SetHotKeyTracking for
// armed-vs-disarmed A/B measurement).  Disarmed, every accounting hook
// compiles down to this one relaxed atomic load.
bool Armed();
void Arm(bool on);

// Hot-key replica arm switch (docs/embedding.md): latched from
// `-hotkey_replica` at Zoo::Start, togglable live via
// MV_SetHotKeyReplica.  Disarmed, the worker GetRows replica probe is
// one relaxed atomic load (the same discipline as Armed()).
bool ReplicaArmed();
void ArmReplica(bool on);

// Stable 64-bit key hash shared with the Python mirror
// (multiverso_tpu/sketch.py) so per-rank sketches merge coherently:
// FNV-1a, the same function KVHash uses for the partition contract.
uint64_t KeyHash(const void* data, size_t n);
inline uint64_t KeyHash(const std::string& s) {
  return KeyHash(s.data(), s.size());
}
inline uint64_t KeyHash(int64_t v) { return KeyHash(&v, sizeof(v)); }

// ---------------------------------------------------------------------
// SpaceSaving top-K.  NOT internally synchronized — the owning
// HotKeyTracker serializes access under its own mutex.
class SpaceSaving {
 public:
  explicit SpaceSaving(int k);

  struct Entry {
    std::string label;   // human-readable key (row id / KV key)
    uint64_t hash = 0;
    int64_t count = 0;   // upper bound on the true frequency
    int64_t error = 0;   // inherited overcount: true >= count - error
  };

  // O(1) expected: bump a monitored key, or evict the minimum counter
  // and inherit its count as the new key's error.
  void Offer(uint64_t hash, const std::string& label, int64_t n = 1);
  // Monitored entries, descending by count.
  std::vector<Entry> TopK() const;
  int64_t total() const { return total_; }
  int capacity() const { return k_; }
  // Fold another sketch in (fleet-scope / per-rank merges): offers every
  // entry of `other` carrying its count; errors add conservatively.
  void Merge(const SpaceSaving& other);

 private:
  int FindMin() const;
  int k_;
  int64_t total_ = 0;
  std::vector<Entry> entries_;                  // <= k_ monitored keys
  // hash -> slot in entries_ (size <= k_; evictions retarget one key).
  std::unordered_map<uint64_t, int> index_;
  int IndexOf(uint64_t hash) const;
};

// ---------------------------------------------------------------------
// CountMin.  Counter cells are relaxed atomics: Add/Estimate are
// lock-free (a torn read can only mis-estimate one sample, which the
// sketch's own eps bound already dwarfs).
class CountMin {
 public:
  explicit CountMin(int width = 1024, int depth = 4);
  CountMin(const CountMin&) = delete;
  CountMin& operator=(const CountMin&) = delete;

  void Add(uint64_t hash, int64_t n = 1);
  int64_t Estimate(uint64_t hash) const;     // min over rows; never under
  int64_t total() const {
    return total_.load(std::memory_order_relaxed);
  }
  int width() const { return width_; }
  int depth() const { return depth_; }

 private:
  uint64_t RowHash(int row, uint64_t hash) const;
  int width_, depth_;
  std::vector<std::atomic<int64_t>> cells_;   // depth_ * width_
  std::atomic<int64_t> total_{0};
};

// ---------------------------------------------------------------------
// Per-table tracker: one SpaceSaving (sized from `-hotkey_topk` at
// first armed offer) + one CountMin, behind one small mutex on the
// SpaceSaving side only.  All entry points no-op on a single atomic
// load when disarmed.
class HotKeyTracker {
 public:
  HotKeyTracker();

  // O(1): offer one touched key to both sketches.
  void Note(uint64_t hash, const std::string& label, int64_t n = 1);

  struct Item {
    std::string label;
    int64_t count;      // SpaceSaving upper bound
    int64_t error;      // SpaceSaving inherited overcount
    int64_t estimate;   // CountMin estimate for the same key
  };
  std::vector<Item> TopK() const;
  int64_t Estimate(uint64_t hash) const { return cm_.Estimate(hash); }
  int64_t total() const { return cm_.total(); }
  // JSON fragment: {"total":N,"topk":[{"key":..,"count":..,...},...]}
  std::string Json() const;

 private:
  mutable Mutex mu_;
  // Lazily sized from -hotkey_topk (flags may not be parsed when a
  // standalone table constructs the tracker).
  std::unique_ptr<SpaceSaving> ss_ GUARDED_BY(mu_);
  CountMin cm_;
};

}  // namespace workload
}  // namespace mvtpu
