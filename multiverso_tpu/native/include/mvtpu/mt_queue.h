// MtQueue — blocking MPMC queue; every actor's mailbox.
// Capability parity with include/multiverso/util/mt_queue.h (SURVEY.md §2.22).
#pragma once

#include <deque>
#include <utility>

#include "mvtpu/mutex.h"

namespace mvtpu {

template <typename T>
class MtQueue {
 public:
  void Push(T item) {
    {
      MutexLock lk(mu_);
      q_.push_back(std::move(item));
    }
    cv_.NotifyOne();
  }

  // Blocks until an item arrives or Exit() is called.
  // Returns false iff exited and drained.
  bool Pop(T* out) {
    MutexLock lk(mu_);
    while (q_.empty() && !exit_) cv_.Wait(mu_);
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    return true;
  }

  bool TryPop(T* out) {
    MutexLock lk(mu_);
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    return true;
  }

  void Exit() {
    {
      MutexLock lk(mu_);
      exit_ = true;
    }
    cv_.NotifyAll();
  }

  size_t Size() const {
    MutexLock lk(mu_);
    return q_.size();
  }

 private:
  mutable Mutex mu_;
  CondVar cv_;
  std::deque<T> q_ GUARDED_BY(mu_);
  bool exit_ GUARDED_BY(mu_) = false;
};

}  // namespace mvtpu
