// MtQueue — blocking MPMC queue; every actor's mailbox.
// Capability parity with include/multiverso/util/mt_queue.h (SURVEY.md §2.22).
#pragma once

#include <condition_variable>
#include <deque>
#include <mutex>
#include <utility>

namespace mvtpu {

template <typename T>
class MtQueue {
 public:
  void Push(T item) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      q_.push_back(std::move(item));
    }
    cv_.notify_one();
  }

  // Blocks until an item arrives or Exit() is called.
  // Returns false iff exited and drained.
  bool Pop(T* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return !q_.empty() || exit_; });
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    return true;
  }

  bool TryPop(T* out) {
    std::lock_guard<std::mutex> lk(mu_);
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    return true;
  }

  void Exit() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      exit_ = true;
    }
    cv_.notify_all();
  }

  size_t Size() const {
    std::lock_guard<std::mutex> lk(mu_);
    return q_.size();
  }

 private:
  mutable std::mutex mu_;
  std::condition_variable cv_;
  std::deque<T> q_;
  bool exit_ = false;
};

}  // namespace mvtpu
