// Flat extern-C surface for FFI bindings.
// Capability parity with include/multiverso/c_api.h (SURVEY.md §2.19):
// init/shutdown/barrier, ids, array + matrix tables with sync and async
// Add variants. float32 payloads (the reference's binding-facing type).
// All functions return 0 on success, negative on error, unless noted:
// -1 bad args / not started, -2 unknown handle, -3 unreachable peer or
// `-rpc_timeout_ms`/`-barrier_timeout_ms` deadline expired (fail-fast
// instead of hanging on a dead rank), -4 shard (de)serialization
// failed, -5 local stream open failed (an IO problem, NOT peer death),
// -6 a server SHED the request under `-server_inflight_max`
// backpressure (docs/serving.md) — retryable after backoff, and unlike
// -3 it is NOT indeterminate: the server did no work, -7 a *Borrowed
// call's buffer is not (entirely) inside a live HostArena buffer
// (docs/host_bridge.md) — nothing was sent.
// A -3 from a DEADLINE is indeterminate, not at-most-once: a slow
// server may still apply the Add after the caller gave up (a blind
// retry can double-apply), and a timed-out Get's output buffer may be
// partially filled.  Treat -3 as "state unknown": re-Get before
// deciding whether to re-Add.
// Contract-checked: tools/mvcontract.py (`make contract`) parses the
// rc map above and every prototype below, and diffs them against the
// ctypes binding and the Lua cdef — a new entry point must land with
// its Python side or tier-1 fails.
#pragma once

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

int MV_Init(int argc, const char* const* argv);
int MV_ShutDown();
int MV_Barrier();
// SSP (bounded staleness): advance this worker's clock.  With
// `-staleness=s`, a server holds this worker's Gets while it is more
// than s clocks ahead of the slowest worker (released as peers Clock;
// the rpc deadline still bounds the wait).  s=0 = read-side per-clock
// rendezvous (BSP reads without a barrier).
int MV_Clock();
int MV_NumWorkers();
int MV_WorkerId();
int MV_ServerId();

// Flags (reference configure surface).
int MV_SetFlag(const char* name, const char* value);

// Tables. handle := table id (>=0).
int MV_NewArrayTable(int64_t size, int32_t* handle);
int MV_GetArrayTable(int32_t handle, float* data, int64_t size);
int MV_AddArrayTable(int32_t handle, const float* delta, int64_t size);
int MV_AddAsyncArrayTable(int32_t handle, const float* delta, int64_t size);

int MV_NewMatrixTable(int64_t rows, int64_t cols, int32_t* handle);
// Sparse variant: worker-side row cache (hits skip the wire until this
// worker Adds the row or a barrier closes the clock).  Same Get/Add
// functions as the plain matrix table.
int MV_NewSparseMatrixTable(int64_t rows, int64_t cols, int32_t* handle);
int MV_GetMatrixTableAll(int32_t handle, float* data, int64_t size);
int MV_AddMatrixTableAll(int32_t handle, const float* delta, int64_t size);
int MV_AddAsyncMatrixTableAll(int32_t handle, const float* delta, int64_t size);
int MV_GetMatrixTableByRows(int32_t handle, float* data, const int32_t* row_ids,
                            int64_t num_rows, int64_t cols);
int MV_AddMatrixTableByRows(int32_t handle, const float* delta,
                            const int32_t* row_ids, int64_t num_rows,
                            int64_t cols);
int MV_AddAsyncMatrixTableByRows(int32_t handle, const float* delta,
                                 const int32_t* row_ids, int64_t num_rows,
                                 int64_t cols);

// Async Gets (reference WorkerTable::GetAsync + Wait, SURVEY.md §2.10):
// the pull is on the wire when the call returns; *wait_handle receives
// a ticket for MV_WaitGet, which blocks until every contacted shard
// replied (0), or returns -3 on dead shard / deadline — indeterminate
// like every -3 above (the buffer may be partially filled).  The output
// buffer must stay alive and untouched until MV_WaitGet returns, which
// also frees the ticket (a second wait on it returns -2).  A ticket the
// caller will never wait on MUST be released with MV_CancelGet before
// its output buffer dies — cancelling withdraws the in-flight request
// so a late shard reply cannot scatter into freed memory (the ctypes
// binding does this from the handle's destructor).  Tickets neither
// waited nor cancelled are reclaimed at MV_ShutDown.  On a sparse
// matrix table the async path goes straight to the wire (no row-cache
// read or install).
int MV_GetAsyncArrayTable(int32_t handle, float* data, int64_t size,
                          int32_t* wait_handle);
int MV_GetAsyncMatrixTableByRows(int32_t handle, float* data,
                                 const int32_t* row_ids, int64_t num_rows,
                                 int64_t cols, int32_t* wait_handle);
int MV_WaitGet(int32_t wait_handle);
int MV_CancelGet(int32_t wait_handle);  // 0, or -2 unknown/consumed

// ---- host-bridge fast path (docs/host_bridge.md) ---------------------
// Pinned buffer arena: recycled 64-byte-aligned host buffers whose
// bytes the *Borrowed calls below ship ZERO-COPY into the scatter-
// gather send path (Blob borrows instead of copies).  Ownership
// contract: a buffer is caller-held from MV_ArenaAcquire until
// MV_ArenaRelease; in-flight borrowed sends add native holds, and the
// buffer is recycled only when BOTH are gone — releasing mid-flight is
// always safe (the recycle defers), but MUTATING the bytes before the
// in-flight send drains is the caller's bug.  rc: 0, -1 bad args /
// allocation failure, -2 double release.
int MV_ArenaAcquire(int64_t bytes, void** ptr);
int MV_ArenaRelease(void* ptr);
// Arena accounting (any pointer may be NULL): live buffers, recycled
// free-list depth, total arena bytes, buffers with in-flight borrows,
// releases that had to defer behind a borrow, Acquires served from the
// free list, and buffers successfully mlock'd (-arena_pin).
int MV_ArenaStats(long long* buffers, long long* free_buffers,
                  long long* bytes, long long* in_flight,
                  long long* deferred, long long* recycled,
                  long long* pinned);

// Borrowed siblings of the Add/Get calls above: `delta`/`data` MUST lie
// inside a live arena buffer (rc -7 otherwise — the call does nothing;
// Borrowed calls fail loudly rather than silently copying).  Adds ship
// the caller's bytes straight into the sendmsg iovecs — no intermediate
// Blob copy; the arena defers the buffer's recycle until the wire (or
// the local server apply) is done with it.  Codec-encoded tables
// (1bit/sparse) and the add-aggregation buffer take ownership by
// copying exactly where they must mutate (copy-on-conflict).  Gets
// land replies directly in `data` as always; the Borrowed variants
// additionally validate the destination and — for the async forms —
// hold the arena buffer until MV_WaitGet/MV_CancelGet consumes the
// ticket, so an early MV_ArenaRelease cannot recycle a buffer a late
// shard reply could still scatter into.
int MV_AddArrayTableBorrowed(int32_t handle, const float* delta,
                             int64_t size);
int MV_AddAsyncArrayTableBorrowed(int32_t handle, const float* delta,
                                  int64_t size);
int MV_GetArrayTableBorrowed(int32_t handle, float* data, int64_t size);
int MV_GetAsyncArrayTableBorrowed(int32_t handle, float* data,
                                  int64_t size, int32_t* wait_handle);
int MV_AddMatrixTableAllBorrowed(int32_t handle, const float* delta,
                                 int64_t size);
int MV_AddAsyncMatrixTableAllBorrowed(int32_t handle, const float* delta,
                                      int64_t size);
int MV_AddMatrixTableByRowsBorrowed(int32_t handle, const float* delta,
                                    const int32_t* row_ids,
                                    int64_t num_rows, int64_t cols);
int MV_AddAsyncMatrixTableByRowsBorrowed(int32_t handle,
                                         const float* delta,
                                         const int32_t* row_ids,
                                         int64_t num_rows, int64_t cols);
int MV_GetAsyncMatrixTableByRowsBorrowed(int32_t handle, float* data,
                                         const int32_t* row_ids,
                                         int64_t num_rows, int64_t cols,
                                         int32_t* wait_handle);

// KV table (string key -> float value; SURVEY.md §2.14).  Batch calls
// take keys as concatenated NUL-FREE bytes with per-key lengths.
int MV_NewKVTable(int32_t* handle);
int MV_GetKV(int32_t handle, const char* key, float* value);
int MV_AddKV(int32_t handle, const char* key, float delta);
int MV_AddAsyncKV(int32_t handle, const char* key, float delta);
int MV_GetKVBatch(int32_t handle, const char* keys, const int32_t* key_lens,
                  int64_t num_keys, float* values);
int MV_AddKVBatch(int32_t handle, const char* keys, const int32_t* key_lens,
                  int64_t num_keys, const float* deltas);

// Per-call hyper-parameters for subsequent Add* on this thread
// (reference AddOption-in-message).
int MV_SetAddOption(float learning_rate, float momentum, float rho, float eps);

// Checkpoint one table to / from a local file.
int MV_StoreTable(int32_t handle, const char* path);
int MV_LoadTable(int32_t handle, const char* path);

// Dashboard report as a malloc'd C string; caller frees with MV_FreeString.
char* MV_DashboardReport();
void MV_FreeString(char* s);
// One monitor's hit count (0 when the monitor never fired) — how the
// chaos suite asserts `net.retries` / `net.dropped` / `hb.missed`.
int MV_QueryMonitor(const char* name, long long* count);

// ---- observability (docs/observability.md) ---------------------------
// EVERY Dashboard monitor in one call (the enumeration the Python
// metrics registry bridges instead of name-by-name MV_QueryMonitor):
// one line per monitor, tab-separated
//   name \t count \t total_s \t max_s \t b0,b1,...,b27
// where bucket i counts observations <= 1e-6 * 2^i seconds (the last
// bucket is +inf) — enough to reconstruct p50/p95/p99 host-side.
// malloc'd; caller frees with MV_FreeString.
char* MV_DumpMonitors(void);
// Span recording: with tracing on, every monitored op (worker Get/Add,
// server apply, wire send) records a wall-clock span tagged with a
// trace id that PROPAGATES through message headers — a worker Get and
// its server-side apply on another rank share the id.  `-trace=true`
// arms it at MV_Init; these toggle it at runtime.
int MV_SetTraceEnabled(int on);
// Pin this thread's trace id for subsequent ops (0 = auto per-op ids);
// lets a host-side tracer stitch native spans under its own span.
int MV_SetTraceId(long long trace_id);
// All recorded spans, one line each, tab-separated
//   name \t trace_id \t ts_us \t dur_us \t rank \t tid
// (ts_us is wall-clock, so per-rank dumps merge onto one timeline).
// malloc'd; caller frees with MV_FreeString.
char* MV_DumpSpans(void);
int MV_ClearSpans(void);

// ---- introspection plane (docs/observability.md; mvtpu/ops.h) --------
// This rank's ops report text — the SAME payload the wire serves for an
// in-band MsgType::OpsQuery.  kind: "metrics" (Prometheus exposition:
// the host-pushed registry rendering when present, else the native
// Dashboard with per-bucket exemplar trace ids) | "health" (JSON
// verdict: queue depth vs -server_inflight_max, lease state, fan-in
// counters) | "tables" (JSON per-table version / bucket-version spread /
// codec / agg depth).  malloc'd; caller frees with MV_FreeString.
char* MV_OpsReport(const char* kind);
// Push the host (Python) metrics registry's Prometheus rendering so
// in-band scrapes serve the full superset (the PR 3 registry already
// bridges every native monitor).  The metrics flush thread calls this
// each interval.  NULL or empty clears the push (native fallback).
int MV_SetOpsHostMetrics(const char* prom_text);
// Push the host (Python) health evaluator's alert state (JSON object
// text) so the in-band `"alerts"` OpsQuery kind serves it under its
// "host" key beside the native watchdog table.  The health flush hook
// calls this each metrics flush.  NULL or empty clears the push
// (served as null).
int MV_SetOpsHostAlerts(const char* alerts_json);
// Flight recorder ("black box"): record one lifecycle event into the
// bounded in-memory ring (-blackbox_events), and/or trigger a dump of
// ring + recent spans + monitor totals to
// <trace_dir>/blackbox_rank<r>.json.  Native failure paths (barrier
// timeout, dead peer, shed storm) trigger automatically; these let the
// host layer add its own events/triggers (e.g. CheckpointCorrupt).
int MV_BlackboxEvent(const char* kind, const char* detail);
int MV_BlackboxTrigger(const char* reason);

// ---- workload observability (docs/observability.md) ------------------
// Per-table hot-key / shard-load report as JSON — the same payload the
// in-band `"hotkeys"` OpsQuery kind serves: for each server table,
// get/add totals, per-bucket load skew (max bucket / mean bucket),
// space-saving top-K hot keys with count-min estimates, observed-
// staleness stats, and the add L2/Linf + NaN/Inf health sentinels.
// handle >= 0 restricts to one table; < 0 reports every table.
// malloc'd; caller frees with MV_FreeString.
char* MV_HotKeys(int32_t handle);
// Numeric slice of the same accounting for one table (any output
// pointer may be NULL): served gets/adds, bucket-load skew ratio, the
// accumulated add L2 norm / max |element|, and NaN/Inf counts.  rc 0,
// -1 not started, -2 bad handle or no local shard on this rank.
int MV_TableLoadStats(int32_t handle, long long* gets, long long* adds,
                      double* skew_ratio, double* add_l2,
                      double* add_linf, long long* nan_count,
                      long long* inf_count);
// Toggle the workload accounting live (the `-hotkey_enabled` flag is
// the boot-time value): disarmed, every hot-path hook is one relaxed
// atomic check — the armed-vs-disarmed A/B behind the bench_skew
// overhead bar.
int MV_SetHotKeyTracking(int on);
// Fleet-scope ops report assembled BY THIS RANK over the rank wire
// (the same bounded fan-out + merge an inbound fleet OpsQuery runs) —
// works on every engine, including the blocking tcp engine that
// refuses anonymous scraper connections.  Any ops kind ("metrics" |
// "health" | "tables" | "hotkeys" | "latency" | "audit" |
// "replication" | "capacity" | "alerts").  malloc'd; caller frees
// with MV_FreeString.
char* MV_OpsFleetReport(const char* kind);

// ---- capacity plane (docs/observability.md "capacity plane") ---------
// This rank's capacity report as JSON — the same payload the in-band
// `"capacity"` OpsQuery kind serves: /proc/self process stats (RSS,
// VmHWM, open fds, uptime), arena + write-queue + registered byte
// gauges, and per table the shard's resident bytes/rows per bucket,
// per-bucket get/add load counters, the bounded load-history ring
// (rate curves), worker-side replica/agg/cache bytes as their OWN
// fields (never folded into shard counts), and backup-shard bytes.
// tools/mvplan.py bin-packs placement proposals over the fleet scrape.
// malloc'd; caller frees with MV_FreeString.
char* MV_CapacityReport(void);
// Toggle the byte accounting live (boot value: the `-capacity_enabled`
// flag).  Disarmed, every hot-path growth hook is one relaxed atomic
// check; re-arming resyncs every shard with an exact walk, so counters
// are accurate whenever tracking is on.
int MV_SetCapacityTracking(int on);

// ---- latency attribution plane (docs/observability.md) ---------------
// Toggle wire-header timing trails live (boot value: `-wire_timing`,
// default ON).  Armed, every worker request carries six monotonic
// stage stamps (client enqueue/send, server recv/dequeue/apply_done/
// reply_send); replies echo + extend the trail, and the client folds
// each round trip into lat.stage.{queue,wire_out,mailbox,apply,
// reactor,wire_back} + lat.total Dashboard histograms (PR 7 exemplars
// included) and the per-peer clock-offset estimator.  The "latency"
// OpsQuery kind / MV_OpsReport("latency") serves the JSON breakdown.
int MV_SetWireTiming(int on);
// Toggle the delivery-audit plane live (boot value: `-audit`, default
// ON; docs/observability.md "audit plane").  Armed, every worker Add
// carries a per-(worker, table, shard) seq range behind a wire flag,
// ReplyAdd acks echo it into the client acked-add ledger, and server
// tables keep per-origin applied watermarks + dup/reorder/gap anomaly
// rings with an `audit_gap` flight-recorder trigger past
// `-audit_grace_ms`.  The "audit" OpsQuery kind / MV_OpsReport("audit")
// serves the JSON books; tools/mvaudit.py diffs them fleet-wide.
int MV_SetAudit(int on);
// Best current NTP-style clock-offset estimate for a peer rank:
// *offset_ns is how far the peer's monotonic clock runs ahead of this
// process's; *rtt_ns the minimum observed round trip backing it.
// Estimated from every timed request/reply AND the PR 2 heartbeat
// echo.  rc 0; -1 not started / bad args; -2 no timed round trip to
// that rank completed yet.
int MV_ClockOffset(int rank, long long* offset_ns, long long* rtt_ns);
// Sampling profiler (SIGPROF, CPU-time): hz > 0 (re)arms at that rate,
// hz <= 0 stops.  Boot value: the `-profile_hz` flag.  rc 0, -1 when
// the timer/handler could not be installed.
int MV_SetProfiler(int hz);
// Folded-stack aggregation of everything sampled so far — one line per
// distinct stack, "outer;...;leaf count\n" (the flamegraph folded
// convention; multiverso_tpu/profiler.py lands it in the Chrome trace
// beside the spans).  malloc'd; caller frees with MV_FreeString.
char* MV_ProfilerDump(void);
// Drop recorded samples (per-phase A/B runs, test isolation).
int MV_ProfilerClear(void);

// ---- health plane: stall watchdog (docs/observability.md) ------------
// Arm the native stall watchdog at `stall_ms` (<= 0 disarms; boot
// value: the `-watchdog_stall_ms` flag).  Armed, every critical loop
// (epoll reactor shards, actors, heartbeat scan, plus host loops via
// MV_WatchdogBump/Busy) that makes zero progress for stall_ms while
// work is queued gets flagged: `watchdog.stalls` bumps, a
// "stall: <loop> no progress for Nms, queue=D" blackbox event lands
// beside the profiler's folded stacks, and a blackbox dump triggers.
// stall_ms must exceed the slowest legitimate loop period.  rc 0.
int MV_SetWatchdog(int stall_ms);
// One unit of progress on a HOST loop (e.g. "py.flush", the Python
// metrics flusher) — registers the loop on first use; no-op disarmed.
int MV_WatchdogBump(const char* loop);
// Declare a host loop's queued work; 0 = idle (an idle loop cannot
// stall).  no-op disarmed.
int MV_WatchdogBusy(const char* loop, long long queued);
// Per-loop watchdog table as a JSON array — the same payload the
// `"alerts"` OpsQuery kind serves under "watchdog": loop name,
// progress, queued, stalls, stalled flag, seconds since progress.
// malloc'd; caller frees with MV_FreeString.
char* MV_WatchdogStats(void);

// ---- hot-key read replica (docs/embedding.md) ------------------------
// Toggle replica-served matrix row reads live (the `-hotkey_replica`
// flag is the boot value).  Armed, MatrixWorkerTable::GetRows consults
// a worker-local side table of the servers' pushed SpaceSaving top-K
// rows BEFORE the wire; invalidation rides the version-stamp protocol
// (entries older than last_version - `-replica_max_staleness` miss),
// and the snapshot re-pulls past `-replica_lease_ms`.
int MV_SetHotKeyReplica(int on);
// Force one replica refresh round trip (RequestReplica to every shard)
// for a matrix table.  rc 0, -1 not started, -2 not a matrix table,
// -3 dead shard / deadline, -6 shed (retryable).
int MV_ReplicaRefresh(int32_t handle);
// Replica ledger for a matrix table (any output pointer may be NULL):
// rows served from the replica (hits), rows that went to the wire
// (misses), rows currently held, refresh round trips, and this rank's
// server-side push count.  rc 0, -1 not started, -2 not a matrix table.
int MV_ReplicaStats(int32_t handle, long long* hits, long long* misses,
                    long long* rows, long long* refreshes,
                    long long* pushes);

// ---- serve layer (docs/serving.md) -----------------------------------
// Version probe: one header-only round trip filling *version with the
// max CURRENT version over every server shard of the table — the cheap
// alternative to a full fetch when a client must validate a cached
// copy.  Every server-side apply bumps the table's monotonic version
// (row/key adds bump per-bucket versions; replies stamp the version
// covering the data they serve).  rc: 0 / -1 / -2 / -3 / -6.
int MV_TableVersion(int32_t handle, long long* version);
// The highest version stamp observed in ANY reply to this process's
// worker stub (Get payloads and blocking-Add acks) — a FREE local
// lower bound on the server version, no wire traffic.
int MV_LastVersion(int32_t handle, long long* version);
// Native worker-side cache counters (the sparse matrix row cache):
// calls fully served from cache vs calls that paid a wire fetch
// (Dashboard serve.cache.hit / serve.cache.miss).
int MV_CacheStats(long long* hits, long long* misses);
// Current server-actor mailbox backlog — the queue-depth gauge behind
// `-server_inflight_max` shedding.  >= 0; -1 when not started.
int MV_ServeQueueDepth(void);

// ---- fault injection (mvtpu/fault.h; docs/fault_tolerance.md) --------
// Chaos hooks on the wire plane, deterministic under MV_SetFaultSeed.
// kinds: "drop" | "delay" | "dup" | "fail_send" (probability in [0,1]),
// plus "delay_ms" whose `rate` sets the injected delay length.
// MV_SetFaultN fires on exactly the next n matching ops instead of by
// probability.  All return 0, -1 on unknown kind / bad rate.  With no
// faults configured (the default) the hooks are a single atomic load.
int MV_SetFault(const char* kind, double rate);
int MV_SetFaultN(const char* kind, long long n);
int MV_SetFaultSeed(long long seed);
int MV_ClearFaults(void);

// Heartbeat failure detection (`-heartbeat_ms`): number of peers whose
// liveness lease is currently expired ON THIS RANK.  Lease watching is
// SYMMETRIC (docs/replication.md): every rank tracks every peer, so a
// backup can self-trigger promotion even when rank 0 is the corpse.
int MV_DeadPeerCount(void);

// ---- shard replication + failover (docs/replication.md) --------------
// Live toggle for the primary->backup forward stream (the bench's
// armed-vs-disarmed overhead A/B); the chained backup assignment
// itself is latched from -replication_factor at MV_Init.
int MV_SetReplication(int on);
// Current fleet routing epoch (0 = the registration-time shard map;
// every promotion/join bumps and broadcasts it).
long long MV_RoutingEpoch(void);
// The rank currently serving shard `shard_idx` per the routed map, or
// -1 when out of range.
int MV_ShardOwner(int shard_idx);
// The shard index this rank BACKS (chained or joined), -1 for none.
int MV_BackupShard(void);
// Promote this rank's backup shard(s) for `dead_rank` into serving —
// the operator-driven twin of lease-triggered auto-promotion.
// Returns the number of shards promoted.
int MV_PromoteBackup(int dead_rank);
// Elastic join: become shard `shard_idx`'s backup — creates backup
// instances, announces via a routing-epoch flip, and pulls whole-shard
// catch-up snapshots (blocking; idempotent, so chaos re-runs re-pull).
// 0 on success, -1 not started / refused, -3 catch-up failed.
int MV_ReplJoin(int shard_idx);
// Replication ledger: forwards/acks (primary side), applied (backup
// side), currently outstanding forwards, promotions + epoch flips,
// post-failover dup-skipped replays, and catch-up snapshot installs.
// Any output pointer may be NULL.
int MV_ReplicationStats(long long* forwards, long long* acks,
                        long long* applied, long long* outstanding,
                        long long* promotions, long long* epoch_flips,
                        long long* dup_skips, long long* catchups);

// ---- transport (docs/transport.md) -----------------------------------
// Active (EFFECTIVE) wire engine name: "tcp" | "epoll" | "mpi" |
// "uring", or "local" for a single process with no transport.  When
// `-net_engine=uring` was requested on a kernel that cannot run it,
// Start degrades to epoll and this reports "epoll".  malloc'd; caller
// frees with MV_FreeString.
char* MV_NetEngine(void);
// 1 when THIS kernel can run the io_uring engine (io_uring_setup plus
// every opcode the data plane needs), 0 otherwise.  Callable before
// MV_Init — it probes the kernel, not the session (the uring test
// suites gate on it).
int MV_UringSupported(void);
// Anonymous serve-tier fan-in counters: connections accepted without a
// rank identity (external serve clients), how many are currently
// connected, and how many of their requests the per-client admission
// gate (`-client_inflight_max`) answered ReplyBusy.  Nonzero only on
// the epoll engine; any output pointer may be NULL.
int MV_FanInStats(long long* accepted_total, long long* active_clients,
                  long long* client_shed);

// ---- wire data plane (docs/wire_compression.md) ----------------------
// Retarget one table's wire codec: "raw" | "1bit" (sign bits + two
// scales per message, worker-side error feedback so the quantization
// loss re-enters the next add) | "sparse" (lossless nonzero
// index/value pairs, per-message raw fallback when not smaller).
// Tables start on the `-wire_codec` flag's value.  -1 on an unknown
// codec name, -2 on a bad handle.
int MV_SetTableCodec(int32_t handle, const char* codec);
// Drain the add-aggregation buffer (`-add_agg_ms`/`-add_agg_bytes`) of
// one table — or of EVERY table when handle < 0 — onto the wire.
// Get/Clock/Barrier/shutdown flush implicitly; this is the explicit
// trigger ("Flush" in the aggregation contract).
int MV_FlushAdds(int32_t handle);
// Transport byte/message ledger: total wire bytes and frames this
// process sent/received (TcpNet + MpiNet, headers included).  The
// counters behind the Python `net.bytes{dir=...}`/`net.msgs` bridge;
// any output pointer may be NULL.
int MV_WireStats(long long* sent_bytes, long long* recv_bytes,
                 long long* sent_msgs, long long* recv_msgs);

#ifdef __cplusplus
}
#endif
