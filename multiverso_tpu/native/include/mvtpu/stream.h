// Byte streams for checkpoints — local filesystem flavor.
// Capability parity with include/multiverso/io/ (SURVEY.md §2.27); the
// HDFS flavor is delegated to the Python layer's fsspec seam.
#pragma once

#include <cstdio>
#include <memory>
#include <string>

namespace mvtpu {

class Stream {
 public:
  virtual ~Stream() = default;
  virtual size_t Write(const void* buf, size_t size) = 0;
  virtual size_t Read(void* buf, size_t size) = 0;
  virtual bool Good() const = 0;
};

class LocalStream : public Stream {
 public:
  LocalStream(const std::string& path, const char* mode);
  ~LocalStream() override;
  size_t Write(const void* buf, size_t size) override;
  size_t Read(void* buf, size_t size) override;
  bool Good() const override { return f_ != nullptr; }

 private:
  FILE* f_ = nullptr;
};

class StreamFactory {
 public:
  // "file:///path" or plain path → LocalStream; unknown scheme → nullptr.
  static std::unique_ptr<Stream> Open(const std::string& uri, const char* mode);
};

}  // namespace mvtpu
