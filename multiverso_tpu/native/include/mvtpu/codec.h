// Payload codecs for the native wire (docs/wire_compression.md).
//
// The reference parameter server ships every Add/Get payload as raw
// fp32 — 32 bits per element.  Its DMTK lineage made its name partly on
// 1-bit SGD gradient compression with error feedback (Seide et al.
// 2014); this module brings that wire format (plus a lossless sparse
// form) to the native transport:
//
// - kOneBit: sign bit per element + two per-message scales (mean of the
//   positive and of the negative bucket).  ~32x fewer payload bytes;
//   lossy per message, convergent under SGD because the WORKER keeps
//   the quantization error as a residual that re-enters the next add.
// - kSparse: (index, value) pairs of the nonzero elements — lossless,
//   used when it is actually smaller (the encoder falls back to kRaw
//   otherwise, so the per-MESSAGE codec stamp is authoritative).
//
// Encoding happens worker-side on the LAST blob of an Add request (the
// float delta; AddOption/row-id blobs stay raw); the server decodes
// before ProcessAdd, and Get replies may be sparse-encoded when the
// requester's accept flags allow it — so the table layer on both sides
// only ever sees raw float payloads.
#pragma once

#include <string>
#include <vector>

#include "mvtpu/blob.h"
#include "mvtpu/message.h"

namespace mvtpu {
namespace codec {

// raw | 1bit | sparse.  Unknown names map to kRaw (callers validate
// with IsCodecName first — the C API returns -1 on an unknown name).
Codec FromName(const std::string& name);
bool IsCodecName(const std::string& name);
const char* Name(Codec c);
// The msgflag:: accept bit advertising this codec (kAcceptRaw for kRaw).
int32_t AcceptFlag(Codec c);

// 1-bit encode of n floats.  Layout:
//   [int64 n][float pos_scale][float neg_scale][uint8 bits[(n+7)/8]]
// bit i (LSB-first within each byte) set means element i decodes to
// pos_scale, clear to neg_scale.  `residual` (may be null) is the
// caller's error-feedback buffer for these n elements: it is ADDED to
// the delta before quantization and overwritten with what the
// reconstruction lost — the sender must feed the same buffer to the
// next encode of the same elements or 1-bit SGD diverges.  Non-finite
// inputs are treated as 0 and their residual is reset to 0 (a NaN must
// not poison the scales or ride the feedback loop forever).
Blob EncodeOneBit(const float* delta, size_t n, float* residual);
bool DecodeOneBit(const Blob& in, std::vector<float>* out);

// Sparse encode of n floats.  Layout:
//   [int64 n][int64 k][int32 idx[k]][float val[k]]
// Lossless for every stored element (values copied bit-exact, so
// NaN/Inf survive); exact zeros are dropped (-0.0 decodes as +0.0).
// Returns an EMPTY blob when the sparse form would not be smaller than
// raw — the caller then ships kRaw.
Blob EncodeSparse(const float* delta, size_t n);
bool DecodeSparse(const Blob& in, std::vector<float>* out);

// Decode msg->data.back() in place per msg->codec (no-op for kRaw);
// resets the stamp to kRaw on success.  False on a malformed payload —
// the caller must drop the message rather than feed garbage to a table.
bool DecodeInPlace(Message* msg);

// Server-side reply hook: when the requester accepts kSparse and the
// reply's single payload blob is mostly zeros, swap it for the sparse
// form and stamp reply->codec.  No-op (and no scan) when the accept
// flags carry only kAcceptRaw — raw-codec tables pay nothing.
void MaybeEncodeReply(Message* reply, int32_t accept_flags);

}  // namespace codec
}  // namespace mvtpu
