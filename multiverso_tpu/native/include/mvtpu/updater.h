// Server-side updaters applied per Add.
// Capability parity with include/multiverso/updater/ (SURVEY.md §2.16):
// default(add)/sgd/adagrad/momentum/smooth_gradient selected by
// -updater_type, hyper-parameters carried per call in AddOption.
// Math matches the Python/JAX updaters bit-for-bit in float32 so the two
// control planes are interchangeable.
#pragma once

#include <cmath>
#include <cstddef>
#include <string>
#include <vector>

namespace mvtpu {

struct AddOption {
  float learning_rate = 0.1f;
  float momentum = 0.9f;
  float rho = 0.9f;
  float eps = 1e-8f;
  int32_t worker_id = -1;
};

enum class UpdaterType : int { kDefault = 0, kSGD, kAdaGrad, kMomentum,
                               kSmoothGradient,
                               // assign: w = delta (last-write-wins) — the
                               // "put" of the offload bridge
                               // (docs/host_bridge.md): remotely stored
                               // optimizer/embedding state round-trips
                               // bit-exactly because the server stores the
                               // pushed float32 bits verbatim instead of
                               // accumulating into them.
                               kAssign };

inline int NumSlots(UpdaterType t) {
  return (t == UpdaterType::kAdaGrad || t == UpdaterType::kMomentum ||
          t == UpdaterType::kSmoothGradient)
             ? 1
             : 0;
}

// Returns kDefault for unknown names (caller validates via IsUpdaterName).
UpdaterType UpdaterFromName(const std::string& name);
bool IsUpdaterName(const std::string& name);

// Apply `delta[0..n)` to `w[offset..offset+n)` with per-element state slot.
void ApplyUpdate(UpdaterType t, const AddOption& opt, float* w, float* slot0,
                 const float* delta, size_t n);

}  // namespace mvtpu
