// Dashboard — named accumulating monitors (per-op latency counters),
// dumped at shutdown. Capability parity with include/multiverso/dashboard.h
// (SURVEY.md §2.26).
#pragma once

#include <chrono>
#include <string>

namespace mvtpu {

class Dashboard {
 public:
  static void Record(const std::string& name, double seconds);
  static std::string Report();
  static void Reset();
  // count/total for one monitor (testing/introspection).
  static bool Query(const std::string& name, long long* count, double* total);
};

// RAII timer: MONITOR-macro equivalent.
class Monitor {
 public:
  explicit Monitor(std::string name)
      : name_(std::move(name)), start_(std::chrono::steady_clock::now()) {}
  ~Monitor() {
    auto dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - start_).count();
    Dashboard::Record(name_, dt);
  }

 private:
  std::string name_;
  std::chrono::steady_clock::time_point start_;
};

}  // namespace mvtpu
