// Dashboard — named accumulating monitors (per-op latency counters),
// dumped at shutdown. Capability parity with include/multiverso/dashboard.h
// (SURVEY.md §2.26), extended for the observability layer
// (docs/observability.md):
//
// - every monitor keeps fixed log2 latency buckets (1 µs .. ~67 s) so the
//   Python metrics registry can reconstruct p50/p95/p99 from one
//   MV_DumpMonitors() call instead of name-by-name MV_QueryMonitor;
// - when tracing is enabled, each Monitor also records a SPAN (wall-clock
//   start + duration) tagged with a trace id.  The id lives in a
//   thread-local: a worker-side op generates one, stamps it into the
//   request message header, and the server actor adopts it before
//   ProcessGet/ProcessAdd — so a worker Get and its server-side apply
//   (and the wire Send that carried it) share one trace id across ranks.
#pragma once

#include <chrono>
#include <cstdint>
#include <string>

namespace mvtpu {

// Bucket i holds values <= 1e-6 * 2^i seconds (i in [0, kNumBuckets-2]);
// the last bucket is the +inf overflow.  The Python side mirrors these
// bounds (multiverso_tpu/metrics.py NATIVE_TIME_BUCKETS) — the two lists
// MUST stay identical or bridged percentiles silently skew.
constexpr int kDashboardBuckets = 28;

class Dashboard {
 public:
  // Records into the monitor's bucket AND, when this thread carries a
  // trace id, stamps that id as the bucket's EXEMPLAR — the last trace
  // that landed there, so a p99 bucket links straight to the span
  // timeline that explains it (docs/observability.md).
  static void Record(const std::string& name, double seconds);
  static std::string Report();
  static void Reset();
  // count/total for one monitor (testing/introspection).
  static bool Query(const std::string& name, long long* count, double* total);
  // Every monitor in one pass (MV_DumpMonitors): one line per stat,
  //   name\tcount\ttotal\tmax\tb0,b1,...,b27\te0,e1,...,e27\n
  // The trailing exemplar field (last trace id per bucket, 0 = none) is
  // OPTIONAL on the parse side — pre-exemplar consumers read 4 fields.
  static std::string Dump();

  // ---- tracing (spans) -------------------------------------------------
  static void SetTraceEnabled(bool on);
  static bool TraceEnabled();
  // Rank salt for NewTraceId + the pid column of DumpSpans (set by
  // Zoo::Start so ids never collide across ranks).
  static void SetTraceRank(int rank);
  // Thread-local trace id: 0 = none.  Worker ops own a fresh id for the
  // op's duration; the server actor adopts the one riding the message.
  static void SetThreadTraceId(int64_t id);
  static int64_t ThreadTraceId();
  static int64_t NewTraceId();
  static void RecordSpan(const std::string& name, int64_t trace_id,
                         int64_t ts_us, int64_t dur_us);
  // One line per span: name\ttrace_id\tts_us\tdur_us\trank\ttid\n
  // (ts is wall-clock µs so per-rank dumps merge on one timeline).
  static std::string DumpSpans();
  static void ClearSpans();
};

// RAII timer: MONITOR-macro equivalent.  With tracing on it also emits a
// span; `trace_id` pins the span to a specific id (e.g. the one riding a
// wire message) — 0 uses/creates the thread-local id.
class Monitor {
 public:
  explicit Monitor(std::string name, int64_t trace_id = 0);
  ~Monitor();

 private:
  std::string name_;
  std::chrono::steady_clock::time_point start_;
  int64_t trace_id_ = 0;     // span id (0 = tracing off at ctor)
  int64_t wall_us_ = 0;      // span start, wall-clock µs
  bool own_thread_id_ = false;
};

}  // namespace mvtpu
