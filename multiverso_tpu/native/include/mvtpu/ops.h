// Ops — the live introspection plane (docs/observability.md).
//
// Three pieces, all servable IN-BAND over the existing wire (the epoll
// reactor answers MsgType::OpsQuery without touching the actor mailbox,
// so a wedged server still answers its health scrape):
//
//  - LocalReport(kind): this rank's report text.  "metrics" renders the
//    native Dashboard as Prometheus exposition (histograms with
//    per-bucket EXEMPLAR trace ids) — unless the host pushed its own
//    registry rendering (SetHostMetrics, fed by the Python metrics
//    flusher, which already bridges every native monitor), in which
//    case the pushed superset is served.  "health" and "tables" are
//    JSON built by the Zoo (queue depth vs -server_inflight_max, lease
//    state, per-table version/spread/codec/agg depth); "hotkeys" is the
//    workload plane (hot-key top-K + count-min estimates, bucket-load
//    skew, observed staleness, add-health sentinels).
//  - BuildReply(query, reply): wraps LocalReport into an OpsReply
//    message (local scope only — fleet scope is Zoo::HandleOpsQuery's
//    bounded fan-out).
//  - The flight recorder ("black box"): a bounded in-memory ring of
//    lifecycle events that BlackboxTrigger dumps — together with the
//    recent span ring and monitor totals — to
//    <trace_dir>/blackbox_rank<r>.json on failure triggers (barrier
//    timeout, dead peer, shed storm; the Python layer adds
//    CheckpointCorrupt), so the first chaos-induced failover ships with
//    a black box whose spans correlate by trace id with the surviving
//    ranks' traces.
#pragma once

#include <cstdint>
#include <string>

#include "mvtpu/message.h"

namespace mvtpu {
namespace ops {

// Host-pushed registry rendering (Prometheus text).  Empty = none; the
// Python metrics flusher pushes via MV_SetOpsHostMetrics.
void SetHostMetrics(const std::string& prom_text);

// Host-pushed alert state (JSON object text from the Python health
// evaluator, via MV_SetOpsHostAlerts each metrics flush).  Served
// verbatim under the "alerts" report's "host" key — the native side
// never parses it.  Empty = served as null.
void SetHostAlerts(const std::string& alerts_json);

// This rank's report for `kind` ("metrics" | "health" | "tables" |
// "hotkeys" | "latency" | "audit" | "replication" | "capacity" |
// "alerts" — the health plane's watchdog table + host alert state).
// Unknown kinds return a one-line JSON error instead of failing — a
// scraper probing a newer protocol must not kill the connection.
std::string LocalReport(const std::string& kind);

// Fill `reply` as the OpsReply to a LOCAL-scope `query` (kind from the
// query's first blob).  Routing fields (src/dst) are the caller's job.
void BuildReply(const Message& query, Message* reply);

// Fill `reply` as the ReplyReplica to an anonymous RequestReplica —
// the shard's hot-key top-K snapshot (docs/serving.md "tail"): a
// bounded read under the shard lock, safe from the reactor thread like
// the table-stats scrape, which is what lets a hedged read win while a
// straggling apply clogs the actor mailbox.  Routing fields (src/dst)
// are the caller's job; a table with no local shard answers empty.
void BuildReplicaReply(const Message& query, Message* reply);

// Prometheus-sanitized metric name (mirrors metrics.py _prom_name).
std::string PromName(const std::string& name);

// ---- flight recorder -------------------------------------------------
// Bounded event ring (capacity: the -blackbox_events flag); recording
// is always on and costs one small lock — the ring IS the black box.
void BlackboxEvent(const std::string& kind, const std::string& detail);
// Dump ring + recent spans + monitor totals to
// <trace_dir>/blackbox_rank<r>.json (the -trace_dir flag; no-op without
// it, the event still lands in the ring).  Returns the path written, or
// "" when no dump happened.  Re-triggering overwrites (last failure
// wins — each dump carries every ring event before it anyway).
std::string BlackboxTrigger(const std::string& reason);
// Triggers fired so far (testing).
long long BlackboxTriggerCount();
// Test isolation: drop ring + counters + pushed host metrics.
void BlackboxReset();

}  // namespace ops
}  // namespace mvtpu
