// MpiNet — the literal MPI wire transport (reference
// include/multiverso/net/mpi_net.h, SURVEY.md §2.17), selected with
// `-net_type=mpi`.
//
// No mpi.h ships in this image, so libmpi is dlopen'd at runtime and
// the (OpenMPI) ABI is declared locally: predefined handles like
// MPI_COMM_WORLD are exported data symbols (`ompi_mpi_comm_world`), and
// MPI_Status has the stable public layout.  `Available()` reports
// whether a usable libmpi resolved — callers (and tests) gate on it.
//
// Rank/size come from MPI itself, not a machine file: under `mpirun -n
// N` the whole job shows up; under a plain process launch OpenMPI's
// isolated singleton mode (set automatically when no PMIx launcher
// environment is present) gives rank 0 / size 1.
//
// Thread model: serial mode — every MPI call runs under one
// process-wide mutex (the reference's MPINetWrapper serialized the
// same way), with an Iprobe poll loop instead of a blocking Probe so
// Stop() cannot hang on a transport with no inbound traffic.
//
// Lifecycle restriction (MPI's, not ours): MPI_Finalize is terminal —
// one Init/Stop cycle per process; a second Init after Stop fails with
// a clear error instead of aborting inside libmpi.
#pragma once

#include <atomic>
#include <thread>

#include "mvtpu/net.h"

namespace mvtpu {

class MpiNet : public Net {
 public:
  using InboundFn = Net::InboundFn;

  ~MpiNet() override { Stop(); }

  // True when a dlopen-able libmpi with the expected ABI is present.
  static bool Available();

  // Number of send payloads parked for the life of the process after a
  // timed-out or failed send (MPI may keep reading a buffer whose
  // request we freed).  Diagnostic/test hook: healthy runs stay at 0;
  // every increment already logged an error.
  static size_t OrphanedSendBufCount();

  // Initialize MPI (MPI_THREAD_MULTIPLE requested; serial-mode locking
  // regardless), read rank/size, start the inbound probe thread.
  bool Init(InboundFn fn);

  bool Send(int dst_rank, const Message& msg) override;
  void Stop() override;

  int rank() const override { return rank_; }
  int size() const override { return size_; }
  const char* engine() const override { return "mpi"; }

 private:
  void ProbeLoop();

  InboundFn inbound_;
  int rank_ = 0;
  int size_ = 1;
  std::thread probe_thread_;
  std::atomic<bool> running_{false};
};

}  // namespace mvtpu
