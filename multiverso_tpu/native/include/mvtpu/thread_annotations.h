// Clang thread-safety capability annotations (no-ops elsewhere).
//
// `make analyze` builds the runtime with
//   clang++ -Wthread-safety -Werror=thread-safety
// so a Get/Add/registry path that touches a GUARDED_BY member without
// its mutex fails the BUILD — the static complement of the dynamic
// `make tsan` sweep (docs/static_analysis.md).  GCC compiles the same
// sources with every macro empty.
//
// The annotations only bite on capability-annotated mutex types;
// libstdc++'s std::mutex carries none, which is why the runtime locks
// through the annotated Mutex/MutexLock/CondVar shims in mvtpu/mutex.h
// rather than std::mutex directly.
#pragma once

#if defined(__clang__)
#define MVTPU_TSA(x) __attribute__((x))
#else
#define MVTPU_TSA(x)  // GCC/MSVC: annotations compile away
#endif

#ifndef CAPABILITY
#define CAPABILITY(x) MVTPU_TSA(capability(x))
#endif

#ifndef SCOPED_CAPABILITY
#define SCOPED_CAPABILITY MVTPU_TSA(scoped_lockable)
#endif

// Data members: which mutex must be held to touch them.
#ifndef GUARDED_BY
#define GUARDED_BY(x) MVTPU_TSA(guarded_by(x))
#endif

#ifndef PT_GUARDED_BY
#define PT_GUARDED_BY(x) MVTPU_TSA(pt_guarded_by(x))
#endif

// Functions: caller must already hold the capability (the `*Locked`
// helper convention), or acquires/releases it itself.
#ifndef REQUIRES
#define REQUIRES(...) MVTPU_TSA(requires_capability(__VA_ARGS__))
#endif

#ifndef ACQUIRE
#define ACQUIRE(...) MVTPU_TSA(acquire_capability(__VA_ARGS__))
#endif

#ifndef RELEASE
#define RELEASE(...) MVTPU_TSA(release_capability(__VA_ARGS__))
#endif

#ifndef TRY_ACQUIRE
#define TRY_ACQUIRE(...) MVTPU_TSA(try_acquire_capability(__VA_ARGS__))
#endif

#ifndef EXCLUDES
#define EXCLUDES(...) MVTPU_TSA(locks_excluded(__VA_ARGS__))
#endif

#ifndef RETURN_CAPABILITY
#define RETURN_CAPABILITY(x) MVTPU_TSA(lock_returned(x))
#endif

// Escape hatch for patterns the analysis cannot see through (e.g. the
// adopt/release dance inside CondVar, which hands a held mutex to
// std::condition_variable and takes it back).  Every use must carry a
// comment saying why the analysis is blind there.
#ifndef NO_THREAD_SAFETY_ANALYSIS
#define NO_THREAD_SAFETY_ANALYSIS MVTPU_TSA(no_thread_safety_analysis)
#endif
