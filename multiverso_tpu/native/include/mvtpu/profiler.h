// Always-on sampling profiler (docs/observability.md "latency plane").
//
// A SIGPROF/ITIMER_PROF sampler in the classic gprof shape: the signal
// fires on whichever thread is burning CPU, the handler captures a raw
// backtrace into a preallocated lock-free ring (no malloc, no locks —
// the handler is async-signal-safe by construction), and Dump()
// aggregates + symbolizes off the hot path into folded-stack lines
//
//   sym_outer;sym_inner;sym_leaf <count>
//
// that the Python layer renders into the Chrome trace beside the span
// timeline (multiverso_tpu/profiler.py).  Because ITIMER_PROF counts
// CPU time, an idle serve tier costs literally zero samples; a busy one
// pays ~one backtrace per sampling period — the bench_latency
// `profiler_overhead_pct < 1` bar holds at the default 97 Hz with room
// to spare.  97 (prime) rather than 100 so the sampler cannot phase-
// lock with millisecond-periodic work and alias it in or out.
#pragma once

#include <string>

namespace mvtpu {
namespace profiler {

// Start sampling at `hz` (<= 0 stops).  Idempotent; restarting with a
// new rate rearms the timer but keeps the ring.  Returns false when the
// timer/handler could not be installed.
bool Start(int hz);
void Stop();
bool Running();

// Folded-stack aggregation of everything sampled so far:
//   one line per distinct stack, "outer;...;leaf count\n", innermost
//   frame LAST (the flamegraph.pl / speedscope folded convention).
// Symbolized via dladdr; address-only frames render as hex.
std::string DumpFolded();

// {"running":bool,"hz":n,"samples":n,"dropped":n} — the "profiler"
// section of the "latency" OpsQuery report.
std::string StatusJson();

// Drop every recorded sample (test isolation / per-phase A-B runs).
void Clear();

}  // namespace profiler
}  // namespace mvtpu
