// Shard replication + lease-triggered failover (docs/replication.md).
//
// With `-replication_factor=1` every server shard gets a BACKUP rank
// (chained assignment: shard i's backup is server i+1 mod n).  The
// primary re-ships every applied add to its backup as a ReplForward —
// decoded payload, origin rank and audit stamp preserved — so the
// backup's shard bytes, per-bucket CRC beacons, and per-origin audit
// watermarks track the primary's.  `-repl_sync=true` (the default)
// parks the client's ReplyAdd until the backup's ReplAck lands: an
// ACKED add is by construction applied on BOTH replicas, which is what
// makes "zero lost acked adds" a structural property of failover
// rather than a replay protocol.  `-repl_sync=false` acks immediately
// and only bounds the forward/ack gap at `-repl_lag_max` (measured by
// the `repl.lag` histogram).
//
// On lease expiry (symmetric dead-peer detection — every rank watches
// every peer, not just rank 0) the backup PROMOTES: it installs its
// backup shard as the serving shard, bumps the fleet routing epoch,
// and broadcasts the new shard→rank map; workers re-route in-flight
// retries through Zoo::server_rank() without a fleet restart.  A new
// rank joins the serving set the same way: whole-shard catch-up
// (ShardSnapshot — Store/Load at a snapshot version, audit watermarks
// included) followed by the same delta forwarding — a join is just
// replication plus a routing-epoch flip.
//
// This header holds the arm latches, counters, and the in-memory
// Stream the snapshot path serializes through; the routing epoch,
// backup-table registry, and promotion state machine live in Zoo.
#pragma once

#include <cstdint>
#include <cstring>
#include <string>

#include "mvtpu/stream.h"

namespace mvtpu {
namespace repl {

// Latched from -replication_factor at Zoo::Start (MV_SetReplication
// toggles live for armed-vs-disarmed overhead A/Bs).  One relaxed
// atomic load when off — the ProcessAdd hot path's only cost.
void Arm(bool on);
bool Armed();

// Latched from -repl_sync: park client acks until the backup acked.
void ArmSync(bool on);
bool Sync();

struct Stats {
  long long forwards = 0;    // ReplForwards shipped (primary side)
  long long acks = 0;        // ReplAcks received (primary side)
  long long applied = 0;     // forwarded deltas applied (backup side)
  long long parked = 0;      // client acks parked for sync replication
  long long lag_waits = 0;   // async-mode stalls at -repl_lag_max
  long long snapshots = 0;   // ShardSnapshots served (primary side)
  long long catchups = 0;    // snapshots installed (backup side)
  long long promotions = 0;  // shards this rank promoted into serving
  long long epoch_flips = 0; // RoutingEpoch broadcasts adopted
  long long dup_skips = 0;   // replayed stamped adds skipped as dups
};
Stats GetStats();
void NoteForward();
void NoteAck();
void NoteApplied();
void NoteParked();
void NoteLagWait();
void NoteSnapshot();
void NoteCatchup();
void NotePromotion();
void NoteEpochFlip();
void NoteDupSkip();
void ResetStats();  // test/bench isolation

// In-memory byte stream: the ShardSnapshot path runs ServerTable::
// Store/Load over the wire instead of the filesystem.
class MemStream : public Stream {
 public:
  MemStream() = default;
  explicit MemStream(std::string bytes) : buf_(std::move(bytes)) {}
  size_t Write(const void* p, size_t n) override {
    buf_.append(static_cast<const char*>(p), n);
    return n;
  }
  size_t Read(void* p, size_t n) override {
    size_t take = buf_.size() - pos_ < n ? buf_.size() - pos_ : n;
    std::memcpy(p, buf_.data() + pos_, take);
    pos_ += take;
    return take;
  }
  bool Good() const override { return true; }
  const std::string& bytes() const { return buf_; }

 private:
  std::string buf_;
  size_t pos_ = 0;
};

}  // namespace repl
}  // namespace mvtpu
