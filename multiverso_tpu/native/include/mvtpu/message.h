// Message — the wire/mailbox unit: routing header + blob payload.
// Capability parity with include/multiverso/message.h (SURVEY.md §2.4).
// Contract-checked: tools/mvcontract.py (`make contract`) statically
// diffs the MsgType/msgflag values and the stamp struct layouts below
// against serve/wire.py — change them together or tier-1 fails.
#pragma once

#include <cstdint>
#include <memory>
#include <vector>

#include "mvtpu/blob.h"

namespace mvtpu {

enum class MsgType : int32_t {
  RequestGet = 1,
  RequestAdd = 2,
  ReplyGet = 3,
  ReplyAdd = 4,
  // Synthesized locally when the transport cannot deliver a request —
  // unblocks the pending RoundTrip with an error instead of a hang.
  ReplyError = 5,
  // Pipeline flush marker: rides each worker→server connection BEHIND
  // any earlier async adds (per-connection FIFO), acked after the
  // server processed everything before it.  Barrier() drains one per
  // remote server shard before announcing arrival — the mechanism that
  // makes "async adds apply before the barrier completes" true for
  // n >= 3 (two connections to different peers have no mutual order).
  RequestFlush = 6,
  ReplyFlush = 7,
  ControlRegister = 16,
  ControlReply = 17,
  ControlBarrier = 18,
  ControlBarrierReply = 19,
  // Serve layer (docs/serving.md): version probe.  A read-optimized
  // client that holds a cached copy asks for the table's CURRENT
  // version instead of paying a full fetch — the request's `version`
  // field carries a bucket index (>= 0) for bucket-granular tables
  // (KV/matrix) or -1 for the whole table; the reply's `version` field
  // carries the answer.
  RequestVersion = 8,
  ReplyVersion = 9,
  // Serve backpressure shed (docs/serving.md): the server actor's
  // mailbox exceeded `-server_inflight_max`, so this Get/probe was
  // answered WITHOUT processing.  Retryable — and unlike a deadline -3
  // it is not indeterminate: the server did no work.
  ReplyBusy = 10,
  // Hot-key replica pull (docs/embedding.md): the requester asks a
  // server shard to PUSH its current SpaceSaving top-K rows.  The
  // reply carries three blobs — [int32 global row ids][int64 per-row
  // bucket versions][float row data, k*cols] — snapshotted atomically
  // against concurrent adds, plus the shard's table version in the
  // header.  Workers (and anonymous serve clients) install the rows in
  // a read-replica side table consulted BEFORE the wire; invalidation
  // rides the existing version-stamp protocol (an entry older than the
  // staleness bound misses).  Sheddable like a Get — never blocks adds.
  RequestReplica = 11,
  ReplyReplica = 12,
  // Hedge-cancel token (docs/serving.md "tail"): fire-and-forget notice
  // that the sender no longer wants the answer to (src, msg_id) — the
  // LOSER of a hedged read race.  Consumed AT THE REACTOR (never the
  // actor mailbox, so it overtakes the FIFO the loser is parked in);
  // the server actor drops a cancelled Get at dequeue instead of
  // burning an apply slot on an answer nobody is waiting for.  Only
  // reads are ever cancelled; there is no reply.
  RequestCancel = 13,
  // SSP clock announcement (msg_id = the worker's new clock).  Rides
  // each worker->server connection BEHIND that clock's adds (FIFO), so
  // "min worker clock >= c" implies every rank's adds through clock c
  // landed — the bounded-staleness guarantee MV_Clock documents.
  ClockTick = 20,
  // Liveness lease (docs/fault_tolerance.md): every non-zero rank
  // announces itself to rank 0 every `-heartbeat_ms`; rank 0's lease
  // loop reports peers whose announcements stop (Dashboard hb.missed)
  // instead of letting the next barrier discover the corpse by hanging.
  Heartbeat = 21,
  // Connection-identify frame (docs/transport.md): the FIRST frame a
  // rank peer sends on a fresh outbound connection, carrying its rank
  // in `src` and nothing else.  The epoll reactor caps UNIDENTIFIED
  // accepted connections at the small anonymous-client frame bound, so
  // a rank peer must announce itself with this tiny frame before its
  // first (possibly shard-sized) payload frame; the reactor consumes it
  // during identification — it is never forwarded upstream.
  Hello = 22,
  // Live introspection plane (docs/observability.md): an in-band scrape
  // over the SAME wire the serve tier speaks.  The request's first blob
  // names the report kind ("metrics" | "health" | "tables"); `version`
  // carries the scope (0 = this rank, 1 = fleet: the receiving rank
  // fans out to every peer with a bounded deadline and merges, marking
  // silent ranks).  Local-scope queries are answered AT THE REACTOR
  // (like ReplyBusy — never through the actor mailbox), so a wedged
  // server still answers its health scrape.  The reply's single blob is
  // the report text (Prometheus exposition for "metrics", JSON
  // otherwise).
  OpsQuery = 23,
  OpsReply = 24,
  // ---- shard replication + failover (docs/replication.md) ------------
  // Primary→backup delta stream: after a primary shard applies a
  // RequestAdd it re-ships the DECODED payload to its backup rank as a
  // ReplForward.  `version` carries the ORIGIN worker rank (the backup
  // books the same per-origin audit watermark the primary did), `shard`
  // names the shard stream, and the AuditStamp rides along when the
  // original add carried one.  `msg_id` is the forward's ack token:
  // the backup answers every forward with a ReplAck echoing it, which
  // is how the primary bounds replication lag (`-repl_lag_max`) and,
  // in sync mode, when it releases the client's parked ReplyAdd.
  ReplForward = 25,
  ReplAck = 26,
  // Whole-shard catch-up (the PR 10 replica machinery generalized from
  // top-K rows to the full shard): a (re)joining backup asks the
  // primary for a snapshot of one table's shard — request has no data
  // blobs; the reply carries [serialized shard state][exported audit
  // watermarks] with the snapshot's table version in `version`.
  // Served by the primary's server actor, so it serializes against
  // ProcessAdd: every delta after the snapshot reaches the backup as a
  // ReplForward BEHIND the reply on the same connection (FIFO).
  ShardSnapshot = 27,
  // Versioned routing-epoch broadcast: blobs = [int32 owner ranks per
  // shard][int32 backup ranks per shard], msg_id = the epoch.  Receivers
  // adopt iff newer (max-merge), re-pointing Zoo::server_rank() so every
  // in-flight retry re-routes to the promoted/new primary without a
  // fleet restart.
  RoutingEpoch = 28,
  // Operator/controller-initiated promotion nudge: asks the receiving
  // rank to promote its backup shard(s) for the rank in `version` (the
  // same path lease expiry triggers automatically).
  Promote = 29,
  Exit = 64,
};

// Payload codec (docs/wire_compression.md): how the LAST blob of a
// message's data (the float delta/value payload) is encoded on the
// wire.  Negotiated per table at creation (`-wire_codec` /
// MV_SetTableCodec) and stamped per MESSAGE in the wire header — a
// sparse-codec table falls back to kRaw on payloads where the sparse
// form would be larger, so the receiver must trust the stamp, not the
// table setting.
enum class Codec : int32_t {
  kRaw = 0,     // float32, one element per 4 bytes (the reference wire)
  kOneBit = 1,  // sign bits + two scales, worker-side error feedback
  kSparse = 2,  // (index, value) pairs of nonzeros — lossless
};

// Header flag bits: the codecs the SENDER of a request accepts in the
// reply.  Every request carries kAcceptRaw; tables with a non-raw codec
// additionally advertise the lossless sparse codec so large mostly-zero
// Get replies can shrink.  (Replies are never 1-bit encoded: error
// feedback needs a per-receiver residual the server does not hold.)
namespace msgflag {
inline constexpr int32_t kAcceptRaw = 1 << 0;
inline constexpr int32_t kAccept1Bit = 1 << 1;
inline constexpr int32_t kAcceptSparse = 1 << 2;
// Latency-attribution trail (docs/observability.md "latency plane"): a
// TimingTrail follows the WireHeader on the wire.  VERSION-TOLERANT by
// construction: a peer that never sets the bit ships the PR 3 header
// unchanged and is parsed exactly as before; a receiver that does not
// understand the bit would still frame correctly (the trail is inside
// the length-prefixed frame) — replies only carry a trail when the
// REQUEST did, so an old client is never handed bytes it cannot parse.
inline constexpr int32_t kHasTiming = 1 << 3;
// Delivery-audit stamp (docs/observability.md "audit plane"): an
// AuditStamp follows the WireHeader (after the TimingTrail when both
// bits are set).  Version-tolerant exactly like kHasTiming: peers that
// never stamp ship/parse the old layout, and replies carry a stamp
// only when the request did.
inline constexpr int32_t kHasAudit = 1 << 4;
// Tenant QoS + deadline stamp (docs/serving.md "tail"): a QosStamp
// follows the WireHeader (after the AuditStamp when both bits are
// set).  Version-tolerant exactly like kHasTiming/kHasAudit: peers
// that never stamp ship/parse the old layout byte-identically, and a
// flagged-but-short frame is malformed, never a misparse.
inline constexpr int32_t kHasQos = 1 << 5;
}  // namespace msgflag

// Wire-stamped request-lifecycle timing trail (docs/observability.md):
// six monotonic-clock nanosecond stamps, each taken on whichever rank
// owns the stage boundary.  Client-side stamps (enqueue/send) and
// server-side stamps (recv/dequeue/apply_done/reply_send) live on
// DIFFERENT clocks — cross-clock stage deltas are only meaningful after
// the per-peer NTP-style offset correction (mvtpu/latency.h).  0 = the
// stage boundary was never crossed (local delivery has no recv stamp;
// an old peer stamps nothing).
struct TimingTrail {
  enum Stamp {
    kEnqueue = 0,    // client: request minted (MakeReq)
    kSend = 1,       // client: handed to the transport (Zoo::Deliver)
    kRecv = 2,       // server: frame complete at the reactor/reader
    kDequeue = 3,    // server: actor dequeued it (handler entry)
    kApplyDone = 4,  // server: table work done, reply built
    kReplySend = 5,  // server: reply handed to the transport
    kStamps = 6,
  };
  int64_t t[kStamps] = {0, 0, 0, 0, 0, 0};
};

// Delivery-audit identity (docs/observability.md "audit plane"): the
// inclusive range of per-(worker, table, server-shard) Add sequence
// numbers this message covers.  A plain add covers one seq (lo == hi);
// a PR 5 aggregation flush covers the whole collapsed window, so the
// auditor can account every absorbed logical add through the single
// wire message that carried it.  The origin rank rides in the header's
// `src`; seqs start at 1 and are dense PER SHARD STREAM — each server
// shard observes 1,2,3,... from each origin, which is what makes the
// applied watermark (mvtpu/audit.h) a loss/dup/reorder detector rather
// than a heuristic.  Retries re-send the identical stamp: a duplicated
// delivery is counted as a dup, never double-advanced.
struct AuditStamp {
  int64_t seq_lo = 0;
  int64_t seq_hi = 0;
};

// Tenant QoS + deadline-propagation stamp (docs/serving.md "tail").
// `klass` is the sender's tenant class — a POSITIONAL index into the
// server's `-qos_classes` list (both sides must agree on the list, the
// same contract as codec negotiation); the reactor's weighted admission
// gate budgets inflight reads per class.  `budget_ns` is the REMAINING
// deadline budget at client send time (0 = no deadline): the receiver
// converts it to a local-clock deadline at frame receipt — correcting
// for wire time via the PR 11 clock-offset estimate when one exists —
// and drops a read that is already past it at dequeue instead of
// burning an apply slot on an answer nobody is waiting for.  Adds are
// never deadline-shed.
struct QosStamp {
  int32_t klass = 0;
  int32_t pad = 0;
  int64_t budget_ns = 0;
};

// Fixed-size wire header — ONE definition shared by Message::Serialize
// (contiguous form: tests, MpiNet) and TcpNet's scatter-gather send
// (header + blob iovecs, no payload copy).  Layout changes here change
// the wire format; both sides memcpy this struct.
struct WireHeader {
  int32_t src, dst, type, table_id;
  int64_t msg_id;
  int64_t trace_id;
  int64_t version;
  int32_t codec;      // Codec of data.back() (kRaw when data is empty)
  int32_t flags;      // msgflag:: accept bits for the reply
  int32_t num_blobs;
  // Shard routing hint (docs/replication.md), BIASED BY ONE so the
  // pre-replication wire value 0 still means "no hint": requests stamp
  // the target shard index + 1 and replies echo it.  After a failover
  // one rank can serve TWO shards of a table, so neither the dst rank
  // (on requests) nor the src rank (on replies) names the shard any
  // more — the hint does.  Was the `pad` byte-alignment field; old
  // peers ship 0 here and parse as hint -1, the pre-epoch routing.
  int32_t shard_hint = 0;
};

struct Message {
  int32_t src = -1;
  int32_t dst = -1;
  MsgType type = MsgType::RequestGet;
  int32_t table_id = -1;
  int64_t msg_id = -1;
  // Observability span id (0 = none): stamped by the worker-side op that
  // originated the request, adopted by the server actor before
  // ProcessGet/ProcessAdd, and echoed on replies — the cross-rank
  // correlation key for merged traces (docs/observability.md).
  int64_t trace_id = 0;
  // Serve-layer version stamp (docs/serving.md): every server-side
  // apply bumps a per-table (and per-row-bucket) monotonic counter;
  // replies carry the version covering the data they serve so clients
  // can bound cache staleness.  On a RequestVersion it instead carries
  // the REQUESTED bucket (-1 = whole table).  0 = unversioned.
  int64_t version = 0;
  // Wire codec of data.back() (docs/wire_compression.md).  kRaw unless a
  // worker-side encode stamped it; the server decodes before ProcessAdd
  // and the worker actor decodes replies before Notify, so the table
  // layer itself only ever sees raw float payloads.
  Codec codec = Codec::kRaw;
  // msgflag:: accept bits: the reply codecs this request's sender can
  // decode (stamped by Get/version requests; replies echo kAcceptRaw).
  int32_t flags = msgflag::kAcceptRaw;
  // Latency trail — on the wire ONLY when flags carries kHasTiming
  // (docs/observability.md): requests stamp the client-side slots,
  // the server copies the trail into the reply and adds its own, and
  // the client attributes the round trip per stage on reply receipt.
  TimingTrail timing;
  // Delivery-audit stamp — on the wire ONLY when flags carries
  // kHasAudit (docs/observability.md "audit plane"): Add requests
  // carry the covered seq range, the server's ReplyAdd ack echoes it
  // so the client ledger can advance its acked watermark.
  AuditStamp audit;
  // Tenant QoS + deadline stamp — on the wire ONLY when flags carries
  // kHasQos (docs/serving.md "tail"): read requests carry their class
  // and remaining deadline budget; replies never carry one.
  QosStamp qos;
  // Shard routing hint (docs/replication.md): the target shard index a
  // request addresses / the shard a reply answers for, or -1 (no hint —
  // the pre-replication wire, where dst/src ranks named shards
  // uniquely).  Rides the header's shard_hint slot biased by one, so
  // old frames stay byte-identical.
  int32_t shard = -1;
  // NOT serialized: the local-monotonic-clock deadline adopted from
  // `qos.budget_ns` at frame receipt (qos::AdoptDeadline).  0 = none.
  int64_t qos_deadline_ns = 0;
  std::vector<Blob> data;

  bool has_timing() const { return (flags & msgflag::kHasTiming) != 0; }
  bool has_audit() const { return (flags & msgflag::kHasAudit) != 0; }
  bool has_qos() const { return (flags & msgflag::kHasQos) != 0; }

  // Header <-> message field marshalling (shared by Serialize and the
  // transport's scatter-gather framing).
  void FillWireHeader(WireHeader* h) const;
  void AdoptWireHeader(const WireHeader& h);
  // Total framed byte count (header + per-blob length prefixes + blob
  // payloads) — what one wire frame of this message occupies.
  int64_t WireBytes() const;

  // Serialize to one contiguous buffer (header + per-blob length prefix):
  // the MpiNet wire shape and the test-suite round-trip form.  TcpNet
  // ships the identical layout via scatter-gather iovecs instead
  // (net.cc SendFramed) — no full-payload copy on the hot path.
  Blob Serialize() const;
  static Message Deserialize(const Blob& buf);
  // Zero-copy deserialize (the epoll receive path, docs/transport.md):
  // the frame at [off, off+len) of `slab` is parsed in place, each data
  // blob becoming a Blob::View sharing the slab's ownership — no payload
  // copy.  `off` must be 8-aligned (the reactor's arena packs frames
  // that way); blobs landing at unaligned offsets inside the frame are
  // flattened to owning copies instead of views, so consumers may
  // always As<T>() the payload.  False on a malformed frame (blob
  // lengths overrunning `len`); the caller drops the connection.
  static bool DeserializeView(std::shared_ptr<std::vector<char>> slab,
                              size_t off, size_t len, Message* out);
  // Zero-copy deserialize over BORROWED memory (the io_uring registered-
  // buffer receive path, docs/transport.md): same parse and same
  // malformed-frame contract as DeserializeView, but the frame lives in
  // raw caller-owned bytes (a HostArena slab registered with the
  // kernel), so aligned blobs become Blob::Borrow windows sharing
  // `keepalive` — the slab recycles only once every borrow (and the
  // caller's own hold) is gone, the PR 9 two-hold discipline.  `align`
  // is the frame's byte offset inside its slab, used only for the
  // 8-alignment view-vs-copy split (the slab base itself must be
  // 8-aligned, as HostArena buffers are).
  static bool DeserializeBorrow(const char* frame, size_t align, size_t len,
                                const std::shared_ptr<void>& keepalive,
                                Message* out);
};

using MessagePtr = std::unique_ptr<Message>;

}  // namespace mvtpu
