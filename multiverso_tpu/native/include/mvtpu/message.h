// Message — the wire/mailbox unit: routing header + blob payload.
// Capability parity with include/multiverso/message.h (SURVEY.md §2.4).
#pragma once

#include <cstdint>
#include <memory>
#include <vector>

#include "mvtpu/blob.h"

namespace mvtpu {

enum class MsgType : int32_t {
  RequestGet = 1,
  RequestAdd = 2,
  ReplyGet = 3,
  ReplyAdd = 4,
  // Synthesized locally when the transport cannot deliver a request —
  // unblocks the pending RoundTrip with an error instead of a hang.
  ReplyError = 5,
  // Pipeline flush marker: rides each worker→server connection BEHIND
  // any earlier async adds (per-connection FIFO), acked after the
  // server processed everything before it.  Barrier() drains one per
  // remote server shard before announcing arrival — the mechanism that
  // makes "async adds apply before the barrier completes" true for
  // n >= 3 (two connections to different peers have no mutual order).
  RequestFlush = 6,
  ReplyFlush = 7,
  ControlRegister = 16,
  ControlReply = 17,
  ControlBarrier = 18,
  ControlBarrierReply = 19,
  // Serve layer (docs/serving.md): version probe.  A read-optimized
  // client that holds a cached copy asks for the table's CURRENT
  // version instead of paying a full fetch — the request's `version`
  // field carries a bucket index (>= 0) for bucket-granular tables
  // (KV/matrix) or -1 for the whole table; the reply's `version` field
  // carries the answer.
  RequestVersion = 8,
  ReplyVersion = 9,
  // Serve backpressure shed (docs/serving.md): the server actor's
  // mailbox exceeded `-server_inflight_max`, so this Get/probe was
  // answered WITHOUT processing.  Retryable — and unlike a deadline -3
  // it is not indeterminate: the server did no work.
  ReplyBusy = 10,
  // SSP clock announcement (msg_id = the worker's new clock).  Rides
  // each worker->server connection BEHIND that clock's adds (FIFO), so
  // "min worker clock >= c" implies every rank's adds through clock c
  // landed — the bounded-staleness guarantee MV_Clock documents.
  ClockTick = 20,
  // Liveness lease (docs/fault_tolerance.md): every non-zero rank
  // announces itself to rank 0 every `-heartbeat_ms`; rank 0's lease
  // loop reports peers whose announcements stop (Dashboard hb.missed)
  // instead of letting the next barrier discover the corpse by hanging.
  Heartbeat = 21,
  Exit = 64,
};

struct Message {
  int32_t src = -1;
  int32_t dst = -1;
  MsgType type = MsgType::RequestGet;
  int32_t table_id = -1;
  int64_t msg_id = -1;
  // Observability span id (0 = none): stamped by the worker-side op that
  // originated the request, adopted by the server actor before
  // ProcessGet/ProcessAdd, and echoed on replies — the cross-rank
  // correlation key for merged traces (docs/observability.md).
  int64_t trace_id = 0;
  // Serve-layer version stamp (docs/serving.md): every server-side
  // apply bumps a per-table (and per-row-bucket) monotonic counter;
  // replies carry the version covering the data they serve so clients
  // can bound cache staleness.  On a RequestVersion it instead carries
  // the REQUESTED bucket (-1 = whole table).  0 = unversioned.
  int64_t version = 0;
  std::vector<Blob> data;

  // Serialize to one contiguous buffer (header + per-blob length prefix) —
  // the shape a cross-process transport would ship. Exercised by tests and
  // available to future DCN transports; in-process routing skips it.
  Blob Serialize() const;
  static Message Deserialize(const Blob& buf);
};

using MessagePtr = std::unique_ptr<Message>;

}  // namespace mvtpu
