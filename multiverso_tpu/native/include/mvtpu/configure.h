// Flag registry + "-name=value" command-line parsing.
// Capability parity with include/multiverso/util/configure.h (SURVEY.md
// §2.20): the reference's MV_DEFINE_* macro system, rebuilt as a typed
// registry. Known reference flags (sync, updater_type, machine_file, port,
// backup_worker_ratio, log_level, log_file) are pre-registered.
#pragma once

#include <string>

namespace mvtpu {
namespace configure {

void DefineBool(const std::string& name, bool dflt, const std::string& help);
void DefineInt(const std::string& name, long long dflt, const std::string& help);
void DefineDouble(const std::string& name, double dflt, const std::string& help);
void DefineString(const std::string& name, const std::string& dflt,
                  const std::string& help);

bool GetBool(const std::string& name);
long long GetInt(const std::string& name);
double GetDouble(const std::string& name);
std::string GetString(const std::string& name);

bool Has(const std::string& name);
// Accepts "-name=value" / "--name=value"; returns number parsed,
// -1 on first unknown flag or bad value.
int ParseCmdFlags(int argc, const char* const* argv);
void Set(const std::string& name, const std::string& value);  // throws std::invalid_argument
void Reset();  // restore every flag to its default

void RegisterDefaults();

}  // namespace configure
}  // namespace mvtpu
