// Annotated mutex/condvar shims — the lock vocabulary of the runtime.
//
// Thin zero-cost wrappers over std::mutex/std::condition_variable that
// carry the clang thread-safety capability attributes
// (thread_annotations.h).  libstdc++'s std::mutex is unannotated, so
// locking it directly would leave `clang++ -Wthread-safety` with
// nothing to check; every runtime mutex goes through these instead.
#pragma once

#include <chrono>
#include <condition_variable>
#include <mutex>

#include "mvtpu/thread_annotations.h"

namespace mvtpu {

class CAPABILITY("mutex") Mutex {
 public:
  Mutex() = default;
  Mutex(const Mutex&) = delete;
  Mutex& operator=(const Mutex&) = delete;

  void Lock() ACQUIRE() { mu_.lock(); }
  void Unlock() RELEASE() { mu_.unlock(); }

 private:
  friend class CondVar;
  std::mutex mu_;
};

// RAII scope lock (std::lock_guard with a SCOPED_CAPABILITY attribute,
// so the analysis knows the capability is held for the block).
class SCOPED_CAPABILITY MutexLock {
 public:
  explicit MutexLock(Mutex& mu) ACQUIRE(mu) : mu_(mu) { mu_.Lock(); }
  ~MutexLock() RELEASE() { mu_.Unlock(); }
  MutexLock(const MutexLock&) = delete;
  MutexLock& operator=(const MutexLock&) = delete;

 private:
  Mutex& mu_;
};

// Condition variable over Mutex.  Waits REQUIRE the mutex held and
// return with it held; there is deliberately no predicate overload —
// callers loop `while (!cond) cv.Wait(mu);` under their MutexLock so
// every guarded read in the condition stays visible to the analysis
// (a predicate lambda would be analyzed as an unlocked function).
class CondVar {
 public:
  void Wait(Mutex& mu) REQUIRES(mu) NO_THREAD_SAFETY_ANALYSIS {
    // adopt/release: hand the already-held mutex to the condvar, take
    // it back on wake — net effect "still held", which the analysis
    // cannot see through (hence the suppression; REQUIRES is still
    // enforced at every call site).
    std::unique_lock<std::mutex> lk(mu.mu_, std::adopt_lock);
    cv_.wait(lk);
    lk.release();
  }

  // False on deadline expiry, true when notified (spurious wakes
  // included — callers re-check their condition either way).
  //
  // system_clock, NOT a steady_clock wait_for: libstdc++'s wait_for
  // rides pthread_cond_clockwait (CLOCK_MONOTONIC), which gcc-10's
  // libtsan does not intercept — TSan then misses the wait's internal
  // unlock/relock and reports a bogus "double lock of a mutex" against
  // the next notifier.  system_clock goes through the intercepted
  // pthread_cond_timedwait.  Cost: a wall-clock jump can stretch or
  // shrink one in-flight deadline.
  bool WaitUntil(Mutex& mu, std::chrono::system_clock::time_point deadline)
      REQUIRES(mu) NO_THREAD_SAFETY_ANALYSIS {
    std::unique_lock<std::mutex> lk(mu.mu_, std::adopt_lock);
    auto st = cv_.wait_until(lk, deadline);
    lk.release();
    return st != std::cv_status::timeout;
  }

  void NotifyOne() { cv_.notify_one(); }
  void NotifyAll() { cv_.notify_all(); }

 private:
  std::condition_variable cv_;
};

}  // namespace mvtpu
