// TcpNet — point-to-point transport between runtime processes.
// Capability parity with include/multiverso/net/zmq_net.h (SURVEY.md
// §2.18): peers come from a machine file (one "host:port" per line, line
// index = rank), frames are length-prefixed serialized Messages, and the
// receive side hands decoded messages to a router callback.  Plain POSIX
// TCP instead of libzmq: same dealer-style lazy connect, no external
// dependency.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <memory>
#include <string>
#include <thread>
#include <vector>

#include "mvtpu/message.h"
#include "mvtpu/mutex.h"
#include "mvtpu/transport.h"

namespace mvtpu {

// The wire-transport interface itself (class Net + RankTransport) lives
// in mvtpu/transport.h — the `-net_engine` seam.  TcpNet here is the
// blocking thread-per-connection engine; EpollNet (epoll_net.h) the
// event-driven reactor; MpiNet (mpi_net.h) the literal MPI wire.
class TcpNet : public RankTransport {
 public:
  using InboundFn = Net::InboundFn;

  ~TcpNet() override { Stop(); }

  // Parse a machine file into "host:port" endpoints; empty on error.
  static std::vector<std::string> ParseMachineFile(const std::string& path);

  // One length-prefixed Message frame over a raw fd (used by the
  // dynamic-registration handshake, which runs before the transport,
  // and by the transport's own ReadLoop/Send).  The frame is written
  // SCATTER-GATHER (sendmsg over header + per-blob iovecs): the payload
  // blobs go to the kernel in place — no full-message Serialize() copy
  // on the send path (the frame layout is identical to Serialize()'s,
  // so RecvFramed/Deserialize are unchanged).  `max_bytes <= 0` means
  // the transport-wide frame cap; the handshake passes a tight bound so
  // a hostile/garbled registration connection cannot force a huge
  // allocation on the controller.
  static bool SendFramed(int fd, const Message& msg);
  // `body_timeout_ms > 0` bounds the read of a frame's BODY once its
  // length prefix arrived (an idle connection may block forever on the
  // prefix — that is legitimate; a peer that stalls mid-frame is not).
  // `frame_bytes` (optional) receives the frame's byte count — the
  // receive-side feed for the net.bytes.recv counter.
  static bool RecvFramed(int fd, Message* msg, int64_t max_bytes = 0,
                         int64_t body_timeout_ms = 0,
                         int64_t* frame_bytes = nullptr);

  // Dynamic registration (reference src/controller.cpp Control_Register,
  // SURVEY.md §2.7/§3.1): the controller listens on `ctrl_endpoint`,
  // collects `num_nodes - 1` ControlRegister messages (each carrying the
  // registrant's endpoint + role bitmask), assigns ranks in arrival
  // order, and answers every registrant with the full node table.
  // Registrants block until the table arrives.  On success: endpoints
  // and roles are rank-indexed, *my_rank is set (controller == 0), and
  // every registration socket is closed — the regular transport then
  // starts from the returned table.
  // `timeout_ms` bounds the whole collection (a crashed registrant must
  // not hang MV_Init forever); silent clients are bounded per-read.
  static bool RegisterController(const std::string& ctrl_endpoint,
                                 int num_nodes, int my_role,
                                 std::vector<std::string>* endpoints,
                                 std::vector<int>* roles,
                                 int64_t timeout_ms = 30000);
  static bool RegisterWithController(const std::string& ctrl_endpoint,
                                     const std::string& my_endpoint,
                                     int my_role, int64_t retry_ms,
                                     std::vector<std::string>* endpoints,
                                     std::vector<int>* roles, int* my_rank);

  // Bind + listen on endpoints[rank]'s port, start the accept loop,
  // deliver every inbound message to `fn` (called from reader threads).
  // `connect_retry_ms` bounds each lazy-connect's retry budget.
  bool Init(const std::vector<std::string>& endpoints, int rank,
            InboundFn fn, int64_t connect_retry_ms = 15000) override;

  // Frame + write to the peer (lazy connect with retries — peers start
  // in any order; scatter-gather, so the payload is never copied into a
  // contiguous wire buffer first).  A failed write is retried up to
  // `-send_retries` times with exponential backoff (`-send_backoff_ms`
  // base), reconnecting between attempts; writes are bounded by
  // `-io_timeout_ms` (SO_SNDTIMEO) so a wedged peer cannot park the
  // sender forever.  Fault-injection hooks (mvtpu/fault.h) sit on this
  // path: drop/delay/duplicate per logical message, fail per attempt.
  // Dashboard counters: net.retries, net.dropped.  Returns false on a
  // dead peer (after the retry budget).
  bool Send(int dst_rank, const Message& msg) override;

  void Stop() override;

  int rank() const override { return rank_; }
  int size() const override { return static_cast<int>(endpoints_.size()); }
  const char* engine() const override { return "tcp"; }

 private:
  void AcceptLoop();
  void ReadLoop(int fd);
  int ConnectTo(int dst_rank);
  // One connect-if-needed + framed-write attempt (no retry).  The retry
  // loop re-invokes this with the same Message — the iovec set is
  // rebuilt per attempt, so a partial write on a torn-down connection
  // never leaks into the next one.
  bool SendAttempt(int dst_rank, const Message& msg);

  std::vector<std::string> endpoints_;
  int rank_ = 0;
  InboundFn inbound_;
  int64_t connect_retry_ms_ = 15000;

  // listen_fd_/running_ are atomics, not mutex-guarded: AcceptLoop
  // blocks inside ::accept() holding no lock while Stop() shuts the fd
  // down from another thread to unblock it — the flags must be readable
  // concurrently with that teardown (TSan-verified, round 5).
  std::atomic<int> listen_fd_{-1};
  std::thread accept_thread_;
  Mutex readers_mu_;
  std::vector<std::thread> readers_ GUARDED_BY(readers_mu_);
  std::vector<int> accepted_fds_ GUARDED_BY(readers_mu_);

  // Per-destination locks: send_mus_[i] guards send_fds_[i] (lazy
  // connect install + framed write).  A per-ELEMENT capability is
  // beyond the annotation language, so the pairing is enforced by
  // review + TSan; the vectors themselves are sized once in Init.
  std::vector<int> send_fds_;
  std::vector<std::unique_ptr<Mutex>> send_mus_;

  std::atomic<bool> running_{false};
  Mutex mu_;  // serializes Stop vs ConnectTo's retry-abort check
};

}  // namespace mvtpu
