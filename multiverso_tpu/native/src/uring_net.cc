// mvlint: reactor-context — this file runs inside the io_uring
// completion loop.  The completion model never issues a blocking socket
// call from the reactor (the kernel owns the waiting), but the
// pre-reactor connect/Hello handshake below uses the same blocking
// socket discipline as epoll_net.cc and carries the same MV009
// exemptions; and every CQE drain is BATCH-BOUNDED, enforced by mvlint
// rule MV019 (docs/transport.md).
#include "mvtpu/uring_net.h"

#include <arpa/inet.h>
#include <errno.h>
#include <linux/io_uring.h>
#include <linux/time_types.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/syscall.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdlib>
#include <cstring>

#include "mvtpu/configure.h"
#include "mvtpu/dashboard.h"
#include "mvtpu/fault.h"
#include "mvtpu/host_arena.h"
#include "mvtpu/latency.h"
#include "mvtpu/log.h"
#include "mvtpu/net.h"
#include "mvtpu/ops.h"
#include "mvtpu/qos.h"
#include "mvtpu/watchdog.h"

namespace mvtpu {

namespace {

// ---- uapi supplements: the container's linux/io_uring.h predates the
// zero-copy send and multishot-accept uapi, but the RUNNING kernel has
// both — define the constants locally and let runtime probing (not the
// compile-time header) decide what is actually used.
constexpr uint8_t kOpSendmsgZc = 48;      // IORING_OP_SENDMSG_ZC (5.19+)
constexpr uint32_t kCqeFNotif = 1u << 3;  // IORING_CQE_F_NOTIF
constexpr uint16_t kAcceptMultishot = 1u << 0;  // IORING_ACCEPT_MULTISHOT
constexpr uint16_t kProbeOpSupported = 1u << 0;  // IO_URING_OP_SUPPORTED

// user_data encoding: [63:56] op kind, [55:32] zero-copy sequence,
// [31:0] connection id.  Conn IDs are monotonic — NEVER the fd — so a
// stale CQE for a torn-down connection can't alias a reused descriptor.
enum : uint8_t {
  kKindWake = 1,
  kKindAccept = 2,
  kKindTimeout = 3,
  kKindRecv = 4,
  kKindSend = 5,
  kKindSendZc = 6,
};

constexpr uint64_t MakeUd(uint8_t kind, uint32_t zc_seq, uint32_t conn_id) {
  return (static_cast<uint64_t>(kind) << 56) |
         (static_cast<uint64_t>(zc_seq & 0xffffffu) << 32) | conn_id;
}

bool SplitHostPort(const std::string& ep, std::string* host, int* port) {
  auto colon = ep.rfind(':');
  if (colon == std::string::npos) return false;
  *host = ep.substr(0, colon);
  try {
    *port = std::stoi(ep.substr(colon + 1));
  } catch (...) {
    return false;
  }
  return *port > 0 && *port < 65536;
}

int64_t FlagOr(const char* name, int64_t dflt) {
  return mvtpu::configure::Has(name) ? mvtpu::configure::GetInt(name)
                                     : dflt;
}

void SetNoDelay(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

bool AddrIsLoopback(const sockaddr* sa) {
  if (sa->sa_family == AF_INET) {
    const auto* in4 = reinterpret_cast<const sockaddr_in*>(sa);
    return (ntohl(in4->sin_addr.s_addr) >> 24) == 127;
  }
  if (sa->sa_family == AF_INET6) {
    const auto* in6 = reinterpret_cast<const sockaddr_in6*>(sa);
    if (IN6_IS_ADDR_LOOPBACK(&in6->sin6_addr)) return true;
    return IN6_IS_ADDR_V4MAPPED(&in6->sin6_addr) &&
           in6->sin6_addr.s6_addr[12] == 127;
  }
  return false;
}

bool PeerIsLoopback(int fd) {
  sockaddr_storage ss;
  socklen_t sl = sizeof(ss);
  if (::getpeername(fd, reinterpret_cast<sockaddr*>(&ss), &sl) != 0)
    return false;
  return AddrIsLoopback(reinterpret_cast<const sockaddr*>(&ss));
}

// Frame caps — identical to EpollNet: rank peers may ship table shards,
// unidentified/anonymous connections are capped small.
constexpr int64_t kMaxRankFrameBytes = int64_t{1} << 40;
constexpr int64_t kMaxClientFrameBytes = int64_t{1} << 26;  // 64 MiB
constexpr size_t kDefaultSlabBytes = 256 << 10;
constexpr size_t kMaxIov = 64;

#if defined(__SANITIZE_THREAD__)
#define MVTPU_TSAN 1
#elif defined(__has_feature)
#if __has_feature(thread_sanitizer)
#define MVTPU_TSAN 1
#endif
#endif

// Same rewind discipline as EpollNet::SlabExclusive: use_count()==1
// plus an acquire fence pairing with the consumer's shared_ptr release;
// compiled out under TSan (which does not model fences) in favor of a
// fresh allocation.
template <typename T>
bool HandleExclusive(const std::shared_ptr<T>& h) {
#ifdef MVTPU_TSAN
  (void)h;
  return false;
#else
  if (h.use_count() != 1) return false;
  std::atomic_thread_fence(std::memory_order_acquire);
  return true;
#endif
}

int UringSetup(unsigned entries, io_uring_params* p) {
  return static_cast<int>(::syscall(__NR_io_uring_setup, entries, p));
}

int UringEnter(int fd, unsigned to_submit, unsigned min_complete,
               unsigned flags) {
  return static_cast<int>(::syscall(__NR_io_uring_enter, fd, to_submit,
                                    min_complete, flags, nullptr,
                                    size_t{0}));
}

int UringRegister(int fd, unsigned opcode, void* arg, unsigned nr_args) {
  return static_cast<int>(
      ::syscall(__NR_io_uring_register, fd, opcode, arg, nr_args));
}

// One-shot opcode support check (its own tiny ring, torn down before
// returning): io_uring reports per-opcode support via REGISTER_PROBE.
bool KernelSupportsOp(uint8_t op, std::string* reason) {
  io_uring_params p{};
  int fd = UringSetup(4, &p);
  if (fd < 0) {
    if (reason)
      *reason = std::string("io_uring_setup: ") + ::strerror(errno);
    return false;
  }
  struct {
    io_uring_probe probe;
    io_uring_probe_op ops[64];
  } pb;
  std::memset(&pb, 0, sizeof(pb));
  int rc = UringRegister(fd, IORING_REGISTER_PROBE, &pb, 64);
  ::close(fd);
  if (rc < 0) {
    if (reason)
      *reason = std::string("IORING_REGISTER_PROBE: ") + ::strerror(errno);
    return false;
  }
  if (op >= pb.probe.ops_len ||
      !(pb.ops[op].flags & kProbeOpSupported)) {
    if (reason)
      *reason = "kernel lacks io_uring opcode " + std::to_string(op);
    return false;
  }
  return true;
}

}  // namespace

namespace uring {

bool Probe(std::string* reason) {
  const char* force = ::getenv("MVTPU_URING_FORCE_UNSUPPORTED");
  if (force != nullptr && force[0] == '1') {
    if (reason)
      *reason = "forced unsupported (MVTPU_URING_FORCE_UNSUPPORTED=1)";
    return false;
  }
  // Every opcode the data plane cannot run without.  SENDMSG_ZC is
  // deliberately absent — it degrades to plain SENDMSG per send.
  const uint8_t need[] = {IORING_OP_READ_FIXED, IORING_OP_POLL_ADD,
                          IORING_OP_SENDMSG,    IORING_OP_TIMEOUT,
                          IORING_OP_ACCEPT,     IORING_OP_RECV};
  for (uint8_t op : need)
    if (!KernelSupportsOp(op, reason)) return false;
  return true;
}

}  // namespace uring

// Identical layout + gather semantics to EpollNet::PendingFrame (the
// PR 5 no-copy send contract); held by shared_ptr here because a frame
// must outlive its queue slot while the kernel references its pages
// (the in-flight `sending` hold and the zero-copy `zc_holds` pins).
struct UringNet::PendingFrame {
  struct Head {
    int64_t frame_len;
    WireHeader h;
  } head;
  std::vector<int64_t> lens;
  Message msg;        // shallow blob copies keep the payload alive
  int64_t total = 0;  // prefix + frame bytes
  int64_t done = 0;   // bytes already on the wire

  explicit PendingFrame(const Message& m) : msg(m) {
    head.frame_len = m.WireBytes();
    m.FillWireHeader(&head.h);
    lens.resize(m.data.size());
    for (size_t i = 0; i < m.data.size(); ++i)
      lens[i] = static_cast<int64_t>(m.data[i].size());
    total = head.frame_len + static_cast<int64_t>(sizeof(int64_t));
  }

  size_t FillIov(iovec* iov, size_t max_iov) {
    size_t n = 0;
    int64_t skip = done;
    auto push = [&](const void* base, size_t len) {
      if (n >= max_iov || len == 0) return;
      if (skip >= static_cast<int64_t>(len)) {
        skip -= static_cast<int64_t>(len);
        return;
      }
      iov[n].iov_base = const_cast<char*>(
          static_cast<const char*>(base) + skip);
      iov[n].iov_len = len - static_cast<size_t>(skip);
      skip = 0;
      ++n;
    };
    push(&head, sizeof(head));
    if (msg.has_timing()) push(&msg.timing, sizeof(TimingTrail));
    if (msg.has_audit()) push(&msg.audit, sizeof(AuditStamp));
    if (msg.has_qos()) push(&msg.qos, sizeof(QosStamp));
    for (size_t i = 0; i < msg.data.size(); ++i) {
      push(&lens[i], sizeof(int64_t));
      push(msg.data[i].data(), msg.data[i].size());
    }
    return n;
  }
};

// Per-shard pool of fixed receive buffers: `-uring_reg_bufs` HostArena
// buffers registered once with IORING_REGISTER_BUFFERS.  The pool is
// held by shared_ptr from the Shard AND from every outstanding RegSlab,
// so the HostArena caller-holds release only after the engine is down
// AND the last consumer view has died — never under an in-flight DMA.
struct UringNet::RegPool {
  std::vector<char*> bases;
  size_t cap = 0;
  Mutex mu;
  std::vector<int> free_list GUARDED_BY(mu);

  ~RegPool() {
    for (char* b : bases) HostArena::Get()->Release(b);
  }

  int TryTake() {
    MutexLock lk(mu);
    if (free_list.empty()) return -1;
    int idx = free_list.back();
    free_list.pop_back();
    return idx;
  }
  void Put(int idx) {
    MutexLock lk(mu);
    free_list.push_back(idx);
  }
};

// One leased registered buffer.  The conn holds it while frames
// assemble; Blob::Borrow keepalives are aliases of the same handle, so
// the destructor — wherever the LAST view drops — returns the buffer
// index to the pool for the next conn.
struct UringNet::RegSlab {
  char* base;
  size_t cap;
  int index;
  std::shared_ptr<RegPool> pool;

  RegSlab(char* b, size_t c, int i, std::shared_ptr<RegPool> p)
      : base(b), cap(c), index(i), pool(std::move(p)) {}
  ~RegSlab() { pool->Put(index); }

  static std::shared_ptr<RegSlab> Take(const std::shared_ptr<RegPool>& p) {
    int idx = p->TryTake();
    if (idx < 0) return nullptr;
    return std::make_shared<RegSlab>(p->bases[static_cast<size_t>(idx)],
                                     p->cap, idx, p);
  }
};

struct UringNet::Conn {
  int fd = -1;
  int shard = 0;
  uint32_t id = 0;
  bool accepted = false;
  std::atomic<int> peer{-1};

  // ---- read state machine: owning shard's reactor thread only.
  char len_buf[sizeof(int64_t)] = {0};
  size_t len_got = 0;
  int64_t body_len = -1;  // -1: reading the length prefix
  size_t body_got = 0;
  // The frame's home is EITHER a registered slab (zero-copy READ_FIXED
  // + Blob::Borrow) or a heap fallback slab (plain RECV + Blob::View);
  // frame_in_reg says which one the CURRENT frame assembles in.
  std::shared_ptr<RegSlab> reg;
  std::shared_ptr<std::vector<char>> heap;
  bool frame_in_reg = false;
  size_t slab_off = 0;
  size_t slab_used = 0;
  // Heap-slab bytes counted in rx_arena_total_ (registered pool bytes
  // are counted once, engine-wide, at Init).
  size_t heap_tracked = 0;

  // ---- in-flight op accounting: reactor-only.  At most ONE recv and
  // ONE send SQE outstanding per conn; close is two-phase (RetireConn
  // shuts the socket down, FinalizeConn runs at pending_ops == 0).
  bool recv_armed = false;
  bool send_armed = false;
  int pending_ops = 0;
  bool closing = false;
  // The frame BATCH the in-flight send references (survives a wq
  // teardown), plus a pin per un-notified zero-copy send: the kernel
  // reads these pages AFTER sendmsg completes, until the F_NOTIF CQE.
  std::vector<std::shared_ptr<PendingFrame>> sending;
  iovec iov[kMaxIov];
  msghdr mh {};
  uint32_t zc_next = 1;
  std::unordered_map<uint32_t, std::vector<std::shared_ptr<PendingFrame>>>
      zc_holds;
  // Loopback peers never take the SENDMSG_ZC path: MSG_ZEROCOPY over
  // loopback is copied by the kernel anyway and the notification is
  // deferred until the RECEIVER consumes the skb — measured ~2x slower
  // than plain SENDMSG at the 64 KiB frame point, pure overhead.
  bool peer_loopback = false;

  std::atomic<long long> inflight{0};
  std::atomic<int> qos_class{-1};

  Mutex mu;
  CondVar can_write;  // backpressure + drain-on-stop waiters
  // capacity: wq_bytes_total_ gauge — the "capacity" report's
  // net.writeq_bytes; bounded per conn by -net_writeq_bytes.
  std::deque<std::shared_ptr<PendingFrame>> wq GUARDED_BY(mu);
  int64_t wq_bytes GUARDED_BY(mu) = 0;
  bool closed GUARDED_BY(mu) = false;
};

struct UringNet::Shard {
  int idx = 0;
  int ring_fd = -1;
  int wake_fd = -1;
  std::thread thread;

  // ---- mmap'd rings: reactor-owned after setup (Stop touches them
  // only after thread.join()).
  void* sq_ring = nullptr;
  void* cq_ring = nullptr;
  size_t sq_ring_sz = 0;
  size_t cq_ring_sz = 0;
  bool single_mmap = false;
  io_uring_sqe* sqes = nullptr;
  size_t sqes_sz = 0;
  unsigned* sq_head = nullptr;
  unsigned* sq_tail = nullptr;
  unsigned* sq_flags = nullptr;
  unsigned* sq_array = nullptr;
  unsigned sq_mask = 0;
  unsigned sq_entries = 0;
  unsigned sq_tail_local = 0;
  unsigned sq_pending = 0;
  unsigned* cq_head = nullptr;
  unsigned* cq_tail = nullptr;
  io_uring_cqe* cqes = nullptr;
  unsigned cq_mask = 0;
  bool sqpoll = false;

  bool wake_armed = false;
  bool accept_armed = false;
  bool timeout_armed = false;
  // Downgrade latches: old kernels without multishot answer -EINVAL
  // once; after that the op re-arms single-shot.
  bool poll_multishot = true;
  bool accept_multishot = true;
  // Stable across the in-flight TIMEOUT op (the kernel copies it at
  // prep, but keeping it pinned costs nothing and survives uapi drift).
  struct __kernel_timespec tick_ts {};

  std::shared_ptr<RegPool> pool;

  Mutex mu;
  std::vector<std::shared_ptr<Conn>> to_register GUARDED_BY(mu);
  std::vector<std::shared_ptr<Conn>> to_arm GUARDED_BY(mu);
  // conn-id -> conn; reactor-thread-only after registration.
  std::unordered_map<uint32_t, std::shared_ptr<Conn>> conns;
};

UringNet::~UringNet() { Stop(); }

// ---------------------------------------------------------------- ring

bool UringNet::SetupRing(Shard* s, unsigned depth, bool sqpoll) {
  io_uring_params p{};
  p.flags = IORING_SETUP_CQSIZE;
  p.cq_entries = depth * 4;  // CQ headroom: multishot ops fan out CQEs
  if (sqpoll) {
    p.flags |= IORING_SETUP_SQPOLL;
    p.sq_thread_idle = 1000;
  }
  int fd = UringSetup(depth, &p);
  if (fd < 0 && sqpoll) {
    Log::Info("UringNet: SQPOLL setup failed (%s) — plain submission",
              ::strerror(errno));
    std::memset(&p, 0, sizeof(p));
    p.flags = IORING_SETUP_CQSIZE;
    p.cq_entries = depth * 4;
    sqpoll = false;
    fd = UringSetup(depth, &p);
  }
  if (fd < 0) {
    Log::Error("UringNet: io_uring_setup failed: %s", ::strerror(errno));
    return false;
  }
  s->ring_fd = fd;
  s->sqpoll = sqpoll;
  s->sq_ring_sz = p.sq_off.array + p.sq_entries * sizeof(unsigned);
  s->cq_ring_sz = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
  s->single_mmap = (p.features & IORING_FEAT_SINGLE_MMAP) != 0;
  if (s->single_mmap)
    s->sq_ring_sz = s->cq_ring_sz = std::max(s->sq_ring_sz, s->cq_ring_sz);
  s->sq_ring = ::mmap(nullptr, s->sq_ring_sz, PROT_READ | PROT_WRITE,
                      MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQ_RING);
  if (s->sq_ring == MAP_FAILED) {
    s->sq_ring = nullptr;
    TeardownRing(s);
    return false;
  }
  if (s->single_mmap) {
    s->cq_ring = s->sq_ring;
  } else {
    s->cq_ring = ::mmap(nullptr, s->cq_ring_sz, PROT_READ | PROT_WRITE,
                        MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_CQ_RING);
    if (s->cq_ring == MAP_FAILED) {
      s->cq_ring = nullptr;
      TeardownRing(s);
      return false;
    }
  }
  s->sqes_sz = p.sq_entries * sizeof(io_uring_sqe);
  s->sqes = static_cast<io_uring_sqe*>(
      ::mmap(nullptr, s->sqes_sz, PROT_READ | PROT_WRITE,
             MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQES));
  if (s->sqes == MAP_FAILED) {
    s->sqes = nullptr;
    TeardownRing(s);
    return false;
  }
  char* sqr = static_cast<char*>(s->sq_ring);
  char* cqr = static_cast<char*>(s->cq_ring);
  s->sq_head = reinterpret_cast<unsigned*>(sqr + p.sq_off.head);
  s->sq_tail = reinterpret_cast<unsigned*>(sqr + p.sq_off.tail);
  s->sq_flags = reinterpret_cast<unsigned*>(sqr + p.sq_off.flags);
  s->sq_array = reinterpret_cast<unsigned*>(sqr + p.sq_off.array);
  s->sq_mask = *reinterpret_cast<unsigned*>(sqr + p.sq_off.ring_mask);
  s->sq_entries = p.sq_entries;
  s->sq_tail_local = *s->sq_tail;
  s->cq_head = reinterpret_cast<unsigned*>(cqr + p.cq_off.head);
  s->cq_tail = reinterpret_cast<unsigned*>(cqr + p.cq_off.tail);
  s->cq_mask = *reinterpret_cast<unsigned*>(cqr + p.cq_off.ring_mask);
  s->cqes = reinterpret_cast<io_uring_cqe*>(cqr + p.cq_off.cqes);
  return true;
}

void UringNet::TeardownRing(Shard* s) {
  if (s->ring_fd >= 0 && s->pool)
    UringRegister(s->ring_fd, IORING_UNREGISTER_BUFFERS, nullptr, 0);
  if (s->sqes) ::munmap(s->sqes, s->sqes_sz);
  if (s->cq_ring && !s->single_mmap) ::munmap(s->cq_ring, s->cq_ring_sz);
  if (s->sq_ring) ::munmap(s->sq_ring, s->sq_ring_sz);
  s->sqes = nullptr;
  s->sq_ring = nullptr;
  s->cq_ring = nullptr;
  if (s->ring_fd >= 0) ::close(s->ring_fd);
  s->ring_fd = -1;
  if (s->wake_fd >= 0) ::close(s->wake_fd);
  s->wake_fd = -1;
}

void* UringNet::GetSqe(Shard* s) {
  // SQ-full is transient — a flush hands the window back — so the
  // retry here is BOUNDED, not while(true): a wedged SQPOLL thread
  // must surface as a conn error, not a hung reactor.
  for (int tries = 0; tries < 1000; ++tries) {
    unsigned head = __atomic_load_n(s->sq_head, __ATOMIC_ACQUIRE);
    if (s->sq_tail_local - head < s->sq_entries) {
      io_uring_sqe* sqe = &s->sqes[s->sq_tail_local & s->sq_mask];
      std::memset(sqe, 0, sizeof(*sqe));
      s->sq_array[s->sq_tail_local & s->sq_mask] =
          s->sq_tail_local & s->sq_mask;
      ++s->sq_tail_local;
      ++s->sq_pending;
      return sqe;
    }
    SubmitPending(s, /*wait=*/false);
  }
  return nullptr;
}

int UringNet::SubmitPending(Shard* s, bool wait) {
  __atomic_store_n(s->sq_tail, s->sq_tail_local, __ATOMIC_RELEASE);
  unsigned to_submit = s->sq_pending;
  unsigned flags = 0;
  if (s->sqpoll) {
    // The kernel thread consumes the SQ by itself; enter() is only a
    // wakeup (when it idled) or a completion wait.
    s->sq_pending = 0;
    to_submit = 0;
    if (__atomic_load_n(s->sq_flags, __ATOMIC_ACQUIRE) &
        IORING_SQ_NEED_WAKEUP)
      flags |= IORING_ENTER_SQ_WAKEUP;
    if (!wait && flags == 0) return 0;
  }
  if (wait) flags |= IORING_ENTER_GETEVENTS;
  while (true) {
    int r = UringEnter(s->ring_fd, to_submit, wait ? 1u : 0u, flags);
    if (r >= 0) {
      if (!s->sqpoll) s->sq_pending = to_submit - static_cast<unsigned>(r);
      return r;
    }
    if (errno == EINTR) continue;
    if (errno == EAGAIN || errno == EBUSY) {
      // CQ backed up: the caller's drain is what frees it — yield
      // briefly so a wait-mode call doesn't spin hot.
      if (wait)
        std::this_thread::sleep_for(std::chrono::microseconds(100));
      return -1;
    }
    Log::Error("UringNet: io_uring_enter failed: %s", ::strerror(errno));
    return -1;
  }
}

unsigned UringNet::DrainCqes(Shard* s) {
  // Bounded batch (mvlint MV019): cap CQEs consumed per call so a peer
  // that can keep the CQ non-empty cannot starve the running_ check —
  // leftovers satisfy the next cycle's GETEVENTS immediately.
  constexpr unsigned kCqeBatch = 256;
  unsigned head = __atomic_load_n(s->cq_head, __ATOMIC_RELAXED);
  unsigned n = 0;
  while (n < kCqeBatch) {
    unsigned tail = __atomic_load_n(s->cq_tail, __ATOMIC_ACQUIRE);
    if (head == tail) break;
    const io_uring_cqe* cqe = &s->cqes[head & s->cq_mask];
    // Copy out BEFORE advancing head: the kernel owns the entry again
    // the instant the head store lands.
    uint64_t ud = cqe->user_data;
    int32_t res = cqe->res;
    uint32_t fl = cqe->flags;
    ++head;
    __atomic_store_n(s->cq_head, head, __ATOMIC_RELEASE);
    ProcessCqe(s, ud, res, fl);
    ++n;
  }
  return n;
}

// ---------------------------------------------------------- arming ops

void UringNet::ArmWake(Shard* s) {
  if (s->wake_armed || !running_) return;
  auto* sqe = static_cast<io_uring_sqe*>(GetSqe(s));
  if (!sqe) return;  // timeout tick retries
  sqe->opcode = IORING_OP_POLL_ADD;
  sqe->fd = s->wake_fd;
  if (s->poll_multishot) sqe->len = IORING_POLL_ADD_MULTI;
  sqe->poll32_events = POLLIN;
  sqe->user_data = MakeUd(kKindWake, 0, 0);
  s->wake_armed = true;
}

void UringNet::ArmAccept(Shard* s) {
  if (s->accept_armed || !running_) return;
  int lfd = listen_fd_.load();
  if (lfd < 0) return;
  auto* sqe = static_cast<io_uring_sqe*>(GetSqe(s));
  if (!sqe) return;
  sqe->opcode = IORING_OP_ACCEPT;
  sqe->fd = lfd;
  if (s->accept_multishot) sqe->ioprio = kAcceptMultishot;
  sqe->user_data = MakeUd(kKindAccept, 0, 0);
  s->accept_armed = true;
}

void UringNet::ArmTimeout(Shard* s) {
  if (s->timeout_armed || !running_) return;
  auto* sqe = static_cast<io_uring_sqe*>(GetSqe(s));
  if (!sqe) return;
  // The loop's 200 ms heartbeat: epoll_wait's timeout argument,
  // recast as an operation (running_ checks + watchdog cadence + a
  // retry tick for transiently unarmable ops).
  s->tick_ts.tv_sec = 0;
  s->tick_ts.tv_nsec = 200 * 1000 * 1000;
  sqe->opcode = IORING_OP_TIMEOUT;
  sqe->fd = -1;
  sqe->addr = reinterpret_cast<uint64_t>(&s->tick_ts);
  sqe->len = 1;
  sqe->user_data = MakeUd(kKindTimeout, 0, 0);
  s->timeout_armed = true;
}

void UringNet::ArmRecv(Shard* s, const std::shared_ptr<Conn>& c) {
  if (c->recv_armed || c->closing || !running_) return;
  auto* sqe = static_cast<io_uring_sqe*>(GetSqe(s));
  if (!sqe) {
    RetireConn(s, c, "submission queue exhausted");
    return;
  }
  if (c->body_len < 0) {
    // Length prefix — possibly one byte at a time (dribble peers).
    sqe->opcode = IORING_OP_RECV;
    sqe->fd = c->fd;
    sqe->addr = reinterpret_cast<uint64_t>(c->len_buf + c->len_got);
    sqe->len = static_cast<uint32_t>(sizeof(c->len_buf) - c->len_got);
  } else {
    size_t want = static_cast<size_t>(c->body_len) - c->body_got;
    if (c->frame_in_reg) {
      // Zero-copy landing: the kernel writes straight into the
      // registered slab — no per-op pin/unpin, no bounce buffer.
      sqe->opcode = IORING_OP_READ_FIXED;
      sqe->fd = c->fd;
      sqe->addr = reinterpret_cast<uint64_t>(c->reg->base + c->slab_off +
                                             c->body_got);
      sqe->len = static_cast<uint32_t>(want);
      sqe->buf_index = static_cast<uint16_t>(c->reg->index);
    } else {
      sqe->opcode = IORING_OP_RECV;
      sqe->fd = c->fd;
      sqe->addr = reinterpret_cast<uint64_t>(c->heap->data() + c->slab_off +
                                             c->body_got);
      sqe->len = static_cast<uint32_t>(want);
    }
  }
  sqe->user_data = MakeUd(kKindRecv, 0, c->id);
  c->recv_armed = true;
  ++c->pending_ops;
}

void UringNet::PumpSend(Shard* s, const std::shared_ptr<Conn>& c) {
  if (c->send_armed || c->closing || !running_) return;
  // Gather MULTIPLE queued frames into one SENDMSG: TCP is a byte
  // stream and the frame boundaries are the length prefixes already
  // inside the iovecs, so batching is free.  The readiness engine
  // amortizes syscalls by draining its write queue in a sendmsg loop
  // per wake; one ring roundtrip per frame here would halve streaming
  // throughput (measured on the wire_bench put burst).  A frame with
  // more segments than the remaining iov slots is covered PARTIALLY —
  // its tail goes out next pump, exactly like a short write.
  c->sending.clear();
  size_t niov = 0;
  int64_t remaining = 0;
  {
    MutexLock lk(c->mu);
    for (const auto& f : c->wq) {
      if (niov >= kMaxIov) break;
      size_t n = f->FillIov(c->iov + niov, kMaxIov - niov);
      if (n == 0) break;
      niov += n;
      remaining += f->total - f->done;
      c->sending.push_back(f);
    }
  }
  if (c->sending.empty()) return;
  auto* sqe = static_cast<io_uring_sqe*>(GetSqe(s));
  if (!sqe) {
    c->sending.clear();
    RetireConn(s, c, "submission queue exhausted");
    return;
  }
  std::memset(&c->mh, 0, sizeof(c->mh));
  c->mh.msg_iov = c->iov;
  c->mh.msg_iovlen = niov;
  const bool zc = zc_ok_.load(std::memory_order_relaxed) &&
                  !c->peer_loopback && remaining >= zc_bytes_;
  sqe->opcode =
      zc ? kOpSendmsgZc : static_cast<uint8_t>(IORING_OP_SENDMSG);
  sqe->fd = c->fd;
  sqe->addr = reinterpret_cast<uint64_t>(&c->mh);
  sqe->len = 1;
  sqe->msg_flags = MSG_NOSIGNAL;
  uint32_t seq = 0;
  if (zc) {
    seq = c->zc_next++ & 0xffffffu;
    if (seq == 0) seq = c->zc_next++ & 0xffffffu;
    // Pin until F_NOTIF: the kernel references these pages AFTER the
    // send's result CQE — releasing on result would hand a recycled
    // buffer to a DMA still reading it.
    c->zc_holds[seq] = c->sending;
  }
  sqe->user_data = MakeUd(zc ? kKindSendZc : kKindSend, seq, c->id);
  c->send_armed = true;
  c->pending_ops += zc ? 2 : 1;  // result CQE (+ notif CQE when zc)
}

// ------------------------------------------------------------ reactor

void UringNet::WakeShard(Shard* s) {
  uint64_t one = 1;
  ssize_t n = ::write(s->wake_fd, &one, sizeof(one));
  (void)n;  // EAGAIN means a wake is already pending — good enough
}

void UringNet::AdoptHandoffs(Shard* s) {
  std::vector<std::shared_ptr<Conn>> regs, arms;
  {
    MutexLock lk(s->mu);
    regs.swap(s->to_register);
    arms.swap(s->to_arm);
  }
  for (auto& c : regs) {
    s->conns[c->id] = c;
    ArmRecv(s, c);
  }
  for (auto& c : arms) {
    auto it = s->conns.find(c->id);
    if (it == s->conns.end() || it->second != c) continue;
    PumpSend(s, c);
  }
}

void UringNet::ReactorLoop(Shard* s) {
  // Watchdog (docs/observability.md "health plane"): one Bump per
  // drained completion batch, "busy" while a batch is in hand — the
  // same contract the epoll reactor keeps, under a distinct loop name.
  const std::string wd_name = "uring." + std::to_string(s->idx);
  ArmWake(s);
  if (s->idx == 0) ArmAccept(s);
  ArmTimeout(s);
  while (running_) {
    SubmitPending(s, /*wait=*/true);
    if (!running_) break;
    unsigned avail = __atomic_load_n(s->cq_tail, __ATOMIC_ACQUIRE) -
                     __atomic_load_n(s->cq_head, __ATOMIC_RELAXED);
    watchdog::Busy(wd_name, static_cast<int>(avail));
    // Adopt hand-offs first so a just-connected peer's recv arms
    // before we wait again (the eventfd CQE also re-adopts, mirroring
    // the epoll engine's consumed-wake fix).
    AdoptHandoffs(s);
    DrainCqes(s);
    watchdog::Bump(wd_name);
    watchdog::Busy(wd_name, 0);
  }
}

void UringNet::ProcessCqe(Shard* s, uint64_t ud, int32_t res,
                          uint32_t fl) {
  const uint8_t kind = static_cast<uint8_t>(ud >> 56);
  const uint32_t seq = static_cast<uint32_t>((ud >> 32) & 0xffffffu);
  const uint32_t id = static_cast<uint32_t>(ud & 0xffffffffu);
  switch (kind) {
    case kKindWake: {
      if (!(fl & IORING_CQE_F_MORE)) s->wake_armed = false;
      if (res == -EINVAL && s->poll_multishot) {
        s->poll_multishot = false;  // old kernel: single-shot poll
        ArmWake(s);
        return;
      }
      uint64_t junk;
      while (::read(s->wake_fd, &junk, sizeof(junk)) > 0) {
      }
      // Adopt AFTER draining the eventfd — a sender enqueueing between
      // the loop-top adoption and this drain just had its wake
      // consumed (the epoll engine's lost-wakeup fix, verbatim).
      AdoptHandoffs(s);
      ArmWake(s);
      return;
    }
    case kKindAccept: {
      if (!(fl & IORING_CQE_F_MORE)) s->accept_armed = false;
      if (res >= 0) {
        OnAccepted(s, res);
      } else if (res == -EINVAL && s->accept_multishot) {
        s->accept_multishot = false;  // old kernel: re-armed single-shot
      } else if (res != -EAGAIN && res != -EINTR &&
                 res != -ECONNABORTED) {
        return;  // listen socket gone (Stop) — do not re-arm
      }
      ArmAccept(s);
      return;
    }
    case kKindTimeout: {
      s->timeout_armed = false;
      AdoptHandoffs(s);
      // Retry tick for ops a transiently-full SQ left unarmed.
      ArmWake(s);
      if (s->idx == 0) ArmAccept(s);
      ArmTimeout(s);
      return;
    }
    default:
      break;
  }
  auto it = s->conns.find(id);
  if (it == s->conns.end()) return;  // conn finalized earlier
  std::shared_ptr<Conn> c = it->second;
  switch (kind) {
    case kKindRecv:
      OnRecv(s, c, res);
      break;
    case kKindSend:
      OnSent(s, c, res, fl, 0, /*zc=*/false);
      break;
    case kKindSendZc:
      OnSent(s, c, res, fl, seq, /*zc=*/true);
      break;
    default:
      break;
  }
}

void UringNet::OnAccepted(Shard* s, int fd) {
  SetNoDelay(fd);
  auto c = std::make_shared<Conn>();
  c->fd = fd;
  c->peer_loopback = PeerIsLoopback(fd);
  c->accepted = true;
  c->id = next_conn_id_.fetch_add(1);
  c->shard = next_shard_.fetch_add(1) % static_cast<int>(shards_.size());
  {
    MutexLock lk(conns_mu_);
    all_conns_.push_back(c);
  }
  Shard* target = shards_[static_cast<size_t>(c->shard)].get();
  if (target == s) {
    s->conns[c->id] = c;
    ArmRecv(s, c);
  } else {
    {
      MutexLock lk(target->mu);
      target->to_register.push_back(c);
    }
    WakeShard(target);
  }
}

void UringNet::PlaceFrame(Shard* s, const std::shared_ptr<Conn>& c,
                          size_t need) {
  const size_t slab_bytes = static_cast<size_t>(
      FlagOr("net_arena_bytes", static_cast<int64_t>(kDefaultSlabBytes)));
  // 8-ALIGNED packing, same rationale as the epoll arena: the previous
  // frame may still be read through views while the next lands.
  c->slab_used = (c->slab_used + 7) & ~size_t{7};
  if (c->frame_in_reg && c->reg) {
    if (HandleExclusive(c->reg)) {
      if (need <= c->reg->cap) {
        c->slab_used = 0;  // rewind: nothing references the slab
        return;
      }
    } else if (c->slab_used + need <= c->reg->cap) {
      return;  // append into leftover registered space
    }
    // Registered slabs have a FIXED capacity — a frame that doesn't
    // fit moves the conn to a new home; the index returns to the pool
    // when the last view dies.
    c->reg.reset();
  } else if (!c->frame_in_reg && c->heap) {
    if (HandleExclusive(c->heap)) {
      if (c->heap->size() < need)
        c->heap->resize(std::max(need, slab_bytes));
      c->slab_used = 0;
      size_t sz = c->heap->size();
      if (sz != c->heap_tracked) {
        rx_arena_total_.fetch_add(static_cast<long long>(sz) -
                                      static_cast<long long>(c->heap_tracked),
                                  std::memory_order_relaxed);
        c->heap_tracked = sz;
      }
      return;
    }
    // Addition, never subtraction (the epoll engine's underflow
    // lesson): aligned slab_used can EXCEED size() after an exact fit.
    if (c->heap->size() >= c->slab_used + need) return;
  }
  // Fresh home: prefer a registered slab — zero-copy receive — and
  // fall back to heap when the pool is dry or the frame outgrows it.
  if (s->pool) {
    auto reg = RegSlab::Take(s->pool);
    if (reg && need <= reg->cap) {
      rx_arena_total_.fetch_add(-static_cast<long long>(c->heap_tracked),
                                std::memory_order_relaxed);
      c->heap_tracked = 0;
      c->heap.reset();
      c->reg = std::move(reg);
      c->frame_in_reg = true;
      c->slab_used = 0;
      return;
    }
    // An undersized lease bounces straight back to the pool here
    // (RegSlab destructor) — no conn ever holds a slab it can't use.
  }
  c->reg.reset();
  c->frame_in_reg = false;
  c->heap =
      std::make_shared<std::vector<char>>(std::max(need, slab_bytes));
  c->slab_used = 0;
  size_t sz = c->heap->size();
  rx_arena_total_.fetch_add(static_cast<long long>(sz) -
                                static_cast<long long>(c->heap_tracked),
                            std::memory_order_relaxed);
  c->heap_tracked = sz;
}

void UringNet::OnRecv(Shard* s, const std::shared_ptr<Conn>& c,
                      int32_t res) {
  c->recv_armed = false;
  --c->pending_ops;
  if (c->closing) {
    if (c->pending_ops == 0) FinalizeConn(s, c);
    return;
  }
  if (res == 0 || (res < 0 && res != -EAGAIN && res != -EINTR)) {
    RetireConn(s, c,
               res == 0
                   ? (c->body_len < 0 ? "peer closed" : "peer closed mid-frame")
                   : "read error");
    return;
  }
  if (res < 0) {  // -EAGAIN/-EINTR: just re-arm
    ArmRecv(s, c);
    return;
  }
  if (c->body_len < 0) {
    c->len_got += static_cast<size_t>(res);
    if (c->len_got == sizeof(c->len_buf)) {
      int64_t len;
      std::memcpy(&len, c->len_buf, sizeof(len));
      // PER FRAME cap selection, exactly like the epoll engine: the
      // Hello may identify the conn mid-stream and the very next
      // frame must already enjoy the rank bound.
      const int64_t max_frame =
          (c->accepted && c->peer.load() < 0) ||
                  transport::IsClientRank(c->peer.load())
              ? kMaxClientFrameBytes
              : kMaxRankFrameBytes;
      if (len <= 0 || len > max_frame) {
        RetireConn(s, c, "bad frame length");
        return;
      }
      PlaceFrame(s, c, static_cast<size_t>(len));
      c->slab_off = c->slab_used;
      c->body_len = len;
      c->body_got = 0;
      c->len_got = 0;
    }
  } else {
    c->body_got += static_cast<size_t>(res);
    if (c->body_got == static_cast<size_t>(c->body_len)) {
      if (!FinishFrame(s, c)) {
        RetireConn(s, c, "malformed frame");
        return;
      }
    }
  }
  ArmRecv(s, c);
}

void UringNet::OnSent(Shard* s, const std::shared_ptr<Conn>& c,
                      int32_t res, uint32_t fl, uint32_t seq, bool zc) {
  if (zc && (fl & kCqeFNotif)) {
    // The kernel dropped its last page reference for this send: the
    // frame (and the arena/table buffers under its iovecs) may now be
    // recycled.
    c->zc_holds.erase(seq);
    --c->pending_ops;
    if (c->closing && c->pending_ops == 0) FinalizeConn(s, c);
    return;
  }
  c->send_armed = false;
  --c->pending_ops;
  std::vector<std::shared_ptr<PendingFrame>> batch = std::move(c->sending);
  c->sending.clear();
  if (zc && !(fl & IORING_CQE_F_MORE)) {
    // No notif will follow (errored send): release the pin here.
    c->zc_holds.erase(seq);
    --c->pending_ops;
  }
  if (c->closing) {
    if (c->pending_ops == 0) FinalizeConn(s, c);
    return;
  }
  if (res < 0) {
    if (zc && (res == -EINVAL || res == -EOPNOTSUPP)) {
      // Engine-wide degradation, no data loss: the frame is still at
      // the queue head and resubmits as a plain SENDMSG.
      if (zc_ok_.exchange(false))
        Log::Info("UringNet: kernel rejected SENDMSG_ZC (%s) — "
                  "falling back to copying sends",
                  ::strerror(-res));
      PumpSend(s, c);
      return;
    }
    if (res == -EAGAIN || res == -EINTR) {
      PumpSend(s, c);
      return;
    }
    RetireConn(s, c, "write error");
    return;
  }
  {
    // Distribute the written bytes across the batch IN ORDER — the
    // iovecs were laid out front-to-back, so a short write leaves a
    // fully-sent prefix, one partial frame, and untouched tails that
    // all stay queued for the next pump.
    MutexLock lk(c->mu);
    int64_t left = res;
    for (const auto& f : batch) {
      if (left <= 0) break;
      const int64_t take = std::min<int64_t>(left, f->total - f->done);
      f->done += take;
      left -= take;
      if (f->done >= f->total) {
        Dashboard::Record("net.bytes.sent", static_cast<double>(f->total));
        if (!c->wq.empty() && c->wq.front() == f) {
          c->wq_bytes -= f->total;
          wq_bytes_total_.fetch_add(-f->total, std::memory_order_relaxed);
          c->wq.pop_front();
        }
      }
    }
    c->can_write.NotifyAll();
  }
  PumpSend(s, c);
}

bool UringNet::FinishFrame(Shard* s, const std::shared_ptr<Conn>& c) {
  size_t len = static_cast<size_t>(c->body_len);
  Dashboard::Record(
      "net.bytes.recv",
      static_cast<double>(c->body_len +
                          static_cast<int64_t>(sizeof(int64_t))));
  Message m;
  bool ok;
  if (c->frame_in_reg) {
    // Zero-copy decode over registered memory: blobs BORROW the slab
    // bytes, the keepalive is the RegSlab lease itself — the buffer
    // index returns to the pool when the last consumer drops.
    ok = Message::DeserializeBorrow(c->reg->base + c->slab_off, c->slab_off,
                                    len, std::shared_ptr<void>(c->reg), &m);
  } else {
    ok = Message::DeserializeView(c->heap, c->slab_off, len, &m);
  }
  c->slab_used = c->slab_off + len;
  c->body_len = -1;
  c->body_got = 0;
  if (!ok) return false;
  latency::StampRecv(&m);
  qos::AdoptDeadline(&m);

  // From here on the semantics are EpollNet::FinishFrame verbatim —
  // Hello identify, anonymous pseudo-ranks, reactor-answered cancel/
  // ops/busy, per-client + per-tenant admission (docs/transport.md).
  int peer = c->peer.load();
  if (c->accepted && peer < 0) {
    if (m.type == MsgType::Hello && m.src >= 0 &&
        m.src < static_cast<int>(endpoints_.size())) {
      peer = m.src;
      c->peer = peer;
    } else {
      peer = transport::kClientRankBase + next_client_.fetch_add(1);
      c->peer = peer;
      accepted_total_.fetch_add(1);
      active_clients_.fetch_add(1);
      MutexLock lk(conns_mu_);
      client_conns_[peer] = c;
    }
  }
  if (m.type == MsgType::Hello) return true;
  if (m.type == MsgType::RequestCancel) {
    qos::NoteCancel(transport::IsClientRank(peer) ? peer : m.src,
                    m.msg_id);
    Dashboard::Record("serve.hedge.cancel_noted", 0.0);
    return true;
  }
  if (m.type == MsgType::OpsQuery) {
    if (transport::IsClientRank(peer)) m.src = peer;
    if (m.version != 1) {
      Message reply;
      ops::BuildReply(m, &reply);
      reply.src = rank_;
      reply.dst = m.src;
      latency::StampDequeue(&m);
      latency::StampReply(m, &reply);
      latency::StampSend(&reply);
      return Enqueue(c, reply, /*may_block=*/false);
    }
    if (inbound_) inbound_(std::move(m));
    return true;
  }
  if (transport::IsClientRank(peer)) {
    m.src = peer;
    if (m.has_qos()) c->qos_class.store(m.qos.klass);
    int qc = c->qos_class.load();
    if (qc < 0) qc = 0;
    bool counted =
        m.type == MsgType::RequestGet || m.type == MsgType::RequestVersion ||
        m.type == MsgType::RequestReplica ||
        m.type == MsgType::RequestFlush ||
        (m.type == MsgType::RequestAdd && m.msg_id >= 0);
    bool readlike = counted && m.type != MsgType::RequestAdd &&
                    m.type != MsgType::RequestFlush;
    auto reply_busy = [&]() {
      Message busy;
      busy.type = MsgType::ReplyBusy;
      busy.table_id = m.table_id;
      busy.msg_id = m.msg_id;
      busy.trace_id = m.trace_id;
      busy.src = rank_;
      busy.dst = peer;
      latency::StampDequeue(&m);
      latency::StampReply(m, &busy);
      latency::StampSend(&busy);
      return Enqueue(c, busy, /*may_block=*/false);
    };
    if (readlike && qos::ShedExpired(m)) return true;
    int64_t cap = FlagOr("client_inflight_max", 64);
    if (cap > 0 && readlike && c->inflight.load() >= cap) {
      client_shed_.fetch_add(1);
      Dashboard::Record("serve.client_shed", 0.0);
      return reply_busy();
    }
    if (readlike && !qos::TryAdmit(qc)) return reply_busy();
    if (m.type == MsgType::RequestReplica &&
        (!mvtpu::configure::Has("replica_serve_reactor") ||
         mvtpu::configure::GetBool("replica_serve_reactor"))) {
      Message reply;
      ops::BuildReplicaReply(m, &reply);
      reply.src = rank_;
      reply.dst = peer;
      latency::StampDequeue(&m);
      latency::StampReply(m, &reply);
      latency::StampSend(&reply);
      qos::Release(qc);
      return Enqueue(c, reply, /*may_block=*/false);
    }
    if (counted) c->inflight.fetch_add(1);
  }
  (void)s;
  if (inbound_) inbound_(std::move(m));
  return true;
}

void UringNet::RetireConn(Shard* s, const std::shared_ptr<Conn>& c,
                          const char* why) {
  if (c->closing) return;
  c->closing = true;
  int peer = c->peer.load();
  Log::Debug("UringNet: closing connection (peer %d): %s", peer, why);
  // Force the kernel's in-flight recv/send on this socket to complete
  // (0 / ECONNRESET) without touching the submission queue; the fd
  // itself closes in FinalizeConn once the last CQE lands — closing it
  // now could let a reused descriptor meet a stale op.
  ::shutdown(c->fd, SHUT_RDWR);
  rx_arena_total_.fetch_add(-static_cast<long long>(c->heap_tracked),
                            std::memory_order_relaxed);
  c->heap_tracked = 0;
  {
    MutexLock lk(c->mu);
    c->closed = true;
    if (!c->wq.empty())
      Log::Error("UringNet: dropping %zu queued frame(s) to peer %d (%s)",
                 c->wq.size(), peer, why);
    c->wq.clear();
    wq_bytes_total_.fetch_add(-c->wq_bytes, std::memory_order_relaxed);
    c->wq_bytes = 0;
    c->can_write.NotifyAll();
  }
  {
    MutexLock lk(conns_mu_);
    if (transport::IsClientRank(peer)) {
      if (client_conns_.erase(peer)) active_clients_.fetch_add(-1);
    } else if (peer >= 0 &&
               peer < static_cast<int>(rank_conns_.size()) &&
               rank_conns_[static_cast<size_t>(peer)] == c) {
      rank_conns_[static_cast<size_t>(peer)] = nullptr;
    }
    for (auto it = all_conns_.begin(); it != all_conns_.end(); ++it)
      if (*it == c) {
        all_conns_.erase(it);
        break;
      }
  }
  if (c->pending_ops == 0) FinalizeConn(s, c);
}

void UringNet::FinalizeConn(Shard* s, const std::shared_ptr<Conn>& c) {
  ::close(c->fd);
  c->sending.clear();
  c->zc_holds.clear();
  c->reg.reset();
  c->heap.reset();
  s->conns.erase(c->id);
}

// ------------------------------------------------------------- control

bool UringNet::Init(const std::vector<std::string>& endpoints, int rank,
                    InboundFn fn, int64_t connect_retry_ms) {
  std::string why;
  if (!uring::Probe(&why)) {
    // The zoo probes before constructing us; this guards direct users.
    Log::Error("UringNet: io_uring unavailable: %s", why.c_str());
    return false;
  }
  endpoints_ = endpoints;
  rank_ = rank;
  inbound_ = std::move(fn);
  connect_retry_ms_ = connect_retry_ms;
  {
    MutexLock lk(conns_mu_);
    rank_conns_.assign(endpoints_.size(), nullptr);
  }

  std::string host;
  int port = 0;
  if (rank_ < 0 || rank_ >= static_cast<int>(endpoints_.size()) ||
      !SplitHostPort(endpoints_[rank_], &host, &port)) {
    Log::Error("UringNet: bad rank %d / endpoint list (%zu entries)",
               rank_, endpoints_.size());
    return false;
  }

  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return false;
  int one = 1;
  ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(lfd, 1024) < 0) {
    Log::Error("UringNet: cannot listen on port %d", port);
    ::close(lfd);
    return false;
  }
  listen_fd_ = lfd;

  const unsigned depth = static_cast<unsigned>(std::min<int64_t>(
      4096, std::max<int64_t>(8, FlagOr("uring_depth", 256))));
  const bool sqpoll = mvtpu::configure::Has("uring_sqpoll") &&
                      mvtpu::configure::GetBool("uring_sqpoll");
  const int64_t reg_bufs = std::min<int64_t>(
      1024, std::max<int64_t>(0, FlagOr("uring_reg_bufs", 16)));
  zc_bytes_ = FlagOr("uring_zc_bytes", 64 << 10);
  zc_ok_ = zc_bytes_ >= 0 && KernelSupportsOp(kOpSendmsgZc, nullptr);
  const size_t slab_bytes = std::max<size_t>(
      4096, static_cast<size_t>(FlagOr(
                "net_arena_bytes", static_cast<int64_t>(kDefaultSlabBytes))));

  int nshards = static_cast<int>(
      std::min<int64_t>(16, std::max<int64_t>(1, FlagOr("net_threads", 1))));
  running_ = true;
  stopping_ = false;
  // Two passes, like the epoll engine: every shard exists before any
  // reactor thread runs (round-robin placement reads shards_.size()).
  for (int i = 0; i < nshards; ++i) {
    auto s = std::make_unique<Shard>();
    s->idx = i;
    s->wake_fd = ::eventfd(0, EFD_NONBLOCK);
    if (s->wake_fd < 0 || !SetupRing(s.get(), depth, sqpoll)) {
      Log::Error("UringNet: shard %d setup failed", i);
      running_ = false;
      TeardownRing(s.get());
      ::close(lfd);
      listen_fd_ = -1;
      for (auto& sh : shards_) TeardownRing(sh.get());
      shards_.clear();
      return false;
    }
    // Registered receive pool: best-effort — a failed registration
    // (RLIMIT_MEMLOCK, exhausted arena) leaves the shard on the heap
    // fallback path, never broken.
    if (reg_bufs > 0) {
      auto pool = std::make_shared<RegPool>();
      pool->cap = slab_bytes;
      std::vector<iovec> iovs;
      for (int64_t b = 0; b < reg_bufs; ++b) {
        void* base = HostArena::Get()->Acquire(slab_bytes);
        if (base == nullptr) break;
        pool->bases.push_back(static_cast<char*>(base));
        iovs.push_back({base, slab_bytes});
      }
      if (!iovs.empty() &&
          UringRegister(s->ring_fd, IORING_REGISTER_BUFFERS, iovs.data(),
                        static_cast<unsigned>(iovs.size())) == 0) {
        {
          MutexLock lk(pool->mu);
          for (size_t b = 0; b < iovs.size(); ++b)
            pool->free_list.push_back(static_cast<int>(b));
        }
        s->pool = pool;
        rx_arena_total_.fetch_add(
            static_cast<long long>(iovs.size() * slab_bytes),
            std::memory_order_relaxed);
      } else {
        Log::Info("UringNet: shard %d running without registered buffers "
                  "(%s)",
                  i, iovs.empty() ? "arena dry" : ::strerror(errno));
      }
    }
    shards_.push_back(std::move(s));
  }
  for (auto& s : shards_) {
    Shard* raw = s.get();
    s->thread = std::thread([this, raw] { ReactorLoop(raw); });
  }
  Log::Info("UringNet: rank %d/%zu listening on :%d (%d shard%s, depth %u,"
            "%s%s %lld reg buf%s/shard)",
            rank_, endpoints_.size(), port, nshards,
            nshards == 1 ? "" : "s", depth,
            shards_[0]->sqpoll ? " sqpoll," : "",
            zc_ok_.load() ? " zc," : "",
            static_cast<long long>(reg_bufs), reg_bufs == 1 ? "" : "s");
  return true;
}

std::shared_ptr<UringNet::Conn> UringNet::ConnectToRank(int dst_rank) {
  std::string host;
  int port = 0;
  if (!SplitHostPort(endpoints_[static_cast<size_t>(dst_rank)], &host,
                     &port))
    return nullptr;
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (::getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints,
                    &res) != 0 ||
      !res)
    return nullptr;
  // Peers start in any order: blocking connect with the same retry
  // budget as TcpNet/EpollNet — the socket stays in blocking mode even
  // afterwards (the completion model needs no O_NONBLOCK; io_uring
  // parks the op internally).
  int fd = -1;
  int attempts = static_cast<int>(
      std::max<int64_t>(1, connect_retry_ms_ / 100));
  for (int attempt = 0; attempt < attempts; ++attempt) {
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) break;
    // Pre-reactor blocking handshake on the SENDER's thread.
    if (::connect(fd, res->ai_addr,  // mvlint: MV009-exempt(pre-reactor)
                  res->ai_addrlen) == 0)
      break;
    ::close(fd);
    fd = -1;
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
    if (!running_ || stopping_) break;
  }
  ::freeaddrinfo(res);
  if (fd < 0) return nullptr;
  SetNoDelay(fd);
  // Identify before payload: tiny Hello first, same as the epoll
  // engine — the accept side caps unidentified conns small.
  Message hello;
  hello.type = MsgType::Hello;
  hello.src = rank_;
  hello.dst = dst_rank;
  Blob hello_body = hello.Serialize();
  int64_t hello_len = static_cast<int64_t>(hello_body.size());
  std::vector<char> hello_wire(sizeof(hello_len) + hello_body.size());
  std::memcpy(hello_wire.data(), &hello_len, sizeof(hello_len));
  std::memcpy(hello_wire.data() + sizeof(hello_len), hello_body.data(),
              hello_body.size());
  size_t hello_sent = 0;
  while (hello_sent < hello_wire.size()) {
    ssize_t w = ::send(  // mvlint: MV009-exempt(pre-reactor handshake)
        fd, hello_wire.data() + hello_sent, hello_wire.size() - hello_sent,
        MSG_NOSIGNAL);
    if (w <= 0) {
      ::close(fd);
      return nullptr;
    }
    hello_sent += static_cast<size_t>(w);
  }
  auto c = std::make_shared<Conn>();
  c->fd = fd;
  c->peer_loopback = PeerIsLoopback(fd);
  c->peer = dst_rank;
  c->id = next_conn_id_.fetch_add(1);
  c->shard = next_shard_.fetch_add(1) % static_cast<int>(shards_.size());
  return c;
}

std::shared_ptr<UringNet::Conn> UringNet::ResolveConn(int dst_rank) {
  if (transport::IsClientRank(dst_rank)) {
    MutexLock lk(conns_mu_);
    auto it = client_conns_.find(dst_rank);
    return it == client_conns_.end() ? nullptr : it->second;
  }
  {
    MutexLock lk(conns_mu_);
    auto& slot = rank_conns_[static_cast<size_t>(dst_rank)];
    if (slot) return slot;
  }
  auto fresh = ConnectToRank(dst_rank);
  if (!fresh) return nullptr;
  std::shared_ptr<Conn> winner;
  {
    MutexLock lk(conns_mu_);
    auto& slot = rank_conns_[static_cast<size_t>(dst_rank)];
    if (!slot) {
      slot = fresh;
      all_conns_.push_back(fresh);
    }
    winner = slot;
  }
  if (winner == fresh) {
    Shard* target = shards_[static_cast<size_t>(fresh->shard)].get();
    {
      MutexLock lk(target->mu);
      target->to_register.push_back(fresh);
    }
    WakeShard(target);
  } else {
    ::close(fresh->fd);  // raced: another sender connected first
  }
  return winner;
}

bool UringNet::Enqueue(const std::shared_ptr<Conn>& c, const Message& msg,
                       bool may_block) {
  // Admission settle-before-failure, verbatim from EpollNet::Enqueue:
  // a reply dying on a full queue still releases the client's slot.
  if (may_block && transport::IsClientRank(c->peer.load()) &&
      (msg.type == MsgType::ReplyGet || msg.type == MsgType::ReplyAdd ||
       msg.type == MsgType::ReplyVersion ||
       msg.type == MsgType::ReplyReplica ||
       msg.type == MsgType::ReplyBusy || msg.type == MsgType::ReplyFlush ||
       msg.type == MsgType::ReplyError)) {
    long long now = c->inflight.fetch_add(-1);
    if (now <= 0) c->inflight.fetch_add(1);  // floor at zero
    if (msg.type != MsgType::ReplyAdd && msg.type != MsgType::ReplyFlush) {
      int qc = c->qos_class.load();
      qos::Release(qc < 0 ? 0 : qc);
    }
  }
  const int64_t cap = FlagOr("net_writeq_bytes", 64 << 20);
  const int64_t timeout_ms = FlagOr("io_timeout_ms", 30000);
  {
    MutexLock lk(c->mu);
    if (c->closed) return false;
    if (cap > 0 && c->wq_bytes >= cap) {
      if (!may_block) {
        Dashboard::Record("net.reply_dropped", 0.0);
        return false;
      }
      auto deadline = std::chrono::system_clock::now() +
                      std::chrono::milliseconds(
                          timeout_ms > 0 ? timeout_ms : 30000);
      while (c->wq_bytes >= cap && !c->closed) {
        if (!c->can_write.WaitUntil(c->mu, deadline)) break;
      }
      if (c->closed || c->wq_bytes >= cap) {
        Log::Error("UringNet: write queue to peer %d full (%lld bytes) "
                   "past the io deadline",
                   c->peer.load(),
                   static_cast<long long>(c->wq_bytes));
        return false;
      }
    }
    auto pf = std::make_shared<PendingFrame>(msg);
    c->wq_bytes += pf->total;
    wq_bytes_total_.fetch_add(pf->total, std::memory_order_relaxed);
    c->wq.push_back(std::move(pf));
  }
  Shard* target = shards_[static_cast<size_t>(c->shard)].get();
  // Wake coalescing: a non-empty handoff list means an earlier enqueue
  // already signalled the eventfd and the reactor has not adopted yet —
  // the push and the reactor's swap are serialized by the shard mutex,
  // so that pending wake covers this entry too.  Under a send burst
  // this drops the per-frame eventfd write syscall (one core: syscalls
  // ARE the budget); a wake is only ever skipped when one is provably
  // still in flight, never lost.
  bool need_wake;
  {
    MutexLock lk(target->mu);
    need_wake = target->to_arm.empty();
    target->to_arm.push_back(c);
  }
  if (need_wake) WakeShard(target);
  return true;
}

bool UringNet::SendAttempt(int dst_rank, const Message& msg) {
  if (Fault::Enabled() && Fault::FailSendAttempt()) {
    Dashboard::Record("fault.fail_send", 0.0);
    Log::Error("UringNet: send to rank %d failed (injected)", dst_rank);
    return false;
  }
  std::shared_ptr<Conn> c = ResolveConn(dst_rank);
  if (!c) {
    Log::Error("UringNet: cannot reach rank %d%s", dst_rank,
               transport::IsClientRank(dst_rank) ? " (client gone)" : "");
    return false;
  }
  return Enqueue(c, msg);
}

bool UringNet::Send(int dst_rank, const Message& msg) {
  bool is_client = transport::IsClientRank(dst_rank);
  if (!is_client &&
      (dst_rank < 0 || dst_rank >= static_cast<int>(endpoints_.size())))
    return false;
  if (!running_) return false;
  Monitor mon("Net::Send", msg.trace_id);

  bool duplicate = false;
  if (Fault::Enabled()) {
    int64_t delay_ms = 0;
    switch (Fault::OnSend(&delay_ms)) {
      case Fault::Action::kDrop:
        Dashboard::Record("net.dropped", 0.0);
        return true;
      case Fault::Action::kDelay:
        Dashboard::Record("net.delayed", 0.0);
        std::this_thread::sleep_for(std::chrono::milliseconds(delay_ms));
        break;
      case Fault::Action::kDuplicate:
        duplicate = true;
        break;
      case Fault::Action::kNone:
        break;
    }
  }

  const int retries =
      static_cast<int>(std::max<int64_t>(0, FlagOr("send_retries", 2)));
  int64_t backoff_ms = std::max<int64_t>(1, FlagOr("send_backoff_ms", 50));
  for (int attempt = 0; attempt <= retries; ++attempt) {
    if (attempt > 0) {
      Dashboard::Record("net.retries", 0.0);
      std::this_thread::sleep_for(std::chrono::milliseconds(backoff_ms));
      backoff_ms *= 2;
      if (!running_) return false;
    }
    if (SendAttempt(dst_rank, msg)) {
      if (duplicate) {
        Dashboard::Record("net.duplicated", 0.0);
        SendAttempt(dst_rank, msg);
      }
      return true;
    }
  }
  Log::Error("UringNet: send to rank %d failed after %d attempt(s)",
             dst_rank, retries + 1);
  return false;
}

void UringNet::SettleClient(int client_rank) {
  std::shared_ptr<Conn> c;
  {
    MutexLock lk(conns_mu_);
    auto it = client_conns_.find(client_rank);
    if (it == client_conns_.end()) return;  // client gone: slots died too
    c = it->second;
  }
  long long now = c->inflight.fetch_add(-1);
  if (now <= 0) c->inflight.fetch_add(1);  // floor at zero
  int qc = c->qos_class.load();
  qos::Release(qc < 0 ? 0 : qc);
}

Net::FanInStats UringNet::FanIn() const {
  FanInStats st;
  st.accepted_total = accepted_total_.load();
  st.active_clients = active_clients_.load();
  st.client_shed = client_shed_.load();
  return st;
}

void UringNet::Stop() {
  {
    // Same Stop-vs-Stop latch as the epoll engine.
    MutexLock lk(stop_mu_);
    if (!running_ || stopping_) return;
    stopping_ = true;
  }
  // Graceful drain: bounded window for queued frames to flush.
  int64_t grace_ms = std::min<int64_t>(FlagOr("io_timeout_ms", 30000),
                                       5000);
  auto deadline = std::chrono::system_clock::now() +
                  std::chrono::milliseconds(std::max<int64_t>(grace_ms, 1));
  std::vector<std::shared_ptr<Conn>> snapshot;
  {
    MutexLock lk(conns_mu_);
    snapshot = all_conns_;
  }
  for (auto& c : snapshot) {
    MutexLock lk(c->mu);
    while (!c->wq.empty() && !c->closed) {
      if (!c->can_write.WaitUntil(c->mu, deadline)) break;
    }
  }
  running_ = false;
  int lfd = listen_fd_.exchange(-1);
  if (lfd >= 0) ::close(lfd);
  for (auto& s : shards_) WakeShard(s.get());
  for (auto& s : shards_)
    if (s->thread.joinable()) s->thread.join();
  // Reactor threads are gone: this thread owns every shard.  Quiesce
  // the kernel's in-flight socket ops BEFORE releasing the memory they
  // write into: shutdown forces each recv/send to complete, and the
  // bounded reap below consumes the completions (FinalizeConn erases
  // each conn at pending_ops == 0).
  for (auto& s : shards_) {
    for (auto& kv : s->conns) {
      auto& c = kv.second;
      if (c->closing) continue;
      c->closing = true;
      ::shutdown(c->fd, SHUT_RDWR);
      rx_arena_total_.fetch_add(-static_cast<long long>(c->heap_tracked),
                                std::memory_order_relaxed);
      c->heap_tracked = 0;
      MutexLock lk(c->mu);
      c->closed = true;
      c->wq.clear();
      wq_bytes_total_.fetch_add(-c->wq_bytes, std::memory_order_relaxed);
      c->wq_bytes = 0;
      c->can_write.NotifyAll();
    }
    auto reap_deadline = std::chrono::steady_clock::now() +
                         std::chrono::milliseconds(500);
    while (!s->conns.empty() &&
           std::chrono::steady_clock::now() < reap_deadline) {
      SubmitPending(s.get(), /*wait=*/false);
      if (DrainCqes(s.get()) == 0 && !s->conns.empty())
        std::this_thread::sleep_for(std::chrono::milliseconds(1));
      // Finalize any conn whose last CQE already landed earlier.
      for (auto it = s->conns.begin(); it != s->conns.end();) {
        auto c = it->second;
        ++it;
        if (c->pending_ops == 0) FinalizeConn(s.get(), c);
      }
    }
    if (!s->conns.empty()) {
      // Safety valve: ops the kernel never completed within the reap
      // window keep their buffers pinned forever rather than freed
      // under a possible late DMA (HostArena never unmaps, so even the
      // pool path cannot fault — this guards the heap slabs).
      Log::Error("UringNet: %zu connection(s) with in-flight kernel ops "
                 "at teardown — retaining their buffers",
                 s->conns.size());
      static Mutex retain_mu;
      static std::vector<std::shared_ptr<void>>* retained =
          new std::vector<std::shared_ptr<void>>();
      MutexLock lk(retain_mu);
      for (auto& kv : s->conns) {
        ::close(kv.second->fd);
        retained->push_back(kv.second);
      }
      if (s->pool) retained->push_back(s->pool);
      s->conns.clear();
    }
    TeardownRing(s.get());
  }
  {
    MutexLock lk(conns_mu_);
    for (auto& c : all_conns_) {
      MutexLock clk(c->mu);
      if (!c->closed) {
        c->closed = true;
        ::close(c->fd);
      }
      c->wq.clear();
      c->wq_bytes = 0;
      c->can_write.NotifyAll();
    }
    all_conns_.clear();
    client_conns_.clear();
    rank_conns_.clear();
  }
  wq_bytes_total_.store(0, std::memory_order_relaxed);
  rx_arena_total_.store(0, std::memory_order_relaxed);
  shards_.clear();
}

std::unique_ptr<RankTransport> MakeUringTransport() {
  return std::make_unique<UringNet>();
}

}  // namespace mvtpu
