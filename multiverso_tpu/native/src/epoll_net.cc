// mvlint: reactor-context — this file runs inside the epoll event loop:
// every socket op must be nonblocking (MSG_DONTWAIT / SOCK_NONBLOCK),
// enforced by mvlint rule MV009 (docs/transport.md).
#include "mvtpu/epoll_net.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstring>

#include "mvtpu/configure.h"
#include "mvtpu/dashboard.h"
#include "mvtpu/fault.h"
#include "mvtpu/latency.h"
#include "mvtpu/log.h"
#include "mvtpu/net.h"
#include "mvtpu/ops.h"
#include "mvtpu/qos.h"
#include "mvtpu/uring_net.h"
#include "mvtpu/watchdog.h"

namespace mvtpu {

namespace {

bool SplitHostPort(const std::string& ep, std::string* host, int* port) {
  auto colon = ep.rfind(':');
  if (colon == std::string::npos) return false;
  *host = ep.substr(0, colon);
  try {
    *port = std::stoi(ep.substr(colon + 1));
  } catch (...) {
    return false;
  }
  return *port > 0 && *port < 65536;
}

int64_t FlagOr(const char* name, int64_t dflt) {
  return mvtpu::configure::Has(name) ? mvtpu::configure::GetInt(name)
                                     : dflt;
}

bool SetNonBlocking(int fd) {
  int fl = ::fcntl(fd, F_GETFL, 0);
  return fl >= 0 && ::fcntl(fd, F_SETFL, fl | O_NONBLOCK) == 0;
}

void SetNoDelay(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

// Frame caps: rank peers may ship table shards (the TcpNet bound); an
// anonymous/unidentified connection is untrusted — its frames are serve
// requests (tiny), so a garbled or hostile client cannot force a huge
// arena allocation.
constexpr int64_t kMaxRankFrameBytes = int64_t{1} << 40;
constexpr int64_t kMaxClientFrameBytes = int64_t{1} << 26;  // 64 MiB
constexpr size_t kDefaultSlabBytes = 256 << 10;

#if defined(__SANITIZE_THREAD__)
#define MVTPU_TSAN 1
#elif defined(__has_feature)
#if __has_feature(thread_sanitizer)
#define MVTPU_TSAN 1
#endif
#endif

// True when the reactor may REWIND the slab and overwrite it: no Blob
// view is left alive.  The consumer's last read of a view is ordered
// before our overwrite by (a) the view's shared_ptr release decrement
// (acq_rel in libstdc++) and (b) the acquire FENCE below pairing with
// it after the relaxed use_count() observation (atomics.fences) — the
// bare use_count() == 1 check alone carries no happens-before edge
// (TSan caught exactly that on the ssp_tput sweep).  TSan does not
// model fences, so under it the fast path is compiled out (a fresh
// slab is allocated instead of rewinding) rather than suppressed.
bool SlabExclusive(const std::shared_ptr<std::vector<char>>& slab) {
#ifdef MVTPU_TSAN
  (void)slab;
  return false;
#else
  if (slab.use_count() != 1) return false;
  std::atomic_thread_fence(std::memory_order_acquire);
  return true;
#endif
}

}  // namespace

// One queued outbound frame: the interleaved scratch (length prefix +
// wire header + per-blob length prefixes) plus refcounted blob handles —
// the payload bytes are gather-written from the Message's own buffers,
// never copied into a contiguous wire image (the PR 5 send contract,
// now preserved across partial writes by `done`).
struct EpollNet::PendingFrame {
  struct Head {
    int64_t frame_len;
    WireHeader h;
  } head;
  std::vector<int64_t> lens;
  Message msg;        // shallow blob copies keep the payload alive
  int64_t total = 0;  // prefix + frame bytes
  int64_t done = 0;   // bytes already on the wire

  explicit PendingFrame(const Message& m) : msg(m) {
    head.frame_len = m.WireBytes();
    m.FillWireHeader(&head.h);
    lens.resize(m.data.size());
    for (size_t i = 0; i < m.data.size(); ++i)
      lens[i] = static_cast<int64_t>(m.data[i].size());
    total = head.frame_len + static_cast<int64_t>(sizeof(int64_t));
  }

  // Segment view for gather writes: [head][len0][blob0][len1][blob1]...
  // Fills iovecs starting `done` bytes into the frame; returns count.
  size_t FillIov(iovec* iov, size_t max_iov) {
    size_t n = 0;
    int64_t skip = done;
    auto push = [&](const void* base, size_t len) {
      if (n >= max_iov || len == 0) return;
      if (skip >= static_cast<int64_t>(len)) {
        skip -= static_cast<int64_t>(len);
        return;
      }
      iov[n].iov_base = const_cast<char*>(
          static_cast<const char*>(base) + skip);
      iov[n].iov_len = len - static_cast<size_t>(skip);
      skip = 0;
      ++n;
    };
    push(&head, sizeof(head));
    // Latency trail rides between header and blob prefixes (message.cc
    // Serialize order); head.frame_len already counts it (WireBytes).
    if (msg.has_timing()) push(&msg.timing, sizeof(TimingTrail));
    // Delivery-audit stamp rides after the trail (same Serialize
    // order); head.frame_len counts it via WireBytes().
    if (msg.has_audit()) push(&msg.audit, sizeof(AuditStamp));
    // QoS/deadline stamp rides after the audit stamp (same order).
    if (msg.has_qos()) push(&msg.qos, sizeof(QosStamp));
    for (size_t i = 0; i < msg.data.size(); ++i) {
      push(&lens[i], sizeof(int64_t));
      push(msg.data[i].data(), msg.data[i].size());
    }
    return n;
  }
};

struct EpollNet::Conn {
  int fd = -1;
  int shard = 0;
  bool accepted = false;
  // rank, pseudo-rank (>= transport::kClientRankBase), or -1 for an
  // accepted connection whose first message has not arrived yet.
  std::atomic<int> peer{-1};

  // ---- read state machine: touched ONLY by the owning shard's reactor
  // thread, so it needs no lock.
  char len_buf[sizeof(int64_t)] = {0};
  size_t len_got = 0;
  int64_t body_len = -1;  // -1: reading the length prefix
  size_t body_got = 0;
  // Receive arena: frames assemble in `slab` at slab_off; completed
  // frames stay referenced by Blob views until the table layer drops
  // them, at which point use_count()==1 lets the reactor rewind and
  // reuse the slab instead of allocating.
  std::shared_ptr<std::vector<char>> slab;
  size_t slab_off = 0;
  size_t slab_used = 0;
  // Bytes of `slab` currently counted in rx_arena_total_ (reactor-thread
  // only, like the slab itself) — the net.rx_arena_bytes gauge.
  size_t slab_tracked = 0;

  // Per-client admission (reactor increments on forwarded requests;
  // Send decrements when the reply goes out).
  std::atomic<long long> inflight{0};
  // Tenant class (docs/serving.md "tail"): latched from the first
  // frame carrying a QoS stamp (-1 until declared; effective class 0 =
  // the first -qos_classes entry).  A connection property so replies
  // can settle the right class budget without carrying the stamp back.
  std::atomic<int> qos_class{-1};

  Mutex mu;
  CondVar can_write;  // backpressure + drain-on-stop waiters
  // capacity: wq_bytes_total_ gauge — the "capacity" report's
  // net.writeq_bytes field (bounded at -net_writeq_bytes per conn)
  std::deque<PendingFrame> wq GUARDED_BY(mu);
  int64_t wq_bytes GUARDED_BY(mu) = 0;
  bool want_out GUARDED_BY(mu) = false;  // EPOLLOUT armed
  bool closed GUARDED_BY(mu) = false;
};

struct EpollNet::Shard {
  int epfd = -1;
  int wake_fd = -1;
  int idx = 0;  // position in shards_ — names the watchdog loop
  std::thread thread;
  // Hand-off queues: Send/accept threads push, the reactor pops.
  Mutex mu;
  std::vector<std::shared_ptr<Conn>> to_register GUARDED_BY(mu);
  std::vector<std::shared_ptr<Conn>> to_arm GUARDED_BY(mu);
  // fd -> conn, reactor-thread-only after registration.
  std::unordered_map<int, std::shared_ptr<Conn>> conns;
};

bool EpollNet::Init(const std::vector<std::string>& endpoints, int rank,
                    InboundFn fn, int64_t connect_retry_ms) {
  endpoints_ = endpoints;
  rank_ = rank;
  inbound_ = std::move(fn);
  connect_retry_ms_ = connect_retry_ms;
  {
    MutexLock lk(conns_mu_);
    rank_conns_.assign(endpoints_.size(), nullptr);
  }

  std::string host;
  int port = 0;
  if (rank_ < 0 || rank_ >= static_cast<int>(endpoints_.size()) ||
      !SplitHostPort(endpoints_[rank_], &host, &port)) {
    Log::Error("EpollNet: bad rank %d / endpoint list (%zu entries)",
               rank_, endpoints_.size());
    return false;
  }

  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return false;
  int one = 1;
  ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(lfd, 1024) < 0 || !SetNonBlocking(lfd)) {
    Log::Error("EpollNet: cannot listen on port %d", port);
    ::close(lfd);
    return false;
  }
  listen_fd_ = lfd;

  int nshards = static_cast<int>(
      std::min<int64_t>(16, std::max<int64_t>(1, FlagOr("net_threads", 1))));
  running_ = true;
  stopping_ = false;
  // Two passes: EVERY shard exists in shards_ before ANY reactor thread
  // runs — shard 0's reactor accepts connections immediately, and its
  // round-robin placement (next_shard_ % shards_.size()) must see the
  // full, immutable shard vector, never a vector mid-growth.
  for (int i = 0; i < nshards; ++i) {
    auto s = std::make_unique<Shard>();
    s->idx = i;
    s->epfd = ::epoll_create1(0);
    s->wake_fd = ::eventfd(0, EFD_NONBLOCK);
    if (s->epfd < 0 || s->wake_fd < 0) {
      Log::Error("EpollNet: epoll/eventfd creation failed");
      running_ = false;
      if (s->epfd >= 0) ::close(s->epfd);
      if (s->wake_fd >= 0) ::close(s->wake_fd);
      ::close(lfd);
      listen_fd_ = -1;
      for (auto& sh : shards_) {
        ::close(sh->epfd);
        ::close(sh->wake_fd);
      }
      shards_.clear();
      return false;
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = s->wake_fd;
    ::epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->wake_fd, &ev);
    if (i == 0) {
      epoll_event lev{};
      lev.events = EPOLLIN;
      lev.data.fd = lfd;
      ::epoll_ctl(s->epfd, EPOLL_CTL_ADD, lfd, &lev);
    }
    shards_.push_back(std::move(s));
  }
  for (auto& s : shards_) {
    Shard* raw = s.get();
    s->thread = std::thread([this, raw] { ReactorLoop(raw); });
  }
  Log::Info("EpollNet: rank %d/%zu listening on :%d (%d shard%s)", rank_,
            endpoints_.size(), port, nshards, nshards == 1 ? "" : "s");
  return true;
}

void EpollNet::WakeShard(Shard* s) {
  uint64_t one = 1;
  ssize_t n = ::write(s->wake_fd, &one, sizeof(one));
  (void)n;  // EAGAIN means a wake is already pending — good enough
}

void EpollNet::AdoptHandoffs(Shard* s) {
  std::vector<std::shared_ptr<Conn>> regs, arms;
  {
    MutexLock lk(s->mu);
    regs.swap(s->to_register);
    arms.swap(s->to_arm);
  }
  for (auto& c : regs) {
    s->conns[c->fd] = c;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = c->fd;
    ::epoll_ctl(s->epfd, EPOLL_CTL_ADD, c->fd, &ev);
  }
  for (auto& c : arms) {
    auto it = s->conns.find(c->fd);
    if (it == s->conns.end() || it->second != c) continue;
    bool empty = true;
    if (!DrainWrites(c, &empty)) {
      CloseConn(s, c, "write error");
      continue;
    }
    if (!empty) ArmWrite(c);  // EPOLLOUT resumes the drain
  }
}

void EpollNet::ReactorLoop(Shard* s) {
  constexpr int kMaxEvents = 128;
  epoll_event events[kMaxEvents];
  // Watchdog (docs/observability.md "health plane"): one Bump per
  // drained event batch; "busy" while a batch is in hand.  A reactor
  // that stops draining with events pending — the lost-wakeup class of
  // bug — shows as "reactor.<shard> no progress" with a nonzero queue.
  const std::string wd_name = "reactor." + std::to_string(s->idx);
  while (running_) {
    int n = ::epoll_wait(s->epfd, events, kMaxEvents, 200);
    if (!running_) break;
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    watchdog::Busy(wd_name, n);
    // Adopt hand-offs first so a just-connected peer's events register
    // before we sleep again.
    AdoptHandoffs(s);
    for (int i = 0; i < n; ++i) {
      int fd = events[i].data.fd;
      uint32_t what = events[i].events;
      if (fd == s->wake_fd) {
        uint64_t junk;
        while (::read(s->wake_fd, &junk, sizeof(junk)) > 0) {
        }
        // Re-adopt AFTER draining the eventfd: a sender that enqueued
        // between this batch's top-of-loop adoption and the drain just
        // had its wake CONSUMED — without this, its frame would sit in
        // the hand-off queue for a full epoll_wait cycle (a ~200 ms
        // tail spike on quiet paced traffic; the tail bench caught it
        // as a wire_back stage stall).
        AdoptHandoffs(s);
        continue;
      }
      if (fd == listen_fd_.load()) {
        HandleAccept(s);
        continue;
      }
      auto it = s->conns.find(fd);
      if (it == s->conns.end()) continue;  // closed earlier this batch
      std::shared_ptr<Conn> c = it->second;
      if (what & (EPOLLHUP | EPOLLERR)) {
        // Flush whatever the peer managed to send before the hangup,
        // then tear down (a mid-frame partial is discarded).
        HandleReadable(s, c);
        auto again = s->conns.find(fd);
        if (again != s->conns.end() && again->second == c)
          CloseConn(s, c, (what & EPOLLERR) ? "socket error" : "hangup");
        continue;
      }
      if (what & EPOLLOUT) {
        bool empty = true;
        if (!DrainWrites(c, &empty)) {
          CloseConn(s, c, "write error");
          continue;
        }
        if (empty) {
          // Disarm EPOLLOUT so an idle connection stops waking us.
          MutexLock lk(c->mu);
          if (c->wq.empty() && c->want_out) {
            c->want_out = false;
            epoll_event ev{};
            ev.events = EPOLLIN;
            ev.data.fd = c->fd;
            ::epoll_ctl(s->epfd, EPOLL_CTL_MOD, c->fd, &ev);
          }
        }
      }
      if (what & EPOLLIN) HandleReadable(s, c);
    }
    watchdog::Bump(wd_name);
    watchdog::Busy(wd_name, 0);
  }
}

void EpollNet::HandleAccept(Shard* s) {
  (void)s;
  while (true) {
    int fd = ::accept4(listen_fd_.load(), nullptr, nullptr, SOCK_NONBLOCK);
    if (fd < 0) return;  // EAGAIN: drained
    SetNoDelay(fd);
    auto c = std::make_shared<Conn>();
    c->fd = fd;
    c->accepted = true;
    c->shard = next_shard_.fetch_add(1) %
               static_cast<int>(shards_.size());
    {
      MutexLock lk(conns_mu_);
      all_conns_.push_back(c);
    }
    Shard* target = shards_[static_cast<size_t>(c->shard)].get();
    {
      MutexLock lk(target->mu);
      target->to_register.push_back(c);
    }
    WakeShard(target);
  }
}

void EpollNet::HandleReadable(Shard* s, const std::shared_ptr<Conn>& c) {
  const size_t slab_bytes = static_cast<size_t>(
      FlagOr("net_arena_bytes", static_cast<int64_t>(kDefaultSlabBytes)));
  while (true) {
    if (c->body_len < 0) {
      // Length prefix, possibly one byte at a time.
      ssize_t r = ::recv(c->fd, c->len_buf + c->len_got,
                         sizeof(c->len_buf) - c->len_got, MSG_DONTWAIT);
      if (r == 0 || (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
        CloseConn(s, c, r == 0 ? "peer closed" : "read error");
        return;
      }
      if (r < 0) return;  // EAGAIN
      c->len_got += static_cast<size_t>(r);
      if (c->len_got < sizeof(c->len_buf)) continue;
      int64_t len;
      std::memcpy(&len, c->len_buf, sizeof(len));
      // PER FRAME, not per readable batch: a rank peer identifies
      // itself with its tiny Hello first frame (FinishFrame sets
      // c->peer mid-loop), and the very next frame — possibly a
      // shard-sized payload — must already enjoy the rank bound.
      const int64_t max_frame =
          (c->accepted && c->peer.load() < 0) ||
                  transport::IsClientRank(c->peer.load())
              ? kMaxClientFrameBytes
              : kMaxRankFrameBytes;
      if (len <= 0 || len > max_frame) {
        CloseConn(s, c, "bad frame length");
        return;
      }
      // Arena placement: rewind a slab nothing references any more;
      // append into leftover space otherwise; allocate only when the
      // live region leaves no room.  Pack offsets 8-ALIGNED: the
      // previous frame's payload may still be read through a Blob view
      // on another thread while this recv writes the next frame, and
      // adjacent unaligned frames would share an 8-byte granule (a
      // false-sharing data race TSan rightly halts on).
      c->slab_used = (c->slab_used + 7) & ~size_t{7};
      size_t need = static_cast<size_t>(len);
      if (c->slab && SlabExclusive(c->slab)) {
        if (c->slab->size() < need)
          c->slab->resize(std::max(need, slab_bytes));
        c->slab_used = 0;
      } else if (!c->slab ||
                 c->slab->size() < c->slab_used + need) {
        // Addition, never subtraction: an exact-fit frame leaves an
        // odd-sized slab whose aligned slab_used can EXCEED size() —
        // size()-slab_used would underflow to "plenty of room" and the
        // next recv would write past the buffer.
        c->slab = std::make_shared<std::vector<char>>(
            std::max(need, slab_bytes));
        c->slab_used = 0;
      }
      c->slab_off = c->slab_used;
      c->body_len = len;
      c->body_got = 0;
      c->len_got = 0;
      // Capacity plane: keep the rx-arena gauge in step with whatever
      // the placement above allocated/resized (a replaced slab's old
      // bytes leave the gauge with its last view, not here — the gauge
      // tracks what the ENGINE holds).
      size_t sz = c->slab->size();
      if (sz != c->slab_tracked) {
        rx_arena_total_.fetch_add(
            static_cast<long long>(sz) -
                static_cast<long long>(c->slab_tracked),
            std::memory_order_relaxed);
        c->slab_tracked = sz;
      }
    }
    // Frame body straight into the arena slab.
    size_t want = static_cast<size_t>(c->body_len) - c->body_got;
    ssize_t r = ::recv(c->fd, c->slab->data() + c->slab_off + c->body_got,
                       want, MSG_DONTWAIT);
    if (r == 0 || (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
      // Mid-frame disconnect: the partial frame dies with the
      // connection — nothing was delivered upstream.
      CloseConn(s, c, r == 0 ? "peer closed mid-frame" : "read error");
      return;
    }
    if (r < 0) return;  // EAGAIN
    c->body_got += static_cast<size_t>(r);
    if (c->body_got < static_cast<size_t>(c->body_len)) continue;
    if (!FinishFrame(s, c)) {
      CloseConn(s, c, "malformed frame");
      return;
    }
  }
}

bool EpollNet::FinishFrame(Shard* s, const std::shared_ptr<Conn>& c) {
  (void)s;
  size_t len = static_cast<size_t>(c->body_len);
  Dashboard::Record(
      "net.bytes.recv",
      static_cast<double>(c->body_len +
                          static_cast<int64_t>(sizeof(int64_t))));
  Message m;
  bool ok = Message::DeserializeView(c->slab, c->slab_off, len, &m);
  c->slab_used = c->slab_off + len;
  c->body_len = -1;
  c->body_got = 0;
  if (!ok) return false;
  // Latency trail: frame-complete AT THE REACTOR — the stamp the
  // mailbox stage starts from (docs/observability.md).
  latency::StampRecv(&m);
  // Deadline propagation (docs/serving.md "tail"): convert the wire
  // budget into a local-clock deadline while the recv boundary is hot.
  qos::AdoptDeadline(&m);

  int peer = c->peer.load();
  if (c->accepted && peer < 0) {
    // First frame identifies the connection: a fleet peer announces
    // itself with a Hello carrying its rank in src (sent by
    // ConnectToRank before any payload, so the identifying frame is
    // always tiny and always first); ANY other opening frame — valid
    // src or not — is an anonymous serve client, which gets a
    // pseudo-rank so replies can route back over this socket.  A
    // client forging a rank in src therefore neither impersonates a
    // fleet member nor unlocks the rank frame bound.
    if (m.type == MsgType::Hello && m.src >= 0 &&
        m.src < static_cast<int>(endpoints_.size())) {
      peer = m.src;
      c->peer = peer;
    } else {
      peer = transport::kClientRankBase + next_client_.fetch_add(1);
      c->peer = peer;
      accepted_total_.fetch_add(1);
      active_clients_.fetch_add(1);
      MutexLock lk(conns_mu_);
      client_conns_[peer] = c;
    }
  }
  // The identify frame is transport-internal: consumed here, never
  // forwarded upstream (stray Hellos on an identified connection are
  // dropped the same way).
  if (m.type == MsgType::Hello) return true;
  if (m.type == MsgType::RequestCancel) {
    // Hedge-cancel token (docs/serving.md "tail"): consumed AT THE
    // REACTOR like Hello/OpsQuery — never the mailbox, so it OVERTAKES
    // the FIFO the loser read is parked in.  Fire-and-forget:
    // uncounted by admission, no reply.
    qos::NoteCancel(transport::IsClientRank(peer) ? peer : m.src,
                    m.msg_id);
    Dashboard::Record("serve.hedge.cancel_noted", 0.0);
    return true;
  }
  if (m.type == MsgType::OpsQuery) {
    // Introspection scrape (docs/observability.md): answered AT THE
    // REACTOR, exactly like a synthesized busy reply — it must never
    // touch the actor mailbox (a wedged server still reports health),
    // and reactor-originated sends never block (may_block=false: a
    // full write queue drops the reply; the scraper's deadline covers
    // it).  Uncounted by the per-client admission gate, like Hello.
    if (transport::IsClientRank(peer)) m.src = peer;
    if (m.version != 1) {
      Message reply;
      ops::BuildReply(m, &reply);
      reply.src = rank_;
      reply.dst = m.src;
      // The reactor IS this query's actor+applier: close the mailbox
      // and apply stages here so a timed scrape still attributes.
      latency::StampDequeue(&m);
      latency::StampReply(m, &reply);
      latency::StampSend(&reply);
      return Enqueue(c, reply, /*may_block=*/false);
    }
    // Fleet scope: the zoo fans out on a bounded detached thread —
    // the hand-off itself (thread spawn) is reactor-safe.
    if (inbound_) inbound_(std::move(m));
    return true;
  }
  if (transport::IsClientRank(peer)) {
    // Anonymous client: the pseudo-rank IS the reply address.
    m.src = peer;
    // Tenant class declaration (docs/serving.md "tail"): latched from
    // the first QoS-stamped frame; later stamps may retarget it.
    if (m.has_qos()) c->qos_class.store(m.qos.klass);
    int qc = c->qos_class.load();
    if (qc < 0) qc = 0;  // undeclared = the first -qos_classes entry
    bool counted =
        m.type == MsgType::RequestGet || m.type == MsgType::RequestVersion ||
        m.type == MsgType::RequestReplica ||
        m.type == MsgType::RequestFlush ||
        (m.type == MsgType::RequestAdd && m.msg_id >= 0);
    bool readlike = counted && m.type != MsgType::RequestAdd &&
                    m.type != MsgType::RequestFlush;
    auto reply_busy = [&]() {
      Message busy;
      busy.type = MsgType::ReplyBusy;
      busy.table_id = m.table_id;
      busy.msg_id = m.msg_id;
      busy.trace_id = m.trace_id;
      busy.src = rank_;
      busy.dst = peer;
      latency::StampDequeue(&m);
      latency::StampReply(m, &busy);
      latency::StampSend(&busy);
      // Reactor thread: never block on our own write queue.
      return Enqueue(c, busy, /*may_block=*/false);
    };
    // Deadline shed (docs/serving.md "tail"): a read that arrives
    // already past its propagated budget is dropped outright — the
    // caller stopped waiting, so neither a mailbox slot nor a busy
    // reply is owed.  Adds/flushes are never deadline-shed.
    if (readlike && qos::ShedExpired(m)) return true;
    int64_t cap = FlagOr("client_inflight_max", 64);
    if (cap > 0 && readlike && c->inflight.load() >= cap) {
      // Per-client admission on top of -server_inflight_max: shed
      // Gets/probes (never adds) without touching the actor mailbox.
      client_shed_.fetch_add(1);
      Dashboard::Record("serve.client_shed", 0.0);
      return reply_busy();
    }
    // Per-tenant weighted admission (docs/serving.md "tail"): reads
    // compete for per-class inflight budgets — a bulk herd at its
    // share answers ReplyBusy here while gold reads keep flowing.
    if (readlike && !qos::TryAdmit(qc)) return reply_busy();
    // Hedge fast path: answer an anonymous hot-key replica pull AT THE
    // REACTOR — a bounded snapshot read under the shard lock, so a
    // hedged read can win while a straggling apply clogs the actor
    // mailbox.  The admission slot settles synchronously (the reply is
    // queued before we return); per-client inflight never counts it,
    // matching the may_block=false no-settle rule in Enqueue.
    if (m.type == MsgType::RequestReplica &&
        (!mvtpu::configure::Has("replica_serve_reactor") ||
         mvtpu::configure::GetBool("replica_serve_reactor"))) {
      Message reply;
      ops::BuildReplicaReply(m, &reply);
      reply.src = rank_;
      reply.dst = peer;
      latency::StampDequeue(&m);
      latency::StampReply(m, &reply);
      latency::StampSend(&reply);
      qos::Release(qc);
      return Enqueue(c, reply, /*may_block=*/false);
    }
    if (counted) c->inflight.fetch_add(1);
  }
  if (inbound_) inbound_(std::move(m));
  return true;
}

bool EpollNet::DrainWrites(const std::shared_ptr<Conn>& c, bool* empty) {
  constexpr size_t kMaxIov = 64;
  iovec iov[kMaxIov];
  MutexLock lk(c->mu);
  while (!c->wq.empty()) {
    PendingFrame& f = c->wq.front();
    msghdr mh{};
    mh.msg_iov = iov;
    mh.msg_iovlen = f.FillIov(iov, kMaxIov);
    ssize_t w = ::sendmsg(c->fd, &mh, MSG_NOSIGNAL | MSG_DONTWAIT);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        *empty = false;
        return true;  // short write: EPOLLOUT resumes exactly here
      }
      *empty = false;
      return false;
    }
    f.done += w;
    if (f.done < f.total) continue;  // more segments than kMaxIov
    // Frame fully on the wire: only now does the byte ledger count it.
    Dashboard::Record("net.bytes.sent", static_cast<double>(f.total));
    c->wq_bytes -= f.total;
    wq_bytes_total_.fetch_add(-f.total, std::memory_order_relaxed);
    c->wq.pop_front();
    c->can_write.NotifyAll();
  }
  *empty = true;
  return true;
}

void EpollNet::ArmWrite(const std::shared_ptr<Conn>& c) {
  MutexLock lk(c->mu);
  if (c->want_out || c->closed) return;
  c->want_out = true;
  epoll_event ev{};
  ev.events = EPOLLIN | EPOLLOUT;
  ev.data.fd = c->fd;
  ::epoll_ctl(shards_[static_cast<size_t>(c->shard)]->epfd, EPOLL_CTL_MOD,
              c->fd, &ev);
}

void EpollNet::CloseConn(Shard* s, const std::shared_ptr<Conn>& c,
                         const char* why) {
  int peer = c->peer.load();
  Log::Debug("EpollNet: closing connection (peer %d): %s", peer, why);
  ::epoll_ctl(s->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  s->conns.erase(c->fd);
  rx_arena_total_.fetch_add(-static_cast<long long>(c->slab_tracked),
                            std::memory_order_relaxed);
  c->slab_tracked = 0;
  {
    MutexLock lk(c->mu);
    c->closed = true;
    if (!c->wq.empty())
      Log::Error("EpollNet: dropping %zu queued frame(s) to peer %d (%s)",
                 c->wq.size(), peer, why);
    c->wq.clear();
    wq_bytes_total_.fetch_add(-c->wq_bytes, std::memory_order_relaxed);
    c->wq_bytes = 0;
    c->can_write.NotifyAll();
  }
  ::close(c->fd);
  MutexLock lk(conns_mu_);
  if (transport::IsClientRank(peer)) {
    if (client_conns_.erase(peer)) active_clients_.fetch_add(-1);
  } else if (peer >= 0 &&
             peer < static_cast<int>(rank_conns_.size()) &&
             rank_conns_[static_cast<size_t>(peer)] == c) {
    rank_conns_[static_cast<size_t>(peer)] = nullptr;
  }
  for (auto it = all_conns_.begin(); it != all_conns_.end(); ++it)
    if (*it == c) {
      all_conns_.erase(it);
      break;
    }
}

std::shared_ptr<EpollNet::Conn> EpollNet::ConnectToRank(int dst_rank) {
  std::string host;
  int port = 0;
  if (!SplitHostPort(endpoints_[static_cast<size_t>(dst_rank)], &host,
                     &port))
    return nullptr;
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (::getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints,
                    &res) != 0 ||
      !res)
    return nullptr;
  // Peers start in any order: blocking connect with the same retry
  // budget as TcpNet — only the ESTABLISHED socket goes non-blocking
  // into the reactor.
  int fd = -1;
  int attempts = static_cast<int>(
      std::max<int64_t>(1, connect_retry_ms_ / 100));
  for (int attempt = 0; attempt < attempts; ++attempt) {
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) break;
    // Pre-reactor blocking handshake: this runs on the SENDER's thread
    // (never the reactor); only the established socket enters the event
    // loop, nonblocking.
    if (::connect(fd, res->ai_addr,  // mvlint: MV009-exempt(pre-reactor)
                  res->ai_addrlen) == 0)
      break;
    ::close(fd);
    fd = -1;
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
    if (!running_ || stopping_) break;
  }
  ::freeaddrinfo(res);
  if (fd < 0) return nullptr;
  SetNoDelay(fd);
  // Identify before payload: the accept side caps UNIDENTIFIED
  // connections at the small anonymous-client frame bound, so the first
  // frame on a fresh rank connection must be this tiny Hello — after
  // the reactor consumes it, subsequent frames get the rank bound.
  // Still the sender's thread, still the blocking socket (it goes
  // nonblocking into the reactor only below).
  Message hello;
  hello.type = MsgType::Hello;
  hello.src = rank_;
  hello.dst = dst_rank;
  Blob hello_body = hello.Serialize();
  int64_t hello_len = static_cast<int64_t>(hello_body.size());
  std::vector<char> hello_wire(sizeof(hello_len) + hello_body.size());
  std::memcpy(hello_wire.data(), &hello_len, sizeof(hello_len));
  std::memcpy(hello_wire.data() + sizeof(hello_len), hello_body.data(),
              hello_body.size());
  size_t hello_sent = 0;
  while (hello_sent < hello_wire.size()) {
    ssize_t w = ::send(  // mvlint: MV009-exempt(pre-reactor handshake)
        fd, hello_wire.data() + hello_sent, hello_wire.size() - hello_sent,
        MSG_NOSIGNAL);
    if (w <= 0) {
      ::close(fd);
      return nullptr;
    }
    hello_sent += static_cast<size_t>(w);
  }
  if (!SetNonBlocking(fd)) {
    ::close(fd);
    return nullptr;
  }
  auto c = std::make_shared<Conn>();
  c->fd = fd;
  c->peer = dst_rank;
  c->shard = next_shard_.fetch_add(1) % static_cast<int>(shards_.size());
  return c;
}

std::shared_ptr<EpollNet::Conn> EpollNet::ResolveConn(int dst_rank) {
  if (transport::IsClientRank(dst_rank)) {
    MutexLock lk(conns_mu_);
    auto it = client_conns_.find(dst_rank);
    return it == client_conns_.end() ? nullptr : it->second;
  }
  {
    MutexLock lk(conns_mu_);
    auto& slot = rank_conns_[static_cast<size_t>(dst_rank)];
    if (slot) return slot;
  }
  auto fresh = ConnectToRank(dst_rank);
  if (!fresh) return nullptr;
  std::shared_ptr<Conn> winner;
  {
    MutexLock lk(conns_mu_);
    auto& slot = rank_conns_[static_cast<size_t>(dst_rank)];
    if (!slot) {
      slot = fresh;
      all_conns_.push_back(fresh);
    }
    winner = slot;
  }
  if (winner == fresh) {
    Shard* target = shards_[static_cast<size_t>(fresh->shard)].get();
    {
      MutexLock lk(target->mu);
      target->to_register.push_back(fresh);
    }
    WakeShard(target);
  } else {
    ::close(fresh->fd);  // raced: another sender connected first
  }
  return winner;
}

bool EpollNet::Enqueue(const std::shared_ptr<Conn>& c, const Message& msg,
                       bool may_block) {
  // A reply headed back to an anonymous client settles one admission
  // slot — BEFORE any failure exit below, so a reply dying on a full
  // write queue or a just-closed connection still releases it (a leak
  // here would permanently shed the client once leaks eat the whole
  // cap).  Reactor-synthesized busy replies (may_block=false) answer
  // requests that were never counted, so they settle nothing.
  if (may_block && transport::IsClientRank(c->peer.load()) &&
      (msg.type == MsgType::ReplyGet || msg.type == MsgType::ReplyAdd ||
       msg.type == MsgType::ReplyVersion ||
       msg.type == MsgType::ReplyReplica ||
       msg.type == MsgType::ReplyBusy || msg.type == MsgType::ReplyFlush ||
       msg.type == MsgType::ReplyError)) {
    long long now = c->inflight.fetch_add(-1);
    if (now <= 0) c->inflight.fetch_add(1);  // floor at zero
    // A read reply also settles its tenant-class admission slot (adds/
    // flushes were never class-admitted; Release floors per class).
    if (msg.type != MsgType::ReplyAdd && msg.type != MsgType::ReplyFlush) {
      int qc = c->qos_class.load();
      qos::Release(qc < 0 ? 0 : qc);
    }
  }
  const int64_t cap = FlagOr("net_writeq_bytes", 64 << 20);
  const int64_t timeout_ms = FlagOr("io_timeout_ms", 30000);
  {
    MutexLock lk(c->mu);
    if (c->closed) return false;
    // Backpressure: a slow reader fills the bounded queue; the sender
    // waits for drain up to the io deadline instead of ballooning
    // memory — the readiness-model twin of SO_SNDTIMEO.  may_block is
    // false for REACTOR-originated sends (synthesized busy replies):
    // the reactor is the only thread that drains queues, so waiting
    // here would deadlock the shard — a full queue drops the reply
    // instead (the client's rpc deadline covers it).
    if (cap > 0 && c->wq_bytes >= cap) {
      if (!may_block) {
        Dashboard::Record("net.reply_dropped", 0.0);
        return false;
      }
      auto deadline = std::chrono::system_clock::now() +
                      std::chrono::milliseconds(
                          timeout_ms > 0 ? timeout_ms : 30000);
      while (c->wq_bytes >= cap && !c->closed) {
        if (!c->can_write.WaitUntil(c->mu, deadline)) break;
      }
      if (c->closed || c->wq_bytes >= cap) {
        Log::Error("EpollNet: write queue to peer %d full (%lld bytes) "
                   "past the io deadline",
                   c->peer.load(),
                   static_cast<long long>(c->wq_bytes));
        return false;
      }
    }
    c->wq.emplace_back(msg);
    c->wq_bytes += c->wq.back().total;
    wq_bytes_total_.fetch_add(c->wq.back().total,
                              std::memory_order_relaxed);
  }
  Shard* target = shards_[static_cast<size_t>(c->shard)].get();
  {
    MutexLock lk(target->mu);
    target->to_arm.push_back(c);
  }
  WakeShard(target);
  return true;
}

bool EpollNet::SendAttempt(int dst_rank, const Message& msg) {
  // Injected wire failure (chaos suite): consumes a retry attempt just
  // like a real failed write on the blocking engine.
  if (Fault::Enabled() && Fault::FailSendAttempt()) {
    Dashboard::Record("fault.fail_send", 0.0);
    Log::Error("EpollNet: send to rank %d failed (injected)", dst_rank);
    return false;
  }
  std::shared_ptr<Conn> c = ResolveConn(dst_rank);
  if (!c) {
    Log::Error("EpollNet: cannot reach rank %d%s", dst_rank,
               transport::IsClientRank(dst_rank) ? " (client gone)" : "");
    return false;
  }
  return Enqueue(c, msg);
}

bool EpollNet::Send(int dst_rank, const Message& msg) {
  bool is_client = transport::IsClientRank(dst_rank);
  if (!is_client &&
      (dst_rank < 0 || dst_rank >= static_cast<int>(endpoints_.size())))
    return false;
  if (!running_) return false;
  Monitor mon("Net::Send", msg.trace_id);

  bool duplicate = false;
  if (Fault::Enabled()) {
    int64_t delay_ms = 0;
    switch (Fault::OnSend(&delay_ms)) {
      case Fault::Action::kDrop:
        Dashboard::Record("net.dropped", 0.0);
        return true;
      case Fault::Action::kDelay:
        Dashboard::Record("net.delayed", 0.0);
        std::this_thread::sleep_for(std::chrono::milliseconds(delay_ms));
        break;
      case Fault::Action::kDuplicate:
        duplicate = true;
        break;
      case Fault::Action::kNone:
        break;
    }
  }

  const int retries =
      static_cast<int>(std::max<int64_t>(0, FlagOr("send_retries", 2)));
  int64_t backoff_ms = std::max<int64_t>(1, FlagOr("send_backoff_ms", 50));
  for (int attempt = 0; attempt <= retries; ++attempt) {
    if (attempt > 0) {
      Dashboard::Record("net.retries", 0.0);
      std::this_thread::sleep_for(std::chrono::milliseconds(backoff_ms));
      backoff_ms *= 2;
      if (!running_) return false;
    }
    if (SendAttempt(dst_rank, msg)) {
      if (duplicate) {
        Dashboard::Record("net.duplicated", 0.0);
        SendAttempt(dst_rank, msg);
      }
      return true;
    }
  }
  Log::Error("EpollNet: send to rank %d failed after %d attempt(s)",
             dst_rank, retries + 1);
  return false;
}

void EpollNet::SettleClient(int client_rank) {
  // An anonymous client's read was DROPPED server-side (deadline shed /
  // hedge cancel): no reply will route back through Enqueue, so the
  // per-client and per-class slots settle here instead of leaking
  // until the client is permanently shed at cap.
  std::shared_ptr<Conn> c;
  {
    MutexLock lk(conns_mu_);
    auto it = client_conns_.find(client_rank);
    if (it == client_conns_.end()) return;  // client gone: slots died too
    c = it->second;
  }
  long long now = c->inflight.fetch_add(-1);
  if (now <= 0) c->inflight.fetch_add(1);  // floor at zero
  int qc = c->qos_class.load();
  qos::Release(qc < 0 ? 0 : qc);
}

Net::FanInStats EpollNet::FanIn() const {
  FanInStats st;
  st.accepted_total = accepted_total_.load();
  st.active_clients = active_clients_.load();
  st.client_shed = client_shed_.load();
  return st;
}

void EpollNet::Stop() {
  {
    // `stopping_` is the Stop-vs-Stop latch (running_ stays true
    // through the multi-second grace drain below, so testing it alone
    // would let a second caller race the first into thread.join() —
    // UB on the same std::thread — and double-close the epoll fds).
    // `running_` remains the reactor-exit flag.
    MutexLock lk(stop_mu_);
    if (!running_ || stopping_) return;
    stopping_ = true;
  }
  // Graceful drain: give the reactor a bounded window to flush queued
  // frames (a peer's exit/flush message must not die in our queue).
  int64_t grace_ms = std::min<int64_t>(FlagOr("io_timeout_ms", 30000),
                                       5000);
  auto deadline = std::chrono::system_clock::now() +
                  std::chrono::milliseconds(std::max<int64_t>(grace_ms, 1));
  std::vector<std::shared_ptr<Conn>> snapshot;
  {
    MutexLock lk(conns_mu_);
    snapshot = all_conns_;
  }
  for (auto& c : snapshot) {
    MutexLock lk(c->mu);
    while (!c->wq.empty() && !c->closed) {
      if (!c->can_write.WaitUntil(c->mu, deadline)) break;
    }
  }
  running_ = false;
  int lfd = listen_fd_.exchange(-1);
  if (lfd >= 0) ::close(lfd);
  for (auto& s : shards_) WakeShard(s.get());
  for (auto& s : shards_)
    if (s->thread.joinable()) s->thread.join();
  {
    MutexLock lk(conns_mu_);
    for (auto& c : all_conns_) {
      MutexLock clk(c->mu);
      if (!c->closed) {
        c->closed = true;
        ::close(c->fd);
      }
      c->wq.clear();
      c->wq_bytes = 0;
      c->can_write.NotifyAll();
    }
    all_conns_.clear();
    client_conns_.clear();
    rank_conns_.clear();
  }
  wq_bytes_total_.store(0, std::memory_order_relaxed);
  rx_arena_total_.store(0, std::memory_order_relaxed);
  for (auto& s : shards_) {
    ::close(s->epfd);
    ::close(s->wake_fd);
  }
  shards_.clear();
}

// `-net_engine` factory (transport.h): the readiness-model seam.
std::unique_ptr<RankTransport> MakeRankTransport(const std::string& engine) {
  if (engine == "epoll") return std::make_unique<EpollNet>();
  if (engine == "tcp") return std::make_unique<TcpNet>();
  if (engine == "uring") return MakeUringTransport();
  return nullptr;
}

}  // namespace mvtpu
