#include "mvtpu/dashboard.h"

#include <algorithm>
#include <map>
#include <sstream>

#include "mvtpu/mutex.h"

namespace mvtpu {

namespace {
struct Stat {
  long long count = 0;
  double total = 0.0;
  double max = 0.0;
};
Mutex g_mu;
std::map<std::string, Stat> g_stats GUARDED_BY(g_mu);
}  // namespace

void Dashboard::Record(const std::string& name, double seconds) {
  MutexLock lk(g_mu);
  Stat& s = g_stats[name];
  ++s.count;
  s.total += seconds;
  s.max = std::max(s.max, seconds);
}

std::string Dashboard::Report() {
  MutexLock lk(g_mu);
  std::ostringstream os;
  os << "---------------- Dashboard ----------------\n";
  for (const auto& kv : g_stats) {
    const Stat& s = kv.second;
    os << "  " << kv.first << ": count=" << s.count
       << " total=" << s.total << "s mean="
       << (s.total / static_cast<double>(s.count)) * 1e3
       << "ms max=" << s.max * 1e3 << "ms\n";
  }
  os << "--------------------------------------------";
  return os.str();
}

void Dashboard::Reset() {
  MutexLock lk(g_mu);
  g_stats.clear();
}

bool Dashboard::Query(const std::string& name, long long* count,
                      double* total) {
  MutexLock lk(g_mu);
  auto it = g_stats.find(name);
  if (it == g_stats.end()) return false;
  if (count) *count = it->second.count;
  if (total) *total = it->second.total;
  return true;
}

}  // namespace mvtpu
