#include "mvtpu/dashboard.h"

#include <algorithm>
#include <atomic>
#include <functional>
#include <map>
#include <sstream>
#include <thread>
#include <vector>

#include "mvtpu/mutex.h"

namespace mvtpu {

namespace {
struct Stat {
  long long count = 0;
  double total = 0.0;
  double max = 0.0;
  long long buckets[kDashboardBuckets] = {0};
  // Per-bucket exemplar: the LAST trace id whose observation landed in
  // the bucket (0 = none yet / tracing off) — the p99-to-trace link.
  long long exemplars[kDashboardBuckets] = {0};
};

// First bucket whose upper bound (1e-6 * 2^i) holds `seconds`; the last
// bucket is +inf.  Mirrored by metrics.py NATIVE_TIME_BUCKETS.
int BucketOf(double seconds) {
  double bound = 1e-6;
  for (int i = 0; i < kDashboardBuckets - 1; ++i) {
    if (seconds <= bound) return i;
    bound *= 2.0;
  }
  return kDashboardBuckets - 1;
}

Mutex g_mu;
std::map<std::string, Stat> g_stats GUARDED_BY(g_mu);

struct Span {
  std::string name;
  int64_t trace_id;
  int64_t ts_us;
  int64_t dur_us;
  uint64_t tid;
};

// Bounded: a long tracing session must not grow the heap without limit —
// the newest spans win (old ones were presumably already dumped).
constexpr size_t kMaxSpans = 1 << 16;
Mutex g_span_mu;
std::vector<Span> g_spans GUARDED_BY(g_span_mu);
size_t g_span_next GUARDED_BY(g_span_mu) = 0;  // ring cursor once full

std::atomic<bool> g_trace_enabled{false};
std::atomic<int> g_trace_rank{0};
std::atomic<int64_t> g_trace_seq{0};
thread_local int64_t t_trace_id = 0;

uint64_t ThisThreadId() {
  return std::hash<std::thread::id>{}(std::this_thread::get_id());
}

int64_t NowWallUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}
}  // namespace

void Dashboard::Record(const std::string& name, double seconds) {
  int bucket = BucketOf(seconds);
  int64_t exemplar = t_trace_id;  // this thread's active span id (0 = none)
  MutexLock lk(g_mu);
  Stat& s = g_stats[name];
  ++s.count;
  s.total += seconds;
  s.max = std::max(s.max, seconds);
  ++s.buckets[bucket];
  if (exemplar != 0) s.exemplars[bucket] = exemplar;
}

std::string Dashboard::Report() {
  MutexLock lk(g_mu);
  std::ostringstream os;
  os << "---------------- Dashboard ----------------\n";
  for (const auto& kv : g_stats) {
    const Stat& s = kv.second;
    os << "  " << kv.first << ": count=" << s.count
       << " total=" << s.total << "s mean="
       << (s.total / static_cast<double>(s.count)) * 1e3
       << "ms max=" << s.max * 1e3 << "ms\n";
  }
  os << "--------------------------------------------";
  return os.str();
}

void Dashboard::Reset() {
  {
    MutexLock lk(g_mu);
    g_stats.clear();
  }
  ClearSpans();
}

bool Dashboard::Query(const std::string& name, long long* count,
                      double* total) {
  MutexLock lk(g_mu);
  auto it = g_stats.find(name);
  if (it == g_stats.end()) return false;
  if (count) *count = it->second.count;
  if (total) *total = it->second.total;
  return true;
}

std::string Dashboard::Dump() {
  MutexLock lk(g_mu);
  std::ostringstream os;
  for (const auto& kv : g_stats) {
    const Stat& s = kv.second;
    os << kv.first << '\t' << s.count << '\t' << s.total << '\t' << s.max
       << '\t';
    for (int i = 0; i < kDashboardBuckets; ++i) {
      if (i) os << ',';
      os << s.buckets[i];
    }
    os << '\t';
    for (int i = 0; i < kDashboardBuckets; ++i) {
      if (i) os << ',';
      os << s.exemplars[i];
    }
    os << '\n';
  }
  return os.str();
}

// ---- tracing --------------------------------------------------------------

void Dashboard::SetTraceEnabled(bool on) { g_trace_enabled = on; }
bool Dashboard::TraceEnabled() { return g_trace_enabled; }
void Dashboard::SetTraceRank(int rank) { g_trace_rank = rank; }

void Dashboard::SetThreadTraceId(int64_t id) { t_trace_id = id; }
int64_t Dashboard::ThreadTraceId() { return t_trace_id; }

int64_t Dashboard::NewTraceId() {
  // Rank salt in the high bits: two ranks can never mint the same id,
  // which is what lets merged traces correlate spans by id alone.
  return ((static_cast<int64_t>(g_trace_rank) + 1) << 40) | ++g_trace_seq;
}

void Dashboard::RecordSpan(const std::string& name, int64_t trace_id,
                           int64_t ts_us, int64_t dur_us) {
  Span sp{name, trace_id, ts_us, dur_us, ThisThreadId()};
  MutexLock lk(g_span_mu);
  if (g_spans.size() < kMaxSpans) {
    g_spans.push_back(std::move(sp));
  } else {
    g_spans[g_span_next] = std::move(sp);
    g_span_next = (g_span_next + 1) % kMaxSpans;
  }
}

std::string Dashboard::DumpSpans() {
  MutexLock lk(g_span_mu);
  std::ostringstream os;
  int rank = g_trace_rank;
  for (const auto& sp : g_spans) {
    os << sp.name << '\t' << sp.trace_id << '\t' << sp.ts_us << '\t'
       << sp.dur_us << '\t' << rank << '\t' << sp.tid << '\n';
  }
  return os.str();
}

void Dashboard::ClearSpans() {
  MutexLock lk(g_span_mu);
  g_spans.clear();
  g_span_next = 0;
}

// ---- Monitor --------------------------------------------------------------

Monitor::Monitor(std::string name, int64_t trace_id)
    : name_(std::move(name)), start_(std::chrono::steady_clock::now()) {
  if (!Dashboard::TraceEnabled()) return;
  wall_us_ = NowWallUs();
  if (trace_id != 0) {
    // Pinned id (e.g. the one riding a wire message): adopt it for the
    // span AND for nested monitors on this thread.
    trace_id_ = trace_id;
  } else if (t_trace_id != 0) {
    trace_id_ = t_trace_id;          // nested op: share the enclosing id
  } else {
    trace_id_ = Dashboard::NewTraceId();
  }
  if (t_trace_id == 0) {
    Dashboard::SetThreadTraceId(trace_id_);
    own_thread_id_ = true;           // restore on destruction
  }
}

Monitor::~Monitor() {
  auto dt = std::chrono::duration<double>(
                std::chrono::steady_clock::now() - start_).count();
  Dashboard::Record(name_, dt);
  if (trace_id_ != 0) {
    Dashboard::RecordSpan(name_, trace_id_, wall_us_,
                          static_cast<int64_t>(dt * 1e6));
    if (own_thread_id_) Dashboard::SetThreadTraceId(0);
  }
}

}  // namespace mvtpu
