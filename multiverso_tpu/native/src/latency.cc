#include "mvtpu/latency.h"

#include <algorithm>
#include <atomic>
#include <chrono>
#include <map>
#include <sstream>

#include "mvtpu/dashboard.h"
#include "mvtpu/mutex.h"

namespace mvtpu {
namespace latency {

namespace {

std::atomic<bool> g_armed{true};

bool IsReplyType(MsgType t) {
  switch (t) {
    case MsgType::ReplyGet:
    case MsgType::ReplyAdd:
    case MsgType::ReplyError:
    case MsgType::ReplyFlush:
    case MsgType::ReplyVersion:
    case MsgType::ReplyBusy:
    case MsgType::ReplyReplica:
    case MsgType::OpsReply:
      return true;
    default:
      return false;
  }
}

// Per-peer clock filter: a bounded window of (rtt, offset) samples from
// timed round trips; the minimum-RTT sample wins (NTP's clock filter —
// queueing delay inflates RTT and, asymmetrically, offset error).
constexpr int kWindow = 8;

struct PeerClock {
  int64_t rtt[kWindow];
  int64_t off[kWindow];
  int next = 0;
  long long samples = 0;
};

Mutex g_mu;
std::map<int, PeerClock> g_peers GUARDED_BY(g_mu);

void UpdateOffset(int rank, int64_t offset_ns, int64_t rtt_ns) {
  MutexLock lk(g_mu);
  PeerClock& pc = g_peers[rank];
  int slot = pc.next;
  pc.rtt[slot] = rtt_ns;
  pc.off[slot] = offset_ns;
  pc.next = (pc.next + 1) % kWindow;
  ++pc.samples;
}

bool BestLocked(const PeerClock& pc, int64_t* offset_ns,
                int64_t* rtt_ns) REQUIRES(g_mu) {
  if (pc.samples == 0) return false;
  int n = static_cast<int>(std::min<long long>(pc.samples, kWindow));
  int best = 0;
  for (int i = 1; i < n; ++i)
    if (pc.rtt[i] < pc.rtt[best]) best = i;
  if (offset_ns) *offset_ns = pc.off[best];
  if (rtt_ns) *rtt_ns = pc.rtt[best];
  return true;
}

void RecordStage(const char* name, int64_t dur_ns) {
  // Clamp at zero: a residual offset error can push a cross-clock stage
  // a few microseconds negative; a negative latency is never data.
  Dashboard::Record(name,
                    static_cast<double>(std::max<int64_t>(dur_ns, 0)) * 1e-9);
}

}  // namespace

int64_t NowNs() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void Arm(bool on) { g_armed.store(on, std::memory_order_relaxed); }
bool Armed() { return g_armed.load(std::memory_order_relaxed); }

void StampEnqueue(Message* m) {
  if (!Armed()) return;
  m->flags |= msgflag::kHasTiming;
  m->timing.t[TimingTrail::kEnqueue] = NowNs();
}

void StampSend(Message* m) {
  if (!m->has_timing()) return;
  // The heartbeat echo keeps its request MsgType but is reply-shaped
  // (its apply stamp is set) — it must fill the reply-send slot, not
  // clobber the origin rank's send stamp with this rank's clock.
  int slot = (IsReplyType(m->type) ||
              m->timing.t[TimingTrail::kApplyDone] != 0)
                 ? TimingTrail::kReplySend
                 : TimingTrail::kSend;
  if (m->timing.t[slot] == 0) m->timing.t[slot] = NowNs();
}

void StampRecv(Message* m) {
  if (!m->has_timing() || IsReplyType(m->type)) return;
  // Reply-shaped heartbeat echoes carry a foreign-rank trail: their
  // recv boundary is the client receipt OnReply takes itself.
  if (m->timing.t[TimingTrail::kApplyDone] != 0) return;
  if (m->timing.t[TimingTrail::kRecv] == 0)
    m->timing.t[TimingTrail::kRecv] = NowNs();
}

void StampDequeue(Message* m) {
  if (!m->has_timing()) return;
  if (m->timing.t[TimingTrail::kDequeue] == 0)
    m->timing.t[TimingTrail::kDequeue] = NowNs();
}

void StampReply(const Message& req, Message* reply) {
  if (!req.has_timing()) return;
  reply->timing = req.timing;
  reply->flags |= msgflag::kHasTiming;
  reply->timing.t[TimingTrail::kApplyDone] = NowNs();
}

void OnReply(const Message& reply, int peer_rank) {
  if (!reply.has_timing()) return;
  const int64_t* t = reply.timing.t;
  int64_t t_enq = t[TimingTrail::kEnqueue];
  int64_t t_send = t[TimingTrail::kSend];
  int64_t t_recv = t[TimingTrail::kRecv];
  int64_t t_deq = t[TimingTrail::kDequeue];
  int64_t t_apply = t[TimingTrail::kApplyDone];
  int64_t t_reply = t[TimingTrail::kReplySend];
  int64_t now = NowNs();

  // NTP sample first, so this very reply's stages use the freshest
  // offset window: offset = ((t2-t1) + (t5-t6))/2, rtt = round trip
  // minus the server's hold time.
  bool remote = t_send != 0 && t_recv != 0 && t_reply != 0;
  if (remote) {
    int64_t offset = ((t_recv - t_send) + (t_reply - now)) / 2;
    int64_t rtt = (now - t_send) - (t_reply - t_recv);
    if (rtt >= 0) UpdateOffset(peer_rank, offset, rtt);
  }
  int64_t off = 0;
  {
    MutexLock lk(g_mu);
    auto it = g_peers.find(peer_rank);
    if (it != g_peers.end()) BestLocked(it->second, &off, nullptr);
  }

  if (t_enq && t_send) RecordStage("lat.stage.queue", t_send - t_enq);
  if (remote) {
    RecordStage("lat.stage.wire_out", (t_recv - off) - t_send);
    if (t_deq) RecordStage("lat.stage.mailbox", t_deq - t_recv);
  } else if (t_send && t_deq) {
    // Local delivery (or an old-transport hop that never stamped recv):
    // the whole send->dequeue leg is the mailbox wait.
    RecordStage("lat.stage.mailbox", t_deq - t_send);
  }
  if (t_deq && t_apply) RecordStage("lat.stage.apply", t_apply - t_deq);
  if (t_apply && t_reply)
    RecordStage("lat.stage.reactor", t_reply - t_apply);
  if (t_reply)
    RecordStage("lat.stage.wire_back",
                remote ? now - (t_reply - off) : now - t_reply);
  if (t_enq) RecordStage("lat.total", now - t_enq);
}

bool PeerOffset(int rank, int64_t* offset_ns, int64_t* rtt_ns,
                long long* samples) {
  MutexLock lk(g_mu);
  auto it = g_peers.find(rank);
  if (it == g_peers.end()) return false;
  if (samples) *samples = it->second.samples;
  return BestLocked(it->second, offset_ns, rtt_ns);
}

std::string OffsetsJson() {
  MutexLock lk(g_mu);
  std::ostringstream os;
  os << "[";
  bool first = true;
  for (const auto& [rank, pc] : g_peers) {
    int64_t off = 0, rtt = 0;
    if (!BestLocked(pc, &off, &rtt)) continue;
    if (!first) os << ',';
    first = false;
    os << "{\"rank\":" << rank << ",\"offset_ns\":" << off
       << ",\"rtt_ns\":" << rtt << ",\"samples\":" << pc.samples << "}";
  }
  os << "]";
  return os.str();
}

void Reset() {
  MutexLock lk(g_mu);
  g_peers.clear();
}

}  // namespace latency
}  // namespace mvtpu
