#include "mvtpu/profiler.h"

#include <dlfcn.h>
#include <execinfo.h>
#include <signal.h>
#include <string.h>
#include <sys/time.h>

#include <atomic>
#include <cstdio>
#include <map>
#include <sstream>
#include <vector>

#include "mvtpu/log.h"
#include "mvtpu/mutex.h"

namespace mvtpu {
namespace profiler {

namespace {

constexpr int kMaxDepth = 32;
constexpr int kRingSlots = 8192;

struct Sample {
  void* pc[kMaxDepth];
  int depth;
};

// Preallocated ring written ONLY by the signal handler (slot claimed
// with one fetch_add); the dump side reads slots below the published
// count.  Slots are never recycled — a full ring drops new samples
// (g_dropped) until Clear(), which bounds handler work and memory.
Sample g_ring[kRingSlots];
std::atomic<int> g_next{0};
std::atomic<long long> g_samples{0};
std::atomic<long long> g_dropped{0};
std::atomic<bool> g_running{false};
std::atomic<int> g_hz{0};
bool g_handler_installed = false;
Mutex g_mu;  // Start/Stop/Dump serialization (never the handler)

void OnSigprof(int, siginfo_t*, void*) {
  // Async-signal context: no locks, no allocation.  backtrace(3) is
  // preloaded by Start() so its lazy dynamic-linker initialization
  // cannot run here.
  if (!g_running.load(std::memory_order_relaxed)) return;
  int slot = g_next.fetch_add(1, std::memory_order_relaxed);
  if (slot >= kRingSlots) {
    g_next.store(kRingSlots, std::memory_order_relaxed);
    g_dropped.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  Sample& s = g_ring[slot];
  s.depth = backtrace(s.pc, kMaxDepth);
  g_samples.fetch_add(1, std::memory_order_relaxed);
}

std::string SymbolOf(void* addr) {
  Dl_info info;
  if (dladdr(addr, &info) && info.dli_sname) return info.dli_sname;
  char buf[32];
  std::snprintf(buf, sizeof(buf), "%p", addr);
  return buf;
}

}  // namespace

bool Start(int hz) {
  if (hz <= 0) {
    Stop();
    return true;
  }
  MutexLock lk(g_mu);
  // Pre-warm backtrace's one-time libgcc initialization (it may
  // allocate) OUTSIDE the signal handler.
  void* warm[4];
  backtrace(warm, 4);
  if (!g_handler_installed) {
    struct sigaction sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_sigaction = OnSigprof;
    sa.sa_flags = SA_RESTART | SA_SIGINFO;
    sigemptyset(&sa.sa_mask);
    if (sigaction(SIGPROF, &sa, nullptr) != 0) {
      Log::Error("profiler: sigaction(SIGPROF) failed");
      return false;
    }
    g_handler_installed = true;
  }
  g_hz.store(hz, std::memory_order_relaxed);
  g_running.store(true, std::memory_order_relaxed);
  itimerval it{};
  int64_t period_us = 1000000 / hz;
  if (period_us <= 0) period_us = 1;
  it.it_interval.tv_sec = static_cast<time_t>(period_us / 1000000);
  it.it_interval.tv_usec = static_cast<suseconds_t>(period_us % 1000000);
  it.it_value = it.it_interval;
  if (setitimer(ITIMER_PROF, &it, nullptr) != 0) {
    g_running.store(false, std::memory_order_relaxed);
    Log::Error("profiler: setitimer(ITIMER_PROF) failed");
    return false;
  }
  Log::Info("profiler: sampling at %d Hz (CPU time)", hz);
  return true;
}

void Stop() {
  MutexLock lk(g_mu);
  if (!g_running.exchange(false)) return;
  itimerval off{};
  setitimer(ITIMER_PROF, &off, nullptr);
  g_hz.store(0, std::memory_order_relaxed);
}

bool Running() { return g_running.load(std::memory_order_relaxed); }

std::string DumpFolded() {
  MutexLock lk(g_mu);
  int n = std::min(g_next.load(std::memory_order_acquire), kRingSlots);
  // Aggregate identical stacks first (by raw addresses), symbolize each
  // distinct stack once — dladdr per frame per SAMPLE would make dumps
  // quadratic on hot stacks.
  std::map<std::vector<void*>, long long> agg;
  for (int i = 0; i < n; ++i) {
    const Sample& s = g_ring[i];
    if (s.depth <= 0) continue;
    std::vector<void*> key(s.pc, s.pc + s.depth);
    ++agg[key];
  }
  std::ostringstream os;
  for (const auto& [stack, count] : agg) {
    // backtrace() returns innermost-first; folded convention wants
    // outermost-first with the leaf last.  Skip the two innermost
    // frames (the handler + the kernel trampoline) — they are the
    // profiler observing itself, never the profiled code.
    size_t skip = stack.size() > 2 ? 2 : 0;
    bool first = true;
    for (size_t i = stack.size(); i > skip; --i) {
      if (!first) os << ';';
      first = false;
      os << SymbolOf(stack[i - 1]);
    }
    os << ' ' << count << '\n';
  }
  return os.str();
}

std::string StatusJson() {
  std::ostringstream os;
  os << "{\"running\":" << (Running() ? "true" : "false")
     << ",\"hz\":" << g_hz.load(std::memory_order_relaxed)
     << ",\"samples\":" << g_samples.load(std::memory_order_relaxed)
     << ",\"dropped\":" << g_dropped.load(std::memory_order_relaxed)
     << "}";
  return os.str();
}

void Clear() {
  MutexLock lk(g_mu);
  g_next.store(0, std::memory_order_relaxed);
  g_samples.store(0, std::memory_order_relaxed);
  g_dropped.store(0, std::memory_order_relaxed);
}

}  // namespace profiler
}  // namespace mvtpu
