#include "mvtpu/repl.h"

#include <atomic>

namespace mvtpu {
namespace repl {

namespace {
std::atomic<bool> g_armed{false};
std::atomic<bool> g_sync{true};
std::atomic<long long> g_forwards{0};
std::atomic<long long> g_acks{0};
std::atomic<long long> g_applied{0};
std::atomic<long long> g_parked{0};
std::atomic<long long> g_lag_waits{0};
std::atomic<long long> g_snapshots{0};
std::atomic<long long> g_catchups{0};
std::atomic<long long> g_promotions{0};
std::atomic<long long> g_epoch_flips{0};
std::atomic<long long> g_dup_skips{0};
}  // namespace

void Arm(bool on) { g_armed.store(on, std::memory_order_release); }
bool Armed() { return g_armed.load(std::memory_order_relaxed); }
void ArmSync(bool on) { g_sync.store(on, std::memory_order_release); }
bool Sync() { return g_sync.load(std::memory_order_relaxed); }

Stats GetStats() {
  Stats s;
  s.forwards = g_forwards.load(std::memory_order_relaxed);
  s.acks = g_acks.load(std::memory_order_relaxed);
  s.applied = g_applied.load(std::memory_order_relaxed);
  s.parked = g_parked.load(std::memory_order_relaxed);
  s.lag_waits = g_lag_waits.load(std::memory_order_relaxed);
  s.snapshots = g_snapshots.load(std::memory_order_relaxed);
  s.catchups = g_catchups.load(std::memory_order_relaxed);
  s.promotions = g_promotions.load(std::memory_order_relaxed);
  s.epoch_flips = g_epoch_flips.load(std::memory_order_relaxed);
  s.dup_skips = g_dup_skips.load(std::memory_order_relaxed);
  return s;
}

void NoteForward() { g_forwards.fetch_add(1, std::memory_order_relaxed); }
void NoteAck() { g_acks.fetch_add(1, std::memory_order_relaxed); }
void NoteApplied() { g_applied.fetch_add(1, std::memory_order_relaxed); }
void NoteParked() { g_parked.fetch_add(1, std::memory_order_relaxed); }
void NoteLagWait() { g_lag_waits.fetch_add(1, std::memory_order_relaxed); }
void NoteSnapshot() { g_snapshots.fetch_add(1, std::memory_order_relaxed); }
void NoteCatchup() { g_catchups.fetch_add(1, std::memory_order_relaxed); }
void NotePromotion() {
  g_promotions.fetch_add(1, std::memory_order_relaxed);
}
void NoteEpochFlip() {
  g_epoch_flips.fetch_add(1, std::memory_order_relaxed);
}
void NoteDupSkip() { g_dup_skips.fetch_add(1, std::memory_order_relaxed); }

void ResetStats() {
  g_forwards.store(0);
  g_acks.store(0);
  g_applied.store(0);
  g_parked.store(0);
  g_lag_waits.store(0);
  g_snapshots.store(0);
  g_catchups.store(0);
  g_promotions.store(0);
  g_epoch_flips.store(0);
  g_dup_skips.store(0);
}

}  // namespace repl
}  // namespace mvtpu
