// Wire conventions between worker stubs and server shards:
//   Array Get      req: no blobs                 reply: [float local-shard]
//   Array Add      req: [AddOption][float shard-slice]
//   Matrix GetAll  req: no blobs                 reply: [float row-block]
//   Matrix GetRows req: [int32 global ids]       reply: [float rows-packed]
//   Matrix AddAll  req: [AddOption][float row-block-slice]
//   Matrix AddRows req: [AddOption][int32 global ids][float rows-packed]
// The worker partitions every request across shard owners (ShardOf /
// OwnerOf are the partition contract) and reassembles replies by the
// reply's src rank.  msg_id >= 0 means the caller blocks until every
// contacted shard replied; msg_id < 0 is async.
#include "mvtpu/table.h"

#include <chrono>
#include <cmath>
#include <cstdlib>
#include <cstring>

#include "mvtpu/codec.h"
#include "mvtpu/configure.h"
#include "mvtpu/dashboard.h"
#include "mvtpu/latency.h"
#include "mvtpu/qos.h"
#include "mvtpu/log.h"
#include "mvtpu/ops.h"
#include "mvtpu/zoo.h"

namespace mvtpu {

// The capacity history ring's bucket arrays mirror the version-bucket
// map one to one (docs/observability.md "capacity plane").
static_assert(capacity::kLoadBuckets == ServerTable::kVersionBuckets,
              "capacity history buckets must match version buckets");

namespace {

// Flags may not be registered when tables are driven standalone.
int64_t TableFlagOr(const char* name, int64_t dflt) {
  return configure::Has(name) ? configure::GetInt(name) : dflt;
}

int64_t SteadyNowMs() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

}  // namespace

// ---------------- workload observability (docs/observability.md) -------

void ServerTable::NoteStaleness(int64_t request_version) {
  if (!workload::Armed() || request_version < 0) return;
  int64_t stale = version() - request_version;
  if (stale < 0) stale = 0;  // a racing reply can out-stamp us; clamp
  // Ride the µs-bucket Dashboard ladder at 1 unit = 1 version (the
  // serve.queue_depth trick): bucket i ≈ staleness 2^i, and the
  // bridged histogram reconstructs the distribution host-side.
  Dashboard::Record(
      "workload.staleness.t" + std::to_string(obs_table_id_),
      static_cast<double>(stale) * 1e-6);
}

void ServerTable::NoteAddHealth(const float* delta, size_t n) {
  if (!workload::Armed() || !delta || n == 0) return;
  double l2sq = 0.0, linf = 0.0;
  long long nans = 0, infs = 0;
  for (size_t i = 0; i < n; ++i) {
    float v = delta[i];
    if (std::isnan(v)) {
      ++nans;
      continue;
    }
    if (std::isinf(v)) {
      ++infs;
      continue;
    }
    double d = static_cast<double>(v);
    l2sq += d * d;
    if (std::fabs(d) > linf) linf = std::fabs(d);
  }
  {
    MutexLock lk(health_mu_);
    add_l2sq_ += l2sq;
    if (linf > add_linf_) add_linf_ = linf;
    nan_count_ += nans;
    inf_count_ += infs;
  }
  if (nans > 0) {
    Dashboard::Record("workload.nan.t" + std::to_string(obs_table_id_),
                      0.0);
    // First NaN per table trips the black box: a diverging update is a
    // failure whose post-mortem needs the recent event/span ring NOW,
    // not a silent shard poisoning discovered at eval time.
    if (!nan_triggered_.exchange(true))
      ops::BlackboxTrigger(
          "nan_update: table " + std::to_string(obs_table_id_) + " (" +
          std::to_string(nans) + " NaN element(s) in one add)");
  }
  if (infs > 0)
    Dashboard::Record("workload.inf.t" + std::to_string(obs_table_id_),
                      0.0);
}

ServerTable::LoadStats ServerTable::Load() const {
  LoadStats out;
  out.gets = total_gets_.load(std::memory_order_relaxed);
  out.adds = total_adds_.load(std::memory_order_relaxed);
  int64_t max_load = 0, sum = 0;
  for (int b = 0; b < kVersionBuckets; ++b) {
    int64_t load = bucket_gets_[b].load(std::memory_order_relaxed) +
                   bucket_adds_[b].load(std::memory_order_relaxed);
    sum += load;
    if (load > max_load) max_load = load;
  }
  out.bucket_load_max = max_load;
  out.bucket_load_mean =
      static_cast<double>(sum) / static_cast<double>(kVersionBuckets);
  out.skew_ratio = out.bucket_load_mean > 0
                       ? static_cast<double>(max_load) / out.bucket_load_mean
                       : 0.0;
  {
    MutexLock lk(health_mu_);
    out.add_l2 = std::sqrt(add_l2sq_);
    out.add_linf = add_linf_;
    out.nan_count = nan_count_;
    out.inf_count = inf_count_;
  }
  long long cnt = 0;
  double total = 0.0;
  if (Dashboard::Query(
          "workload.staleness.t" + std::to_string(obs_table_id_), &cnt,
          &total)) {
    out.staleness_count = cnt;
    // Recorded at 1e-6 per version (the µs ladder); undo the scale.
    out.staleness_mean = cnt ? total * 1e6 / static_cast<double>(cnt) : 0.0;
  }
  return out;
}

// ---------------------------------------------------------------- server

ArrayServerTable::ArrayServerTable(int64_t global_size, UpdaterType updater,
                                   int rank, int size)
    : range_(ShardOf(global_size, rank, size)),
      data_(static_cast<size_t>(range_.len()), 0.0f), updater_(updater) {
  if (NumSlots(updater_) > 0) slot0_.assign(data_.size(), 0.0f);
  RecomputeCapacity();
}

void ArrayServerTable::RecomputeCapacity() {
  // Arrays are whole-shard spans (whole-shard versioning, whole-shard
  // checksum): shard bytes only, no per-bucket attribution.
  MutexLock lk(mu_);
  ResetCapacity(
      static_cast<int64_t>((data_.size() + slot0_.size()) * sizeof(float)),
      static_cast<int64_t>(data_.size()));
}

void ArrayServerTable::ProcessGet(const Message& req, Message* reply) {
  Monitor mon("ArrayServer::ProcessGet");
  NoteGet(-1);                 // whole-array read: totals only
  NoteStaleness(req.version);  // requester stamped its last-seen version
  reply->version = version();  // serve-layer staleness stamp
  MutexLock lk(mu_);
  reply->data.emplace_back(data_.data(), data_.size() * sizeof(float));
}

void ArrayServerTable::ProcessAdd(const Message& req) {
  Monitor mon("ArrayServer::ProcessAdd");
  const AddOption* opt = req.data[0].As<AddOption>();
  const float* delta = req.data[1].As<float>();
  size_t n = req.data[1].count<float>();
  NoteAdd(-1);
  NoteAddHealth(delta, n);
  MutexLock lk(mu_);
  if (n != data_.size()) {
    Log::Error("ArrayServerTable: delta size %zu != %zu", n, data_.size());
    return;
  }
  ApplyUpdate(updater_, *opt, data_.data(),
              slot0_.empty() ? nullptr : slot0_.data(), delta, n);
  BumpVersion();  // whole-array add: every bucket advances
}

bool ArrayServerTable::Store(Stream* out) const {
  MutexLock lk(mu_);
  int64_t n = static_cast<int64_t>(data_.size());
  return out->Write(&n, sizeof(n)) == sizeof(n) &&
         out->Write(data_.data(), n * sizeof(float)) == n * sizeof(float) &&
         (slot0_.empty() ||
          out->Write(slot0_.data(), n * sizeof(float)) == n * sizeof(float));
}

bool ArrayServerTable::Load(Stream* in) {
  MutexLock lk(mu_);
  int64_t n = 0;
  if (in->Read(&n, sizeof(n)) != sizeof(n) ||
      n != static_cast<int64_t>(data_.size()))
    return false;
  if (in->Read(data_.data(), n * sizeof(float)) !=
      static_cast<size_t>(n) * sizeof(float))
    return false;
  if (!slot0_.empty() &&
      in->Read(slot0_.data(), n * sizeof(float)) !=
          static_cast<size_t>(n) * sizeof(float))
    return false;
  ResetCapacity(
      static_cast<int64_t>((data_.size() + slot0_.size()) * sizeof(float)),
      static_cast<int64_t>(data_.size()));
  return true;
}

std::vector<uint32_t> ArrayServerTable::BucketChecksums() const {
  // Arrays version whole-shard (BumpVersion(-1)), so one whole-shard
  // checksum is the matching granularity.
  MutexLock lk(mu_);
  return {audit::Crc32(data_.data(), data_.size() * sizeof(float))};
}

MatrixServerTable::MatrixServerTable(int64_t rows, int64_t cols,
                                     UpdaterType updater, int rank, int size)
    : global_rows_(rows), cols_(cols), range_(ShardOf(rows, rank, size)),
      data_(static_cast<size_t>(range_.len() * cols), 0.0f),
      updater_(updater) {
  if (NumSlots(updater_) > 0) slot0_.assign(data_.size(), 0.0f);
  RecomputeCapacity();
}

void MatrixServerTable::RecomputeCapacity() {
  // Dense row block: fixed bytes once constructed, attributed per
  // bucket on the SAME global-row->bucket map the version stamps and
  // CRC beacons use — a bucket's bytes are exactly what a bucket
  // migration would move (docs/observability.md "capacity plane").
  MutexLock lk(mu_);
  int64_t row_bytes =
      cols_ * static_cast<int64_t>(sizeof(float)) *
      (slot0_.empty() ? 1 : 2);
  ResetCapacity(range_.len() * row_bytes, range_.len());
  for (int64_t r = 0; r < range_.len(); ++r)
    ChargeBucketBytes(RowBucket(range_.begin + r), row_bytes);
}

void MatrixServerTable::ProcessGet(const Message& req, Message* reply) {
  Monitor mon("MatrixServer::ProcessGet");
  NoteGet(-1);  // totals; per-row bucket loads charge via NoteKey below
  NoteStaleness(req.version);
  MutexLock lk(mu_);
  if (req.data.empty()) {  // GetAll: reply with the local row block
    reply->version = version();
    reply->data.emplace_back(data_.data(), data_.size() * sizeof(float));
    return;
  }
  const int32_t* ids = req.data[0].As<int32_t>();
  size_t k = req.data[0].count<int32_t>();
  // Bucket-granular stamp: the max version over the TOUCHED row
  // buckets — adds to other rows don't invalidate this read's cache.
  int64_t stamp = 0;
  for (size_t i = 0; i < k; ++i)
    if (ids[i] >= 0)
      stamp = std::max(stamp, bucket_version(RowBucket(ids[i])));
  reply->version = stamp;
  if (workload::Armed())
    for (size_t i = 0; i < k; ++i)
      if (ids[i] >= 0 && ids[i] < global_rows_)
        NoteKey(workload::KeyHash(static_cast<int64_t>(ids[i])),
                std::to_string(ids[i]), RowBucket(ids[i]),
                /*is_add=*/false);
  Blob out(k * cols_ * sizeof(float));
  float* dst = out.As<float>();
  for (size_t i = 0; i < k; ++i) {
    int64_t r = ids[i] - range_.begin;  // global -> local row
    if (ids[i] < 0 || ids[i] >= global_rows_ || r < 0 || r >= range_.len()) {
      // out-of-range / mis-routed rows read as zeros
      std::memset(dst + i * cols_, 0, cols_ * sizeof(float));
      continue;
    }
    std::memcpy(dst + i * cols_, data_.data() + r * cols_,
                cols_ * sizeof(float));
  }
  reply->data.push_back(std::move(out));
}

namespace {

// AddRows delta rows may arrive split across SEVERAL blobs (the
// borrowed multi-shard path ships each contiguous caller-order run as
// its own zero-copy iovec, docs/embedding.md); blob boundaries are
// row-aligned by the sender contract.  This cursor walks rows across
// the blob sequence [first, req.data.size()).
struct RowBlobCursor {
  const Message& req;
  size_t blob;
  size_t off = 0;  // floats consumed inside the current blob
  RowBlobCursor(const Message& r, size_t first) : req(r), blob(first) {}
  const float* Next(int64_t cols) {
    while (blob < req.data.size() &&
           off + static_cast<size_t>(cols) > req.data[blob].count<float>()) {
      blob += 1;
      off = 0;
    }
    if (blob >= req.data.size()) return nullptr;
    const float* p = req.data[blob].As<float>() + off;
    off += static_cast<size_t>(cols);
    return p;
  }
};

}  // namespace

void MatrixServerTable::ProcessAdd(const Message& req) {
  Monitor mon("MatrixServer::ProcessAdd");
  const AddOption* opt = req.data[0].As<AddOption>();
  NoteAdd(-1);
  // Update-health scan over EVERY delta blob (a multi-shard borrowed
  // AddRows splits the payload across run blobs — scanning only
  // data.back() would miss NaNs in the earlier runs).
  for (size_t b = req.data.size() == 2 ? 1 : 2; b < req.data.size(); ++b)
    NoteAddHealth(req.data[b].As<float>(), req.data[b].count<float>());
  if (workload::Armed() && req.data.size() >= 3) {
    const int32_t* note_ids = req.data[1].As<int32_t>();
    size_t note_k = req.data[1].count<int32_t>();
    for (size_t i = 0; i < note_k; ++i)
      if (note_ids[i] >= 0 && note_ids[i] < global_rows_)
        NoteKey(workload::KeyHash(static_cast<int64_t>(note_ids[i])),
                std::to_string(note_ids[i]), RowBucket(note_ids[i]),
                /*is_add=*/true);
  }
  MutexLock lk(mu_);
  float* slots = slot0_.empty() ? nullptr : slot0_.data();
  if (req.data.size() == 2) {  // AddAll: the local row-block slice
    const float* delta = req.data[1].As<float>();
    if (req.data[1].count<float>() != data_.size()) {
      Log::Error("MatrixServerTable: AddAll size mismatch");
      return;
    }
    ApplyUpdate(updater_, *opt, data_.data(), slots, delta, data_.size());
    BumpVersion();
    return;
  }
  const int32_t* ids = req.data[1].As<int32_t>();
  size_t k = req.data[1].count<int32_t>();
  size_t delta_floats = 0;
  for (size_t b = 2; b < req.data.size(); ++b)
    delta_floats += req.data[b].count<float>();
  if (delta_floats != k * static_cast<size_t>(cols_)) {
    Log::Error("MatrixServerTable: AddRows size mismatch");
    return;
  }
  RowBlobCursor cur(req, 2);
  if (!slots) {
    // Stateless add: sequential application composes like consecutive
    // reference Adds (duplicates sum).
    for (size_t i = 0; i < k; ++i) {
      const float* row = cur.Next(cols_);
      if (!row) break;
      int64_t r = ids[i] - range_.begin;
      if (ids[i] < 0 || ids[i] >= global_rows_ || r < 0 || r >= range_.len())
        continue;
      ApplyUpdate(updater_, *opt, data_.data() + r * cols_, nullptr, row,
                  static_cast<size_t>(cols_));
      BumpVersion(RowBucket(ids[i]));
    }
    return;
  }
  // Stateful updaters (adagrad/momentum/...): pre-aggregate duplicate row
  // ids so the math matches the JAX plane, which segment-sums duplicates
  // before one updater call per row (tables/matrix_table.py).
  std::unordered_map<int64_t, std::vector<float>> agg;
  for (size_t i = 0; i < k; ++i) {
    const float* row = cur.Next(cols_);
    if (!row) break;
    int64_t r = ids[i] - range_.begin;
    if (ids[i] < 0 || ids[i] >= global_rows_ || r < 0 || r >= range_.len())
      continue;
    auto& acc = agg[r];
    if (acc.empty()) acc.assign(static_cast<size_t>(cols_), 0.0f);
    for (int64_t c = 0; c < cols_; ++c) acc[c] += row[c];
  }
  for (auto& kv : agg) {
    ApplyUpdate(updater_, *opt, data_.data() + kv.first * cols_,
                slots + kv.first * cols_, kv.second.data(),
                static_cast<size_t>(cols_));
    BumpVersion(RowBucket(kv.first + range_.begin));  // global row bucket
  }
}

void MatrixServerTable::BuildReplica(Message* reply) {
  Monitor mon("MatrixServer::BuildReplica");
  NoteReplicaPush();
  // The SERVER chooses what to replicate: its SpaceSaving top-K row
  // ids (docs/embedding.md).  Tracker disarmed or cold => empty push
  // (still three blobs — the wire shape is fixed).
  auto top = HotTopK();
  std::vector<int32_t> ids;
  ids.reserve(top.size());
  for (const auto& item : top) {
    char* end = nullptr;
    long v = std::strtol(item.label.c_str(), &end, 10);
    if (!end || *end != '\0' || item.label.empty()) continue;
    if (v < range_.begin || v >= range_.end) continue;  // not my shard
    ids.push_back(static_cast<int32_t>(v));
  }
  Blob id_blob(ids.size() * sizeof(int32_t));
  Blob ver_blob(ids.size() * sizeof(int64_t));
  Blob row_blob(ids.size() * static_cast<size_t>(cols_) * sizeof(float));
  int32_t* id_p = id_blob.As<int32_t>();
  int64_t* ver_p = ver_blob.As<int64_t>();
  float* row_p = row_blob.As<float>();
  {
    // One lock over versions AND data: ProcessAdd bumps versions under
    // mu_ too, so a pushed row can never carry a version newer than its
    // bytes (the stamp may be conservative, never optimistic — the same
    // pre-fetch discipline the client caches follow).
    MutexLock lk(mu_);
    for (size_t i = 0; i < ids.size(); ++i) {
      id_p[i] = ids[i];
      ver_p[i] = bucket_version(RowBucket(ids[i]));
      std::memcpy(row_p + i * cols_,
                  data_.data() + (ids[i] - range_.begin) * cols_,
                  static_cast<size_t>(cols_) * sizeof(float));
    }
    reply->version = version();
  }
  reply->data.push_back(std::move(id_blob));
  reply->data.push_back(std::move(ver_blob));
  reply->data.push_back(std::move(row_blob));
  Dashboard::Record("replica.push", static_cast<double>(ids.size()));
}

bool MatrixServerTable::Store(Stream* out) const {
  MutexLock lk(mu_);
  int64_t hdr[2] = {range_.len(), cols_};
  size_t bytes = data_.size() * sizeof(float);
  return out->Write(hdr, sizeof(hdr)) == sizeof(hdr) &&
         out->Write(data_.data(), bytes) == bytes &&
         (slot0_.empty() || out->Write(slot0_.data(), bytes) == bytes);
}

bool MatrixServerTable::Load(Stream* in) {
  MutexLock lk(mu_);
  int64_t hdr[2];
  if (in->Read(hdr, sizeof(hdr)) != sizeof(hdr) || hdr[0] != range_.len() ||
      hdr[1] != cols_)
    return false;
  size_t bytes = data_.size() * sizeof(float);
  if (in->Read(data_.data(), bytes) != bytes) return false;
  if (!slot0_.empty() && in->Read(slot0_.data(), bytes) != bytes) return false;
  int64_t row_bytes =
      cols_ * static_cast<int64_t>(sizeof(float)) *
      (slot0_.empty() ? 1 : 2);
  ResetCapacity(range_.len() * row_bytes, range_.len());
  for (int64_t r = 0; r < range_.len(); ++r)
    ChargeBucketBytes(RowBucket(range_.begin + r), row_bytes);
  return true;
}

std::vector<uint32_t> MatrixServerTable::BucketChecksums() const {
  // Per-bucket beacons on the SAME row->bucket map the version stamps
  // use: each row's CRC is seeded with its GLOBAL row id (identical
  // rows in different slots must not cancel) and XORed into its
  // bucket, so the value is independent of iteration order and of how
  // rows are distributed across replicas of the same shard.
  std::vector<uint32_t> out(kVersionBuckets, 0);
  MutexLock lk(mu_);
  for (int64_t r = 0; r < range_.len(); ++r) {
    int64_t gid = range_.begin + r;
    uint32_t seed = audit::Crc32(&gid, sizeof(gid));
    uint32_t c = audit::Crc32(data_.data() + r * cols_,
                              static_cast<size_t>(cols_) * sizeof(float),
                              seed);
    out[RowBucket(gid)] ^= c;
  }
  return out;
}

// -------------------------------------------------------------------- KV

Blob PackKeys(const std::vector<std::string>& keys) {
  size_t bytes = 0;
  for (const auto& k : keys) bytes += sizeof(uint32_t) + k.size();
  Blob out(bytes);
  char* p = out.As<char>();
  for (const auto& k : keys) {
    uint32_t n = static_cast<uint32_t>(k.size());
    std::memcpy(p, &n, sizeof(n));
    p += sizeof(n);
    std::memcpy(p, k.data(), k.size());
    p += k.size();
  }
  return out;
}

std::vector<std::string> UnpackKeys(const Blob& b) {
  std::vector<std::string> keys;
  const char* p = b.As<char>();
  size_t left = b.size();
  while (left >= sizeof(uint32_t)) {
    uint32_t n;
    std::memcpy(&n, p, sizeof(n));
    p += sizeof(n);
    left -= sizeof(n);
    if (n > left) break;  // truncated frame: stop, don't overread
    keys.emplace_back(p, n);
    p += n;
    left -= n;
  }
  return keys;
}

void KVServerTable::ProcessGet(const Message& req, Message* reply) {
  Monitor mon("KVServer::ProcessGet");
  if (req.data.empty()) return;
  auto keys = UnpackKeys(req.data[0]);
  NoteGet(-1);
  NoteStaleness(req.version);
  Blob out(keys.size() * sizeof(float));
  float* vals = out.As<float>();
  // Bucket-granular stamp: max version over the touched key buckets.
  int64_t stamp = 0;
  for (const auto& k : keys) {
    uint64_t h = KVHash(k.data(), k.size());
    stamp = std::max(stamp, bucket_version(
        static_cast<int>(h % kVersionBuckets)));
    NoteKey(h, k, static_cast<int>(h % kVersionBuckets),
            /*is_add=*/false);
  }
  reply->version = stamp;
  MutexLock lk(mu_);
  for (size_t i = 0; i < keys.size(); ++i) {
    auto it = data_.find(keys[i]);
    vals[i] = it == data_.end() ? 0.0f : it->second;
  }
  reply->data.push_back(std::move(out));
}

void KVServerTable::ProcessAdd(const Message& req) {
  Monitor mon("KVServer::ProcessAdd");
  if (req.data.size() < 3) return;
  const AddOption* opt = req.data[0].As<AddOption>();
  auto keys = UnpackKeys(req.data[1]);
  const float* deltas = req.data[2].As<float>();
  if (req.data[2].count<float>() < keys.size()) {
    Log::Error("KVServerTable: %zu keys but %zu deltas", keys.size(),
               req.data[2].count<float>());
    return;
  }
  NoteAdd(-1);
  NoteAddHealth(deltas, keys.size());
  if (workload::Armed())
    for (const auto& k : keys) {
      uint64_t h = KVHash(k.data(), k.size());
      NoteKey(h, k, static_cast<int>(h % kVersionBuckets),
              /*is_add=*/true);
    }
  bool stateful = NumSlots(updater_) > 0;
  auto bump_key = [this](const std::string& k) {
    BumpVersion(static_cast<int64_t>(KVHash(k.data(), k.size()) %
                                     kVersionBuckets));
  };
  // Capacity accounting (docs/observability.md "capacity plane"): a
  // NEW key grows the shard — one relaxed Armed() load per insert,
  // charging key + value + entry-overhead bytes to the key's bucket
  // (slot entries charge the same shape; Recompute uses one formula).
  auto note_insert = [this](const std::string& k, int64_t rows) {
    NoteEntryBytes(
        static_cast<int>(KVHash(k.data(), k.size()) % kVersionBuckets),
        static_cast<int64_t>(k.size()) +
            static_cast<int64_t>(sizeof(float)) +
            capacity::kKVEntryOverhead,
        rows);
  };
  MutexLock lk(mu_);
  if (!stateful) {
    for (size_t i = 0; i < keys.size(); ++i) {
      auto ins = data_.try_emplace(keys[i], 0.0f);
      if (ins.second) note_insert(keys[i], 1);
      ApplyUpdate(updater_, *opt, &ins.first->second, nullptr, deltas + i,
                  1);
      bump_key(keys[i]);
    }
    return;
  }
  // Pre-aggregate duplicate keys so stateful updaters see one delta per
  // key (the same contract as the matrix row path / the JAX plane).
  std::unordered_map<std::string, float> agg;
  for (size_t i = 0; i < keys.size(); ++i) agg[keys[i]] += deltas[i];
  for (auto& kv : agg) {
    auto ins = data_.try_emplace(kv.first, 0.0f);
    if (ins.second) note_insert(kv.first, 1);
    auto slot = slot0_.try_emplace(kv.first, 0.0f);
    if (slot.second) note_insert(kv.first, 0);  // slot bytes, no new entry
    ApplyUpdate(updater_, *opt, &ins.first->second, &slot.first->second,
                &kv.second, 1);
    bump_key(kv.first);
  }
}

size_t KVServerTable::size() const {
  MutexLock lk(mu_);
  return data_.size();
}

void KVServerTable::RecomputeCapacity() {
  MutexLock lk(mu_);
  RecomputeCapacityLocked();
}

void KVServerTable::RecomputeCapacityLocked() {
  // Exact walk under the shard lock — the resync entry (re-arm, Load):
  // the SAME per-entry formula the incremental insert path charges, so
  // armed counters and a ground-truth walk agree by construction.
  int64_t bytes = 0;
  std::vector<int64_t> per_bucket(kVersionBuckets, 0);
  auto walk = [&](const std::unordered_map<std::string, float>& m) {
    for (const auto& kv : m) {
      int64_t b = static_cast<int64_t>(kv.first.size()) +
                  static_cast<int64_t>(sizeof(float)) +
                  capacity::kKVEntryOverhead;
      bytes += b;
      per_bucket[KVHash(kv.first.data(), kv.first.size()) %
                 kVersionBuckets] += b;
    }
  };
  walk(data_);
  walk(slot0_);
  ResetCapacity(bytes, static_cast<int64_t>(data_.size()));
  for (int b = 0; b < kVersionBuckets; ++b)
    ChargeBucketBytes(b, per_bucket[b]);
}

std::vector<uint32_t> KVServerTable::BucketChecksums() const {
  // Order-independent by construction: unordered_map iteration order
  // is load-factor dependent, so each entry's CRC (value seeded by the
  // key's CRC) XORs into its KVHash bucket — two shards holding the
  // same pairs agree bit for bit.
  std::vector<uint32_t> out(kVersionBuckets, 0);
  MutexLock lk(mu_);
  for (const auto& kv : data_) {
    uint32_t seed = audit::Crc32(kv.first.data(), kv.first.size());
    uint32_t c = audit::Crc32(&kv.second, sizeof(float), seed);
    out[KVHash(kv.first.data(), kv.first.size()) % kVersionBuckets] ^= c;
  }
  return out;
}

bool KVServerTable::Store(Stream* out) const {
  MutexLock lk(mu_);
  int64_t n = static_cast<int64_t>(data_.size());
  int8_t has_slots = slot0_.empty() ? 0 : 1;
  if (out->Write(&n, sizeof(n)) != sizeof(n) ||
      out->Write(&has_slots, 1) != 1)
    return false;
  for (const auto& kv : data_) {
    uint32_t len = static_cast<uint32_t>(kv.first.size());
    float slot = 0.0f;
    if (has_slots) {
      auto it = slot0_.find(kv.first);
      if (it != slot0_.end()) slot = it->second;
    }
    if (out->Write(&len, sizeof(len)) != sizeof(len) ||
        out->Write(kv.first.data(), len) != len ||
        out->Write(&kv.second, sizeof(float)) != sizeof(float) ||
        (has_slots &&
         out->Write(&slot, sizeof(float)) != sizeof(float)))
      return false;
  }
  return true;
}

bool KVServerTable::Load(Stream* in) {
  MutexLock lk(mu_);
  int64_t n = 0;
  int8_t has_slots = 0;
  if (in->Read(&n, sizeof(n)) != sizeof(n) ||
      in->Read(&has_slots, 1) != 1 || n < 0)
    return false;
  data_.clear();
  slot0_.clear();
  for (int64_t i = 0; i < n; ++i) {
    uint32_t len = 0;
    if (in->Read(&len, sizeof(len)) != sizeof(len)) return false;
    std::string key(len, '\0');
    float val = 0.0f, slot = 0.0f;
    if (in->Read(&key[0], len) != len ||
        in->Read(&val, sizeof(float)) != sizeof(float) ||
        (has_slots && in->Read(&slot, sizeof(float)) != sizeof(float)))
      return false;
    data_[key] = val;
    if (has_slots) slot0_[key] = slot;
  }
  RecomputeCapacityLocked();
  return true;
}

// ---------------------------------------------------------------- worker

// Per-thread busy latch: RoundTrip/Wait run on the CALLER's thread, so
// this distinguishes "server shed it (retryable, rc -6)" from "dead
// shard / deadline (indeterminate, rc -3)" without widening the bool
// return every table op and binding already speaks.
namespace {
thread_local bool g_rt_busy = false;

// Delivery audit (docs/observability.md "audit plane"): while FlushAdds
// ships a collapsed aggregation window, every message it creates covers
// this many logical adds — the seq RANGE the wire stamp carries, so the
// auditor can account each absorbed add through the one message that
// carried it.  Thread-local because the flush runs on the caller's
// thread and a concurrent plain add on another thread must keep span 1.
thread_local int64_t g_audit_flush_span = 0;

// Active host-bridge borrow window (docs/host_bridge.md) — thread-local
// because the *Borrowed C API runs table ops on the caller's thread and
// the window must never leak into unrelated ops on other threads.
struct BorrowWindow {
  const char* base = nullptr;
  size_t len = 0;
  std::shared_ptr<void> hold;
};
thread_local BorrowWindow g_borrow;
}  // namespace

bool WorkerTable::last_call_busy() { return g_rt_busy; }

BorrowScope::BorrowScope(const void* base, size_t len,
                         std::shared_ptr<void> hold) {
  g_borrow.base = static_cast<const char*>(base);
  g_borrow.len = len;
  g_borrow.hold = std::move(hold);
}

BorrowScope::~BorrowScope() {
  // Blobs minted inside the scope keep their own keepalive copies; only
  // the thread-local template dies here.
  g_borrow = BorrowWindow{};
}

Blob WrapPayload(const void* p, size_t bytes) {
  const char* cp = static_cast<const char*>(p);
  if (g_borrow.base != nullptr && cp >= g_borrow.base &&
      cp + bytes <= g_borrow.base + g_borrow.len) {
    return Blob::Borrow(p, bytes, g_borrow.hold);
  }
  return Blob(p, bytes);
}

namespace {
// True when the active borrow scope covers [p, p+bytes) — the gate the
// multi-shard borrowed AddRows uses to pick run-iovec shipping over
// per-rank staging (docs/embedding.md).
bool BorrowCovers(const void* p, size_t bytes) {
  const char* cp = static_cast<const char*>(p);
  return g_borrow.base != nullptr && cp >= g_borrow.base &&
         cp + bytes <= g_borrow.base + g_borrow.len;
}
}  // namespace

// ---- delivery audit (docs/observability.md "audit plane") ------------

void WorkerTable::StampAuditAdd(Message* req, int shard) {
  if (!audit::Armed()) return;
  int64_t span = g_audit_flush_span > 0 ? g_audit_flush_span : 1;
  int64_t lo = 0, hi = 0;
  ack_ledger_.NextRange(shard, span, &lo, &hi);
  req->flags |= msgflag::kHasAudit;
  req->audit.seq_lo = lo;
  req->audit.seq_hi = hi;
}

// ---- wire codec + add aggregation (docs/wire_compression.md) ---------

void WorkerTable::AppendEncodedDelta(Message* req, const float* delta,
                                     int64_t n, int64_t elem_offset,
                                     int64_t table_elems) {
  Codec c = wire_codec();
  size_t raw_bytes = static_cast<size_t>(n) * sizeof(float);
  if (c == Codec::kOneBit) {
    float* res;
    Blob enc;
    {
      MutexLock lk(residual_mu_);
      if (residual_.size() < static_cast<size_t>(table_elems))
        residual_.resize(static_cast<size_t>(table_elems), 0.0f);
      res = residual_.data() + elem_offset;
      enc = codec::EncodeOneBit(delta, static_cast<size_t>(n), res);
    }
    req->codec = Codec::kOneBit;
    req->data.push_back(std::move(enc));
  } else if (c == Codec::kSparse) {
    Blob enc = codec::EncodeSparse(delta, static_cast<size_t>(n));
    if (enc.size() == 0) {  // denser than the sparse form: ship raw
      req->data.push_back(WrapPayload(delta, raw_bytes));
    } else {
      req->codec = Codec::kSparse;
      req->data.push_back(std::move(enc));
    }
  } else {
    // Raw payloads borrow the caller's bytes when a host-bridge borrow
    // scope covers them (docs/host_bridge.md) — no copy into the blob.
    req->data.push_back(WrapPayload(delta, raw_bytes));
    return;  // raw tables keep the encode path at zero cost — no ratio
  }
  // Per-table compression ledger: mean of (encoded / raw payload bytes)
  // — `codec.ratio.t<id>` count = encoded messages, total/count = mean.
  if (raw_bytes > 0)
    Dashboard::Record("codec.ratio.t" + std::to_string(table_id_),
                      static_cast<double>(req->data.back().size()) /
                          static_cast<double>(raw_bytes));
}

bool WorkerTable::MaybeAggregate(const float* delta, int64_t n,
                                 const AddOption& opt) {
  int64_t agg_ms = TableFlagOr("add_agg_ms", 0);
  int64_t agg_bytes = TableFlagOr("add_agg_bytes", 0);
  if (agg_ms <= 0 && agg_bytes <= 0) return false;
  bool flush_incompatible = false;
  bool flush_now = false;
  {
    MutexLock lk(agg_mu_);
    if (agg_count_ > 0 &&
        (static_cast<int64_t>(agg_sum_.size()) != n ||
         std::memcmp(&agg_opt_, &opt, sizeof(opt)) != 0))
      flush_incompatible = true;
    else {
      if (agg_count_ == 0) {
        agg_sum_.assign(static_cast<size_t>(n), 0.0f);
        agg_opt_ = opt;
        agg_first_ms_ = SteadyNowMs();
      }
      for (int64_t i = 0; i < n; ++i) agg_sum_[i] += delta[i];
      ++agg_count_;
      Dashboard::Record("agg.adds", 0.0);
      // Bounds: absorbed payload bytes (count × delta size — the wire
      // traffic this window is collapsing) and the lazy time window.
      if (agg_bytes > 0 && agg_count_ * n * 4 >= agg_bytes)
        flush_now = true;
      if (agg_ms > 0 && SteadyNowMs() - agg_first_ms_ >= agg_ms)
        flush_now = true;
    }
  }
  if (flush_incompatible) {
    // Different shape/option: FIFO order demands the buffered aggregate
    // ships first; the new add then starts a fresh window.
    FlushAdds();
    return MaybeAggregate(delta, n, opt);
  }
  if (flush_now) FlushAdds();
  return true;
}

void WorkerTable::FlushAdds() {
  std::vector<float> sum;
  AddOption opt;
  int64_t adds;
  {
    MutexLock lk(agg_mu_);
    if (agg_count_ == 0) return;
    sum.swap(agg_sum_);
    opt = agg_opt_;
    adds = agg_count_;
    agg_count_ = 0;
  }
  // count = flush windows, total = adds collapsed: total/count is the
  // adds-per-wire-message ratio the bench/demo report.
  Dashboard::Record("agg.flush", static_cast<double>(adds));
  // Audit accounting: every message this flush creates covers the whole
  // collapsed window's seq range (docs/observability.md "audit plane").
  g_audit_flush_span = adds;
  SendAggregate(sum.data(), static_cast<int64_t>(sum.size()), opt);
  g_audit_flush_span = 0;
}

void WorkerTable::Notify(int64_t msg_id, const Message& reply) {
  // Latency attribution: fold the reply's timing trail into the
  // per-stage histograms + the peer clock-offset estimator BEFORE the
  // pending lookup — an expired round trip's reply still carries a
  // complete (and perfectly valid) stage breakdown.  The reply's trace
  // id is adopted for the scope so the stage buckets capture it as
  // their EXEMPLAR (PR 7): a p99 stage links straight into the merged
  // Chrome trace that explains it.
  {
    int64_t prev_tid = Dashboard::ThreadTraceId();
    bool adopt = reply.trace_id != 0 && Dashboard::TraceEnabled();
    if (adopt) Dashboard::SetThreadTraceId(reply.trace_id);
    latency::OnReply(reply, reply.src);
    if (adopt) Dashboard::SetThreadTraceId(prev_tid);
  }
  // Delivery audit: a ReplyAdd echoing its request's stamp advances
  // the acked watermark for that shard's stream — recorded BEFORE the
  // pending lookup, because an ack landing after the round trip's
  // deadline still proves the server applied those seqs (the very
  // distinction between "never acked" and "lost" the auditor draws).
  if (reply.type == MsgType::ReplyAdd && reply.has_audit() &&
      audit::Armed()) {
    // Shard hint first (docs/replication.md): a promoted rank acks for
    // a shard its src rank never owned at registration time.
    int shard = reply.shard >= 0 ? reply.shard
                                 : Zoo::Get()->server_index(reply.src);
    if (shard >= 0) ack_ledger_.Ack(shard, reply.audit.seq_hi);
  }
  // Serve layer: every reply's version stamp refreshes the free local
  // lower bound on the server version (max-merge; replies can race).
  if (reply.version > 0) {
    int64_t cur = last_version_.load(std::memory_order_relaxed);
    while (cur < reply.version &&
           !last_version_.compare_exchange_weak(cur, reply.version)) {
    }
  }
  // Everything — lookup, consume, waiter notify — runs under mu_ so it
  // serializes with RoundTrip's timeout path: once the timeout erases
  // the entry, a late reply finds nothing and cannot touch the (gone)
  // stack waiter or the caller's output buffers.
  MutexLock lk(mu_);
  auto it = pending_.find(msg_id);
  if (it == pending_.end()) {
    Log::Error("WorkerTable %d: reply for unknown/expired msg %lld",
               table_id_, static_cast<long long>(msg_id));
    return;
  }
  Pending& p = it->second;
  if (reply.type == MsgType::ReplyError) {
    *p.failed = true;                   // shard unreachable — no payload
  } else if (reply.type == MsgType::ReplyBusy) {
    *p.failed = true;                   // shed — retryable, no payload
    if (p.busy) *p.busy = true;
  } else if (p.consume) {
    p.consume(p.arg, reply);
  }
  std::shared_ptr<Waiter> waiter = p.waiter;  // keep alive across erase
  if (--p.remaining == 0) pending_.erase(it);
  waiter->Notify();
}

bool WorkerTable::RoundTrip(std::vector<MessagePtr> reqs,
                            void (*consume)(void*, const Message&),
                            void* arg) {
  g_rt_busy = false;
  if (reqs.empty()) return true;
  auto waiter = std::make_shared<Waiter>(static_cast<int>(reqs.size()));
  bool failed = false;
  bool busy = false;
  int64_t msg_id = reqs[0]->msg_id;
  {
    MutexLock lk(mu_);
    pending_[msg_id] = Pending{waiter, consume, arg,
                               static_cast<int>(reqs.size()), &failed,
                               &busy};
  }
  for (auto& req : reqs)
    Zoo::Get()->SendTo(actor::kWorker, std::move(req));
  int64_t timeout_ms = configure::GetInt("rpc_timeout_ms");
  if (waiter->WaitFor(timeout_ms)) {
    MutexLock lk(mu_);
    g_rt_busy = busy;
    return !failed;
  }
  // Deadline passed: withdraw the pending entry so late replies are
  // dropped at the door instead of touching dead stack frames.
  //
  // CONTRACT: a timed-out result (rc -3 at the C API) is INDETERMINATE,
  // not at-most-once.  The server may still apply an Add whose ack was
  // merely slow — a caller that blindly retries can double-apply the
  // delta — and a timed-out Get leaves the caller's buffer partially
  // filled (some shards landed, some did not).  Callers must treat -3
  // as "state unknown": re-Get before deciding to re-Add.  (Documented
  // at MV_* in c_api.h as well.)
  MutexLock lk(mu_);
  auto it = pending_.find(msg_id);
  if (it == pending_.end()) {           // raced: replies completed
    g_rt_busy = busy;
    return !failed;
  }
  pending_.erase(it);
  Log::Error("WorkerTable %d: request %lld timed out after %lld ms",
             table_id_, static_cast<long long>(msg_id),
             static_cast<long long>(timeout_ms));
  return false;
}

AsyncGetPtr WorkerTable::StartRoundTrip(std::vector<MessagePtr> reqs,
                                        void (*consume)(void*,
                                                        const Message&),
                                        void* arg,
                                        std::shared_ptr<void> state) {
  int64_t msg_id = reqs.empty() ? -1 : reqs[0]->msg_id;
  AsyncGetPtr h(new AsyncGetHandle(this, msg_id,
                                   static_cast<int>(reqs.size()),
                                   std::move(state)));
  if (reqs.empty()) return h;
  {
    MutexLock lk(mu_);
    pending_[msg_id] = Pending{h->waiter_, consume, arg,
                               static_cast<int>(reqs.size()), &h->failed_,
                               &h->busy_};
  }
  for (auto& req : reqs)
    Zoo::Get()->SendTo(actor::kWorker, std::move(req));
  return h;
}

bool AsyncGetHandle::Wait() {
  if (waited_) return ok_;
  waited_ = true;
  g_rt_busy = false;
  if (msg_id_ < 0) {      // empty request: nothing was on the wire
    ok_ = true;
    return ok_;
  }
  // Identical deadline + withdrawal discipline as the blocking
  // RoundTrip, including the INDETERMINATE -3 contract on timeout.
  int64_t timeout_ms = configure::GetInt("rpc_timeout_ms");
  if (waiter_->WaitFor(timeout_ms)) {
    MutexLock lk(table_->mu_);
    g_rt_busy = busy_;
    ok_ = !failed_;
    return ok_;
  }
  MutexLock lk(table_->mu_);
  auto it = table_->pending_.find(msg_id_);
  if (it == table_->pending_.end()) {  // raced: replies completed
    g_rt_busy = busy_;
    ok_ = !failed_;
    return ok_;
  }
  table_->pending_.erase(it);
  Log::Error("WorkerTable %d: async get %lld timed out after %lld ms",
             table_->table_id_, static_cast<long long>(msg_id_),
             static_cast<long long>(timeout_ms));
  ok_ = false;
  return false;
}

AsyncGetHandle::~AsyncGetHandle() {
  if (waited_ || msg_id_ < 0) return;
  // Un-awaited handle: withdraw the pending entry so late replies are
  // dropped at the door instead of touching the dying waiter or the
  // caller's (possibly gone) output buffer.  Notify holds the same
  // lock for its whole lookup-consume-notify sequence, so after this
  // erase no reply can be mid-flight into our state.
  MutexLock lk(table_->mu_);
  table_->pending_.erase(msg_id_);
}

namespace {

MessagePtr MakeReq(MsgType type, int32_t table_id, int64_t msg_id,
                   int shard_idx,
                   int32_t accept_flags = msgflag::kAcceptRaw) {
  // Requests address SHARD indices; the wire needs the owning global
  // rank (they differ when worker-only/server-only roles exist).
  auto req = std::make_unique<Message>();
  req->type = type;
  req->table_id = table_id;
  req->msg_id = msg_id;
  // Reply-codec negotiation: the server may sparse-encode its reply
  // payload only when this request advertises kAcceptSparse.
  req->flags = accept_flags;
  // Span propagation: the enclosing op's Monitor set the thread trace id
  // (0 when tracing is off), and the server actor adopts it before the
  // apply — worker op and server apply share one id across ranks.
  req->trace_id = Dashboard::ThreadTraceId();
  req->src = Zoo::Get()->rank();
  // Routed through the VERSIONED shard map (docs/replication.md): a
  // promotion or join re-points the shard, so a retry minted after the
  // epoch flip lands on the live owner.  The shard hint rides the wire
  // because the owning rank no longer names the shard uniquely — a
  // promoted rank serves two — and replies echo it for reassembly.
  req->shard = shard_idx;
  req->dst = Zoo::Get()->server_rank(shard_idx);
  // Latency trail (docs/observability.md): the enqueue stamp opens the
  // client queue stage; the reply's trail closes the whole breakdown.
  latency::StampEnqueue(req.get());
  // Tail plane (docs/serving.md "tail"): tenant class + remaining
  // deadline budget ride the same header so the server can drop a
  // request whose caller already gave up.
  qos::StampRequest(req.get());
  return req;
}

// Assemble contiguous-shard replies into the caller's buffer: the reply's
// src rank names the shard, ShardOf names its offsets.
struct GatherDest {
  float* dst;
  size_t cap;        // caller buffer length (floats)
  int64_t global;    // partitioned length (array elems or matrix rows)
  int servers;
  int64_t stride;    // floats per partitioned element (1 or cols)
};

// Reassembly key for a reply: its echoed shard hint when present (a
// post-failover rank serves two shards, so src alone is ambiguous),
// falling back to the registration-time src→shard map for replies
// from pre-hint peers.
int ReplyShard(const Message& reply) {
  return reply.shard >= 0 ? reply.shard
                          : Zoo::Get()->server_index(reply.src);
}

void GatherReply(void* arg, const Message& reply) {
  auto* d = static_cast<GatherDest*>(arg);
  if (reply.data.empty()) return;
  int shard = ReplyShard(reply);
  if (shard < 0) return;  // reply from a rank that owns no shard
  ShardRange rg = ShardOf(d->global, shard, d->servers);
  size_t off = static_cast<size_t>(rg.begin * d->stride);
  size_t n = reply.data[0].count<float>();
  if (off >= d->cap) return;
  n = std::min(n, d->cap - off);
  std::memcpy(d->dst + off, reply.data[0].As<float>(), n * sizeof(float));
}

// Scatter row-subset replies: positions[src] lists, per contacted rank,
// the caller-order slots its rows fill (in request order).
struct RowsDest {
  float* dst;
  int64_t cols;
  const std::vector<std::vector<int64_t>>* positions;
};

void ScatterRowsReply(void* arg, const Message& reply) {
  auto* d = static_cast<RowsDest*>(arg);
  if (reply.data.empty()) return;
  int shard = ReplyShard(reply);
  if (shard < 0) return;
  const auto& pos = (*d->positions)[static_cast<size_t>(shard)];
  const float* src = reply.data[0].As<float>();
  size_t have = reply.data[0].count<float>() / d->cols;
  for (size_t i = 0; i < pos.size() && i < have; ++i) {
    std::memcpy(d->dst + pos[i] * d->cols, src + i * d->cols,
                d->cols * sizeof(float));
  }
}

void DiscardReply(void*, const Message&) {}

// QueryVersion's consume: max-merge every shard's reply stamp.
void MaxVersionReply(void* arg, const Message& reply) {
  auto* out = static_cast<int64_t*>(arg);
  if (reply.version > *out) *out = reply.version;
}

}  // namespace

bool WorkerTable::QueryVersion(int64_t* version, int bucket) {
  Monitor mon("Worker::QueryVersion");
  FlushAdds();  // the probed version must cover our buffered adds
  *version = 0;
  int64_t msg_id = Zoo::Get()->NextMsgId();
  int servers = Zoo::Get()->num_servers();
  std::vector<MessagePtr> reqs;
  for (int r = 0; r < servers; ++r) {
    auto req = MakeReq(MsgType::RequestVersion, table_id_, msg_id, r);
    req->version = bucket;  // -1 = whole table (see message.h)
    reqs.push_back(std::move(req));
  }
  return RoundTrip(std::move(reqs), MaxVersionReply, version);
}

bool ArrayWorkerTable::Get(float* data, int64_t size) {
  Monitor mon("ArrayWorker::Get");
  FlushAdds();  // read-your-aggregated-writes: flush rides ahead (FIFO)
  int64_t msg_id = Zoo::Get()->NextMsgId();
  std::vector<MessagePtr> reqs;
  for (int r = 0; r < servers_; ++r) {
    auto req = MakeReq(MsgType::RequestGet, table_id_, msg_id, r,
                       accept_flags());
    req->version = last_version();  // observed-staleness stamp
    reqs.push_back(std::move(req));
  }
  GatherDest d{data, static_cast<size_t>(size), global_, servers_, 1};
  return RoundTrip(std::move(reqs), GatherReply, &d);
}

AsyncGetPtr ArrayWorkerTable::GetAsync(float* data, int64_t size) {
  Monitor mon("ArrayWorker::GetAsync");
  FlushAdds();
  int64_t msg_id = Zoo::Get()->NextMsgId();
  std::vector<MessagePtr> reqs;
  for (int r = 0; r < servers_; ++r) {
    auto req = MakeReq(MsgType::RequestGet, table_id_, msg_id, r,
                       accept_flags());
    req->version = last_version();  // observed-staleness stamp
    reqs.push_back(std::move(req));
  }
  auto d = std::make_shared<GatherDest>();
  *d = GatherDest{data, static_cast<size_t>(size), global_, servers_, 1};
  GatherDest* raw = d.get();
  return StartRoundTrip(std::move(reqs), GatherReply, raw, std::move(d));
}

bool ArrayWorkerTable::SendAdd(const float* delta, int64_t size,
                               const AddOption& opt, bool blocking) {
  int64_t msg_id = blocking ? Zoo::Get()->NextMsgId() : -1;
  std::vector<MessagePtr> reqs;
  for (int r = 0; r < servers_; ++r) {
    ShardRange rg = ShardOf(global_, r, servers_);
    if (rg.begin >= size) continue;
    auto req = MakeReq(MsgType::RequestAdd, table_id_, msg_id, r);
    StampAuditAdd(req.get(), r);
    req->data.emplace_back(&opt, sizeof(opt));
    AppendEncodedDelta(req.get(), delta + rg.begin,
                       std::min(rg.len(), size - rg.begin), rg.begin,
                       global_);
    reqs.push_back(std::move(req));
  }
  if (blocking)
    return RoundTrip(std::move(reqs), DiscardReply, nullptr);
  for (auto& req : reqs)
    Zoo::Get()->SendTo(actor::kWorker, std::move(req));
  return true;
}

void ArrayWorkerTable::SendAggregate(const float* sum, int64_t n,
                                     const AddOption& opt) {
  SendAdd(sum, n, opt, /*blocking=*/false);
}

bool ArrayWorkerTable::Add(const float* delta, int64_t size,
                           const AddOption& opt, bool blocking) {
  Monitor mon("ArrayWorker::Add");
  if (blocking) {
    // The ack must cover everything this caller pushed — earlier
    // aggregated adds included (FIFO keeps them ahead on the wire).
    FlushAdds();
  } else if (size == global_ && MaybeAggregate(delta, size, opt)) {
    return true;  // absorbed; ships with the next flush window
  }
  return SendAdd(delta, size, opt, blocking);
}

bool MatrixWorkerTable::GetAll(float* data) {
  Monitor mon("MatrixWorker::GetAll");
  FlushAdds();
  int64_t msg_id = Zoo::Get()->NextMsgId();
  std::vector<MessagePtr> reqs;
  for (int r = 0; r < servers_; ++r) {
    auto req = MakeReq(MsgType::RequestGet, table_id_, msg_id, r,
                       accept_flags());
    req->version = last_version();  // observed-staleness stamp
    reqs.push_back(std::move(req));
  }
  GatherDest d{data, static_cast<size_t>(rows_ * cols_), rows_, servers_,
               cols_};
  return RoundTrip(std::move(reqs), GatherReply, &d);
}

std::vector<MessagePtr> MatrixWorkerTable::PlanRowsGet(
    const int32_t* row_ids, int64_t k, float* data,
    std::vector<std::vector<int64_t>>* positions) {
  // Partition ids by owner; remember which caller slots each owner fills.
  positions->assign(static_cast<size_t>(servers_), {});
  std::vector<std::vector<int32_t>> per_rank_ids(servers_);
  for (int64_t i = 0; i < k; ++i) {
    int owner = (row_ids[i] >= 0 && row_ids[i] < rows_)
                    ? OwnerOf(row_ids[i], rows_, servers_)
                    : 0;  // out-of-range: any shard answers zeros
    per_rank_ids[owner].push_back(row_ids[i]);
    (*positions)[owner].push_back(i);
  }
  std::memset(data, 0, static_cast<size_t>(k * cols_) * sizeof(float));
  FlushAdds();  // planned reads must see our buffered adds (FIFO)
  int64_t msg_id = Zoo::Get()->NextMsgId();
  std::vector<MessagePtr> reqs;
  for (int r = 0; r < servers_; ++r) {
    if (per_rank_ids[r].empty()) continue;
    auto req = MakeReq(MsgType::RequestGet, table_id_, msg_id, r,
                       accept_flags());
    req->version = last_version();  // observed-staleness stamp
    req->data.emplace_back(per_rank_ids[r].data(),
                           per_rank_ids[r].size() * sizeof(int32_t));
    reqs.push_back(std::move(req));
  }
  return reqs;
}

bool MatrixWorkerTable::FetchRowsWire(const int32_t* row_ids, int64_t k,
                                      float* data) {
  std::vector<std::vector<int64_t>> positions;
  auto reqs = PlanRowsGet(row_ids, k, data, &positions);
  RowsDest d{data, cols_, &positions};
  return RoundTrip(std::move(reqs), ScatterRowsReply, &d);
}

bool MatrixWorkerTable::GetRows(const int32_t* row_ids, int64_t k,
                                float* data) {
  Monitor mon("MatrixWorker::GetRows");
  if (!workload::ReplicaArmed() || k <= 0)
    return FetchRowsWire(row_ids, k, data);
  // Hot-key read replica (docs/embedding.md): serve what the servers'
  // pushed top-K covers, wire-fetch only the remainder.  FIFO parity
  // with the wire path: buffered aggregates flush first, so a replica
  // hit is never *less* fresh than the wire read it replaces.
  FlushAdds();
  MaybeRefreshReplica();
  std::vector<int32_t> rem;
  std::vector<int64_t> rem_slot;
  // Version gating IS the invalidation: our own add acks (and every
  // reply stamp) advance last_version, so at -replica_max_staleness=0
  // any entry older than the last observed apply misses.
  int64_t min_v = last_version() - TableFlagOr("replica_max_staleness", 0);
  {
    int64_t lease = TableFlagOr("replica_lease_ms", 50);
    MutexLock lk(replica_mu_);
    bool fresh = replica_ts_ms_ >= 0 &&
                 SteadyNowMs() - replica_ts_ms_ <= lease;
    for (int64_t i = 0; i < k; ++i) {
      if (fresh) {
        auto it = replica_.find(row_ids[i]);
        if (it != replica_.end() && it->second.version >= min_v) {
          std::memcpy(data + i * cols_, it->second.data.data(),
                      static_cast<size_t>(cols_) * sizeof(float));
          replica_hits_.fetch_add(1, std::memory_order_relaxed);
          continue;
        }
      }
      rem.push_back(row_ids[i]);
      rem_slot.push_back(i);
    }
  }
  replica_misses_.fetch_add(static_cast<long long>(rem.size()),
                            std::memory_order_relaxed);
  if (rem.empty()) {
    Dashboard::Record("replica.serve", 0.0);  // zero-wire row get
    return true;
  }
  if (rem.size() == static_cast<size_t>(k))
    return FetchRowsWire(row_ids, k, data);
  std::vector<float> buf(rem.size() * static_cast<size_t>(cols_));
  if (!FetchRowsWire(rem.data(), static_cast<int64_t>(rem.size()),
                     buf.data()))
    return false;
  for (size_t j = 0; j < rem.size(); ++j)
    std::memcpy(data + rem_slot[j] * cols_,
                buf.data() + j * cols_,
                static_cast<size_t>(cols_) * sizeof(float));
  return true;
}

namespace {
// RefreshReplica's consume trampoline (runs under WorkerTable::mu_ on
// the worker actor thread; OnReplicaPush takes replica_mu_ after it —
// the one fixed order those two locks are ever taken in).
void ConsumeReplica(void* arg, const Message& reply) {
  static_cast<MatrixWorkerTable*>(arg)->OnReplicaPush(reply);
}
}  // namespace

void MatrixWorkerTable::MaybeRefreshReplica() {
  int64_t lease = TableFlagOr("replica_lease_ms", 50);
  {
    MutexLock lk(replica_mu_);
    if (replica_ts_ms_ >= 0 && SteadyNowMs() - replica_ts_ms_ <= lease)
      return;
    // Stamp the ATTEMPT, not the success: a shedding/dead shard must
    // not turn every GetRows into a failed refresh round trip — the
    // lease paces attempts either way.
    replica_ts_ms_ = SteadyNowMs();
  }
  RefreshReplica();
}

bool MatrixWorkerTable::RefreshReplica() {
  Monitor mon("MatrixWorker::RefreshReplica");
  replica_refreshes_.fetch_add(1, std::memory_order_relaxed);
  int64_t msg_id = Zoo::Get()->NextMsgId();
  std::vector<MessagePtr> reqs;
  for (int r = 0; r < servers_; ++r) {
    auto req = MakeReq(MsgType::RequestReplica, table_id_, msg_id, r);
    req->version = last_version();  // observed-staleness stamp
    reqs.push_back(std::move(req));
  }
  return RoundTrip(std::move(reqs), ConsumeReplica, this);
}

void MatrixWorkerTable::OnReplicaPush(const Message& reply) {
  if (reply.data.size() < 3) return;
  const int32_t* ids = reply.data[0].As<int32_t>();
  size_t k = reply.data[0].count<int32_t>();
  const int64_t* vers = reply.data[1].As<int64_t>();
  const float* rows = reply.data[2].As<float>();
  if (reply.data[1].count<int64_t>() < k ||
      reply.data[2].count<float>() < k * static_cast<size_t>(cols_))
    return;  // malformed push: drop, never install torn rows
  // Bound the historical hot set: the map holds at most a few pushes'
  // worth of rows (per-shard top-K); a workload whose head drifts
  // re-fills from scratch instead of growing without bound (MV007's
  // discipline, native edition).
  int64_t topk = TableFlagOr("hotkey_topk", 16);
  size_t cap = static_cast<size_t>(4 * std::max<int64_t>(topk, 1) *
                                   std::max(servers_, 1));
  MutexLock lk(replica_mu_);
  if (replica_.size() > cap) replica_.clear();
  for (size_t i = 0; i < k; ++i) {
    ReplicaRow& r = replica_[ids[i]];
    // Install at the SNAPSHOT's table version (reply.version), not the
    // row's bucket version: the push copied data and version under one
    // server lock, so every pushed row is current AS OF that version —
    // gating on the (older) bucket stamp would mark a row stale the
    // moment any OTHER row was ever added after it, starving the
    // replica at staleness 0.  The per-row bucket stamps still ride
    // the wire (blob 1) for clients that track per-bucket knowledge.
    int64_t v = std::max(reply.version, vers[i]);
    if (r.version > v) continue;  // never roll a fresher entry back
    r.version = v;
    r.data.assign(rows + i * cols_, rows + (i + 1) * cols_);
  }
  replica_ts_ms_ = SteadyNowMs();
}

int64_t MatrixWorkerTable::replica_bytes() const {
  MutexLock lk(replica_mu_);
  // rows x (cols floats + id/version/map-node overhead): the same
  // entry-overhead constant the KV books use, so fleet capacity math
  // speaks one unit.
  return static_cast<int64_t>(replica_.size()) *
         (cols_ * static_cast<int64_t>(sizeof(float)) +
          capacity::kKVEntryOverhead);
}

MatrixWorkerTable::ReplicaStats MatrixWorkerTable::replica_stats() const {
  ReplicaStats s;
  s.hits = replica_hits_.load(std::memory_order_relaxed);
  s.misses = replica_misses_.load(std::memory_order_relaxed);
  s.refreshes = replica_refreshes_.load(std::memory_order_relaxed);
  MutexLock lk(replica_mu_);
  s.rows = static_cast<long long>(replica_.size());
  return s;
}

void MatrixWorkerTable::InvalidateReplicaRows(const int32_t* row_ids,
                                              int64_t k) {
  MutexLock lk(replica_mu_);
  if (replica_.empty()) return;
  if (k < 0) {  // whole-table add: every replicated row changed
    replica_.clear();
    return;
  }
  for (int64_t i = 0; i < k; ++i) replica_.erase(row_ids[i]);
}

void MatrixWorkerTable::OnClockInvalidate() {
  // Clock closed: peers' adds are applied server-side — every pushed
  // row may be stale regardless of its version stamp's lease.
  MutexLock lk(replica_mu_);
  replica_.clear();
  replica_ts_ms_ = -1;
}

namespace {
// The async GetRows' scatter plan must outlive the starting call (the
// blocking path keeps it on the stack); the handle owns one of these.
struct RowsGetState {
  RowsDest d;
  std::vector<std::vector<int64_t>> positions;
};
}  // namespace

AsyncGetPtr MatrixWorkerTable::GetRowsAsync(const int32_t* row_ids,
                                            int64_t k, float* data) {
  Monitor mon("MatrixWorker::GetRowsAsync");
  auto state = std::make_shared<RowsGetState>();
  auto reqs = PlanRowsGet(row_ids, k, data, &state->positions);
  state->d = RowsDest{data, cols_, &state->positions};
  RowsGetState* raw = state.get();
  return StartRoundTrip(std::move(reqs), ScatterRowsReply, &raw->d,
                        std::move(state));
}

bool MatrixWorkerTable::SendAddAll(const float* delta, const AddOption& opt,
                                   bool blocking) {
  InvalidateReplicaRows(nullptr, -1);  // whole-table add: replica void
  int64_t msg_id = blocking ? Zoo::Get()->NextMsgId() : -1;
  std::vector<MessagePtr> reqs;
  for (int r = 0; r < servers_; ++r) {
    ShardRange rg = ShardOf(rows_, r, servers_);
    if (rg.len() == 0) continue;
    auto req = MakeReq(MsgType::RequestAdd, table_id_, msg_id, r);
    StampAuditAdd(req.get(), r);
    req->data.emplace_back(&opt, sizeof(opt));
    AppendEncodedDelta(req.get(), delta + rg.begin * cols_,
                       rg.len() * cols_, rg.begin * cols_, rows_ * cols_);
    reqs.push_back(std::move(req));
  }
  if (blocking)
    return RoundTrip(std::move(reqs), DiscardReply, nullptr);
  for (auto& req : reqs)
    Zoo::Get()->SendTo(actor::kWorker, std::move(req));
  return true;
}

void MatrixWorkerTable::SendAggregate(const float* sum, int64_t n,
                                      const AddOption& opt) {
  if (n != rows_ * cols_) return;  // only whole-table adds aggregate
  SendAddAll(sum, opt, /*blocking=*/false);
}

bool MatrixWorkerTable::AddAll(const float* delta, const AddOption& opt,
                               bool blocking) {
  Monitor mon("MatrixWorker::AddAll");
  if (blocking)
    FlushAdds();  // the ack must cover buffered aggregates too
  else if (MaybeAggregate(delta, rows_ * cols_, opt)) {
    InvalidateReplicaRows(nullptr, -1);  // whole table changed
    return true;
  }
  return SendAddAll(delta, opt, blocking);
}

bool MatrixWorkerTable::AddRows(const int32_t* row_ids, int64_t k,
                                const float* delta, const AddOption& opt,
                                bool blocking) {
  Monitor mon("MatrixWorker::AddRows");
  // FIFO with any buffered whole-table aggregate: it ships first so the
  // server applies adds in submission order.
  FlushAdds();
  bool ok = SendAddRows(row_ids, k, delta, opt, blocking);
  // Replica invalidation is belt to the version gate's braces: the ack
  // that would stale the touched entries may still be in flight when a
  // concurrent read consults the replica.
  InvalidateReplicaRows(row_ids, k);
  return ok;
}

bool MatrixWorkerTable::SendAddRows(const int32_t* row_ids, int64_t k,
                                    const float* delta,
                                    const AddOption& opt, bool blocking) {
  // Single-shard fast path (the offload bridge's embedding case,
  // docs/host_bridge.md): with one server and only in-range ids there
  // is nothing to partition — ship the id list once and let the packed
  // delta borrow the caller's bytes (WrapPayload) instead of staging
  // per-rank copies.  The sparse codec keeps the staging path: its
  // encode owns a fresh blob anyway.
  if (servers_ == 1 && k > 0 && wire_codec() != Codec::kSparse) {
    bool all_valid = true;
    for (int64_t i = 0; i < k; ++i)
      if (row_ids[i] < 0 || row_ids[i] >= rows_) {
        all_valid = false;
        break;
      }
    if (all_valid) {
      int64_t msg_id = blocking ? Zoo::Get()->NextMsgId() : -1;
      auto req = MakeReq(MsgType::RequestAdd, table_id_, msg_id, 0);
      StampAuditAdd(req.get(), 0);
      req->data.emplace_back(&opt, sizeof(opt));
      req->data.emplace_back(row_ids, static_cast<size_t>(k) *
                                          sizeof(int32_t));
      req->data.push_back(WrapPayload(
          delta, static_cast<size_t>(k * cols_) * sizeof(float)));
      std::vector<MessagePtr> reqs;
      reqs.push_back(std::move(req));
      if (blocking)
        return RoundTrip(std::move(reqs), DiscardReply, nullptr);
      for (auto& r : reqs)
        Zoo::Get()->SendTo(actor::kWorker, std::move(r));
      return true;
    }
  }
  // Multi-shard borrowed fast path (docs/embedding.md — the gap PR 9's
  // single-shard path left open): when the packed delta sits inside
  // the active host-bridge borrow window (an arena buffer), every
  // shard's rows ship as borrowed iovecs straight out of that ONE
  // buffer — contiguous caller-order runs owned by the same shard
  // collapse into one Blob::Borrow each, and the server re-walks rows
  // across the blob sequence (RowBlobCursor).  No per-rank staging
  // copies, no send-side Blob copy.  The sparse codec keeps staging
  // (its encode owns a fresh blob anyway); a pathological interleaving
  // whose run count would blow the sendmsg iovec budget falls back.
  if (servers_ > 1 && k > 0 && wire_codec() != Codec::kSparse &&
      BorrowCovers(delta, static_cast<size_t>(k * cols_) * sizeof(float))) {
    bool all_valid = true;
    for (int64_t i = 0; i < k; ++i)
      if (row_ids[i] < 0 || row_ids[i] >= rows_) {
        all_valid = false;
        break;
      }
    if (all_valid) {
      // One pass: per-shard id lists + caller-order (first_idx, nrows)
      // runs.  A run extends while consecutive caller rows share an
      // owner — its bytes are contiguous in the caller's buffer by
      // construction (row i sits at delta + i*cols).
      constexpr size_t kMaxRunsPerShard = 256;  // sendmsg IOV budget
      std::vector<std::vector<int32_t>> ids(servers_);
      std::vector<std::vector<std::pair<int64_t, int64_t>>> runs(servers_);
      bool runs_ok = true;
      int prev_owner = -1;
      for (int64_t i = 0; i < k; ++i) {
        int owner = OwnerOf(row_ids[i], rows_, servers_);
        ids[owner].push_back(row_ids[i]);
        if (i > 0 && owner == prev_owner) {
          runs[owner].back().second += 1;
        } else {
          runs[owner].emplace_back(i, 1);
          if (runs[owner].size() > kMaxRunsPerShard) {
            runs_ok = false;
            break;
          }
        }
        prev_owner = owner;
      }
      if (runs_ok) {
        int64_t msg_id = blocking ? Zoo::Get()->NextMsgId() : -1;
        std::vector<MessagePtr> reqs;
        for (int r = 0; r < servers_; ++r) {
          if (ids[r].empty()) continue;
          auto req = MakeReq(MsgType::RequestAdd, table_id_, msg_id, r);
          StampAuditAdd(req.get(), r);
          req->data.emplace_back(&opt, sizeof(opt));
          req->data.emplace_back(ids[r].data(),
                                 ids[r].size() * sizeof(int32_t));
          for (const auto& run : runs[r])
            req->data.push_back(WrapPayload(
                delta + run.first * cols_,
                static_cast<size_t>(run.second * cols_) * sizeof(float)));
          reqs.push_back(std::move(req));
        }
        Dashboard::Record("addrows.borrowed", 0.0);
        if (blocking)
          return RoundTrip(std::move(reqs), DiscardReply, nullptr);
        for (auto& req : reqs)
          Zoo::Get()->SendTo(actor::kWorker, std::move(req));
        return true;
      }
    }
  }
  std::vector<std::vector<int32_t>> per_rank_ids(servers_);
  std::vector<std::vector<float>> per_rank_delta(servers_);
  for (int64_t i = 0; i < k; ++i) {
    if (row_ids[i] < 0 || row_ids[i] >= rows_) continue;  // dropped
    int owner = OwnerOf(row_ids[i], rows_, servers_);
    per_rank_ids[owner].push_back(row_ids[i]);
    per_rank_delta[owner].insert(per_rank_delta[owner].end(),
                                 delta + i * cols_,
                                 delta + (i + 1) * cols_);
  }
  int64_t msg_id = blocking ? Zoo::Get()->NextMsgId() : -1;
  std::vector<MessagePtr> reqs;
  for (int r = 0; r < servers_; ++r) {
    if (per_rank_ids[r].empty()) continue;
    auto req = MakeReq(MsgType::RequestAdd, table_id_, msg_id, r);
    StampAuditAdd(req.get(), r);
    req->data.emplace_back(&opt, sizeof(opt));
    req->data.emplace_back(per_rank_ids[r].data(),
                           per_rank_ids[r].size() * sizeof(int32_t));
    if (wire_codec() == Codec::kSparse) {
      // Row-subset adds take the lossless sparse codec only: the 1-bit
      // error-feedback residual is indexed by STABLE element offsets,
      // which a varying packed row set does not have.
      AppendEncodedDelta(req.get(), per_rank_delta[r].data(),
                         static_cast<int64_t>(per_rank_delta[r].size()),
                         0, 0);
    } else {
      req->data.emplace_back(per_rank_delta[r].data(),
                             per_rank_delta[r].size() * sizeof(float));
    }
    reqs.push_back(std::move(req));
  }
  if (reqs.empty()) return true;
  if (blocking)
    return RoundTrip(std::move(reqs), DiscardReply, nullptr);
  for (auto& req : reqs)
    Zoo::Get()->SendTo(actor::kWorker, std::move(req));
  return true;
}

// ------------------------------------------------- sparse matrix worker

bool SparseMatrixWorkerTable::GetRows(const int32_t* row_ids, int64_t k,
                                      float* data) {
  Monitor mon("SparseMatrixWorker::GetRows");
  // Plan under the lock, fetch OUTSIDE it: a wire round-trip (up to
  // rpc_timeout_ms when SSP parks the get) must not serialize other
  // readers or stall a barrier's OnClockInvalidate.
  std::vector<int32_t> missing;
  std::unordered_map<int32_t, size_t> fetch_slot;
  uint64_t epoch;
  {
    MutexLock lk(cache_mu_);
    if (valid_.empty()) {
      valid_.assign(static_cast<size_t>(rows_), 0);
      mirror_.assign(static_cast<size_t>(rows_ * cols_), 0.0f);
    }
    epoch = cache_epoch_;
    for (int64_t i = 0; i < k; ++i) {
      int32_t r = row_ids[i];
      if (r >= 0 && r < rows_ && !valid_[r] && !fetch_slot.count(r)) {
        fetch_slot[r] = missing.size();
        missing.push_back(r);
      }
    }
  }
  // Serve-layer observability: one counter tick per call — all-hit
  // calls skip the wire entirely (MV_CacheStats reads these).
  Dashboard::Record(missing.empty() ? "serve.cache.hit"
                                    : "serve.cache.miss", 0.0);
  std::vector<float> fetched(missing.size() * cols_);
  if (!missing.empty() &&
      !MatrixWorkerTable::GetRows(missing.data(),
                                  static_cast<int64_t>(missing.size()),
                                  fetched.data()))
    return false;

  MutexLock lk(cache_mu_);
  // Install only if no invalidation ran while the wire was in flight —
  // caching a pre-add value after the add's invalidation would serve
  // stale reads forever.  The fetched values themselves are still fine
  // to RETURN: a get that races a concurrent add may see either side.
  if (!missing.empty() && cache_epoch_ == epoch) {
    for (size_t i = 0; i < missing.size(); ++i) {
      std::memcpy(mirror_.data() + missing[i] * cols_,
                  fetched.data() + i * cols_, cols_ * sizeof(float));
      valid_[missing[i]] = 1;
    }
  }
  for (int64_t i = 0; i < k; ++i) {
    int32_t r = row_ids[i];
    auto it = fetch_slot.find(r);
    if (it != fetch_slot.end())
      std::memcpy(data + i * cols_, fetched.data() + it->second * cols_,
                  cols_ * sizeof(float));
    else if (r >= 0 && r < rows_)
      std::memcpy(data + i * cols_, mirror_.data() + r * cols_,
                  cols_ * sizeof(float));
    else
      std::memset(data + i * cols_, 0, cols_ * sizeof(float));
  }
  return true;
}

bool SparseMatrixWorkerTable::AddAll(const float* delta,
                                     const AddOption& opt, bool blocking) {
  // Invalidate AFTER the base add: doing it first opens a window where
  // a concurrent GetRows re-caches the pre-add value and a blocking
  // adder's own next read is stale.  Invalidate even on failure — a
  // deadline rc is indeterminate (the server may still apply it).
  bool ok = MatrixWorkerTable::AddAll(delta, opt, blocking);
  MutexLock lk(cache_mu_);
  ++cache_epoch_;
  if (!valid_.empty()) std::fill(valid_.begin(), valid_.end(), 0);
  return ok;
}

bool SparseMatrixWorkerTable::AddRows(const int32_t* row_ids, int64_t k,
                                      const float* delta,
                                      const AddOption& opt, bool blocking) {
  bool ok = MatrixWorkerTable::AddRows(row_ids, k, delta, opt, blocking);
  MutexLock lk(cache_mu_);
  ++cache_epoch_;
  if (!valid_.empty())
    for (int64_t i = 0; i < k; ++i)
      if (row_ids[i] >= 0 && row_ids[i] < rows_) valid_[row_ids[i]] = 0;
  return ok;
}

void SparseMatrixWorkerTable::OnClockInvalidate() {
  // Clock closed: peers' adds are now applied server-side — every
  // cached row may be stale.  The base clears the hot-key replica for
  // the same reason.
  MatrixWorkerTable::OnClockInvalidate();
  MutexLock lk(cache_mu_);
  ++cache_epoch_;
  if (!valid_.empty()) std::fill(valid_.begin(), valid_.end(), 0);
}

// -------------------------------------------------------------- KV worker

namespace {

// Scatter KV get replies: positions[shard] lists the caller-order slots
// that shard's reply values fill (request order within the shard).
struct KVDest {
  float* vals;
  const std::vector<std::vector<int64_t>>* positions;
};

void ScatterKVReply(void* arg, const Message& reply) {
  auto* d = static_cast<KVDest*>(arg);
  if (reply.data.empty()) return;
  int shard = ReplyShard(reply);
  if (shard < 0) return;
  const auto& pos = (*d->positions)[static_cast<size_t>(shard)];
  const float* src = reply.data[0].As<float>();
  size_t have = reply.data[0].count<float>();
  for (size_t i = 0; i < pos.size() && i < have; ++i)
    d->vals[pos[i]] = src[i];
}

}  // namespace

bool KVWorkerTable::Get(const std::vector<std::string>& keys, float* vals) {
  Monitor mon("KVWorker::Get");
  FlushAdds();
  std::vector<std::vector<std::string>> per_rank(servers_);
  std::vector<std::vector<int64_t>> positions(servers_);
  for (size_t i = 0; i < keys.size(); ++i) {
    int owner = static_cast<int>(
        KVHash(keys[i].data(), keys[i].size()) %
        static_cast<uint64_t>(servers_));
    per_rank[owner].push_back(keys[i]);
    positions[owner].push_back(static_cast<int64_t>(i));
  }
  std::memset(vals, 0, keys.size() * sizeof(float));
  int64_t msg_id = Zoo::Get()->NextMsgId();
  std::vector<MessagePtr> reqs;
  for (int r = 0; r < servers_; ++r) {
    if (per_rank[r].empty()) continue;
    auto req = MakeReq(MsgType::RequestGet, table_id_, msg_id, r,
                       accept_flags());
    req->version = last_version();  // observed-staleness stamp
    req->data.push_back(PackKeys(per_rank[r]));
    reqs.push_back(std::move(req));
  }
  KVDest d{vals, &positions};
  bool ok = reqs.empty() || RoundTrip(std::move(reqs), ScatterKVReply, &d);
  if (ok) {
    // Refresh the worker-side dict (the reference KVWorkerTable `raw`).
    MutexLock lk(cache_mu_);
    for (size_t i = 0; i < keys.size(); ++i) cache_[keys[i]] = vals[i];
  }
  return ok;
}

bool KVWorkerTable::Add(const std::vector<std::string>& keys,
                        const float* deltas, const AddOption& opt,
                        bool blocking) {
  Monitor mon("KVWorker::Add");
  std::vector<std::vector<std::string>> per_rank(servers_);
  std::vector<std::vector<float>> per_vals(servers_);
  for (size_t i = 0; i < keys.size(); ++i) {
    int owner = static_cast<int>(
        KVHash(keys[i].data(), keys[i].size()) %
        static_cast<uint64_t>(servers_));
    per_rank[owner].push_back(keys[i]);
    per_vals[owner].push_back(deltas[i]);
  }
  int64_t msg_id = blocking ? Zoo::Get()->NextMsgId() : -1;
  std::vector<MessagePtr> reqs;
  for (int r = 0; r < servers_; ++r) {
    if (per_rank[r].empty()) continue;
    auto req = MakeReq(MsgType::RequestAdd, table_id_, msg_id, r);
    StampAuditAdd(req.get(), r);
    req->data.emplace_back(&opt, sizeof(opt));
    req->data.push_back(PackKeys(per_rank[r]));
    req->data.emplace_back(per_vals[r].data(),
                           per_vals[r].size() * sizeof(float));
    reqs.push_back(std::move(req));
  }
  if (reqs.empty()) return true;
  if (blocking)
    return RoundTrip(std::move(reqs), DiscardReply, nullptr);
  for (auto& req : reqs)
    Zoo::Get()->SendTo(actor::kWorker, std::move(req));
  return true;
}

}  // namespace mvtpu
