// Wire conventions between worker stubs and server tables (in-process):
//   Array Get      req: no blobs                 reply: [float data]
//   Array Add      req: [AddOption][float delta]
//   Matrix GetAll  req: no blobs                 reply: [float data]
//   Matrix GetRows req: [int32 ids]              reply: [float rows-packed]
//   Matrix AddAll  req: [AddOption][float delta]
//   Matrix AddRows req: [AddOption][int32 ids][float rows-packed]
// msg_id >= 0 means the caller blocks on a reply; msg_id < 0 is async.
#include "mvtpu/table.h"

#include <cstring>

#include "mvtpu/dashboard.h"
#include "mvtpu/log.h"
#include "mvtpu/zoo.h"

namespace mvtpu {

// ---------------------------------------------------------------- server

ArrayServerTable::ArrayServerTable(int64_t size, UpdaterType updater)
    : data_(static_cast<size_t>(size), 0.0f), updater_(updater) {
  if (NumSlots(updater_) > 0) slot0_.assign(data_.size(), 0.0f);
}

void ArrayServerTable::ProcessGet(const Message& req, Message* reply) {
  (void)req;
  Monitor mon("ArrayServer::ProcessGet");
  std::lock_guard<std::mutex> lk(mu_);
  reply->data.emplace_back(data_.data(), data_.size() * sizeof(float));
}

void ArrayServerTable::ProcessAdd(const Message& req) {
  Monitor mon("ArrayServer::ProcessAdd");
  const AddOption* opt = req.data[0].As<AddOption>();
  const float* delta = req.data[1].As<float>();
  size_t n = req.data[1].count<float>();
  std::lock_guard<std::mutex> lk(mu_);
  if (n != data_.size()) {
    Log::Error("ArrayServerTable: delta size %zu != %zu", n, data_.size());
    return;
  }
  ApplyUpdate(updater_, *opt, data_.data(),
              slot0_.empty() ? nullptr : slot0_.data(), delta, n);
}

bool ArrayServerTable::Store(Stream* out) const {
  int64_t n = size();
  return out->Write(&n, sizeof(n)) == sizeof(n) &&
         out->Write(data_.data(), n * sizeof(float)) == n * sizeof(float) &&
         (slot0_.empty() ||
          out->Write(slot0_.data(), n * sizeof(float)) == n * sizeof(float));
}

bool ArrayServerTable::Load(Stream* in) {
  int64_t n = 0;
  if (in->Read(&n, sizeof(n)) != sizeof(n) || n != size()) return false;
  if (in->Read(data_.data(), n * sizeof(float)) !=
      static_cast<size_t>(n) * sizeof(float))
    return false;
  if (!slot0_.empty() &&
      in->Read(slot0_.data(), n * sizeof(float)) !=
          static_cast<size_t>(n) * sizeof(float))
    return false;
  return true;
}

MatrixServerTable::MatrixServerTable(int64_t rows, int64_t cols,
                                     UpdaterType updater)
    : rows_(rows), cols_(cols),
      data_(static_cast<size_t>(rows * cols), 0.0f), updater_(updater) {
  if (NumSlots(updater_) > 0) slot0_.assign(data_.size(), 0.0f);
}

void MatrixServerTable::ProcessGet(const Message& req, Message* reply) {
  Monitor mon("MatrixServer::ProcessGet");
  std::lock_guard<std::mutex> lk(mu_);
  if (req.data.empty()) {  // GetAll
    reply->data.emplace_back(data_.data(), data_.size() * sizeof(float));
    return;
  }
  const int32_t* ids = req.data[0].As<int32_t>();
  size_t k = req.data[0].count<int32_t>();
  Blob out(k * cols_ * sizeof(float));
  float* dst = out.As<float>();
  for (size_t i = 0; i < k; ++i) {
    int64_t r = ids[i];
    if (r < 0 || r >= rows_) {  // out-of-range rows read as zeros
      std::memset(dst + i * cols_, 0, cols_ * sizeof(float));
      continue;
    }
    std::memcpy(dst + i * cols_, data_.data() + r * cols_,
                cols_ * sizeof(float));
  }
  reply->data.push_back(std::move(out));
}

void MatrixServerTable::ProcessAdd(const Message& req) {
  Monitor mon("MatrixServer::ProcessAdd");
  const AddOption* opt = req.data[0].As<AddOption>();
  std::lock_guard<std::mutex> lk(mu_);
  float* slots = slot0_.empty() ? nullptr : slot0_.data();
  if (req.data.size() == 2) {  // AddAll
    const float* delta = req.data[1].As<float>();
    if (req.data[1].count<float>() != data_.size()) {
      Log::Error("MatrixServerTable: AddAll size mismatch");
      return;
    }
    ApplyUpdate(updater_, *opt, data_.data(), slots, delta, data_.size());
    return;
  }
  // AddRows: rows applied sequentially — duplicate ids compose like
  // consecutive reference Adds.
  const int32_t* ids = req.data[1].As<int32_t>();
  size_t k = req.data[1].count<int32_t>();
  const float* delta = req.data[2].As<float>();
  if (req.data[2].count<float>() != k * static_cast<size_t>(cols_)) {
    Log::Error("MatrixServerTable: AddRows size mismatch");
    return;
  }
  for (size_t i = 0; i < k; ++i) {
    int64_t r = ids[i];
    if (r < 0 || r >= rows_) continue;  // out-of-range rows dropped
    ApplyUpdate(updater_, *opt, data_.data() + r * cols_,
                slots ? slots + r * cols_ : nullptr, delta + i * cols_,
                static_cast<size_t>(cols_));
  }
}

bool MatrixServerTable::Store(Stream* out) const {
  int64_t hdr[2] = {rows_, cols_};
  size_t bytes = data_.size() * sizeof(float);
  return out->Write(hdr, sizeof(hdr)) == sizeof(hdr) &&
         out->Write(data_.data(), bytes) == bytes &&
         (slot0_.empty() || out->Write(slot0_.data(), bytes) == bytes);
}

bool MatrixServerTable::Load(Stream* in) {
  int64_t hdr[2];
  if (in->Read(hdr, sizeof(hdr)) != sizeof(hdr) || hdr[0] != rows_ ||
      hdr[1] != cols_)
    return false;
  size_t bytes = data_.size() * sizeof(float);
  if (in->Read(data_.data(), bytes) != bytes) return false;
  if (!slot0_.empty() && in->Read(slot0_.data(), bytes) != bytes) return false;
  return true;
}

// ---------------------------------------------------------------- worker

void WorkerTable::Notify(int64_t msg_id, const Message& reply) {
  Pending p;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = pending_.find(msg_id);
    if (it == pending_.end()) {
      Log::Error("WorkerTable %d: reply for unknown msg %lld", table_id_,
                 static_cast<long long>(msg_id));
      return;
    }
    p = it->second;
    pending_.erase(it);
  }
  if (p.consume) p.consume(p.arg, reply);
  p.waiter->Notify();
}

void WorkerTable::RoundTrip(MessagePtr req,
                            void (*consume)(void*, const Message&),
                            void* arg) {
  Waiter waiter(1);
  {
    std::lock_guard<std::mutex> lk(mu_);
    pending_[req->msg_id] = Pending{&waiter, consume, arg};
  }
  Zoo::Get()->SendTo(actor::kWorker, std::move(req));
  waiter.Wait();
}

namespace {
struct CopyDest {
  float* dst;
  size_t count;
};
void CopyReply(void* arg, const Message& reply) {
  auto* d = static_cast<CopyDest*>(arg);
  size_t n = reply.data.empty() ? 0 : reply.data[0].count<float>();
  if (n > d->count) n = d->count;
  if (n) std::memcpy(d->dst, reply.data[0].As<float>(), n * sizeof(float));
}
void DiscardReply(void*, const Message&) {}
}  // namespace

void ArrayWorkerTable::Get(float* data, int64_t size) {
  Monitor mon("ArrayWorker::Get");
  auto req = std::make_unique<Message>();
  req->type = MsgType::RequestGet;
  req->table_id = table_id_;
  req->msg_id = Zoo::Get()->NextMsgId();
  CopyDest d{data, static_cast<size_t>(size)};
  RoundTrip(std::move(req), CopyReply, &d);
}

void ArrayWorkerTable::Add(const float* delta, int64_t size,
                           const AddOption& opt, bool blocking) {
  Monitor mon("ArrayWorker::Add");
  auto req = std::make_unique<Message>();
  req->type = MsgType::RequestAdd;
  req->table_id = table_id_;
  req->data.emplace_back(&opt, sizeof(opt));
  req->data.emplace_back(delta, size * sizeof(float));
  if (blocking) {
    req->msg_id = Zoo::Get()->NextMsgId();
    RoundTrip(std::move(req), DiscardReply, nullptr);
  } else {
    req->msg_id = -1;
    Zoo::Get()->SendTo(actor::kWorker, std::move(req));
  }
}

void MatrixWorkerTable::GetAll(float* data) {
  Monitor mon("MatrixWorker::GetAll");
  auto req = std::make_unique<Message>();
  req->type = MsgType::RequestGet;
  req->table_id = table_id_;
  req->msg_id = Zoo::Get()->NextMsgId();
  CopyDest d{data, static_cast<size_t>(rows_ * cols_)};
  RoundTrip(std::move(req), CopyReply, &d);
}

void MatrixWorkerTable::GetRows(const int32_t* row_ids, int64_t k,
                                float* data) {
  Monitor mon("MatrixWorker::GetRows");
  auto req = std::make_unique<Message>();
  req->type = MsgType::RequestGet;
  req->table_id = table_id_;
  req->msg_id = Zoo::Get()->NextMsgId();
  req->data.emplace_back(row_ids, k * sizeof(int32_t));
  CopyDest d{data, static_cast<size_t>(k * cols_)};
  RoundTrip(std::move(req), CopyReply, &d);
}

void MatrixWorkerTable::AddAll(const float* delta, const AddOption& opt,
                               bool blocking) {
  Monitor mon("MatrixWorker::AddAll");
  auto req = std::make_unique<Message>();
  req->type = MsgType::RequestAdd;
  req->table_id = table_id_;
  req->data.emplace_back(&opt, sizeof(opt));
  req->data.emplace_back(delta, rows_ * cols_ * sizeof(float));
  if (blocking) {
    req->msg_id = Zoo::Get()->NextMsgId();
    RoundTrip(std::move(req), DiscardReply, nullptr);
  } else {
    req->msg_id = -1;
    Zoo::Get()->SendTo(actor::kWorker, std::move(req));
  }
}

void MatrixWorkerTable::AddRows(const int32_t* row_ids, int64_t k,
                                const float* delta, const AddOption& opt,
                                bool blocking) {
  Monitor mon("MatrixWorker::AddRows");
  auto req = std::make_unique<Message>();
  req->type = MsgType::RequestAdd;
  req->table_id = table_id_;
  req->data.emplace_back(&opt, sizeof(opt));
  req->data.emplace_back(row_ids, k * sizeof(int32_t));
  req->data.emplace_back(delta, k * cols_ * sizeof(float));
  if (blocking) {
    req->msg_id = Zoo::Get()->NextMsgId();
    RoundTrip(std::move(req), DiscardReply, nullptr);
  } else {
    req->msg_id = -1;
    Zoo::Get()->SendTo(actor::kWorker, std::move(req));
  }
}

}  // namespace mvtpu
