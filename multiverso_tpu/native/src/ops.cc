#include "mvtpu/ops.h"

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <deque>
#include <sstream>
#include <vector>

#include "mvtpu/configure.h"
#include "mvtpu/dashboard.h"
#include "mvtpu/latency.h"
#include "mvtpu/log.h"
#include "mvtpu/mutex.h"
#include "mvtpu/profiler.h"
#include "mvtpu/qos.h"
#include "mvtpu/watchdog.h"
#include "mvtpu/zoo.h"

namespace mvtpu {
namespace ops {

namespace {

Mutex g_mu;
std::string g_host_metrics GUARDED_BY(g_mu);
// Host-pushed alert state (JSON object text from the Python health
// evaluator, spliced verbatim into the "alerts" report — the native
// side never parses it).  Empty = no host push yet.
std::string g_host_alerts GUARDED_BY(g_mu);

struct Event {
  int64_t ts_us;
  std::string kind;
  std::string detail;
};
Mutex g_box_mu;
// mvlint: MV018-exempt(bounded ring — BlackboxEvent pops the front
// past -blackbox_events; the ring IS the black box, never traffic)
std::deque<Event> g_events GUARDED_BY(g_box_mu);
long long g_triggers GUARDED_BY(g_box_mu) = 0;

int64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

// Minimal JSON string escape (names/details are runtime-controlled, but
// a rogue flag value must not produce an unparseable black box).
std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c & 0xff);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

std::vector<std::string> SplitTabs(const std::string& line) {
  std::vector<std::string> out;
  size_t start = 0;
  while (true) {
    size_t tab = line.find('\t', start);
    if (tab == std::string::npos) {
      out.push_back(line.substr(start));
      return out;
    }
    out.push_back(line.substr(start, tab - start));
    start = tab + 1;
  }
}

std::vector<long long> SplitCsv(const std::string& s) {
  std::vector<long long> out;
  size_t start = 0;
  while (start <= s.size()) {
    size_t comma = s.find(',', start);
    std::string tok = s.substr(
        start, comma == std::string::npos ? std::string::npos
                                          : comma - start);
    if (!tok.empty()) out.push_back(std::stoll(tok));
    if (comma == std::string::npos) break;
    start = comma + 1;
  }
  return out;
}

std::string FmtDouble(double v) {
  char buf[64];
  std::snprintf(buf, sizeof(buf), "%.9g", v);
  return buf;
}

// Native Dashboard -> Prometheus exposition, with per-bucket exemplar
// trace ids in OpenMetrics style:
//   name_bucket{le="0.001024"} 17 # {trace_id="0x..."} 0.001024
// Served only when the host has not pushed its own (superset)
// rendering — the pushed text already bridges every native monitor.
std::string RenderNativePrometheus() {
  std::ostringstream os;
  std::istringstream in(Dashboard::Dump());
  std::string line;
  while (std::getline(in, line)) {
    if (line.empty()) continue;
    auto fields = SplitTabs(line);
    if (fields.size() < 5) continue;
    const std::string pname = PromName(fields[0]);
    long long count = std::stoll(fields[1]);
    double total = std::stod(fields[2]);
    auto buckets = SplitCsv(fields[4]);
    std::vector<long long> exemplars;
    if (fields.size() >= 6) exemplars = SplitCsv(fields[5]);
    os << "# TYPE " << pname << " histogram\n";
    long long cum = 0;
    double bound = 1e-6;
    for (size_t i = 0; i < buckets.size(); ++i) {
      bool inf = i + 1 == buckets.size();
      cum += buckets[i];
      os << pname << "_bucket{le=\""
         << (inf ? "+Inf" : FmtDouble(bound)) << "\"} " << cum;
      if (i < exemplars.size() && exemplars[i] != 0) {
        char hex[32];
        std::snprintf(hex, sizeof(hex), "0x%llx",
                      static_cast<unsigned long long>(exemplars[i]));
        os << " # {trace_id=\"" << hex << "\"} "
           << (inf ? FmtDouble(bound) : FmtDouble(bound));
      }
      os << '\n';
      bound *= 2.0;
    }
    os << pname << "_sum " << FmtDouble(total) << '\n';
    os << pname << "_count " << count << '\n';
  }
  return os.str();
}

// Interpolated q-quantile out of the Dashboard's fixed log2 buckets
// (bucket i holds values <= 1e-6 * 2^i seconds; the last is +inf) —
// the native mirror of metrics.py Histogram.quantile, so latdoctor and
// a Python scrape agree to within one bucket ratio.
double BucketQuantile(const std::vector<long long>& buckets,
                      long long count, double vmax, double q) {
  if (count <= 0 || buckets.empty()) return 0.0;
  double target = q * static_cast<double>(count);
  long long cum = 0;
  double bound = 1e-6;
  for (size_t i = 0; i < buckets.size(); ++i) {
    long long c = buckets[i];
    if (c > 0 && static_cast<double>(cum + c) >= target) {
      double lo = i > 0 ? bound / 2.0 : 0.0;
      double hi = i + 1 < buckets.size() ? bound : vmax;
      double v = lo + (hi - lo) * (target - static_cast<double>(cum)) /
                          static_cast<double>(c);
      return std::min(v, vmax > 0 ? vmax : v);
    }
    cum += c;
    if (i + 1 < buckets.size()) bound *= 2.0;
  }
  return vmax;
}

// One stage's JSON object from a parsed MV_DumpMonitors line.
std::string StageJson(const std::vector<std::string>& fields) {
  long long count = std::stoll(fields[1]);
  double total = std::stod(fields[2]);
  double vmax = std::stod(fields[3]);
  auto buckets = SplitCsv(fields[4]);
  std::ostringstream os;
  os << "{\"count\":" << count << ",\"sum_s\":" << FmtDouble(total)
     << ",\"max_ms\":" << FmtDouble(vmax * 1e3);
  for (auto [name, q] : {std::pair<const char*, double>{"p50_ms", 0.50},
                         {"p95_ms", 0.95},
                         {"p99_ms", 0.99}})
    os << ",\"" << name << "\":"
       << FmtDouble(BucketQuantile(buckets, count, vmax, q) * 1e3);
  if (fields.size() >= 6) {
    // The p99 bucket's exemplar trace id (0 = none): the link from a
    // slow stage straight into the merged Chrome trace.
    auto exemplars = SplitCsv(fields[5]);
    double target = 0.99 * static_cast<double>(count);
    long long cum = 0;
    long long ex = 0;
    for (size_t i = 0; i < buckets.size() && i < exemplars.size(); ++i) {
      cum += buckets[i];
      if (buckets[i] > 0 && exemplars[i] != 0) ex = exemplars[i];
      if (static_cast<double>(cum) >= target && ex != 0) break;
    }
    if (ex != 0) {
      char hex[32];
      std::snprintf(hex, sizeof(hex), "0x%llx",
                    static_cast<unsigned long long>(ex));
      os << ",\"exemplar_p99\":\"" << hex << "\"";
    }
  }
  os << "}";
  return os.str();
}

// The "latency" OpsQuery kind (docs/observability.md "latency plane"):
// per-stage histograms (from the lat.stage.* Dashboard monitors the
// timing trail feeds), the end-to-end lat.total, per-peer clock
// offsets, and the sampling profiler's status — everything latdoctor
// needs to name the dominant stage per percentile.  Fleet scope comes
// free through the generic JSON merge.
std::string LatencyJson() {
  std::ostringstream os;
  os << "{\"rank\":" << Zoo::Get()->rank();
  os << ",\"armed\":" << (latency::Armed() ? "true" : "false");
  os << ",\"stages\":{";
  bool first = true;
  std::string total_json;
  std::istringstream in(Dashboard::Dump());
  std::string line;
  while (std::getline(in, line)) {
    if (line.empty()) continue;
    auto fields = SplitTabs(line);
    if (fields.size() < 5) continue;
    const std::string& name = fields[0];
    if (name == "lat.total") {
      total_json = StageJson(fields);
      continue;
    }
    constexpr const char kPrefix[] = "lat.stage.";
    if (name.rfind(kPrefix, 0) != 0) continue;
    if (!first) os << ',';
    first = false;
    os << "\"" << name.substr(sizeof(kPrefix) - 1) << "\":"
       << StageJson(fields);
  }
  os << "}";
  if (!total_json.empty()) os << ",\"total\":" << total_json;
  os << ",\"offsets\":" << latency::OffsetsJson();
  os << ",\"profiler\":" << profiler::StatusJson();
  // Tail plane (docs/serving.md "tail"): per-class admission ledger +
  // deadline sheds + hedge cancels, so mvtop --qos and latdoctor's
  // shed-dominance note ride the same scrape as the stage histograms.
  os << ",\"qos\":" << qos::Json();
  os << "}";
  return os.str();
}

}  // namespace

std::string PromName(const std::string& name) {
  std::string out;
  out.reserve(name.size());
  for (size_t i = 0; i < name.size(); ++i) {
    char c = name[i];
    bool ok = (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
              c == '_' || c == ':' || (c >= '0' && c <= '9' && i != 0);
    out += ok ? c : '_';
  }
  return out;
}

void SetHostMetrics(const std::string& prom_text) {
  MutexLock lk(g_mu);
  g_host_metrics = prom_text;
}

void SetHostAlerts(const std::string& alerts_json) {
  MutexLock lk(g_mu);
  g_host_alerts = alerts_json;
}

std::string LocalReport(const std::string& kind) {
  if (kind == "metrics") {
    {
      MutexLock lk(g_mu);
      if (!g_host_metrics.empty()) return g_host_metrics;
    }
    return RenderNativePrometheus();
  }
  if (kind == "health") return Zoo::Get()->OpsHealthJson();
  if (kind == "tables") return Zoo::Get()->OpsTablesJson();
  // Workload plane (docs/observability.md): per-table hot-key top-K +
  // count-min estimates, bucket-load skew, staleness, health sentinels.
  if (kind == "hotkeys") return Zoo::Get()->OpsHotKeysJson();
  // Latency-attribution plane (docs/observability.md): stage
  // histograms + clock offsets + profiler status.
  if (kind == "latency") return LatencyJson();
  // Delivery-audit plane (docs/observability.md "audit plane"):
  // acked-add ledgers, per-origin applied watermarks, dup/reorder/gap
  // anomalies, bucket checksums.  Fleet scope free via the JSON merge;
  // tools/mvaudit.py diffs acked-vs-applied across the fleet.
  if (kind == "audit") return Zoo::Get()->OpsAuditJson();
  // Replication plane (docs/replication.md): routing epoch + shard
  // map, backup identity, and the forward/ack/promotion ledger.
  // Fleet scope rides the generic JSON merge for free.
  if (kind == "replication") return Zoo::Get()->OpsReplicationJson();
  // Capacity plane (docs/observability.md "capacity plane"): proc
  // stats, arena/write-queue/registered byte gauges, per-table
  // resident bytes per bucket + the load-history ring.  Fleet scope
  // rides the generic JSON merge; tools/mvplan.py plans over it.
  if (kind == "capacity") return Zoo::Get()->OpsCapacityJson();
  // Health plane (docs/observability.md "health plane"): the native
  // stall watchdog's per-loop progress table plus the host-pushed
  // alert state (SetHostAlerts, fed by health.py each metrics flush —
  // spliced verbatim, never parsed here).  Fleet scope rides the
  // generic JSON merge; mvtop --alerts / mvdoctor render it.
  if (kind == "alerts") {
    std::string host;
    {
      MutexLock lk(g_mu);
      host = g_host_alerts;
    }
    std::ostringstream os;
    os << "{\"rank\":" << Zoo::Get()->rank()
       << ",\"watchdog\":" << watchdog::StatsJson()
       << ",\"host\":" << (host.empty() ? "null" : host) << "}";
    return os.str();
  }
  return "{\"error\":\"unknown ops kind '" + JsonEscape(kind) + "'\"}";
}

void BuildReply(const Message& query, Message* reply) {
  std::string kind = "health";
  if (!query.data.empty() && query.data[0].size() > 0)
    kind.assign(query.data[0].data(), query.data[0].size());
  std::string text = LocalReport(kind);
  reply->type = MsgType::OpsReply;
  reply->table_id = query.table_id;
  reply->msg_id = query.msg_id;
  reply->trace_id = query.trace_id;
  reply->version = query.version;  // echo the scope
  reply->data.clear();
  reply->data.emplace_back(text.data(), text.size());
}

void BuildReplicaReply(const Message& query, Message* reply) {
  reply->type = MsgType::ReplyReplica;
  reply->table_id = query.table_id;
  reply->msg_id = query.msg_id;
  reply->trace_id = query.trace_id;
  reply->data.clear();
  auto* st = Zoo::Get()->server_table(query.table_id);
  if (st) st->BuildReplica(reply);
}

// ---- flight recorder -------------------------------------------------

namespace {

// Dump rotation: beside the canonical blackbox_rank<r>.json (always the
// LATEST dump — every existing reader keeps working), each trigger also
// lands a timestamped archive blackbox_rank<r>.<ts_us>.<n>.json, and a
// small manifest lists the retained archives.  Keep-N (-blackbox_keep)
// prunes the oldest — a second trigger on the same rank no longer
// destroys the first dump's evidence.
Mutex g_rot_mu;
// mvlint: MV018-exempt(bounded at -blackbox_keep archive names —
// RotateDump prunes the oldest past the keep bound)
std::deque<std::string> g_archives GUARDED_BY(g_rot_mu);
long long g_dump_seq GUARDED_BY(g_rot_mu) = 0;

bool WriteWhole(const std::string& path, const std::string& doc) {
  std::FILE* fp = std::fopen(path.c_str(), "wb");
  if (!fp) return false;
  size_t wrote = std::fwrite(doc.data(), 1, doc.size(), fp);
  std::fclose(fp);
  return wrote == doc.size();
}

void RotateDump(const std::string& dir, const std::string& doc) {
  size_t keep = static_cast<size_t>(
      std::max<long long>(1, configure::Has("blackbox_keep")
                                 ? configure::GetInt("blackbox_keep")
                                 : 4));
  int rank = Zoo::Get()->rank();
  std::string base = "blackbox_rank" + std::to_string(rank);
  MutexLock lk(g_rot_mu);
  // ts + per-process seq: two triggers in the same microsecond (or a
  // stepped clock) still get distinct archive names.
  std::string name = base + "." + std::to_string(NowUs()) + "." +
                     std::to_string(++g_dump_seq) + ".json";
  if (!WriteWhole(dir + "/" + name, doc)) {
    Log::Error("blackbox: cannot archive %s", name.c_str());
    return;
  }
  g_archives.push_back(name);
  while (g_archives.size() > keep) {
    std::remove((dir + "/" + g_archives.front()).c_str());
    g_archives.pop_front();
  }
  std::ostringstream m;
  m << "{\"rank\":" << rank << ",\"keep\":" << keep << ",\"dumps\":[";
  for (size_t i = 0; i < g_archives.size(); ++i) {
    if (i) m << ',';
    m << "\"" << g_archives[i] << "\"";
  }
  m << "],\"total_triggers\":" << g_dump_seq << "}";
  std::string mpath = dir + "/" + base + ".manifest.json";
  std::string mtmp = mpath + ".tmp";
  if (!WriteWhole(mtmp, m.str()) ||
      std::rename(mtmp.c_str(), mpath.c_str()) != 0) {
    Log::Error("blackbox: manifest write failed for %s", mpath.c_str());
    std::remove(mtmp.c_str());
  }
}

}  // namespace

void BlackboxEvent(const std::string& kind, const std::string& detail) {
  size_t cap = static_cast<size_t>(
      std::max<long long>(16, configure::Has("blackbox_events")
                                  ? configure::GetInt("blackbox_events")
                                  : 512));
  Event ev{NowUs(), kind, detail};
  MutexLock lk(g_box_mu);
  g_events.push_back(std::move(ev));
  while (g_events.size() > cap) g_events.pop_front();
}

std::string BlackboxTrigger(const std::string& reason) {
  BlackboxEvent("trigger", reason);
  Dashboard::Record("blackbox.trigger", 0.0);
  std::string dir = configure::Has("trace_dir")
                        ? configure::GetString("trace_dir")
                        : "";
  {
    MutexLock lk(g_box_mu);
    ++g_triggers;
  }
  if (dir.empty()) return "";

  std::ostringstream os;
  os << "{\"reason\":\"" << JsonEscape(reason) << "\",";
  os << "\"rank\":" << Zoo::Get()->rank() << ",";
  os << "\"ts_us\":" << NowUs() << ",";
  os << "\"events\":[";
  {
    MutexLock lk(g_box_mu);
    bool first = true;
    for (const auto& ev : g_events) {
      if (!first) os << ',';
      first = false;
      os << "{\"ts_us\":" << ev.ts_us << ",\"kind\":\""
         << JsonEscape(ev.kind) << "\",\"detail\":\""
         << JsonEscape(ev.detail) << "\"}";
    }
  }
  os << "],\"spans\":[";
  {
    std::istringstream in(Dashboard::DumpSpans());
    std::string line;
    bool first = true;
    while (std::getline(in, line)) {
      if (line.empty()) continue;
      auto f = SplitTabs(line);
      if (f.size() < 6) continue;
      if (!first) os << ',';
      first = false;
      char hex[32];
      std::snprintf(hex, sizeof(hex), "0x%llx",
                    static_cast<unsigned long long>(std::stoll(f[1])));
      os << "{\"name\":\"" << JsonEscape(f[0]) << "\",\"trace_id\":\""
         << hex << "\",\"ts\":" << f[2] << ",\"dur\":" << f[3]
         << ",\"pid\":" << f[4] << ",\"tid\":" << f[5] << "}";
    }
  }
  os << "],\"monitors\":{";
  {
    std::istringstream in(Dashboard::Dump());
    std::string line;
    bool first = true;
    while (std::getline(in, line)) {
      if (line.empty()) continue;
      auto f = SplitTabs(line);
      if (f.size() < 3) continue;
      if (!first) os << ',';
      first = false;
      os << "\"" << JsonEscape(f[0]) << "\":{\"count\":" << f[1]
         << ",\"total_s\":" << f[2] << "}";
    }
  }
  os << "}}";

  std::string path =
      dir + "/blackbox_rank" + std::to_string(Zoo::Get()->rank()) + ".json";
  std::string tmp = path + ".tmp";
  std::FILE* fp = std::fopen(tmp.c_str(), "wb");
  if (!fp) {
    Log::Error("blackbox: cannot write %s", tmp.c_str());
    return "";
  }
  std::string doc = os.str();
  size_t wrote = std::fwrite(doc.data(), 1, doc.size(), fp);
  std::fclose(fp);
  if (wrote != doc.size() || std::rename(tmp.c_str(), path.c_str()) != 0) {
    Log::Error("blackbox: short write / rename failed for %s",
               path.c_str());
    std::remove(tmp.c_str());
    return "";
  }
  RotateDump(dir, doc);
  Log::Error("blackbox: dumped flight recorder to %s (reason: %s)",
             path.c_str(), reason.c_str());
  return path;
}

long long BlackboxTriggerCount() {
  MutexLock lk(g_box_mu);
  return g_triggers;
}

void BlackboxReset() {
  {
    MutexLock lk(g_box_mu);
    g_events.clear();
    g_triggers = 0;
  }
  {
    // Forget the rotation ledger (files on disk stay); g_dump_seq keeps
    // counting so archive names never collide across resets.
    MutexLock lk(g_rot_mu);
    g_archives.clear();
  }
  MutexLock lk(g_mu);
  g_host_metrics.clear();
  g_host_alerts.clear();
}

}  // namespace ops
}  // namespace mvtpu
