#include "mvtpu/message.h"

#include <cstring>

namespace mvtpu {

namespace {
struct Header {
  int32_t src, dst, type, table_id;
  int64_t msg_id;
  int64_t trace_id;
  int64_t version;
  int32_t num_blobs;
};
}  // namespace

Blob Message::Serialize() const {
  size_t total = sizeof(Header);
  for (const auto& b : data) total += sizeof(int64_t) + b.size();
  Blob out(total);
  char* p = out.data();
  Header h{src, dst, static_cast<int32_t>(type), table_id, msg_id,
           trace_id, version, static_cast<int32_t>(data.size())};
  std::memcpy(p, &h, sizeof(h));
  p += sizeof(h);
  for (const auto& b : data) {
    int64_t len = static_cast<int64_t>(b.size());
    std::memcpy(p, &len, sizeof(len));
    p += sizeof(len);
    std::memcpy(p, b.data(), b.size());
    p += b.size();
  }
  return out;
}

Message Message::Deserialize(const Blob& buf) {
  Message m;
  const char* p = buf.data();
  Header h;
  std::memcpy(&h, p, sizeof(h));
  p += sizeof(h);
  m.src = h.src;
  m.dst = h.dst;
  m.type = static_cast<MsgType>(h.type);
  m.table_id = h.table_id;
  m.msg_id = h.msg_id;
  m.trace_id = h.trace_id;
  m.version = h.version;
  m.data.reserve(h.num_blobs);
  for (int32_t i = 0; i < h.num_blobs; ++i) {
    int64_t len;
    std::memcpy(&len, p, sizeof(len));
    p += sizeof(len);
    m.data.emplace_back(p, static_cast<size_t>(len));
    p += len;
  }
  return m;
}

}  // namespace mvtpu
