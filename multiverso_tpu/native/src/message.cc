#include "mvtpu/message.h"

#include <cstring>

namespace mvtpu {

void Message::FillWireHeader(WireHeader* h) const {
  *h = WireHeader{src,
                  dst,
                  static_cast<int32_t>(type),
                  table_id,
                  msg_id,
                  trace_id,
                  version,
                  static_cast<int32_t>(codec),
                  flags,
                  static_cast<int32_t>(data.size()),
                  shard + 1};  // biased: wire 0 = no hint (old peers)
}

void Message::AdoptWireHeader(const WireHeader& h) {
  src = h.src;
  dst = h.dst;
  type = static_cast<MsgType>(h.type);
  table_id = h.table_id;
  msg_id = h.msg_id;
  trace_id = h.trace_id;
  version = h.version;
  codec = static_cast<Codec>(h.codec);
  flags = h.flags;
  shard = h.shard_hint - 1;
}

int64_t Message::WireBytes() const {
  int64_t total = static_cast<int64_t>(sizeof(WireHeader));
  if (has_timing()) total += static_cast<int64_t>(sizeof(TimingTrail));
  if (has_audit()) total += static_cast<int64_t>(sizeof(AuditStamp));
  if (has_qos()) total += static_cast<int64_t>(sizeof(QosStamp));
  for (const auto& b : data)
    total += static_cast<int64_t>(sizeof(int64_t) + b.size());
  return total;
}

Blob Message::Serialize() const {
  Blob out(static_cast<size_t>(WireBytes()));
  char* p = out.data();
  WireHeader h;
  FillWireHeader(&h);
  std::memcpy(p, &h, sizeof(h));
  p += sizeof(h);
  if (has_timing()) {
    std::memcpy(p, &timing, sizeof(timing));
    p += sizeof(timing);
  }
  if (has_audit()) {
    std::memcpy(p, &audit, sizeof(audit));
    p += sizeof(audit);
  }
  if (has_qos()) {
    std::memcpy(p, &qos, sizeof(qos));
    p += sizeof(qos);
  }
  for (const auto& b : data) {
    int64_t len = static_cast<int64_t>(b.size());
    std::memcpy(p, &len, sizeof(len));
    p += sizeof(len);
    std::memcpy(p, b.data(), b.size());
    p += b.size();
  }
  return out;
}

namespace {

// Shared frame parser behind DeserializeView / DeserializeBorrow: the
// two receive paths differ ONLY in how an aligned payload blob is
// minted (a Blob::View sharing a vector slab vs a Blob::Borrow over
// registered arena bytes), so the bounds discipline — the hostile
// num_blobs cap, per-blob length validation, the 8-aligned view-vs-copy
// split, and the exact-consumption check — is written once and cannot
// drift between engines.  `align` is the frame's offset inside its
// 8-aligned slab (alignment is a slab property, not a frame property).
template <typename MakeBlob>
bool ParseWireFrame(const char* base, size_t align, size_t len,
                    Message* out, MakeBlob&& make_blob) {
  WireHeader h;
  std::memcpy(&h, base, sizeof(h));
  out->AdoptWireHeader(h);
  out->data.clear();
  out->timing = TimingTrail{};
  out->audit = AuditStamp{};
  out->qos = QosStamp{};
  out->qos_deadline_ns = 0;
  size_t pos = sizeof(h);
  // Optional latency trail (docs/observability.md): present iff the
  // sender set kHasTiming — an old-header frame parses exactly as
  // before, and a flagged frame too short to hold the trail is
  // malformed, not a silent misparse of blob bytes as timestamps.
  if (out->has_timing()) {
    if (len < pos + sizeof(TimingTrail)) return false;
    std::memcpy(&out->timing, base + pos, sizeof(TimingTrail));
    pos += sizeof(TimingTrail);
  }
  // Optional delivery-audit stamp (docs/observability.md "audit
  // plane"): same version-tolerance discipline as the trail.
  if (out->has_audit()) {
    if (len < pos + sizeof(AuditStamp)) return false;
    std::memcpy(&out->audit, base + pos, sizeof(AuditStamp));
    pos += sizeof(AuditStamp);
  }
  // Optional tenant QoS/deadline stamp (docs/serving.md "tail"): same
  // version-tolerance discipline as the trail and audit stamp.
  if (out->has_qos()) {
    if (len < pos + sizeof(QosStamp)) return false;
    std::memcpy(&out->qos, base + pos, sizeof(QosStamp));
    pos += sizeof(QosStamp);
  }
  // num_blobs comes off the wire: bound it against the frame BEFORE the
  // reserve — each blob costs at least its 8-byte length prefix, so a
  // frame of `len` bytes cannot hold more than (len - header)/8 blobs.
  // An unchecked reserve would let a 56-byte hostile frame claim
  // INT32_MAX blobs and force a multi-GB allocation the frame caps
  // exist to prevent.
  if (h.num_blobs < 0 ||
      static_cast<size_t>(h.num_blobs) > (len - pos) / sizeof(int64_t))
    return false;
  out->data.reserve(static_cast<size_t>(h.num_blobs));
  for (int32_t i = 0; i < h.num_blobs; ++i) {
    if (pos + sizeof(int64_t) > len) return false;
    int64_t blen;
    std::memcpy(&blen, base + pos, sizeof(blen));
    pos += sizeof(blen);
    if (blen < 0 || pos + static_cast<size_t>(blen) > len) return false;
    // Zero-copy only at 8-aligned payload offsets: consumers read
    // blobs as typed float/int32/int64 arrays (As<T>), and a view
    // following an odd-length blob would hand them a misaligned
    // pointer (UB, and a real fault on strict architectures).  The
    // hot path — one large payload right after the 8-aligned header —
    // always qualifies; small trailing blobs behind odd-length keys
    // pay a copy instead.
    if ((align + pos) % 8 == 0) {
      out->data.push_back(make_blob(pos, static_cast<size_t>(blen)));
    } else {
      out->data.emplace_back(base + pos, static_cast<size_t>(blen));
    }
    pos += static_cast<size_t>(blen);
  }
  return pos == len;
}

}  // namespace

bool Message::DeserializeView(std::shared_ptr<std::vector<char>> slab,
                              size_t off, size_t len, Message* out) {
  if (len < sizeof(WireHeader) || off + len > slab->size()) return false;
  const char* base = slab->data() + off;
  return ParseWireFrame(base, off, len, out,
                        [&](size_t pos, size_t blen) {
                          return Blob::View(slab, off + pos, blen);
                        });
}

bool Message::DeserializeBorrow(const char* frame, size_t align, size_t len,
                                const std::shared_ptr<void>& keepalive,
                                Message* out) {
  if (frame == nullptr || len < sizeof(WireHeader)) return false;
  return ParseWireFrame(frame, align, len, out,
                        [&](size_t pos, size_t blen) {
                          return Blob::Borrow(frame + pos, blen, keepalive);
                        });
}

Message Message::Deserialize(const Blob& buf) {
  Message m;
  const char* p = buf.data();
  WireHeader h;
  std::memcpy(&h, p, sizeof(h));
  p += sizeof(h);
  m.AdoptWireHeader(h);
  if (m.has_timing()) {
    std::memcpy(&m.timing, p, sizeof(m.timing));
    p += sizeof(m.timing);
  }
  if (m.has_audit()) {
    std::memcpy(&m.audit, p, sizeof(m.audit));
    p += sizeof(m.audit);
  }
  if (m.has_qos()) {
    std::memcpy(&m.qos, p, sizeof(m.qos));
    p += sizeof(m.qos);
  }
  m.data.reserve(static_cast<size_t>(h.num_blobs));
  for (int32_t i = 0; i < h.num_blobs; ++i) {
    int64_t len;
    std::memcpy(&len, p, sizeof(len));
    p += sizeof(len);
    m.data.emplace_back(p, static_cast<size_t>(len));
    p += len;
  }
  return m;
}

}  // namespace mvtpu
