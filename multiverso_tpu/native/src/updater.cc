#include "mvtpu/updater.h"

namespace mvtpu {

UpdaterType UpdaterFromName(const std::string& name) {
  if (name == "sgd") return UpdaterType::kSGD;
  if (name == "adagrad") return UpdaterType::kAdaGrad;
  if (name == "momentum") return UpdaterType::kMomentum;
  if (name == "smooth_gradient") return UpdaterType::kSmoothGradient;
  if (name == "assign") return UpdaterType::kAssign;
  return UpdaterType::kDefault;
}

bool IsUpdaterName(const std::string& name) {
  return name == "default" || name == "add" || name == "sgd" ||
         name == "adagrad" || name == "momentum" ||
         name == "smooth_gradient" || name == "assign";
}

void ApplyUpdate(UpdaterType t, const AddOption& opt, float* w, float* slot0,
                 const float* delta, size_t n) {
  const float lr = opt.learning_rate;
  switch (t) {
    case UpdaterType::kDefault:
      for (size_t i = 0; i < n; ++i) w[i] += delta[i];
      break;
    case UpdaterType::kSGD:
      for (size_t i = 0; i < n; ++i) w[i] -= lr * delta[i];
      break;
    case UpdaterType::kAdaGrad:
      for (size_t i = 0; i < n; ++i) {
        slot0[i] += delta[i] * delta[i];
        w[i] -= lr * delta[i] / (sqrtf(slot0[i]) + opt.eps);
      }
      break;
    case UpdaterType::kMomentum:
      for (size_t i = 0; i < n; ++i) {
        slot0[i] = opt.momentum * slot0[i] + lr * delta[i];
        w[i] -= slot0[i];
      }
      break;
    case UpdaterType::kSmoothGradient:
      for (size_t i = 0; i < n; ++i) {
        slot0[i] = opt.rho * slot0[i] + (1.0f - opt.rho) * delta[i];
        w[i] -= lr * slot0[i];
      }
      break;
    case UpdaterType::kAssign:
      // Stored bits == pushed bits: the offload bridge's bit-exactness
      // contract (docs/host_bridge.md) rests on this memcpy semantics.
      for (size_t i = 0; i < n; ++i) w[i] = delta[i];
      break;
  }
}

}  // namespace mvtpu
