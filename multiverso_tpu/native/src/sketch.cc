#include "mvtpu/sketch.h"

#include <algorithm>
#include <sstream>

#include "mvtpu/configure.h"

namespace mvtpu {
namespace workload {

namespace {

// Armed by default (the `-hotkey_enabled` flag default); Zoo::Start
// re-latches from the parsed flags, MV_SetHotKeyTracking toggles live.
std::atomic<bool> g_armed{true};

// Replica disarmed by default (the `-hotkey_replica` flag default):
// serving reads from a side table is an opt-in semantics choice, not
// free observability.
std::atomic<bool> g_replica_armed{false};

// Minimal JSON string escape for key labels (KV keys are caller data).
std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) out += ' ';
        else out += c;
    }
  }
  return out;
}

}  // namespace

bool Armed() { return g_armed.load(std::memory_order_relaxed); }
void Arm(bool on) { g_armed.store(on, std::memory_order_relaxed); }

bool ReplicaArmed() {
  return g_replica_armed.load(std::memory_order_relaxed);
}
void ArmReplica(bool on) {
  g_replica_armed.store(on, std::memory_order_relaxed);
}

uint64_t KeyHash(const void* data, size_t n) {
  // FNV-1a 64 — identical to table.h KVHash and the Python mirror
  // (multiverso_tpu/sketch.py), so per-rank CountMin cells line up and
  // fleet merges estimate the same key the same way everywhere.
  const unsigned char* p = static_cast<const unsigned char*>(data);
  uint64_t h = 1469598103934665603ull;
  for (size_t i = 0; i < n; ++i) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  return h;
}

// ------------------------------------------------------------ SpaceSaving

SpaceSaving::SpaceSaving(int k) : k_(std::max(1, k)) {
  entries_.reserve(static_cast<size_t>(k_));
}

int SpaceSaving::IndexOf(uint64_t hash) const {
  auto it = index_.find(hash);
  return it == index_.end() ? -1 : it->second;
}

int SpaceSaving::FindMin() const {
  int min_i = 0;
  for (size_t i = 1; i < entries_.size(); ++i)
    if (entries_[i].count < entries_[min_i].count)
      min_i = static_cast<int>(i);
  return min_i;
}

void SpaceSaving::Offer(uint64_t hash, const std::string& label,
                        int64_t n) {
  total_ += n;
  int slot = IndexOf(hash);
  if (slot >= 0) {
    entries_[static_cast<size_t>(slot)].count += n;
    return;
  }
  if (static_cast<int>(entries_.size()) < k_) {
    entries_.push_back(Entry{label, hash, n, 0});
    index_.emplace(hash, static_cast<int>(entries_.size()) - 1);
    return;
  }
  // Evict the minimum counter: the newcomer inherits its count as
  // `error` — the classic space-saving guarantee that any key with
  // true frequency > total/K is monitored.
  int min_i = FindMin();
  Entry& e = entries_[static_cast<size_t>(min_i)];
  index_.erase(e.hash);
  e.error = e.count;       // everything below could belong to the evictee
  e.count += n;
  e.hash = hash;
  e.label = label;
  index_.emplace(hash, min_i);
}

std::vector<SpaceSaving::Entry> SpaceSaving::TopK() const {
  std::vector<Entry> out = entries_;
  std::sort(out.begin(), out.end(),
            [](const Entry& a, const Entry& b) { return a.count > b.count; });
  return out;
}

void SpaceSaving::Merge(const SpaceSaving& other) {
  for (const auto& e : other.entries_) {
    int slot = IndexOf(e.hash);
    if (slot >= 0) {
      entries_[static_cast<size_t>(slot)].count += e.count;
      entries_[static_cast<size_t>(slot)].error += e.error;
      total_ += e.count;
      continue;
    }
    Offer(e.hash, e.label, e.count);
    int now = IndexOf(e.hash);
    if (now >= 0)
      entries_[static_cast<size_t>(now)].error += e.error;
  }
}

// --------------------------------------------------------------- CountMin

CountMin::CountMin(int width, int depth)
    : width_(std::max(8, width)), depth_(std::max(1, depth)),
      cells_(static_cast<size_t>(width_) * static_cast<size_t>(depth_)) {
  for (auto& c : cells_) c.store(0, std::memory_order_relaxed);
}

uint64_t CountMin::RowHash(int row, uint64_t hash) const {
  // Distinct per-row hash families via a splitmix64 finalize of
  // (hash ^ row-salt) — cheap and well-mixed.
  uint64_t x = hash ^ (0x9e3779b97f4a7c15ull *
                       static_cast<uint64_t>(row + 1));
  x ^= x >> 30;
  x *= 0xbf58476d1ce4e5b9ull;
  x ^= x >> 27;
  x *= 0x94d049bb133111ebull;
  x ^= x >> 31;
  return x;
}

void CountMin::Add(uint64_t hash, int64_t n) {
  for (int r = 0; r < depth_; ++r) {
    size_t cell = static_cast<size_t>(r) * static_cast<size_t>(width_) +
                  RowHash(r, hash) % static_cast<uint64_t>(width_);
    cells_[cell].fetch_add(n, std::memory_order_relaxed);
  }
  total_.fetch_add(n, std::memory_order_relaxed);
}

int64_t CountMin::Estimate(uint64_t hash) const {
  int64_t est = INT64_MAX;
  for (int r = 0; r < depth_; ++r) {
    size_t cell = static_cast<size_t>(r) * static_cast<size_t>(width_) +
                  RowHash(r, hash) % static_cast<uint64_t>(width_);
    est = std::min(est, cells_[cell].load(std::memory_order_relaxed));
  }
  return est == INT64_MAX ? 0 : est;
}

// ----------------------------------------------------------- HotKeyTracker

HotKeyTracker::HotKeyTracker() = default;

void HotKeyTracker::Note(uint64_t hash, const std::string& label,
                         int64_t n) {
  if (!Armed()) return;
  cm_.Add(hash, n);
  MutexLock lk(mu_);
  if (!ss_) {
    int k = static_cast<int>(
        configure::Has("hotkey_topk") ? configure::GetInt("hotkey_topk")
                                      : 16);
    ss_ = std::make_unique<SpaceSaving>(k);
  }
  ss_->Offer(hash, label, n);
}

std::vector<HotKeyTracker::Item> HotKeyTracker::TopK() const {
  std::vector<Item> out;
  MutexLock lk(mu_);
  if (!ss_) return out;
  for (const auto& e : ss_->TopK())
    out.push_back(Item{e.label, e.count, e.error, cm_.Estimate(e.hash)});
  return out;
}

std::string HotKeyTracker::Json() const {
  std::ostringstream os;
  os << "{\"total\":" << total() << ",\"topk\":[";
  bool first = true;
  for (const auto& it : TopK()) {
    if (!first) os << ',';
    first = false;
    os << "{\"key\":\"" << JsonEscape(it.label) << "\",\"count\":"
       << it.count << ",\"error\":" << it.error << ",\"estimate\":"
       << it.estimate << "}";
  }
  os << "]}";
  return os.str();
}

}  // namespace workload
}  // namespace mvtpu
