// Stall watchdog (mvtpu/watchdog.h) — progress counters + a low-rate
// checker that turns "alive process, dead loop" into a blackbox dump.
#include "mvtpu/watchdog.h"

#include <atomic>
#include <chrono>
#include <cstdio>
#include <memory>
#include <string>
#include <thread>
#include <unordered_map>
#include <utility>
#include <vector>

#include "mvtpu/dashboard.h"
#include "mvtpu/mutex.h"
#include "mvtpu/ops.h"
#include "mvtpu/profiler.h"

namespace mvtpu {
namespace watchdog {

namespace {

using Clock = std::chrono::steady_clock;

struct Loop {
  std::string name;
  std::atomic<long long> progress{0};
  std::atomic<long long> queued{0};
  std::atomic<long long> stalls{0};
  std::atomic<bool> stalled{false};
  // Checker-thread-local bookkeeping (only the checker reads/writes):
  long long seen_progress = 0;
  Clock::time_point seen_at{};
};

// Armed state on the hot path is ONE relaxed load — a disarmed
// watchdog (the default) costs nothing measurable anywhere.
std::atomic<int> g_stall_ms{0};

Mutex g_mu;
// Loops register once and live until Reset(); unique_ptr keeps the
// Loop address stable across map rehashes so the atomics stay valid
// outside the lock.
std::unordered_map<std::string, std::unique_ptr<Loop>> g_loops
    GUARDED_BY(g_mu);
std::thread g_checker GUARDED_BY(g_mu);
std::atomic<bool> g_checker_run{false};

std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c & 0xff);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

Loop* FindOrCreate(const std::string& name) {
  MutexLock lock(g_mu);
  auto it = g_loops.find(name);
  if (it != g_loops.end()) return it->second.get();
  auto loop = std::make_unique<Loop>();
  loop->name = name;
  loop->seen_at = Clock::now();
  Loop* raw = loop.get();
  g_loops.emplace(name, std::move(loop));
  return raw;
}

struct Stall {
  std::string loop;
  long long age_ms;
  long long queued;
};

// One checker pass: flag every loop with queued work and zero progress
// past the deadline.  Stalls are COLLECTED under the map lock and
// fired after it drops — BlackboxTrigger/DumpFolded take their own
// locks and must never nest inside g_mu.
void CheckOnce(int stall_ms) {
  std::vector<Stall> fired;
  Clock::time_point now = Clock::now();
  {
    MutexLock lock(g_mu);
    for (auto& kv : g_loops) {
      Loop* l = kv.second.get();
      long long progress = l->progress.load(std::memory_order_relaxed);
      if (progress != l->seen_progress) {
        l->seen_progress = progress;
        l->seen_at = now;
        l->stalled.store(false, std::memory_order_relaxed);
        continue;
      }
      long long queued = l->queued.load(std::memory_order_relaxed);
      long long age_ms = std::chrono::duration_cast<
          std::chrono::milliseconds>(now - l->seen_at).count();
      if (queued > 0 && age_ms >= static_cast<long long>(stall_ms) &&
          !l->stalled.load(std::memory_order_relaxed)) {
        l->stalled.store(true, std::memory_order_relaxed);
        l->stalls.fetch_add(1, std::memory_order_relaxed);
        fired.push_back(Stall{l->name, age_ms, queued});
      }
    }
  }
  for (const Stall& s : fired) {
    Dashboard::Record("watchdog.stalls", 0.0);
    char buf[256];
    std::snprintf(buf, sizeof(buf),
                  "stall: %s no progress for %lldms, queue=%lld",
                  s.loop.c_str(), s.age_ms, s.queued);
    ops::BlackboxEvent("watchdog_stall", buf);
    // The folded stacks name WHERE the loop is stuck; with the
    // profiler disarmed this is just an empty dump, still cheap.
    ops::BlackboxEvent("watchdog_stacks", profiler::DumpFolded());
    ops::BlackboxTrigger(buf);
  }
}

void CheckerLoop(int stall_ms) {
  int period_ms = stall_ms / 4;
  if (period_ms < 10) period_ms = 10;
  if (period_ms > 1000) period_ms = 1000;
  while (g_checker_run.load(std::memory_order_acquire)) {
    std::this_thread::sleep_for(std::chrono::milliseconds(period_ms));
    if (!g_checker_run.load(std::memory_order_acquire)) break;
    CheckOnce(stall_ms);
  }
}

void StopChecker() {
  std::thread joinme;
  {
    MutexLock lock(g_mu);
    g_checker_run.store(false, std::memory_order_release);
    joinme = std::move(g_checker);
  }
  if (joinme.joinable()) joinme.join();
}

}  // namespace

void Arm(int stall_ms) {
  StopChecker();
  if (stall_ms <= 0) {
    g_stall_ms.store(0, std::memory_order_release);
    return;
  }
  g_stall_ms.store(stall_ms, std::memory_order_release);
  MutexLock lock(g_mu);
  // Re-baseline every loop so a pre-arm quiet period never reads as an
  // instant stall.
  Clock::time_point now = Clock::now();
  for (auto& kv : g_loops) {
    Loop* l = kv.second.get();
    l->seen_progress = l->progress.load(std::memory_order_relaxed);
    l->seen_at = now;
    l->stalled.store(false, std::memory_order_relaxed);
  }
  g_checker_run.store(true, std::memory_order_release);
  g_checker = std::thread(CheckerLoop, stall_ms);
}

bool Armed() {
  return g_stall_ms.load(std::memory_order_relaxed) > 0;
}

void Bump(const std::string& loop) {
  if (!Armed()) return;
  FindOrCreate(loop)->progress.fetch_add(1, std::memory_order_relaxed);
}

void Busy(const std::string& loop, long long queued) {
  if (!Armed()) return;
  FindOrCreate(loop)->queued.store(queued, std::memory_order_relaxed);
}

std::string StatsJson() {
  Clock::time_point now = Clock::now();
  std::string out = "[";
  MutexLock lock(g_mu);
  bool first = true;
  for (auto& kv : g_loops) {
    Loop* l = kv.second.get();
    long long age_ms = std::chrono::duration_cast<
        std::chrono::milliseconds>(now - l->seen_at).count();
    bool stalled = l->stalled.load(std::memory_order_relaxed);
    char buf[320];
    std::snprintf(
        buf, sizeof(buf),
        "%s{\"loop\":\"%s\",\"progress\":%lld,\"queued\":%lld,"
        "\"stalls\":%lld,\"stalled\":%s,\"age_s\":%.3f,"
        "\"stalled_s\":%.3f}",
        first ? "" : ",", JsonEscape(l->name).c_str(),
        l->progress.load(std::memory_order_relaxed),
        l->queued.load(std::memory_order_relaxed),
        l->stalls.load(std::memory_order_relaxed),
        stalled ? "true" : "false",
        static_cast<double>(age_ms) / 1e3,
        stalled ? static_cast<double>(age_ms) / 1e3 : 0.0);
    out += buf;
    first = false;
  }
  out += "]";
  return out;
}

long long StallCount() {
  MutexLock lock(g_mu);
  long long total = 0;
  for (auto& kv : g_loops)
    total += kv.second->stalls.load(std::memory_order_relaxed);
  return total;
}

void Reset() {
  StopChecker();
  g_stall_ms.store(0, std::memory_order_release);
  MutexLock lock(g_mu);
  g_loops.clear();
}

}  // namespace watchdog
}  // namespace mvtpu
