#include "mvtpu/configure.h"

#include <map>
#include <mutex>
#include <stdexcept>

#include "mvtpu/mutex.h"

namespace mvtpu {
namespace configure {

namespace {

enum class Kind { kBool, kInt, kDouble, kString };

struct Flag {
  Kind kind;
  std::string value;
  std::string dflt;
  std::string help;
};

Mutex g_mu;

// The registry map lives behind a function-local static (first use may
// precede any other global's ctor); REQUIRES is the enforcement point —
// the map itself is only reachable through these two accessors.
std::map<std::string, Flag>& Registry() REQUIRES(g_mu) {
  static std::map<std::string, Flag> r;
  return r;
}

void Define(const std::string& name, Kind kind, const std::string& dflt,
            const std::string& help) {
  MutexLock lk(g_mu);
  Registry()[name] = Flag{kind, dflt, dflt, help};
}

Flag& Find(const std::string& name) REQUIRES(g_mu) {
  auto it = Registry().find(name);
  if (it == Registry().end())
    throw std::invalid_argument("unknown flag: " + name);
  return it->second;
}

void Validate(Kind kind, const std::string& value) {
  size_t pos = 0;
  switch (kind) {
    case Kind::kBool:
      if (value != "true" && value != "false" && value != "1" && value != "0")
        throw std::invalid_argument("bad bool: " + value);
      break;
    case Kind::kInt:
      (void)std::stoll(value, &pos);
      if (pos != value.size()) throw std::invalid_argument("bad int: " + value);
      break;
    case Kind::kDouble:
      (void)std::stod(value, &pos);
      if (pos != value.size())
        throw std::invalid_argument("bad double: " + value);
      break;
    case Kind::kString:
      break;
  }
}

}  // namespace

void DefineBool(const std::string& n, bool d, const std::string& h) {
  Define(n, Kind::kBool, d ? "true" : "false", h);
}
void DefineInt(const std::string& n, long long d, const std::string& h) {
  Define(n, Kind::kInt, std::to_string(d), h);
}
void DefineDouble(const std::string& n, double d, const std::string& h) {
  Define(n, Kind::kDouble, std::to_string(d), h);
}
void DefineString(const std::string& n, const std::string& d,
                  const std::string& h) {
  Define(n, Kind::kString, d, h);
}

bool GetBool(const std::string& n) {
  MutexLock lk(g_mu);
  const std::string& v = Find(n).value;
  return v == "true" || v == "1";
}
long long GetInt(const std::string& n) {
  MutexLock lk(g_mu);
  return std::stoll(Find(n).value);
}
double GetDouble(const std::string& n) {
  MutexLock lk(g_mu);
  return std::stod(Find(n).value);
}
std::string GetString(const std::string& n) {
  MutexLock lk(g_mu);
  return Find(n).value;
}

bool Has(const std::string& n) {
  MutexLock lk(g_mu);
  return Registry().count(n) > 0;
}

void Set(const std::string& n, const std::string& value) {
  MutexLock lk(g_mu);
  Flag& f = Find(n);
  Validate(f.kind, value);
  f.value = value;
}

int ParseCmdFlags(int argc, const char* const* argv) {
  int parsed = 0;
  for (int i = 0; i < argc; ++i) {
    std::string a = argv[i] ? argv[i] : "";
    if (a.rfind("--", 0) == 0) a = a.substr(2);
    else if (a.rfind("-", 0) == 0) a = a.substr(1);
    else continue;  // non-flag argv entries are ignored (reference behavior)
    auto eq = a.find('=');
    if (eq == std::string::npos) continue;
    try {
      Set(a.substr(0, eq), a.substr(eq + 1));
      ++parsed;
    } catch (const std::invalid_argument&) {
      return -1;
    }
  }
  return parsed;
}

void Reset() {
  MutexLock lk(g_mu);
  for (auto& kv : Registry()) kv.second.value = kv.second.dflt;
}

// Contract-checked: tools/mvcontract.py (`make contract`) diffs these
// registrations against config.py and the docs/*.md flag tables — a
// flag shared with the Python plane must keep the same default.
void RegisterDefaults() {
  static std::once_flag once;
  std::call_once(once, [] {
    DefineBool("sync", false, "BSP (true) vs ASP (false) training");
    DefineString("updater_type", "default",
                 "default|sgd|adagrad|momentum|smooth_gradient|assign "
                 "(assign: w = delta, last-write-wins — the offload "
                 "bridge's bit-exact remote store, docs/host_bridge.md)");
    DefineString("machine_file", "",
                 "host:port per line; >1 line enables the TCP transport");
    DefineString("net_type", "tcp",
                 "tcp|mpi — wire transport (reference net.h NetLib). mpi "
                 "dlopen's libmpi: rank/size come from MPI (mpirun for "
                 ">1 node; isolated singleton otherwise), no machine "
                 "file needed");
    DefineString("net_engine", "epoll",
                 "tcp|epoll|mpi|uring — readiness model of the wire "
                 "transport (docs/transport.md).  epoll (default): one "
                 "event-loop reactor (plus -net_threads shards) drives "
                 "nonblocking sockets and accepts ANONYMOUS serve "
                 "clients; tcp: the blocking thread-per-connection "
                 "engine; mpi: the literal MPI wire (same as "
                 "-net_type=mpi); uring: the io_uring completion "
                 "engine — registered-buffer zero-copy receive, "
                 "zero-copy sends, multishot accept; degrades to epoll "
                 "(logged, health `effective_engine`) when the kernel "
                 "lacks io_uring");
    DefineInt("net_threads", 1,
              "epoll engine: number of reactor shards (event-loop "
              "threads); connections round-robin across them.  1 "
              "(default) is right below ~10k connections");
    DefineInt("net_arena_bytes", 262144,
              "epoll engine: receive-arena slab size per connection; "
              "frames assemble in the slab and decode zero-copy "
              "(Blob views).  Larger frames allocate exactly; smaller "
              "ones pack and the slab recycles once no view is alive");
    DefineInt("net_writeq_bytes", 67108864,
              "epoll engine: per-connection write-queue bound.  A slow "
              "reader fills it; senders then wait for drain up to "
              "-io_timeout_ms (the readiness-model twin of SO_SNDTIMEO) "
              "instead of ballooning memory.  <=0 unbounded");
    DefineInt("uring_depth", 256,
              "uring engine: submission-queue entries per reactor shard "
              "(clamped 8..4096; CQ sized 4x).  The depth bounds "
              "in-flight SQEs, not connections — a full SQ flushes and "
              "retries");
    DefineBool("uring_sqpoll", false,
               "uring engine: IORING_SETUP_SQPOLL — a kernel thread "
               "polls the submission queue, removing the submit syscall "
               "from the send path at the cost of a busy kernel thread "
               "per shard (needs CAP_SYS_NICE on older kernels; setup "
               "failure falls back to plain submission)");
    DefineInt("uring_reg_bufs", 16,
              "uring engine: fixed receive buffers registered with the "
              "kernel per shard (each -net_arena_bytes big, carved from "
              "the host arena).  Frames landing in one decode zero-copy "
              "end to end; 0 disables registration (heap fallback "
              "only).  Clamped 0..1024");
    DefineInt("uring_zc_bytes", 65536,
              "uring engine: frames with at least this many bytes left "
              "to send go out IORING_OP_SENDMSG_ZC (pages pinned until "
              "the kernel's notif completion) instead of a copying "
              "send.  <0 disables zero-copy sends");
    DefineInt("client_inflight_max", 64,
              "epoll engine: per-anonymous-client admission on top of "
              "-server_inflight_max — a client with this many "
              "unanswered Gets/probes is shed with ReplyBusy at the "
              "reactor, before the actor mailbox.  Adds are never "
              "shed.  <=0 disables");
    DefineInt("rank", 0, "this process's line index in machine_file");
    DefineString("controller_endpoint", "",
                 "dynamic registration: rank 0's host:port (no machine "
                 "file / -rank needed; reference Control_Register)");
    DefineBool("is_controller", false,
               "this process IS the registration controller (rank 0)");
    DefineInt("num_nodes", 0, "dynamic registration: total process count");
    DefineString("role", "all", "worker|server|all — this node's roles");
    DefineString("node_host", "127.0.0.1",
                 "dynamic registration: address peers reach this node at");
    DefineInt("port", 55555, "base port (transport parity flag)");
    DefineDouble("backup_worker_ratio", 0.0,
                 "sync-plane straggler slack: clock t counts as reached "
                 "once ceil((1-ratio)*workers) ticked it; the slowest "
                 "floor(ratio*workers) cannot park reads (their late "
                 "adds fold into the open clock)");
    DefineInt("staleness", 0,
              "SSP bound: a worker's Get is held while it runs more than "
              "this many MV_Clock() ticks ahead of the slowest worker "
              "(0 = per-clock rendezvous on read; clocks start equal so "
              "jobs that never call MV_Clock are unaffected)");
    DefineInt("rpc_timeout_ms", 30000,
              "blocking Get/Add deadline; <=0 waits forever");
    DefineInt("connect_retry_ms", 15000,
              "per-destination connect retry budget");
    DefineInt("barrier_timeout_ms", 0,
              "barrier deadline; <=0 (default) waits forever (BSP)");
    DefineInt("io_timeout_ms", 30000,
              "per-socket send deadline + mid-frame recv deadline: a "
              "peer that wedges mid-message errors out instead of "
              "parking the thread; <=0 disables");
    DefineInt("send_retries", 2,
              "bounded wire-send retries after a failed write "
              "(reconnect between attempts); 0 fails on first error");
    DefineInt("send_backoff_ms", 50,
              "base exponential backoff between send retries");
    DefineInt("heartbeat_ms", 0,
              "liveness lease interval: non-zero ranks announce to "
              "rank 0 every interval, rank 0 reports silent peers "
              "(Dashboard hb.missed); 0 (default) disables");
    DefineInt("heartbeat_timeout_ms", 0,
              "lease expiry; <=0 derives 5*heartbeat_ms");
    DefineInt("server_inflight_max", 0,
              "serve backpressure (docs/serving.md): when the server "
              "actor's mailbox backlog reaches this, incoming Gets and "
              "version probes are shed with a retryable ReplyBusy (C "
              "API rc -6) instead of growing the queue; adds are never "
              "shed.  0 (default) disables shedding");
    DefineString("wire_codec", "raw",
                 "payload codec for table wire traffic "
                 "(docs/wire_compression.md): raw|1bit|sparse.  1bit "
                 "ships dense adds as sign bits + two scales with "
                 "worker-side error feedback (~32x fewer payload "
                 "bytes); sparse ships nonzero (index,value) pairs "
                 "losslessly, falling back to raw per message when not "
                 "smaller.  Negotiated per table at creation; "
                 "MV_SetTableCodec retargets one table");
    DefineInt("add_agg_ms", 0,
              "worker-side add aggregation window (ms): async dense "
              "adds within the window sum locally and ship as ONE "
              "codec-encoded wire message.  Flushed by the window "
              "(checked at the next table op), -add_agg_bytes, any "
              "Get, blocking Add, Clock, Barrier, and shutdown — "
              "BSP/SSP visibility is unchanged.  0 (default) with "
              "add_agg_bytes=0 disables aggregation");
    DefineInt("add_agg_bytes", 0,
              "worker-side add aggregation size bound: flush once the "
              "absorbed payload bytes (adds x delta size) reach this. "
              "0 (default) with add_agg_ms=0 disables aggregation");
    DefineString("log_level", "info", "debug|info|error|fatal");
    DefineString("log_file", "", "optional log sink path");
    DefineBool("trace", false,
               "record per-op spans (worker Get/Add, server apply, wire "
               "send) with cross-rank trace ids; dump via MV_DumpSpans "
               "(docs/observability.md)");
    DefineString("trace_dir", "",
                 "introspection output dir (docs/observability.md): the "
                 "flight recorder dumps blackbox_rank<r>.json here on "
                 "failure triggers (barrier timeout, dead peer, shed "
                 "storm).  Empty (default) disables dumps; events still "
                 "accumulate in the in-memory ring");
    DefineInt("blackbox_events", 512,
              "flight-recorder ring capacity (lifecycle events kept in "
              "memory; dumped with recent spans + monitor totals on a "
              "trigger)");
    DefineInt("ops_fleet_timeout_ms", 2000,
              "fleet-scope OpsQuery fan-out deadline: rank answers with "
              "whatever peers replied by then, explicitly marking the "
              "silent ranks instead of hanging the scraper");
    DefineInt("ops_inflight_max", 4,
              "concurrent fleet-scope OpsQuery aggregations; excess "
              "queries are answered with a busy error document instead "
              "of spawning unbounded fan-out threads");
    DefineBool("hotkey_enabled", true,
               "workload observability (docs/observability.md): per-table "
               "hot-key sketches (space-saving top-K + count-min), "
               "per-bucket get/add load counters, observed-staleness "
               "histogram, and add L2/Linf + NaN/Inf health sentinels in "
               "the server hot path.  false compiles every hook down to "
               "one relaxed atomic check (MV_SetHotKeyTracking toggles "
               "live for A/B overhead measurement)");
    DefineBool("capacity_enabled", true,
               "capacity plane (docs/observability.md \"capacity "
               "plane\"): per-table resident-byte accounting (matrix "
               "rows, KV entries + key bytes, array spans) per bucket "
               "and per shard, recomputed incrementally on the hot "
               "path.  false compiles every growth hook down to one "
               "relaxed atomic check; MV_SetCapacityTracking toggles "
               "live (re-arming resyncs every shard exactly)");
    DefineInt("capacity_history_ms", 250,
              "minimum interval between capacity load-history windows: "
              "each \"capacity\" scrape at least this far from the "
              "last appends one (ts, gets, adds, bytes, per-bucket "
              "load) window to the bounded 64-window ring, so one "
              "scrape yields per-bucket load RATES (the placement "
              "advisor's input).  <= 0 records every scrape");
    DefineInt("hotkey_topk", 16,
              "capacity of the space-saving top-K hot-key sketch per "
              "server table (memory bound: this many monitored keys; "
              "every true heavy hitter with frequency > total/K is "
              "guaranteed monitored)");
    DefineBool("hotkey_replica", false,
               "hot-key read replica (docs/embedding.md): matrix worker "
               "stubs keep a side table of the servers' pushed "
               "SpaceSaving top-K rows and serve GetRows hits from it "
               "before the wire; invalidation rides the version-stamp "
               "protocol (an entry older than last_version - "
               "-replica_max_staleness misses).  Requires "
               "-hotkey_enabled (the push IS the top-K sketch); "
               "MV_SetHotKeyReplica toggles live");
    DefineInt("replica_lease_ms", 50,
              "hot-key replica snapshot lease: GetRows refreshes the "
              "pushed row set (one RequestReplica round trip per shard) "
              "once the snapshot ages past this; entries are never "
              "served from a snapshot older than the lease");
    DefineInt("replica_max_staleness", 0,
              "version distance a replica-served row may be behind the "
              "last observed apply (the worker's reply-stamp ledger); "
              "0 = a row older than ANY later observed add misses — "
              "staleness-0 reads after an acked add always refetch");
    DefineBool("arena_pin", true,
               "host bridge (docs/host_bridge.md): mlock(2) HostArena "
               "buffers so the scatter-gather send path never page-"
               "faults mid-write.  Best-effort — RLIMIT_MEMLOCK misses "
               "are counted in MV_ArenaStats, not fatal");
    DefineBool("wire_timing", true,
               "latency attribution (docs/observability.md): stamp a "
               "48-byte TimingTrail into request/reply wire headers "
               "(client enqueue/send, server recv/dequeue/apply_done/"
               "reply_send) and fold replies into lat.stage.* "
               "histograms + the per-peer NTP-style clock-offset "
               "estimator.  Version-tolerant: peers that never stamp "
               "are parsed exactly as before.  MV_SetWireTiming "
               "toggles live (the overhead A/B)");
    DefineInt("profile_hz", 0,
              "boot the SIGPROF sampling profiler at this rate "
              "(CPU-time sampling; folded stacks via MV_ProfilerDump "
              "land in the Chrome trace beside spans).  0 (default) "
              "boots disarmed; MV_SetProfiler toggles live.  97 Hz is "
              "the house rate — prime, so it cannot phase-lock with "
              "millisecond-periodic work");
    DefineInt("watchdog_stall_ms", 0,
              "stall watchdog (docs/observability.md \"health "
              "plane\"): flag any critical loop (epoll reactor "
              "shards, actors, heartbeat scan, host metrics flusher) "
              "that makes zero progress for this long while work is "
              "queued — dumps profiler folded stacks + a 'stall:' "
              "blackbox and bumps watchdog.stalls.  0 (default) "
              "disarms (every Bump is one relaxed load); must exceed "
              "the slowest legitimate loop period.  MV_SetWatchdog "
              "toggles live");
    DefineBool("audit", true,
               "delivery-audit plane (docs/observability.md \"audit "
               "plane\"): stamp every Add with a per-(worker, table, "
               "shard) seq range behind a wire flag, keep client "
               "acked-add ledgers + server per-origin applied "
               "watermarks with dup/reorder/gap anomaly rings, and "
               "serve the \"audit\" OpsQuery kind.  false compiles "
               "every site down to one relaxed atomic load "
               "(MV_SetAudit toggles live — the overhead A/B)");
    DefineInt("replication_factor", 0,
              "shard replication (docs/replication.md): 0 (default) = "
              "off — a dead server rank is fatal for its shard; 1 = "
              "every shard gets a backup rank (chained: shard i's "
              "backup is server i+1 mod n) fed by a primary->backup "
              "ReplForward delta stream, with lease-triggered "
              "promotion and routing-epoch re-pointing on failure");
    DefineBool("repl_sync", true,
               "sync replication: park the client's add ack until the "
               "backup's ReplAck, so \"acked\" means applied on BOTH "
               "replicas — zero lost acked adds across a failover by "
               "construction.  false = ack immediately and only bound "
               "the forward/ack gap at -repl_lag_max (faster, a "
               "just-acked add can die with the primary)");
    DefineInt("repl_lag_max", 64,
              "async replication lag bound: with -repl_sync=false, "
              "stall the apply path while this many forwards are "
              "unacked by the backup (measured by the repl.lag "
              "histogram; <=0 = unbounded)");
    DefineBool("promote_auto", true,
               "lease-triggered promotion: when a watched peer's "
               "heartbeat lease expires and this rank backs a shard "
               "the corpse owned, promote it automatically (false = "
               "operator-driven via MV_PromoteBackup / MsgType::"
               "Promote only)");
    DefineInt("audit_grace_ms", 2000,
              "delivery-audit gap grace window: an out-of-order "
              "pending range older than this fires the audit_gap "
              "flight-recorder trigger (a benign reorder drains in "
              "round-trip time; a real loss never does)");
    DefineInt("audit_ring", 64,
              "delivery-audit anomaly ring capacity per server table "
              "(recent dup/reorder/gap records with their seq ranges "
              "and origins, served in the \"audit\" report)");
    DefineInt("blackbox_keep", 4,
              "flight-recorder dump rotation: keep this many "
              "timestamped blackbox_rank<r>.<ts>.json archives per "
              "rank beside the canonical latest dump (a second "
              "trigger no longer overwrites the first dump's "
              "evidence); a manifest lists the retained dumps");
    DefineString("qos_classes", "bulk:1,gold:8",
                 "tail-at-scale QoS (docs/serving.md \"tail\"): tenant "
                 "classes and weights, 'name:weight,...'.  Class ids on "
                 "the wire are POSITIONAL indices into this list (both "
                 "sides must agree, like codec negotiation); weights "
                 "split -qos_inflight_max into guaranteed per-class "
                 "read budgets and set the borrow ratio for spare "
                 "capacity");
    DefineInt("qos_inflight_max", 0,
              "per-class weighted admission over anonymous serve reads "
              "at the reactor: total inflight read slots split across "
              "-qos_classes by weight (deficit-round-robin borrowing "
              "of spare capacity); a class at its share answers "
              "ReplyBusy while other classes keep flowing.  Adds and "
              "flushes are never shed.  0 (default) disables the gate "
              "(per-class counters still accrue)");
    DefineString("qos_class", "bulk",
                 "the tenant class THIS process's worker requests "
                 "declare in their QoS wire stamp (a name from "
                 "-qos_classes; unknown names map to class 0)");
    DefineBool("wire_deadline", true,
               "deadline propagation (docs/serving.md \"tail\"): stamp "
               "worker requests with their remaining -rpc_timeout_ms "
               "budget behind a version-tolerant wire flag; receivers "
               "drop a read already past its deadline at dequeue "
               "(serve.deadline.shed) instead of burning an apply slot. "
               "Adds are never deadline-shed.  false stamps nothing");
    DefineBool("replica_serve_reactor", true,
               "answer ANONYMOUS hot-key replica pulls (RequestReplica) "
               "at the epoll reactor instead of the actor mailbox — a "
               "bounded snapshot read under the shard lock, so a hedged "
               "read can win against a straggling apply clogging the "
               "mailbox (docs/serving.md \"tail\").  Rank-peer replica "
               "refreshes keep the mailbox path either way");
    DefineInt("shed_storm_threshold", 0,
              "flight-recorder trigger: this many CONSECUTIVE busy-sheds "
              "(-server_inflight_max) dump the black box once per storm "
              "(an admit resets the streak).  0 (default) disables");
  });
}

}  // namespace configure
}  // namespace mvtpu
