#include "mvtpu/actor.h"

#include "mvtpu/log.h"

namespace mvtpu {

Actor::~Actor() { Stop(); }

void Actor::Start() {
  if (running_) return;
  running_ = true;
  thread_ = std::thread(&Actor::Main, this);
}

void Actor::Stop() {
  if (!running_) return;
  running_ = false;
  mailbox_.Exit();
  if (thread_.joinable()) thread_.join();
}

void Actor::Main() {
  MessagePtr msg;
  while (mailbox_.Pop(&msg)) {
    if (!msg) continue;
    if (msg->type == MsgType::Exit) break;
    auto it = handlers_.find(msg->type);
    if (it == handlers_.end()) {
      Log::Error("actor %s: no handler for msg type %d", name_.c_str(),
                 static_cast<int>(msg->type));
      continue;
    }
    it->second(msg);
  }
}

}  // namespace mvtpu
