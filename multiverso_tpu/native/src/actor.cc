#include "mvtpu/actor.h"

#include "mvtpu/log.h"
#include "mvtpu/watchdog.h"

namespace mvtpu {

Actor::~Actor() { Stop(); }

void Actor::Start() {
  if (running_) return;
  running_ = true;
  thread_ = std::thread(&Actor::Main, this);
}

void Actor::Stop() {
  if (!running_) return;
  running_ = false;
  mailbox_.Exit();
  if (thread_.joinable()) thread_.join();
}

void Actor::Main() {
  // Watchdog (docs/observability.md "health plane"): each dispatched
  // message is one unit of progress; queued = this message plus
  // whatever is still in the mailbox.  A handler that never returns —
  // the wedged-server-actor class of bug — shows as "actor.<name>
  // no progress" with a nonzero queue.
  const std::string wd_name = "actor." + name_;
  MessagePtr msg;
  while (mailbox_.Pop(&msg)) {
    if (!msg) continue;
    if (msg->type == MsgType::Exit) break;
    auto it = handlers_.find(msg->type);
    if (it == handlers_.end()) {
      Log::Error("actor %s: no handler for msg type %d", name_.c_str(),
                 static_cast<int>(msg->type));
      continue;
    }
    watchdog::Busy(wd_name, static_cast<long long>(mailbox_.Size()) + 1);
    it->second(msg);
    watchdog::Bump(wd_name);
    watchdog::Busy(wd_name, 0);
  }
  watchdog::Busy(wd_name, 0);
}

}  // namespace mvtpu
