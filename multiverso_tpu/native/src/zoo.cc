#include "mvtpu/zoo.h"

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <functional>
#include <map>
#include <sstream>
#include <tuple>
#include <type_traits>

#include "mvtpu/audit.h"
#include "mvtpu/capacity.h"
#include "mvtpu/codec.h"
#include "mvtpu/configure.h"
#include "mvtpu/host_arena.h"
#include "mvtpu/dashboard.h"
#include "mvtpu/fault.h"
#include "mvtpu/latency.h"
#include "mvtpu/log.h"
#include "mvtpu/profiler.h"
#include "mvtpu/mpi_net.h"
#include "mvtpu/ops.h"
#include "mvtpu/repl.h"
#include "mvtpu/qos.h"
#include "mvtpu/sketch.h"
#include "mvtpu/uring_net.h"
#include "mvtpu/waiter.h"
#include "mvtpu/watchdog.h"

namespace mvtpu {

namespace {

std::string JoinInts(const std::vector<int>& v) {
  std::string out;
  for (int x : v) {
    if (!out.empty()) out += ',';
    out += std::to_string(x);
  }
  return out;
}

// Adopt a wire message's trace id as this thread's span context for the
// scope (restored on exit).  No-op when tracing is off or id == 0.
class TraceScope {
 public:
  explicit TraceScope(int64_t trace_id) {
    if (trace_id != 0 && Dashboard::TraceEnabled()) {
      prev_ = Dashboard::ThreadTraceId();
      Dashboard::SetThreadTraceId(trace_id);
      set_ = true;
    }
  }
  ~TraceScope() {
    if (set_) Dashboard::SetThreadTraceId(prev_);
  }

 private:
  bool set_ = false;
  int64_t prev_ = 0;
};

// The actor chain worker → server → controller carries barrier messages
// so every request enqueued before the barrier is processed before it
// completes (the flush guarantee); across processes the server leg
// forwards to rank 0's controller over TCP.
class WorkerActor : public Actor {
 public:
  WorkerActor() : Actor(actor::kWorker) {
    RegisterHandler(MsgType::RequestGet, [](MessagePtr& m) {
      Zoo::Get()->Deliver(actor::kServer, std::move(m));
    });
    RegisterHandler(MsgType::RequestAdd, [](MessagePtr& m) {
      Zoo::Get()->Deliver(actor::kServer, std::move(m));
    });
    RegisterHandler(MsgType::RequestFlush, [](MessagePtr& m) {
      Zoo::Get()->Deliver(actor::kServer, std::move(m));
    });
    RegisterHandler(MsgType::RequestVersion, [](MessagePtr& m) {
      // Serve-layer probe: same worker->server leg as Get.
      Zoo::Get()->Deliver(actor::kServer, std::move(m));
    });
    RegisterHandler(MsgType::RequestReplica, [](MessagePtr& m) {
      // Hot-key replica pull (docs/embedding.md): same leg as Get.
      Zoo::Get()->Deliver(actor::kServer, std::move(m));
    });
    RegisterHandler(MsgType::ClockTick, [](MessagePtr& m) {
      // Outbound SSP tick: same worker->server leg as Get/Add, so the
      // per-connection FIFO keeps it behind this clock's adds.
      Zoo::Get()->Deliver(actor::kServer, std::move(m));
    });
    RegisterHandler(MsgType::ReplyFlush, [](MessagePtr& m) {
      Zoo::Get()->OnFlushReply(m->msg_id);
    });
    RegisterHandler(MsgType::ControlBarrier, [](MessagePtr& m) {
      // Local pipeline flush leg: worker → (local) server.
      Zoo::Get()->SendTo(actor::kServer, std::move(m));
    });
    RegisterHandler(MsgType::ReplyGet, [](MessagePtr& m) {
      // Sparse-encoded reply payload (docs/wire_compression.md): decode
      // before the table's consume sees it — a malformed payload is
      // dropped here, never scattered into a caller's buffer.
      if (m->codec != Codec::kRaw && !codec::DecodeInPlace(m.get())) {
        Log::Error("ReplyGet for table %d: malformed %s payload dropped",
                   m->table_id, codec::Name(m->codec));
        return;
      }
      Zoo::Get()->worker_table(m->table_id)->Notify(m->msg_id, *m);
    });
    RegisterHandler(MsgType::ReplyAdd, [](MessagePtr& m) {
      Zoo::Get()->worker_table(m->table_id)->Notify(m->msg_id, *m);
    });
    RegisterHandler(MsgType::ReplyError, [](MessagePtr& m) {
      // Synthesized by Deliver when a request's peer was unreachable:
      // unblocks the pending RoundTrip with an error.
      Zoo::Get()->worker_table(m->table_id)->Notify(m->msg_id, *m);
    });
    RegisterHandler(MsgType::ReplyVersion, [](MessagePtr& m) {
      Zoo::Get()->worker_table(m->table_id)->Notify(m->msg_id, *m);
    });
    RegisterHandler(MsgType::ReplyReplica, [](MessagePtr& m) {
      // The pending RefreshReplica's consume installs the pushed rows.
      Zoo::Get()->worker_table(m->table_id)->Notify(m->msg_id, *m);
    });
    RegisterHandler(MsgType::ReplyBusy, [](MessagePtr& m) {
      // Server shed the request under -server_inflight_max: fail the
      // pending round trip as BUSY (retryable; rc -6 at the C API).
      Zoo::Get()->worker_table(m->table_id)->Notify(m->msg_id, *m);
    });
  }
};

class ServerActor : public Actor {
 public:
  ServerActor() : Actor(actor::kServer) {
    RegisterHandler(MsgType::RequestGet, [](MessagePtr& m) {
      // Latency trail (docs/observability.md): the dequeue stamp closes
      // the mailbox stage — taken BEFORE the shed/SSP checks so a shed
      // or park is attributed to the mailbox, not the apply.
      latency::StampDequeue(m.get());
      // Shard-hint routing (docs/replication.md): a promoted rank
      // serves TWO shards of a table; reads whose hint names the
      // backed shard are also served pre-promotion (the hedge's true
      // backup target).
      auto* table = Zoo::Get()->RoutedServerTable(*m);
      if (!table) {  // misrouted: this rank has no server role/shard
        Log::Error("RequestGet for table %d on non-server rank",
                   m->table_id);
        return;
      }
      // Tail plane (docs/serving.md "tail"): a deadline-expired or
      // hedge-cancelled get is dropped at dequeue — nobody is waiting
      // for the answer, so it must not burn an apply slot.
      if (Zoo::Get()->DropServeRead(m)) return;
      // Serve backpressure: shed BEFORE any table work so an overloaded
      // server drains its backlog at ReplyBusy speed (docs/serving.md).
      if (Zoo::Get()->ShedIfOverloaded(m)) return;
      // SSP: park the get while its sender runs too far ahead of the
      // slowest worker; OnClockTick re-delivers it here when admitted.
      if (Zoo::Get()->MaybeHoldGet(m)) return;
      auto reply = std::make_unique<Message>();
      reply->type = MsgType::ReplyGet;
      reply->table_id = m->table_id;
      reply->msg_id = m->msg_id;
      reply->trace_id = m->trace_id;  // span id rides the full round trip
      reply->shard = m->shard;  // reassembly key: src rank is ambiguous
      reply->src = Zoo::Get()->rank();
      reply->dst = m->src;
      // Adopt the requester's span id for the handler's duration so the
      // server-side ProcessGet monitor's span (and any send it triggers)
      // correlates with the worker's Get across ranks.
      TraceScope scope(m->trace_id);
      // Seeded apply-path slowdown (docs/fault_tolerance.md): sleeps
      // INSIDE the dequeue->apply_done stage so the latency plane can
      // prove it names `apply`, not the wire (latdoctor acceptance).
      if (Fault::Enabled()) {
        int64_t d = Fault::ApplyDelayMs();
        if (d > 0) {
          Dashboard::Record("fault.apply_delay", 0.0);
          std::this_thread::sleep_for(std::chrono::milliseconds(d));
        }
      }
      table->ProcessGet(*m, reply.get());
      latency::StampReply(*m, reply.get());
      // Reply-codec negotiation: a requester that advertised
      // kAcceptSparse gets a lossless sparse payload when smaller.
      codec::MaybeEncodeReply(reply.get(), m->flags);
      Zoo::Get()->Deliver(actor::kWorker, std::move(reply));
    });
    RegisterHandler(MsgType::RequestVersion, [](MessagePtr& m) {
      // Serve-layer probe: answer with the current table (or bucket)
      // version — a header-only reply, no payload, no table lock.
      latency::StampDequeue(m.get());
      auto* table = Zoo::Get()->RoutedServerTable(*m);
      if (!table) {
        Log::Error("RequestVersion for table %d on non-server rank",
                   m->table_id);
        return;
      }
      if (Zoo::Get()->DropServeRead(m)) return;
      if (Zoo::Get()->ShedIfOverloaded(m)) return;
      auto reply = std::make_unique<Message>();
      reply->type = MsgType::ReplyVersion;
      reply->table_id = m->table_id;
      reply->msg_id = m->msg_id;
      reply->trace_id = m->trace_id;
      reply->shard = m->shard;
      reply->src = Zoo::Get()->rank();
      reply->dst = m->src;
      reply->version = m->version >= 0
                           ? table->bucket_version(
                                 static_cast<int>(m->version))
                           : table->version();
      latency::StampReply(*m, reply.get());
      Zoo::Get()->Deliver(actor::kWorker, std::move(reply));
    });
    RegisterHandler(MsgType::RequestReplica, [](MessagePtr& m) {
      // Hot-key replica push (docs/embedding.md): answer with this
      // shard's current SpaceSaving top-K rows + bucket versions.  A
      // read, so it sheds under backpressure exactly like a Get —
      // never competes with adds.
      latency::StampDequeue(m.get());
      auto* table = Zoo::Get()->RoutedServerTable(*m);
      if (!table) {
        Log::Error("RequestReplica for table %d on non-server rank",
                   m->table_id);
        return;
      }
      if (Zoo::Get()->DropServeRead(m)) return;
      if (Zoo::Get()->ShedIfOverloaded(m)) return;
      auto reply = std::make_unique<Message>();
      reply->type = MsgType::ReplyReplica;
      reply->table_id = m->table_id;
      reply->msg_id = m->msg_id;
      reply->trace_id = m->trace_id;
      reply->shard = m->shard;
      reply->src = Zoo::Get()->rank();
      reply->dst = m->src;
      TraceScope scope(m->trace_id);
      table->BuildReplica(reply.get());
      latency::StampReply(*m, reply.get());
      Zoo::Get()->Deliver(actor::kWorker, std::move(reply));
    });
    RegisterHandler(MsgType::ClockTick, [](MessagePtr& m) {
      Zoo::Get()->OnClockTick(m->src, m->msg_id);
    });
    RegisterHandler(MsgType::RequestAdd, [](MessagePtr& m) {
      latency::StampDequeue(m.get());
      auto* table = Zoo::Get()->RoutedServerTable(*m);
      if (!table) {
        Log::Error("RequestAdd for table %d on non-server rank",
                   m->table_id);
        return;
      }
      // Codec-encoded delta payload: decode to raw floats BEFORE
      // ProcessAdd, so the table layer (and its updaters/version
      // stamps) are codec-oblivious.  Malformed payloads are dropped —
      // feeding garbage deltas to an updater would corrupt the shard.
      if (m->codec != Codec::kRaw && !codec::DecodeInPlace(m.get())) {
        Log::Error("RequestAdd for table %d: malformed %s payload "
                   "dropped", m->table_id, codec::Name(m->codec));
        return;
      }
      TraceScope scope(m->trace_id);  // correlate apply with the Add
      if (Fault::Enabled()) {
        int64_t d = Fault::ApplyDelayMs();
        if (d > 0) {
          Dashboard::Record("fault.apply_delay", 0.0);
          std::this_thread::sleep_for(std::chrono::milliseconds(d));
        }
        // Seeded SILENT server-side discard (docs/observability.md
        // "audit plane"): the add vanishes after the wire delivered it
        // — no apply, no book entry, no ack.  The one failure class
        // retry/agg cannot absorb; exists so the audit plane's gap
        // detection has a real loss to catch (make audit-demo).
        if (Fault::DiscardApply()) {
          Dashboard::Record("fault.discard_apply", 0.0);
          return;
        }
      }
      // Replication makes stamped adds IDEMPOTENT (docs/replication.md):
      // a post-failover retry of a seq the promoted shard already
      // received as a ReplForward must ack without re-applying — the
      // retried delta would otherwise double-count.  Only with
      // replication armed: the base contract keeps dup deliveries
      // visible as dup-applies (docs/observability.md "audit plane").
      bool dup_skip =
          repl::Armed() && audit::Armed() && m->has_audit() &&
          table->audit_book().Covers(m->src, m->audit.seq_lo,
                                     m->audit.seq_hi);
      if (dup_skip) {
        table->audit_book().NoteDupSkipped(m->src, m->audit.seq_lo,
                                           m->audit.seq_hi);
        repl::NoteDupSkip();
        Dashboard::Record("repl.dup_skip", 0.0);
      } else {
        table->ProcessAdd(*m);
        // Delivery audit: book the applied seq range AFTER the apply so
        // the watermark never runs ahead of table state.
        table->NoteAuditApply(*m);
      }
      MessagePtr reply;
      if (m->msg_id >= 0) {  // blocking add wants an ack
        reply = std::make_unique<Message>();
        reply->type = MsgType::ReplyAdd;
        reply->table_id = m->table_id;
        reply->msg_id = m->msg_id;
        reply->trace_id = m->trace_id;
        reply->shard = m->shard;
        reply->src = Zoo::Get()->rank();
        reply->dst = m->src;
        // The ack carries the post-apply version: a write-through
        // client learns its own add's version for free (serving.md).
        reply->version = table->version();
        // Echo the audit stamp so the origin's acked-add ledger can
        // advance its watermark (docs/observability.md "audit plane").
        // The acked BOUND is the book's per-origin watermark, not the
        // request's seq_hi: under per-connection FIFO they are equal,
        // but across a failover a hole — an attempt that died with
        // the old primary — must never be covered by a later ack, or
        // the auditor would read a real (benign) gap as a LOST ACKED
        // ADD (docs/replication.md).
        if (m->has_audit()) {
          reply->flags |= msgflag::kHasAudit;
          reply->audit = m->audit;
          if (audit::Armed()) {
            int64_t wm = table->audit_book().Watermark(m->src);
            reply->audit.seq_hi = wm;
          }
        }
        latency::StampReply(*m, reply.get());
      }
      // Primary→backup delta stream (docs/replication.md): re-ship the
      // decoded add; sync mode parks the ack until the backup's
      // ReplAck, making "acked" mean "applied on both replicas".  An
      // already-applied dup is not re-forwarded (the backup saw it).
      if (!dup_skip && Zoo::Get()->ForwardAddToBackup(*m, &reply))
        return;  // ack parked; OnReplAck releases it
      if (reply) Zoo::Get()->Deliver(actor::kWorker, std::move(reply));
    });
    RegisterHandler(MsgType::ReplForward, [](MessagePtr& m) {
      Zoo::Get()->OnReplForward(std::move(m));
    });
    RegisterHandler(MsgType::ShardSnapshot, [](MessagePtr& m) {
      Zoo::Get()->OnShardSnapshot(std::move(m));
    });
    RegisterHandler(MsgType::RequestFlush, [](MessagePtr& m) {
      // Reaching here means every earlier message on the requester's
      // connection was processed — ack so its Barrier can proceed.
      auto reply = std::make_unique<Message>();
      reply->type = MsgType::ReplyFlush;
      reply->msg_id = m->msg_id;
      reply->src = Zoo::Get()->rank();
      reply->dst = m->src;
      Zoo::Get()->Deliver(actor::kWorker, std::move(reply));
    });
    RegisterHandler(MsgType::ControlBarrier, [](MessagePtr& m) {
      m->dst = 0;  // the controller authority lives on rank 0
      Zoo::Get()->Deliver(actor::kController, std::move(m));
    });
  }
};

class ControllerActor : public Actor {
 public:
  ControllerActor() : Actor(actor::kController) {
    RegisterHandler(MsgType::ControlBarrier, [](MessagePtr& m) {
      Zoo::Get()->OnBarrierArrive(m->src, m->msg_id);
    });
    RegisterHandler(MsgType::ControlBarrierReply, [](MessagePtr& m) {
      Zoo::Get()->OnBarrierRelease(m->msg_id);
    });
    RegisterHandler(MsgType::Heartbeat, [](MessagePtr& m) {
      // Symmetric leases (docs/replication.md): every rank renews to
      // every peer, so src==0 is now ambiguous — rank 0's own renewal
      // ships WITHOUT a trail; a trail-carrying src==0 frame is rank
      // 0's ECHO of our timed heartbeat, an NTP sample for the rank-0
      // clock offset (docs/observability.md), nothing lease-related.
      if (m->src == 0 && m->has_timing() && Zoo::Get()->rank() != 0) {
        latency::OnReply(*m, 0);
        return;
      }
      latency::StampDequeue(m.get());
      Zoo::Get()->OnHeartbeat(m->src);
      if (m->has_timing() && Zoo::Get()->rank() == 0) {
        // Echo the trail back so the announcing rank can close the
        // NTP round trip over the heartbeat RTT (PR 2's lease wire).
        auto echo = std::make_unique<Message>();
        echo->type = MsgType::Heartbeat;
        echo->src = Zoo::Get()->rank();
        echo->dst = m->src;
        latency::StampReply(*m, echo.get());
        Zoo::Get()->Deliver(actor::kController, std::move(echo));
      }
    });
    RegisterHandler(MsgType::Promote, [](MessagePtr& m) {
      // Operator/controller promotion nudge (docs/replication.md):
      // the same path lease expiry triggers automatically.
      Zoo::Get()->PromoteFor(static_cast<int>(m->version));
    });
  }
};

}  // namespace

static int64_t NowMs();

Zoo* Zoo::Get() {
  static Zoo zoo;
  return &zoo;
}

bool Zoo::Start(int argc, const char* const* argv) {
  MutexLock lk(mu_);
  if (started_) return true;
  configure::RegisterDefaults();
  if (configure::ParseCmdFlags(argc, argv) < 0) return false;
  std::string upd = configure::GetString("updater_type");
  if (!IsUpdaterName(upd)) {
    Log::Error("unknown updater_type '%s'", upd.c_str());
    return false;
  }
  updater_type_ = UpdaterFromName(upd);
  std::string lvl = configure::GetString("log_level");
  Log::SetLevel(lvl == "debug" ? LogLevel::kDebug
                : lvl == "error" ? LogLevel::kError
                : lvl == "fatal" ? LogLevel::kFatal
                                 : LogLevel::kInfo);
  Log::ResetLogFile(configure::GetString("log_file"));

  rank_ = 0;
  size_ = 1;
  worker_ranks_ = {0};
  server_ranks_ = {0};
  std::string machine_file = configure::GetString("machine_file");
  std::string ctrl = configure::GetString("controller_endpoint");
  std::string net_type = configure::GetString("net_type");
  if (net_type != "tcp" && net_type != "mpi") {
    Log::Error("unknown -net_type '%s' (expected tcp|mpi)",
               net_type.c_str());
    return false;
  }
  // Readiness-model seam (docs/transport.md): -net_engine picks the
  // transport engine.  `epoll` (the default) and `tcp` are the two TCP
  // engines behind MakeRankTransport; `mpi` forces the MPI wire (the
  // legacy -net_type=mpi spelling still works and wins).
  std::string engine = configure::GetString("net_engine");
  if (engine != "tcp" && engine != "epoll" && engine != "mpi" &&
      engine != "uring") {
    Log::Error("unknown -net_engine '%s' (expected tcp|epoll|mpi|uring)",
               engine.c_str());
    return false;
  }
  engine_requested_ = engine;
  engine_fallback_ = false;
  if (engine == "uring") {
    // Capability probe (docs/transport.md "io_uring data plane"): the
    // uring engine needs io_uring_setup plus a handful of opcodes.  A
    // kernel that can't run it degrades to epoll — same message
    // semantics, just the readiness model — with the reason logged and
    // the downgrade visible in the health report (`effective_engine`).
    std::string why;
    if (!uring::Probe(&why)) {
      Log::Info("-net_engine=uring unavailable (%s): falling back to "
                "epoll", why.c_str());
      ops::BlackboxEvent("lifecycle",
                         "net_engine fallback uring->epoll: " + why);
      engine = "epoll";
      engine_fallback_ = true;
    }
  }
  if (net_type == "mpi" || engine == "mpi") {
    // Literal MPI wire (reference net/mpi_net.h, SURVEY §2.17): rank and
    // size come from MPI itself — machine_file / -rank / registration
    // are TCP-mode concepts and are ignored.  Every rank is
    // worker + server (the reference's MPI static mode, Role::All).
    auto mpi = std::make_unique<MpiNet>();
    if (!mpi->Init([this](Message&& m) { RouteInbound(std::move(m)); }))
      return false;
    rank_ = mpi->rank();
    size_ = mpi->size();
    std::string role_str = configure::GetString("role");
    if (role_str != "all")
      Log::Info("-net_type=mpi ignores -role=%s: MPI static mode runs "
                "every rank as worker+server (use the registration "
                "transport for split roles)", role_str.c_str());
    SetRoles(std::vector<int>(size_, kRoleWorker | kRoleServer));
    net_ = std::move(mpi);
  } else if (!ctrl.empty()) {
    // Dynamic registration (reference Control_Register, SURVEY §2.7):
    // no machine file, no -rank — the controller assigns ranks and
    // broadcasts the node table; roles can differ per process.
    std::string role_str = configure::GetString("role");
    if (role_str != "worker" && role_str != "server" && role_str != "all") {
      // A typo must not silently become a full worker+server node (it
      // would host an unintended shard and shift every worker_id).
      Log::Error("unknown -role '%s' (expected worker|server|all)",
                 role_str.c_str());
      return false;
    }
    int role = role_str == "worker" ? kRoleWorker
               : role_str == "server" ? kRoleServer
                                      : (kRoleWorker | kRoleServer);
    int num = static_cast<int>(configure::GetInt("num_nodes"));
    std::vector<std::string> endpoints;
    std::vector<int> roles;
    bool ok;
    if (configure::GetBool("is_controller")) {
      rank_ = 0;
      ok = TcpNet::RegisterController(ctrl, num, role, &endpoints, &roles,
                                      configure::GetInt("rpc_timeout_ms"));
    } else {
      std::string me = configure::GetString("node_host") + ":" +
                       std::to_string(configure::GetInt("port"));
      ok = TcpNet::RegisterWithController(
          ctrl, me, role, configure::GetInt("connect_retry_ms"),
          &endpoints, &roles, &rank_);
    }
    if (!ok) {
      Log::Error("dynamic registration failed (controller=%s)",
                 ctrl.c_str());
      return false;
    }
    size_ = static_cast<int>(endpoints.size());
    SetRoles(roles);
    if (size_ > 1) {
      auto wire = MakeRankTransport(engine);
      if (!wire ||
          !wire->Init(endpoints, rank_,
                      [this](Message&& m) { RouteInbound(std::move(m)); },
                      configure::GetInt("connect_retry_ms")))
        return false;
      net_ = std::move(wire);
    }
  } else if (!machine_file.empty()) {
    auto endpoints = TcpNet::ParseMachineFile(machine_file);
    if (endpoints.size() > 1) {
      rank_ = static_cast<int>(configure::GetInt("rank"));
      size_ = static_cast<int>(endpoints.size());
      // Static mode: every rank is worker + server (reference Role::All).
      SetRoles(std::vector<int>(size_, kRoleWorker | kRoleServer));
      auto wire = MakeRankTransport(engine);
      if (!wire ||
          !wire->Init(endpoints, rank_,
                      [this](Message&& m) { RouteInbound(std::move(m)); },
                      configure::GetInt("connect_retry_ms")))
        return false;
      net_ = std::move(wire);
    }
  }

  worker_actor_ = std::make_unique<WorkerActor>();
  server_actor_ = std::make_unique<ServerActor>();
  controller_actor_ = std::make_unique<ControllerActor>();
  worker_actor_->Start();
  server_actor_->Start();
  controller_actor_->Start();
  if (size_ > 1 && configure::GetInt("heartbeat_ms") > 0) {
    {
      MutexLock hlk(hb_mu_);
      hb_last_seen_.assign(static_cast<size_t>(size_), NowMs());
      hb_dead_.assign(static_cast<size_t>(size_), false);
    }
    hb_running_ = true;
    hb_thread_ = std::thread([this] { HeartbeatLoop(); });
  }
  // Observability: rank-salt span ids (and the pid column of span
  // dumps); `-trace=true` arms span recording from the first op.
  Dashboard::SetTraceRank(rank_);
  // Workload plane (docs/observability.md): latch the hot-key/load
  // accounting arm switch from the flag (MV_SetHotKeyTracking toggles
  // it live for armed-vs-disarmed overhead A/Bs).
  workload::Arm(configure::GetBool("hotkey_enabled"));
  workload::ArmReplica(configure::GetBool("hotkey_replica"));
  // Capacity plane (docs/observability.md "capacity plane"): -capacity_
  // enabled latches the byte accounting; MV_SetCapacityTracking toggles
  // live (re-arming resyncs every shard's counters).
  capacity::Arm(configure::GetBool("capacity_enabled"));
  capacity::ResetHistory();
  // Byte gauges into the shared registry (the "capacity" report's
  // gauges object): the arena and the engine write queues are the two
  // native non-table byte holders; Python-plane caches register into
  // the metrics-side mirror (multiverso_tpu/capacity.py).
  capacity::RegisterGauge("host_arena.bytes", [] {
    return HostArena::Get()->GetStats().bytes;
  });
  capacity::RegisterGauge("net.writeq_bytes", [this]() -> long long {
    return net_ ? net_->QueuedBytes() : 0;
  });
  // Receive-side mirror of the write-queue gauge: reassembly slabs on
  // the epoll engine, registered buffer pools + heap fallback slabs on
  // the uring engine (transport memory mvplan placement math must see).
  capacity::RegisterGauge("net.rx_arena_bytes", [this]() -> long long {
    return net_ ? net_->RxArenaBytes() : 0;
  });
  // Delivery-audit plane (docs/observability.md "audit plane"): -audit
  // latches the seq stamping + server books; MV_SetAudit toggles live.
  audit::Arm(configure::GetBool("audit"));
  // Shard replication (docs/replication.md): -replication_factor arms
  // the primary→backup forward stream (factor 1, chained assignment);
  // meaningful only with >1 server rank.  The routing table starts at
  // epoch 0 = the registration-time shard map.
  repl::Arm(configure::GetInt("replication_factor") > 0 &&
            num_servers() > 1);
  repl::ArmSync(configure::GetBool("repl_sync"));
  {
    MutexLock rlk(route_mu_);
    routing_epoch_.store(0, std::memory_order_release);
    route_owner_ = server_ranks_;
    route_backup_.assign(server_ranks_.size(), -1);
    promoted_.assign(server_ranks_.size(), false);
    backup_shard_ = -1;
    int n = static_cast<int>(server_ranks_.size());
    if (repl::Armed() && n > 1) {
      // Chained assignment: shard i's backup is server i+1 mod n, so
      // server j backs shard j-1 mod n.
      for (int i = 0; i < n; ++i)
        route_backup_[i] = server_ranks_[(i + 1) % n];
      int sid = server_id();
      if (sid >= 0) backup_shard_ = (sid - 1 + n) % n;
    }
  }
  // Tail plane (docs/serving.md "tail"): latch the tenant classes,
  // per-class admission budgets, and deadline-stamp switch.
  qos::Configure();
  qos::Reset();
  // Latency plane (docs/observability.md): -wire_timing latches the
  // header-trail stamping; -profile_hz boots the SIGPROF sampler.
  latency::Arm(configure::GetBool("wire_timing"));
  if (configure::GetInt("profile_hz") > 0)
    profiler::Start(static_cast<int>(configure::GetInt("profile_hz")));
  // Health plane (docs/observability.md "health plane"): the stall
  // watchdog's checker boots AFTER the loops it watches exist; its
  // stall dump reuses the profiler's folded stacks when armed.
  if (configure::GetInt("watchdog_stall_ms") > 0)
    watchdog::Arm(static_cast<int>(configure::GetInt("watchdog_stall_ms")));
  if (configure::GetBool("trace")) Dashboard::SetTraceEnabled(true);
  started_ = true;
  ops::BlackboxEvent("lifecycle",
                     "start rank " + std::to_string(rank_) + "/" +
                         std::to_string(size_) + " engine=" + net_engine());
  Log::Info("mvtpu native runtime started (rank %d/%d, updater=%s, "
            "engine=%s)", rank_, size_, upd.c_str(), net_engine());
  return true;
}

const char* Zoo::net_engine() const {
  // Phase-stable like net_ itself (set by Start, cleared by the Stop
  // latch winner); "local" = single process, no wire at all.
  return net_ ? net_->engine() : "local";
}

Net::FanInStats Zoo::FanIn() const {
  return net_ ? net_->FanIn() : Net::FanInStats{};
}

void Zoo::Stop() {
  {
    // First Stop wins the latch; a concurrent second Stop returns here
    // instead of re-joining/resetting actors mid-teardown (a UB hole
    // the thread-safety annotations flagged: both callers used to pass
    // the old started_ check before either cleared it).
    MutexLock lk(mu_);
    if (!started_.exchange(false)) return;
  }
  // Cross-process: no rank may tear down while peers still need its
  // server shard — rendezvous first (also flushes every pipeline,
  // aggregated adds included).  Single-process: drain the aggregation
  // buffers directly so no absorbed add dies with the runtime.
  if (size_ > 1) Barrier();
  else FlushWorkerAdds();
  ops::BlackboxEvent("lifecycle", "stop rank " + std::to_string(rank_));
  // Watchdog off FIRST: the loops it watches are about to be joined,
  // and a legitimately-exiting loop must never read as a stall.
  watchdog::Arm(0);
  if (configure::GetInt("profile_hz") > 0) profiler::Stop();
  // Lease loop dies before the transport it sends through.
  if (hb_running_.exchange(false)) {
    if (hb_thread_.joinable()) hb_thread_.join();
  }
  // Detached fleet-ops aggregation threads send through net_ — give
  // them a bounded window to finish before the transport dies (their
  // deadline is -ops_fleet_timeout_ms, so this drain is bounded too).
  for (int i = 0; i < 500 && ops_inflight_.load() > 0; ++i)
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  // Un-waited async-get tickets hold pointers into the worker tables —
  // reclaim them before the registry dies (c_api.cc).
  CApiReclaimAsyncGets();
  // Join OUTSIDE mu_ (a draining handler may SendTo, which takes mu_):
  // snapshot the pointers under the lock, stop through the snapshots —
  // only the latch winner reaches here, so the pointees are stable.
  // Pipeline order so queued async adds apply before teardown.
  Actor* worker;
  Actor* server;
  Actor* controller;
  Net* net;
  {
    MutexLock lk(mu_);
    worker = worker_actor_.get();
    server = server_actor_.get();
    controller = controller_actor_.get();
    net = net_.get();
  }
  if (worker) worker->Stop();
  if (server) server->Stop();
  if (controller) controller->Stop();
  if (net) net->Stop();
  // Capacity gauges die with the runtime they read (a scrape after
  // Stop must not chase a dead transport).
  capacity::UnregisterGauge("net.rx_arena_bytes");
  capacity::UnregisterGauge("net.writeq_bytes");
  capacity::UnregisterGauge("host_arena.bytes");
  capacity::ResetHistory();
  MutexLock lk(mu_);
  worker_actor_.reset();
  server_actor_.reset();
  controller_actor_.reset();
  net_.reset();
  {
    MutexLock tlk(tables_mu_);
    server_tables_.clear();
    worker_tables_.clear();
    backup_tables_.clear();
    table_specs_.clear();
  }
  {
    MutexLock rlk(route_mu_);
    route_owner_.clear();
    route_backup_.clear();
    promoted_.clear();
    backup_shard_ = -1;
    routing_epoch_.store(0, std::memory_order_release);
  }
  {
    MutexLock plk(repl_mu_);
    parked_acks_.clear();
    snapshot_pending_.clear();
  }
  repl_outstanding_.store(0);
  rank_ = 0;
  size_ = 1;
  worker_ranks_ = {0};
  server_ranks_ = {0};
  {
    MutexLock blk(barrier_mu_);
    barrier_arrived_.clear();
    barrier_failed_ = false;
  }
  {
    MutexLock hlk(hb_mu_);
    hb_last_seen_.clear();
    hb_dead_.clear();
  }
  Log::Info("%s", Dashboard::Report().c_str());
}

void Zoo::FlushWorkerAdds() {
  // Drain every table's add-aggregation buffer onto the wire
  // (docs/wire_compression.md).  Pointers copied out of tables_mu_
  // before the flush runs: FlushAdds takes the table's own agg lock and
  // enqueues sends — doing that under tables_mu_ could deadlock against
  // a service path that needs the registry.
  std::vector<WorkerTable*> snapshot;
  {
    MutexLock lk(tables_mu_);
    for (auto& t : worker_tables_)
      if (t) snapshot.push_back(t.get());
  }
  for (auto* t : snapshot) t->FlushAdds();
}

bool Zoo::FlushPipelines() {
  // Aggregated adds first: the RequestFlush below must ride BEHIND them
  // on every connection, so "flush acked" still means "adds applied" —
  // the invariant Barrier's BSP guarantee stands on.
  FlushWorkerAdds();
  if (!net_) return true;
  // Targets follow the ROUTED shard map (docs/replication.md): after a
  // promotion the dead rank owns nothing, so the flush drains the live
  // owners instead of latching barrier_failed_ on a corpse forever.
  std::vector<int> targets;
  for (int s = 0; s < num_servers(); ++s) {
    int r = server_rank(s);
    if (r != rank_ &&
        std::find(targets.begin(), targets.end(), r) == targets.end())
      targets.push_back(r);
  }
  if (targets.empty()) return true;
  int64_t id = NextMsgId();
  auto waiter = std::make_shared<Waiter>(static_cast<int>(targets.size()));
  {
    MutexLock lk(flush_mu_);
    flush_pending_[id] = waiter;
  }
  for (int s : targets) {
    auto msg = std::make_unique<Message>();
    msg->type = MsgType::RequestFlush;
    msg->msg_id = id;
    msg->src = rank_;
    msg->dst = s;
    SendTo(actor::kWorker, std::move(msg));
  }
  bool ok = waiter->WaitFor(configure::GetInt("rpc_timeout_ms"));
  MutexLock lk(flush_mu_);
  flush_pending_.erase(id);
  if (!ok)
    Log::Error("Zoo::FlushPipelines: timed out (rank %d)", rank_);
  return ok;
}

void Zoo::OnFlushReply(int64_t msg_id) {
  MutexLock lk(flush_mu_);
  auto it = flush_pending_.find(msg_id);
  if (it != flush_pending_.end()) it->second->Notify();
}

bool Zoo::Barrier() {
  Monitor mon("Zoo::Barrier");
  {
    MutexLock lk(barrier_mu_);
    barrier_failed_ = false;  // fresh round; flush may re-latch it
  }
  // First drain this rank's async pipeline INTO EVERY REMOTE SHARD:
  // barrier-arrive rides the connection to rank 0 only, so without this
  // an async add to a third rank could still be in flight when the
  // release lands (observed at n=4).
  bool flushed = FlushPipelines();
  auto waiter = std::make_shared<Waiter>(1);
  int64_t round;
  {
    MutexLock lk(barrier_mu_);
    barrier_waiter_ = waiter;
    // OR, don't assign: a dead shard latched barrier_failed_ during the
    // flush (Deliver's RequestFlush case) and that must survive.
    barrier_failed_ = barrier_failed_ || !flushed;
    round = ++barrier_round_;
  }
  auto msg = std::make_unique<Message>();
  msg->type = MsgType::ControlBarrier;
  msg->msg_id = round;  // round tag: lets stale releases be dropped
  msg->src = rank_;
  msg->dst = 0;
  SendTo(actor::kWorker, std::move(msg));
  // Default (<=0) waits forever — BSP semantics; a deadline turns a dead
  // peer into an error return instead of a hang (the release message may
  // still arrive later: OnBarrierRelease tolerates a cleared waiter).
  bool ok = waiter->WaitFor(configure::GetInt("barrier_timeout_ms"));
  if (!ok) {
    // Name the unresponsive rank(s): the authority knows exactly who
    // never announced arrival; everyone else can only name the silent
    // authority.  Dead-lease info (heartbeats) rides along when on.
    std::string who;
    if (rank_ == 0) {
      MutexLock lk(barrier_mu_);
      for (int r = 0; r < size_; ++r) {
        bool arrived = r < static_cast<int>(barrier_arrived_.size()) &&
                       barrier_arrived_[r];
        if (!arrived) who += (who.empty() ? "" : ",") + std::to_string(r);
      }
    } else {
      who = "0 (barrier authority)";
    }
    Log::Error("Zoo::Barrier: rank %d timed out after %lld ms waiting "
               "for rank(s) %s",
               rank_,
               static_cast<long long>(
                   configure::GetInt("barrier_timeout_ms")),
               who.c_str());
    for (int r : DeadPeers())
      Log::Error("Zoo::Barrier: rank %d's heartbeat lease is expired "
                 "(likely dead)", r);
    // Flight-recorder trigger (docs/observability.md): a barrier that
    // timed out is exactly the moment a post-mortem needs the recent
    // spans/events — dump the black box naming the missing rank(s).
    ops::BlackboxTrigger("barrier_timeout: waiting for rank(s) " + who);
  }
  bool failed;
  {
    MutexLock lk(barrier_mu_);
    barrier_waiter_.reset();
    failed = barrier_failed_;
  }
  if (ok && !failed) {
    // Clock boundary: peers' adds are applied — drop worker-side row
    // caches (SparseMatrixWorkerTable) so post-barrier Gets see them.
    // Pointers copied OUT of tables_mu_ before the hooks run: a hook
    // takes its cache lock, which another thread may hold across a
    // blocking fetch whose service path needs tables_mu_ — invoking
    // under the lock would close that cycle into a deadlock.  (Tables
    // are never unregistered, so the copied pointers stay valid.)
    std::vector<WorkerTable*> snapshot;
    {
      MutexLock lk(tables_mu_);
      for (auto& t : worker_tables_)
        if (t) snapshot.push_back(t.get());
    }
    for (auto* t : snapshot) t->OnClockInvalidate();
  }
  return ok && !failed;
}

void Zoo::OnBarrierArrive(int src_rank, int64_t round) {
  std::vector<std::pair<int, int64_t>> release;  // (rank, its round)
  {
    MutexLock lk(barrier_mu_);
    if (barrier_arrived_.size() != static_cast<size_t>(size_))
      barrier_arrived_.assign(size_, false);
    if (barrier_rounds_.size() != static_cast<size_t>(size_))
      barrier_rounds_.assign(size_, 0);
    if (src_rank < 0 || src_rank >= size_) return;
    // Track the rank's LATEST round even on a duplicate arrive: a retry
    // after an abandoned round re-announces with round k+1, and the
    // eventual release must echo that so the retry's waiter accepts it.
    if (round > barrier_rounds_[src_rank]) barrier_rounds_[src_rank] = round;
    // Per-rank, not per-message: a retry after an abandoned (timed-out)
    // round must not double-count toward the quorum.
    if (barrier_arrived_[src_rank]) return;
    barrier_arrived_[src_rank] = true;
    // Elastic membership (docs/replication.md): with replication armed
    // a peer whose heartbeat lease is expired is EXCUSED from the
    // quorum — the fleet rendezvouses without the corpse instead of
    // timing out, which is what lets survivors keep running (and shut
    // down cleanly) after a failover.  Without replication the old
    // strict quorum stands: a silent rank is an error, not a member
    // change.
    for (int r = 0; r < size_; ++r) {
      if (barrier_arrived_[r]) continue;
      if (repl::Armed()) {
        MutexLock hlk(hb_mu_);
        if (r < static_cast<int>(hb_dead_.size()) && hb_dead_[r]) {
          Log::Info("Zoo::Barrier: excusing dead-leased rank %d from "
                    "the quorum", r);
          continue;
        }
      }
      return;
    }
    barrier_arrived_.assign(size_, false);
    for (int r = 0; r < size_; ++r)
      release.emplace_back(r, barrier_rounds_[r]);
  }
  // Remote releases FIRST, the local one last: the local release wakes
  // this rank's Barrier() caller, and anything it does next (e.g. the
  // chaos suite arming a fault) must not race releases still queued for
  // the wire.
  for (auto& [r, r_round] : release) {
    if (r == rank_) continue;
    Message reply;
    reply.type = MsgType::ControlBarrierReply;
    reply.msg_id = r_round;  // echo the receiver's announced round
    reply.src = rank_;
    reply.dst = r;
    net_->Send(r, reply);
  }
  for (auto& [r, r_round] : release)
    if (r == rank_) OnBarrierRelease(r_round);
}

void Zoo::OnBarrierRelease(int64_t round) {
  MutexLock lk(barrier_mu_);
  // round >= 0: a wire release — drop it unless it matches the waiter's
  // current round (a late round-k release after a timeout must not free
  // the round-k+1 rendezvous).  round < 0: local failure path, always
  // releases (barrier_failed_ is already latched).
  if (round >= 0 && round != barrier_round_) {
    Log::Debug("Zoo::OnBarrierRelease: dropping stale release "
               "(round %lld, current %lld)",
               static_cast<long long>(round),
               static_cast<long long>(barrier_round_));
    return;
  }
  if (barrier_waiter_) barrier_waiter_->Notify();
}

void Zoo::HeartbeatLoop() {
  const int64_t interval = configure::GetInt("heartbeat_ms");
  int64_t timeout = configure::GetInt("heartbeat_timeout_ms");
  if (timeout <= 0) timeout = 5 * interval;
  // SYMMETRIC lease renewal (docs/replication.md): every rank —
  // rank 0 included — announces to EVERY peer, so every survivor can
  // detect any corpse, rank 0 itself included (the old rank-0-only
  // watch left a backup blind exactly when the lease authority was
  // the one that died).  ONE SENDER THREAD PER PEER: a send to a dead
  // peer blocks in the transport's reconnect/backoff for whole lease
  // windows, and a single shared sender stalling there would starve
  // the renewals every LIVE peer's lease depends on — the mutual
  // false-dead cascade the failover chaos scenario caught.  The
  // rank→0 renewal keeps its timing trail: rank 0's echo closes an
  // NTP offset sample (docs/observability.md); renewals to other
  // peers ship bare.  A failed send is already logged by the
  // transport; the lease simply expires on the peer's side.
  std::vector<std::thread> senders;
  for (int peer = 0; peer < size_; ++peer) {
    if (peer == rank_) continue;
    senders.emplace_back([this, peer, interval] {
      while (hb_running_) {
        for (int64_t slept = 0; slept < interval && hb_running_;
             slept += 20)
          std::this_thread::sleep_for(std::chrono::milliseconds(
              std::min<int64_t>(20, interval - slept)));
        if (!hb_running_) break;
        Message hb;
        hb.type = MsgType::Heartbeat;
        hb.src = rank_;
        hb.dst = peer;
        if (peer == 0) {
          latency::StampEnqueue(&hb);
          latency::StampSend(&hb);
        }
        if (net_) net_->Send(peer, hb);
      }
    });
  }
  // Watchdog (docs/observability.md "health plane"): the lease scan is
  // permanently "busy" while running — a wedged scan means every peer
  // death goes undetected.  -watchdog_stall_ms must therefore exceed
  // -heartbeat_ms (the scan's legitimate period).
  watchdog::Busy("hb.lease", 1);
  while (hb_running_) {
    // Sleep in small steps so Stop never waits a full interval.
    for (int64_t slept = 0; slept < interval && hb_running_; slept += 20)
      std::this_thread::sleep_for(std::chrono::milliseconds(
          std::min<int64_t>(20, interval - slept)));
    if (!hb_running_) break;
    watchdog::Bump("hb.lease");
    // Scan the leases (every rank, not just rank 0).  A peer
    // transitions to dead ONCE per outage (hb.missed counts outages,
    // not scans) and recovers when a late heartbeat arrives.  With
    // replication armed the expiry is no longer report-only: the
    // backup promotes (docs/replication.md); otherwise eviction/
    // replacement stays the operator's call.
    int64_t now = NowMs();
    std::vector<int> newly_dead;
    {
      MutexLock lk(hb_mu_);
      for (int r = 0; r < size_; ++r) {
        if (r == rank_) continue;
        bool silent = now - hb_last_seen_[r] > timeout;
        if (silent && !hb_dead_[r]) {
          hb_dead_[r] = true;
          Dashboard::Record("hb.missed", 0.0);
          Log::Error("heartbeat: rank %d silent for over %lld ms — lease "
                     "expired, reporting peer dead",
                     r, static_cast<long long>(timeout));
          newly_dead.push_back(r);
        }
      }
    }
    // Blackbox dump OUTSIDE hb_mu_ (it reads zoo state): a dead peer is
    // a first-class failure trigger (docs/observability.md).
    for (int r : newly_dead) {
      ops::BlackboxTrigger("dead_peer: rank " + std::to_string(r) +
                           " silent past the heartbeat lease");
      OnPeerDead(r);
    }
    // Sync-replication hygiene: a parked ack whose backup never
    // answered must not wedge the client past its deadline.
    ReleaseParkedAcks(/*all=*/false);
  }
  watchdog::Busy("hb.lease", 0);  // clean exit is idle, not a stall
  for (auto& t : senders) t.join();
}

void Zoo::OnHeartbeat(int src_rank) {
  MutexLock lk(hb_mu_);
  if (src_rank < 0 || src_rank >= static_cast<int>(hb_last_seen_.size()))
    return;
  hb_last_seen_[src_rank] = NowMs();
  if (hb_dead_[src_rank]) {
    hb_dead_[src_rank] = false;
    Log::Info("heartbeat: rank %d is back — lease renewed", src_rank);
  }
}

int Zoo::DeadPeerCount() {
  MutexLock lk(hb_mu_);
  int n = 0;
  for (bool d : hb_dead_) n += d ? 1 : 0;
  return n;
}

std::vector<int> Zoo::DeadPeers() {
  MutexLock lk(hb_mu_);
  std::vector<int> out;
  for (size_t r = 0; r < hb_dead_.size(); ++r)
    if (hb_dead_[r]) out.push_back(static_cast<int>(r));
  return out;
}

// ---- shard replication + failover (docs/replication.md) ---------------

int Zoo::server_rank(int idx) const {
  MutexLock lk(route_mu_);
  if (idx >= 0 && idx < static_cast<int>(route_owner_.size()))
    return route_owner_[idx];
  return (idx >= 0 && idx < static_cast<int>(server_ranks_.size()))
             ? server_ranks_[idx]
             : 0;
}

std::vector<int> Zoo::RouteOwners() const {
  MutexLock lk(route_mu_);
  return route_owner_;
}

std::vector<int> Zoo::RouteBackups() const {
  MutexLock lk(route_mu_);
  return route_backup_;
}

int Zoo::BackupShard() const {
  MutexLock lk(route_mu_);
  return backup_shard_;
}

ServerTable* Zoo::backup_table(int32_t id) {
  MutexLock lk(tables_mu_);
  return (id >= 0 && id < static_cast<int32_t>(backup_tables_.size()))
             ? backup_tables_[id].get()
             : nullptr;
}

ServerTable* Zoo::RoutedServerTable(const Message& msg) {
  // LOCK ORDER: route_mu_ is released before the table registry lookup
  // (never nest tables_mu_ under it).
  int hint = msg.shard;
  if (hint >= 0 && hint != server_id()) {
    bool backed;
    {
      MutexLock lk(route_mu_);
      backed = backup_shard_ == hint ||
               (hint < static_cast<int>(promoted_.size()) &&
                promoted_[hint]);
    }
    if (backed) {
      ServerTable* bt = backup_table(msg.table_id);
      if (bt) return bt;
    }
  }
  return server_table(msg.table_id);
}

bool Zoo::ForwardAddToBackup(const Message& m, MessagePtr* reply) {
  if (!repl::Armed()) return false;
  int shard = m.shard >= 0 ? m.shard : server_id();
  int backup = -1;
  {
    MutexLock lk(route_mu_);
    if (shard < 0 || shard >= static_cast<int>(route_backup_.size()))
      return false;
    if (route_owner_[shard] != rank_) return false;  // not the primary
    backup = route_backup_[shard];
  }
  if (backup < 0 || backup == rank_ || !net_) return false;
  // Lease check (defense in depth): a stale adopted map may still name
  // a dead backup — forwarding there would park the apply thread in
  // the transport's reconnect backoff for whole lease windows.
  {
    MutexLock lk(hb_mu_);
    if (backup < static_cast<int>(hb_dead_.size()) && hb_dead_[backup])
      return false;
  }
  // Bounded-lag backpressure (async mode): the apply thread stalls
  // while the forward/ack gap exceeds -repl_lag_max, deadline-bounded
  // so a dying backup degrades instead of wedging the shard.  Sync
  // mode needs no gap bound — every client add parks on its own ack.
  int64_t lag_max = configure::GetInt("repl_lag_max");
  if (!repl::Sync() && lag_max > 0 &&
      repl_outstanding_.load() >= lag_max) {
    repl::NoteLagWait();
    Dashboard::Record("repl.lag_wait", 0.0);
    int64_t deadline = NowMs() + 2000;
    while (repl_outstanding_.load() >= lag_max && NowMs() < deadline)
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  int64_t fwd_id = NextMsgId();
  Message fwd;
  fwd.type = MsgType::ReplForward;
  fwd.table_id = m.table_id;
  fwd.msg_id = fwd_id;
  fwd.trace_id = m.trace_id;
  fwd.shard = shard;
  fwd.version = m.src;  // ORIGIN rank: the backup books its watermark
  fwd.src = rank_;
  fwd.dst = backup;
  if (m.has_audit()) {
    fwd.flags |= msgflag::kHasAudit;
    fwd.audit = m.audit;
  }
  fwd.data = m.data;  // decoded payload; shallow blob copies share bytes
  bool parked = false;
  if (reply && *reply && repl::Sync()) {
    // Park BEFORE the send so a lightning-fast ReplAck can never race
    // an unparked reply; a failed send takes it right back out.
    int64_t t = configure::GetInt("rpc_timeout_ms");
    MutexLock lk(repl_mu_);
    parked_acks_[fwd_id] =
        ParkedAck{NowMs() + (t > 0 ? t / 2 : 2000), std::move(*reply)};
    parked = true;
    repl::NoteParked();
  }
  repl_outstanding_.fetch_add(1);
  repl::NoteForward();
  // Replication-lag ledger on the µs-bucket ladder (1 unit = 1
  // outstanding forward) — the bounded-lag gauge the staleness
  // histogram discipline measures (docs/observability.md).
  Dashboard::Record("repl.lag",
                    static_cast<double>(repl_outstanding_.load()) * 1e-6);
  Dashboard::Record("repl.forward", 0.0);
  if (!net_->Send(backup, fwd)) {
    repl_outstanding_.fetch_add(-1);
    if (parked) {
      MutexLock lk(repl_mu_);
      auto it = parked_acks_.find(fwd_id);
      if (it != parked_acks_.end()) {
        *reply = std::move(it->second.reply);
        parked_acks_.erase(it);
        parked = false;
      }
    }
  }
  return parked;
}

void Zoo::OnReplForward(MessagePtr msg) {
  latency::StampDequeue(msg.get());
  int primary = msg->src;
  int origin = static_cast<int>(msg->version);
  ServerTable* bt = nullptr;
  {
    bool mine;
    {
      MutexLock lk(route_mu_);
      mine = backup_shard_ == msg->shard;
    }
    if (mine) bt = backup_table(msg->table_id);
  }
  if (!bt) {
    Dashboard::Record("repl.forward_orphan", 0.0);
    Log::Error("ReplForward for table %d shard %d: no backup instance",
               msg->table_id, msg->shard);
    return;
  }
  TraceScope scope(msg->trace_id);
  // Apply under the ORIGIN's identity so the backup's delivery book
  // carries the same per-origin watermark the primary's does — what
  // lets mvaudit diff primary vs backup and post-failover retries
  // dedup against the promoted shard.
  msg->src = origin;
  bt->ProcessAdd(*msg);
  bt->NoteAuditApply(*msg);
  repl::NoteApplied();
  Dashboard::Record("repl.apply", 0.0);
  if (!net_) return;
  Message ack;
  ack.type = MsgType::ReplAck;
  ack.table_id = msg->table_id;
  ack.msg_id = msg->msg_id;
  ack.shard = msg->shard;
  ack.src = rank_;
  ack.dst = primary;
  net_->Send(primary, ack);
}

void Zoo::OnReplAck(MessagePtr msg) {
  repl_outstanding_.fetch_add(-1);
  repl::NoteAck();
  MessagePtr parked;
  {
    MutexLock lk(repl_mu_);
    auto it = parked_acks_.find(msg->msg_id);
    if (it != parked_acks_.end()) {
      parked = std::move(it->second.reply);
      parked_acks_.erase(it);
    }
  }
  // Sync replication: "acked" now means applied on BOTH replicas.
  // Runs ON THE REACTOR THREAD (RouteInbound): never Deliver at a
  // lease-dead destination from here — the transport's reconnect
  // backoff would stall the reactor for whole lease windows, starving
  // heartbeat receipt into false-positive expiries (observed as a
  // live peer's lease flapping right after a real kill).
  if (!parked) return;
  int dst = parked->dst;
  {
    MutexLock lk(hb_mu_);
    if (dst >= 0 && dst < static_cast<int>(hb_dead_.size()) &&
        hb_dead_[dst])
      return;  // the client is a corpse; nothing waits for this ack
  }
  Deliver(actor::kWorker, std::move(parked));
}

void Zoo::OnShardSnapshot(MessagePtr msg) {
  latency::StampDequeue(msg.get());
  if (msg->data.empty()) {
    // Request: serve a whole-shard snapshot of the shard we own under
    // this hint.  Runs on the server actor, so it serializes against
    // ProcessAdd — every later delta reaches the requester as a
    // ReplForward BEHIND this reply on the same connection (FIFO).
    auto* table = RoutedServerTable(*msg);
    if (!table) {
      Log::Error("ShardSnapshot request for table %d on non-server rank",
                 msg->table_id);
      return;
    }
    repl::MemStream ms;
    if (!table->Store(&ms)) {
      Log::Error("ShardSnapshot: Store failed for table %d",
                 msg->table_id);
      return;
    }
    auto marks = table->audit_book().ExportWatermarks();
    std::vector<int64_t> wm;
    wm.reserve(marks.size() * 2);
    for (const auto& [o, mark] : marks) {
      wm.push_back(o);
      wm.push_back(mark);
    }
    auto reply = std::make_unique<Message>();
    reply->type = MsgType::ShardSnapshot;
    reply->table_id = msg->table_id;
    reply->msg_id = msg->msg_id;
    reply->trace_id = msg->trace_id;
    reply->shard = msg->shard;
    reply->version = table->version();
    reply->src = rank_;
    reply->dst = msg->src;
    reply->data.emplace_back(ms.bytes().data(), ms.bytes().size());
    if (!wm.empty())
      reply->data.emplace_back(wm.data(), wm.size() * sizeof(int64_t));
    repl::NoteSnapshot();
    Dashboard::Record("repl.snapshot", 0.0);
    Deliver(actor::kServer, std::move(reply));
    return;
  }
  // Reply: install the snapshot into our backup instance.  Forwards
  // already applied before the install are INSIDE the snapshot (the
  // primary serialized it after them); forwards sent after it arrive
  // behind this frame — either way the bytes converge.
  bool mine;
  {
    MutexLock lk(route_mu_);
    mine = backup_shard_ == msg->shard;
  }
  ServerTable* bt = mine ? backup_table(msg->table_id) : nullptr;
  if (!bt) {
    Log::Error("ShardSnapshot reply for table %d shard %d: no backup "
               "instance", msg->table_id, msg->shard);
  } else {
    repl::MemStream ms(
        std::string(msg->data[0].data(), msg->data[0].size()));
    if (!bt->Load(&ms)) {
      Log::Error("ShardSnapshot: install failed for table %d",
                 msg->table_id);
    } else {
      if (msg->data.size() > 1) {
        const int64_t* wm = msg->data[1].As<int64_t>();
        size_t n = msg->data[1].count<int64_t>() / 2;
        std::vector<std::pair<int, int64_t>> marks;
        marks.reserve(n);
        for (size_t i = 0; i < n; ++i)
          marks.emplace_back(static_cast<int>(wm[2 * i]), wm[2 * i + 1]);
        bt->audit_book().ImportWatermarks(marks);
      }
      // Adopt the primary's version so post-promotion reply stamps
      // never run BEHIND what clients already observed (stale cache
      // hits would otherwise look fresh).
      bt->AdvanceVersionTo(msg->version);
      repl::NoteCatchup();
      Dashboard::Record("repl.catchup", 0.0);
    }
  }
  std::shared_ptr<Waiter> w;
  {
    MutexLock lk(repl_mu_);
    auto it = snapshot_pending_.find(msg->msg_id);
    if (it != snapshot_pending_.end()) w = it->second;
  }
  if (w) w->Notify();
}

void Zoo::BroadcastRoutingEpoch(int64_t epoch,
                                const std::vector<int>& owners,
                                const std::vector<int>& backups) {
  if (!net_) return;
  std::vector<int32_t> own(owners.begin(), owners.end());
  std::vector<int32_t> bak(backups.begin(), backups.end());
  for (int r = 0; r < size_; ++r) {
    if (r == rank_) continue;
    Message m;
    m.type = MsgType::RoutingEpoch;
    m.msg_id = epoch;
    m.src = rank_;
    m.dst = r;
    m.data.emplace_back(own.data(), own.size() * sizeof(int32_t));
    m.data.emplace_back(bak.data(), bak.size() * sizeof(int32_t));
    net_->Send(r, m);  // a dead peer's failure is already logged
  }
}

void Zoo::OnRoutingEpoch(MessagePtr msg) {
  if (msg->data.size() < 2) return;
  int64_t epoch = msg->msg_id;
  const int32_t* own = msg->data[0].As<int32_t>();
  size_t n = msg->data[0].count<int32_t>();
  const int32_t* bak = msg->data[1].As<int32_t>();
  if (msg->data[1].count<int32_t>() < n || n == 0) return;
  bool adopted = false;
  {
    MutexLock lk(route_mu_);
    // Max-merge: only a NEWER epoch flips the route (stale broadcasts
    // from slow paths are dropped, the PR 4 version-gate discipline).
    if (epoch > routing_epoch_.load(std::memory_order_relaxed)) {
      route_owner_.assign(own, own + n);
      route_backup_.assign(bak, bak + n);
      // Local lease knowledge beats the adopted map: never re-instate
      // a backup this rank already watched die (forwarding there would
      // wedge the apply thread in reconnect backoff).
      {
        MutexLock hlk(hb_mu_);
        for (size_t s = 0; s < route_backup_.size(); ++s) {
          int b = route_backup_[s];
          if (b >= 0 && b < static_cast<int>(hb_dead_.size()) &&
              hb_dead_[b])
            route_backup_[s] = -1;
        }
      }
      if (promoted_.size() < n) promoted_.resize(n, false);
      // Recompute local identity from the map (a join may have moved
      // the backup slot); a shard we PROMOTED stays ours regardless.
      backup_shard_ = -1;
      for (size_t s = 0; s < n; ++s)
        if (bak[s] == rank_) backup_shard_ = static_cast<int>(s);
      if (backup_shard_ < 0)
        for (size_t s = 0; s < promoted_.size(); ++s)
          if (promoted_[s]) backup_shard_ = static_cast<int>(s);
      routing_epoch_.store(epoch, std::memory_order_release);
      adopted = true;
    }
  }
  if (adopted) {
    repl::NoteEpochFlip();
    Dashboard::Record("repl.epoch_flip", 0.0);
    Log::Info("replication: adopted routing epoch %lld from rank %d",
              static_cast<long long>(epoch), msg->src);
    // The flip is a cache boundary: worker-side serve caches may hold
    // rows stamped by the dead primary — drop them like a clock tick.
    InvalidateWorkerCaches();
  }
}

int Zoo::PromoteFor(int dead) {
  if (!repl::Armed()) return 0;
  std::vector<int> owners, backups, shards;
  int64_t epoch = 0;
  {
    MutexLock lk(route_mu_);
    for (size_t s = 0; s < route_owner_.size(); ++s) {
      if (route_owner_[s] == dead && route_backup_[s] == rank_) {
        route_owner_[s] = rank_;
        route_backup_[s] = -1;  // chain repair = a future JoinAsBackup
        if (promoted_.size() <= s) promoted_.resize(s + 1, false);
        promoted_[s] = true;
        shards.push_back(static_cast<int>(s));
      }
    }
    if (shards.empty()) return 0;
    epoch = NextEpochLocked();
    owners = route_owner_;
    backups = route_backup_;
  }
  for (int s : shards) {
    repl::NotePromotion();
    Dashboard::Record("repl.promoted", 0.0);
    Log::Info("replication: promoted shard %d (rank %d dead) at epoch "
              "%lld", s, dead, static_cast<long long>(epoch));
    ops::BlackboxEvent(
        "replication", "promote: shard " + std::to_string(s) +
                           " after rank " + std::to_string(dead) +
                           " lease expiry, epoch " + std::to_string(epoch));
  }
  BroadcastRoutingEpoch(epoch, owners, backups);
  InvalidateWorkerCaches();
  return static_cast<int>(shards.size());
}

void Zoo::InvalidateWorkerCaches() {
  // The Barrier/Clock snapshot discipline: pointers copied OUT of
  // tables_mu_ before the hooks run (they take per-table locks).
  std::vector<WorkerTable*> snapshot;
  {
    MutexLock lk(tables_mu_);
    for (auto& t : worker_tables_)
      if (t) snapshot.push_back(t.get());
  }
  for (auto* t : snapshot) t->OnClockInvalidate();
}

void Zoo::ReleaseParkedAcks(bool all) {
  std::vector<MessagePtr> release;
  int64_t now = NowMs();
  {
    MutexLock lk(repl_mu_);
    for (auto it = parked_acks_.begin(); it != parked_acks_.end();) {
      if (all || now >= it->second.deadline_ms) {
        release.push_back(std::move(it->second.reply));
        it = parked_acks_.erase(it);
      } else {
        ++it;
      }
    }
  }
  for (auto& r : release) {
    // Degraded ack: the backup never confirmed, but the add IS applied
    // on the primary — the client must not wedge on a dying backup.
    // The replication report carries the degradation instead.  A
    // lease-dead client's ack is dropped outright: delivering it
    // would park THIS thread in the transport's reconnect backoff.
    Dashboard::Record("repl.park_timeout", 0.0);
    int dst = r->dst;
    {
      MutexLock lk(hb_mu_);
      if (dst >= 0 && dst < static_cast<int>(hb_dead_.size()) &&
          hb_dead_[dst])
        continue;
    }
    Deliver(actor::kWorker, std::move(r));
  }
}

void Zoo::OnPeerDead(int r) {
  if (!repl::Armed()) return;
  // Confirm the corpse before the (irreversible) route surgery: a
  // transient stall can expire a LIVE peer's lease for one beat, and
  // promoting on a flap would mint a split-brain epoch.  One extra
  // heartbeat interval of silence is cheap against the lease window;
  // a recovered peer clears hb_dead_ on its next renewal and we walk
  // away.
  int64_t confirm = configure::GetInt("heartbeat_ms");
  std::this_thread::sleep_for(
      std::chrono::milliseconds(std::max<int64_t>(confirm, 50)));
  {
    MutexLock lk(hb_mu_);
    if (r >= 0 && r < static_cast<int>(hb_dead_.size()) && !hb_dead_[r])
      return;  // lease recovered: a flap, not a corpse
  }
  // ONE route pass, ONE epoch bump, ONE broadcast: clearing the
  // corpse's backup slots and promoting its shards must ship as a
  // single map — a promote-only broadcast would re-instate the dead
  // rank as a backup on every adopter, and primaries would then block
  // their apply threads forwarding at a corpse.
  bool promote = configure::GetBool("promote_auto");
  std::vector<int> owners, backups, shards;
  bool dropped_mine = false, changed = false;
  int64_t epoch = 0;
  {
    MutexLock lk(route_mu_);
    for (size_t s = 0; s < route_backup_.size(); ++s) {
      if (route_backup_[s] == r) {
        route_backup_[s] = -1;  // never forward at a corpse
        if (route_owner_[s] == rank_) dropped_mine = true;
        changed = true;
      }
    }
    if (promote) {
      for (size_t s = 0; s < route_owner_.size(); ++s) {
        if (route_owner_[s] == r && backup_shard_ == static_cast<int>(s)) {
          route_owner_[s] = rank_;
          route_backup_[s] = -1;  // chain repair = a future join
          if (promoted_.size() <= s) promoted_.resize(s + 1, false);
          promoted_[s] = true;
          shards.push_back(static_cast<int>(s));
          changed = true;
        }
      }
    }
    if (!changed) return;
    epoch = NextEpochLocked();
    owners = route_owner_;
    backups = route_backup_;
  }
  for (int s : shards) {
    repl::NotePromotion();
    Dashboard::Record("repl.promoted", 0.0);
    Log::Info("replication: promoted shard %d (rank %d dead) at epoch "
              "%lld", s, r, static_cast<long long>(epoch));
    ops::BlackboxEvent(
        "replication", "promote: shard " + std::to_string(s) +
                           " after rank " + std::to_string(r) +
                           " lease expiry, epoch " + std::to_string(epoch));
  }
  if (dropped_mine) {
    Log::Error("replication: backup rank %d dead — shard unreplicated "
               "until a new backup joins", r);
    ReleaseParkedAcks(/*all=*/true);
  }
  BroadcastRoutingEpoch(epoch, owners, backups);
  InvalidateWorkerCaches();
}

bool Zoo::JoinAsBackup(int shard) {
  if (!started_.load() || size_ <= 1 || !repl::Armed() || !net_)
    return false;
  int primary = -1;
  int64_t epoch = 0;
  std::vector<int> owners, backups;
  {
    MutexLock lk(route_mu_);
    if (shard < 0 || shard >= static_cast<int>(route_owner_.size()))
      return false;
    if (backup_shard_ >= 0 && backup_shard_ != shard)
      return false;  // factor 1: one backed shard per rank
    primary = route_owner_[shard];
    if (primary == rank_) return false;
    route_backup_[shard] = rank_;
    backup_shard_ = shard;
    epoch = NextEpochLocked();
    owners = route_owner_;
    backups = route_backup_;
  }
  // Backup instances first (a forward must never find no table), then
  // the announce (the primary starts forwarding on adoption), then the
  // snapshots — deltas between announce and snapshot are either inside
  // the snapshot or arrive behind it (FIFO), so the bytes converge.
  int32_t ntables;
  {
    MutexLock lk(tables_mu_);
    ntables = static_cast<int32_t>(table_specs_.size());
    if (backup_tables_.size() < table_specs_.size())
      backup_tables_.resize(table_specs_.size());
    for (size_t i = 0; i < table_specs_.size(); ++i) {
      if (!backup_tables_[i]) {
        backup_tables_[i] =
            MakeShard(table_specs_[i], shard, num_servers());
        if (backup_tables_[i])
          backup_tables_[i]->set_table_id(static_cast<int32_t>(i));
      }
    }
  }
  BroadcastRoutingEpoch(epoch, owners, backups);
  bool ok = true;
  for (int32_t id = 0; id < ntables; ++id) {
    int64_t mid = NextMsgId();
    auto waiter = std::make_shared<Waiter>(1);
    {
      MutexLock lk(repl_mu_);
      snapshot_pending_[mid] = waiter;
    }
    Message req;
    req.type = MsgType::ShardSnapshot;
    req.table_id = id;
    req.msg_id = mid;
    req.shard = shard;
    req.src = rank_;
    req.dst = primary;
    bool sent = net_->Send(primary, req);
    if (!sent || !waiter->WaitFor(configure::GetInt("rpc_timeout_ms")))
      ok = false;
    MutexLock lk(repl_mu_);
    snapshot_pending_.erase(mid);
  }
  if (ok)
    ops::BlackboxEvent("replication",
                       "join: rank " + std::to_string(rank_) +
                           " now backs shard " + std::to_string(shard) +
                           ", epoch " + std::to_string(epoch));
  return ok;
}

std::string Zoo::OpsReplicationJson() {
  auto owners = RouteOwners();
  auto backups = RouteBackups();
  std::vector<int> promoted;
  {
    MutexLock lk(route_mu_);
    for (size_t s = 0; s < promoted_.size(); ++s)
      if (promoted_[s]) promoted.push_back(static_cast<int>(s));
  }
  auto st = repl::GetStats();
  std::ostringstream os;
  os << "{\"rank\":" << rank_ << ",\"armed\":"
     << (repl::Armed() ? "true" : "false") << ",\"sync\":"
     << (repl::Sync() ? "true" : "false") << ",\"epoch\":"
     << RoutingEpoch() << ",\"backup_shard\":" << BackupShard();
  os << ",\"owners\":[" << JoinInts(owners) << "]";
  os << ",\"backups\":[" << JoinInts(backups) << "]";
  os << ",\"promoted\":[" << JoinInts(promoted) << "]";
  os << ",\"outstanding\":" << repl_outstanding_.load();
  os << ",\"stats\":{\"forwards\":" << st.forwards << ",\"acks\":"
     << st.acks << ",\"applied\":" << st.applied << ",\"parked\":"
     << st.parked << ",\"lag_waits\":" << st.lag_waits
     << ",\"snapshots\":" << st.snapshots << ",\"catchups\":"
     << st.catchups << ",\"promotions\":" << st.promotions
     << ",\"epoch_flips\":" << st.epoch_flips << ",\"dup_skips\":"
     << st.dup_skips << "}}";
  return os.str();
}

std::unique_ptr<ServerTable> Zoo::MakeShard(const TableSpec& spec,
                                            int sid, int nservers) {
  switch (spec.kind) {
    case TableSpec::kArray:
      return std::make_unique<ArrayServerTable>(spec.rows, updater_type_,
                                                sid, nservers);
    case TableSpec::kMatrix:
    case TableSpec::kSparseMatrix:
      // Both matrix kinds share the server shard (the sparse flavor is
      // a worker-side cache, zoo.cc registration note).
      return std::make_unique<MatrixServerTable>(
          spec.rows, spec.cols, updater_type_, sid, nservers);
    case TableSpec::kKV:
      return std::make_unique<KVServerTable>(updater_type_);
  }
  return nullptr;
}

void Zoo::RegisterBackupShard(const TableSpec& spec) {
  int32_t id = static_cast<int32_t>(table_specs_.size());
  table_specs_.push_back(spec);
  int bs = -1;
  {
    MutexLock lk(route_mu_);
    bs = backup_shard_;
  }
  std::unique_ptr<ServerTable> bt;
  if (repl::Armed() && bs >= 0)
    bt = MakeShard(spec, bs, num_servers());
  if (bt) bt->set_table_id(id);
  backup_tables_.push_back(std::move(bt));
}

void Zoo::Clock() {
  int64_t c = ++clock_;
  // Aggregated adds belong to the clock being closed: flush them BEFORE
  // the tick ships, so the per-connection FIFO keeps "min worker clock
  // >= c implies clock-c adds applied" true under aggregation.
  FlushWorkerAdds();
  // A tick is the SSP read boundary: cached rows fetched before it
  // would be served as hits FOREVER — never reaching the server where
  // MaybeHoldGet enforces `-staleness` — so the bound would silently
  // not hold.  Invalidate like Barrier does (snapshot under tables_mu_,
  // call outside — OnClockInvalidate takes the table's own lock).
  {
    std::vector<WorkerTable*> snapshot;
    {
      MutexLock lk(tables_mu_);
      for (auto& t : worker_tables_)
        if (t) snapshot.push_back(t.get());
    }
    for (auto* t : snapshot) t->OnClockInvalidate();
  }
  // Announce to every server shard, async.  Per-connection FIFO puts the
  // tick BEHIND this clock's adds on the same connection, which is what
  // makes "min worker clock >= c" mean those adds are applied.
  for (int s = 0; s < num_servers(); ++s) {
    auto msg = std::make_unique<Message>();
    msg->type = MsgType::ClockTick;
    msg->msg_id = c;
    msg->src = rank_;
    msg->dst = server_rank(s);
    SendTo(actor::kWorker, std::move(msg));
  }
}

static int64_t NowMs() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void Zoo::PurgeExpiredHeldLocked(std::vector<MessagePtr>* expired) {
  int64_t now = NowMs();
  auto keep = held_gets_.begin();
  for (auto& [deadline, m] : held_gets_) {
    if (deadline > 0 && now >= deadline)
      expired->push_back(std::move(m));
    else
      *keep++ = {deadline, std::move(m)};
  }
  held_gets_.erase(keep, held_gets_.end());
}

void Zoo::FailHeldGets(std::vector<MessagePtr> expired) {
  // A dead straggler's clock may never advance: fail the parked get
  // fast (the caller's RoundTrip sees ReplyError -> rc=-3) instead of
  // leaking it — the SSP analog of Deliver's dead-peer synthesis.
  for (auto& m : expired) {
    Log::Error("SSP: held get from rank %d expired (straggler stuck?)",
               m->src);
    auto err = std::make_unique<Message>();
    err->type = MsgType::ReplyError;
    err->table_id = m->table_id;
    err->msg_id = m->msg_id;
    err->src = rank_;
    err->dst = m->src;
    Deliver(actor::kWorker, std::move(err));
  }
}

bool Zoo::HeldBySspLocked(int src) {
  // Admission predicate (ssp_mu_ held): src runs more than `staleness`
  // ticks ahead of the QUORUM clock.  With -backup_worker_ratio=0 (the
  // default) the quorum is every worker, so the quorum clock is the
  // slowest worker's — plain sync semantics.  With ratio r > 0
  // (reference include/multiverso/server.h sync variant, SURVEY §2.9)
  // the slowest floor(r·N) workers are backup slack: clock t counts as
  // reached once ceil((1-r)·N) workers ticked it, so a straggler
  // beyond the allowance cannot park the fleet's reads.  Its late adds
  // are NOT dropped — they apply on arrival, i.e. fold into whichever
  // clock is then open (the reference's fold-into-next-clock).
  int64_t s = configure::GetInt("staleness");
  if (worker_clocks_.size() != static_cast<size_t>(size_))
    worker_clocks_.assign(size_, 0);
  if (src < 0 || src >= size_) return false;
  int64_t mine = worker_clocks_[src];
  double ratio = configure::GetDouble("backup_worker_ratio");
  if (ratio <= 0.0) {
    // Default path, run per admission check on the server hot path:
    // allocation-free single-pass min (quorum == all workers).
    int64_t slowest = mine;
    for (int r : worker_ranks_)
      slowest = std::min(slowest, worker_clocks_[r]);
    return mine - slowest > s;
  }
  std::vector<int64_t> clocks;
  clocks.reserve(worker_ranks_.size());
  for (int r : worker_ranks_) clocks.push_back(worker_clocks_[r]);
  if (clocks.empty()) return false;
  int n = static_cast<int>(clocks.size());
  int quorum = std::min(
      n, std::max(1, static_cast<int>(std::ceil((1.0 - ratio) * n))));
  // The quorum-th FASTEST worker's clock = the highest clock at least
  // `quorum` workers have reached.
  std::nth_element(clocks.begin(), clocks.begin() + (quorum - 1),
                   clocks.end(), std::greater<int64_t>());
  int64_t quorum_clock = clocks[quorum - 1];
  return mine - quorum_clock > s;
}

bool Zoo::MaybeHoldGet(MessagePtr& msg) {
  std::vector<MessagePtr> expired;
  bool held = false;
  {
    MutexLock lk(ssp_mu_);
    PurgeExpiredHeldLocked(&expired);
    if (HeldBySspLocked(msg->src)) {
      int64_t t = configure::GetInt("rpc_timeout_ms");
      held_gets_.emplace_back(t > 0 ? NowMs() + t : 0, std::move(msg));
      held = true;
    }
  }
  FailHeldGets(std::move(expired));
  return held;
}

void Zoo::OnClockTick(int src_rank, int64_t clock) {
  std::vector<MessagePtr> admit;
  std::vector<MessagePtr> expired;
  {
    MutexLock lk(ssp_mu_);
    PurgeExpiredHeldLocked(&expired);
    if (worker_clocks_.size() != static_cast<size_t>(size_))
      worker_clocks_.assign(size_, 0);
    if (src_rank >= 0 && src_rank < size_) {
      worker_clocks_[src_rank] =
          std::max(worker_clocks_[src_rank], clock);
      // Admission decided IN PLACE: only now-admitted gets re-deliver
      // (through the server mailbox, so the normal handler reruns).
      // Still-held gets KEEP their original park deadline — a blanket
      // release-and-repark would refresh deadlines on every tick and a
      // dead straggler's parks would never expire while live workers
      // keep ticking.
      auto keep = held_gets_.begin();
      for (auto& [deadline, m] : held_gets_) {
        if (!HeldBySspLocked(m->src))
          admit.push_back(std::move(m));
        else
          *keep++ = {deadline, std::move(m)};
      }
      held_gets_.erase(keep, held_gets_.end());
    }
  }
  FailHeldGets(std::move(expired));
  for (auto& m : admit) SendTo(actor::kServer, std::move(m));
}

void Zoo::SetRoles(const std::vector<int>& roles) {
  worker_ranks_.clear();
  server_ranks_.clear();
  for (size_t r = 0; r < roles.size(); ++r) {
    if (roles[r] & kRoleWorker) worker_ranks_.push_back(static_cast<int>(r));
    if (roles[r] & kRoleServer) server_ranks_.push_back(static_cast<int>(r));
  }
  if (server_ranks_.empty())
    Log::Error("no server-role rank registered — tables have no shards");
}

int Zoo::ServeQueueDepth() {
  MutexLock lk(mu_);
  return server_actor_ ? static_cast<int>(server_actor_->QueueSize()) : 0;
}

bool Zoo::DropServeRead(MessagePtr& msg) {
  // Tail plane (docs/serving.md "tail"): reads only — the two dequeue
  // drop reasons that mean "nobody is waiting for this answer".
  bool cancelled = qos::Cancelled(msg->src, msg->msg_id);
  bool expired = !cancelled && qos::ShedExpired(*msg);
  if (!cancelled && !expired) return false;
  Log::Debug("serve: dropping %s read from %d at dequeue (msg %lld)",
             cancelled ? "cancelled" : "deadline-expired", msg->src,
             static_cast<long long>(msg->msg_id));
  // An anonymous client's dropped read settles its reactor admission
  // slots here — no reply will ever route back to release them.
  if (transport::IsClientRank(msg->src) && net_)
    net_->SettleClient(msg->src);
  return true;
}

bool Zoo::ShedIfOverloaded(MessagePtr& msg) {
  int64_t max_inflight = configure::GetInt("server_inflight_max");
  if (max_inflight <= 0) return false;
  int depth = ServeQueueDepth();
  // Depth histogram in the µs-bucket Dashboard (1 unit = 1 µs): bucket
  // i ≈ depth 2^i, so the Dump shows the backlog distribution and
  // `serve.queue_depth`'s total/count is the mean depth per sample.
  Dashboard::Record("serve.queue_depth", depth * 1e-6);
  if (depth < max_inflight) {
    // An admit ends the shed streak: the storm detector counts
    // CONSECUTIVE sheds, re-arming once the server breathes again.
    shed_streak_.store(0);
    shed_storm_latched_.store(false);
    return false;
  }
  Dashboard::Record("serve.shed", 0.0);
  int64_t storm = configure::GetInt("shed_storm_threshold");
  long long streak = shed_streak_.fetch_add(1) + 1;
  if (storm > 0 && streak >= storm &&
      !shed_storm_latched_.exchange(true))
    ops::BlackboxTrigger("shed_storm: " + std::to_string(streak) +
                         " consecutive busy-sheds at queue depth " +
                         std::to_string(depth));
  auto reply = std::make_unique<Message>();
  reply->type = MsgType::ReplyBusy;
  reply->table_id = msg->table_id;
  reply->msg_id = msg->msg_id;
  reply->trace_id = msg->trace_id;
  reply->src = rank_;
  reply->dst = msg->src;
  latency::StampReply(*msg, reply.get());
  Deliver(actor::kWorker, std::move(reply));
  return true;
}

// ---- introspection plane (docs/observability.md) ----------------------

std::string Zoo::OpsHealthJson() {
  std::ostringstream os;
  bool up = started_.load();
  os << "{\"started\":" << (up ? "true" : "false");
  if (!up) {
    os << ",\"ready\":false,\"healthy\":false}";
    return os.str();
  }
  int64_t inflight_max = configure::GetInt("server_inflight_max");
  int depth = ServeQueueDepth();
  bool overloaded = inflight_max > 0 && depth >= inflight_max;
  auto dead = DeadPeers();
  auto fanin = FanIn();
  os << ",\"rank\":" << rank_ << ",\"size\":" << size_;
  os << ",\"engine\":\"" << net_engine() << "\"";
  // Engine-degradation record: `engine` above is the EFFECTIVE engine;
  // these say what was asked for and whether Start downgraded (uring
  // probe failure -> epoll).  mvtop/mvdoctor surface the mismatch.
  os << ",\"engine_requested\":\""
     << (engine_requested_.empty() ? net_engine()
                                   : engine_requested_.c_str())
     << "\"";
  os << ",\"engine_fallback\":" << (engine_fallback_ ? "true" : "false");
  os << ",\"workers\":" << num_workers() << ",\"servers\":"
     << num_servers();
  os << ",\"is_server\":" << (server_id() >= 0 ? "true" : "false");
  os << ",\"clock\":" << clock_.load();
  os << ",\"serve_queue_depth\":" << depth;
  os << ",\"server_inflight_max\":" << inflight_max;
  os << ",\"dead_peers\":[" << JoinInts(dead) << "]";
  os << ",\"clients\":" << fanin.active_clients;
  os << ",\"clients_accepted\":" << fanin.accepted_total;
  os << ",\"client_shed\":" << fanin.client_shed;
  os << ",\"blackbox_triggers\":" << ops::BlackboxTriggerCount();
  // Host-level process stats (docs/observability.md "capacity plane"):
  // RSS / peak RSS / open fds / uptime from /proc/self, so a health
  // scrape answers "is this host running out of memory or fds" without
  // a second probe.
  {
    capacity::ProcStats proc = capacity::Proc();
    char num[64];
    os << ",\"rss_bytes\":" << proc.rss_bytes;
    os << ",\"vm_hwm_bytes\":" << proc.vm_hwm_bytes;
    os << ",\"open_fds\":" << proc.open_fds;
    std::snprintf(num, sizeof(num), "%.3f", proc.uptime_s);
    os << ",\"uptime_s\":" << num;
  }
  // Readiness: the runtime answers requests at all; health: it is not
  // drowning (queue within the shed bound) and, on the lease authority,
  // the fleet has no expired peers.
  os << ",\"ready\":true";
  os << ",\"healthy\":" << (!overloaded && dead.empty() ? "true" : "false");
  os << "}";
  return os.str();
}

std::string Zoo::OpsTablesJson() {
  // Snapshot pointers under tables_mu_, read stats OUTSIDE it: the
  // accessors take per-table locks, and tables are never unregistered.
  std::vector<std::pair<WorkerTable*, ServerTable*>> snapshot;
  {
    MutexLock lk(tables_mu_);
    for (size_t i = 0; i < worker_tables_.size(); ++i)
      snapshot.emplace_back(
          worker_tables_[i].get(),
          i < server_tables_.size() ? server_tables_[i].get() : nullptr);
  }
  std::ostringstream os;
  os << "[";
  for (size_t i = 0; i < snapshot.size(); ++i) {
    auto [wt, st] = snapshot[i];
    if (i) os << ',';
    os << "{\"id\":" << i;
    if (wt) {
      os << ",\"codec\":\"" << codec::Name(wt->wire_codec()) << "\"";
      os << ",\"last_version\":" << wt->last_version();
      os << ",\"agg_pending\":" << wt->agg_pending();
      // Hot-key replica side-table entries are their OWN field, NEVER
      // folded into the shard row count below: a replicated row is a
      // COPY of a row some shard already owns, and capacity math that
      // summed both would count it twice after a PR 10 replica install
      // (the double-count bugfix; regression-tested with an armed
      // replica in tests/test_capacity.py).
      if (auto* mw = dynamic_cast<MatrixWorkerTable*>(wt))
        os << ",\"replica_rows\":" << mw->replica_stats().rows;
    }
    if (st) {
      // Shard-resident entries only (matrix rows / KV entries / array
      // elements) — the capacity plane's row count.
      auto cap = st->Capacity();
      os << ",\"rows\":" << cap.rows;
      os << ",\"resident_bytes\":" << cap.bytes;
      int64_t v = st->version();
      int64_t lo = v, hi = 0;
      for (int b = 0; b < ServerTable::kVersionBuckets; ++b) {
        int64_t bv = st->bucket_version(b);
        lo = std::min(lo, bv);
        hi = std::max(hi, bv);
      }
      os << ",\"version\":" << v;
      os << ",\"bucket_version_min\":" << lo;
      os << ",\"bucket_version_max\":" << hi;
      os << ",\"bucket_version_spread\":" << (hi - lo);
      // Workload plane (docs/observability.md): load totals, skew,
      // observed staleness, and update-health sentinels ride the same
      // report so mvtop's table view needs one scrape, not two.
      auto load = st->Load();
      char num[64];
      os << ",\"gets\":" << load.gets << ",\"adds\":" << load.adds;
      std::snprintf(num, sizeof(num), "%.6g", load.skew_ratio);
      os << ",\"skew_ratio\":" << num;
      os << ",\"bucket_load_max\":" << load.bucket_load_max;
      std::snprintf(num, sizeof(num), "%.6g", load.bucket_load_mean);
      os << ",\"bucket_load_mean\":" << num;
      std::snprintf(num, sizeof(num), "%.6g", load.add_l2);
      os << ",\"add_l2\":" << num;
      std::snprintf(num, sizeof(num), "%.6g", load.add_linf);
      os << ",\"add_linf\":" << num;
      os << ",\"nan_count\":" << load.nan_count;
      os << ",\"inf_count\":" << load.inf_count;
      os << ",\"staleness_count\":" << load.staleness_count;
      std::snprintf(num, sizeof(num), "%.6g", load.staleness_mean);
      os << ",\"staleness_mean\":" << num;
    } else {
      os << ",\"shard\":null";
    }
    os << "}";
  }
  os << "]";
  return os.str();
}

struct Zoo::OpsPending {
  std::shared_ptr<Waiter> waiter;
  Mutex mu;
  std::map<int, std::string> replies GUARDED_BY(mu);  // rank -> payload
};

void Zoo::HandleOpsQuery(MessagePtr msg) {
  if (msg->src < 0 || msg->src == rank_) return;  // no route back
  if (msg->version != 1) {
    // Local scope: build + answer right here (transport reader thread —
    // the epoll engine answers even earlier, at the reactor).
    auto reply = std::make_unique<Message>();
    ops::BuildReply(*msg, reply.get());
    reply->src = rank_;
    reply->dst = msg->src;
    Deliver(actor::kWorker, std::move(reply));
    return;
  }
  // Fleet scope: bounded fan-out on a detached (but counted) thread —
  // the deadline wait must never park a transport/reactor thread.
  int cap = static_cast<int>(
      std::max<int64_t>(1, configure::GetInt("ops_inflight_max")));
  if (ops_inflight_.load() >= cap) {
    auto reply = std::make_unique<Message>();
    std::string busy = "{\"error\":\"ops busy: " + std::to_string(cap) +
                       " fleet queries already in flight\"}";
    reply->type = MsgType::OpsReply;
    reply->msg_id = msg->msg_id;
    reply->trace_id = msg->trace_id;
    reply->version = 1;
    reply->src = rank_;
    reply->dst = msg->src;
    reply->data.emplace_back(busy.data(), busy.size());
    Deliver(actor::kWorker, std::move(reply));
    return;
  }
  ops_inflight_.fetch_add(1);
  // Deep-copy the query OUT of the receive arena before detaching (the
  // kind blob may be a Blob::View into a reactor slab).
  Message q;
  q.src = msg->src;
  q.msg_id = msg->msg_id;
  q.trace_id = msg->trace_id;
  q.version = msg->version;
  if (!msg->data.empty()) {
    Blob kind;
    kind.CopyFrom(msg->data[0]);
    q.data.push_back(kind);
  }
  int64_t id = NextMsgId();
  std::thread([this, id, q]() mutable {
    FleetOpsThread(id, std::move(q));
    ops_inflight_.fetch_add(-1);
  }).detach();
}

void Zoo::OnOpsReply(MessagePtr msg) {
  std::shared_ptr<OpsPending> p;
  {
    MutexLock lk(ops_mu_);
    auto it = ops_pending_.find(msg->msg_id);
    if (it == ops_pending_.end()) return;  // past the deadline: dropped
    p = it->second;
  }
  std::string text;
  if (!msg->data.empty())
    text.assign(msg->data[0].data(), msg->data[0].size());
  {
    MutexLock lk(p->mu);
    p->replies[msg->src] = std::move(text);
  }
  p->waiter->Notify();
}

namespace {
// Inject a rank label into one Prometheus exposition line:
//   name{a="b"} v      ->  name{rank="0",a="b"} v
//   name v # {...} e   ->  name{rank="0"} v # {...} e
// Comment lines return "" (a fleet merge keeps data lines only — the
// per-rank # TYPE duplicates would be invalid exposition).
std::string InjectRankLabel(const std::string& line, int rank) {
  if (line.empty() || line[0] == '#') return "";
  std::string label = "rank=\"" + std::to_string(rank) + "\"";
  size_t space = line.find(' ');
  size_t brace = line.find('{');
  if (brace != std::string::npos &&
      (space == std::string::npos || brace < space))
    return line.substr(0, brace + 1) + label + "," +
           line.substr(brace + 1);
  if (space == std::string::npos) return line;  // malformed: keep as-is
  return line.substr(0, space) + "{" + label + "}" + line.substr(space);
}
}  // namespace

std::string Zoo::OpsHotKeysJson(int32_t id) {
  // Snapshot pointers under tables_mu_, read stats OUTSIDE it (the
  // accessors take per-table/tracker locks; tables never unregister).
  std::vector<ServerTable*> snapshot;
  std::vector<WorkerTable*> workers;
  {
    MutexLock lk(tables_mu_);
    for (auto& t : server_tables_)
      snapshot.push_back(t.get());
    for (auto& t : worker_tables_)
      workers.push_back(t.get());
  }
  std::ostringstream os;
  os << "[";
  bool first = true;
  for (size_t i = 0; i < snapshot.size(); ++i) {
    if (id >= 0 && static_cast<size_t>(id) != i) continue;
    ServerTable* st = snapshot[i];
    if (!first) os << ',';
    first = false;
    os << "{\"id\":" << i;
    if (!st) {
      os << ",\"shard\":null}";
      continue;
    }
    auto load = st->Load();
    char num[64];
    os << ",\"gets\":" << load.gets << ",\"adds\":" << load.adds;
    std::snprintf(num, sizeof(num), "%.6g", load.skew_ratio);
    os << ",\"skew_ratio\":" << num;
    os << ",\"bucket_load_max\":" << load.bucket_load_max;
    std::snprintf(num, sizeof(num), "%.6g", load.bucket_load_mean);
    os << ",\"bucket_load_mean\":" << num;
    std::snprintf(num, sizeof(num), "%.6g", load.add_l2);
    os << ",\"add_l2\":" << num;
    std::snprintf(num, sizeof(num), "%.6g", load.add_linf);
    os << ",\"add_linf\":" << num;
    os << ",\"nan_count\":" << load.nan_count;
    os << ",\"inf_count\":" << load.inf_count;
    os << ",\"staleness_count\":" << load.staleness_count;
    std::snprintf(num, sizeof(num), "%.6g", load.staleness_mean);
    os << ",\"staleness_mean\":" << num;
    os << ",\"armed\":" << (workload::Armed() ? "true" : "false");
    // Hot-key replica plane (docs/embedding.md): this shard's push
    // count plus the co-located worker stub's replica hit ledger (in
    // static mode every rank carries both roles, so the pair describes
    // the rank's full replica participation).
    os << ",\"replica\":{\"armed\":"
       << (workload::ReplicaArmed() ? "true" : "false");
    os << ",\"pushes\":" << st->replica_pushes();
    auto* mw = i < workers.size()
                   ? dynamic_cast<MatrixWorkerTable*>(workers[i])
                   : nullptr;
    if (mw) {
      auto rs = mw->replica_stats();
      os << ",\"hits\":" << rs.hits << ",\"misses\":" << rs.misses
         << ",\"rows\":" << rs.rows << ",\"refreshes\":" << rs.refreshes;
    }
    os << "}";
    os << ",\"hotkeys\":" << st->HotKeysJson();
    os << "}";
  }
  os << "]";
  return os.str();
}

std::string Zoo::OpsAuditJson() {
  // Snapshot pointers under tables_mu_, read books OUTSIDE it (the
  // accessors take per-book locks; tables never unregister).
  std::vector<std::tuple<WorkerTable*, ServerTable*, ServerTable*>>
      snapshot;
  {
    MutexLock lk(tables_mu_);
    for (size_t i = 0; i < worker_tables_.size(); ++i)
      snapshot.emplace_back(
          worker_tables_[i].get(),
          i < server_tables_.size() ? server_tables_[i].get() : nullptr,
          i < backup_tables_.size() ? backup_tables_[i].get() : nullptr);
  }
  int bshard = BackupShard();
  std::ostringstream os;
  os << "{\"rank\":" << rank_ << ",\"armed\":"
     << (audit::Armed() ? "true" : "false")
     << ",\"backup_shard\":" << bshard << ",\"tables\":[";
  auto emit_sums = [&os](ServerTable* t) {
    os << "[";
    auto sums = t->BucketChecksums();
    for (size_t b = 0; b < sums.size(); ++b) {
      if (b) os << ',';
      os << sums[b];
    }
    os << "]";
  };
  for (size_t i = 0; i < snapshot.size(); ++i) {
    auto [wt, st, bt] = snapshot[i];
    if (i) os << ',';
    os << "{\"id\":" << i;
    if (wt) os << ",\"worker\":" << wt->AuditLedgerJson();
    if (st) {
      // A gap with no follow-up traffic must still fire its grace
      // deadline — the scrape IS the periodic sweep.
      st->audit_book().CheckGaps(static_cast<int32_t>(i));
      os << ",\"server\":" << st->audit_book().Json();
      os << ",\"checksums\":";
      emit_sums(st);
    } else {
      os << ",\"server\":null";
    }
    if (bt) {
      // Replication plane (docs/replication.md): the backed shard's
      // book + beacons, so mvaudit can diff primary vs backup —
      // identical rows must report identical bucket checksums.
      bt->audit_book().CheckGaps(static_cast<int32_t>(i));
      os << ",\"backup\":" << bt->audit_book().Json();
      os << ",\"backup_checksums\":";
      emit_sums(bt);
    }
    os << "}";
  }
  os << "]}";
  return os.str();
}

std::string Zoo::OpsCapacityJson() {
  // Snapshot pointers under tables_mu_, read stats OUTSIDE it (the
  // accessors take per-table locks; tables never unregister).
  std::vector<std::tuple<WorkerTable*, ServerTable*, ServerTable*>>
      snapshot;
  {
    MutexLock lk(tables_mu_);
    for (size_t i = 0; i < worker_tables_.size(); ++i)
      snapshot.emplace_back(
          worker_tables_[i].get(),
          i < server_tables_.size() ? server_tables_[i].get() : nullptr,
          i < backup_tables_.size() ? backup_tables_[i].get() : nullptr);
  }
  // History windows record at most once per -capacity_history_ms, all
  // tables together (one shared clock keeps windows aligned), so a
  // watch-mode scraper accumulates the rate curve as a side effect.
  bool record = capacity::HistoryDue();
  std::ostringstream os;
  os << "{\"rank\":" << rank_;
  os << ",\"armed\":" << (capacity::Armed() ? "true" : "false");
  os << ",\"server_id\":" << server_id();
  os << ",\"servers\":" << num_servers();
  os << ",\"proc\":" << capacity::ProcJson();
  {
    HostArena::Stats a = HostArena::Get()->GetStats();
    os << ",\"arena\":{\"buffers\":" << a.buffers
       << ",\"free_buffers\":" << a.free_buffers
       << ",\"bytes\":" << a.bytes << ",\"in_flight\":" << a.in_flight
       << ",\"deferred\":" << a.deferred << "}";
  }
  os << ",\"net\":{\"engine\":\"" << net_engine()
     << "\",\"writeq_bytes\":" << (net_ ? net_->QueuedBytes() : 0)
     << ",\"rx_arena_bytes\":" << (net_ ? net_->RxArenaBytes() : 0) << "}";
  os << ",\"gauges\":" << capacity::GaugesJson();
  os << ",\"tables\":[";
  for (size_t i = 0; i < snapshot.size(); ++i) {
    auto [wt, st, bt] = snapshot[i];
    if (i) os << ',';
    os << "{\"id\":" << i;
    if (st) {
      auto cap = st->Capacity();
      int64_t bucket_gets[capacity::kLoadBuckets];
      int64_t bucket_adds[capacity::kLoadBuckets];
      st->BucketLoads(bucket_gets, bucket_adds);
      os << ",\"shard\":{\"resident_bytes\":" << cap.bytes
         << ",\"rows\":" << cap.rows;
      os << ",\"gets\":" << st->total_gets()
         << ",\"adds\":" << st->total_adds();
      auto emit_i64 = [&os](const char* name, const int64_t* v, int n) {
        os << ",\"" << name << "\":[";
        for (int b = 0; b < n; ++b) {
          if (b) os << ',';
          os << v[b];
        }
        os << "]";
      };
      auto bb = st->BucketBytes();
      emit_i64("bucket_bytes", bb.data(),
               static_cast<int>(bb.size()));
      emit_i64("bucket_gets", bucket_gets, capacity::kLoadBuckets);
      emit_i64("bucket_adds", bucket_adds, capacity::kLoadBuckets);
      os << "}";
      if (record) {
        int64_t load[capacity::kLoadBuckets];
        for (int b = 0; b < capacity::kLoadBuckets; ++b)
          load[b] = bucket_gets[b] + bucket_adds[b];
        capacity::RecordHistory(static_cast<int32_t>(i),
                                st->total_gets(), st->total_adds(),
                                cap.bytes, load);
      }
      os << ",\"history\":"
         << capacity::HistoryJson(static_cast<int32_t>(i));
    } else {
      os << ",\"shard\":null";
    }
    if (bt) os << ",\"backup_bytes\":" << bt->Capacity().bytes;
    if (wt) {
      os << ",\"worker\":{\"agg_bytes\":" << wt->agg_bytes();
      // Side-table bytes are their OWN fields (never folded into the
      // shard count — the replica double-count fix, PR 15).
      if (auto* mw = dynamic_cast<MatrixWorkerTable*>(wt)) {
        auto rs = mw->replica_stats();
        os << ",\"replica_rows\":" << rs.rows
           << ",\"replica_bytes\":" << mw->replica_bytes();
      }
      if (auto* kw = dynamic_cast<KVWorkerTable*>(wt))
        os << ",\"cache_bytes\":" << kw->cache_bytes();
      os << "}";
    }
    os << "}";
  }
  os << "]}";
  return os.str();
}

void Zoo::RecomputeCapacityAll() {
  std::vector<ServerTable*> tables;
  {
    MutexLock lk(tables_mu_);
    for (auto& t : server_tables_)
      if (t) tables.push_back(t.get());
    for (auto& t : backup_tables_)
      if (t) tables.push_back(t.get());
  }
  for (auto* t : tables) t->RecomputeCapacity();
}

std::string Zoo::FleetReport(const std::string& kind) {
  // Synchronous fleet aggregation from THIS rank — the engine-agnostic
  // twin of an inbound fleet-scope OpsQuery (on the blocking tcp
  // engine no anonymous scraper can connect, but a rank can still
  // assemble the fleet view itself over the rank wire).
  if (!started_.load()) return "{\"error\":\"not started\"}";
  ops_inflight_.fetch_add(1);  // Stop drains us before the wire dies
  std::string out = FleetCollect(kind, Dashboard::ThreadTraceId(),
                                 NextMsgId());
  ops_inflight_.fetch_add(-1);
  return out;
}

void Zoo::FleetOpsThread(int64_t id, Message query) {
  std::string kind = "health";
  if (!query.data.empty() && query.data[0].size() > 0)
    kind.assign(query.data[0].data(), query.data[0].size());

  std::string merged = FleetCollect(kind, query.trace_id, id);

  auto reply = std::make_unique<Message>();
  reply->type = MsgType::OpsReply;
  reply->msg_id = query.msg_id;
  reply->trace_id = query.trace_id;
  reply->version = 1;
  reply->src = rank_;
  reply->dst = query.src;
  reply->data.emplace_back(merged.data(), merged.size());
  Deliver(actor::kWorker, std::move(reply));
}

std::string Zoo::FleetCollect(const std::string& kind, int64_t trace_id,
                              int64_t id) {
  std::vector<int> targets;
  for (int r = 0; r < size_; ++r)
    if (r != rank_) targets.push_back(r);

  auto pending = std::make_shared<OpsPending>();
  pending->waiter =
      std::make_shared<Waiter>(static_cast<int>(targets.size()));
  if (!targets.empty()) {
    {
      MutexLock lk(ops_mu_);
      ops_pending_[id] = pending;
    }
    for (int r : targets) {
      auto sub = std::make_unique<Message>();
      sub->type = MsgType::OpsQuery;
      sub->msg_id = id;
      sub->trace_id = trace_id;
      sub->version = 0;  // local scope at the peer
      sub->src = rank_;
      sub->dst = r;
      sub->data.emplace_back(kind.data(), kind.size());
      if (net_) net_->Send(r, *sub);
    }
    pending->waiter->WaitFor(configure::GetInt("ops_fleet_timeout_ms"));
    MutexLock lk(ops_mu_);
    ops_pending_.erase(id);
  }

  std::map<int, std::string> replies;
  {
    MutexLock lk(pending->mu);
    replies = pending->replies;
  }
  replies[rank_] = ops::LocalReport(kind);
  std::vector<int> silent;
  for (int r : targets)
    if (!replies.count(r)) silent.push_back(r);
  std::vector<int> dead = DeadPeers();

  std::ostringstream os;
  if (kind == "metrics") {
    // Per-rank labels on every series; silent ranks are explicit
    // zero-valued mv_ops_rank_up series, never just missing data.
    os << "# fleet scrape from rank " << rank_ << " (" << replies.size()
       << "/" << size_ << " ranks)\n";
    for (auto& [r, text] : replies) {
      std::istringstream in(text);
      std::string line;
      while (std::getline(in, line)) {
        std::string labeled = InjectRankLabel(line, r);
        if (!labeled.empty()) os << labeled << '\n';
      }
    }
    for (int r = 0; r < size_; ++r)
      os << "mv_ops_rank_up{rank=\"" << r << "\"} "
         << (replies.count(r) ? 1 : 0) << '\n';
    for (int r : dead)
      os << "mv_ops_rank_dead{rank=\"" << r << "\"} 1\n";
  } else {
    os << "{\"scope\":\"fleet\",\"kind\":\"" << kind
       << "\",\"aggregator\":" << rank_ << ",\"size\":" << size_;
    os << ",\"silent\":[" << JoinInts(silent) << "]";
    os << ",\"dead\":[" << JoinInts(dead) << "]";
    os << ",\"ranks\":{";
    bool first = true;
    for (int r = 0; r < size_; ++r) {
      if (!first) os << ',';
      first = false;
      os << "\"" << r << "\":";
      auto it = replies.find(r);
      os << (it == replies.end() ? std::string("null") : it->second);
    }
    os << "}}";
  }
  return os.str();
}

void Zoo::SendTo(const std::string& actor_name, MessagePtr msg) {
  // Snapshot the pointer AND push under mu_ so a concurrent Stop cannot
  // free the actor between the lookup and the mailbox push.
  MutexLock lk(mu_);
  Actor* a = nullptr;
  if (actor_name == actor::kWorker) a = worker_actor_.get();
  else if (actor_name == actor::kServer) a = server_actor_.get();
  else if (actor_name == actor::kController) a = controller_actor_.get();
  if (!a) {
    Log::Error("SendTo: unknown or stopped actor '%s'", actor_name.c_str());
    return;
  }
  a->Receive(std::move(msg));
}

void Zoo::Deliver(const std::string& actor_name, MessagePtr msg) {
  // Latency trail: the transport hand-off stamp (requests close the
  // client queue stage, replies open the wire_back stage) — taken for
  // local deliveries too, so a single process still attributes its
  // mailbox and apply stages.
  latency::StampSend(msg.get());
  if (msg->dst < 0 || msg->dst == rank_ || !net_) {
    SendTo(actor_name, std::move(msg));
    return;
  }
  if (net_->Send(msg->dst, *msg)) return;
  // Unreachable peer: fail blocking callers fast instead of hanging.
  switch (msg->type) {
    case MsgType::RequestGet:
    case MsgType::RequestAdd:
    case MsgType::RequestVersion: {
      if (msg->msg_id < 0) return;  // async add: nothing waits
      auto err = std::make_unique<Message>();
      err->type = MsgType::ReplyError;
      err->table_id = msg->table_id;
      err->msg_id = msg->msg_id;
      err->src = msg->dst;          // "from" the dead shard
      err->dst = rank_;
      SendTo(actor::kWorker, std::move(err));
      break;
    }
    case MsgType::RequestFlush: {
      // Dead shard: nothing to drain there — ack so Barrier proceeds,
      // but latch the failure so it reports false.
      {
        MutexLock lk(barrier_mu_);
        barrier_failed_ = true;
      }
      OnFlushReply(msg->msg_id);
      break;
    }
    case MsgType::ControlBarrier: {
      // Rank 0 unreachable: latch the failure, then release the local
      // waiter so Barrier() returns FALSE immediately instead of either
      // hanging or (worse) reporting a successful rendezvous.
      Log::Error("Zoo::Deliver: barrier authority (rank 0) unreachable");
      {
        MutexLock lk(barrier_mu_);
        barrier_failed_ = true;
      }
      OnBarrierRelease();
      break;
    }
    default:
      // Reply to a dead requester / release to a dead peer: that
      // process's state is gone — drop, the log already has the error.
      break;
  }
}

void Zoo::RouteInbound(Message&& m) {
  auto msg = std::make_unique<Message>(std::move(m));
  switch (msg->type) {
    case MsgType::RequestGet:
    case MsgType::RequestAdd:
    case MsgType::RequestFlush:
    case MsgType::RequestVersion:
    case MsgType::RequestReplica:
    case MsgType::ClockTick:
      SendTo(actor::kServer, std::move(msg));
      break;
    case MsgType::ReplyGet:
    case MsgType::ReplyAdd:
    case MsgType::ReplyFlush:
    case MsgType::ReplyVersion:
    case MsgType::ReplyReplica:
    case MsgType::ReplyBusy:
      SendTo(actor::kWorker, std::move(msg));
      break;
    case MsgType::ControlBarrier:
    case MsgType::ControlBarrierReply:
    case MsgType::Heartbeat:
      SendTo(actor::kController, std::move(msg));
      break;
    // Introspection plane: NEVER through the actor mailbox — a wedged
    // server must still answer its scrape.  (On the epoll engine the
    // reactor already answered local-scope queries before inbound_;
    // only fleet-scope queries and fan-out replies reach here.)
    // Hedge-cancel token (docs/serving.md "tail"): consumed at the
    // transport layer, never the mailbox — on the epoll engine the
    // reactor already ate it; this is the blocking/MPI engines' path.
    case MsgType::RequestCancel:
      qos::NoteCancel(msg->src, msg->msg_id);
      break;
    // Replication plane (docs/replication.md): forwards + snapshots go
    // through the server actor (serialized with applies); acks and
    // routing-epoch flips are consumed at the transport layer so a
    // primary's apply thread waiting on its backup can always make
    // progress, and promotions are controller-plane.
    case MsgType::ReplForward:
    case MsgType::ShardSnapshot:
      SendTo(actor::kServer, std::move(msg));
      break;
    case MsgType::ReplAck:
      OnReplAck(std::move(msg));
      break;
    case MsgType::RoutingEpoch:
      OnRoutingEpoch(std::move(msg));
      break;
    case MsgType::Promote:
      SendTo(actor::kController, std::move(msg));
      break;
    case MsgType::OpsQuery:
      HandleOpsQuery(std::move(msg));
      break;
    case MsgType::OpsReply:
      OnOpsReply(std::move(msg));
      break;
    default:
      Log::Error("RouteInbound: unhandled message type %d",
                 static_cast<int>(msg->type));
  }
}

namespace {
// Table-creation codec negotiation (docs/wire_compression.md): every
// new worker stub starts on the `-wire_codec` default; MV_SetTableCodec
// can retarget one table afterwards.
Codec DefaultCodec() {
  return configure::Has("wire_codec")
             ? codec::FromName(configure::GetString("wire_codec"))
             : Codec::kRaw;
}
}  // namespace

int32_t Zoo::RegisterArrayTable(int64_t size) {
  MutexLock lk(tables_mu_);
  int32_t id = static_cast<int32_t>(server_tables_.size());
  // Shards live on server-role ranks only; a worker-only rank registers
  // a null server slot (ids must line up across every rank).
  int sid = server_id();
  server_tables_.push_back(
      sid < 0 ? nullptr
              : std::make_unique<ArrayServerTable>(size, updater_type_,
                                                   sid, num_servers()));
  if (server_tables_.back()) server_tables_.back()->set_table_id(id);
  RegisterBackupShard(TableSpec{TableSpec::kArray, size, 0});
  worker_tables_.push_back(
      std::make_unique<ArrayWorkerTable>(id, size, num_servers()));
  worker_tables_.back()->set_codec(DefaultCodec());
  return id;
}

// Both matrix kinds share the server shard (only requested rows ever
// ride the wire); the sparse table's value-add is purely the
// WORKER-side row cache, so registration differs only in the
// worker-table type.
template <typename WorkerT>
int32_t Zoo::RegisterMatrixTableImpl(int64_t rows, int64_t cols) {
  MutexLock lk(tables_mu_);
  int32_t id = static_cast<int32_t>(server_tables_.size());
  int sid = server_id();
  server_tables_.push_back(
      sid < 0 ? nullptr
              : std::make_unique<MatrixServerTable>(
                    rows, cols, updater_type_, sid, num_servers()));
  if (server_tables_.back()) server_tables_.back()->set_table_id(id);
  RegisterBackupShard(TableSpec{
      std::is_same<WorkerT, SparseMatrixWorkerTable>::value
          ? TableSpec::kSparseMatrix
          : TableSpec::kMatrix,
      rows, cols});
  worker_tables_.push_back(
      std::make_unique<WorkerT>(id, rows, cols, num_servers()));
  worker_tables_.back()->set_codec(DefaultCodec());
  return id;
}

int32_t Zoo::RegisterMatrixTable(int64_t rows, int64_t cols) {
  return RegisterMatrixTableImpl<MatrixWorkerTable>(rows, cols);
}

int32_t Zoo::RegisterSparseMatrixTable(int64_t rows, int64_t cols) {
  return RegisterMatrixTableImpl<SparseMatrixWorkerTable>(rows, cols);
}

int32_t Zoo::RegisterKVTable() {
  MutexLock lk(tables_mu_);
  int32_t id = static_cast<int32_t>(server_tables_.size());
  int sid = server_id();
  server_tables_.push_back(
      sid < 0 ? nullptr
              : std::make_unique<KVServerTable>(updater_type_));
  if (server_tables_.back()) server_tables_.back()->set_table_id(id);
  RegisterBackupShard(TableSpec{TableSpec::kKV, 0, 0});
  worker_tables_.push_back(
      std::make_unique<KVWorkerTable>(id, num_servers()));
  worker_tables_.back()->set_codec(DefaultCodec());
  return id;
}

ServerTable* Zoo::server_table(int32_t id) {
  MutexLock lk(tables_mu_);
  return (id >= 0 && id < static_cast<int32_t>(server_tables_.size()))
             ? server_tables_[id].get()
             : nullptr;
}

WorkerTable* Zoo::worker_table(int32_t id) {
  MutexLock lk(tables_mu_);
  return (id >= 0 && id < static_cast<int32_t>(worker_tables_.size()))
             ? worker_tables_[id].get()
             : nullptr;
}

ArrayWorkerTable* Zoo::array_worker(int32_t id) {
  return dynamic_cast<ArrayWorkerTable*>(worker_table(id));
}

MatrixWorkerTable* Zoo::matrix_worker(int32_t id) {
  return dynamic_cast<MatrixWorkerTable*>(worker_table(id));
}

KVWorkerTable* Zoo::kv_worker(int32_t id) {
  return dynamic_cast<KVWorkerTable*>(worker_table(id));
}

}  // namespace mvtpu
