#include "mvtpu/zoo.h"

#include "mvtpu/configure.h"
#include "mvtpu/dashboard.h"
#include "mvtpu/log.h"
#include "mvtpu/waiter.h"

namespace mvtpu {

namespace {

// Barrier messages carry the requester's Waiter through the actor chain
// worker → server → controller so every request enqueued before the
// barrier is processed before it completes (the flush guarantee).
struct BarrierPayload {
  Waiter* waiter;
};

class WorkerActor : public Actor {
 public:
  WorkerActor() : Actor(actor::kWorker) {
    RegisterHandler(MsgType::RequestGet, [](MessagePtr& m) {
      Zoo::Get()->SendTo(actor::kServer, std::move(m));
    });
    RegisterHandler(MsgType::RequestAdd, [](MessagePtr& m) {
      Zoo::Get()->SendTo(actor::kServer, std::move(m));
    });
    RegisterHandler(MsgType::ControlBarrier, [](MessagePtr& m) {
      Zoo::Get()->SendTo(actor::kServer, std::move(m));
    });
    RegisterHandler(MsgType::ReplyGet, [](MessagePtr& m) {
      Zoo::Get()->worker_table(m->table_id)->Notify(m->msg_id, *m);
    });
    RegisterHandler(MsgType::ReplyAdd, [](MessagePtr& m) {
      Zoo::Get()->worker_table(m->table_id)->Notify(m->msg_id, *m);
    });
  }
};

class ServerActor : public Actor {
 public:
  ServerActor() : Actor(actor::kServer) {
    RegisterHandler(MsgType::RequestGet, [](MessagePtr& m) {
      auto* table = Zoo::Get()->server_table(m->table_id);
      auto reply = std::make_unique<Message>();
      reply->type = MsgType::ReplyGet;
      reply->table_id = m->table_id;
      reply->msg_id = m->msg_id;
      table->ProcessGet(*m, reply.get());
      Zoo::Get()->SendTo(actor::kWorker, std::move(reply));
    });
    RegisterHandler(MsgType::RequestAdd, [](MessagePtr& m) {
      Zoo::Get()->server_table(m->table_id)->ProcessAdd(*m);
      if (m->msg_id >= 0) {  // blocking add wants an ack
        auto reply = std::make_unique<Message>();
        reply->type = MsgType::ReplyAdd;
        reply->table_id = m->table_id;
        reply->msg_id = m->msg_id;
        Zoo::Get()->SendTo(actor::kWorker, std::move(reply));
      }
    });
    RegisterHandler(MsgType::ControlBarrier, [](MessagePtr& m) {
      Zoo::Get()->SendTo(actor::kController, std::move(m));
    });
  }
};

class ControllerActor : public Actor {
 public:
  ControllerActor() : Actor(actor::kController) {
    RegisterHandler(MsgType::ControlBarrier, [](MessagePtr& m) {
      // Single-process control plane: all (one) participants arrived.
      m->data[0].As<BarrierPayload>()->waiter->Notify();
    });
  }
};

}  // namespace

Zoo* Zoo::Get() {
  static Zoo zoo;
  return &zoo;
}

bool Zoo::Start(int argc, const char* const* argv) {
  std::lock_guard<std::mutex> lk(mu_);
  if (started_) return true;
  configure::RegisterDefaults();
  if (configure::ParseCmdFlags(argc, argv) < 0) return false;
  std::string upd = configure::GetString("updater_type");
  if (!IsUpdaterName(upd)) {
    Log::Error("unknown updater_type '%s'", upd.c_str());
    return false;
  }
  updater_type_ = UpdaterFromName(upd);
  std::string lvl = configure::GetString("log_level");
  Log::SetLevel(lvl == "debug" ? LogLevel::kDebug
                : lvl == "error" ? LogLevel::kError
                : lvl == "fatal" ? LogLevel::kFatal
                                 : LogLevel::kInfo);
  Log::ResetLogFile(configure::GetString("log_file"));

  worker_actor_ = std::make_unique<WorkerActor>();
  server_actor_ = std::make_unique<ServerActor>();
  controller_actor_ = std::make_unique<ControllerActor>();
  worker_actor_->Start();
  server_actor_->Start();
  controller_actor_->Start();
  started_ = true;
  Log::Info("mvtpu native runtime started (updater=%s)", upd.c_str());
  return true;
}

void Zoo::Stop() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!started_) return;
    started_ = false;
  }
  // Join OUTSIDE mu_: a draining handler may query the table registry.
  // Pipeline order so queued async adds apply before teardown.
  worker_actor_->Stop();
  server_actor_->Stop();
  controller_actor_->Stop();
  std::lock_guard<std::mutex> lk(mu_);
  worker_actor_.reset();
  server_actor_.reset();
  controller_actor_.reset();
  {
    std::lock_guard<std::mutex> tlk(tables_mu_);
    server_tables_.clear();
    worker_tables_.clear();
  }
  Log::Info("%s", Dashboard::Report().c_str());
}

void Zoo::Barrier() {
  Monitor mon("Zoo::Barrier");
  Waiter waiter(1);
  BarrierPayload payload{&waiter};
  auto msg = std::make_unique<Message>();
  msg->type = MsgType::ControlBarrier;
  msg->msg_id = NextMsgId();
  msg->data.emplace_back(&payload, sizeof(payload));
  SendTo(actor::kWorker, std::move(msg));
  waiter.Wait();
}

void Zoo::SendTo(const std::string& actor_name, MessagePtr msg) {
  Actor* a = nullptr;
  if (actor_name == actor::kWorker) a = worker_actor_.get();
  else if (actor_name == actor::kServer) a = server_actor_.get();
  else if (actor_name == actor::kController) a = controller_actor_.get();
  if (!a) {
    Log::Error("SendTo: unknown or stopped actor '%s'", actor_name.c_str());
    return;
  }
  a->Receive(std::move(msg));
}

int32_t Zoo::RegisterArrayTable(int64_t size) {
  std::lock_guard<std::mutex> lk(tables_mu_);
  int32_t id = static_cast<int32_t>(server_tables_.size());
  server_tables_.push_back(
      std::make_unique<ArrayServerTable>(size, updater_type_));
  worker_tables_.push_back(std::make_unique<ArrayWorkerTable>(id));
  return id;
}

int32_t Zoo::RegisterMatrixTable(int64_t rows, int64_t cols) {
  std::lock_guard<std::mutex> lk(tables_mu_);
  int32_t id = static_cast<int32_t>(server_tables_.size());
  server_tables_.push_back(
      std::make_unique<MatrixServerTable>(rows, cols, updater_type_));
  worker_tables_.push_back(
      std::make_unique<MatrixWorkerTable>(id, rows, cols));
  return id;
}

ServerTable* Zoo::server_table(int32_t id) {
  std::lock_guard<std::mutex> lk(tables_mu_);
  return (id >= 0 && id < static_cast<int32_t>(server_tables_.size()))
             ? server_tables_[id].get()
             : nullptr;
}

WorkerTable* Zoo::worker_table(int32_t id) {
  std::lock_guard<std::mutex> lk(tables_mu_);
  return (id >= 0 && id < static_cast<int32_t>(worker_tables_.size()))
             ? worker_tables_[id].get()
             : nullptr;
}

ArrayWorkerTable* Zoo::array_worker(int32_t id) {
  return dynamic_cast<ArrayWorkerTable*>(worker_table(id));
}

MatrixWorkerTable* Zoo::matrix_worker(int32_t id) {
  return dynamic_cast<MatrixWorkerTable*>(worker_table(id));
}

}  // namespace mvtpu
