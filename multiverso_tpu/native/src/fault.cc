#include "mvtpu/fault.h"

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <string>

#include "mvtpu/mutex.h"

namespace mvtpu {

namespace {

struct Knob {
  double rate = 0.0;      // probability per op
  long long budget = 0;   // deterministic: fire on the next `budget` ops
};

struct State {
  Knob drop;
  Knob delay;
  Knob dup;
  Knob fail_send;
  Knob apply_delay;
  Knob discard_apply;
  int64_t delay_ms = 50;
  uint64_t rng = 0x9e3779b97f4a7c15ull;
};

Mutex g_mu;
State& S() REQUIRES(g_mu) {
  static State* s = new State();
  return *s;
}
// Fast-path gate, kept in sync with the knobs under g_mu.  Relaxed is
// enough: a sender racing a Set/Clear may act on the old verdict for
// one message, which injection semantics tolerate by construction.
std::atomic<bool> g_enabled{false};

uint64_t NextRand() REQUIRES(g_mu) {
  // xorshift64* — tiny, seedable, good enough for injection decisions.
  uint64_t x = S().rng;
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  S().rng = x;
  return x * 0x2545f4914f6cdd1dull;
}

bool Fire(Knob* k) REQUIRES(g_mu) {
  if (k->budget > 0) {
    --k->budget;
    return true;
  }
  if (k->rate > 0.0) {
    double u = static_cast<double>(NextRand() >> 11) * (1.0 / 9007199254740992.0);
    return u < k->rate;
  }
  return false;
}

Knob* Find(const char* kind) REQUIRES(g_mu) {
  if (!kind) return nullptr;
  std::string k(kind);
  if (k == "drop") return &S().drop;
  if (k == "delay") return &S().delay;
  if (k == "dup") return &S().dup;
  if (k == "fail_send") return &S().fail_send;
  if (k == "apply_delay") return &S().apply_delay;
  if (k == "discard_apply") return &S().discard_apply;
  return nullptr;
}

void Recompute() REQUIRES(g_mu) {
  State& s = S();
  auto live = [](const Knob& k) { return k.rate > 0.0 || k.budget > 0; };
  g_enabled.store(live(s.drop) || live(s.delay) || live(s.dup) ||
                      live(s.fail_send) || live(s.apply_delay) ||
                      live(s.discard_apply),
                  std::memory_order_relaxed);
}

double EnvRate(const char* name) {
  const char* v = getenv(name);
  return v ? atof(v) : 0.0;
}

// One-shot env pickup: the chaos Makefile target and multi-process
// scenarios configure child ranks through the environment because they
// have no C-API call site before MV_Init.
void InitFromEnvLocked() REQUIRES(g_mu) {
  static bool done = false;
  if (done) return;
  done = true;
  State& s = S();
  if (const char* v = getenv("MVTPU_FAULT_SEED"))
    s.rng = static_cast<uint64_t>(atoll(v)) | 1ull;
  s.drop.rate = EnvRate("MVTPU_FAULT_DROP");
  s.delay.rate = EnvRate("MVTPU_FAULT_DELAY");
  s.dup.rate = EnvRate("MVTPU_FAULT_DUP");
  s.fail_send.rate = EnvRate("MVTPU_FAULT_FAIL_SEND");
  s.apply_delay.rate = EnvRate("MVTPU_FAULT_APPLY_DELAY");
  s.discard_apply.rate = EnvRate("MVTPU_FAULT_DISCARD_APPLY");
  if (const char* v = getenv("MVTPU_FAULT_DELAY_MS")) s.delay_ms = atoll(v);
  Recompute();
}

struct EnvInit {
  EnvInit() {
    MutexLock lk(g_mu);
    InitFromEnvLocked();
  }
};
EnvInit g_env_init;

}  // namespace

bool Fault::Enabled() { return g_enabled.load(std::memory_order_relaxed); }

Fault::Action Fault::OnSend(int64_t* delay_ms) {
  if (!Enabled()) return Action::kNone;
  MutexLock lk(g_mu);
  if (Fire(&S().drop)) {
    Recompute();
    return Action::kDrop;
  }
  if (Fire(&S().delay)) {
    if (delay_ms) *delay_ms = S().delay_ms;
    Recompute();
    return Action::kDelay;
  }
  if (Fire(&S().dup)) {
    Recompute();
    return Action::kDuplicate;
  }
  return Action::kNone;
}

int64_t Fault::ApplyDelayMs() {
  if (!Enabled()) return 0;
  MutexLock lk(g_mu);
  if (!Fire(&S().apply_delay)) return 0;
  int64_t ms = S().delay_ms;
  Recompute();
  return ms;
}

bool Fault::DiscardApply() {
  if (!Enabled()) return false;
  MutexLock lk(g_mu);
  bool fire = Fire(&S().discard_apply);
  if (fire) Recompute();
  return fire;
}

bool Fault::FailSendAttempt() {
  if (!Enabled()) return false;
  MutexLock lk(g_mu);
  bool fire = Fire(&S().fail_send);
  if (fire) Recompute();
  return fire;
}

int Fault::Set(const char* kind, double rate) {
  MutexLock lk(g_mu);
  if (kind && strcmp(kind, "delay_ms") == 0) {
    S().delay_ms = static_cast<int64_t>(rate);
    return 0;
  }
  Knob* k = Find(kind);
  if (!k || rate < 0.0 || rate > 1.0) return -1;
  k->rate = rate;
  Recompute();
  return 0;
}

int Fault::SetBudget(const char* kind, long long n) {
  MutexLock lk(g_mu);
  Knob* k = Find(kind);
  if (!k || n < 0) return -1;
  k->budget = n;
  Recompute();
  return 0;
}

void Fault::SetSeed(uint64_t seed) {
  MutexLock lk(g_mu);
  S().rng = seed | 1ull;  // xorshift state must be nonzero
}

void Fault::Clear() {
  MutexLock lk(g_mu);
  State& s = S();
  s.drop = Knob{};
  s.delay = Knob{};
  s.dup = Knob{};
  s.fail_send = Knob{};
  s.apply_delay = Knob{};
  s.discard_apply = Knob{};
  Recompute();
}

}  // namespace mvtpu
