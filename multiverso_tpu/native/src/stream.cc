#include "mvtpu/stream.h"

#include <sys/stat.h>

#include <string>

namespace mvtpu {

namespace {
// mkdir -p for the parent directory of `path`.
void EnsureParent(const std::string& path) {
  auto slash = path.find_last_of('/');
  if (slash == std::string::npos) return;
  std::string dir = path.substr(0, slash);
  std::string cur;
  size_t i = 0;
  while (i <= dir.size()) {
    if (i == dir.size() || dir[i] == '/') {
      cur = dir.substr(0, i);
      if (!cur.empty()) mkdir(cur.c_str(), 0755);
    }
    ++i;
  }
}
}  // namespace

LocalStream::LocalStream(const std::string& path, const char* mode) {
  if (mode && (mode[0] == 'w' || mode[0] == 'a')) EnsureParent(path);
  f_ = fopen(path.c_str(), mode);
}

LocalStream::~LocalStream() {
  if (f_) fclose(f_);
}

size_t LocalStream::Write(const void* buf, size_t size) {
  return f_ ? fwrite(buf, 1, size, f_) : 0;
}

size_t LocalStream::Read(void* buf, size_t size) {
  return f_ ? fread(buf, 1, size, f_) : 0;
}

std::unique_ptr<Stream> StreamFactory::Open(const std::string& uri,
                                            const char* mode) {
  std::string path = uri;
  auto pos = uri.find("://");
  if (pos != std::string::npos) {
    std::string scheme = uri.substr(0, pos);
    if (scheme != "file") return nullptr;
    path = uri.substr(pos + 3);
  }
  auto s = std::make_unique<LocalStream>(path, mode);
  if (!s->Good()) return nullptr;
  return s;
}

}  // namespace mvtpu
