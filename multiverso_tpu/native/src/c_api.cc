#include "mvtpu/c_api.h"

#include <cstdlib>
#include <cstring>
#include <stdexcept>
#include <unordered_map>

#include "mvtpu/audit.h"
#include "mvtpu/codec.h"
#include "mvtpu/configure.h"
#include "mvtpu/dashboard.h"
#include "mvtpu/fault.h"
#include "mvtpu/host_arena.h"
#include "mvtpu/latency.h"
#include "mvtpu/profiler.h"
#include "mvtpu/repl.h"
#include "mvtpu/mutex.h"
#include "mvtpu/ops.h"
#include "mvtpu/sketch.h"
#include "mvtpu/stream.h"
#include "mvtpu/uring_net.h"
#include "mvtpu/watchdog.h"
#include "mvtpu/zoo.h"

using mvtpu::AddOption;
using mvtpu::Mutex;
using mvtpu::MutexLock;
using mvtpu::Zoo;

namespace {
thread_local AddOption g_add_option;

int RequireStarted() { return Zoo::Get()->started() ? 0 : -1; }

// Failure rc for a blocking table round trip: -6 when a server SHED it
// under -server_inflight_max (retryable, no work done), -3 otherwise
// (dead shard / deadline — indeterminate; see the header contract).
int FailRc() { return mvtpu::WorkerTable::last_call_busy() ? -6 : -3; }

// Outstanding MV_GetAsync* tickets.  Tickets index AsyncGetHandles so
// the FFI surface stays integer-only; MV_WaitGet consumes the entry.
// Borrowed async gets (docs/host_bridge.md) additionally park an arena
// hold with the ticket: the destination buffer cannot be recycled while
// a late shard reply could still scatter into it — the hold drops when
// Wait/Cancel consumes the ticket (or at shutdown reclaim).
struct GetTicket {
  mvtpu::AsyncGetPtr h;
  std::shared_ptr<void> arena_hold;  // null on non-borrowed gets
};
Mutex g_gets_mu;
std::unordered_map<int32_t, GetTicket>& Gets() REQUIRES(g_gets_mu) {
  static auto* m = new std::unordered_map<int32_t, GetTicket>();
  return *m;
}
int32_t g_next_get_ticket GUARDED_BY(g_gets_mu) = 1;

int32_t StashGet(mvtpu::AsyncGetPtr h,
                 std::shared_ptr<void> arena_hold = nullptr) {
  MutexLock lk(g_gets_mu);
  int32_t t = g_next_get_ticket++;
  Gets()[t] = GetTicket{std::move(h), std::move(arena_hold)};
  return t;
}

// Validate a *Borrowed pointer window and mint its arena hold: fills
// `hold` and returns 0, or returns -7 (not a live arena buffer / the
// window overruns it) with nothing minted.
int ArenaHoldFor(const void* p, size_t bytes, void** base,
                 std::shared_ptr<void>* hold) {
  if (!p) return -1;
  void* b = mvtpu::HostArena::Get()->BufferOf(p, bytes);
  if (!b) return -7;
  *hold = mvtpu::HostArena::Get()->BorrowHold(b);
  if (!*hold) return -7;
  if (base) *base = b;
  return 0;
}
}  // namespace

namespace mvtpu {
// Called by Zoo::Stop(): un-waited tickets must not outlive the tables
// their handles point into (~AsyncGetHandle dereferences the table).
void CApiReclaimAsyncGets() {
  MutexLock lk(g_gets_mu);
  Gets().clear();
}
}  // namespace mvtpu

extern "C" {

int MV_Init(int argc, const char* const* argv) {
  return Zoo::Get()->Start(argc, argv) ? 0 : -1;
}

int MV_ShutDown() {
  Zoo::Get()->Stop();
  return 0;
}

int MV_Barrier() {
  if (RequireStarted()) return -1;
  return Zoo::Get()->Barrier() ? 0 : -3;  // -3: timeout / peer death
}

int MV_Clock() {
  if (RequireStarted()) return -1;
  Zoo::Get()->Clock();
  return 0;
}

int MV_NumWorkers() { return Zoo::Get()->num_workers(); }
int MV_WorkerId() { return Zoo::Get()->worker_id(); }
int MV_ServerId() { return Zoo::Get()->server_id(); }

int MV_SetFlag(const char* name, const char* value) {
  mvtpu::configure::RegisterDefaults();
  try {
    mvtpu::configure::Set(name, value);
  } catch (const std::invalid_argument&) {
    return -1;
  }
  return 0;
}

int MV_NewArrayTable(int64_t size, int32_t* handle) {
  if (RequireStarted() || size <= 0 || !handle) return -1;
  *handle = Zoo::Get()->RegisterArrayTable(size);
  return 0;
}

int MV_GetArrayTable(int32_t handle, float* data, int64_t size) {
  if (RequireStarted()) return -1;
  auto* t = Zoo::Get()->array_worker(handle);
  if (!t) return -2;
  return t->Get(data, size) ? 0 : FailRc();
}

static int AddArray(int32_t handle, const float* delta, int64_t size,
                    bool blocking) {
  if (RequireStarted()) return -1;
  auto* t = Zoo::Get()->array_worker(handle);
  if (!t) return -2;
  return t->Add(delta, size, g_add_option, blocking) ? 0 : FailRc();
}

int MV_AddArrayTable(int32_t h, const float* d, int64_t n) {
  return AddArray(h, d, n, true);
}
int MV_AddAsyncArrayTable(int32_t h, const float* d, int64_t n) {
  return AddArray(h, d, n, false);
}

int MV_NewMatrixTable(int64_t rows, int64_t cols, int32_t* handle) {
  if (RequireStarted() || rows <= 0 || cols <= 0 || !handle) return -1;
  *handle = Zoo::Get()->RegisterMatrixTable(rows, cols);
  return 0;
}

int MV_NewSparseMatrixTable(int64_t rows, int64_t cols, int32_t* handle) {
  if (RequireStarted() || rows <= 0 || cols <= 0 || !handle) return -1;
  *handle = Zoo::Get()->RegisterSparseMatrixTable(rows, cols);
  return 0;
}

int MV_GetMatrixTableAll(int32_t handle, float* data, int64_t /*size*/) {
  if (RequireStarted()) return -1;
  auto* t = Zoo::Get()->matrix_worker(handle);
  if (!t) return -2;
  return t->GetAll(data) ? 0 : FailRc();
}

static int AddMatrixAll(int32_t handle, const float* delta, bool blocking) {
  if (RequireStarted()) return -1;
  auto* t = Zoo::Get()->matrix_worker(handle);
  if (!t) return -2;
  return t->AddAll(delta, g_add_option, blocking) ? 0 : FailRc();
}

int MV_AddMatrixTableAll(int32_t h, const float* d, int64_t) {
  return AddMatrixAll(h, d, true);
}
int MV_AddAsyncMatrixTableAll(int32_t h, const float* d, int64_t) {
  return AddMatrixAll(h, d, false);
}

int MV_GetMatrixTableByRows(int32_t handle, float* data,
                            const int32_t* row_ids, int64_t num_rows,
                            int64_t /*cols*/) {
  if (RequireStarted()) return -1;
  auto* t = Zoo::Get()->matrix_worker(handle);
  if (!t) return -2;
  return t->GetRows(row_ids, num_rows, data) ? 0 : FailRc();
}

static int AddMatrixRows(int32_t handle, const float* delta,
                         const int32_t* row_ids, int64_t num_rows,
                         bool blocking) {
  if (RequireStarted()) return -1;
  auto* t = Zoo::Get()->matrix_worker(handle);
  if (!t) return -2;
  return t->AddRows(row_ids, num_rows, delta, g_add_option, blocking)
             ? 0
             : FailRc();
}

int MV_AddMatrixTableByRows(int32_t h, const float* d, const int32_t* ids,
                            int64_t k, int64_t) {
  return AddMatrixRows(h, d, ids, k, true);
}
int MV_AddAsyncMatrixTableByRows(int32_t h, const float* d, const int32_t* ids,
                                 int64_t k, int64_t) {
  return AddMatrixRows(h, d, ids, k, false);
}

int MV_GetAsyncArrayTable(int32_t handle, float* data, int64_t size,
                          int32_t* wait_handle) {
  if (RequireStarted() || !data || !wait_handle || size < 0) return -1;
  auto* t = Zoo::Get()->array_worker(handle);
  if (!t) return -2;
  *wait_handle = StashGet(t->GetAsync(data, size));
  return 0;
}

int MV_GetAsyncMatrixTableByRows(int32_t handle, float* data,
                                 const int32_t* row_ids, int64_t num_rows,
                                 int64_t /*cols*/, int32_t* wait_handle) {
  if (RequireStarted() || !data || !row_ids || !wait_handle ||
      num_rows < 0)
    return -1;
  auto* t = Zoo::Get()->matrix_worker(handle);
  if (!t) return -2;
  *wait_handle = StashGet(t->GetRowsAsync(row_ids, num_rows, data));
  return 0;
}

int MV_WaitGet(int32_t wait_handle) {
  GetTicket t;
  {
    MutexLock lk(g_gets_mu);
    auto it = Gets().find(wait_handle);
    if (it == Gets().end()) return -2;
    t = std::move(it->second);
    Gets().erase(it);
  }
  // Wait outside the registry lock; the ticket's arena hold (borrowed
  // gets) drops when `t` dies — AFTER every shard reply landed.
  return t.h->Wait() ? 0 : FailRc();
}

int MV_CancelGet(int32_t wait_handle) {
  GetTicket t;
  {
    MutexLock lk(g_gets_mu);
    auto it = Gets().find(wait_handle);
    if (it == Gets().end()) return -2;
    t = std::move(it->second);
    Gets().erase(it);
  }
  // ~AsyncGetHandle withdraws the pending entry (under the table's
  // lock), so a late reply is dropped at the door instead of scattering
  // into an output buffer the caller is about to free; only then does
  // the ticket's arena hold release the destination for recycling.
  return 0;
}

// ---- host-bridge fast path (docs/host_bridge.md) ---------------------

int MV_ArenaAcquire(int64_t bytes, void** ptr) {
  if (bytes <= 0 || !ptr) return -1;
  void* p = mvtpu::HostArena::Get()->Acquire(static_cast<size_t>(bytes));
  if (!p) return -1;
  *ptr = p;
  return 0;
}

int MV_ArenaRelease(void* ptr) {
  if (!ptr) return -1;
  return mvtpu::HostArena::Get()->Release(ptr);
}

int MV_ArenaStats(long long* buffers, long long* free_buffers,
                  long long* bytes, long long* in_flight,
                  long long* deferred, long long* recycled,
                  long long* pinned) {
  auto st = mvtpu::HostArena::Get()->GetStats();
  if (buffers) *buffers = st.buffers;
  if (free_buffers) *free_buffers = st.free_buffers;
  if (bytes) *bytes = st.bytes;
  if (in_flight) *in_flight = st.in_flight;
  if (deferred) *deferred = st.deferred;
  if (recycled) *recycled = st.recycled;
  if (pinned) *pinned = st.pinned;
  return 0;
}

static int AddArrayBorrowed(int32_t handle, const float* delta,
                            int64_t size, bool blocking) {
  if (RequireStarted() || size <= 0) return -1;
  auto* t = Zoo::Get()->array_worker(handle);
  if (!t) return -2;
  std::shared_ptr<void> hold;
  size_t bytes = static_cast<size_t>(size) * sizeof(float);
  int rc = ArenaHoldFor(delta, bytes, nullptr, &hold);
  if (rc) return rc;
  mvtpu::BorrowScope scope(delta, bytes, std::move(hold));
  return t->Add(delta, size, g_add_option, blocking) ? 0 : FailRc();
}

int MV_AddArrayTableBorrowed(int32_t h, const float* d, int64_t n) {
  return AddArrayBorrowed(h, d, n, true);
}
int MV_AddAsyncArrayTableBorrowed(int32_t h, const float* d, int64_t n) {
  return AddArrayBorrowed(h, d, n, false);
}

int MV_GetArrayTableBorrowed(int32_t handle, float* data, int64_t size) {
  if (RequireStarted() || size <= 0) return -1;
  auto* t = Zoo::Get()->array_worker(handle);
  if (!t) return -2;
  // Destination validation + hold for the call's duration: the blocking
  // Get returns only after every shard landed, so the hold's job is the
  // -7 contract (an un-acquired / overrun destination fails loudly).
  std::shared_ptr<void> hold;
  int rc = ArenaHoldFor(data, static_cast<size_t>(size) * sizeof(float),
                        nullptr, &hold);
  if (rc) return rc;
  return t->Get(data, size) ? 0 : FailRc();
}

int MV_GetAsyncArrayTableBorrowed(int32_t handle, float* data,
                                  int64_t size, int32_t* wait_handle) {
  if (RequireStarted() || !data || !wait_handle || size < 0) return -1;
  auto* t = Zoo::Get()->array_worker(handle);
  if (!t) return -2;
  std::shared_ptr<void> hold;
  int rc = ArenaHoldFor(data, static_cast<size_t>(size) * sizeof(float),
                        nullptr, &hold);
  if (rc) return rc;
  *wait_handle = StashGet(t->GetAsync(data, size), std::move(hold));
  return 0;
}

static int AddMatrixAllBorrowed(int32_t handle, const float* delta,
                                int64_t size, bool blocking) {
  if (RequireStarted() || size <= 0) return -1;
  auto* t = Zoo::Get()->matrix_worker(handle);
  if (!t) return -2;
  std::shared_ptr<void> hold;
  size_t bytes = static_cast<size_t>(size) * sizeof(float);
  int rc = ArenaHoldFor(delta, bytes, nullptr, &hold);
  if (rc) return rc;
  mvtpu::BorrowScope scope(delta, bytes, std::move(hold));
  return t->AddAll(delta, g_add_option, blocking) ? 0 : FailRc();
}

int MV_AddMatrixTableAllBorrowed(int32_t h, const float* d, int64_t n) {
  return AddMatrixAllBorrowed(h, d, n, true);
}
int MV_AddAsyncMatrixTableAllBorrowed(int32_t h, const float* d,
                                      int64_t n) {
  return AddMatrixAllBorrowed(h, d, n, false);
}

static int AddMatrixRowsBorrowed(int32_t handle, const float* delta,
                                 const int32_t* row_ids, int64_t num_rows,
                                 int64_t cols, bool blocking) {
  if (RequireStarted() || !row_ids || num_rows <= 0 || cols <= 0)
    return -1;
  auto* t = Zoo::Get()->matrix_worker(handle);
  if (!t) return -2;
  std::shared_ptr<void> hold;
  size_t bytes = static_cast<size_t>(num_rows * cols) * sizeof(float);
  int rc = ArenaHoldFor(delta, bytes, nullptr, &hold);
  if (rc) return rc;
  mvtpu::BorrowScope scope(delta, bytes, std::move(hold));
  return t->AddRows(row_ids, num_rows, delta, g_add_option, blocking)
             ? 0
             : FailRc();
}

int MV_AddMatrixTableByRowsBorrowed(int32_t h, const float* d,
                                    const int32_t* ids, int64_t k,
                                    int64_t cols) {
  return AddMatrixRowsBorrowed(h, d, ids, k, cols, true);
}
int MV_AddAsyncMatrixTableByRowsBorrowed(int32_t h, const float* d,
                                         const int32_t* ids, int64_t k,
                                         int64_t cols) {
  return AddMatrixRowsBorrowed(h, d, ids, k, cols, false);
}

int MV_GetAsyncMatrixTableByRowsBorrowed(int32_t handle, float* data,
                                         const int32_t* row_ids,
                                         int64_t num_rows, int64_t cols,
                                         int32_t* wait_handle) {
  if (RequireStarted() || !data || !row_ids || !wait_handle ||
      num_rows < 0 || cols <= 0)
    return -1;
  auto* t = Zoo::Get()->matrix_worker(handle);
  if (!t) return -2;
  std::shared_ptr<void> hold;
  int rc = ArenaHoldFor(data,
                        static_cast<size_t>(num_rows * cols) *
                            sizeof(float),
                        nullptr, &hold);
  if (rc) return rc;
  *wait_handle =
      StashGet(t->GetRowsAsync(row_ids, num_rows, data), std::move(hold));
  return 0;
}

int MV_NewKVTable(int32_t* handle) {
  if (RequireStarted() || !handle) return -1;
  *handle = Zoo::Get()->RegisterKVTable();
  return 0;
}

namespace {

std::vector<std::string> SplitKeys(const char* keys, const int32_t* lens,
                                   int64_t k) {
  std::vector<std::string> out;
  out.reserve(static_cast<size_t>(k));
  const char* p = keys;
  for (int64_t i = 0; i < k; ++i) {
    out.emplace_back(p, static_cast<size_t>(lens[i]));
    p += lens[i];
  }
  return out;
}

}  // namespace

int MV_GetKV(int32_t handle, const char* key, float* value) {
  if (RequireStarted() || !key || !value) return -1;
  auto* t = Zoo::Get()->kv_worker(handle);
  if (!t) return -2;
  return t->Get({std::string(key)}, value) ? 0 : FailRc();
}

static int AddKV(int32_t handle, const char* key, float delta,
                 bool blocking) {
  if (RequireStarted() || !key) return -1;
  auto* t = Zoo::Get()->kv_worker(handle);
  if (!t) return -2;
  return t->Add({std::string(key)}, &delta, g_add_option, blocking) ? 0 : FailRc();
}

int MV_AddKV(int32_t h, const char* key, float delta) {
  return AddKV(h, key, delta, true);
}
int MV_AddAsyncKV(int32_t h, const char* key, float delta) {
  return AddKV(h, key, delta, false);
}

int MV_GetKVBatch(int32_t handle, const char* keys, const int32_t* key_lens,
                  int64_t num_keys, float* values) {
  if (RequireStarted() || !keys || !key_lens || !values || num_keys < 0)
    return -1;
  auto* t = Zoo::Get()->kv_worker(handle);
  if (!t) return -2;
  return t->Get(SplitKeys(keys, key_lens, num_keys), values) ? 0 : FailRc();
}

int MV_AddKVBatch(int32_t handle, const char* keys, const int32_t* key_lens,
                  int64_t num_keys, const float* deltas) {
  if (RequireStarted() || !keys || !key_lens || !deltas || num_keys < 0)
    return -1;
  auto* t = Zoo::Get()->kv_worker(handle);
  if (!t) return -2;
  return t->Add(SplitKeys(keys, key_lens, num_keys), deltas, g_add_option,
                true)
             ? 0
             : FailRc();
}

int MV_SetAddOption(float learning_rate, float momentum, float rho,
                    float eps) {
  g_add_option.learning_rate = learning_rate;
  g_add_option.momentum = momentum;
  g_add_option.rho = rho;
  g_add_option.eps = eps;
  return 0;
}

int MV_StoreTable(int32_t handle, const char* path) {
  if (RequireStarted()) return -1;
  // Validity via the worker stub (exists on every rank for every id);
  // the server shard may legitimately be null on worker-only ranks.
  if (!Zoo::Get()->worker_table(handle)) return -2;
  // Collective on EVERY rank: the leading barrier flushes pending adds
  // (and must run before the no-shard early-out, or a worker-only rank
  // returning early would strand the server ranks inside it); the
  // trailing barrier fences the snapshot — no rank's post-store adds
  // can land before every shard finished writing.
  if (!Zoo::Get()->Barrier()) return -3;
  int rc = 0;
  auto* t = Zoo::Get()->server_table(handle);
  if (t) {  // worker-only rank: joined the collective, no shard
    auto s = mvtpu::StreamFactory::Open(path, "wb");
    if (!s) rc = -5;                          // local IO, not peer death
    else if (!t->Store(s.get())) rc = -4;
  }
  if (!Zoo::Get()->Barrier()) return rc ? rc : -3;
  return rc;
}

int MV_LoadTable(int32_t handle, const char* path) {
  if (RequireStarted()) return -1;
  if (!Zoo::Get()->worker_table(handle)) return -2;
  if (!Zoo::Get()->Barrier()) return -3;
  int rc = 0;
  auto* t = Zoo::Get()->server_table(handle);
  if (t) {  // worker-only rank: joined the collective, no shard
    auto s = mvtpu::StreamFactory::Open(path, "rb");
    if (!s) rc = -5;                          // local IO, not peer death
    else if (!t->Load(s.get())) rc = -4;
  }
  // Trailing fence: no rank reads/writes restored state before every
  // shard finished loading.
  if (!Zoo::Get()->Barrier()) return rc ? rc : -3;
  return rc;
}

namespace {
char* MallocString(const std::string& r) {
  char* out = static_cast<char*>(malloc(r.size() + 1));
  std::memcpy(out, r.c_str(), r.size() + 1);
  return out;
}
}  // namespace

char* MV_DashboardReport() {
  return MallocString(mvtpu::Dashboard::Report());
}

char* MV_DumpMonitors(void) {
  return MallocString(mvtpu::Dashboard::Dump());
}

int MV_SetTraceEnabled(int on) {
  mvtpu::Dashboard::SetTraceEnabled(on != 0);
  return 0;
}

int MV_SetTraceId(long long trace_id) {
  mvtpu::Dashboard::SetThreadTraceId(static_cast<int64_t>(trace_id));
  return 0;
}

char* MV_DumpSpans(void) {
  return MallocString(mvtpu::Dashboard::DumpSpans());
}

int MV_ClearSpans(void) {
  mvtpu::Dashboard::ClearSpans();
  return 0;
}

void MV_FreeString(char* s) { free(s); }

int MV_QueryMonitor(const char* name, long long* count) {
  if (!name || !count) return -1;
  long long c = 0;
  double total = 0.0;
  *count = mvtpu::Dashboard::Query(name, &c, &total) ? c : 0;
  return 0;
}

int MV_SetFault(const char* kind, double rate) {
  return mvtpu::Fault::Set(kind, rate);
}

int MV_SetFaultN(const char* kind, long long n) {
  return mvtpu::Fault::SetBudget(kind, n);
}

int MV_SetFaultSeed(long long seed) {
  mvtpu::Fault::SetSeed(static_cast<uint64_t>(seed));
  return 0;
}

int MV_ClearFaults(void) {
  mvtpu::Fault::Clear();
  return 0;
}

int MV_DeadPeerCount(void) { return Zoo::Get()->DeadPeerCount(); }

// ---- shard replication + failover (docs/replication.md) --------------

int MV_SetReplication(int on) {
  mvtpu::repl::Arm(on != 0);
  return 0;
}

long long MV_RoutingEpoch(void) { return Zoo::Get()->RoutingEpoch(); }

int MV_ShardOwner(int shard_idx) {
  if (RequireStarted()) return -1;
  if (shard_idx < 0 || shard_idx >= Zoo::Get()->num_servers()) return -1;
  return Zoo::Get()->server_rank(shard_idx);
}

int MV_BackupShard(void) {
  if (RequireStarted()) return -1;
  return Zoo::Get()->BackupShard();
}

int MV_PromoteBackup(int dead_rank) {
  if (RequireStarted()) return -1;
  return Zoo::Get()->PromoteFor(dead_rank);
}

int MV_ReplJoin(int shard_idx) {
  if (RequireStarted()) return -1;
  return Zoo::Get()->JoinAsBackup(shard_idx) ? 0 : -3;
}

int MV_ReplicationStats(long long* forwards, long long* acks,
                        long long* applied, long long* outstanding,
                        long long* promotions, long long* epoch_flips,
                        long long* dup_skips, long long* catchups) {
  auto st = mvtpu::repl::GetStats();
  if (forwards) *forwards = st.forwards;
  if (acks) *acks = st.acks;
  if (applied) *applied = st.applied;
  if (outstanding) *outstanding = st.forwards - st.acks;
  if (promotions) *promotions = st.promotions;
  if (epoch_flips) *epoch_flips = st.epoch_flips;
  if (dup_skips) *dup_skips = st.dup_skips;
  if (catchups) *catchups = st.catchups;
  return 0;
}

// ---- transport (docs/transport.md) -----------------------------------

char* MV_NetEngine(void) {
  return MallocString(Zoo::Get()->net_engine());
}

int MV_UringSupported(void) {
  return mvtpu::uring::Probe(nullptr) ? 1 : 0;
}

int MV_FanInStats(long long* accepted_total, long long* active_clients,
                  long long* client_shed) {
  auto st = Zoo::Get()->FanIn();
  if (accepted_total) *accepted_total = st.accepted_total;
  if (active_clients) *active_clients = st.active_clients;
  if (client_shed) *client_shed = st.client_shed;
  return 0;
}

// ---- wire data plane (docs/wire_compression.md) ----------------------

int MV_SetTableCodec(int32_t handle, const char* codec) {
  if (RequireStarted() || !codec) return -1;
  if (!mvtpu::codec::IsCodecName(codec)) return -1;
  auto* t = Zoo::Get()->worker_table(handle);
  if (!t) return -2;
  t->set_codec(mvtpu::codec::FromName(codec));
  return 0;
}

int MV_FlushAdds(int32_t handle) {
  if (RequireStarted()) return -1;
  if (handle < 0) {
    Zoo::Get()->FlushWorkerAdds();
    return 0;
  }
  auto* t = Zoo::Get()->worker_table(handle);
  if (!t) return -2;
  t->FlushAdds();
  return 0;
}

int MV_WireStats(long long* sent_bytes, long long* recv_bytes,
                 long long* sent_msgs, long long* recv_msgs) {
  long long c = 0;
  double total = 0.0;
  bool have = mvtpu::Dashboard::Query("net.bytes.sent", &c, &total);
  if (sent_bytes) *sent_bytes = have ? static_cast<long long>(total) : 0;
  if (sent_msgs) *sent_msgs = have ? c : 0;
  c = 0;
  total = 0.0;
  have = mvtpu::Dashboard::Query("net.bytes.recv", &c, &total);
  if (recv_bytes) *recv_bytes = have ? static_cast<long long>(total) : 0;
  if (recv_msgs) *recv_msgs = have ? c : 0;
  return 0;
}

// ---- introspection plane (docs/observability.md) ---------------------

char* MV_OpsReport(const char* kind) {
  return MallocString(mvtpu::ops::LocalReport(kind ? kind : "health"));
}

// ---- latency attribution plane (docs/observability.md) ---------------

int MV_SetWireTiming(int on) {
  mvtpu::latency::Arm(on != 0);
  return 0;
}

// ---- delivery-audit plane (docs/observability.md "audit plane") ------

int MV_SetAudit(int on) {
  mvtpu::audit::Arm(on != 0);
  return 0;
}

int MV_ClockOffset(int rank, long long* offset_ns, long long* rtt_ns) {
  if (rank < 0) return -1;
  int64_t off = 0, rtt = 0;
  if (!mvtpu::latency::PeerOffset(rank, &off, &rtt)) return -2;
  if (offset_ns) *offset_ns = off;
  if (rtt_ns) *rtt_ns = rtt;
  return 0;
}

int MV_SetProfiler(int hz) {
  return mvtpu::profiler::Start(hz) ? 0 : -1;
}

char* MV_ProfilerDump(void) {
  return MallocString(mvtpu::profiler::DumpFolded());
}

int MV_ProfilerClear(void) {
  mvtpu::profiler::Clear();
  return 0;
}

int MV_SetOpsHostMetrics(const char* prom_text) {
  mvtpu::ops::SetHostMetrics(prom_text ? prom_text : "");
  return 0;
}

int MV_SetOpsHostAlerts(const char* alerts_json) {
  mvtpu::ops::SetHostAlerts(alerts_json ? alerts_json : "");
  return 0;
}

// ---- health plane: stall watchdog (docs/observability.md) ------------

int MV_SetWatchdog(int stall_ms) {
  mvtpu::watchdog::Arm(stall_ms);
  return 0;
}

int MV_WatchdogBump(const char* loop) {
  if (!loop) return -1;
  mvtpu::watchdog::Bump(loop);
  return 0;
}

int MV_WatchdogBusy(const char* loop, long long queued) {
  if (!loop) return -1;
  mvtpu::watchdog::Busy(loop, queued);
  return 0;
}

char* MV_WatchdogStats(void) {
  return MallocString(mvtpu::watchdog::StatsJson());
}

int MV_BlackboxEvent(const char* kind, const char* detail) {
  if (!kind) return -1;
  mvtpu::ops::BlackboxEvent(kind, detail ? detail : "");
  return 0;
}

int MV_BlackboxTrigger(const char* reason) {
  if (!reason) return -1;
  mvtpu::ops::BlackboxTrigger(reason);
  return 0;
}

// ---- workload observability (docs/observability.md) ------------------

char* MV_HotKeys(int32_t handle) {
  return MallocString(Zoo::Get()->OpsHotKeysJson(handle));
}

int MV_TableLoadStats(int32_t handle, long long* gets, long long* adds,
                      double* skew_ratio, double* add_l2,
                      double* add_linf, long long* nan_count,
                      long long* inf_count) {
  if (RequireStarted()) return -1;
  auto* t = Zoo::Get()->server_table(handle);
  if (!t) return -2;  // bad handle, or no local shard on this rank
  auto load = t->Load();
  if (gets) *gets = load.gets;
  if (adds) *adds = load.adds;
  if (skew_ratio) *skew_ratio = load.skew_ratio;
  if (add_l2) *add_l2 = load.add_l2;
  if (add_linf) *add_linf = load.add_linf;
  if (nan_count) *nan_count = load.nan_count;
  if (inf_count) *inf_count = load.inf_count;
  return 0;
}

int MV_SetHotKeyTracking(int on) {
  mvtpu::workload::Arm(on != 0);
  return 0;
}

// ---- capacity plane (docs/observability.md "capacity plane") ---------

char* MV_CapacityReport(void) {
  return MallocString(Zoo::Get()->OpsCapacityJson());
}

int MV_SetCapacityTracking(int on) {
  bool was = mvtpu::capacity::Armed();
  mvtpu::capacity::Arm(on != 0);
  // Re-arming RESYNCS every shard's byte counters with an exact walk:
  // inserts that landed while disarmed left the incremental books
  // stale, and "armed" must mean "accurate".
  if (on && !was && Zoo::Get()->started())
    Zoo::Get()->RecomputeCapacityAll();
  return 0;
}

char* MV_OpsFleetReport(const char* kind) {
  return MallocString(
      Zoo::Get()->FleetReport(kind ? kind : "health"));
}

// ---- hot-key read replica (docs/embedding.md) ------------------------

int MV_SetHotKeyReplica(int on) {
  mvtpu::workload::ArmReplica(on != 0);
  return 0;
}

int MV_ReplicaRefresh(int32_t handle) {
  if (RequireStarted()) return -1;
  auto* t = Zoo::Get()->matrix_worker(handle);
  if (!t) return -2;
  return t->RefreshReplica() ? 0 : FailRc();
}

int MV_ReplicaStats(int32_t handle, long long* hits, long long* misses,
                    long long* rows, long long* refreshes,
                    long long* pushes) {
  if (RequireStarted()) return -1;
  auto* t = Zoo::Get()->matrix_worker(handle);
  if (!t) return -2;
  auto s = t->replica_stats();
  if (hits) *hits = s.hits;
  if (misses) *misses = s.misses;
  if (rows) *rows = s.rows;
  if (refreshes) *refreshes = s.refreshes;
  if (pushes) {
    auto* st = Zoo::Get()->server_table(handle);
    *pushes = st ? st->replica_pushes() : 0;
  }
  return 0;
}

// ---- serve layer (docs/serving.md) -----------------------------------

int MV_TableVersion(int32_t handle, long long* version) {
  if (RequireStarted() || !version) return -1;
  auto* t = Zoo::Get()->worker_table(handle);
  if (!t) return -2;
  int64_t v = 0;
  if (!t->QueryVersion(&v)) return FailRc();
  *version = v;
  return 0;
}

int MV_LastVersion(int32_t handle, long long* version) {
  if (RequireStarted() || !version) return -1;
  auto* t = Zoo::Get()->worker_table(handle);
  if (!t) return -2;
  *version = t->last_version();
  return 0;
}

int MV_CacheStats(long long* hits, long long* misses) {
  if (!hits || !misses) return -1;
  long long c = 0;
  double total = 0.0;
  *hits = mvtpu::Dashboard::Query("serve.cache.hit", &c, &total) ? c : 0;
  *misses = mvtpu::Dashboard::Query("serve.cache.miss", &c, &total) ? c : 0;
  return 0;
}

int MV_ServeQueueDepth(void) {
  if (RequireStarted()) return -1;
  return Zoo::Get()->ServeQueueDepth();
}

}  // extern "C"
