#include "mvtpu/net.h"

#include <arpa/inet.h>
#include <limits.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstring>
#include <fstream>

#include "mvtpu/configure.h"
#include "mvtpu/dashboard.h"
#include "mvtpu/fault.h"
#include "mvtpu/latency.h"
#include "mvtpu/qos.h"
#include "mvtpu/log.h"

namespace mvtpu {

namespace {

bool SplitHostPort(const std::string& ep, std::string* host, int* port) {
  auto colon = ep.rfind(':');
  if (colon == std::string::npos) return false;
  *host = ep.substr(0, colon);
  try {
    *port = std::stoi(ep.substr(colon + 1));
  } catch (...) {
    return false;
  }
  return *port > 0 && *port < 65536;
}

// Gather-write the whole iovec set (sendmsg with MSG_NOSIGNAL — the
// scatter-gather replacement for the old contiguous WriteAll path).
// Mutates the vector in place to advance past partial writes — callers
// pass a scratch copy.
bool WriteVAll(int fd, std::vector<iovec>* iov) {
  size_t idx = 0;
#ifdef IOV_MAX
  const size_t max_iov = IOV_MAX;
#else
  const size_t max_iov = 1024;
#endif
  while (idx < iov->size()) {
    msghdr mh{};
    mh.msg_iov = iov->data() + idx;
    mh.msg_iovlen = std::min(iov->size() - idx, max_iov);
    ssize_t w = ::sendmsg(fd, &mh, MSG_NOSIGNAL);
    if (w <= 0) return false;
    size_t left = static_cast<size_t>(w);
    while (left > 0 && idx < iov->size()) {
      iovec& v = (*iov)[idx];
      if (left >= v.iov_len) {
        left -= v.iov_len;
        ++idx;
      } else {
        v.iov_base = static_cast<char*>(v.iov_base) + left;
        v.iov_len -= left;
        left = 0;
      }
    }
  }
  return true;
}

bool ReadAll(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// Deadline-bounded ReadAll: a peer that stalls mid-frame (crashed after
// the length prefix, wedged NIC) must not park the reader thread
// forever.  timeout_ms <= 0 keeps the plain blocking read.
bool ReadAllDeadline(int fd, void* buf, size_t n, int64_t timeout_ms) {
  if (timeout_ms <= 0) return ReadAll(fd, buf, n);
  char* p = static_cast<char*>(buf);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (n > 0) {
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                    deadline - std::chrono::steady_clock::now())
                    .count();
    if (left <= 0) return false;
    pollfd pfd{fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, static_cast<int>(std::min<int64_t>(left, 500)));
    if (pr < 0) return false;
    if (pr == 0) continue;
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// Flags may not be registered when TcpNet is driven standalone (tests,
// the registration handshake before Zoo::Start finishes).
int64_t FlagOr(const char* name, int64_t dflt) {
  return mvtpu::configure::Has(name) ? mvtpu::configure::GetInt(name)
                                     : dflt;
}

}  // namespace

std::vector<std::string> TcpNet::ParseMachineFile(const std::string& path) {
  std::vector<std::string> eps;
  std::ifstream in(path);
  if (!in) return eps;
  std::string line;
  while (std::getline(in, line)) {
    // strip whitespace and comments
    auto hash = line.find('#');
    if (hash != std::string::npos) line = line.substr(0, hash);
    size_t b = line.find_first_not_of(" \t\r");
    if (b == std::string::npos) continue;
    size_t e = line.find_last_not_of(" \t\r");
    eps.push_back(line.substr(b, e - b + 1));
  }
  return eps;
}

namespace {
// Transport-wide frame cap (table shard payloads).  The registration
// handshake passes RecvFramed a much tighter bound — its frames are
// tiny, and a garbled/hostile connection must not be able to force a
// huge allocation on the controller.
constexpr int64_t kMaxFrameBytes = int64_t{1} << 40;
}  // namespace

bool TcpNet::SendFramed(int fd, const Message& msg) {
  // Scatter-gather framing: the kernel reads the payload blobs in place
  // — the only bytes assembled host-side are the tiny prefix/header/
  // per-blob-length scratch.  Layout must stay identical to
  // Message::Serialize() (RecvFramed decodes both the same way).
  int64_t frame = msg.WireBytes();
  struct {
    int64_t frame_len;
    WireHeader h;
  } head;
  head.frame_len = frame;
  msg.FillWireHeader(&head.h);
  std::vector<int64_t> lens(msg.data.size());
  std::vector<iovec> iov;
  iov.reserve(2 + 2 * msg.data.size());
  iov.push_back({&head, sizeof(head)});
  // Latency trail (docs/observability.md): rides between the header and
  // the blob prefixes when stamped — WireBytes() already counts it.
  if (msg.has_timing())
    iov.push_back({const_cast<TimingTrail*>(&msg.timing),
                   sizeof(TimingTrail)});
  // Delivery-audit stamp rides after the trail (message.cc Serialize
  // order); WireBytes() already counts it.
  if (msg.has_audit())
    iov.push_back({const_cast<AuditStamp*>(&msg.audit),
                   sizeof(AuditStamp)});
  // QoS/deadline stamp rides after the audit stamp (same order).
  if (msg.has_qos())
    iov.push_back({const_cast<QosStamp*>(&msg.qos), sizeof(QosStamp)});
  for (size_t i = 0; i < msg.data.size(); ++i) {
    lens[i] = static_cast<int64_t>(msg.data[i].size());
    iov.push_back({&lens[i], sizeof(int64_t)});
    if (msg.data[i].size())
      iov.push_back({const_cast<char*>(msg.data[i].data()),
                     msg.data[i].size()});
  }
  return WriteVAll(fd, &iov);
}

bool TcpNet::RecvFramed(int fd, Message* msg, int64_t max_bytes,
                        int64_t body_timeout_ms, int64_t* frame_bytes) {
  if (max_bytes <= 0) max_bytes = kMaxFrameBytes;
  int64_t len = 0;
  // The prefix read may block indefinitely — an idle connection is
  // healthy.  Once a frame STARTED, the rest must arrive within the
  // deadline or the connection is declared dead.
  if (!ReadAll(fd, &len, sizeof(len)) || len <= 0 || len > max_bytes)
    return false;
  Blob buf(static_cast<size_t>(len));
  if (!ReadAllDeadline(fd, buf.data(), buf.size(), body_timeout_ms))
    return false;
  *msg = Message::Deserialize(buf);
  if (frame_bytes) *frame_bytes = len + static_cast<int64_t>(sizeof(len));
  return true;
}

namespace {

// Node-table wire format inside ControlReply: blob0 = int32 assigned
// rank, blob1 = int32 roles[num], blob2 = '\n'-joined endpoints.
Blob PackEndpoints(const std::vector<std::string>& endpoints) {
  std::string joined;
  for (const auto& e : endpoints) {
    joined += e;
    joined += '\n';
  }
  return Blob(joined.data(), joined.size());
}

std::vector<std::string> UnpackEndpoints(const Blob& b) {
  std::vector<std::string> out;
  std::string cur;
  for (size_t i = 0; i < b.size(); ++i) {
    char c = b.data()[i];
    if (c == '\n') {
      out.push_back(cur);
      cur.clear();
    } else {
      cur += c;
    }
  }
  return out;
}

}  // namespace

bool TcpNet::RegisterController(const std::string& ctrl_endpoint,
                                int num_nodes, int my_role,
                                std::vector<std::string>* endpoints,
                                std::vector<int>* roles,
                                int64_t timeout_ms) {
  std::string host;
  int port = 0;
  if (num_nodes < 1 || !SplitHostPort(ctrl_endpoint, &host, &port))
    return false;
  endpoints->assign(num_nodes, "");
  roles->assign(num_nodes, 0);
  (*endpoints)[0] = ctrl_endpoint;
  (*roles)[0] = my_role;
  if (num_nodes == 1) return true;

  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return false;
  int one = 1;
  ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(lfd, 64) < 0) {
    Log::Error("RegisterController: cannot listen on %s",
               ctrl_endpoint.c_str());
    ::close(lfd);
    return false;
  }
  // Ranks assigned in arrival order, 1..num_nodes-1.  The collection is
  // deadline-bounded (poll on the listener) and each accepted client is
  // read under SO_RCVTIMEO so a silent connection cannot park the
  // single-threaded loop and starve real registrants.
  std::vector<int> fds;
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  for (int next = 1; next < num_nodes;) {
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                    deadline - std::chrono::steady_clock::now())
                    .count();
    if (left <= 0) {
      Log::Error("RegisterController: %d/%d nodes after %lld ms", next - 1,
                 num_nodes - 1, static_cast<long long>(timeout_ms));
      break;
    }
    pollfd pfd{lfd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, static_cast<int>(std::min<int64_t>(left, 500)));
    if (pr < 0) break;
    if (pr == 0) continue;
    int fd = ::accept(lfd, nullptr, nullptr);
    if (fd < 0) break;
    timeval tv{5, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    Message reg;
    if (!RecvFramed(fd, &reg, int64_t{1} << 20) ||
        reg.type != MsgType::ControlRegister ||
        reg.data.size() < 2) {
      ::close(fd);
      continue;
    }
    (*endpoints)[next] = std::string(reg.data[0].data(), reg.data[0].size());
    (*roles)[next] = *reg.data[1].As<int32_t>();
    fds.push_back(fd);
    ++next;
  }
  ::close(lfd);
  if (static_cast<int>(fds.size()) != num_nodes - 1) {
    for (int fd : fds) ::close(fd);
    return false;
  }
  bool ok = true;
  std::vector<int32_t> roles32(roles->begin(), roles->end());
  for (size_t i = 0; i < fds.size(); ++i) {
    Message reply;
    reply.type = MsgType::ControlReply;
    int32_t rank = static_cast<int32_t>(i + 1);
    reply.data.emplace_back(&rank, sizeof(rank));
    reply.data.emplace_back(roles32.data(), roles32.size() * sizeof(int32_t));
    reply.data.push_back(PackEndpoints(*endpoints));
    ok = SendFramed(fds[i], reply) && ok;
    ::close(fds[i]);
  }
  Log::Info("controller: %d nodes registered", num_nodes);
  return ok;
}

bool TcpNet::RegisterWithController(const std::string& ctrl_endpoint,
                                    const std::string& my_endpoint,
                                    int my_role, int64_t retry_ms,
                                    std::vector<std::string>* endpoints,
                                    std::vector<int>* roles, int* my_rank) {
  std::string host;
  int port = 0;
  if (!SplitHostPort(ctrl_endpoint, &host, &port)) return false;
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (::getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints,
                    &res) != 0 ||
      !res)
    return false;
  int fd = -1;
  int attempts = static_cast<int>(std::max<int64_t>(1, retry_ms / 100));
  for (int a = 0; a < attempts; ++a) {
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) break;
    if (::connect(fd, res->ai_addr, res->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
  }
  ::freeaddrinfo(res);
  if (fd < 0) {
    Log::Error("RegisterWithController: cannot reach %s",
               ctrl_endpoint.c_str());
    return false;
  }
  Message reg;
  reg.type = MsgType::ControlRegister;
  reg.data.emplace_back(my_endpoint.data(), my_endpoint.size());
  int32_t role32 = my_role;
  reg.data.emplace_back(&role32, sizeof(role32));
  Message reply;
  bool ok = SendFramed(fd, reg) &&
            RecvFramed(fd, &reply, int64_t{1} << 20) &&
            reply.type == MsgType::ControlReply && reply.data.size() >= 3;
  if (ok) {
    *my_rank = *reply.data[0].As<int32_t>();
    size_t n = reply.data[1].count<int32_t>();
    roles->assign(reply.data[1].As<int32_t>(),
                  reply.data[1].As<int32_t>() + n);
    *endpoints = UnpackEndpoints(reply.data[2]);
    ok = endpoints->size() == n && *my_rank > 0 &&
         *my_rank < static_cast<int>(n);
    // The assigned slot must be OUR endpoint: a controller bug or a
    // crossed reply would otherwise make this node answer for another
    // rank's address and misroute every message sent to it.
    if (ok && (*endpoints)[*my_rank] != my_endpoint) {
      Log::Error("RegisterWithController: assigned rank %d maps to "
                 "endpoint %s, but this node registered %s",
                 *my_rank, (*endpoints)[*my_rank].c_str(),
                 my_endpoint.c_str());
      ok = false;
    }
  }
  ::close(fd);
  return ok;
}

bool TcpNet::Init(const std::vector<std::string>& endpoints, int rank,
                  InboundFn fn, int64_t connect_retry_ms) {
  endpoints_ = endpoints;
  rank_ = rank;
  inbound_ = std::move(fn);
  connect_retry_ms_ = connect_retry_ms;
  send_fds_.assign(endpoints_.size(), -1);
  send_mus_.clear();
  for (size_t i = 0; i < endpoints_.size(); ++i)
    send_mus_.push_back(std::make_unique<Mutex>());

  std::string host;
  int port = 0;
  if (rank_ < 0 || rank_ >= static_cast<int>(endpoints_.size()) ||
      !SplitHostPort(endpoints_[rank_], &host, &port)) {
    Log::Error("TcpNet: bad rank %d / endpoint list (%zu entries)", rank_,
               endpoints_.size());
    return false;
  }

  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd_ < 0) return false;
  int one = 1;
  ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
          0 ||
      ::listen(listen_fd_, 64) < 0) {
    Log::Error("TcpNet: cannot listen on port %d", port);
    ::close(listen_fd_);
    listen_fd_ = -1;
    return false;
  }
  running_ = true;
  accept_thread_ = std::thread([this] { AcceptLoop(); });
  Log::Info("TcpNet: rank %d/%zu listening on :%d", rank_,
            endpoints_.size(), port);
  return true;
}

void TcpNet::AcceptLoop() {
  while (true) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) return;  // listen_fd_ closed by Stop
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    MutexLock lk(readers_mu_);
    if (!running_) {
      ::close(fd);
      return;
    }
    accepted_fds_.push_back(fd);
    readers_.emplace_back([this, fd] { ReadLoop(fd); });
  }
}

void TcpNet::ReadLoop(int fd) {
  const int64_t body_timeout = FlagOr("io_timeout_ms", 30000);
  while (true) {
    Message m;
    int64_t frame_bytes = 0;
    if (!RecvFramed(fd, &m, 0, body_timeout, &frame_bytes)) {
      ::close(fd);
      return;
    }
    // Wire-byte ledger (docs/wire_compression.md): count = messages,
    // total = bytes (1 unit = 1 byte) — MV_WireStats / the Python
    // net.bytes{dir=recv} bridge read both from this one monitor.
    Dashboard::Record("net.bytes.recv", static_cast<double>(frame_bytes));
    // Latency trail: frame-complete stamp (the reader thread is this
    // engine's "reactor" boundary) — requests only, stamp-if-zero.
    latency::StampRecv(&m);
    // Tail plane: adopt the propagated deadline at the recv boundary.
    qos::AdoptDeadline(&m);
    if (inbound_) inbound_(std::move(m));
  }
}

int TcpNet::ConnectTo(int dst_rank) {
  std::string host;
  int port = 0;
  if (!SplitHostPort(endpoints_[dst_rank], &host, &port)) return -1;
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (::getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints,
                    &res) != 0 ||
      !res)
    return -1;
  // Peers start in any order: retry within the configured budget.
  int fd = -1;
  int attempts = static_cast<int>(std::max<int64_t>(
      1, connect_retry_ms_ / 100));
  for (int attempt = 0; attempt < attempts; ++attempt) {
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) break;
    if (::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      // Bounded writes: a peer that stops draining its socket (wedged,
      // SIGSTOPped) turns ::send into a deadline error instead of an
      // indefinite block — the write-side half of the recv deadline.
      int64_t io_ms = FlagOr("io_timeout_ms", 30000);
      if (io_ms > 0) {
        timeval tv{static_cast<time_t>(io_ms / 1000),
                   static_cast<suseconds_t>((io_ms % 1000) * 1000)};
        ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
      }
      break;
    }
    ::close(fd);
    fd = -1;
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
    {
      MutexLock lk(mu_);
      if (!running_) break;
    }
  }
  ::freeaddrinfo(res);
  return fd;
}

bool TcpNet::SendAttempt(int dst_rank, const Message& msg) {
  // Connect OUTSIDE the per-destination send mutex: the retry loop can
  // take seconds, and holding the mutex through it would stall Stop()
  // (which closes fds under the same mutex) and serialize every sender
  // to this rank behind the retries.
  bool need_connect;
  {
    MutexLock lk(*send_mus_[dst_rank]);
    need_connect = send_fds_[dst_rank] < 0;
  }
  if (need_connect) {
    int nfd = ConnectTo(dst_rank);
    MutexLock lk(*send_mus_[dst_rank]);
    if (send_fds_[dst_rank] < 0) {
      send_fds_[dst_rank] = nfd;       // install (may still be -1)
    } else if (nfd >= 0) {
      ::close(nfd);                    // raced: another sender connected
    }
  }
  MutexLock lk(*send_mus_[dst_rank]);
  int fd = send_fds_[dst_rank];
  if (fd < 0) {
    Log::Error("TcpNet: cannot reach rank %d (%s)", dst_rank,
               endpoints_[dst_rank].c_str());
    return false;
  }
  // Injected wire failure (chaos suite): indistinguishable from a real
  // failed write downstream of here — the connection is torn down and
  // the retry loop, if any budget remains, reconnects.
  if (Fault::Enabled() && Fault::FailSendAttempt()) {
    Dashboard::Record("fault.fail_send", 0.0);
    ::close(fd);
    send_fds_[dst_rank] = -1;
    Log::Error("TcpNet: send to rank %d failed (injected)", dst_rank);
    return false;
  }
  if (!SendFramed(fd, msg)) {
    ::close(fd);
    send_fds_[dst_rank] = -1;
    Log::Error("TcpNet: send to rank %d failed", dst_rank);
    return false;
  }
  // Per successful write attempt (retries resend the frame — those
  // bytes really crossed the wire too): count = messages, total = bytes.
  Dashboard::Record("net.bytes.sent",
                    static_cast<double>(msg.WireBytes() +
                                        static_cast<int64_t>(sizeof(int64_t))));
  return true;
}

bool TcpNet::Send(int dst_rank, const Message& msg) {
  if (dst_rank < 0 || dst_rank >= static_cast<int>(endpoints_.size()))
    return false;
  // Wire-send latency (with percentile buckets via MV_DumpMonitors);
  // the span shares the message's trace id, so a merged trace shows the
  // hop that carried a Get between its worker and server spans.
  Monitor mon("Net::Send", msg.trace_id);
  // No Serialize() here: SendAttempt gather-writes the message's blobs
  // in place (header + iovecs), so the old full-payload copy — and the
  // allocation behind it — is gone from the hot path entirely.

  bool duplicate = false;
  if (Fault::Enabled()) {
    int64_t delay_ms = 0;
    switch (Fault::OnSend(&delay_ms)) {
      case Fault::Action::kDrop:
        // The message silently vanishes (a lossy wire): the caller sees
        // success and the reply deadline upstream turns it into -3.
        Dashboard::Record("net.dropped", 0.0);
        return true;
      case Fault::Action::kDelay:
        Dashboard::Record("net.delayed", 0.0);
        std::this_thread::sleep_for(std::chrono::milliseconds(delay_ms));
        break;
      case Fault::Action::kDuplicate:
        duplicate = true;
        break;
      case Fault::Action::kNone:
        break;
    }
  }

  // Bounded retry with exponential backoff: a transient failure (peer
  // restarting, injected fault, send buffer deadline) is retried after
  // reconnecting; a genuinely dead peer exhausts the budget and fails.
  const int retries =
      static_cast<int>(std::max<int64_t>(0, FlagOr("send_retries", 2)));
  int64_t backoff_ms = std::max<int64_t>(1, FlagOr("send_backoff_ms", 50));
  for (int attempt = 0; attempt <= retries; ++attempt) {
    if (attempt > 0) {
      Dashboard::Record("net.retries", 0.0);
      std::this_thread::sleep_for(std::chrono::milliseconds(backoff_ms));
      backoff_ms *= 2;
      MutexLock lk(mu_);
      if (!running_) return false;
    }
    if (SendAttempt(dst_rank, msg)) {
      if (duplicate) {
        // Second copy best-effort: a duplicating wire does not get to
        // also claim a delivery failure.
        Dashboard::Record("net.duplicated", 0.0);
        SendAttempt(dst_rank, msg);
      }
      return true;
    }
  }
  Log::Error("TcpNet: send to rank %d failed after %d attempt(s)",
             dst_rank, retries + 1);
  return false;
}

void TcpNet::Stop() {
  {
    MutexLock lk(mu_);
    if (!running_ && listen_fd_ < 0) return;
    running_ = false;
  }
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    listen_fd_ = -1;
  }
  if (accept_thread_.joinable()) accept_thread_.join();
  for (size_t i = 0; i < send_fds_.size(); ++i) {
    MutexLock lk(*send_mus_[i]);
    if (send_fds_[i] >= 0) {
      ::shutdown(send_fds_[i], SHUT_RDWR);
      ::close(send_fds_[i]);
      send_fds_[i] = -1;
    }
  }
  std::vector<std::thread> readers;
  {
    MutexLock lk(readers_mu_);
    // Unblock readers stuck in recv() even if the peer never closes.
    for (int fd : accepted_fds_) ::shutdown(fd, SHUT_RDWR);
    accepted_fds_.clear();
    readers.swap(readers_);
  }
  for (auto& t : readers)
    if (t.joinable()) t.join();
}

}  // namespace mvtpu
