#include "mvtpu/capacity.h"

#include <dirent.h>

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <sstream>

#include "mvtpu/configure.h"
#include "mvtpu/mutex.h"

namespace mvtpu {
namespace capacity {

namespace {

// Armed by default (the `-capacity_enabled` flag default); Zoo::Start
// latches the flag value, MV_SetCapacityTracking toggles live.
std::atomic<bool> g_armed{true};

int64_t SteadyNowMs() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Module-load anchor for the uptime field (steady clock: a stepped
// wall clock must not produce negative uptimes).
const int64_t g_start_ms = SteadyNowMs();

Mutex g_gauge_mu;
// std::map: deterministic JSON ordering for canned-scrape tests.
// capacity: the registry itself is bounded by the (static) set of
// registering subsystems — a handful of names, never per-key.
std::map<std::string, GaugeFn> g_gauges GUARDED_BY(g_gauge_mu);

struct Window {
  int64_t ts_ms = 0;
  int64_t gets = 0;
  int64_t adds = 0;
  int64_t bytes = 0;
  int64_t bucket_load[kLoadBuckets] = {0};
};

Mutex g_hist_mu;
// capacity: bounded by construction — kHistoryWindows windows per live
// table id; table ids are a registry, never per-key.
std::map<int32_t, std::deque<Window>> g_history GUARDED_BY(g_hist_mu);
int64_t g_last_window_ms GUARDED_BY(g_hist_mu) = -1;

std::string FmtDouble(double v) {
  char buf[64];
  std::snprintf(buf, sizeof(buf), "%.9g", v);
  return buf;
}

}  // namespace

bool Armed() { return g_armed.load(std::memory_order_relaxed); }
void Arm(bool on) { g_armed.store(on, std::memory_order_relaxed); }

void RegisterGauge(const std::string& name, GaugeFn fn) {
  MutexLock lk(g_gauge_mu);
  g_gauges[name] = std::move(fn);
}

void UnregisterGauge(const std::string& name) {
  MutexLock lk(g_gauge_mu);
  g_gauges.erase(name);
}

std::string GaugesJson() {
  // Snapshot the callbacks under the lock, RUN them outside it: a
  // gauge that takes its subsystem's lock (arena, write queues) must
  // never nest inside the registry mutex.
  std::vector<std::pair<std::string, GaugeFn>> snap;
  {
    MutexLock lk(g_gauge_mu);
    for (const auto& kv : g_gauges) snap.push_back(kv);
  }
  std::ostringstream os;
  os << "{";
  bool first = true;
  for (const auto& kv : snap) {
    long long v = kv.second ? kv.second() : 0;
    if (!first) os << ',';
    first = false;
    os << "\"" << kv.first << "\":" << v;
  }
  os << "}";
  return os.str();
}

ProcStats Proc() {
  ProcStats st;
  st.uptime_s =
      static_cast<double>(SteadyNowMs() - g_start_ms) / 1e3;
  // VmRSS / VmHWM from /proc/self/status (kB lines); best-effort —
  // non-Linux hosts report -1 and the JSON still parses.
  if (std::FILE* fp = std::fopen("/proc/self/status", "r")) {
    char line[256];
    while (std::fgets(line, sizeof(line), fp)) {
      long long kb = 0;
      if (std::sscanf(line, "VmRSS: %lld kB", &kb) == 1)
        st.rss_bytes = kb * 1024;
      else if (std::sscanf(line, "VmHWM: %lld kB", &kb) == 1)
        st.vm_hwm_bytes = kb * 1024;
    }
    std::fclose(fp);
  }
  if (DIR* d = ::opendir("/proc/self/fd")) {
    long long n = 0;
    while (::readdir(d)) ++n;
    ::closedir(d);
    st.open_fds = n - 3;  // ".", "..", and the opendir fd itself
  }
  return st;
}

std::string ProcJson() {
  ProcStats st = Proc();
  std::ostringstream os;
  os << "{\"rss_bytes\":" << st.rss_bytes
     << ",\"vm_hwm_bytes\":" << st.vm_hwm_bytes
     << ",\"open_fds\":" << st.open_fds
     << ",\"uptime_s\":" << FmtDouble(st.uptime_s) << "}";
  return os.str();
}

bool HistoryDue() {
  int64_t interval = configure::Has("capacity_history_ms")
                         ? configure::GetInt("capacity_history_ms")
                         : 250;
  int64_t now = SteadyNowMs();
  MutexLock lk(g_hist_mu);
  if (g_last_window_ms >= 0 && now - g_last_window_ms < interval)
    return false;
  g_last_window_ms = now;
  return true;
}

void RecordHistory(int32_t table_id, int64_t gets, int64_t adds,
                   int64_t bytes, const int64_t* bucket_load) {
  Window w;
  w.ts_ms = SteadyNowMs();
  w.gets = gets;
  w.adds = adds;
  w.bytes = bytes;
  if (bucket_load)
    std::memcpy(w.bucket_load, bucket_load,
                sizeof(int64_t) * kLoadBuckets);
  MutexLock lk(g_hist_mu);
  auto& ring = g_history[table_id];
  ring.push_back(w);
  while (ring.size() > static_cast<size_t>(kHistoryWindows))
    ring.pop_front();
}

std::string HistoryJson(int32_t table_id) {
  // Render from a snapshot copy so the emitter never holds g_hist_mu.
  std::deque<Window> snap;
  {
    MutexLock lk(g_hist_mu);
    auto it = g_history.find(table_id);
    if (it != g_history.end()) snap = it->second;
  }
  const std::deque<Window>& ring = snap;
  std::ostringstream os;
  os << "{\"windows\":" << ring.size();
  if (ring.size() >= 2) {
    const Window& a = ring.front();
    const Window& b = ring.back();
    double span_s =
        static_cast<double>(b.ts_ms - a.ts_ms) / 1e3;
    os << ",\"span_ms\":" << (b.ts_ms - a.ts_ms);
    if (span_s > 0) {
      auto rate = [&](int64_t hi, int64_t lo) {
        double d = static_cast<double>(hi - lo) / span_s;
        return d > 0 ? d : 0.0;  // a counter reset reads 0, not < 0
      };
      os << ",\"get_rate\":" << FmtDouble(rate(b.gets, a.gets));
      os << ",\"add_rate\":" << FmtDouble(rate(b.adds, a.adds));
      os << ",\"bytes_rate\":" << FmtDouble(rate(b.bytes, a.bytes));
      os << ",\"bucket_rate\":[";
      for (int i = 0; i < kLoadBuckets; ++i) {
        if (i) os << ',';
        os << FmtDouble(rate(b.bucket_load[i], a.bucket_load[i]));
      }
      os << "]";
    }
  }
  os << ",\"curve\":[";
  for (size_t i = 0; i < ring.size(); ++i) {
    if (i) os << ',';
    os << "{\"ts_ms\":" << ring[i].ts_ms << ",\"gets\":" << ring[i].gets
       << ",\"adds\":" << ring[i].adds << ",\"bytes\":" << ring[i].bytes
       << "}";
  }
  os << "]}";
  return os.str();
}

void ResetHistory() {
  MutexLock lk(g_hist_mu);
  g_history.clear();
  g_last_window_ms = -1;
}

}  // namespace capacity
}  // namespace mvtpu
