#include "mvtpu/qos.h"

#include <algorithm>
#include <deque>
#include <sstream>
#include <unordered_set>
#include <vector>

#include "mvtpu/configure.h"
#include "mvtpu/dashboard.h"
#include "mvtpu/latency.h"
#include "mvtpu/log.h"
#include "mvtpu/mutex.h"

namespace mvtpu {
namespace qos {

namespace {

struct Class {
  std::string name;
  long long weight = 1;
  long long budget = 0;     // guaranteed inflight slots
  long long inflight = 0;
  long long deficit = 0;    // borrow credit (WDRR)
  long long admits = 0;
  long long sheds = 0;
  long long deadline_sheds = 0;
};

struct State {
  std::vector<Class> classes;
  long long cap = 0;          // -qos_inflight_max; <=0 disables admission
  long long max_weight = 1;   // deficit quantum: one borrow per round
  int my_class = 0;           // -qos_class resolved to an id
  bool stamp = true;          // -wire_deadline
  long long deadline_sheds = 0;
  long long cancels_noted = 0;
  long long cancelled = 0;
  // Bounded hedge-cancel registry: tokens are consumed once; the
  // oldest is evicted past capacity (a stale token for a request that
  // already completed is harmless — msg ids are never reused).
  std::deque<uint64_t> cancel_fifo;
  std::unordered_set<uint64_t> cancel_set;
};

constexpr size_t kCancelCap = 1024;

Mutex g_mu;
State& S() REQUIRES(g_mu) {
  static State* s = new State();
  return *s;
}

uint64_t CancelKey(int32_t src, int64_t msg_id) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(src)) << 32) ^
         (static_cast<uint64_t>(msg_id) * 0x9e3779b97f4a7c15ull);
}

std::string FlagStr(const char* name, const char* dflt) {
  return configure::Has(name) ? configure::GetString(name) : dflt;
}

int64_t FlagInt(const char* name, int64_t dflt) {
  return configure::Has(name) ? configure::GetInt(name) : dflt;
}

bool FlagBool(const char* name, bool dflt) {
  return configure::Has(name) ? configure::GetBool(name) : dflt;
}

// Parse "name:weight,name:weight" (bad entries skipped with a log, a
// weightless "name" gets weight 1); guarantees at least one class.
std::vector<Class> ParseClasses(const std::string& spec) {
  std::vector<Class> out;
  std::istringstream in(spec);
  std::string tok;
  while (std::getline(in, tok, ',')) {
    if (tok.empty()) continue;
    Class c;
    auto colon = tok.find(':');
    c.name = tok.substr(0, colon);
    if (colon != std::string::npos) {
      try {
        c.weight = std::max(1ll, static_cast<long long>(
                                     std::stoll(tok.substr(colon + 1))));
      } catch (...) {
        Log::Error("qos: bad weight in -qos_classes entry '%s' (using 1)",
                   tok.c_str());
      }
    }
    if (!c.name.empty()) out.push_back(std::move(c));
  }
  if (out.empty()) out.push_back(Class{"bulk", 1, 0, 0, 0, 0, 0, 0});
  return out;
}

int ClampClassLocked(int klass) REQUIRES(g_mu) {
  if (klass < 0 || klass >= static_cast<int>(S().classes.size())) return 0;
  return klass;
}

}  // namespace

void Configure() {
  MutexLock lk(g_mu);
  State& s = S();
  s.classes = ParseClasses(FlagStr("qos_classes", "bulk:1,gold:8"));
  s.cap = FlagInt("qos_inflight_max", 0);
  s.stamp = FlagBool("wire_deadline", true);
  long long wsum = 0;
  s.max_weight = 1;
  for (auto& c : s.classes) {
    wsum += c.weight;
    s.max_weight = std::max(s.max_weight, c.weight);
  }
  // Guaranteed share: cap * weight / sum(weights), floored at one slot
  // so a low-weight class is throttled, never starved outright.
  for (auto& c : s.classes)
    c.budget = s.cap > 0
                   ? std::max(1ll, s.cap * c.weight / std::max(1ll, wsum))
                   : 0;
  s.my_class = 0;
  std::string mine = FlagStr("qos_class", "bulk");
  for (size_t i = 0; i < s.classes.size(); ++i)
    if (s.classes[i].name == mine) s.my_class = static_cast<int>(i);
}

void Reset() {
  MutexLock lk(g_mu);
  State& s = S();
  for (auto& c : s.classes) {
    c.inflight = c.deficit = c.admits = c.sheds = c.deadline_sheds = 0;
  }
  s.deadline_sheds = s.cancels_noted = s.cancelled = 0;
  s.cancel_fifo.clear();
  s.cancel_set.clear();
}

int NumClasses() {
  MutexLock lk(g_mu);
  return static_cast<int>(S().classes.size());
}

int ClassId(const std::string& name) {
  MutexLock lk(g_mu);
  auto& cls = S().classes;
  for (size_t i = 0; i < cls.size(); ++i)
    if (cls[i].name == name) return static_cast<int>(i);
  return -1;
}

std::string ClassName(int klass) {
  MutexLock lk(g_mu);
  auto& cls = S().classes;
  if (klass < 0 || klass >= static_cast<int>(cls.size())) return "?";
  return cls[static_cast<size_t>(klass)].name;
}

bool TryAdmit(int klass) {
  std::string name;
  bool admitted;
  {
    MutexLock lk(g_mu);
    State& s = S();
    klass = ClampClassLocked(klass);
    Class& c = s.classes[static_cast<size_t>(klass)];
    name = c.name;
    if (s.cap <= 0) {
      // Admission disabled: admit (and count — the mvtop view still
      // shows per-class traffic shape with the gate off).
      ++c.admits;
      admitted = true;
    } else if (c.inflight < c.budget) {
      // Guaranteed share.
      ++c.inflight;
      ++c.admits;
      admitted = true;
    } else {
      long long total = 0;
      for (auto& k : s.classes) total += k.inflight;
      if (total < s.cap) {
        // Spare capacity: borrow in weight proportion — each failed
        // guaranteed-share pass earns `weight` credit, one borrow
        // costs the max weight, so gold borrows 8x as often as bulk
        // under gold:8,bulk:1.
        c.deficit += c.weight;
        if (c.deficit >= s.max_weight) {
          c.deficit -= s.max_weight;
          ++c.inflight;
          ++c.admits;
          admitted = true;
        } else {
          ++c.sheds;
          admitted = false;
        }
      } else {
        ++c.sheds;
        admitted = false;
      }
    }
  }
  Dashboard::Record(
      (admitted ? "serve.qos.admit." : "serve.qos.shed.") + name, 0.0);
  return admitted;
}

void Release(int klass) {
  MutexLock lk(g_mu);
  State& s = S();
  if (s.cap <= 0) return;  // nothing was held
  klass = ClampClassLocked(klass);
  Class& c = s.classes[static_cast<size_t>(klass)];
  if (c.inflight > 0) --c.inflight;
}

void StampRequest(Message* m) {
  bool stamp;
  int my_class;
  {
    MutexLock lk(g_mu);
    stamp = S().stamp;
    my_class = S().my_class;
  }
  if (!stamp) return;
  int64_t timeout_ms =
      configure::Has("rpc_timeout_ms") ? configure::GetInt("rpc_timeout_ms")
                                       : 0;
  if (timeout_ms <= 0) return;  // unbounded caller: no deadline to carry
  m->flags |= msgflag::kHasQos;
  m->qos.klass = my_class;
  m->qos.budget_ns = timeout_ms * 1000000;
}

void AdoptDeadline(Message* m) {
  if (!m->has_qos() || m->qos.budget_ns <= 0) {
    m->qos_deadline_ns = 0;
    return;
  }
  int64_t remaining = m->qos.budget_ns;
  // Wire-time correction (the PR 11 clock-offset machinery): with a
  // timing trail and a per-peer offset estimate, the budget already
  // spent crossing the wire comes off the remaining allowance.  No
  // estimate (anonymous clients stamp no rank) = conservative zero.
  if (m->has_timing() && m->timing.t[TimingTrail::kSend] != 0 &&
      m->timing.t[TimingTrail::kRecv] != 0) {
    int64_t offset = 0, rtt = 0;
    if (m->src >= 0 && latency::PeerOffset(m->src, &offset, &rtt)) {
      int64_t wire_ns = (m->timing.t[TimingTrail::kRecv] - offset) -
                        m->timing.t[TimingTrail::kSend];
      if (wire_ns > 0) remaining -= wire_ns;
    }
  }
  m->qos_deadline_ns = latency::NowNs() + std::max<int64_t>(remaining, 0);
}

bool ShedExpired(const Message& m) {
  if (m.qos_deadline_ns == 0 || latency::NowNs() < m.qos_deadline_ns)
    return false;
  std::string name;
  {
    MutexLock lk(g_mu);
    State& s = S();
    int klass = ClampClassLocked(m.qos.klass);
    Class& c = s.classes[static_cast<size_t>(klass)];
    ++c.deadline_sheds;
    ++s.deadline_sheds;
    name = c.name;
  }
  Dashboard::Record("serve.deadline.shed", 0.0);
  Dashboard::Record("serve.deadline.shed." + name, 0.0);
  return true;
}

long long DeadlineSheds() {
  MutexLock lk(g_mu);
  return S().deadline_sheds;
}

void NoteCancel(int32_t src, int64_t msg_id) {
  uint64_t key = CancelKey(src, msg_id);
  MutexLock lk(g_mu);
  State& s = S();
  ++s.cancels_noted;
  if (s.cancel_set.insert(key).second) {
    s.cancel_fifo.push_back(key);
    while (s.cancel_fifo.size() > kCancelCap) {
      s.cancel_set.erase(s.cancel_fifo.front());
      s.cancel_fifo.pop_front();
    }
  }
}

bool Cancelled(int32_t src, int64_t msg_id) {
  uint64_t key = CancelKey(src, msg_id);
  bool hit;
  {
    MutexLock lk(g_mu);
    State& s = S();
    hit = s.cancel_set.erase(key) > 0;
    if (hit) ++s.cancelled;
    // The FIFO entry stays until evicted — a set miss there is cheap.
  }
  if (hit) Dashboard::Record("serve.hedge.cancelled", 0.0);
  return hit;
}

std::string Json() {
  MutexLock lk(g_mu);
  State& s = S();
  std::ostringstream os;
  os << "{\"inflight_max\":" << s.cap << ",\"classes\":[";
  for (size_t i = 0; i < s.classes.size(); ++i) {
    const Class& c = s.classes[i];
    if (i) os << ',';
    os << "{\"name\":\"" << c.name << "\",\"weight\":" << c.weight
       << ",\"budget\":" << c.budget << ",\"inflight\":" << c.inflight
       << ",\"admits\":" << c.admits << ",\"sheds\":" << c.sheds
       << ",\"deadline_sheds\":" << c.deadline_sheds << "}";
  }
  os << "],\"deadline_shed\":" << s.deadline_sheds
     << ",\"cancels_noted\":" << s.cancels_noted
     << ",\"cancelled\":" << s.cancelled << "}";
  return os.str();
}

}  // namespace qos
}  // namespace mvtpu
