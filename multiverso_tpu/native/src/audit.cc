#include "mvtpu/audit.h"

#include <algorithm>
#include <atomic>
#include <chrono>
#include <sstream>

#include "mvtpu/configure.h"
#include "mvtpu/dashboard.h"
#include "mvtpu/ops.h"

namespace mvtpu {
namespace audit {

namespace {

std::atomic<bool> g_armed{true};

int64_t FlagOr(const char* name, int64_t dflt) {
  return configure::Has(name) ? configure::GetInt(name) : dflt;
}

int64_t NowMs() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

const char* AnomalyName(Anomaly::Kind k) {
  switch (k) {
    case Anomaly::kDup: return "dup";
    case Anomaly::kReorder: return "reorder";
    case Anomaly::kGap: return "gap";
  }
  return "?";
}

// Bound on the per-origin pending out-of-order set: a reorder window
// larger than this is already an audit_gap story, and the books must
// stay O(1) against a hostile seq stream.
constexpr size_t kMaxPendingRanges = 64;

std::atomic<uint32_t*> g_crc_table{nullptr};

const uint32_t* CrcTable() {
  uint32_t* t = g_crc_table.load(std::memory_order_acquire);
  if (t) return t;
  uint32_t* fresh = new uint32_t[256];
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? 0xedb88320u ^ (c >> 1) : c >> 1;
    fresh[i] = c;
  }
  uint32_t* expect = nullptr;
  if (!g_crc_table.compare_exchange_strong(expect, fresh,
                                           std::memory_order_acq_rel))
    delete[] fresh;  // lost the race; the winner's table serves everyone
  return g_crc_table.load(std::memory_order_acquire);
}

}  // namespace

void Arm(bool on) { g_armed.store(on, std::memory_order_relaxed); }
bool Armed() { return g_armed.load(std::memory_order_relaxed); }

uint32_t Crc32(const void* data, size_t n, uint32_t seed) {
  const uint32_t* table = CrcTable();
  uint32_t c = seed ^ 0xffffffffu;
  const unsigned char* p = static_cast<const unsigned char*>(data);
  for (size_t i = 0; i < n; ++i) c = table[(c ^ p[i]) & 0xffu] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

// ---------------------------------------------------------- DeliveryBook

void DeliveryBook::RecordAnomaly(Anomaly::Kind kind, int origin,
                                 int64_t lo, int64_t hi) {
  size_t cap = static_cast<size_t>(
      std::max<int64_t>(8, FlagOr("audit_ring", 64)));
  Anomaly a{kind, origin, lo, hi, NowMs()};
  if (ring_.size() < cap) {
    ring_.push_back(a);
  } else {
    // Bounded ring: overwrite the oldest slot (ring_next_ wraps).
    ring_[ring_next_ % cap] = a;
  }
  ring_next_ = (ring_next_ + 1) % cap;
  ++ring_total_;
}

void DeliveryBook::NoteApply(int origin, int64_t seq_lo, int64_t seq_hi,
                             int32_t table_id) {
  if (!Armed() || seq_lo <= 0 || seq_hi < seq_lo) return;
  int64_t now_ms = NowMs();
  MutexLock lk(mu_);
  OriginState& st = origins_[origin];
  ++st.applied;
  st.covered += seq_hi - seq_lo + 1;
  if (seq_hi <= st.watermark) {
    // Entirely below the watermark: a re-delivered message (transport
    // retry, injected dup).  The apply itself already happened — the
    // updater re-applied the delta, which is the documented
    // INDETERMINATE retry contract — the book's job is to make the
    // duplication VISIBLE, not to mask it.
    ++st.dups;
    Dashboard::Record("audit.dup", 0.0);
    RecordAnomaly(Anomaly::kDup, origin, seq_lo, seq_hi);
  } else if (seq_lo <= st.watermark + 1) {
    // Contiguous (or overlapping a retried prefix): advance, then
    // drain any pending ranges the new watermark reaches.
    st.watermark = seq_hi;
    auto it = st.pending.begin();
    while (it != st.pending.end() && it->first <= st.watermark + 1) {
      st.watermark = std::max(st.watermark, it->second);
      it = st.pending.erase(it);
    }
    if (st.pending.empty()) {
      st.pending_since_ms = -1;
      st.gap_fired = false;  // episode closed; a future gap re-arms
    }
  } else {
    // Ahead of a hole: out-of-order.  Park the range; contiguity (or
    // the grace deadline) decides later whether this was a benign
    // reorder or a real loss.
    ++st.reorders;
    Dashboard::Record("audit.reorder", 0.0);
    RecordAnomaly(Anomaly::kReorder, origin, seq_lo, seq_hi);
    auto it = st.pending.find(seq_lo);
    if (it == st.pending.end() || it->second < seq_hi)
      st.pending[seq_lo] = std::max(seq_hi, it == st.pending.end()
                                                ? seq_hi
                                                : it->second);
    if (st.pending_since_ms < 0) st.pending_since_ms = now_ms;
    while (st.pending.size() > kMaxPendingRanges) {
      // Evict the HIGHEST range: the low end is what contiguity will
      // drain next, and the eviction stays visible in the counter.
      st.pending.erase(std::prev(st.pending.end()));
      ++st.pending_dropped;
    }
  }
  CheckGapsLocked(table_id, now_ms);
}

void DeliveryBook::CheckGapsLocked(int32_t table_id, int64_t now_ms) {
  int64_t grace = FlagOr("audit_grace_ms", 2000);
  for (auto& [origin, st] : origins_) {
    if (st.pending.empty() || st.gap_fired ||
        st.pending_since_ms < 0 || now_ms - st.pending_since_ms < grace)
      continue;
    st.gap_fired = true;
    int64_t miss_lo = st.watermark + 1;
    int64_t miss_hi = st.pending.begin()->first - 1;
    RecordAnomaly(Anomaly::kGap, origin, miss_lo, miss_hi);
    Dashboard::Record("audit.gap", 0.0);
    // The whole point of detection-time auditing: the black box
    // captures the evidence NOW, with the recent event/span ring
    // still holding the window the adds vanished in.
    ops::BlackboxTrigger(
        "audit_gap: table " + std::to_string(table_id) + " origin " +
        std::to_string(origin) + " missing seqs [" +
        std::to_string(miss_lo) + "," + std::to_string(miss_hi) +
        "] beyond grace");
  }
}

int64_t DeliveryBook::Watermark(int origin) const {
  MutexLock lk(mu_);
  auto it = origins_.find(origin);
  return it == origins_.end() ? 0 : it->second.watermark;
}

bool DeliveryBook::Covers(int origin, int64_t seq_lo,
                          int64_t seq_hi) const {
  if (seq_lo <= 0 || seq_hi < seq_lo) return false;
  MutexLock lk(mu_);
  auto it = origins_.find(origin);
  if (it == origins_.end()) return false;
  const OriginState& st = it->second;
  if (seq_hi <= st.watermark) return true;
  // Parked out-of-order range fully containing [lo, hi] also counts:
  // that delivery happened, it just arrived ahead of a hole.
  for (const auto& [plo, phi] : st.pending)
    if (plo <= seq_lo && seq_hi <= phi) return true;
  return false;
}

void DeliveryBook::NoteDupSkipped(int origin, int64_t seq_lo,
                                  int64_t seq_hi) {
  if (!Armed()) return;
  MutexLock lk(mu_);
  OriginState& st = origins_[origin];
  ++st.dups;
  Dashboard::Record("audit.dup", 0.0);
  RecordAnomaly(Anomaly::kDup, origin, seq_lo, seq_hi);
}

std::vector<std::pair<int, int64_t>> DeliveryBook::ExportWatermarks()
    const {
  MutexLock lk(mu_);
  std::vector<std::pair<int, int64_t>> out;
  out.reserve(origins_.size());
  for (const auto& [origin, st] : origins_)
    out.emplace_back(origin, st.watermark);
  return out;
}

void DeliveryBook::ImportWatermarks(
    const std::vector<std::pair<int, int64_t>>& w) {
  MutexLock lk(mu_);
  for (const auto& [origin, mark] : w) {
    OriginState& st = origins_[origin];
    if (mark > st.watermark) st.watermark = mark;
  }
}

void DeliveryBook::CheckGaps(int32_t table_id) {
  if (!Armed()) return;
  MutexLock lk(mu_);
  CheckGapsLocked(table_id, NowMs());
}

std::string DeliveryBook::Json() const {
  MutexLock lk(mu_);
  std::ostringstream os;
  os << "{\"origins\":[";
  bool first = true;
  for (const auto& [origin, st] : origins_) {
    if (!first) os << ',';
    first = false;
    os << "{\"origin\":" << origin << ",\"watermark\":" << st.watermark
       << ",\"applied\":" << st.applied << ",\"covered\":" << st.covered
       << ",\"dups\":" << st.dups << ",\"reorders\":" << st.reorders
       << ",\"pending_dropped\":" << st.pending_dropped
       << ",\"pending\":[";
    bool pf = true;
    for (const auto& [lo, hi] : st.pending) {
      if (!pf) os << ',';
      pf = false;
      os << "[" << lo << "," << hi << "]";
    }
    os << "],\"gap_fired\":" << (st.gap_fired ? "true" : "false") << "}";
  }
  os << "],\"anomalies\":[";
  first = true;
  // Oldest-first over the wrapped ring so the report reads as a log.
  size_t n = ring_.size();
  size_t start = n && ring_total_ > static_cast<long long>(n)
                     ? ring_next_ % n
                     : 0;
  for (size_t i = 0; i < n; ++i) {
    const Anomaly& a = ring_[(start + i) % n];
    if (!first) os << ',';
    first = false;
    os << "{\"kind\":\"" << AnomalyName(a.kind) << "\",\"origin\":"
       << a.origin << ",\"seq_lo\":" << a.seq_lo << ",\"seq_hi\":"
       << a.seq_hi << ",\"ts_ms\":" << a.ts_ms << "}";
  }
  os << "],\"anomaly_total\":" << ring_total_ << "}";
  return os.str();
}

void DeliveryBook::Reset() {
  MutexLock lk(mu_);
  origins_.clear();
  ring_.clear();
  ring_next_ = 0;
  ring_total_ = 0;
}

// ------------------------------------------------------------- AckLedger

void AckLedger::NextRange(int shard, int64_t span, int64_t* lo,
                          int64_t* hi) {
  if (span < 1) span = 1;
  MutexLock lk(mu_);
  if (shard >= static_cast<int>(shards_.size()))
    shards_.resize(static_cast<size_t>(shard) + 1);
  ShardState& st = shards_[shard];
  *lo = st.sent + 1;
  *hi = st.sent + span;
  st.sent = *hi;
}

void AckLedger::Ack(int shard, int64_t seq_hi) {
  MutexLock lk(mu_);
  if (shard < 0 || shard >= static_cast<int>(shards_.size())) return;
  ShardState& st = shards_[shard];
  if (seq_hi > st.acked) st.acked = seq_hi;
}

std::vector<AckLedger::ShardState> AckLedger::Snapshot() const {
  MutexLock lk(mu_);
  return shards_;
}

std::string AckLedger::Json() const {
  auto snap = Snapshot();
  std::ostringstream os;
  os << "{\"shards\":[";
  for (size_t s = 0; s < snap.size(); ++s) {
    if (s) os << ',';
    os << "{\"shard\":" << s << ",\"sent\":" << snap[s].sent
       << ",\"acked\":" << snap[s].acked << "}";
  }
  os << "]}";
  return os.str();
}

void AckLedger::Reset() {
  MutexLock lk(mu_);
  shards_.clear();
}

}  // namespace audit
}  // namespace mvtpu
