#include "mvtpu/codec.h"

#include <cmath>
#include <cstring>

namespace mvtpu {
namespace codec {

namespace {

// Both encoded layouts open with the element count.
struct OneBitHeader {
  int64_t n;
  float pos_scale;
  float neg_scale;
};

struct SparseHeader {
  int64_t n;
  int64_t k;
};

}  // namespace

Codec FromName(const std::string& name) {
  if (name == "1bit") return Codec::kOneBit;
  if (name == "sparse") return Codec::kSparse;
  return Codec::kRaw;
}

bool IsCodecName(const std::string& name) {
  return name == "raw" || name == "1bit" || name == "sparse";
}

const char* Name(Codec c) {
  switch (c) {
    case Codec::kOneBit: return "1bit";
    case Codec::kSparse: return "sparse";
    case Codec::kRaw: default: return "raw";
  }
}

int32_t AcceptFlag(Codec c) {
  switch (c) {
    case Codec::kOneBit: return msgflag::kAccept1Bit;
    case Codec::kSparse: return msgflag::kAcceptSparse;
    case Codec::kRaw: default: return msgflag::kAcceptRaw;
  }
}

Blob EncodeOneBit(const float* delta, size_t n, float* residual) {
  // Pass 1: fold in the residual, sanitize non-finite, bucket means.
  std::vector<float> v(n);
  double pos_sum = 0.0, neg_sum = 0.0;
  size_t pos_cnt = 0, neg_cnt = 0;
  for (size_t i = 0; i < n; ++i) {
    float x = delta[i] + (residual ? residual[i] : 0.0f);
    if (!std::isfinite(x)) x = 0.0f;
    v[i] = x;
    if (x >= 0.0f) {
      pos_sum += x;
      ++pos_cnt;
    } else {
      neg_sum += x;
      ++neg_cnt;
    }
  }
  OneBitHeader h;
  h.n = static_cast<int64_t>(n);
  h.pos_scale = pos_cnt ? static_cast<float>(pos_sum / pos_cnt) : 0.0f;
  h.neg_scale = neg_cnt ? static_cast<float>(neg_sum / neg_cnt) : 0.0f;
  // Pass 2: pack sign bits (LSB-first), write back the residual.
  size_t nbytes = (n + 7) / 8;
  Blob out(sizeof(OneBitHeader) + nbytes);
  std::memcpy(out.data(), &h, sizeof(h));
  uint8_t* bits = reinterpret_cast<uint8_t*>(out.data()) + sizeof(h);
  std::memset(bits, 0, nbytes);
  for (size_t i = 0; i < n; ++i) {
    bool pos = v[i] >= 0.0f;
    if (pos) bits[i / 8] = static_cast<uint8_t>(bits[i / 8] | (1u << (i % 8)));
    if (residual) {
      float recon = pos ? h.pos_scale : h.neg_scale;
      // A sanitized non-finite element must not re-inject -recon next
      // round: its residual resets instead of carrying the correction.
      residual[i] = std::isfinite(delta[i]) ? v[i] - recon : 0.0f;
    }
  }
  return out;
}

bool DecodeOneBit(const Blob& in, std::vector<float>* out) {
  if (in.size() < sizeof(OneBitHeader)) return false;
  OneBitHeader h;
  std::memcpy(&h, in.data(), sizeof(h));
  if (h.n < 0) return false;
  size_t n = static_cast<size_t>(h.n);
  if (in.size() != sizeof(OneBitHeader) + (n + 7) / 8) return false;
  const uint8_t* bits =
      reinterpret_cast<const uint8_t*>(in.data()) + sizeof(h);
  out->resize(n);
  for (size_t i = 0; i < n; ++i)
    (*out)[i] = (bits[i / 8] >> (i % 8)) & 1 ? h.pos_scale : h.neg_scale;
  return true;
}

Blob EncodeSparse(const float* delta, size_t n) {
  size_t k = 0;
  for (size_t i = 0; i < n; ++i)
    if (delta[i] != 0.0f) ++k;
  size_t enc = sizeof(SparseHeader) + k * (sizeof(int32_t) + sizeof(float));
  if (enc >= n * sizeof(float)) return Blob();  // not smaller: ship raw
  SparseHeader h{static_cast<int64_t>(n), static_cast<int64_t>(k)};
  Blob out(enc);
  char* p = out.data();
  std::memcpy(p, &h, sizeof(h));
  p += sizeof(h);
  int32_t* idx = reinterpret_cast<int32_t*>(p);
  float* val = reinterpret_cast<float*>(p + k * sizeof(int32_t));
  size_t j = 0;
  for (size_t i = 0; i < n; ++i) {
    if (delta[i] == 0.0f) continue;
    idx[j] = static_cast<int32_t>(i);
    val[j] = delta[i];  // bit-exact: NaN/Inf survive the round trip
    ++j;
  }
  return out;
}

bool DecodeSparse(const Blob& in, std::vector<float>* out) {
  if (in.size() < sizeof(SparseHeader)) return false;
  SparseHeader h;
  std::memcpy(&h, in.data(), sizeof(h));
  if (h.n < 0 || h.k < 0 || h.k > h.n) return false;
  size_t n = static_cast<size_t>(h.n), k = static_cast<size_t>(h.k);
  if (in.size() != sizeof(SparseHeader) + k * 8) return false;
  const char* p = in.data() + sizeof(h);
  const int32_t* idx = reinterpret_cast<const int32_t*>(p);
  const float* val =
      reinterpret_cast<const float*>(p + k * sizeof(int32_t));
  out->assign(n, 0.0f);
  for (size_t j = 0; j < k; ++j) {
    if (idx[j] < 0 || static_cast<size_t>(idx[j]) >= n) return false;
    (*out)[static_cast<size_t>(idx[j])] = val[j];
  }
  return true;
}

bool DecodeInPlace(Message* msg) {
  if (msg->codec == Codec::kRaw) return true;
  if (msg->data.empty()) return false;
  std::vector<float> out;
  bool ok = msg->codec == Codec::kOneBit
                ? DecodeOneBit(msg->data.back(), &out)
                : msg->codec == Codec::kSparse
                      ? DecodeSparse(msg->data.back(), &out)
                      : false;
  if (!ok) return false;
  msg->data.back() = Blob(out.data(), out.size() * sizeof(float));
  msg->codec = Codec::kRaw;
  return true;
}

void MaybeEncodeReply(Message* reply, int32_t accept_flags) {
  if (!(accept_flags & msgflag::kAcceptSparse)) return;
  if (reply->data.size() != 1 || reply->codec != Codec::kRaw) return;
  const Blob& raw = reply->data[0];
  size_t n = raw.count<float>();
  if (n == 0 || raw.size() != n * sizeof(float)) return;
  Blob enc = EncodeSparse(raw.As<float>(), n);
  if (enc.size() == 0) return;  // dense payload: raw is already smaller
  reply->data[0] = std::move(enc);
  reply->codec = Codec::kSparse;
}

}  // namespace codec
}  // namespace mvtpu
