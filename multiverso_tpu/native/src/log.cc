#include "mvtpu/log.h"

#include <atomic>
#include <cstdarg>
#include <cstdio>
#include <cstdlib>
#include <ctime>

#include "mvtpu/mutex.h"

namespace mvtpu {

namespace {
Mutex g_mu;
// Atomic: the level gate runs before taking g_mu on every log call and
// SetLevel may race an in-flight Emit.
std::atomic<LogLevel> g_level{LogLevel::kInfo};
FILE* g_file GUARDED_BY(g_mu) = nullptr;

void Emit(LogLevel level, const char* tag, const char* fmt, va_list ap) {
  if (level < g_level.load(std::memory_order_relaxed)) return;
  MutexLock lk(g_mu);
  char ts[32];
  time_t now = time(nullptr);
  struct tm tmv;
  localtime_r(&now, &tmv);
  strftime(ts, sizeof(ts), "%H:%M:%S", &tmv);
  va_list ap2;
  va_copy(ap2, ap);
  fprintf(stderr, "[%s %s mvtpu] ", tag, ts);
  vfprintf(stderr, fmt, ap);
  fputc('\n', stderr);
  if (g_file) {
    fprintf(g_file, "[%s %s mvtpu] ", tag, ts);
    vfprintf(g_file, fmt, ap2);
    fputc('\n', g_file);
    fflush(g_file);
  }
  va_end(ap2);
}
}  // namespace

void Log::SetLevel(LogLevel level) {
  g_level.store(level, std::memory_order_relaxed);
}

void Log::ResetLogFile(const std::string& path) {
  MutexLock lk(g_mu);
  if (g_file) fclose(g_file);
  g_file = path.empty() ? nullptr : fopen(path.c_str(), "a");
}

#define MVTPU_LOG_BODY(level, tag)      \
  va_list ap;                           \
  va_start(ap, fmt);                    \
  Emit(level, tag, fmt, ap);            \
  va_end(ap)

void Log::Debug(const char* fmt, ...) { MVTPU_LOG_BODY(LogLevel::kDebug, "D"); }
void Log::Info(const char* fmt, ...) { MVTPU_LOG_BODY(LogLevel::kInfo, "I"); }
void Log::Error(const char* fmt, ...) { MVTPU_LOG_BODY(LogLevel::kError, "E"); }

void Log::Fatal(const char* fmt, ...) {
  MVTPU_LOG_BODY(LogLevel::kFatal, "F");
  abort();
}

}  // namespace mvtpu
