#include "mvtpu/host_arena.h"

#include <stdlib.h>
#include <sys/mman.h>

#include "mvtpu/configure.h"

namespace mvtpu {

namespace {

constexpr size_t kAlign = 64;

size_t RoundCap(size_t bytes) {
  if (bytes == 0) bytes = 1;
  return (bytes + kAlign - 1) / kAlign * kAlign;
}

bool PinEnabled() {
  // Flags may not be registered when the arena is driven standalone
  // (unit tests acquire before MV_Init).
  return configure::Has("arena_pin") ? configure::GetBool("arena_pin")
                                     : true;
}

}  // namespace

HostArena* HostArena::Get() {
  static auto* a = new HostArena();
  return a;
}

void* HostArena::Acquire(size_t bytes) {
  size_t cap = RoundCap(bytes);
  {
    MutexLock lk(mu_);
    // First fit with bounded waste: a recycled buffer serves requests
    // down to half its capacity, so size-class drift cannot strand a
    // large buffer behind a stream of tiny Acquires (or vice versa).
    auto it = free_.lower_bound(cap);
    if (it != free_.end() && it->first <= cap * 2) {
      char* base = it->second;
      free_.erase(it);
      Buf& b = bufs_[base];
      b.caller_held = true;
      ++stats_.recycled;
      --stats_.free_buffers;
      ++stats_.buffers;
      return base;
    }
  }
  void* p = nullptr;
  if (posix_memalign(&p, kAlign, cap) != 0) return nullptr;
  Buf b;
  b.cap = cap;
  b.caller_held = true;
  // Best-effort pin: RLIMIT_MEMLOCK commonly forbids large mlocks in
  // unprivileged containers — a miss costs the page-fault/migration
  // guarantee, never correctness, so it is counted rather than fatal.
  if (PinEnabled() && mlock(p, cap) == 0) b.pinned = true;
  MutexLock lk(mu_);
  if (b.pinned) ++stats_.pinned;
  stats_.bytes += static_cast<long long>(cap);
  ++stats_.buffers;
  bufs_[static_cast<char*>(p)] = b;
  return p;
}

void HostArena::Recycle(char* base, Buf* b) {
  free_.emplace(b->cap, base);
  ++stats_.free_buffers;
  --stats_.buffers;
}

int HostArena::Release(void* ptr) {
  MutexLock lk(mu_);
  auto it = bufs_.find(static_cast<char*>(ptr));
  if (it == bufs_.end()) return -1;
  if (!it->second.caller_held) return -2;
  it->second.caller_held = false;
  if (it->second.borrows == 0) {
    Recycle(it->first, &it->second);
  } else {
    // In-flight borrowed send: the recycle waits for the last borrow
    // (DropBorrow) — the caller's Release is still correct and cheap.
    ++stats_.deferred;
  }
  return 0;
}

void* HostArena::BufferOf(const void* p, size_t len) {
  const char* cp = static_cast<const char*>(p);
  MutexLock lk(mu_);
  auto it = bufs_.upper_bound(const_cast<char*>(cp));
  if (it == bufs_.begin()) return nullptr;
  --it;
  const Buf& b = it->second;
  if (!b.caller_held) return nullptr;
  if (cp < it->first || cp + len > it->first + b.cap) return nullptr;
  return it->first;
}

void HostArena::DropBorrow(void* base) {
  MutexLock lk(mu_);
  auto it = bufs_.find(static_cast<char*>(base));
  if (it == bufs_.end()) return;
  if (--it->second.borrows == 0) {
    --stats_.in_flight;
    if (!it->second.caller_held) Recycle(it->first, &it->second);
  }
}

std::shared_ptr<void> HostArena::BorrowHold(void* base) {
  {
    MutexLock lk(mu_);
    auto it = bufs_.find(static_cast<char*>(base));
    if (it == bufs_.end()) return nullptr;
    if (it->second.borrows++ == 0) ++stats_.in_flight;
  }
  return std::shared_ptr<void>(
      base, [](void* b) { HostArena::Get()->DropBorrow(b); });
}

HostArena::Stats HostArena::GetStats() {
  MutexLock lk(mu_);
  return stats_;
}

}  // namespace mvtpu
