#include "mvtpu/mpi_net.h"

#include <dlfcn.h>
#include <stdlib.h>

#include <chrono>
#include <climits>
#include <vector>

#include "mvtpu/configure.h"
#include "mvtpu/dashboard.h"
#include "mvtpu/latency.h"
#include "mvtpu/qos.h"
#include "mvtpu/log.h"

namespace mvtpu {

namespace {

// OpenMPI's public MPI_Status layout (stable across the 4.x ABI): the
// three standard fields plus two internals that only pad the struct.
struct MpiStatus {
  int source;
  int tag;
  int error;
  int cancelled_;
  size_t ucount_;
};

constexpr int kAnySource = -1;        // OpenMPI MPI_ANY_SOURCE
constexpr int kThreadMultiple = 3;    // MPI_THREAD_MULTIPLE
constexpr int kTag = 0x3777;          // all mvtpu traffic rides one tag

// Function pointers + predefined handles resolved from libmpi.  MPI_Comm
// and MPI_Datatype are opaque pointers in the OpenMPI ABI.
struct MpiApi {
  void* handle = nullptr;
  int (*init_thread)(int*, char***, int, int*) = nullptr;
  int (*initialized)(int*) = nullptr;
  int (*finalized)(int*) = nullptr;
  int (*finalize)() = nullptr;
  int (*comm_rank)(void*, int*) = nullptr;
  int (*comm_size)(void*, int*) = nullptr;
  int (*isend)(const void*, int, void*, int, int, void*, void**) = nullptr;
  int (*test)(void**, int*, MpiStatus*) = nullptr;
  int (*recv)(void*, int, void*, int, int, void*, MpiStatus*) = nullptr;
  int (*iprobe)(int, int, void*, int*, MpiStatus*) = nullptr;
  int (*get_count)(const MpiStatus*, void*, int*) = nullptr;
  int (*cancel)(void**) = nullptr;
  int (*request_free)(void**) = nullptr;
  void* comm_world = nullptr;
  void* byte = nullptr;
  bool ok = false;
};

MpiApi LoadMpi() {
  MpiApi api;
  // RTLD_GLOBAL: OpenMPI dlopens its MCA plugins, which resolve symbols
  // against the already-loaded libmpi.
  for (const char* name : {"libmpi.so.40", "libmpi.so", "libmpi.so.80",
                           "libmpi.so.12"}) {
    api.handle = dlopen(name, RTLD_NOW | RTLD_GLOBAL);
    if (api.handle) break;
  }
  if (!api.handle) return api;
  auto sym = [&](const char* n) { return dlsym(api.handle, n); };
  api.init_thread = reinterpret_cast<int (*)(int*, char***, int, int*)>(
      sym("MPI_Init_thread"));
  api.initialized = reinterpret_cast<int (*)(int*)>(sym("MPI_Initialized"));
  api.finalized = reinterpret_cast<int (*)(int*)>(sym("MPI_Finalized"));
  api.finalize = reinterpret_cast<int (*)()>(sym("MPI_Finalize"));
  api.comm_rank =
      reinterpret_cast<int (*)(void*, int*)>(sym("MPI_Comm_rank"));
  api.comm_size =
      reinterpret_cast<int (*)(void*, int*)>(sym("MPI_Comm_size"));
  api.isend = reinterpret_cast<int (*)(const void*, int, void*, int, int,
                                       void*, void**)>(sym("MPI_Isend"));
  api.test =
      reinterpret_cast<int (*)(void**, int*, MpiStatus*)>(sym("MPI_Test"));
  api.recv = reinterpret_cast<int (*)(void*, int, void*, int, int, void*,
                                      MpiStatus*)>(sym("MPI_Recv"));
  api.iprobe = reinterpret_cast<int (*)(int, int, void*, int*, MpiStatus*)>(
      sym("MPI_Iprobe"));
  api.get_count = reinterpret_cast<int (*)(const MpiStatus*, void*, int*)>(
      sym("MPI_Get_count"));
  api.cancel = reinterpret_cast<int (*)(void**)>(sym("MPI_Cancel"));
  api.request_free =
      reinterpret_cast<int (*)(void**)>(sym("MPI_Request_free"));
  // Predefined handles are data symbols in the OpenMPI ABI; their
  // absence means some other MPI (e.g. MPICH's integer handles), whose
  // ABI these declarations would corrupt — treat as unavailable.
  api.comm_world = sym("ompi_mpi_comm_world");
  api.byte = sym("ompi_mpi_byte");
  api.ok = api.init_thread && api.initialized && api.finalized &&
           api.finalize && api.comm_rank && api.comm_size && api.isend &&
           api.test && api.recv && api.iprobe && api.get_count &&
           api.cancel && api.request_free && api.comm_world && api.byte;
  return api;
}

MpiApi& Api() {
  static MpiApi api = LoadMpi();
  return api;
}

// Serial-mode lock: MPI state is process-wide, so the lock is too.
// A namespace-scope Mutex (constant-initialized: std::mutex's ctor is
// constexpr) rather than a function-local static, so GUARDED_BY /
// REQUIRES below have a name to bind to.
Mutex g_mpi_mu;

// Payloads of timed-out/failed sends.  MPI_Request_free drops our
// handle but the library may still read the user buffer until the
// (cancelled or completed) send drains, so the blob is parked for the
// life of the process — bounded by the number of failures, each of
// which already logged an error.
std::vector<Blob>& OrphanedSendBufs() REQUIRES(g_mpi_mu) {
  static auto* v = new std::vector<Blob>();
  return *v;
}

// MPI_Finalize is terminal for the process; latch it so a second
// Init fails cleanly instead of aborting inside libmpi.
std::atomic<bool> g_finalized{false};
// Whether MpiNet::Init performed the MPI_Init — an embedding app that
// initialized MPI itself keeps ownership, and Stop() must not finalize
// the host program's MPI out from under it.
std::atomic<bool> g_we_initialized{false};

}  // namespace

bool MpiNet::Available() { return Api().ok; }

bool MpiNet::Init(InboundFn fn) {
  MpiApi& api = Api();
  if (!api.ok) {
    Log::Error("-net_type=mpi: no usable libmpi (dlopen failed or the "
               "ABI is not OpenMPI's)");
    return false;
  }
  if (g_finalized.load()) {
    Log::Error("-net_type=mpi: MPI was already finalized in this process "
               "(MPI allows one init/finalize cycle; use -net_type=tcp "
               "for restartable runs)");
    return false;
  }
  {
    MutexLock lk(g_mpi_mu);
    int inited = 0;
    api.initialized(&inited);
    if (!inited) {
      // No launcher environment (mpirun/PMIx exports these) → isolated
      // singleton mode, which needs no orted helper binary.
      if (!getenv("OMPI_COMM_WORLD_SIZE") && !getenv("PMIX_RANK") &&
          !getenv("PMI_RANK"))
        setenv("OMPI_MCA_ess_singleton_isolated", "1", 0);
      int provided = 0;
      if (api.init_thread(nullptr, nullptr, kThreadMultiple, &provided) !=
          0) {
        Log::Error("MPI_Init_thread failed");
        return false;
      }
      g_we_initialized.store(true);
      // Serial-mode locking means any `provided` level works; still log
      // a surprising one.
      if (provided < kThreadMultiple)
        Log::Info("MPI provided thread level %d (< MULTIPLE); serial-mode "
                  "locking covers it", provided);
    }
    api.comm_rank(api.comm_world, &rank_);
    api.comm_size(api.comm_world, &size_);
  }
  inbound_ = std::move(fn);
  running_.store(true);
  probe_thread_ = std::thread(&MpiNet::ProbeLoop, this);
  Log::Info("MpiNet up: rank %d/%d (tag %#x)", rank_, size_, kTag);
  return true;
}

size_t MpiNet::OrphanedSendBufCount() {
  MutexLock lk(g_mpi_mu);
  return OrphanedSendBufs().size();
}

bool MpiNet::Send(int dst_rank, const Message& msg) {
  MpiApi& api = Api();
  if (!running_.load() || dst_rank < 0 || dst_rank >= size_) return false;
  // Wire-send latency + trace span (same contract as TcpNet::Send).
  Monitor mon("Net::Send", msg.trace_id);
  // Serialize OUTSIDE the MPI lock (full-payload copy).
  Blob wire = msg.Serialize();
  if (wire.size() > static_cast<size_t>(INT_MAX)) {
    Log::Error("MpiNet: %zu-byte message exceeds MPI's int count",
               wire.size());
    return false;
  }
  // Isend + Test poll, RELEASING the lock between polls: a blocking
  // MPI_Send under g_mpi_mu would starve this rank's own ProbeLoop of
  // the lock, and two ranks exchanging rendezvous-size messages would
  // deadlock (neither probe thread could post the matching Recv).
  void* req = nullptr;
  {
    MutexLock lk(g_mpi_mu);
    if (api.isend(wire.data(), static_cast<int>(wire.size()), api.byte,
                  dst_rank, kTag, api.comm_world, &req) != 0)
      return false;
  }
  // The poll is bounded by -rpc_timeout_ms: a dead or wedged peer that
  // never posts the matching Recv must not wedge this rank forever —
  // the same fail-fast contract TcpNet implements.  On expiry the
  // request is cancelled best-effort (MPI may ignore cancel on sends)
  // and freed; the payload blob is parked in OrphanedSendBufs() because
  // the library can keep reading it until the send actually drains.
  // Has() guard: MpiNet can be driven standalone (tests, embedders)
  // before Zoo registered the flag defaults.  <=0 keeps the flag's
  // documented wait-forever contract (configure.cc).
  const int64_t timeout_ms = configure::Has("rpc_timeout_ms")
                                 ? configure::GetInt("rpc_timeout_ms")
                                 : 30000;
  const auto deadline = std::chrono::steady_clock::now() +
                        std::chrono::milliseconds(timeout_ms);
  while (true) {
    {
      MutexLock lk(g_mpi_mu);
      int done = 0;
      MpiStatus st{};
      if (api.test(&req, &done, &st) != 0) {
        // Error path mirrors the timeout branch below: MPI_Test failing
        // does NOT mean the send drained — the library may still read
        // the user buffer, so free our handle and park the payload
        // instead of letting `wire` die on return.
        api.cancel(&req);
        api.request_free(&req);
        OrphanedSendBufs().push_back(std::move(wire));
        Log::Error("MpiNet::Send to rank %d: MPI_Test failed; request "
                   "freed, payload parked", dst_rank);
        return false;
      }
      if (done) {
        // Same wire-byte ledger as TcpNet (count = msgs, total = bytes).
        Dashboard::Record("net.bytes.sent",
                          static_cast<double>(wire.size()));
        return true;
      }
      if (timeout_ms > 0 && std::chrono::steady_clock::now() >= deadline) {
        api.cancel(&req);
        api.request_free(&req);
        OrphanedSendBufs().push_back(std::move(wire));
        Log::Error("MpiNet::Send to rank %d timed out after %lld ms "
                   "(peer dead or never posted the matching Recv)",
                   dst_rank, static_cast<long long>(timeout_ms));
        return false;
      }
    }
    std::this_thread::sleep_for(std::chrono::microseconds(50));
  }
}

void MpiNet::ProbeLoop() {
  MpiApi& api = Api();
  while (running_.load()) {
    Blob buf;
    bool got = false;
    {
      MutexLock lk(g_mpi_mu);
      int flag = 0;
      MpiStatus st{};
      if (api.iprobe(kAnySource, kTag, api.comm_world, &flag, &st) != 0)
        break;
      if (flag) {
        int n = 0;
        api.get_count(&st, api.byte, &n);
        buf = Blob(static_cast<size_t>(n));
        MpiStatus recv_st{};
        // Probe + matched Recv under one lock hold: no other thread
        // receives, so the probed message cannot be stolen.
        if (api.recv(buf.data(), n, api.byte, st.source, kTag,
                     api.comm_world, &recv_st) == 0)
          got = true;
      }
    }
    if (got) {
      Dashboard::Record("net.bytes.recv", static_cast<double>(buf.size()));
      Message m = Message::Deserialize(buf);
      latency::StampRecv(&m);  // frame-complete on the MPI wire
      qos::AdoptDeadline(&m);  // tail plane: deadline adopted at recv
      inbound_(std::move(m));  // outside the MPI lock
    } else
      std::this_thread::sleep_for(std::chrono::microseconds(200));
  }
}

void MpiNet::Stop() {
  if (!running_.exchange(false)) return;
  if (probe_thread_.joinable()) probe_thread_.join();
  MpiApi& api = Api();
  MutexLock lk(g_mpi_mu);
  int inited = 0, fin = 0;
  api.initialized(&inited);
  api.finalized(&fin);
  // Finalize only the MPI we started: an embedding app that called
  // MPI_Init itself keeps ownership of its MPI lifetime.
  if (inited && !fin && g_we_initialized.load()) {
    g_finalized.store(true);
    api.finalize();
  }
}

}  // namespace mvtpu
