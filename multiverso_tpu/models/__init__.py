"""Model zoo.

The reference has no model zoo — models live in user scripts (SURVEY.md
§1) — but BASELINE.json's stretch config asks for Llama-style decoder
training through the framework, and this package is where the TPU-native
model layer lives: pure-function transformers with mesh-aware sharding
(data/tensor/sequence parallel) and ring attention for long context.
"""

from .transformer import (TransformerConfig, TransformerTrainer,
                          init_params, transformer_forward)

__all__ = ["TransformerConfig", "TransformerTrainer", "init_params",
           "transformer_forward"]
