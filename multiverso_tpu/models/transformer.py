"""Llama-style decoder-only transformer, TPU-first.

Design (not in the reference — see models/__init__):

- pure functions over a params pytree; everything jits;
- **bfloat16 compute, float32 params/state** — the MXU-friendly recipe;
- mesh-aware: batch shards over ``dp``, attention heads + MLP hidden +
  vocab shard over ``tp`` (GSPMD inserts the collectives), sequence shards
  over ``sp`` with ring attention (``parallel/ring_attention.py``);
- updater integration: the train step applies the framework's server-side
  updaters (SURVEY.md §2.16) per parameter leaf, so a Multiverso user's
  ``-updater_type`` flag means the same thing here.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..updaters import AddOption, get_updater
from .. import dashboard

__all__ = ["TransformerConfig", "init_params", "stack_layer_params",
           "transformer_forward", "TransformerTrainer"]


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    dim: int = 512
    n_layers: int = 4
    n_heads: int = 8
    hidden: int = 1408          # SwiGLU inner dim
    max_seq: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    compute_dtype: Any = jnp.bfloat16
    # Mixture-of-Experts: 0 experts = dense SwiGLU MLP; >0 replaces every
    # MLP with a top_k-routed expert layer (models/moe.py), experts
    # sharded over the mesh's ``ep`` axis.
    num_experts: int = 0
    top_k: int = 2
    aux_loss_coef: float = 0.01
    # "dense" = exact all-experts dispatch (the oracle); "capacity" =
    # GShard-style static buckets, FLOPs ∝ top_k·capacity_factor/E.
    moe_dispatch: str = "dense"
    capacity_factor: float = 1.25
    # remat: gradient checkpointing — recompute each layer's forward during
    # the backward pass instead of saving activations.  Trades ~1/3 more
    # matmul FLOPs for O(layers·B·T·dim) activation memory, the knob that
    # lets batch·seq scale to MXU-bound sizes on one chip.  (A save-the-
    # attention-output policy was tried and REMOVED: saving the output
    # prunes no backward recompute — grads w.r.t. wq/wk/wv still need the
    # attention internals — so it only added residual memory.)
    remat: bool = False
    # remat_policy (with remat=True):
    # - "full": save only layer boundaries; the backward re-runs the whole
    #   layer forward (~2P extra matmul FLOPs — bills MFU at ~6/8 of the
    #   hardware's actual utilization).  Minimal memory.
    # - "dots": jax.checkpoint_policies selective remat — save every
    #   matmul output (q/k/v/wo/w1/w3/w2 projections), recompute only the
    #   cheap tensor ops (norms, rope) and the flash-attention kernel
    #   (its custom_vjp output is not a dot, so it replays from the saved
    #   q/k/v).  Recompute tax drops from ~2P to roughly the attention
    #   FLOPs; memory grows to O(layers·B·T·(5·dim+2·hidden)).
    remat_policy: str = "full"
    # scan_layers: stack the per-layer params into [L, ...] arrays and run
    # ``lax.scan`` over them — O(1) trace/compile time in depth and the
    # natural pairing with remat (XLA sees one layer body once).
    scan_layers: bool = False
    # Pipeline parallelism: with a ``pp`` mesh axis and M > 0, the layer
    # stack splits into pp stages and batches flow through the GPipe
    # microbatch schedule (``parallel/pipeline.py``).  Requires
    # scan_layers (stages slice the stacked params), dense MLPs, sp == 1,
    # and batch divisible by M.
    pipeline_microbatches: int = 0

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


def init_params(cfg: TransformerConfig, seed: int = 0) -> Dict[str, Any]:
    """Float32 master weights, truncated-normal-ish init."""
    rng = np.random.RandomState(seed)

    def w(*shape, scale=None):
        scale = scale or (shape[0] ** -0.5)
        return (scale * rng.randn(*shape)).astype(np.float32)

    layers = []
    for _ in range(cfg.n_layers):
        lyr = {
            "wq": w(cfg.dim, cfg.dim),
            "wk": w(cfg.dim, cfg.dim),
            "wv": w(cfg.dim, cfg.dim),
            "wo": w(cfg.dim, cfg.dim),
            "attn_norm": np.ones(cfg.dim, np.float32),
            "mlp_norm": np.ones(cfg.dim, np.float32),
        }
        if cfg.num_experts:
            from .moe import init_moe_params

            lyr["moe"] = init_moe_params(cfg.dim, cfg.hidden,
                                         cfg.num_experts,
                                         seed=rng.randint(2 ** 31))
        else:
            lyr.update({
                "w1": w(cfg.dim, cfg.hidden),   # gate
                "w3": w(cfg.dim, cfg.hidden),   # up
                "w2": w(cfg.hidden, cfg.dim),   # down
            })
        layers.append(lyr)
    if cfg.scan_layers:
        layers = stack_layer_params(layers)
    return {
        "embed": w(cfg.vocab_size, cfg.dim, scale=0.02),
        "out_norm": np.ones(cfg.dim, np.float32),
        "head": w(cfg.dim, cfg.vocab_size),
        "layers": layers,
    }


def stack_layer_params(layers):
    """List of per-layer param dicts → one dict of stacked [L, ...] arrays.

    The scan-format params: leaf k holds ``stack([lyr[k] for lyr in
    layers])``.  Works on numpy or jax leaves (nested dicts included, e.g.
    MoE); used by ``init_params(scan_layers=True)`` and by tests converting
    loop-format params for parity checks.
    """
    return jax.tree_util.tree_map(
        lambda *xs: (np.stack(xs) if isinstance(xs[0], np.ndarray)
                     else jnp.stack(xs)), *layers)


def _layer_pspecs(cfg: TransformerConfig, mesh: Mesh) -> Dict[str, Any]:
    """Per-layer weight PartitionSpecs for the Megatron-style tp layout:
    attention io dims and MLP hidden shard over ``tp`` (column-parallel
    wq/wk/wv/w1/w3, row-parallel wo/w2); norms replicated."""
    tp = "tp" if "tp" in mesh.shape else None

    layer = {
        "wq": P(None, tp), "wk": P(None, tp), "wv": P(None, tp),
        "wo": P(tp, None),
        "attn_norm": P(None), "mlp_norm": P(None),
    }
    if cfg.num_experts:
        from .moe import moe_pspecs

        layer["moe"] = moe_pspecs(mesh)
    else:
        layer.update({"w1": P(None, tp), "w3": P(None, tp),
                      "w2": P(tp, None)})
    return layer


def param_shardings(cfg: TransformerConfig, mesh: Mesh) -> Dict[str, Any]:
    """TP layout: attention io dims, MLP hidden, and vocab shard over ``tp``;
    everything else replicated (dp/sp shard activations, not weights).

    Scan-format params get the same per-layer specs with an unsharded
    leading layer dim."""
    tp = "tp" if "tp" in mesh.shape else None
    layer = _layer_pspecs(cfg, mesh)

    is_spec = lambda x: isinstance(x, P)
    if cfg.scan_layers:
        # With pipeline parallelism the stacked layer dim shards over
        # ``pp`` (each stage holds its own layers); otherwise replicated.
        lead = ("pp" if ("pp" in mesh.shape and cfg.pipeline_microbatches
                         and cfg.n_layers % mesh.shape["pp"] == 0)
                else None)
        layers = jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, P(lead, *spec)), layer,
            is_leaf=is_spec)
    else:
        layers = [jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec), layer, is_leaf=is_spec)
            for _ in range(cfg.n_layers)]

    def s(*spec):
        return NamedSharding(mesh, P(*spec))

    return {
        "embed": s(None, None),
        "out_norm": s(None),
        "head": s(None, tp),
        "layers": layers,
    }


def _rms_norm(x, gain, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * gain


def _rope(x, theta: float):
    """Rotary embedding over global positions; x [B, H, T, D]."""
    B, H, T, D = x.shape
    half = D // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = jnp.arange(T, dtype=jnp.float32)[:, None] * freqs[None, :]  # [T,half]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
    return rot.astype(x.dtype)


def transformer_forward(params, tokens, cfg: TransformerConfig,
                        mesh: Optional[Mesh] = None,
                        return_aux: bool = False):
    """tokens [B, T] int32 → logits [B, T, vocab] (compute dtype).

    With ``return_aux=True`` also returns the summed MoE load-balancing
    auxiliary loss (zero for dense configs)."""
    from ..parallel.ring_attention import blockwise_attention_local, ring_attention

    if tokens.shape[1] > cfg.max_seq:
        raise ValueError(
            f"sequence length {tokens.shape[1]} exceeds max_seq "
            f"{cfg.max_seq}")
    dt = cfg.compute_dtype
    x = params["embed"][tokens].astype(dt)                # [B,T,dim]
    B, T, _ = x.shape
    scale = cfg.head_dim ** -0.5
    use_ring = mesh is not None and int(mesh.shape.get("sp", 1)) > 1

    def make_block(local_heads: int, reduce=None):
        """Build one decoder-layer fn (with the remat wrapper applied).

        ``local_heads``/``reduce`` specialize it for manual tensor
        parallelism inside a pipeline stage: the block then sees
        tp-local column shards of wq/wk/wv/w1/w3 (so ``local_heads =
        n_heads/tp`` and the io width is ``dim/tp``) and ``reduce`` —
        a ``psum`` over the tp axis — completes the row-parallel
        wo/w2 matmuls (the Megatron two-all-reduce-per-layer pattern).
        Default (GSPMD paths): full heads, no explicit collective.
        """
        red = reduce if reduce is not None else (lambda t: t)

        def block(x, lyr):
            """One decoder layer: attn + residual, MLP/MoE + residual.

            Shapes derive from ``x`` itself — under pipeline parallelism
            the block sees microbatches, not the full batch."""
            Bb, Tb, _ = x.shape

            def wc(w):
                # Named so the "dots" policy SAVES the bf16 weight cast:
                # the cast is not a dot, so without the name the
                # backward re-reads the f32 masters and recasts every
                # big weight per layer — avoidable HBM traffic for one
                # bf16 copy of the layer weights of residency.
                return checkpoint_name(w.astype(dt), "wcast")

            h = _rms_norm(x, lyr["attn_norm"].astype(dt), cfg.norm_eps)
            q = (h @ wc(lyr["wq"])).reshape(Bb, Tb, local_heads,
                                            cfg.head_dim)
            k = (h @ wc(lyr["wk"])).reshape(Bb, Tb, local_heads,
                                            cfg.head_dim)
            v = (h @ wc(lyr["wv"])).reshape(Bb, Tb, local_heads,
                                            cfg.head_dim)
            q = _rope(q.transpose(0, 2, 1, 3), cfg.rope_theta)
            k = _rope(k.transpose(0, 2, 1, 3), cfg.rope_theta)
            v = v.transpose(0, 2, 1, 3)
            if use_ring:
                o = ring_attention(q, k, v, mesh, axis_name="sp",
                                   causal=True, scale=scale)
            else:
                o = blockwise_attention_local(q, k, v, scale, causal=True)
            o = o.transpose(0, 2, 1, 3).reshape(Bb, Tb,
                                                local_heads * cfg.head_dim)
            x = x + red(o @ wc(lyr["wo"]))

            h = _rms_norm(x, lyr["mlp_norm"].astype(dt), cfg.norm_eps)
            if cfg.num_experts:
                from .moe import moe_ffn

                out, aux = moe_ffn(lyr["moe"], h, top_k=cfg.top_k,
                                   compute_dtype=dt,
                                   dispatch=cfg.moe_dispatch,
                                   capacity_factor=cfg.capacity_factor)
                return x + out, aux
            gated = (jax.nn.silu(h @ wc(lyr["w1"]))
                     * (h @ wc(lyr["w3"])))
            return x + red(gated @ wc(lyr["w2"])), jnp.float32(0)

        if cfg.remat:
            # Under scan the body already blocks CSE, so the anti-CSE
            # barriers are pure overhead there.  The flash kernel's
            # custom_vjp composes with checkpoint under both policies.
            if cfg.remat_policy == "dots":
                # Dot outputs PLUS the flash kernel's named (o, lse)
                # residuals (ops/flash_attention.py `_flash_fwd`): with
                # them saved, the backward calls the dq/dkv kernels
                # directly instead of replaying the forward kernel —
                # the recompute tax drops to the cheap tensor ops
                # (norms, rope) for ~one extra o-sized buffer per layer.
                block = jax.checkpoint(
                    block,
                    policy=jax.checkpoint_policies.save_from_both_policies(
                        jax.checkpoint_policies
                        .dots_with_no_batch_dims_saveable,
                        jax.checkpoint_policies.save_only_these_names(
                            "flash_out", "flash_lse", "wcast")),
                    prevent_cse=not cfg.scan_layers)
            elif cfg.remat_policy == "full":
                block = jax.checkpoint(block,
                                       prevent_cse=not cfg.scan_layers)
            else:
                raise ValueError(
                    f"unknown remat_policy '{cfg.remat_policy}' "
                    "(expected 'full' or 'dots')")
        return block

    block = make_block(cfg.n_heads)

    use_pp = (mesh is not None and cfg.pipeline_microbatches > 0
              and int(mesh.shape.get("pp", 1)) > 1)
    if use_pp:
        # GPipe over the layer stack: embed/head stay replicated, the
        # [L, ...] params reshape to [pp, L/pp, ...] stages, microbatches
        # ride the schedule in parallel/pipeline.py.
        from ..parallel.pipeline import gpipe

        if not cfg.scan_layers or cfg.num_experts:
            raise ValueError(
                "pipeline_microbatches requires scan_layers=True and a "
                "dense MLP (num_experts=0)")
        if use_ring:
            # Ring attention's own shard_map cannot nest inside gpipe's.
            raise ValueError(
                "pipeline parallelism composes with dp and tp, not sp "
                "(ring attention inside pipeline stages is unsupported)")
        pp = int(mesh.shape["pp"])
        dp = int(mesh.shape.get("dp", 1))
        tp = int(mesh.shape.get("tp", 1))
        M = cfg.pipeline_microbatches
        if cfg.n_layers % pp or B % (M * dp):
            raise ValueError(
                f"n_layers ({cfg.n_layers}) must divide into pp ({pp}) "
                f"stages and batch ({B}) into {M} microbatches x dp "
                f"({dp}) shards")
        if cfg.n_heads % tp or cfg.hidden % tp or cfg.dim % tp:
            raise ValueError(
                f"pp x tp needs n_heads ({cfg.n_heads}), hidden "
                f"({cfg.hidden}) and dim ({cfg.dim}) divisible by tp "
                f"({tp}) — the stage body shards them manually")
        stages = jax.tree_util.tree_map(
            lambda l: l.reshape(pp, cfg.n_layers // pp, *l.shape[1:]),
            params["layers"])

        if tp > 1:
            # Manual tensor parallelism inside the stage: gpipe's
            # shard_map makes every named axis manual, so the tp layout
            # becomes explicit — column-parallel wq/wk/wv/w1/w3 shards
            # arrive via param_specs, and the block psums the
            # row-parallel wo/w2 outputs over "tp".
            stage_block = make_block(
                cfg.n_heads // tp,
                reduce=lambda t: jax.lax.psum(t, "tp"))
        else:
            stage_block = block

        def stage_fn(stage_params, h):
            def body(h, lyr):
                h, _ = stage_block(h, lyr)
                return h, None

            h, _ = jax.lax.scan(body, h, stage_params)
            return h

        # INTERLEAVED microbatch assignment (row r -> microbatch r % M):
        # each microbatch's rows stay evenly spread over the contiguous
        # dp batch shards, so no cross-device reshard per step — a
        # contiguous split would all-to-all the whole activation tensor.
        xm = x.reshape(B // M, M, T, cfg.dim).swapaxes(0, 1)
        xm = gpipe(stage_fn, stages, xm, mesh, axis_name="pp",
                   batch_axis="dp",
                   param_specs=(_layer_pspecs(cfg, mesh) if tp > 1
                                else None))
        x = xm.swapaxes(0, 1).reshape(B, T, cfg.dim)
        aux_total = jnp.float32(0)
    elif cfg.scan_layers:
        def scan_body(carry, lyr):
            x, aux = carry
            x, a = block(x, lyr)
            return (x, aux + a), None

        (x, aux_total), _ = jax.lax.scan(
            scan_body, (x, jnp.float32(0)), params["layers"])
    else:
        aux_total = jnp.float32(0)
        for lyr in params["layers"]:
            x, a = block(x, lyr)
            aux_total = aux_total + a

    x = _rms_norm(x, params["out_norm"].astype(dt), cfg.norm_eps)
    logits = x @ params["head"].astype(dt)
    if return_aux:
        return logits, aux_total
    return logits


def _ce_value(logits, targets):
    lf = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(lf, axis=-1)
    ll = jnp.take_along_axis(lf, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - ll)


@jax.custom_vjp
def _ce(logits, targets):
    return _ce_value(logits, targets)


def _ce_fwd(logits, targets):
    return _ce_value(logits, targets), (logits, targets)


def _ce_bwd(res, g):
    # dlogits = (softmax − onehot)/N · g, computed in f32 then cast back
    # to the LOGITS' dtype.  Without this vjp the cotangent inherits the
    # f32 of the loss math, and the whole head backward (the two largest
    # matmuls in the model at vocab 32k) runs f32 at half MXU rate; in
    # f32 compute mode the cast is the identity, so fp32 parity checks
    # are untouched.  one_hot lowers to an iota-compare that XLA fuses
    # into the elementwise (p−onehot)·scale pass — a scatter formulation
    # was measured 4% SLOWER end-to-end on v5e.
    logits, targets = res
    B, T, V = logits.shape
    p = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    d = (p - jax.nn.one_hot(targets, V, dtype=jnp.float32)) * (g / (B * T))
    return d.astype(logits.dtype), None


_ce.defvjp(_ce_fwd, _ce_bwd)


def lm_loss(params, tokens, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None):
    """Next-token cross-entropy, mean over all positions (float32).

    MoE configs add ``aux_loss_coef`` × the summed load-balancing loss.

    Two CE lowerings, picked by head size (all v5e-measured): the
    ``_ce`` custom_vjp wins at vocab 32k (+0.9 MFU points on the ~1B
    config — its bf16 dlogits keep the model's two largest matmuls on
    the MXU fast path) and by ~2% at vocab 16k (MoE bench config), but
    LOSES 40% end-to-end on a small head (dim 512 / vocab 8k toy:
    525k → 313k tok/s) because the vjp boundary blocks XLA from fusing
    the CE backward, and those extra HBM passes dwarf the cheap
    matmul's dtype win."""
    logits, aux = transformer_forward(params, tokens, cfg, mesh,
                                      return_aux=True)
    # Crossover measured between 8192 (big loss) and 16384 (small win).
    ce_fn = _ce if cfg.vocab_size >= 16384 else _ce_value
    ce = ce_fn(logits[:, :-1], tokens[:, 1:])
    if cfg.num_experts:
        return ce + cfg.aux_loss_coef * aux
    return ce


class TransformerTrainer:
    """Mesh-parallel LM training through the framework's updaters.

    The parameter pytree is the "table": sharded master weights in float32,
    updated in place by the same Updater the tables use — the reference's
    server-side optimizer semantics at transformer scale.
    """

    def __init__(self, cfg: TransformerConfig, mesh: Mesh,
                 updater_type: str = "sgd",
                 option: Optional[AddOption] = None,
                 seed: int = 0):
        self.cfg = cfg
        self.mesh = mesh
        self.updater = get_updater(updater_type)
        self.option = option or AddOption(learning_rate=0.1)
        shardings = param_shardings(cfg, mesh)
        host = init_params(cfg, seed)
        self.params = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, s), host, shardings,
            is_leaf=lambda x: isinstance(x, np.ndarray))
        self.state = jax.tree_util.tree_map(
            lambda p: tuple(jnp.zeros_like(p)
                            for _ in range(self.updater.num_slots)),
            self.params)
        self._step = None
        self._eval = None
        self._offload = None  # (bridge, leaf shapes/shardings) — see below

    def _apply_updates(self, params, state, grads):
        """One updater application over the whole param pytree."""
        updater, opt = self.updater, self.option
        flat_p, tree = jax.tree_util.tree_flatten(params)
        flat_s = tree.flatten_up_to(state)
        flat_g = tree.flatten_up_to(grads)
        out = [updater.apply_dense(p, s, g, opt)
               for p, s, g in zip(flat_p, flat_s, flat_g)]
        params = jax.tree_util.tree_unflatten(tree, [p for p, _ in out])
        state = jax.tree_util.tree_unflatten(tree, [s for _, s in out])
        return params, state

    def _raw_step(self, accum: int = 1):
        """Un-jitted (params, state, tokens) -> (params, state, loss).

        ``accum > 1`` splits the batch into that many microbatches,
        accumulates their gradients in float32 (a ``lax.scan`` so the
        activation memory is ONE microbatch's), and applies a single
        update — mathematically the full-batch step (the CE is a mean
        over equal-size chunks), with the activation footprint of
        ``batch/accum``.  The trade is an extra f32 grad accumulator of
        one full parameter set riding the scan carry, so the knob pays
        off on ACTIVATION-dominated configs (long context, few params);
        on the ~0.96B bench config the carry (~3.9 GB) was measured to
        eat the whole 16 GB headroom the smaller microbatch freed.  The
        microbatch must still be divisible by the mesh's dp axis.
        MoE configs are rejected: their load-balancing aux loss is a
        product of batch MEANS (nonlinear in the batch) and capacity
        buckets size from N=B·T, so microbatching would silently change
        the training objective, not just its memory profile."""
        cfg, mesh = self.cfg, self.mesh
        if accum > 1 and cfg.num_experts:
            raise ValueError(
                "grad accumulation is not equivalence-preserving for MoE "
                "configs (batch-nonlinear aux loss, capacity buckets "
                "sized from the microbatch); run MoE at full batch")

        def step(params, state, tokens):
            if accum == 1:
                loss, grads = jax.value_and_grad(lm_loss)(params, tokens,
                                                          cfg, mesh)
            else:
                B, T = tokens.shape
                if B % accum:
                    raise ValueError(
                        f"batch {B} not divisible by accum {accum}")
                dp = int(mesh.shape.get("dp", 1)) if mesh is not None else 1
                if (B // accum) % dp:
                    raise ValueError(
                        f"microbatch {B // accum} (batch {B} / accum "
                        f"{accum}) not divisible by the dp axis ({dp})")
                chunks = tokens.reshape(accum, B // accum, T)

                def body(g_acc, chunk):
                    li, gi = jax.value_and_grad(lm_loss)(params, chunk,
                                                         cfg, mesh)
                    g_acc = jax.tree_util.tree_map(
                        lambda a, g: a + g.astype(jnp.float32), g_acc, gi)
                    return g_acc, li

                zeros = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params)
                g_sum, losses = jax.lax.scan(body, zeros, chunks)
                grads = jax.tree_util.tree_map(
                    lambda g: (g / accum), g_sum)
                loss = jnp.mean(losses)
            params, state = self._apply_updates(params, state, grads)
            return params, state, loss

        return step

    def train_steps_fused(self, tokens, n: int) -> jax.Array:
        """Run ``n`` train steps on one batch inside ONE compiled program
        (``fori_loop`` over the step body); returns the last device loss.

        The honest way to measure step time on remote-tunneled devices —
        a per-step dispatch costs ~10 ms through the tunnel, which at
        small step times IS the measurement; one fused program amortizes
        it to nothing.  Also useful for burn-in loops where the batch is
        fixed.
        """
        from ..parallel.sharding import batch_placer
        if self._offload is not None:
            raise RuntimeError(
                "train_steps_fused keeps the state on device across the "
                "whole fused program — incompatible with offload_state "
                "(use train_step_async)")
        fn = getattr(self, "_multi_step", None)
        if fn is None:
            raw = self._raw_step()

            @partial(jax.jit, donate_argnums=(0, 1))
            def multi(params, state, tokens, n):
                def body(_, carry):
                    p, s, _loss = carry
                    return raw(p, s, tokens)

                zero = jnp.float32(0)
                # Dynamic bound: one compile serves every n.
                return jax.lax.fori_loop(0, n, body,
                                         (params, state, zero))

            self._multi_step = fn = multi
        _, place = batch_placer(self.mesh, "dp", dtype=jnp.int32)
        self.params, self.state, loss = fn(self.params, self.state,
                                           place(tokens),
                                           jnp.int32(n))
        return loss

    # ------------------------------------------------------ state offload
    def offload_state(self, bridge) -> None:
        """Move the optimizer state to a remote store (ZeRO-style
        offload over the host bridge, docs/host_bridge.md).

        ``bridge`` is a :class:`~multiverso_tpu.parallel.OffloadedState`
        sized to the flat state element count (``offload_size()``) whose
        backing fleet runs ``-updater_type=assign`` — the bridge is a
        bit-exact store, so the offloaded run's loss trajectory matches
        the in-memory baseline bit for bit (``make bridge-demo``
        asserts exactly that).  After this call, ``train_step_async``
        round-trips the state each step: fetch the prefetched vector,
        rebuild the device pytree, step, push the new state async and
        prefetch the next — the wire rides behind the tail of the
        step's device execution instead of serializing with it.  The
        trade is host<->device traffic of one state set per step for
        state that no longer occupies device memory between steps."""
        leaves = jax.tree_util.tree_leaves(self.state)
        if not leaves:
            raise ValueError(
                f"updater '{self.updater.name}' keeps no optimizer "
                f"state — nothing to offload")
        if bridge.size != self.offload_size():
            raise ValueError(
                f"bridge sized {bridge.size}, state needs "
                f"{self.offload_size()} elements")
        self._offload = bridge
        bridge.init(self._state_to_flat())
        # The device copies now live remotely; drop them so the memory
        # relief is real (rebuilt from the bridge on the next step).
        self.state = jax.tree_util.tree_map(
            lambda p: tuple(None for _ in range(self.updater.num_slots)),
            self.params)
        bridge.prefetch()

    def offload_size(self) -> int:
        """Flat float32 element count of the optimizer state — the
        ``OffloadedState`` size this trainer needs."""
        return int(sum(np.prod(p.shape)
                       for p in jax.tree_util.tree_leaves(self.params))
                   ) * self.updater.num_slots

    def _state_to_flat(self, state=None) -> np.ndarray:
        leaves = jax.tree_util.tree_leaves(
            self.state if state is None else state)
        out = np.empty(self.offload_size(), np.float32)
        pos = 0
        for leaf in leaves:
            n = int(np.prod(leaf.shape))
            np.copyto(out[pos:pos + n],
                      np.asarray(leaf, np.float32).ravel())
            pos += n
        return out

    def _flat_to_state(self, flat: np.ndarray):
        """Rebuild the sharded state pytree from the bridge's vector
        (device_put per leaf with the matching param sharding)."""
        flat_p, tree = jax.tree_util.tree_flatten(self.params)
        pos = 0
        slots_per = self.updater.num_slots
        rebuilt = []
        for p in flat_p:
            n = int(np.prod(p.shape))
            slots = []
            for _ in range(slots_per):
                host = flat[pos:pos + n].reshape(p.shape)
                slots.append(jax.device_put(host, p.sharding))
                pos += n
            rebuilt.append(tuple(slots))
        return jax.tree_util.tree_unflatten(tree, rebuilt)

    def train_step_async(self, tokens, accum: int = 1) -> jax.Array:
        """Enqueue one step; returns the device loss scalar (no host
        sync).  Back-to-back callers (the bench loop) pipeline dispatches
        and fetch once at the end — on remote-tunneled devices a per-step
        host sync costs more than the step itself.

        ``accum`` > 1 runs the gradient-accumulation step (see
        ``_raw_step``): one update from ``accum`` microbatches with a
        single microbatch's activation memory.  Compiled steps are
        cached PER accum value, so interleaving regimes does not
        recompile."""
        if self._step is None:
            self._step = {}
        if accum not in self._step:
            from ..parallel.sharding import batch_placer

            _, place = batch_placer(self.mesh, "dp", dtype=jnp.int32)
            step = jax.jit(self._raw_step(accum), donate_argnums=(0, 1))
            self._step[accum] = (step, place)
        step, place = self._step[accum]
        if self._offload is None:
            self.params, self.state, loss = step(self.params, self.state,
                                                 place(tokens))
            return loss
        # Offloaded state (docs/host_bridge.md): the vector prefetched
        # during the previous step's tail is ready (or fetched now on
        # the first step), rebuilt on device, donated into the step;
        # the new state ships back ASYNC and the next prefetch rides
        # behind it (FIFO) while the caller moves on.
        with dashboard.monitor("Transformer::offload_wait"):
            state = self._flat_to_state(self._offload.wait())
        self.params, new_state, loss = step(self.params, state,
                                            place(tokens))
        with dashboard.monitor("Transformer::offload_push"):
            self._offload.push(self._state_to_flat(new_state))
            self._offload.prefetch()
        del new_state  # device copies die; the remote store owns them
        return loss

    def train_step(self, tokens) -> float:
        with dashboard.monitor("Transformer::train_step"):
            return float(self.train_step_async(tokens))

    def loss(self, tokens) -> float:
        if self._eval is None:
            cfg, mesh = self.cfg, self.mesh
            self._eval = jax.jit(
                lambda p, t: lm_loss(p, t, cfg, mesh))
        return float(self._eval(self.params,
                                jnp.asarray(tokens, jnp.int32)))

    # ------------------------------------------------------------ checkpoint
    def save(self, uri: str) -> None:
        """Snapshot params + updater state (collective; rank-0 atomic
        write — same durability as the table checkpoints).  With the
        state offloaded, it is re-materialized from the bridge first
        (the next step's wait simply pays one blocking fetch)."""
        from .. import checkpoint

        state = self.state
        if self._offload is not None:
            state = self._flat_to_state(self._offload.wait())
        checkpoint.save_pytree(uri, {"params": self.params,
                                     "state": state})

    def restore(self, uri: str) -> None:
        """Load a snapshot onto THIS trainer's mesh/shardings (the
        writing mesh need not match — leaves re-place by the current
        params' shardings)."""
        from .. import checkpoint

        like_state = self.state
        if self._offload is not None:
            # Offloaded runs keep no device state; restore against a
            # zeros-like template, then re-seed the remote store.
            like_state = jax.tree_util.tree_map(
                lambda p: tuple(jnp.zeros_like(p)
                                for _ in range(self.updater.num_slots)),
                self.params)
        snap = checkpoint.restore_pytree(
            uri, like={"params": self.params, "state": like_state})
        self.params = snap["params"]
        if self._offload is not None:
            self._offload.init(self._state_to_flat(snap["state"]))
            self._offload.prefetch()
        else:
            self.state = snap["state"]
