"""Mixture-of-Experts feed-forward with expert parallelism.

Not in the reference (a 2016 parameter server predates MoE); included
because expert parallelism is a first-class layout for this framework.

TPU-first design choices:

- **Dense dispatch**: routing uses a top-k one-hot combine tensor and two
  einsums instead of gather/scatter of token buckets — static shapes, no
  capacity overflow logic, MXU-friendly, and GSPMD partitions it cleanly.
  (At trillion-scale one would move to a Pallas a2a pipeline; dense
  dispatch is the right first rung and exact.)
- **Expert parallelism**: expert-indexed weights [E, ...] carry a
  ``NamedSharding`` over the ``ep`` mesh axis; XLA turns the token-expert
  einsums into all-to-alls over ICI.  Token activations stay sharded over
  ``dp``/``sp`` as in the dense path.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["init_moe_params", "moe_capacity", "moe_ffn", "moe_pspecs",
           "moe_shardings"]


def init_moe_params(dim: int, hidden: int, num_experts: int,
                    seed: int = 0) -> Dict[str, Any]:
    rng = np.random.RandomState(seed)

    def w(*shape, scale):
        return (scale * rng.randn(*shape)).astype(np.float32)

    return {
        "router": w(dim, num_experts, scale=0.02),
        "w1": w(num_experts, dim, hidden, scale=dim ** -0.5),   # gate
        "w3": w(num_experts, dim, hidden, scale=dim ** -0.5),   # up
        "w2": w(num_experts, hidden, dim, scale=hidden ** -0.5),
    }


def moe_pspecs(mesh: Mesh) -> Dict[str, Any]:
    """PartitionSpecs: experts shard over ``ep`` when the mesh has one."""
    ep = "ep" if "ep" in mesh.shape else None
    return {
        "router": P(None, None),
        "w1": P(ep, None, None),
        "w3": P(ep, None, None),
        "w2": P(ep, None, None),
    }


def moe_shardings(mesh: Mesh) -> Dict[str, Any]:
    """Experts shard over ``ep`` when the mesh has one; router replicated."""
    return {k: NamedSharding(mesh, s) for k, s in moe_pspecs(mesh).items()}


def _routing(params, x, top_k: int):
    """Shared router: probs, normalized top-k weights/indices, aux loss.

    Aux is the standard switch/GShard load-balancing term
    (E · Σ_e fraction_e · prob_e), computed on the routing decisions
    (pre-drop, so the capacity path optimizes the same objective).
    """
    E = params["router"].shape[1]
    logits = (x.astype(jnp.float32)
              @ params["router"].astype(jnp.float32))        # [B,T,E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_idx = jax.lax.top_k(probs, top_k)             # [B,T,k]
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    routed = jnp.sum(jax.nn.one_hot(top_idx, E, dtype=jnp.float32), axis=2)
    frac_tokens = jnp.mean((routed > 0).astype(jnp.float32), axis=(0, 1))
    frac_prob = jnp.mean(probs, axis=(0, 1))
    aux = E * jnp.sum(frac_tokens * frac_prob)
    return probs, top_p, top_idx, aux


def moe_capacity(num_tokens: int, num_experts: int, top_k: int,
                 capacity_factor: float) -> int:
    """Static per-expert bucket size (rounded up to the fp32 sublane 8)."""
    c = int(np.ceil(num_tokens * top_k / num_experts * capacity_factor))
    return max(8, -(-c // 8) * 8)


def moe_ffn(params: Dict[str, Any], x: jax.Array, top_k: int = 2,
            compute_dtype=None, dispatch: str = "dense",
            capacity_factor: float = 1.25) -> tuple[jax.Array, jax.Array]:
    """x [B, T, dim] → (out [B, T, dim], aux_loss scalar).

    Two dispatch schedules:

    - ``"dense"`` — every expert computes every token, scaled post-hoc by
      the combine weights.  Exact (no token ever dropped), E/top_k× the
      useful FLOPs; the correctness oracle the capacity path is tested
      against.
    - ``"capacity"`` — GShard-style static buckets: each expert takes at
      most C = ceil(N·top_k/E · capacity_factor) tokens (scatter in,
      batched [E, C, ·] expert FFN on the MXU, gather out).  FLOPs scale
      with top_k·capacity_factor/E instead of 1; tokens overflowing a
      bucket lose that expert's contribution (their other routes and the
      residual still apply).  Static shapes throughout — the capacity is
      a trace-time constant, so this jits/scans/pjits like any dense op.
    """
    if dispatch == "dense":
        return _moe_dense(params, x, top_k, compute_dtype)
    if dispatch == "capacity":
        return _moe_capacity_dispatch(params, x, top_k, compute_dtype,
                                      capacity_factor)
    raise ValueError(f"unknown moe dispatch '{dispatch}' "
                     "(expected dense|capacity)")


def _moe_dense(params, x, top_k, compute_dtype):
    dt = compute_dtype or x.dtype
    E = params["router"].shape[1]
    probs, top_p, top_idx, aux = _routing(params, x, top_k)
    # combine [B,T,E]: routing weight per expert (0 for unrouted)
    combine = jnp.sum(
        jax.nn.one_hot(top_idx, E, dtype=jnp.float32)
        * top_p[..., None], axis=2)

    # dense dispatch: every expert sees every token, scaled post-hoc.
    xc = x.astype(dt)
    gate = jax.nn.silu(jnp.einsum("btd,edh->beth", xc,
                                  params["w1"].astype(dt)))
    up = jnp.einsum("btd,edh->beth", xc, params["w3"].astype(dt))
    expert_out = jnp.einsum("beth,ehd->betd", gate * up,
                            params["w2"].astype(dt))          # [B,E,T,d]
    out = jnp.einsum("betd,bte->btd", expert_out,
                     combine.astype(dt))
    return out.astype(x.dtype), aux


def _moe_capacity_dispatch(params, x, top_k, compute_dtype,
                           capacity_factor):
    dt = compute_dtype or x.dtype
    B, T, D = x.shape
    N = B * T
    E = params["router"].shape[1]
    _, top_p, top_idx, aux = _routing(params, x, top_k)
    C = moe_capacity(N, E, top_k, capacity_factor)

    # Slot assignment, token-major (earlier tokens win bucket slots, the
    # reference-free standard tie-break).  [N·k] flat routes.
    e_flat = top_idx.reshape(-1)                       # [N*k]
    onehot = jax.nn.one_hot(e_flat, E, dtype=jnp.int32)
    pos = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot, axis=1) - 1
    valid = pos < C                                    # dropped = overflow
    slot = jnp.where(valid, e_flat * C + jnp.minimum(pos, C - 1), E * C)

    # Scatter tokens into [E·C (+1 overflow row), D] buckets.
    x_rep = jnp.repeat(x.reshape(N, D), top_k, axis=0).astype(dt)
    buckets = jnp.zeros((E * C + 1, D), dt).at[slot].add(
        x_rep * valid[:, None].astype(dt))
    xe = buckets[:E * C].reshape(E, C, D)

    # Batched expert FFN — one [E, C, ·] einsum chain on the MXU.
    gate = jax.nn.silu(jnp.einsum("ecd,edh->ech", xe,
                                  params["w1"].astype(dt)))
    up = jnp.einsum("ecd,edh->ech", xe, params["w3"].astype(dt))
    ye = jnp.einsum("ech,ehd->ecd", gate * up,
                    params["w2"].astype(dt)).reshape(E * C, D)

    # Gather back, weight, and sum each token's surviving routes.
    w = (top_p.reshape(-1) * valid.astype(jnp.float32)).astype(dt)
    y_tok = ye[jnp.minimum(slot, E * C - 1)] * w[:, None]
    out = jnp.sum(y_tok.reshape(N, top_k, D), axis=1).reshape(B, T, D)
    return out.astype(x.dtype), aux
