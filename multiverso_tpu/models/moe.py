"""Mixture-of-Experts feed-forward with expert parallelism.

Not in the reference (a 2016 parameter server predates MoE); included
because expert parallelism is a first-class layout for this framework.

TPU-first design choices:

- **Dense dispatch**: routing uses a top-k one-hot combine tensor and two
  einsums instead of gather/scatter of token buckets — static shapes, no
  capacity overflow logic, MXU-friendly, and GSPMD partitions it cleanly.
  (At trillion-scale one would move to a Pallas a2a pipeline; dense
  dispatch is the right first rung and exact.)
- **Expert parallelism**: expert-indexed weights [E, ...] carry a
  ``NamedSharding`` over the ``ep`` mesh axis; XLA turns the token-expert
  einsums into all-to-alls over ICI.  Token activations stay sharded over
  ``dp``/``sp`` as in the dense path.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["init_moe_params", "moe_ffn", "moe_pspecs", "moe_shardings"]


def init_moe_params(dim: int, hidden: int, num_experts: int,
                    seed: int = 0) -> Dict[str, Any]:
    rng = np.random.RandomState(seed)

    def w(*shape, scale):
        return (scale * rng.randn(*shape)).astype(np.float32)

    return {
        "router": w(dim, num_experts, scale=0.02),
        "w1": w(num_experts, dim, hidden, scale=dim ** -0.5),   # gate
        "w3": w(num_experts, dim, hidden, scale=dim ** -0.5),   # up
        "w2": w(num_experts, hidden, dim, scale=hidden ** -0.5),
    }


def moe_pspecs(mesh: Mesh) -> Dict[str, Any]:
    """PartitionSpecs: experts shard over ``ep`` when the mesh has one."""
    ep = "ep" if "ep" in mesh.shape else None
    return {
        "router": P(None, None),
        "w1": P(ep, None, None),
        "w3": P(ep, None, None),
        "w2": P(ep, None, None),
    }


def moe_shardings(mesh: Mesh) -> Dict[str, Any]:
    """Experts shard over ``ep`` when the mesh has one; router replicated."""
    return {k: NamedSharding(mesh, s) for k, s in moe_pspecs(mesh).items()}


def moe_ffn(params: Dict[str, Any], x: jax.Array, top_k: int = 2,
            compute_dtype=None) -> tuple[jax.Array, jax.Array]:
    """x [B, T, dim] → (out [B, T, dim], aux_loss scalar).

    Top-k softmax routing with a load-balancing auxiliary loss (the
    standard switch/GShard formulation: E · Σ_e fraction_e · prob_e).
    """
    dt = compute_dtype or x.dtype
    E = params["router"].shape[1]
    logits = (x.astype(jnp.float32)
              @ params["router"].astype(jnp.float32))        # [B,T,E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_idx = jax.lax.top_k(probs, top_k)             # [B,T,k]
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    # combine [B,T,E]: routing weight per expert (0 for unrouted)
    combine = jnp.sum(
        jax.nn.one_hot(top_idx, E, dtype=jnp.float32)
        * top_p[..., None], axis=2)

    # load-balancing aux loss
    frac_tokens = jnp.mean((combine > 0).astype(jnp.float32), axis=(0, 1))
    frac_prob = jnp.mean(probs, axis=(0, 1))
    aux = E * jnp.sum(frac_tokens * frac_prob)

    # dense dispatch: every expert sees every token, scaled post-hoc.
    xc = x.astype(dt)
    gate = jax.nn.silu(jnp.einsum("btd,edh->beth", xc,
                                  params["w1"].astype(dt)))
    up = jnp.einsum("btd,edh->beth", xc, params["w3"].astype(dt))
    expert_out = jnp.einsum("beth,ehd->betd", gate * up,
                            params["w2"].astype(dt))          # [B,E,T,d]
    out = jnp.einsum("betd,bte->btd", expert_out,
                     combine.astype(dt))
    return out.astype(x.dtype), aux
