"""Checkpoint / resume — reference ``ServerTable::Store/Load`` over Streams
(SURVEY.md §5 "Checkpoint / resume", §2.27).

The reference periodically dumps each server table shard through a Stream
and reloads it on restart.  Here a checkpoint is one atomic snapshot of
every registered table (weights + updater state, pulled from device), the
runtime clock, and optional app extras — written through the ``io`` Stream
seam so local/remote backends interchange.

Resume follows the reference's shape: the app re-creates its tables (same
kinds/shapes, same order), then ``restore()`` loads state back into them by
table name.  Multi-host: only process 0 writes; everyone barriers after.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, Optional

from .core import context as core_context
from .io import StreamFactory
from .log import Log

__all__ = ["save", "restore"]

_MAGIC = b"MVTPUCKPT1"


def save(uri: str, extra: Optional[Dict[str, Any]] = None) -> None:
    """Snapshot all registered tables + clock to ``uri`` (one file).

    Multi-host: ``store_state`` is collective (tables sharded across
    hosts gather via ``process_allgather`` in ``tables.base.host_fetch``),
    so EVERY process materializes the snapshot; only rank 0 writes it.
    The local write goes to a temp file and renames into place, so a
    crash mid-write never leaves a truncated file at the final path.
    """
    ctx = core_context.get_context()
    # Collective on multi-host meshes — all ranks must run it together.
    tables_snap = {t.name: t.store_state() for t in ctx.tables()}
    if ctx.node.rank == 0:
        snap = {
            "clock": ctx.clock,
            "extra": extra or {},
            "tables": tables_snap,
        }
        with StreamFactory.open(uri, "wb", atomic=True) as s:
            s.write(_MAGIC)
            s.write(pickle.dumps(snap, protocol=4))
        Log.info("checkpoint saved: %s (%d tables, clock=%d)",
                 uri, len(snap["tables"]), ctx.clock)
    ctx.host_sync("mvtpu_checkpoint_save")


def restore(uri: str, strict: bool = True) -> Dict[str, Any]:
    """Load a snapshot into the currently registered tables (matched by
    name).  Returns the ``extra`` dict stored at save time.

    ``strict=True`` raises if any registered table has no snapshot entry or
    vice versa (the reference's Load aborts on shard mismatch).

    Trust boundary: the snapshot body is a pickle — restoring a
    checkpoint executes code chosen by whoever wrote the file.  Only
    restore checkpoints from storage you control, exactly as you would
    only load model weights you trust.

    Multi-host: every process reads ``uri`` (the reference's HDFS model —
    checkpoint storage is shared); rank-0-only distribution of the bytes
    would need a broadcast seam here.
    """
    ctx = core_context.get_context()
    with StreamFactory.open(uri, "rb") as s:
        magic = s.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError(f"{uri}: not a multiverso_tpu checkpoint")
        snap = pickle.loads(s.read())

    tables = {t.name: t for t in ctx.tables()}
    missing = set(tables) - set(snap["tables"])
    orphaned = set(snap["tables"]) - set(tables)
    if strict and (missing or orphaned):
        raise ValueError(
            f"checkpoint/table mismatch: tables without snapshot entries "
            f"{sorted(missing)}; snapshot entries without tables "
            f"{sorted(orphaned)} (re-create tables before restore, or pass "
            f"strict=False)")
    for name in set(tables) & set(snap["tables"]):
        t = tables[name]
        # Stale pre-restore BSP buffers must not apply on top of restored
        # weights at the next barrier.
        t.discard_pending()
        t.load_state(snap["tables"][name])
    ctx.clock = int(snap["clock"])
    ctx.host_sync("mvtpu_checkpoint_restore")
    Log.info("checkpoint restored: %s (%d tables, clock=%d)",
             uri, len(snap["tables"]), ctx.clock)
    return snap["extra"]
