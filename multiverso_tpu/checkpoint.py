"""Checkpoint / resume — reference ``ServerTable::Store/Load`` over Streams
(SURVEY.md §5 "Checkpoint / resume", §2.27).

The reference periodically dumps each server table shard through a Stream
and reloads it on restart.  Here a checkpoint is one atomic snapshot of
every registered table (weights + updater state, pulled from device), the
runtime clock, and optional app extras — written through the ``io`` Stream
seam so local/remote backends interchange.

Resume follows the reference's shape: the app re-creates its tables (same
kinds/shapes, same order), then ``restore()`` loads state back into them by
table name.  Multi-host: only process 0 writes; everyone barriers after.
"""

from __future__ import annotations

import json
import os
import pickle
import struct
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

from .core import context as core_context
from .fault import RetryPolicy
from .io import StreamFactory
from .log import Log

__all__ = ["save", "restore", "save_pytree", "restore_pytree",
           "save_pytree_async", "AsyncSave", "CheckpointCorrupt",
           "CheckpointManager"]

# v2 framing: magic + <uint64 body_len, uint32 crc32> + pickle body.
# The CRC turns "killed mid-write" / "bit-rotted storage" into a
# CheckpointCorrupt at restore time instead of a pickle crash (or,
# worse, silently-wrong weights).  v1 files (magic + bare pickle) are
# still readable — only without the integrity check.
_MAGIC = b"MVTPUCKPT2"
_MAGIC_TREE = b"MVTPUTREE2"
_MAGIC_V1 = b"MVTPUCKPT1"
_MAGIC_TREE_V1 = b"MVTPUTREE1"
_HEADER = struct.Struct("<QI")

# Transient-IO retry for every snapshot read/write (docs/
# fault_tolerance.md).  Module attribute so deployments (and the chaos
# suite) can swap the schedule.
IO_RETRY = RetryPolicy(attempts=3, backoff_s=0.05, retry_on=(OSError,))


class CheckpointCorrupt(ValueError):
    """The snapshot file is damaged (truncated, bit-flipped, or not a
    checkpoint at all) — restore refuses to unpickle garbage.  Catchable
    separately so callers (``CheckpointManager.restore_latest``) can
    fall back to the previous good snapshot.

    Constructing one is a flight-recorder trigger
    (docs/observability.md): even when ``restore_latest`` tolerates the
    corruption by falling back, the black box records that a snapshot
    rotted — silent corruption is exactly what a post-mortem needs."""

    def __init__(self, *args: Any) -> None:
        super().__init__(*args)
        try:
            from .ops.flight_recorder import recorder

            recorder.trigger(f"checkpoint_corrupt: "
                             f"{args[0] if args else ''}")
        except Exception:  # the trigger must never mask the corruption
            pass


def _write_snapshot(uri: str, magic: bytes, obj: Any) -> None:
    """THE one framing for every checkpoint file: magic + CRC32-framed
    pickle body, written through an atomic Stream (temp + rename),
    retried on transient IO errors."""
    body = pickle.dumps(obj, protocol=4)
    header = _HEADER.pack(len(body), zlib.crc32(body))

    def write() -> None:
        with StreamFactory.open(uri, "wb", atomic=True) as s:
            s.write(magic)
            s.write(header)
            s.write(body)

    IO_RETRY.run(write)


def _read_snapshot(uri: str, magic: bytes, what: str) -> Any:
    def read() -> bytes:
        with StreamFactory.open(uri, "rb") as s:
            return s.read()

    raw = IO_RETRY.run(read)
    legacy = _MAGIC_V1 if magic == _MAGIC else _MAGIC_TREE_V1
    if raw.startswith(magic):
        off = len(magic)
        if len(raw) < off + _HEADER.size:
            raise CheckpointCorrupt(
                f"{uri}: truncated {what} (header incomplete)")
        body_len, crc = _HEADER.unpack_from(raw, off)
        body = raw[off + _HEADER.size:off + _HEADER.size + body_len]
        if len(body) != body_len:
            raise CheckpointCorrupt(
                f"{uri}: truncated {what} ({len(body)} of {body_len} "
                f"body bytes — killed mid-write?)")
        if zlib.crc32(body) != crc:
            raise CheckpointCorrupt(
                f"{uri}: CRC mismatch in {what} body — storage "
                f"corruption; restore from an earlier snapshot")
    elif raw.startswith(legacy):
        body = raw[len(legacy):]  # pre-CRC file: no integrity check
    else:
        raise CheckpointCorrupt(f"{uri}: not a multiverso_tpu {what}")
    try:
        return pickle.loads(body)
    except Exception as exc:
        raise CheckpointCorrupt(
            f"{uri}: {what} body does not unpickle ({exc}) — corrupt "
            f"file") from exc


def save_pytree(uri: str, tree: Any) -> None:
    """Snapshot an arbitrary pytree of arrays (model params, optimizer
    state — anything that is NOT a registered table) to ``uri``.

    Same write discipline as :func:`save`: device arrays materialize to
    host (collectively under multi-host), rank 0 writes atomically,
    every rank syncs before returning.  Used by
    ``TransformerTrainer.save`` — the flagship model's params live in a
    sharded pytree, not a table, but deserve the same durability.
    """
    import jax

    from .tables.base import host_fetch

    ctx = core_context.get_context()
    # Only device arrays materialize; other leaves (scalars, strings,
    # configs) pickle natively and round-trip with their own types.
    host_tree = jax.tree_util.tree_map(
        lambda a: host_fetch(a) if isinstance(a, jax.Array) else a, tree)
    if ctx.node.rank == 0:
        _write_snapshot(uri, _MAGIC_TREE, host_tree)
        Log.info("pytree checkpoint saved: %s", uri)
    ctx.host_sync("mvtpu_pytree_save")


def restore_pytree(uri: str, like: Any = None) -> Any:
    """Load a pytree snapshot.  With ``like`` (a pytree of placed
    ``jax.Array`` leaves), each loaded leaf is ``device_put`` with the
    matching leaf's sharding — restoring a trainer onto any mesh.

    Multi-host: ``save_pytree`` writes on rank 0 only, but EVERY rank
    reads ``uri`` here — the path must resolve on all hosts (shared
    filesystem, or pre-distributed copies), the same broadcast seam
    :func:`restore` documents.

    Trust boundary: pickle body — restore only checkpoints you control
    (same caveat as :func:`restore`).
    """
    import numpy as np

    ctx = core_context.get_context()
    host_tree = _read_snapshot(uri, _MAGIC_TREE, "pytree snapshot")
    ctx.host_sync("mvtpu_pytree_restore")
    if like is None:
        return host_tree
    import jax

    from .tables.base import host_put

    class _LeafMismatch(ValueError):
        pass

    def place(path, h, ref):
        if not isinstance(ref, jax.Array):
            return h
        h = np.asarray(h)
        if h.shape != ref.shape or h.dtype != ref.dtype:
            raise _LeafMismatch(
                f"snapshot leaf {jax.tree_util.keystr(path)} is "
                f"{h.shape}/{h.dtype} but the live tree expects "
                f"{ref.shape}/{ref.dtype} — wrong config/updater for "
                f"this checkpoint?")
        return host_put(h, ref.sharding)

    try:
        return jax.tree_util.tree_map_with_path(place, host_tree, like)
    except _LeafMismatch:
        raise
    except Exception as exc:
        raise ValueError(
            f"{uri}: snapshot tree structure does not match the live "
            f"tree (different model config or updater?): {exc}") from exc


_STATUS_OK, _STATUS_ERR, _STATUS_PENDING = 0, 1, 2


def _exchange_status(status: int) -> int:
    """All-ranks agreement on the async writer's status — a collective
    (every rank's ``AsyncSave.result()`` calls it).  Rank 0 is the only
    writer, so its status is the one broadcast."""
    import jax

    if jax.process_count() == 1:
        return status
    import numpy as np
    from jax.experimental import multihost_utils

    return int(multihost_utils.broadcast_one_to_all(np.asarray(status)))


class AsyncSave:
    """Handle for an in-flight :func:`save_pytree_async` write.

    ``result()`` joins the writer thread, re-raises any IO error, and
    host-syncs every rank — after it returns on all ranks the file is
    durable and safe to restore.  Dropping the handle without calling
    ``result()`` leaves a daemon thread that may still be writing at
    interpreter exit (the atomic temp+rename means a killed write never
    leaves a truncated file at the final path, just no file)."""

    def __init__(self, uri: str, thread: Optional[threading.Thread]):
        self._uri = uri
        self._thread = thread
        self._err: Optional[BaseException] = None

    def done(self) -> bool:
        return self._thread is None or not self._thread.is_alive()

    def result(self, timeout: Optional[float] = None) -> None:
        # Exchange the writer status across hosts BEFORE raising: if
        # rank 0 raised its IO error (or join timeout) here while the
        # other ranks went straight into the rendezvous below, they
        # would block in the barrier forever.  The broadcast is itself
        # a collective, so after it every rank takes the SAME exit:
        # return (file durable), raise the IO error, or raise
        # TimeoutError (write still in flight on rank 0 — the thread
        # keeps running; call result() again to re-join it).  Non-zero
        # ranks have no writer thread; they learn all three outcomes
        # from the broadcast.
        status = _STATUS_OK
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                status = _STATUS_PENDING
            elif self._err is not None:
                status = _STATUS_ERR
        status = _exchange_status(status)
        if status == _STATUS_PENDING:
            raise TimeoutError(
                f"checkpoint write still in flight: {self._uri}")
        if status == _STATUS_ERR:
            if self._err is not None:
                raise self._err
            raise IOError(
                "checkpoint write failed on rank 0 (see its log): "
                f"{self._uri}")
        # Same durability contract as the sync save: every rank agrees
        # the file exists before anyone restores it.
        core_context.get_context().host_sync("mvtpu_pytree_async_save")


def save_pytree_async(uri: str, tree: Any) -> AsyncSave:
    """:func:`save_pytree` with the slow half off the critical path.

    The device→host fetch runs synchronously at the call point — it is
    the collective, consistency-critical part (the snapshot is of the
    params AS OF this call, and multi-host gathers need every rank) —
    then rank 0's pickle + stream write happens on a background thread
    while training continues.  For the ~seconds a multi-GB write takes,
    the train loop only pays the D2H copy.  Call ``result()`` on the
    returned handle (every rank) before restoring or shutting down.
    """
    import jax

    from .tables.base import host_fetch

    ctx = core_context.get_context()
    host_tree = jax.tree_util.tree_map(
        lambda a: host_fetch(a) if isinstance(a, jax.Array) else a, tree)
    if ctx.node.rank != 0:
        return AsyncSave(uri, None)

    handle = AsyncSave(uri, None)

    def write():
        try:
            _write_snapshot(uri, _MAGIC_TREE, host_tree)
            Log.info("pytree checkpoint saved (async): %s", uri)
        except BaseException as exc:  # surfaced by result()
            handle._err = exc

    t = threading.Thread(target=write, name="mvtpu-ckpt-write", daemon=True)
    handle._thread = t
    t.start()
    return handle


def save(uri: str, extra: Optional[Dict[str, Any]] = None) -> None:
    """Snapshot all registered tables + clock to ``uri`` (one file).

    Multi-host: ``store_state`` is collective (tables sharded across
    hosts gather via ``process_allgather`` in ``tables.base.host_fetch``),
    so EVERY process materializes the snapshot; only rank 0 writes it.
    The local write goes to a temp file and renames into place, so a
    crash mid-write never leaves a truncated file at the final path.
    """
    ctx = core_context.get_context()
    # Collective on multi-host meshes — all ranks must run it together.
    tables_snap = {t.name: t.store_state() for t in ctx.tables()}
    if ctx.node.rank == 0:
        snap = {
            "clock": ctx.clock,
            "extra": extra or {},
            "tables": tables_snap,
        }
        _write_snapshot(uri, _MAGIC, snap)
        Log.info("checkpoint saved: %s (%d tables, clock=%d)",
                 uri, len(snap["tables"]), ctx.clock)
    ctx.host_sync("mvtpu_checkpoint_save")


def restore(uri: str, strict: bool = True) -> Dict[str, Any]:
    """Load a snapshot into the currently registered tables (matched by
    name).  Returns the ``extra`` dict stored at save time.

    ``strict=True`` raises if any registered table has no snapshot entry or
    vice versa (the reference's Load aborts on shard mismatch).

    Trust boundary: the snapshot body is a pickle — restoring a
    checkpoint executes code chosen by whoever wrote the file.  Only
    restore checkpoints from storage you control, exactly as you would
    only load model weights you trust.

    Multi-host: every process reads ``uri`` (the reference's HDFS model —
    checkpoint storage is shared); rank-0-only distribution of the bytes
    would need a broadcast seam here.
    """
    ctx = core_context.get_context()
    snap = _read_snapshot(uri, _MAGIC, "checkpoint")

    tables = {t.name: t for t in ctx.tables()}
    missing = set(tables) - set(snap["tables"])
    orphaned = set(snap["tables"]) - set(tables)
    if strict and (missing or orphaned):
        raise ValueError(
            f"checkpoint/table mismatch: tables without snapshot entries "
            f"{sorted(missing)}; snapshot entries without tables "
            f"{sorted(orphaned)} (re-create tables before restore, or pass "
            f"strict=False)")
    for name in set(tables) & set(snap["tables"]):
        t = tables[name]
        # Stale pre-restore BSP buffers must not apply on top of restored
        # weights at the next barrier.
        t.discard_pending()
        t.load_state(snap["tables"][name])
    ctx.clock = int(snap["clock"])
    ctx.host_sync("mvtpu_checkpoint_restore")
    Log.info("checkpoint restored: %s (%d tables, clock=%d)",
             uri, len(snap["tables"]), ctx.clock)
    return snap["extra"]


class CheckpointManager:
    """Rolling snapshots behind an atomic MANIFEST — crash-safe resume.

    ``save_step(step)`` writes one :func:`save` snapshot per call into
    ``directory``, records it in ``MANIFEST.json`` (written atomically,
    AFTER the snapshot is durable), and prunes beyond ``keep`` — so the
    directory always holds N known-good restore points and a torn write
    can never be the only copy.  ``restore_latest()`` walks the manifest
    newest-first and FALLS BACK past corrupt/missing snapshots
    (:class:`CheckpointCorrupt` per file is logged, not fatal) to the
    last good one — a job killed mid-write resumes from the previous
    step instead of dying on a half-written file.

    Multi-host: rank 0 owns the manifest and pruning; :func:`save` /
    :func:`restore` carry their own collectives and fences.
    """

    MANIFEST = "MANIFEST.json"

    def __init__(self, directory: str, keep: Optional[int] = None,
                 prefix: str = "step"):
        from . import config

        self.directory = directory
        self.keep = int(config.get("ckpt_keep")) if keep is None else keep
        if self.keep < 1:
            raise ValueError(f"keep must be >= 1, got {self.keep}")
        self.prefix = prefix
        os.makedirs(directory, exist_ok=True)

    # -- manifest ----------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self.directory, self.MANIFEST)

    def _entries(self) -> List[Dict[str, Any]]:
        """Manifest entries, oldest first.  A damaged/absent manifest is
        rebuilt from the snapshot files on disk (the manifest is an
        index, never the only source of truth)."""
        try:
            with StreamFactory.open(self._manifest_path(), "rb") as s:
                entries = json.loads(s.read().decode("utf-8"))
            if isinstance(entries, list):
                return entries
        except (OSError, ValueError):
            pass
        entries = []
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return entries
        for name in names:
            if name.startswith(f"{self.prefix}_") and name.endswith(".ckpt"):
                try:
                    step = int(name[len(self.prefix) + 1:-len(".ckpt")])
                except ValueError:
                    continue
                entries.append({"step": step, "file": name})
        entries.sort(key=lambda e: e["step"])
        return entries

    def _write_manifest(self, entries: List[Dict[str, Any]]) -> None:
        def write() -> None:
            with StreamFactory.open(self._manifest_path(), "wb",
                                    atomic=True) as s:
                s.write(json.dumps(entries).encode("utf-8"))

        IO_RETRY.run(write)

    def steps(self) -> List[int]:
        return [int(e["step"]) for e in self._entries()]

    def _uri(self, name: str) -> str:
        return os.path.join(self.directory, name)

    # -- save / restore ----------------------------------------------------
    def save_step(self, step: int,
                  extra: Optional[Dict[str, Any]] = None) -> str:
        """Snapshot all tables as snapshot ``step``; returns its path."""
        ctx = core_context.get_context()
        name = f"{self.prefix}_{step:010d}.ckpt"
        uri = self._uri(name)
        merged = dict(extra or {})
        merged["__step__"] = step
        save(uri, extra=merged)  # collective; durable after this returns
        if ctx.node.rank == 0:
            entries = [e for e in self._entries() if e["file"] != name]
            entries.append({"step": step, "file": name})
            entries.sort(key=lambda e: e["step"])
            pruned, entries = entries[:-self.keep], entries[-self.keep:]
            # Manifest first (atomic rename): from this instant the new
            # snapshot is the restore point; only THEN drop old files.
            self._write_manifest(entries)
            for e in pruned:
                try:
                    os.unlink(self._uri(e["file"]))
                except OSError:
                    pass  # e.g. non-local scheme; stale files are benign
        ctx.host_sync("mvtpu_ckpt_manager_save")
        return uri

    def restore_latest(self, strict: bool = True) -> Tuple[int, Dict[str, Any]]:
        """Restore the newest GOOD snapshot; returns ``(step, extra)``.

        Corrupt or missing snapshots are skipped (with an error log) in
        favor of the previous entry; raises :class:`CheckpointCorrupt`
        only when no snapshot in the manifest restores.
        """
        entries = self._entries()
        for e in reversed(entries):
            uri = self._uri(e["file"])
            try:
                extra = restore(uri, strict=strict)
            except (CheckpointCorrupt, OSError) as exc:
                Log.error("CheckpointManager: snapshot %s unusable (%s); "
                          "falling back to the previous one", uri, exc)
                continue
            step = int(extra.pop("__step__", e["step"]))
            Log.info("CheckpointManager: resumed from step %d (%s)",
                     step, uri)
            return step, extra
        raise CheckpointCorrupt(
            f"{self.directory}: no restorable snapshot among "
            f"{[e['file'] for e in entries]}")
