"""Fleet holder for the health-plane demo (``make doctor-demo``).

Run as ``python doctor_demo_worker.py <machine_file> <rank>``: two of
these form a 2-rank native epoll fleet with wire timing, heartbeats,
the native stall watchdog armed and the PYTHON health plane armed (a
demo-tightened latency burn-rate rule riding the default windows down
so the closed loop is watchable in seconds, not minutes), then print
``DOC_READY`` and serve stdin commands:

- ``probe``  — native cross-rank gets (feeds the peer-visible stage
  histograms) plus timed ANONYMOUS probes against the PEER's serve
  port (feeds this rank's ``lat.total`` / ``lat.slo.*`` error-budget
  counters — the series the burn-rate rule watches); print
  ``DOC_PROBE_DONE``.
- ``fault``  — arm a 100% 25 ms ``apply_delay`` fault on THIS rank's
  server apply path; print ``DOC_FAULT_ARMED``.
- ``clear``  — clear faults; print ``DOC_CLEARED``.
- ``alerts`` — print this rank's alert doc as one line
  (``DOC_ALERTS <json>``) for the driver's asserts.
- ``quit``   — disarm, shut down, print ``DOC_OK <rank>``.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np  # noqa: E402

from multiverso_tpu import config, health, latency, metrics  # noqa: E402
from multiverso_tpu import native as nat  # noqa: E402
from multiverso_tpu.serve import wire  # noqa: E402

SIZE = 256
FLUSH_MS = 250
# 10 ms SLO vs a 25 ms injected apply delay: every faulted probe is a
# breach, so the burn rate saturates within one flush of traffic.
SLO_MS = 10.0


def demo_rules():
    """The default pack's latency burn rule with demo-scale windows:
    8 s long / 2 s short so the alert both fires within two flushes of
    faulted traffic AND resolves within seconds of the fault clearing
    (the production windows are 300 s / 30 s)."""
    return [health.Rule(
        name="lat-slo-burn", metric="lat.slo.breach", op="burn_rate_gt",
        threshold=2.0, total_metric="lat.slo.total", objective=0.99,
        window_s=8.0, short_window_s=4.0, for_s=0.0,
        severity="critical")]


def main() -> int:
    mf, rank = sys.argv[1], int(sys.argv[2])
    with open(mf) as f:
        eps = [ln.strip() for ln in f if ln.strip()]
    peer = eps[1 - rank]
    rt = nat.NativeRuntime(args=[
        f"-machine_file={mf}", f"-rank={rank}", "-log_level=error",
        "-heartbeat_ms=100", "-heartbeat_timeout_ms=5000",
        "-watchdog_stall_ms=2000",
        "-rpc_timeout_ms=30000", "-barrier_timeout_ms=60000"])
    assert rt.net_engine() == "epoll", rt.net_engine()
    h = rt.new_array_table(SIZE)
    rt.barrier()

    config.set_flag("health_latency_slo_ms", SLO_MS)
    metrics.reset()
    metrics.start_flush(FLUSH_MS)
    health.arm(rules=demo_rules(), runtime=rt)
    print("DOC_READY", flush=True)

    for line in sys.stdin:
        cmd = line.strip()
        if cmd == "probe":
            for _ in range(5):
                rt.array_add(h, np.full(SIZE, 0.5, np.float32))
                rt.array_get(h, SIZE)
            client = latency.attach_metrics(
                wire.AnonServeClient(peer, timeout=15, timing=True))
            for _ in range(10):
                client.get_shard(h)
            client.close()
            print("DOC_PROBE_DONE", flush=True)
        elif cmd == "fault":
            rt.set_fault("delay_ms", 25)
            rt.set_fault("apply_delay", 1.0)
            print("DOC_FAULT_ARMED", flush=True)
        elif cmd == "clear":
            rt.clear_faults()
            print("DOC_CLEARED", flush=True)
        elif cmd == "alerts":
            print("DOC_ALERTS " + json.dumps(health.alerts_doc()),
                  flush=True)
        elif cmd == "quit":
            break
    rt.clear_faults()
    rt.barrier()
    health.disarm(rt)
    metrics.stop_flush()
    rt.shutdown()
    print(f"DOC_OK {rank}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
