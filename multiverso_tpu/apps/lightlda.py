"""LightLDA-style distributed topic model (collapsed Gibbs LDA).

Reference (SURVEY.md §2.36, ``Microsoft/LightLDA`` linking libmultiverso):
the word-topic count matrix lives in a SparseMatrixTable (V x K) and the
topic totals in an ArrayTable (K); workers sweep their document shard,
resample token topics, and push count *deltas* with async ``Add`` (plain
add updater) — the AD-LDA scheme where workers sample against slightly
stale counts and reconcile through the server.

TPU-native: the same AD-LDA math, two execution paths:

- ``sample_pass`` — parity path: pull touched word rows + topic totals,
  resample on host, push sparse count deltas (async Add).
- ``make_fused_pass`` — one XLA program per document batch: gather word
  rows, compute the collapsed-Gibbs posterior for every token *in
  parallel* (blocked/AD-LDA approximation — token updates within a batch
  see start-of-batch counts, exactly like workers see stale server state),
  sample with ``jax.random.categorical``, scatter count deltas back.
  Static shapes via padded [docs, max_len] token matrices.  O(K) work and
  memory per token — fine for K up to a few hundred.
- ``make_mh_pass`` — the actual LightLDA algorithm (WWW'15): factorized
  cycle proposals + Metropolis-Hastings, with per-token cost independent
  of K.  The word proposal q_w(k) ∝ (n_kw+β)/(n_k+Vβ) is drawn by
  inverse-CDF binary search — a row-wise ``cumsum`` build is one fused
  parallel op where the reference's Vose alias construction is inherently
  sequential, and the per-draw cost is O(log K) *element* gathers, the
  TPU-native trade for the alias table's O(1).  The doc proposal
  q_d(k) ∝ (n_kd+α) uses LightLDA's token trick (no table at all).
  Acceptance ratios are O(1) element gathers.  Proposal tables are built
  from sweep-start counts and corrected through the acceptance term,
  exactly the staleness the reference's amortized alias tables have.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import context as core_context
from ..tables import ArrayTable, SparseMatrixTable

__all__ = ["LightLDA", "synthetic_documents"]

PAD = -1  # padding token id in [docs, max_len] matrices


def synthetic_documents(num_docs: int, vocab_size: int, num_topics: int,
                        doc_len: int = 64, seed: int = 0,
                        concentration: float = 0.1
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Documents with planted topic structure; returns (docs, true_topics).

    Each topic owns a contiguous slice of the vocabulary; each doc mixes
    1-2 topics.  ``docs`` is int32 [num_docs, doc_len] (PAD-free here).
    """
    rng = np.random.RandomState(seed)
    words_per_topic = vocab_size // num_topics
    docs = np.zeros((num_docs, doc_len), np.int32)
    true_topics = rng.randint(num_topics, size=num_docs)
    for d in range(num_docs):
        k = true_topics[d]
        own = rng.rand(doc_len) > concentration
        topic_words = (k * words_per_topic
                       + rng.randint(words_per_topic, size=doc_len))
        noise_words = rng.randint(vocab_size, size=doc_len)
        docs[d] = np.where(own, topic_words, noise_words)
    return docs, true_topics


class LightLDA:
    """AD-LDA over a SparseMatrixTable (word-topic) + ArrayTable (totals)."""

    def __init__(self, vocab_size: int, num_topics: int,
                 alpha: float = 0.1, beta: float = 0.01,
                 name: str = "lda",
                 seed: int = 0):
        self.V = int(vocab_size)
        self.K = int(num_topics)
        self.alpha = float(alpha)
        self.beta = float(beta)
        # Plain-add updater and ASP pinned regardless of runtime defaults:
        # LDA pushes count deltas (not gradients) and the AD-LDA scheme
        # requires async Adds visible to the next sweep.
        self.word_topic = SparseMatrixTable(self.V, self.K,
                                            updater_type="default",
                                            sync=False,
                                            name=f"{name}_word_topic")
        self.topic_sum = ArrayTable(self.K, updater_type="default",
                                    sync=False,
                                    name=f"{name}_topic_sum")
        self._key = jax.random.PRNGKey(seed)
        self._fused_cache = {}

    # ------------------------------------------------------------ init pass
    def initialize_counts(self, docs: np.ndarray,
                          seed: int = 0) -> np.ndarray:
        """Random topic init; returns doc-topic counts [D, K] (worker-local
        state in the reference) and pushes global counts."""
        rng = np.random.RandomState(seed)
        D, L = docs.shape
        z = rng.randint(self.K, size=(D, L)).astype(np.int32)
        z[docs == PAD] = -1
        doc_topic = np.zeros((D, self.K), np.float32)
        wt_delta = np.zeros((self.V, self.K), np.float32)
        ts_delta = np.zeros(self.K, np.float32)
        valid = docs != PAD
        for d in range(D):
            for i in np.nonzero(valid[d])[0]:
                k = z[d, i]
                doc_topic[d, k] += 1
                wt_delta[docs[d, i], k] += 1
                ts_delta[k] += 1
        touched = np.unique(docs[valid])
        self.word_topic.add_rows(touched, wt_delta[touched])
        self.topic_sum.add(ts_delta)
        self._z = z
        return doc_topic

    # ------------------------------------------------ parity push-pull path
    def sample_pass(self, docs: np.ndarray, doc_topic: np.ndarray,
                    seed: int = 0) -> np.ndarray:
        """One AD-LDA sweep via eager Get/Add (the reference worker loop)."""
        rng = np.random.RandomState(seed)
        # The fused drivers may hand back an (immutable) device array;
        # this host loop mutates in place, so take a host copy.
        doc_topic = np.array(doc_topic)
        D, L = docs.shape
        valid = docs != PAD
        touched = np.unique(docs[valid])
        wt = self.word_topic.get_rows(touched).astype(np.float64)
        row_of = {int(w): i for i, w in enumerate(touched)}
        ts = self.topic_sum.get().astype(np.float64)
        wt_delta = np.zeros_like(wt)
        ts_delta = np.zeros(self.K, np.float64)
        z = self._z
        for d in range(D):
            for i in np.nonzero(valid[d])[0]:
                w, old = int(docs[d, i]), int(z[d, i])
                r = row_of[w]
                # decrement
                doc_topic[d, old] -= 1
                wt[r, old] -= 1
                ts[old] -= 1
                wt_delta[r, old] -= 1
                ts_delta[old] -= 1
                # collapsed posterior
                p = ((wt[r] + self.beta) * (doc_topic[d] + self.alpha)
                     / (ts + self.V * self.beta))
                p = np.maximum(p, 0)
                new = rng.choice(self.K, p=p / p.sum())
                # increment
                z[d, i] = new
                doc_topic[d, new] += 1
                wt[r, new] += 1
                ts[new] += 1
                wt_delta[r, new] += 1
                ts_delta[new] += 1
        self.word_topic.add_rows(touched, wt_delta.astype(np.float32))
        self.topic_sum.add(ts_delta.astype(np.float32))
        return doc_topic

    # ------------------------------------------------------ fused SPMD path
    def make_fused_pass(self, max_len: int, batch_axis: str = "worker"):
        """Compile one blocked-Gibbs sweep over a doc batch into XLA.

        All tokens resample in parallel against start-of-batch counts
        (AD-LDA staleness, same approximation the reference's async Add
        makes across workers).  Returns
        ``pass_fn(wt, ts, docs, z, doc_topic, key) ->
        (z', doc_topic', topic_sum_delta)`` wired through
        ``run_fused_pass`` (which rebuilds the sparse word-topic deltas
        host-side from ``z``/``z'``).
        """
        cached = self._fused_cache.get((max_len, batch_axis))
        if cached is not None:
            return cached
        ctx = core_context.get_context()
        from ..parallel.sharding import batch_placer
        _, place_f = batch_placer(ctx.mesh, batch_axis)
        V, K, alpha, beta = self.V, self.K, self.alpha, self.beta

        @jax.jit
        def pass_fn(wt, ts, docs, z, doc_topic, key):
            valid = docs != PAD
            w_safe = jnp.where(valid, docs, 0)
            # remove each token's own count (collapsed Gibbs "minus self")
            own = jax.nn.one_hot(z, K, dtype=wt.dtype) * valid[..., None]
            wt_tok = wt[w_safe] - own                       # [D, L, K]
            dt_tok = doc_topic[:, None, :] - own            # [D, L, K]
            ts_tok = ts[None, None, :] - own                # [D, L, K]
            logits = (jnp.log(jnp.maximum(wt_tok + beta, 1e-30))
                      + jnp.log(jnp.maximum(dt_tok + alpha, 1e-30))
                      - jnp.log(jnp.maximum(ts_tok + V * beta, 1e-30)))
            new_z = jax.random.categorical(key, logits, axis=-1)
            new_z = jnp.where(valid, new_z, -1)
            # deltas: -old +new per token; only the [D,K]/[K] reductions
            # leave the device — the [D,L,K] intermediate fuses away.
            old_oh = own
            new_oh = jax.nn.one_hot(new_z, K, dtype=wt.dtype) * valid[..., None]
            delta = new_oh - old_oh
            doc_topic = doc_topic + delta.sum(axis=1)
            ts_delta = delta.sum(axis=(0, 1))
            return new_z, doc_topic, ts_delta

        self._fused_cache[(max_len, batch_axis)] = (pass_fn, place_f)
        return pass_fn, place_f

    # ---------------------------------------------- LightLDA MH SPMD path
    def make_mh_pass(self, max_len: int, mh_steps: int = 4,
                     batch_axis: str = "worker"):
        """Compile one LightLDA Metropolis-Hastings sweep into XLA.

        Reference: the WWW'15 LightLDA sampler (``Microsoft/LightLDA``,
        SURVEY.md §2.36/§6) — alternating word/doc cycle proposals with
        O(1) acceptance.  Per-token cost here is O(mh_steps · log K)
        element gathers + O(1) scatters; nothing materializes a K-sized
        axis per token, so throughput holds at K in the thousands where
        the dense kernel's [D·L·K] tensor is the ceiling.

        Same blocked/AD-LDA staleness as ``make_fused_pass``: every token
        proposes and accepts against sweep-start counts (minus its own
        sweep-start assignment — collapsed Gibbs "minus self"), and the
        word-proposal CDF is built once per sweep from those counts, with
        the MH ratio using that same stale density (so the chain targets
        the exact sweep-start posterior — amortized-table staleness is
        corrected through acceptance, as in the reference).
        """
        from ..tables.base import is_multiprocess

        # Trace-time choice: the dense [V, K] wt_delta scatter only exists
        # where it will be consumed (the single-controller device-add path)
        # — multi-host sweeps use the host sparse rebuild and must not pay
        # a discarded [V, K] scatter per sweep.
        with_wt_delta = not is_multiprocess()
        cache_key = ("mh", max_len, mh_steps, batch_axis, with_wt_delta)
        cached = self._fused_cache.get(cache_key)
        if cached is not None:
            return cached
        ctx = core_context.get_context()
        from ..parallel.sharding import batch_placer
        _, place_f = batch_placer(ctx.mesh, batch_axis)
        V, K, alpha, beta = self.V, self.K, self.alpha, self.beta
        n_bits = max(1, (K - 1).bit_length())

        @jax.jit
        def pass_fn(wt, ts, docs, z, doc_topic, key):
            D = docs.shape[0]
            valid = docs != PAD
            w = jnp.where(valid, docs, 0)
            z0 = jnp.where(valid, z, 0)
            d_idx = jnp.broadcast_to(jnp.arange(D)[:, None], docs.shape)
            vf = valid.astype(wt.dtype)

            # Sweep-start word-proposal density + CDF (the "alias tables").
            qw = (wt + beta) / (ts + V * beta)[None, :]          # [V, K]
            cdf = jnp.cumsum(qw, axis=-1)                        # [V, K]
            total = cdf[w, K - 1]                                # [D, L]

            # Minus-self π terms: subtract the token's own sweep-start
            # assignment from every count it reads.
            def pi_num(t):
                self_c = ((t == z0) & valid).astype(wt.dtype)
                n_tw = wt[w, t] - self_c
                n_td = doc_topic[d_idx, t] - self_c
                n_t = ts[t] - self_c
                return ((n_tw + beta) * (n_td + alpha)
                        / (n_t + V * beta))

            # Doc-proposal token trick: j-th valid token of doc d, found
            # through a stable sort that packs valid positions first.
            order = jnp.argsort(jnp.where(valid, 0, 1), axis=1,
                                stable=True)                     # [D, L]
            n_d = valid.sum(axis=1).astype(wt.dtype)             # [D]

            s = z0
            pi_s = pi_num(s)
            for step in range(mh_steps):
                key, k1, k2, k3, k4 = jax.random.split(key, 5)
                if step % 2 == 0:
                    # ---- word proposal: inverse-CDF binary search
                    u = jax.random.uniform(k1, docs.shape,
                                           dtype=wt.dtype) * total
                    lo = jnp.zeros(docs.shape, jnp.int32)
                    hi = jnp.full(docs.shape, K - 1, jnp.int32)
                    for _ in range(n_bits):
                        mid = (lo + hi) // 2
                        below = cdf[w, mid] < u
                        lo = jnp.where(below, mid + 1, lo)
                        hi = jnp.where(below, hi, mid)
                    t = hi
                    q_s, q_t = qw[w, s], qw[w, t]
                else:
                    # ---- doc proposal: token trick, q_d(k) ∝ n_kd + α
                    pick_tok = (jax.random.uniform(k1, docs.shape,
                                                   dtype=wt.dtype)
                                * (n_d[:, None] + K * alpha)) < n_d[:, None]
                    j = jnp.floor(jax.random.uniform(k2, docs.shape,
                                                     dtype=wt.dtype)
                                  * n_d[:, None]).astype(jnp.int32)
                    # Clip to n_d-1 per doc: fp32 rounding can make
                    # uniform*n_d land exactly on n_d, which would read a
                    # PAD slot (z0 forced to 0 — a bias toward topic 0).
                    j = jnp.clip(
                        j, 0,
                        jnp.maximum(n_d.astype(jnp.int32) - 1, 0)[:, None])
                    t_tok = z0[d_idx, order[d_idx, j]]
                    t_unif = jax.random.randint(k3, docs.shape, 0, K)
                    t = jnp.where(pick_tok, t_tok, t_unif)
                    q_s = doc_topic[d_idx, s] + alpha
                    q_t = doc_topic[d_idx, t] + alpha
                pi_t = pi_num(t)
                ratio = (pi_t * q_s) / jnp.maximum(pi_s * q_t, 1e-30)
                accept = (jax.random.uniform(k4, docs.shape,
                                             dtype=wt.dtype) < ratio)
                accept = accept & valid
                s = jnp.where(accept, t, s)
                pi_s = jnp.where(accept, pi_t, pi_s)

            new_z = jnp.where(valid, s, -1)
            # Deltas via flat scatter-add: O(tokens), never [D, L, K].
            d_flat = d_idx.reshape(-1)
            w_flat = w.reshape(-1)
            old_flat = z0.reshape(-1)
            new_flat = s.reshape(-1)
            v_flat = vf.reshape(-1)
            dt_delta = (jnp.zeros((D, K), wt.dtype)
                        .at[d_flat, new_flat].add(v_flat)
                        .at[d_flat, old_flat].add(-v_flat))
            ts_delta = (jnp.zeros((K,), wt.dtype)
                        .at[new_flat].add(v_flat)
                        .at[old_flat].add(-v_flat))
            if not with_wt_delta:
                return new_z, doc_topic + dt_delta, ts_delta
            # Word-topic delta scattered on device: the [V, K] count
            # update then rides the table's device-resident add tier
            # (HBM speed) instead of a host round trip that at large K
            # would cost seconds per sweep on the host wire.
            wt_delta = (jnp.zeros((V, K), wt.dtype)
                        .at[w_flat, new_flat].add(v_flat)
                        .at[w_flat, old_flat].add(-v_flat))
            return new_z, doc_topic + dt_delta, ts_delta, wt_delta

        self._fused_cache[cache_key] = (pass_fn, place_f)
        return pass_fn, place_f

    def run_mh_pass(self, docs: np.ndarray, doc_topic,
                    mh_steps: int = 4) -> "jax.Array | np.ndarray":
        """Drive one LightLDA-MH sweep: gather → MH in-jit → push deltas.

        Single-controller, the returned doc-topic matrix is a *device*
        array (it never ships host-side between sweeps); ``np.asarray``
        it for host analysis.  Accepts either kind as input.
        """
        pass_fn, place = self.make_mh_pass(docs.shape[1], mh_steps)
        return self._drive_pass(pass_fn, place, docs, doc_topic,
                                device_wt_delta=True)

    def run_fused_pass(self, docs: np.ndarray,
                       doc_topic: np.ndarray) -> np.ndarray:
        """Drive one fused sweep: gather → sample in-jit → push deltas."""
        pass_fn, place = self.make_fused_pass(docs.shape[1])
        return self._drive_pass(pass_fn, place, docs, doc_topic)

    def _drive_pass(self, pass_fn, place, docs: np.ndarray, doc_topic,
                    device_wt_delta: bool = False):
        """Shared driver for the fused/MH SPMD sweeps: pull table state,
        run the jitted pass, push sparse deltas back through the tables.

        ``device_wt_delta``: the pass also returns a dense [V, K]
        word-topic delta which (single-controller) goes straight through
        the table's device-resident add — no host round trip, so sweep
        cost stays sampler-bound at large K.  ``doc_topic`` may be (and
        is returned as) a device array so it never ships host-side
        between sweeps either; ``np.asarray`` it for analysis.
        """
        from ..tables.base import is_multiprocess

        # make_mh_pass omits the wt_delta output at trace time under
        # multi-host (the host sparse rebuild runs instead); mirror that.
        device_wt_delta = device_wt_delta and not is_multiprocess()
        self._key, sub = jax.random.split(self._key)
        wt_full, _ = self.word_topic.raw_value()
        ts = jnp.asarray(self.topic_sum.get())
        # Doc-dimension arrays shard over the worker axis (data parallelism);
        # the word-topic table stays on its own shards; XLA lays the gathers
        # and the one-hot reductions across ICI.
        old_z = self._z
        outs = pass_fn(
            wt_full, ts, place(jnp.asarray(docs)),
            place(jnp.asarray(old_z)), place(jnp.asarray(doc_topic)), sub)
        if device_wt_delta:
            new_z, new_dt, ts_delta, wt_delta = outs
        else:
            (new_z, new_dt, ts_delta), wt_delta = outs, None
        self._z = np.asarray(new_z)
        if wt_delta is not None:
            self.word_topic.add(wt_delta)      # device-resident tier
            self.topic_sum.add(ts_delta)       # ditto (jax.Array routes)
            return new_dt
        # Word-topic deltas rebuilt sparsely on host from (old_z, new_z):
        # [touched_words, K] instead of shipping a dense [D, L, K].
        # (Also the multi-host path: eager adds must be the lockstep
        # host collectives, not per-rank device applies.)
        valid = docs != PAD
        w_flat = docs[valid]
        old_flat = old_z[valid]
        new_flat = self._z[valid]
        touched, inv = np.unique(w_flat, return_inverse=True)
        agg = np.zeros((touched.size, self.K), np.float32)
        np.add.at(agg, (inv, old_flat), -1.0)
        np.add.at(agg, (inv, new_flat), 1.0)
        self.word_topic.add_rows(touched, agg)
        self.topic_sum.add(np.asarray(ts_delta))
        return np.asarray(new_dt)

    def close(self) -> None:
        """Release both tables' device memory (see ``Table.close``)."""
        self.word_topic.close()
        self.topic_sum.close()
        self._fused_cache.clear()

    # ------------------------------------------------------------- analysis
    def topic_purity(self, docs: np.ndarray, true_topics: np.ndarray,
                     doc_topic: np.ndarray) -> float:
        """Fraction of docs whose argmax inferred topic maps 1:1 to the
        planted topic (best matching via greedy assignment)."""
        inferred = doc_topic.argmax(axis=1)
        K = self.K
        conf = np.zeros((K, K))
        for inf, true in zip(inferred, true_topics):
            conf[inf, true] += 1
        purity = 0.0
        used = set()
        for inf in np.argsort(-conf.max(axis=1)):
            best = int(np.argmax(
                [conf[inf, t] if t not in used else -1 for t in range(K)]))
            used.add(best)
            purity += conf[inf, best]
        return purity / len(true_topics)
