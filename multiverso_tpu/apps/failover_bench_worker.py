"""bench_failover fleet (docs/replication.md; bench.py schema 18).

Run as ``python failover_bench_worker.py <machine_file> <rank>
[herd_threads] [reads_per_arm]``: a THREE-rank replicated epoll fleet
(``-replication_factor=1 -repl_sync=true``, fast symmetric leases).

- **rank 0** measures.  Phase A (healthy fleet): an in-process
  anonymous read herd against its own reactor, replication armed vs
  disarmed in interleaved arms (the PR 12 A/B discipline — separate
  herds swing several-fold with host load) → ``repl_overhead_pct``
  (reads never forward, so the armed cost is one routed-table check).
  Phase B: a continuous blocking-add loop with per-success
  timestamps; rank 1 SIGKILLs itself mid-loop — the loop rides the
  blackout (fail-fast retries) through promotion and out the other
  side.  Keys: ``failover_detect_ms`` (last pre-blackout success →
  lease expiry seen locally), ``failover_promote_ms`` (→ shard 1
  routed at rank 2), ``failover_p99_blip_ms`` (widest gap between
  consecutive successful adds — the caller-visible outage),
  ``failover_lost_acked_adds`` (fleet ``"audit"`` diff over the rank
  wire: an acked add missing from the promoted shard's book would be
  the contract violation; failed attempts' seq holes are named gaps /
  unacked tails, never lost).
- **rank 1** is the victim: it waits out a beat of phase B, prints
  nothing more, and SIGKILLs itself (no goodbye).
- **rank 2** is shard 1's chained backup: it serves, promotes on the
  lease expiry, and rendezvouses with rank 0 at the end (the corpse
  is excused from the quorum).

Ranks 0 and 2 print ``FAILOVER_BENCH_OK``; rank 1 never does (the
bench's spawner exempts the victim).
"""

import json
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np  # noqa: E402

from multiverso_tpu import native as nat  # noqa: E402

SIZE = 24


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2] if xs else 0.0


def main() -> int:
    mf, rank = sys.argv[1], int(sys.argv[2])
    herd_threads = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    reads_per_arm = int(sys.argv[4]) if len(sys.argv) > 4 else 150
    eps = open(mf).read().split()
    rt = nat.NativeRuntime(args=[
        f"-machine_file={mf}", f"-rank={rank}", "-log_level=error",
        "-rpc_timeout_ms=1500", "-barrier_timeout_ms=60000",
        "-heartbeat_ms=100", "-heartbeat_timeout_ms=400",
        "-replication_factor=1", "-repl_sync=true", "-promote_auto=true",
        "-send_retries=1", "-send_backoff_ms=10",
        "-connect_retry_ms=500", "-ops_fleet_timeout_ms=1500"])
    h = rt.new_array_table(SIZE)
    rt.barrier()
    ones = np.ones(SIZE, np.float32)
    rt.array_add(h, ones)
    rt.barrier()

    if rank == 1:
        # The victim: let phase A finish (it runs pre-kill, healthy),
        # ride one beat of the add loop, then die with no goodbye.
        rt.barrier()          # phase A done fleet-wide
        time.sleep(1.2)
        sys.stdout.flush()
        os.kill(os.getpid(), signal.SIGKILL)
        return 0              # unreachable

    if rank == 2:
        rt.barrier()          # phase A done fleet-wide
        # Serve through the kill + promotion; rank 0's final barrier
        # (corpse excused) releases us.
        rt.barrier()
        st = rt.replication_stats()
        print(f"rank=2 promotions={st['promotions']} "
              f"applied={st['applied']}", flush=True)
        rt.shutdown()
        print("FAILOVER_BENCH_OK 2", flush=True)
        return 0

    # ---------------- rank 0: phase A — read-path overhead A/B --------
    from multiverso_tpu.serve.wire import AnonServeClient

    def herd_qps() -> float:
        counts = [0] * herd_threads
        errs = []

        def reader(i):
            try:
                c = AnonServeClient(eps[0], timeout=10.0, timing=False)
                for _ in range(reads_per_arm):
                    c.get_shard(h)
                    counts[i] += 1
                c.close()
            except (ConnectionError, OSError) as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=reader, args=(i,))
                   for i in range(herd_threads)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.monotonic() - t0
        if errs:
            raise errs[0]
        return sum(counts) / dt if dt > 0 else 0.0

    herd_qps()  # warm the sockets/route out of the measurement
    on_arms, off_arms = [], []
    for arm in ("on", "off", "on", "off", "on", "off"):
        rt.set_replication(arm == "on")
        (on_arms if arm == "on" else off_arms).append(herd_qps())
    rt.set_replication(True)
    qps_on, qps_off = _median(on_arms), _median(off_arms)
    overhead = ((qps_off - qps_on) / qps_off * 100.0) if qps_off else 0.0
    print(f"rank=0 repl_overhead_pct={max(overhead, 0.0):.3f} "
          f"repl_read_qps={qps_on:.1f}", flush=True)
    rt.barrier()              # release the victim's death timer

    # ---------------- phase B: add loop through the blackout ----------
    succ_ts = []              # monotonic stamps of successful adds
    lat = []                  # per-success add latency (s)
    t_dead = None
    t_owner = None
    fails = 0
    deadline = time.monotonic() + 25.0
    settled = 0
    while time.monotonic() < deadline:
        t0 = time.monotonic()
        try:
            rt.array_add(h, ones)
            succ_ts.append(time.monotonic())
            lat.append(succ_ts[-1] - t0)
        except RuntimeError:
            fails += 1
        if t_dead is None and rt.dead_peer_count() >= 1:
            t_dead = time.monotonic()
        if t_owner is None and rt.shard_owner(1) == 2:
            t_owner = time.monotonic()
        if t_owner is not None:
            settled += 1
            if settled >= 30:
                break
    assert t_dead is not None and t_owner is not None, \
        "failover never observed"
    assert fails > 0 or succ_ts, "add loop never ran"
    # Blackout anchored at the last success BEFORE the widest gap.
    gaps = [(succ_ts[i + 1] - succ_ts[i], succ_ts[i])
            for i in range(len(succ_ts) - 1)]
    blip_s, t_blackout = max(gaps) if gaps else (0.0, t_dead)
    detect_ms = max(t_dead - t_blackout, 0.0) * 1e3
    promote_ms = max(t_owner - t_blackout, 0.0) * 1e3
    lat_ms = sorted(x * 1e3 for x in lat)
    p50 = lat_ms[len(lat_ms) // 2] if lat_ms else 0.0

    # The auditor's verdict, assembled over the rank wire (the corpse
    # is silent; the PROMOTED shard's backup book answers for shard 1).
    from multiverso_tpu.ops.audit import diff_fleet

    fleet = json.loads(rt.ops_fleet_report("audit"))
    lost = [f for f in diff_fleet(fleet) if f["kind"] == "lost"]

    print(f"rank=0 failover_detect_ms={detect_ms:.1f} "
          f"failover_promote_ms={promote_ms:.1f} "
          f"failover_p99_blip_ms={blip_s * 1e3:.1f} "
          f"failover_add_p50_ms={p50:.3f} "
          f"failover_adds_ok={len(succ_ts)} failover_add_fails={fails} "
          f"failover_lost_acked_adds={len(lost)}", flush=True)
    rt.barrier()              # survivor rendezvous (corpse excused)
    rt.shutdown()
    print("FAILOVER_BENCH_OK 0", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
