"""Serve-layer benchmark worker (bench.py's ``bench_serve`` section).

Run as ``python serve_bench_worker.py <machine_file> <rank>``: two of
these form a native TcpNet wire session; rank 0 measures the three
serve-layer read configurations on one sharded ArrayTable and prints a
``SERVE_BENCH_OK key=val ...`` line; rank 1 serves its shard and holds
the rendezvous barriers.

Configurations (docs/serving.md):

- **cold**  — cache disabled: every ``get()`` pays the full wire round
  trip (the reference's read path; the baseline denominator).
- **cached** — versioned cache + a held lease: repeat reads are served
  locally with zero wire messages.
- **coal8** — 8 concurrent uncached readers through the coalescing
  window: per-op latency amortizes one round trip over the batch.
"""

import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from multiverso_tpu import native as nat  # noqa: E402
from multiverso_tpu.serve import ServeClient  # noqa: E402

SIZE = 4096


def pct(times, q):
    return float(np.percentile(np.asarray(times) * 1e3, q))


def main() -> int:
    mf, rank = sys.argv[1], int(sys.argv[2])
    rt = nat.NativeRuntime(args=[f"-machine_file={mf}", f"-rank={rank}",
                                 "-log_level=error",
                                 "-rpc_timeout_ms=30000"])
    h = rt.new_array_table(SIZE)
    rt.barrier()
    out = {}
    if rank == 0:
        rt.array_add(h, np.ones(SIZE, np.float32))

        cold = ServeClient(rt, cache_entries=0, window_us=0.0)
        times = []
        for _ in range(50):
            t0 = time.perf_counter()
            cold.array_get(h, SIZE)
            times.append(time.perf_counter() - t0)
        out["cold_p50_ms"] = pct(times, 50)
        out["cold_p95_ms"] = pct(times, 95)
        out["cold_p99_ms"] = pct(times, 99)
        out["cold_qps"] = len(times) / sum(times)

        cached = ServeClient(rt, cache_entries=32, max_staleness=0,
                             lease_ms=60000.0, window_us=0.0)
        cached.array_get(h, SIZE)          # warm the entry + the lease
        times = []
        for _ in range(500):
            t0 = time.perf_counter()
            cached.array_get(h, SIZE)
            times.append(time.perf_counter() - t0)
        out["cached_p50_ms"] = pct(times, 50)
        out["cached_p95_ms"] = pct(times, 95)
        out["cached_p99_ms"] = pct(times, 99)
        out["cached_qps"] = len(times) / sum(times)

        coal = ServeClient(rt, cache_entries=0, window_us=200.0)
        all_times = [[] for _ in range(8)]
        start = threading.Barrier(8)

        def reader(i):
            start.wait()
            for _ in range(25):
                t0 = time.perf_counter()
                coal.array_get(h, SIZE)
                all_times[i].append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        ts = [threading.Thread(target=reader, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        wall = time.perf_counter() - t0
        flat = [x for ts_ in all_times for x in ts_]
        out["coal8_p50_ms"] = pct(flat, 50)
        out["coal8_p95_ms"] = pct(flat, 95)
        out["coal8_p99_ms"] = pct(flat, 99)
        out["coal8_qps"] = len(flat) / wall
    rt.barrier()
    rt.shutdown()
    kv = " ".join(f"{k}={v:.6f}" for k, v in out.items())
    print(f"SERVE_BENCH_OK rank={rank} {kv}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
