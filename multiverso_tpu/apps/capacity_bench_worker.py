"""Capacity-plane benchmark worker (bench.py ``bench_capacity``; ``make
capacity-demo`` drives it too — docs/observability.md, "capacity
plane").

Run as ``python capacity_bench_worker.py <machine_file> <rank>
[nclients] [rows] [reqs] [demo]``: the ranks form a native epoll fleet
holding one row-sharded MatrixTable and one KV table; the LAST rank
then drives an anonymous zipf row-get herd against rank 0's reactor in
INTERLEAVED armed/disarmed sweeps (``MV_SetCapacityTracking``
coordinated through a KV flag table, three pairs, best-of per arm — the
PR 12 audit-bench discipline: one persistent herd, so connect noise
cancels out of the A/B).  Each sweep also batch-inserts FRESH keys into
the KV table from the driver's worker stub — the one table path where
the capacity accounting actually rides the hot loop (matrix shards are
fixed-size).

Measured keys (driver rank prints them):

- ``capacity_overhead_pct`` — armed-vs-disarmed sweep cost
  (acceptance: < 1%; the armed delta is one relaxed load per op plus
  three relaxed bumps per NEW KV key).
- ``capacity_bytes_accuracy`` — fleet-scraped resident bytes of the
  matrix table over its ground truth (rows x cols x 4, the walkable
  shape) — acceptance within 10% of 1.0.
- ``capacity_kv_accuracy`` — same for the KV table against the
  documented per-entry formula (key + value + overhead).
- ``mvplan_spread_after`` — the placement advisor's projected
  per-shard byte spread over the scraped fleet (acceptance: <= 2x).

``demo=1`` (the capacity-demo mode) additionally loads a LARGE array
table on rank 0 mid-run and reports the RSS/arena movement the demo
asserts.  Every rank prints ``CAPACITY_BENCH_OK``.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from multiverso_tpu import native as nat  # noqa: E402
from multiverso_tpu.ops.introspect import OpsClient  # noqa: E402
from multiverso_tpu.apps.skew_bench_worker import (  # noqa: E402
    Herd, _zipf_ids)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tools"))
import mvplan  # noqa: E402

COLS = 8
KV_BATCH = 1024          # fresh keys inserted per sweep
SWEEP_PAIRS = 3          # interleaved on/off pairs
KV_OVERHEAD = 64         # native capacity::kKVEntryOverhead


def _await_flag(rt, h_kv, name, deadline_s=120):
    deadline = time.time() + deadline_s
    while rt.kv_get(h_kv, name) < 1.0:
        if time.time() > deadline:
            raise RuntimeError(f"flag {name} never raised")
        time.sleep(0.02)


def main() -> int:
    mf, rank = sys.argv[1], int(sys.argv[2])
    nclients = int(sys.argv[3]) if len(sys.argv) > 3 else 256
    rows = int(sys.argv[4]) if len(sys.argv) > 4 else 2048
    reqs = int(sys.argv[5]) if len(sys.argv) > 5 else 512
    demo = int(sys.argv[6]) if len(sys.argv) > 6 else 0
    eps = [ln.strip() for ln in open(mf) if ln.strip()]
    nranks = len(eps)
    driver = nranks - 1
    rt = nat.NativeRuntime(args=[
        f"-machine_file={mf}", f"-rank={rank}", "-log_level=error",
        "-rpc_timeout_ms=60000", "-barrier_timeout_ms=120000",
        "-capacity_history_ms=0"])
    assert rt.net_engine() == "epoll", rt.net_engine()
    h_mat = rt.new_matrix_table(rows, COLS)
    h_kv = rt.new_kv_table()       # the measured growing table
    h_flags = rt.new_kv_table()    # coordination flags
    rt.barrier()

    out = {}
    if rank == driver:
        rng = np.random.RandomState(11)
        shard = rows // nranks                 # rank 0's row block
        zipf_ids = _zipf_ids(reqs * 8, max(shard, 1), rng)
        herd = Herd(eps[0], nclients)
        herd.run_phase(h_mat, zipf_ids)        # full warmup sweep

        kv_keys = 0
        kv_bytes = 0

        def sweep(tag, sweep_no):
            """One timed sweep: a zipf get herd + a fresh-key insert
            batch (the armed hot paths)."""
            nonlocal kv_keys, kv_bytes
            keys = [f"{tag}-{sweep_no}-{i}" for i in range(KV_BATCH)]
            t0 = time.perf_counter()
            got, _ = herd.run_phase(h_mat, zipf_ids)
            rt.kv_add(h_kv, keys, np.ones(KV_BATCH, np.float32))
            dt = time.perf_counter() - t0
            kv_keys += KV_BATCH
            kv_bytes += sum(len(k) + 4 + KV_OVERHEAD for k in keys)
            return (got + KV_BATCH) / dt

        on_qps, off_qps = [], []
        for pair in range(SWEEP_PAIRS):
            # Armed sweep (both the server rank and this driver arm).
            rt.set_capacity_tracking(True)
            rt.kv_add(h_flags, f"arm-{pair}", 1.0)
            _await_flag(rt, h_flags, f"armed-{pair}")
            on_qps.append(sweep("on", pair))
            # Disarmed sweep.
            rt.set_capacity_tracking(False)
            rt.kv_add(h_flags, f"disarm-{pair}", 1.0)
            _await_flag(rt, h_flags, f"disarmed-{pair}")
            off_qps.append(sweep("off", pair))
        rt.set_capacity_tracking(True)
        rt.kv_add(h_flags, "rearm", 1.0)
        _await_flag(rt, h_flags, "rearmed")

        qps_on = max(on_qps)      # best-of: host noise errs the A/B
        qps_off = max(off_qps)
        out["capacity_qps_armed"] = qps_on
        out["capacity_qps_disarmed"] = qps_off
        out["capacity_overhead_pct"] = max(
            0.0, (qps_off - qps_on) / qps_off * 100.0)

        # Fleet scrape -> accuracy + the advisor's projected spread.
        # (Tracking was re-armed above, which RESYNCS the disarmed-
        # sweep inserts into the books — accuracy covers both paths.)
        with OpsClient(eps[0], timeout=30) as c:
            fleet = c.capacity(fleet=True)
        mat_bytes = kv_rep_bytes = 0
        for rep in (fleet.get("ranks") or {}).values():
            for t in (rep or {}).get("tables") or []:
                if not t.get("shard"):
                    continue
                if t["id"] == h_mat:
                    mat_bytes += t["shard"]["resident_bytes"]
                elif t["id"] == h_kv:
                    kv_rep_bytes += t["shard"]["resident_bytes"]
        out["capacity_bytes_accuracy"] = (
            mat_bytes / float(rows * COLS * 4))
        out["capacity_kv_accuracy"] = (
            kv_rep_bytes / float(max(kv_bytes, 1)))

        proposal = mvplan.propose(fleet)
        plan = proposal["tables"].get(str(h_mat))
        assert plan is not None, sorted(proposal["tables"])
        out["mvplan_spread_after"] = plan["spread_after"]["weight"]
        out["mvplan_moves"] = float(len(plan["moves"]))

        if demo:
            # (a) Skewed bucket BYTES: mine keys whose KVHash bucket
            # sits in [0, 8) (the Python sketch mirror is byte-
            # identical to the native hash) and insert them — the KV
            # table's resident bytes pile into 8 of 64 buckets.
            from multiverso_tpu.sketch import key_hash

            mined, i = [], 0
            while len(mined) < 2048:
                k = f"hotbucket-{i}"
                i += 1
                if key_hash(k) % 64 < 8:
                    mined.append(k)
            rt.kv_add(h_kv, mined, np.ones(len(mined), np.float32))

            def scrape():
                with OpsClient(eps[0], timeout=30) as c:
                    return c.capacity(fleet=True)

            def fold_buckets(doc, tid, field):
                total = [0] * 64
                for rep in (doc.get("ranks") or {}).values():
                    for t in (rep or {}).get("tables") or []:
                        if t.get("id") != tid or not t.get("shard"):
                            continue
                        vals = t["shard"].get(field) or []
                        if field == "bucket_gets":
                            adds = t["shard"].get("bucket_adds") or []
                            vals = [g + a for g, a in zip(vals, adds)]
                        for b, v in enumerate(vals[:64]):
                            total[b] += v
                return total

            def skew(vals):
                mean = sum(vals) / float(len(vals) or 1)
                return max(vals) / mean if mean > 0 else 0.0

            before = scrape()
            out["demo_bytes_skew"] = skew(
                fold_buckets(before, h_kv, "bucket_bytes"))
            out["demo_load_skew"] = skew(
                fold_buckets(before, h_mat, "bucket_gets"))
            rss0 = before["ranks"]["0"]["proc"]["rss_bytes"]
            arena0 = before["ranks"]["0"]["gauges"].get(
                "host_arena.bytes", 0)
            # (b) Big table + arena buffer land on rank 0: RSS and the
            # arena gauge must MOVE in the next scrape.
            rt.kv_add(h_flags, "bigload", 1.0)
            _await_flag(rt, h_flags, "bigloaded")
            after = scrape()
            out["demo_rss_delta"] = float(
                after["ranks"]["0"]["proc"]["rss_bytes"] - rss0)
            out["demo_arena_delta"] = float(
                after["ranks"]["0"]["gauges"].get("host_arena.bytes", 0)
                - arena0)
            # The advisor over the post-load fleet: the rank-0-only big
            # table reads as observed imbalance; the proposal's
            # projected spread must still pack <= 2x.
            proposal = mvplan.propose(after)
            out["mvplan_spread_after"] = max(
                p["spread_after"]["weight"]
                for p in proposal["tables"].values())
            out["demo_observed_spread"] = mvplan.max_observed_spread(
                proposal)
        herd.close()
        rt.kv_add(h_flags, "herd_done", 1.0)
    else:
        deadline = time.time() + 600
        pair = 0
        state = "arm"
        while rt.kv_get(h_flags, "herd_done") < 1.0:
            if time.time() > deadline:
                raise RuntimeError("herd never finished")
            if pair < SWEEP_PAIRS and \
                    rt.kv_get(h_flags, f"{state}-{pair}") >= 1.0:
                rt.set_capacity_tracking(state == "arm")
                ack = "armed" if state == "arm" else "disarmed"
                if rank == 0:
                    rt.kv_add(h_flags, f"{ack}-{pair}", 1.0)
                if state == "arm":
                    state = "disarm"
                else:
                    state, pair = "arm", pair + 1
            if rt.kv_get(h_flags, "rearm") >= 1.0:
                rt.set_capacity_tracking(True)
                if rank == 0:
                    rt.kv_add(h_flags, "rearmed", 1.0)
            if demo and rank == 0 and \
                    rt.kv_get(h_flags, "bigload") >= 1.0 and \
                    rt.kv_get(h_flags, "bigloaded") < 1.0:
                # Demo: a big table + a pinned arena buffer land
                # mid-run — the next scrape's RSS and arena gauges
                # must move (the demo asserts the deltas fleet-side).
                big = rt.new_matrix_table(1 << 15, 64)  # ~8 MiB resident
                arena_buf = rt.arena().alloc(1 << 20)   # 4 MiB pinned
                arena_buf[:] = 1.0
                rep = rt.capacity_report()
                entry = rep["tables"][big]["shard"]
                print(f"DEMO_BIG_TABLE id={big} "
                      f"bytes={entry['resident_bytes']}", flush=True)
                rt.kv_add(h_flags, "bigloaded", 1.0)
            time.sleep(0.02)
        rt.set_capacity_tracking(True)

    rt.barrier()
    rt.shutdown()
    kv = " ".join(f"{k}={v:.6f}" for k, v in sorted(out.items()))
    print(f"CAPACITY_BENCH_OK rank={rank} {kv}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
