"""Distributed logistic regression — the north-star parity app.

Reference (SURVEY.md §2.32, §3.4,
``binding/python/examples/theano/logistic_regression.py``): a Theano LR
model whose parameters live in an ArrayTable; each worker trains on its data
shard and syncs via ``add(delta)`` / ``get()`` per batch.

TPU-native: the model is pure JAX.  Two training paths:

- ``train_batch`` — the literal reference loop: pull, local grad, push.
  Useful for API parity and as the semantics oracle.
- ``make_fused_step`` — ONE jitted SPMD step over the mesh's worker axis:
  the global batch is sharded across devices, the cross-replica gradient
  reduction is the ``mean`` XLA compiles to a ``psum`` over ICI, and the
  updater applies in-place on the table's own shards.  This is what the
  reference's worker→server→updater round-trip becomes on TPU.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import context as core_context
from ..tables import ArrayTable
from ..updaters import AddOption

__all__ = ["LogisticRegression", "synthetic_classification"]


def synthetic_classification(num_samples: int, num_features: int,
                             num_classes: int, seed: int = 0,
                             noise: float = 0.1
                             ) -> Tuple[np.ndarray, np.ndarray]:
    """Linearly-separable-ish synthetic data (MNIST stand-in for tests/bench;
    the sandbox has no dataset egress)."""
    rng = np.random.RandomState(seed)
    true_w = rng.randn(num_features, num_classes).astype(np.float32)
    x = rng.randn(num_samples, num_features).astype(np.float32)
    logits = x @ true_w + noise * rng.randn(num_samples, num_classes)
    y = logits.argmax(axis=1).astype(np.int32)
    return x, y


def _loss_fn(w_flat: jax.Array, x: jax.Array, y: jax.Array,
             num_features: int, num_classes: int) -> jax.Array:
    """Softmax cross-entropy; parameters packed flat [(F+1)*C] (W then b)."""
    W = w_flat[: num_features * num_classes].reshape(num_features, num_classes)
    b = w_flat[num_features * num_classes:
               (num_features + 1) * num_classes]
    logits = x @ W + b
    logz = jax.nn.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, y[:, None], axis=-1)[:, 0]
    return jnp.mean(logz - ll)


class LogisticRegression:
    """ArrayTable-backed multinomial logistic regression."""

    def __init__(self, num_features: int, num_classes: int,
                 learning_rate: float = 0.1,
                 updater_type: str = "sgd",
                 name: str = "lr",
                 seed: int = 0):
        self.num_features = int(num_features)
        self.num_classes = int(num_classes)
        self.param_size = (self.num_features + 1) * self.num_classes
        self.option = AddOption(learning_rate=learning_rate)
        rng = np.random.RandomState(seed)
        init = (0.01 * rng.randn(self.param_size)).astype(np.float32)
        init[self.num_features * self.num_classes:] = 0.0  # zero bias
        self.table = ArrayTable(self.param_size, init=init,
                                updater_type=updater_type, name=name,
                                default_option=self.option)
        self._loss = partial(_loss_fn, num_features=self.num_features,
                             num_classes=self.num_classes)
        self._grad_fn = jax.jit(jax.value_and_grad(self._loss))
        self._fused_cache = {}

    # ------------------------------------------------ parity push-pull path
    def train_batch(self, x: np.ndarray, y: np.ndarray) -> float:
        """Reference loop body (§3.4): get → local grad → add(grad)."""
        w = jnp.asarray(self.table.get())
        loss, grad = self._grad_fn(w, jnp.asarray(x), jnp.asarray(y))
        self.table.add(np.asarray(grad), option=self.option)
        return float(loss)

    def evaluate(self, x: np.ndarray, y: np.ndarray) -> Tuple[float, float]:
        w = jnp.asarray(self.table.get())
        loss = float(self._loss(w, jnp.asarray(x), jnp.asarray(y)))
        W = w[: self.num_features * self.num_classes].reshape(
            self.num_features, self.num_classes)
        b = w[self.num_features * self.num_classes:]
        acc = float((np.asarray(jnp.asarray(x) @ W + b).argmax(axis=1)
                     == y).mean())
        return loss, acc

    # ------------------------------------------------------ fused SPMD path
    def make_fused_step(self, batch_axis: str = "worker"):
        """Compile the full data-parallel step into one XLA program.

        Returns ``step(data, state, x, y) -> (data, state, loss)`` plus the
        batch sharding to place inputs with.  The caller drives:

            step, place = lr.make_fused_step()
            data, state = lr.table.raw_value()
            data, state, loss = step(data, state, place(x), place(y))
            lr.table.raw_assign(data, state)

        The gradient's batch-mean reduces across devices (XLA inserts the
        psum over ICI); the updater then applies on the table's own shards —
        the whole reference §3.2+§3.3 round-trip with zero host hops.
        """
        cached = self._fused_cache.get(batch_axis)
        if cached is not None:  # reuse: a fresh jit wrapper would recompile
            return cached
        ctx = core_context.get_context()
        from ..parallel.sharding import batch_placer
        _, place = batch_placer(ctx.mesh, batch_axis)
        updater = self.table.updater
        loss_grad = jax.value_and_grad(self._loss)
        opt = self.option

        @partial(jax.jit, donate_argnums=(0, 1))
        def step(data, state, x, y):
            w = data[: (self.num_features + 1) * self.num_classes]
            loss, grad = loss_grad(w, x, y)
            pad = data.shape[0] - grad.shape[0]
            grad_padded = jnp.concatenate(
                [grad, jnp.zeros((pad,), grad.dtype)])
            data, state = updater.apply_dense(data, state, grad_padded, opt)
            return data, state, loss

        self._fused_cache[batch_axis] = (step, place)
        return step, place

    def train_epoch_fused(self, x: np.ndarray, y: np.ndarray,
                          batch_size: int) -> float:
        """Drive the fused step over an epoch; returns the last batch loss."""
        step, place = self.make_fused_step()
        data, state = self.table.raw_value()
        n = (x.shape[0] // batch_size) * batch_size
        if n == 0:
            raise ValueError(
                f"no full batch: {x.shape[0]} samples < batch_size "
                f"{batch_size} (tail samples are dropped for static shapes)")
        loss = jnp.zeros(())
        for i in range(0, n, batch_size):
            xb = place(x[i:i + batch_size])
            yb = place(y[i:i + batch_size])
            data, state, loss = step(data, state, xb, yb)
        self.table.raw_assign(data, state)
        return float(loss)
