"""Distributed word embedding (skip-gram negative sampling).

Reference (SURVEY.md §2.36, ``Microsoft/distributed_word_embedding`` linking
libmultiverso): embeddings live in (Sparse)MatrixTables row-sharded over
servers; workers pull the rows a batch touches (`Get(rows)`), compute SGNS
gradients locally, and push row deltas (`Add(rows)`), with an AsyncBuffer
overlapping the next pull with compute.

TPU-native: both embedding matrices are row-sharded ``jax.Array`` tables.
The fused step compiles the whole pull→grad→push round-trip into one XLA
program: gathers fetch rows over ICI, autodiff produces the row gradients,
and the updater scatter-applies them on the rows' home shards.  Row batches
are static-shaped; negatives are pre-sampled on host (the reference samples
on the worker too).
"""

from __future__ import annotations

from functools import partial
from typing import Iterator, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import context as core_context
from ..tables import MatrixTable
from ..updaters import AddOption
from ..util import AsyncBuffer

__all__ = ["SkipGram", "synthetic_corpus"]


def synthetic_corpus(num_tokens: int, vocab_size: int, seed: int = 0,
                     zipf_a: float = 1.1) -> np.ndarray:
    """Zipf-distributed token stream (text8 stand-in; no dataset egress)."""
    rng = np.random.RandomState(seed)
    ranks = rng.zipf(zipf_a, size=num_tokens)
    return ((ranks - 1) % vocab_size).astype(np.int32)


def _sgns_loss(vc: jax.Array, uo: jax.Array, un: jax.Array) -> jax.Array:
    """Skip-gram negative-sampling loss.

    ``vc`` [B,D] center (input) embeddings, ``uo`` [B,D] positive context
    (output) embeddings, ``un`` [B,K,D] negative samples.
    """
    pos = jnp.einsum("bd,bd->b", vc, uo)
    neg = jnp.einsum("bd,bkd->bk", vc, un)
    return -(jnp.sum(jax.nn.log_sigmoid(pos))
             + jnp.sum(jax.nn.log_sigmoid(-neg))) / vc.shape[0]


class SkipGram:
    """Word2vec SGNS over two row-sharded MatrixTables."""

    def __init__(self, vocab_size: int, dim: int,
                 learning_rate: float = 0.025,
                 negatives: int = 5,
                 window: int = 5,
                 updater_type: str = "sgd",
                 name: str = "w2v",
                 seed: int = 0):
        self.vocab_size = int(vocab_size)
        self.dim = int(dim)
        self.negatives = int(negatives)
        self.window = int(window)
        self.option = AddOption(learning_rate=learning_rate)
        rng = np.random.RandomState(seed)
        init_in = ((rng.rand(vocab_size, dim) - 0.5) / dim).astype(np.float32)
        self.table_in = MatrixTable(vocab_size, dim, init=init_in,
                                    updater_type=updater_type,
                                    name=f"{name}_in",
                                    default_option=self.option)
        self.table_out = MatrixTable(vocab_size, dim,
                                     updater_type=updater_type,
                                     name=f"{name}_out",
                                     default_option=self.option)
        self._rng = np.random.RandomState(seed + 1)
        self._grad_fn = jax.jit(jax.grad(
            lambda vc, uo, un: _sgns_loss(vc, uo, un), argnums=(0, 1, 2)))
        self._fused_cache = {}

    # ------------------------------------------------------------- batching
    def batches(self, corpus: np.ndarray, batch_size: int,
                seed: int = 0) -> Iterator[Tuple[np.ndarray, np.ndarray,
                                                 np.ndarray]]:
        """Static-shaped (centers [B], contexts [B], negatives [B,K])."""
        rng = np.random.RandomState(seed)
        n = corpus.shape[0]
        centers, contexts = [], []
        for i in range(n):
            w = 1 + rng.randint(self.window)
            for j in range(max(0, i - w), min(n, i + w + 1)):
                if j != i:
                    centers.append(corpus[i])
                    contexts.append(corpus[j])
            while len(centers) >= batch_size:
                c = np.asarray(centers[:batch_size], np.int32)
                o = np.asarray(contexts[:batch_size], np.int32)
                del centers[:batch_size], contexts[:batch_size]
                neg = rng.randint(self.vocab_size,
                                  size=(batch_size, self.negatives)
                                  ).astype(np.int32)
                yield c, o, neg

    # ------------------------------------------------ parity push-pull path
    def train_batch(self, centers: np.ndarray, contexts: np.ndarray,
                    negatives: np.ndarray) -> None:
        """Reference loop body: Get(rows) → local grads → Add(rows)."""
        B, K = negatives.shape
        vc = jnp.asarray(self.table_in.get_rows(centers))
        out_rows = np.concatenate([contexts, negatives.reshape(-1)])
        out_emb = self.table_out.get_rows(out_rows)
        uo = jnp.asarray(out_emb[:B])
        un = jnp.asarray(out_emb[B:]).reshape(B, K, self.dim)
        dvc, duo, dun = self._grad_fn(vc, uo, un)
        self.table_in.add_rows(centers, np.asarray(dvc), option=self.option)
        self.table_out.add_rows(
            out_rows,
            np.concatenate([np.asarray(duo),
                            np.asarray(dun).reshape(B * K, self.dim)]),
            option=self.option)

    def train_epoch(self, corpus: np.ndarray, batch_size: int,
                    seed: int = 0, prefetch: bool = True) -> int:
        """Parity epoch with AsyncBuffer overlapping batch prep (§2.24)."""
        it = self.batches(corpus, batch_size, seed=seed)
        steps = 0
        if not prefetch:
            for c, o, neg in it:
                self.train_batch(c, o, neg)
                steps += 1
        else:
            with AsyncBuffer(lambda: next(it, None)) as buf:
                while True:
                    batch = buf.get()
                    if batch is None:
                        break
                    self.train_batch(*batch)
                    steps += 1
        if steps == 0:
            raise ValueError(
                f"corpus of {corpus.shape[0]} tokens produced no full batch "
                f"of {batch_size} pairs (partial batches are dropped for "
                "static shapes)")
        return steps

    # ------------------------------------------------------ fused SPMD path
    def make_fused_step(self, batch_axis: str = "worker"):
        """One XLA program: gather rows, SGNS grads, scatter-apply updater.

        Index batches are sharded over the mesh's worker axis; the gathers
        and the scatter-adds cross shards over ICI exactly where the
        reference crossed the network.  Returns
        ``step(din, sin, dout, sout, c, o, neg) -> (din, sin, dout, sout, loss)``
        and a placer for the index arrays.
        """
        cached = self._fused_cache.get(batch_axis)
        if cached is not None:  # reuse: a fresh jit wrapper would recompile
            return cached
        ctx = core_context.get_context()
        from ..parallel.sharding import batch_placer
        _, place = batch_placer(ctx.mesh, batch_axis, dtype=jnp.int32)
        upd_in = self.table_in.updater
        upd_out = self.table_out.updater
        opt = self.option
        D = self.dim

        from ..updaters.base import scatter_apply

        @partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def step(din, sin, dout, sout, c, o, neg):
            B, K = neg.shape
            vc = din[c]
            uo = dout[o]
            un = dout[neg.reshape(-1)].reshape(B, K, D)
            loss, grads = jax.value_and_grad(
                _sgns_loss, argnums=(0, 1, 2))(vc, uo, un)
            dvc, duo, dun = grads
            din, sin = scatter_apply(upd_in, din, sin, c, dvc, opt)
            out_rows = jnp.concatenate([o, neg.reshape(-1)])
            out_delta = jnp.concatenate([duo, dun.reshape(B * K, D)])
            dout, sout = scatter_apply(upd_out, dout, sout, out_rows,
                                       out_delta, opt)
            return din, sin, dout, sout, loss

        self._fused_cache[batch_axis] = (step, place)
        return step, place

    def train_epoch_fused(self, corpus: np.ndarray, batch_size: int,
                          seed: int = 0) -> Tuple[int, float]:
        from ..util import prefetch_to_device

        step, place = self.make_fused_step()
        din, sin = self.table_in.raw_value()
        dout, sout = self.table_out.raw_value()
        loss = jnp.zeros(())
        steps = 0
        # Index batches go device-side one step ahead of the compiled
        # step (H2D rides behind the previous step's compute), placed by
        # the same batch_placer closure the step's shardings expect.
        for c, o, neg in prefetch_to_device(
                self.batches(corpus, batch_size, seed=seed), size=2,
                sharding=place):
            din, sin, dout, sout, loss = step(
                din, sin, dout, sout, c, o, neg)
            steps += 1
        if steps == 0:
            raise ValueError(
                f"corpus of {corpus.shape[0]} tokens produced no full batch "
                f"of {batch_size} pairs (partial batches are dropped for "
                "static shapes)")
        self.table_in.raw_assign(din, sin)
        self.table_out.raw_assign(dout, sout)
        return steps, float(loss)

    # ------------------------------------------------------------- analysis
    def most_similar(self, token: int, topk: int = 5) -> np.ndarray:
        emb = self.table_in.get()
        v = emb[token] / (np.linalg.norm(emb[token]) + 1e-8)
        norms = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-8)
        sims = norms @ v
        sims[token] = -np.inf
        return np.argsort(-sims)[:topk]
