"""Workload-skew benchmark worker (bench.py ``bench_skew``; ``make
skew-demo`` drives it too — docs/observability.md, workload plane).

Run as ``python skew_bench_worker.py <machine_file> <rank> [nclients]
[rows] [reqs] [nan]``: two of these form a native epoll fleet with two
row-sharded MatrixTables; rank 1 then drives an ANONYMOUS client herd
(the serve wire protocol) of row-Gets against rank 0's reactor in three
phases:

- **zipf phase** — row ids drawn zipf(1.0) over rank 0's shard (the
  planted hot keys are the distribution head: ids 0..4).  The scraped
  ``"hotkeys"`` report must surface them in the top-K and show a
  bucket-load skew ratio well above 1.
- **uniform phase** — the same request count with uniform ids on the
  second table: its skew ratio must collapse toward 1 (the control).
- **overhead phase** — the zipf herd re-run with the workload
  accounting DISARMED on rank 0 (coordinated through a KV flag;
  ``MV_SetHotKeyTracking``): ``hotkey_track_overhead_pct`` is the
  armed-vs-disarmed QPS delta — the acceptance bar says the sketches
  cost < 2% of serve throughput.

``nan=1`` (the demo mode) finishes with rank 0 blocking-adding a
NaN-poisoned row delta to a scratch table: the update-health sentinel
must dump ``blackbox_rank0.json`` naming the table.

Rank 1 prints the measured keys; both ranks print ``SKEW_BENCH_OK``.
"""

import os
import selectors
import socket
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from multiverso_tpu import native as nat  # noqa: E402
from multiverso_tpu.ops.introspect import OpsClient  # noqa: E402
from multiverso_tpu.serve.wire import (FrameDecoder, MSG,  # noqa: E402
                                       pack_frame, unpack_frame)

COLS = 8
HOT_KEYS = 5          # planted head of the zipf distribution: ids 0..4
IDS_PER_REQ = 8
WINDOW = 8            # outstanding requests while pacing the herd


def _zipf_ids(n, k, rng):
    """n draws from zipf(1.0) over [0, k) — p(i) ∝ 1/(i+1)."""
    p = 1.0 / np.arange(1, k + 1, dtype=np.float64)
    p /= p.sum()
    return rng.choice(k, size=n, p=p).astype(np.int32)


def _raise_fd_limit(need):
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < need:
        resource.setrlimit(resource.RLIMIT_NOFILE,
                           (min(max(need, soft), hard), hard))


class Herd:
    """nclients anonymous sockets driving paced row-Gets (the
    fanin_bench_worker pacing discipline: WINDOW outstanding)."""

    def __init__(self, endpoint, nclients):
        host, port = endpoint.rsplit(":", 1)
        _raise_fd_limit(nclients + 256)
        self.sel = selectors.DefaultSelector()
        self.socks = []
        for i in range(nclients):
            s = socket.socket()
            s.connect((host, int(port)))
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.setblocking(False)
            self.sel.register(s, selectors.EVENT_READ,
                              {"dec": FrameDecoder(), "id": i})
            self.socks.append(s)
        self._mid = 0

    def run_phase(self, table_id, ids, deadline_s=300):
        """Send one row-Get (IDS_PER_REQ ids) per request, paced WINDOW
        outstanding, cycling the id stream; returns (replies, secs)."""
        nreq = len(ids) // IDS_PER_REQ
        got = 0
        t0 = time.perf_counter()
        deadline = time.time() + deadline_s
        for base in range(0, nreq, WINDOW):
            batch = min(WINDOW, nreq - base)
            for j in range(batch):
                s = self.socks[(base + j) % len(self.socks)]
                self._mid += 1
                lo = (base + j) * IDS_PER_REQ
                blob = ids[lo:lo + IDS_PER_REQ].tobytes()
                s.sendall(pack_frame(MSG["RequestGet"], table_id,
                                     self._mid, blobs=[blob],
                                     qos=(0, 60_000_000_000)))
            need = got + batch
            while got < need and time.time() < deadline:
                for key, _ in self.sel.select(timeout=1.0):
                    data = key.data
                    try:
                        chunk = key.fileobj.recv(65536)
                    except BlockingIOError:
                        continue
                    if not chunk:
                        raise RuntimeError(f"conn {data['id']} died")
                    data["dec"].feed(chunk)
                    while True:
                        body = data["dec"].next_frame()
                        if body is None:
                            break
                        reply = unpack_frame(body)
                        assert reply["type_name"] == "ReplyGet", reply
                        got += 1
            if got < need:
                raise RuntimeError(
                    f"herd stalled: {got}/{need} replies")
        return got, time.perf_counter() - t0

    def close(self):
        for s in self.socks:
            self.sel.unregister(s)
            s.close()


def main() -> int:
    mf, rank = sys.argv[1], int(sys.argv[2])
    nclients = int(sys.argv[3]) if len(sys.argv) > 3 else 256
    rows = int(sys.argv[4]) if len(sys.argv) > 4 else 2048
    reqs = int(sys.argv[5]) if len(sys.argv) > 5 else 512
    nan = int(sys.argv[6]) if len(sys.argv) > 6 else 0
    trace_dir = os.environ.get("MVTPU_SKEW_TRACE_DIR", "")
    extra = [f"-trace_dir={trace_dir}"] if trace_dir else []
    rt = nat.NativeRuntime(args=[
        f"-machine_file={mf}", f"-rank={rank}", "-log_level=error",
        "-rpc_timeout_ms=60000", "-barrier_timeout_ms=120000",
        "-hotkey_topk=64", *extra])
    assert rt.net_engine() == "epoll", rt.net_engine()
    h_zipf = rt.new_matrix_table(rows, COLS)
    h_uni = rt.new_matrix_table(rows, COLS)
    h_kv = rt.new_kv_table()
    h_nan = rt.new_matrix_table(4, 2)     # NaN-sentinel scratch table
    rt.barrier()

    out = {}
    shard = rows // 2                     # rank 0 owns rows [0, shard)
    if rank == 1:
        eps = [ln.strip() for ln in open(mf) if ln.strip()]
        rng = np.random.RandomState(7)
        zipf_ids = _zipf_ids(reqs * IDS_PER_REQ, shard, rng)
        uni_ids = rng.randint(0, shard,
                              size=reqs * IDS_PER_REQ).astype(np.int32)

        # A few worker-stub gets so the observed-staleness histogram
        # has stamped samples (anonymous clients stamp no version).
        rt.matrix_add_rows(h_zipf, [1], np.ones((1, COLS), np.float32))
        for _ in range(4):
            rt.matrix_get_rows(h_zipf, [0, 1, 2], COLS)

        herd = Herd(eps[0], nclients)
        # A FULL warmup phase first: connections, reactor state, branch
        # predictors and the python client path all settle before either
        # measured phase runs — the armed-vs-disarmed delta must be the
        # sketches, not cold-start order effects.
        herd.run_phase(h_zipf, zipf_ids)
        n_armed, t_armed = herd.run_phase(h_zipf, zipf_ids)
        herd.run_phase(h_uni, uni_ids)

        with OpsClient(eps[0], timeout=30) as c:
            report = {t["id"]: t for t in c.hotkeys()}
        zt, ut = report[h_zipf], report[h_uni]
        out["skew_ratio_zipf"] = zt["skew_ratio"]
        out["skew_ratio_uniform"] = ut["skew_ratio"]
        top = [e["key"] for e in zt["hotkeys"]["topk"]]
        out["hot_expected"] = float(HOT_KEYS)
        out["hot_hits"] = float(
            sum(1 for i in range(HOT_KEYS) if str(i) in top))
        out["staleness_count"] = float(zt["staleness_count"])

        # Overhead A/B: rank 0 disarms, the identical zipf phase reruns.
        # Disarmed runs LAST (warmest), so any residual warmup drift
        # inflates qps_disarmed — the overhead estimate errs high, never
        # flatters the sketches.
        rt.kv_add(h_kv, "disarm", 1.0)
        deadline = time.time() + 60
        while rt.kv_get(h_kv, "disarmed") < 1.0:
            if time.time() > deadline:
                raise RuntimeError("rank 0 never disarmed")
            time.sleep(0.02)
        n_off, t_off = herd.run_phase(h_zipf, zipf_ids)
        herd.close()
        qps_on = n_armed / t_armed
        qps_off = n_off / t_off
        out["skew_qps_armed"] = qps_on
        out["skew_qps_disarmed"] = qps_off
        out["hotkey_track_overhead_pct"] = max(
            0.0, (qps_off - qps_on) / qps_off * 100.0)
        rt.kv_add(h_kv, "herd_done", 1.0)
    else:
        deadline = time.time() + 600
        disarmed = False
        while rt.kv_get(h_kv, "herd_done") < 1.0:
            if time.time() > deadline:
                raise RuntimeError("herd never finished")
            if not disarmed and rt.kv_get(h_kv, "disarm") >= 1.0:
                rt.set_hotkey_tracking(False)
                disarmed = True
                rt.kv_add(h_kv, "disarmed", 1.0)
            time.sleep(0.02)
        rt.set_hotkey_tracking(True)

    rt.barrier()
    if rank == 0 and nan:
        # Update-health sentinel: one NaN-poisoned blocking add to the
        # scratch table (row 0 lives on this rank) must trip the
        # flight recorder and dump blackbox_rank0.json naming it.
        poison = np.full((1, 2), np.nan, np.float32)
        rt.matrix_add_rows(h_nan, [0], poison)
        stats = rt.table_load_stats(h_nan)
        assert stats["nan_count"] >= 1, stats
        out["nan_count"] = float(stats["nan_count"])
        out["nan_table"] = float(h_nan)
    rt.barrier()
    rt.shutdown()
    kv = " ".join(f"{k}={v:.6f}" for k, v in sorted(out.items()))
    print(f"SKEW_BENCH_OK rank={rank} {kv}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
