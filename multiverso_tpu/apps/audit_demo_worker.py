"""Delivery-audit acceptance worker (``make audit-demo``; not a pytest
module — tools/audit_demo.py drives it, docs/observability.md "audit
plane").

Run as ``python audit_demo_worker.py <machine_file> <rank> <mode>
[trace_dir] [extra flags...]``; both ranks print
``AUDIT_DEMO_WORKER_OK`` on success.  Modes:

- ``chaos`` — rank 1 blocking-adds through injected ``fail_send``
  faults (the PR 2 retry harness absorbs every one; the exact table
  value proves zero lost acked adds), then eats exactly two injected
  ``dup`` sends, then an async burst acked by one final blocking add.
  Rank 0 prints the fleet ``"audit"`` books: the auditor must name
  exactly the two dups and no loss.
- ``loss`` — rank 0 arms a one-shot silent ``discard_apply`` (the real
  loss retry cannot absorb); rank 1's async stream leaves a seq hole
  that fires the ``audit_gap`` blackbox past ``-audit_grace_ms``.
- ``plain`` — launched with ``-audit=false``: every frame ships the
  PRE-AUDIT layout (no flag bit), adds still converge exactly, and the
  scraped report says ``armed: false`` — the version-tolerance proof.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np  # noqa: E402

from multiverso_tpu import native as nat  # noqa: E402

SIZE = 64
FAIL_SEND_ADDS = 3
DUP_ADDS = 2
ASYNC_BURST = 6


def main() -> int:
    mf, rank, mode = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    trace_dir = sys.argv[4] if len(sys.argv) > 4 else ""
    extra = sys.argv[5:]
    args = [f"-machine_file={mf}", f"-rank={rank}", "-log_level=error",
            "-rpc_timeout_ms=20000", "-barrier_timeout_ms=60000",
            "-send_retries=3", "-send_backoff_ms=20",
            "-audit_grace_ms=250", *extra]
    if trace_dir:
        args.append(f"-trace_dir={trace_dir}")
    rt = nat.NativeRuntime(args=args)
    h = rt.new_array_table(SIZE)
    rt.barrier()

    delta = np.ones(SIZE, np.float32)
    if rank == 0 and mode == "loss":
        rt.set_fault_seed(11)
        rt.set_fault_n("discard_apply", 1)
    rt.barrier()

    if rank == 1:
        rt.set_fault_seed(7)
        if mode == "chaos":
            for _ in range(FAIL_SEND_ADDS):
                rt.set_fault_n("fail_send", 1)
                rt.array_add(h, delta)
            rt.clear_faults()
            # Exact convergence BEFORE the dup phase: every acked add
            # applied exactly once — retry absorbed the send failures.
            got = rt.array_get(h, SIZE)
            np.testing.assert_allclose(got, float(FAIL_SEND_ADDS))
            assert rt.query_monitor("net.retries") >= FAIL_SEND_ADDS
            print("CHAOS_ADDS_OK", flush=True)
            rt.set_fault_n("dup", DUP_ADDS)
            for _ in range(DUP_ADDS):
                rt.array_add(h, delta)
            rt.clear_faults()
            for _ in range(ASYNC_BURST):
                rt.array_add(h, delta, sync=False)
            rt.array_add(h, delta)     # the ack covers the tail (FIFO)
        elif mode == "loss":
            for _ in range(4):
                rt.array_add(h, delta, sync=False)
            rt.array_get(h, SIZE)      # drain the pipeline
            time.sleep(0.6)            # outlive -audit_grace_ms
        elif mode == "plain":
            for _ in range(3):
                rt.array_add(h, delta)
            got = rt.array_get(h, SIZE)
            np.testing.assert_allclose(got, 3.0)
            rep = rt.audit_report()
            assert rep["armed"] is False, rep
            print("PLAIN_OK", flush=True)
        ledger = rt.audit_report()["tables"][0]["worker"]
        print(f"LEDGER {json.dumps(ledger)}", flush=True)
    rt.barrier()

    if rank == 0:
        print(f"AUDIT_FLEET {rt.ops_fleet_report('audit')}", flush=True)
    rt.barrier()
    rt.shutdown()
    print(f"AUDIT_DEMO_WORKER_OK {rank}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
