"""N-process native-wire LR worker — the measured stand-in for the
reference's ``mpirun -n 8`` logistic-regression baseline.

``BASELINE.md`` action 2 asks for the reference's 8-process MPI LR run
as the north-star denominator; the reference mount stayed empty through
every round, so the reference binary cannot be built.  This worker
reproduces that job's *mechanism* on this repo's own native runtime
(the architecture the reference shares: C++ actor/server processes, a
wire between them, C++ updaters — SURVEY.md §3.4, ref
``Test/test_logreg`` push/pull per batch): each process is a
worker+server rank over TcpNet, pulling the dense weight table through
the C API, computing a softmax-regression gradient on CPU with numpy,
and pushing it back through a blocking Add.  ``bench.py`` aggregates
N ranks into ``lr_native8_samples_per_sec`` and reports the TPU fused
path's speedup over it as ``lr_fused_vs_native8`` — a real
distributed-wire denominator rather than a same-chip loop.

Run: ``python lr_native_worker.py <machine_file> <rank> <steps>
<batch> [codec]`` (spawned by ``bench.py``; stands alone for
debugging).  ``codec`` (default ``raw``) selects the wire payload codec
(docs/wire_compression.md): with ``1bit`` every gradient Add ships as
sign bits + two scales with worker-side error feedback — ~32x fewer
payload bytes for the same training trajectory, which the printed
``loss=`` (final mean cross-entropy on this rank's batch) lets the
bench verify stays within 5% of the raw run.
"""

import os
import sys
import time

# Before ANY multiverso/jax import: this process must not touch the TPU
# the spawning bench run holds (same seam as tests/mp_worker.py).
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402


def main(argv) -> None:
    mf, rank = argv[0], int(argv[1])
    steps, batch = int(argv[2]), int(argv[3])
    codec = argv[4] if len(argv) > 4 else "raw"
    features, classes = 784, 10

    from multiverso_tpu import native as nat

    rt = nat.NativeRuntime(args=[f"-machine_file={mf}", f"-rank={rank}",
                                 "-updater_type=sgd", "-log_level=error",
                                 f"-wire_codec={codec}"])
    n = features * classes
    h = rt.new_array_table(n)
    rt.set_add_option(learning_rate=0.1)

    rng = np.random.default_rng(rank)
    x = rng.standard_normal((batch, features)).astype(np.float32)
    w_plant = rng.standard_normal((features, classes)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[(x @ w_plant).argmax(1)]

    rt.barrier()              # all ranks timed over the same window
    t0 = time.perf_counter()
    for _ in range(steps):
        w = rt.array_get(h, n).reshape(features, classes)
        logits = x @ w
        logits -= logits.max(axis=1, keepdims=True)
        p = np.exp(logits)
        p /= p.sum(axis=1, keepdims=True)
        grad = x.T @ (p - y) / batch
        rt.array_add(h, grad.reshape(-1))
    rt.barrier()              # every rank's adds applied
    dt = time.perf_counter() - t0

    # Final mean cross-entropy on this rank's batch — the convergence
    # ledger the codec comparison reads (equal steps, raw vs 1bit).
    w = rt.array_get(h, n).reshape(features, classes)
    logits = x @ w
    logits -= logits.max(axis=1, keepdims=True)
    p = np.exp(logits)
    p /= p.sum(axis=1, keepdims=True)
    loss = float(-(y * np.log(p + 1e-12)).sum(axis=1).mean())

    print(f"NATIVE_LR_OK rank={rank} dt={dt:.6f} steps={steps} "
          f"batch={batch} loss={loss:.6f} codec={codec}", flush=True)
    rt.shutdown()


if __name__ == "__main__":
    main(sys.argv[1:])
