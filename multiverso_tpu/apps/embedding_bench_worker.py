"""Sparse-embedding serving benchmark worker (bench.py
``bench_embedding``; ``make embedding-demo`` drives it too —
docs/embedding.md).

Run as ``python embedding_bench_worker.py <machine_file> <rank> [rows]
[reqs] [demo]``: two of these form a native epoll fleet holding one
row-sharded embedding table (``rows`` x 32, shard-faithful scaled-down
stand-in for the O(10^7)-row recommender table — rank 0 owns the zipf
head, so the hot path is genuinely remote from the driving rank).
Rank 1 then measures the three serving tiers on an identical
zipf-hot-head row-get stream:

- **cold** — serve cache off, replica off: every lookup pays the full
  wire round trip (the PR 4 carve-out this tentpole closes);
- **row-cached** — :class:`~multiverso_tpu.serve.client.ServeClient`
  with the row-granular cache armed: each hot row is its own versioned
  entry, repeat lookups never touch the wire;
- **replica-hit** — the native hot-key replica armed
  (``-hotkey_replica``): the server pushes its SpaceSaving top-K rows
  and the worker stub serves row gets from the side table in one
  native call — no wire, no Python cache walk.

Plus: the full-zipf(1.0) tail latency through the row-cached client
(``zipf_p99_ms``), bytes/lookup for cold-tail (all-zero) rows with the
sparse reply codec off vs on, and the multi-shard borrowed-vs-staged
``AddRows`` issue-cost A/B (``addrows_borrow_speedup`` — the per-rank
staging copies the borrowed run-iovec path removes).

``demo=1`` adds the correctness assertions ``make embedding-demo``
reports: replica hits > 0, zero stale reads at staleness 0 after a
server-side add (the updated value must be observed within one
replica lease), and an anonymous-client replica pull that surfaces the
planted hot ids.

Rank 1 prints the measured keys; both ranks print ``EMBED_BENCH_OK``.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from multiverso_tpu import config, native as nat  # noqa: E402
from multiverso_tpu.apps.dlrm import zipf_ids  # noqa: E402
from multiverso_tpu.serve.client import ServeClient  # noqa: E402
from multiverso_tpu.serve.wire import AnonServeClient  # noqa: E402

COLS = 32
IDS_PER_REQ = 8
HOT_K = 32            # the measured hot head (inside the top-K push)
TOPK = 64             # -hotkey_topk: what the server pushes


def _pcts(lat_s):
    lat = np.sort(np.asarray(lat_s, np.float64)) * 1e3
    return (float(lat[int(0.50 * (lat.size - 1))]),
            float(lat[int(0.95 * (lat.size - 1))]),
            float(lat[int(0.99 * (lat.size - 1))]))


def _measure(reqs, fn):
    """Per-request latencies of ``fn(i)`` over ``reqs`` calls."""
    lat = []
    t0 = time.perf_counter()
    for i in range(reqs):
        t = time.perf_counter()
        fn(i)
        lat.append(time.perf_counter() - t)
    wall = time.perf_counter() - t0
    return lat, reqs / wall


def main() -> int:
    mf, rank = sys.argv[1], int(sys.argv[2])
    rows = int(sys.argv[3]) if len(sys.argv) > 3 else 1 << 16
    reqs = int(sys.argv[4]) if len(sys.argv) > 4 else 512
    demo = int(sys.argv[5]) if len(sys.argv) > 5 else 0
    rt = nat.NativeRuntime(args=[
        f"-machine_file={mf}", f"-rank={rank}", "-log_level=error",
        "-rpc_timeout_ms=60000", "-barrier_timeout_ms=120000",
        f"-hotkey_topk={TOPK}", "-replica_lease_ms=1000"])
    assert rt.net_engine() == "epoll", rt.net_engine()
    h = rt.new_matrix_table(rows, COLS)
    h_kv = rt.new_kv_table()
    rt.barrier()

    out = {}
    shard = rows // 2                 # rank 0 owns rows [0, shard)
    if rank == 1:
        rng = np.random.RandomState(11)
        # Seed the hot head with nonzero values (blocking: visible
        # before any measured phase) and teach the server's SpaceSaving
        # sketch who is hot — the cold phase's traffic doubles as the
        # tracker warmup the replica push feeds on.
        rt.matrix_add_rows(
            h, np.arange(HOT_K, dtype=np.int32),
            np.ones((HOT_K, COLS), np.float32))
        hot_stream = zipf_ids(reqs * IDS_PER_REQ, HOT_K,
                              rng).astype(np.int32)
        full_stream = zipf_ids(reqs * IDS_PER_REQ, shard,
                               rng).astype(np.int32)

        def req_ids(stream, i):
            lo = (i % reqs) * IDS_PER_REQ
            return stream[lo:lo + IDS_PER_REQ]

        # --- phase A: cold — cache off, replica off, every get wire ---
        # window_us=0 on BOTH clients: a sequential driver's solo
        # requests must not pay the coalescing window as fake latency
        # (the speedup must come from the cache, not a handicap).
        cold_sc = ServeClient(rt, cache_entries=0, window_us=0.0)
        lat, qps = _measure(reqs, lambda i: cold_sc.matrix_get_rows(
            h, req_ids(hot_stream, i), COLS))
        p50, p95, p99 = _pcts(lat)
        out.update(cold_p50_ms=p50, cold_p95_ms=p95, cold_p99_ms=p99,
                   cold_qps=qps)

        # --- phase B: row-granular cache (docs/embedding.md) ----------
        config.set_flag("serve_row_cache", True)
        sc = ServeClient(rt, cache_entries=8192, max_staleness=0,
                         lease_ms=5000.0, window_us=0.0)
        for i in range(reqs):          # warm: every hot row cached once
            sc.matrix_get_rows(h, req_ids(hot_stream, i), COLS)
        lat, qps = _measure(reqs, lambda i: sc.matrix_get_rows(
            h, req_ids(hot_stream, i), COLS))
        p50, p95, p99 = _pcts(lat)
        out.update(rowcache_p50_ms=p50, rowcache_p99_ms=p99,
                   rowcache_qps=qps)
        out["rowcache_vs_cold_p50"] = out["cold_p50_ms"] / p50

        # Full-zipf(1.0) tail through the row-cached client: the
        # realistic serving mix (head hits, tail misses).
        lat, qps = _measure(reqs, lambda i: sc.matrix_get_rows(
            h, req_ids(full_stream, i), COLS))
        _, _, p99 = _pcts(lat)
        out.update(zipf_p99_ms=p99, zipf_qps=qps)

        # --- phase C: native hot-key replica --------------------------
        rt.set_hotkey_replica(True)
        rt.replica_refresh(h)
        base = rt.replica_stats(h)
        # A serving tier pins its request/reply buffers and calls the C
        # API directly (the replica's real consumers are native
        # frontends — the Lua binding, a C++ inference tier); the
        # Python wrapper's per-call argument validation (~7 us) is not
        # what this phase measures.  Each request copies its 8 ids into
        # the pinned id buffer, then one MV_GetMatrixTableByRows call
        # serves every row from the worker-local replica — zero wire.
        import ctypes

        ids_buf = np.zeros(IDS_PER_REQ, np.int32)
        reply_buf = np.zeros(IDS_PER_REQ * COLS, np.float32)
        fp = reply_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        ip = ids_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

        def replica_req(i):
            np.copyto(ids_buf, req_ids(hot_stream, i))
            rc = rt.lib.MV_GetMatrixTableByRows(h, fp, ip, IDS_PER_REQ,
                                                COLS)
            assert rc == 0, rc

        lat, qps = _measure(reqs, replica_req)
        p50, _, p99 = _pcts(lat)
        stats = rt.replica_stats(h)
        out.update(replica_p50_ms=p50, replica_p99_ms=p99,
                   replica_qps=qps,
                   replica_hits=float(stats["hits"] - base["hits"]),
                   replica_pushes=float(stats["pushes"]))
        out["replica_vs_rowcache_p50"] = out["rowcache_p50_ms"] / p50
        out["replica_hit_rate"] = (
            (stats["hits"] - base["hits"])
            / max(1.0, float(stats["hits"] - base["hits"]
                             + stats["misses"] - base["misses"])))
        rt.set_hotkey_replica(False)

        # --- phase D: bytes/lookup, sparse reply codec off vs on ------
        # Cold-tail ids: untrained (all-zero) rows — the reply payload
        # the lossless sparse codec collapses.
        tail = (shard // 2 + rng.randint(
            0, shard // 2, size=64 * IDS_PER_REQ)).astype(np.int32)
        for codec, key in (("raw", "bytes_per_lookup_raw"),
                           ("sparse", "bytes_per_lookup_sparse")):
            rt.set_table_codec(h, codec)
            before = rt.wire_stats()
            for i in range(64):
                lo = i * IDS_PER_REQ
                cold_sc.matrix_get_rows(h, tail[lo:lo + IDS_PER_REQ],
                                        COLS)
            after = rt.wire_stats()
            moved = (after["sent_bytes"] - before["sent_bytes"]
                     + after["recv_bytes"] - before["recv_bytes"])
            out[key] = moved / (64.0 * IDS_PER_REQ)
        rt.set_table_codec(h, "raw")
        out["sparse_bytes_ratio"] = (out["bytes_per_lookup_raw"]
                                     / max(out["bytes_per_lookup_sparse"],
                                           1e-9))

        # --- phase E: multi-shard borrowed vs staged AddRows ----------
        # Issue-cost A/B (docs/embedding.md): the borrowed run-iovec
        # path removes the per-rank staging copy AND the owning-Blob
        # copy from the caller's async-add path; ids span BOTH shards
        # so the multi-shard plan (not PR 9's single-shard fast path)
        # is what runs.  Timed: N async issues; the barrier drains the
        # wire between rounds (untimed) so rounds don't overlap.
        # 2048 rows x 32 cols = 256 KiB per add: big enough that the
        # staging path's two payload copies (per-rank vector + owning
        # Blob) dominate the fixed per-call overhead both paths share.
        K = min(2048, max(256, rows // 4))
        adds = 50
        # Skip rows 0/1: the demo's staleness probe needs the hot head
        # untouched by this phase's noise adds.  SORTED ids — the
        # embedding-friendly batch layout (pipelines already sort for
        # the dedup/segment-sum) — so each shard's rows form ONE
        # contiguous caller-order run and the borrowed path ships one
        # iovec per shard; unsorted hostile interleavings fall back to
        # staging (covered by the native unit, not measured here).
        ids = np.sort(2 + rng.randint(0, rows - 2, size=K)).astype(
            np.int32)
        arena = rt.arena()
        buf = arena.alloc((K, COLS))
        buf[:] = 0.001
        heap = np.full((K, COLS), 0.001, np.float32)

        def time_adds(borrowed):
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(adds):
                    rt.matrix_add_rows(h, ids,
                                       buf if borrowed else heap,
                                       sync=False, borrowed=borrowed)
                best = min(best, time.perf_counter() - t0)
                # Drain before the next round: one blocking get per
                # shard rides the per-connection FIFO BEHIND the async
                # adds (rank 0 is in its poll loop — a barrier here
                # would hang).
                rt.matrix_get_rows(h, [0, shard], COLS)
            return best

        t_staged = time_adds(False)
        t_borrow = time_adds(True)
        arena.release(buf)
        out["addrows_staged_ms"] = t_staged * 1e3
        out["addrows_borrowed_ms"] = t_borrow * 1e3
        out["addrows_borrow_speedup"] = t_staged / t_borrow

        if demo:
            assert out["replica_hits"] > 0, out
            # Anonymous-client replica pull: the planted hot ids must
            # surface from rank 0's shard push.
            eps = [ln.strip() for ln in open(mf) if ln.strip()]
            with AnonServeClient(eps[0], timeout=30) as anon:
                rep = anon.get_replica(h)
            hot_in_push = sum(1 for i in range(8) if i in rep)
            out["anon_replica_hot"] = float(hot_in_push)
            assert hot_in_push > 0, sorted(rep)[:10]
            # Staleness-0 cross-rank freshness: rank 0 bumps hot row 1
            # server-side; within one replica lease rank 1 must observe
            # the new value (zero stale reads at staleness 0).
            rt.set_hotkey_replica(True)
            rt.kv_add(h_kv, "poke", 1.0)
            deadline = time.time() + 60
            while rt.kv_get(h_kv, "poked") < 1.0:
                if time.time() > deadline:
                    raise RuntimeError("rank 0 never poked")
                time.sleep(0.02)
            time.sleep(1.2)           # one replica lease (1000 ms)
            fresh = rt.matrix_get_rows(h, [1], COLS)
            assert fresh[0, 0] == 101.0, fresh[0, :4]
            out["stale_reads"] = 0.0
            rt.set_hotkey_replica(False)
        rt.kv_add(h_kv, "done", 1.0)
    else:
        deadline = time.time() + 900
        poked = False
        while rt.kv_get(h_kv, "done") < 1.0:
            if time.time() > deadline:
                raise RuntimeError("driver never finished")
            if demo and not poked and rt.kv_get(h_kv, "poke") >= 1.0:
                # Server-side add from the OTHER rank: row 1 jumps to
                # 101 (1 from seeding + 100 here).
                rt.matrix_add_rows(
                    h, [1], np.full((1, COLS), 100.0, np.float32))
                rt.kv_add(h_kv, "poked", 1.0)
                poked = True
            time.sleep(0.02)

    rt.barrier()
    rt.shutdown()
    kv = " ".join(f"{k}={v:.6f}" for k, v in sorted(out.items()))
    print(f"EMBED_BENCH_OK rank={rank} {kv}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
