"""Data-parallel ResNet-20 / CIFAR-10 — the torch-binding flagship.

Reference (SURVEY.md §2.33, ``binding/lua/`` docs): the Lua/Torch binding's
documented example is ``fb.resnet.torch`` ResNet-20 on CIFAR-10 made
data-parallel by syncing parameters through an ArrayTable each iteration.

Here the same app runs on CPU torch (the image's build) through
``ext.torch_ext.TorchParamManager``: N workers train on disjoint shards
and delta-sync through one table per step.  CIFAR-10 itself cannot be
downloaded in this sandbox, so ``synthetic_cifar`` generates CIFAR-shaped
data with planted class structure; swap in real loaders outside.

Torch is imported lazily — importing this module without torch installed
raises only when the app is actually constructed.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..ext.torch_ext import TorchParamManager

__all__ = ["ResNet20DataParallel", "build_resnet20", "synthetic_cifar"]


def synthetic_cifar(num_samples: int, num_classes: int = 10, seed: int = 0
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """CIFAR-shaped [N,3,32,32] data with class-dependent channel structure."""
    rng = np.random.RandomState(seed)
    y = rng.randint(num_classes, size=num_samples).astype(np.int64)
    x = rng.randn(num_samples, 3, 32, 32).astype(np.float32)
    # plant a per-class mean pattern so a small net can separate classes
    patterns = rng.randn(num_classes, 3, 8, 8).astype(np.float32)
    up = np.kron(patterns, np.ones((1, 1, 4, 4), np.float32))
    x += 2.0 * up[y]
    return x, y


def build_resnet20(num_classes: int = 10):
    """ResNet-20 (CIFAR variant: 3 stages x 3 basic blocks, 16/32/64)."""
    import torch
    import torch.nn as nn

    class BasicBlock(nn.Module):
        def __init__(self, cin, cout, stride=1):
            super().__init__()
            self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
            self.bn1 = nn.BatchNorm2d(cout)
            self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
            self.bn2 = nn.BatchNorm2d(cout)
            self.short = (nn.Sequential() if stride == 1 and cin == cout else
                          nn.Sequential(
                              nn.Conv2d(cin, cout, 1, stride, bias=False),
                              nn.BatchNorm2d(cout)))
            self.relu = nn.ReLU(inplace=True)

        def forward(self, x):
            out = self.relu(self.bn1(self.conv1(x)))
            out = self.bn2(self.conv2(out))
            return self.relu(out + self.short(x))

    def stage(cin, cout, n, stride):
        blocks: List[nn.Module] = [BasicBlock(cin, cout, stride)]
        blocks += [BasicBlock(cout, cout) for _ in range(n - 1)]
        return nn.Sequential(*blocks)

    return nn.Sequential(
        nn.Conv2d(3, 16, 3, 1, 1, bias=False), nn.BatchNorm2d(16),
        nn.ReLU(inplace=True),
        stage(16, 16, 3, 1), stage(16, 32, 3, 2), stage(32, 64, 3, 2),
        nn.AdaptiveAvgPool2d(1), nn.Flatten(), nn.Linear(64, num_classes))


class ResNet20DataParallel:
    """N simulated torch workers sharing one parameter table.

    The reference's multi-process layout collapses to in-process workers
    for the degenerate test mode (SURVEY.md §4); on a real deployment each
    worker is a host process and the table rides the TPU mesh.
    """

    def __init__(self, num_workers: int = 2, lr: float = 0.1,
                 num_classes: int = 10, seed: int = 0):
        import torch

        torch.manual_seed(seed)
        self.num_workers = num_workers
        self.nets = []
        self.opts = []
        for _ in range(num_workers):
            torch.manual_seed(seed)  # identical init across workers
            net = build_resnet20(num_classes)
            self.nets.append(net)
            self.opts.append(torch.optim.SGD(net.parameters(), lr=lr,
                                             momentum=0.9))
        self.mgrs = [TorchParamManager(self.nets[0], name="resnet20",
                                       peers=num_workers)]
        for net in self.nets[1:]:
            self.mgrs.append(
                TorchParamManager(net, table=self.mgrs[0].table,
                                  peers=num_workers))
        self.loss_fn = torch.nn.CrossEntropyLoss()

    def train_epoch(self, x: np.ndarray, y: np.ndarray,
                    batch_size: int = 64) -> float:
        import torch

        last = 0.0
        n = x.shape[0]
        for i in range(0, n - batch_size + 1, batch_size):
            for wid in range(self.num_workers):
                # shard the batch across workers
                xb = torch.from_numpy(
                    x[i:i + batch_size][wid::self.num_workers])
                yb = torch.from_numpy(
                    y[i:i + batch_size][wid::self.num_workers])
                self.opts[wid].zero_grad()
                loss = self.loss_fn(self.nets[wid](xb), yb)
                loss.backward()
                self.opts[wid].step()
                last = float(loss)
            for m in self.mgrs:
                m.sync_all_param()
        return last

    def accuracy(self, x: np.ndarray, y: np.ndarray) -> float:
        import torch

        net = self.nets[0]
        net.eval()  # BatchNorm must use running stats, not the eval batch
        try:
            with torch.no_grad():
                logits = net(torch.from_numpy(x))
                return float((logits.argmax(1).numpy() == y).mean())
        finally:
            net.train()
