"""Distributed multi-sense word embedding (skip-gram mixture).

Reference (SURVEY.md §2.36, ``Microsoft/distributed_skipgram_mixture``
linking libmultiverso): each word owns S sense vectors plus a sense-prior
vector, all parameter-server-resident; workers pull the rows a batch
touches, run an EM step — E: posterior responsibility of each sense given
the occurrence's WHOLE context window (per-pair posteriors are too weak to
break sense symmetry); M: responsibility-weighted SGNS gradients and prior
counts — and push row deltas back.

TPU-native: three row-sharded tables —

- ``table_sense`` [V·S, D]: sense (input) vectors; word w's senses live in
  rows ``w·S … w·S+S-1`` (contiguous, so one word's senses land on one
  shard the way the reference keeps them on one server);
- ``table_out`` [V, D]: context (output) vectors, single-sense as in the
  reference;
- ``table_prior`` [V, S]: Dirichlet-style responsibility counts (plain-add
  updater — counts accumulate, they are not gradients).

Batches are whole occurrences: center [B], context bag [B, C] + validity
mask (static C = 2·window, padded), negatives [B, K].  The fused step
compiles the pull → E-step → weighted-grad → push round trip into one XLA
program: gathers and scatter-applies cross shards over ICI,
responsibilities run in float32 under ``stop_gradient`` (the E-step is
not differentiated through — exactly EM).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import context as core_context
from ..tables import MatrixTable
from ..updaters import AddOption

__all__ = ["SkipGramMixture", "synthetic_homonym_corpus"]


def synthetic_homonym_corpus(num_tokens: int, vocab_size: int,
                             homonym: int = 0, groups=((1, 10), (11, 20)),
                             seed: int = 0) -> np.ndarray:
    """Token stream where ``homonym`` appears in two disjoint context
    worlds (group-A neighbours vs group-B neighbours) — the canonical
    two-sense test corpus.  Other tokens are drawn uniformly inside their
    own group, so each has one sense."""

    hi_max = max(hi for _, hi in groups)
    if hi_max >= vocab_size:
        raise ValueError(
            f"group token {hi_max} >= vocab_size {vocab_size}; wrapping "
            "would alias group tokens onto other ids (even the homonym)")
    rng = np.random.RandomState(seed)
    out = np.empty(num_tokens, np.int64)
    i = 0
    while i < num_tokens:
        lo, hi = groups[rng.randint(len(groups))]
        run = min(rng.randint(4, 9), num_tokens - i)
        seg = rng.randint(lo, hi + 1, size=run)
        seg[rng.randint(run)] = homonym       # plant the homonym mid-run
        out[i:i + run] = seg
        i += run
    return out.astype(np.int32)


def _mixture_stats(vs, uc, un, mask, log_prior):
    """E-step over a context bag.

    ``vs`` [B,S,D] sense vectors, ``uc`` [B,C,D] context bag, ``un``
    [B,K,D] negatives, ``mask`` [B,C] validity.  Returns (resp [B,S] f32
    stop-gradiented, loglik [B,S] f32).  Float32 throughout — posterior
    odds underflow in bf16.
    """
    pos = jnp.einsum("bsd,bcd->bsc", vs, uc).astype(jnp.float32)
    neg = jnp.einsum("bsd,bkd->bsk", vs, un).astype(jnp.float32)
    loglik = (jnp.sum(jax.nn.log_sigmoid(pos)
                      * mask.astype(jnp.float32)[:, None, :], axis=-1)
              + jnp.sum(jax.nn.log_sigmoid(-neg), axis=-1))
    resp = jax.nn.softmax(loglik + log_prior, axis=-1)
    return jax.lax.stop_gradient(resp), loglik


def _weighted_sgns_loss(vs, uc, un, mask, resp):
    """M-step objective: responsibility-weighted SGNS loss (mean/batch)."""
    _, loglik = _mixture_stats(vs, uc, un, mask, jnp.zeros(resp.shape))
    return -jnp.sum(resp * loglik) / vs.shape[0]


class SkipGramMixture:
    """Multi-sense word2vec over sense/context/prior MatrixTables."""

    def __init__(self, vocab_size: int, dim: int, senses: int = 2,
                 learning_rate: float = 0.05,
                 negatives: int = 5,
                 window: int = 5,
                 updater_type: str = "sgd",
                 name: str = "sgmix",
                 seed: int = 0):
        self.vocab_size = int(vocab_size)
        self.dim = int(dim)
        self.senses = int(senses)
        self.negatives = int(negatives)
        self.window = int(window)
        self.option = AddOption(learning_rate=learning_rate)
        rng = np.random.RandomState(seed)
        # Senses must start apart — identical init keeps responsibilities
        # symmetric forever (EM's classic degenerate fixed point).
        init_sense = (rng.randn(vocab_size * senses, dim)
                      / np.sqrt(dim)).astype(np.float32)
        self.table_sense = MatrixTable(vocab_size * senses, dim,
                                       init=init_sense,
                                       updater_type=updater_type,
                                       name=f"{name}_sense",
                                       default_option=self.option)
        # Output vectors start random too (word2vec's zero init is a
        # symmetric EM fixed point here: zero scores → uniform posteriors
        # → identical sense gradients, forever).
        init_out = (rng.randn(vocab_size, dim)
                    / np.sqrt(dim)).astype(np.float32)
        self.table_out = MatrixTable(vocab_size, dim, init=init_out,
                                     updater_type=updater_type,
                                     name=f"{name}_out",
                                     default_option=self.option)
        # Dirichlet(1) prior counts; plain add (counts, not gradients).
        self.table_prior = MatrixTable(vocab_size, senses,
                                       init=np.ones((vocab_size, senses),
                                                    np.float32),
                                       updater_type="default",
                                       name=f"{name}_prior")
        self._fused_cache = {}

    # ------------------------------------------------------------- batching
    @property
    def bag_width(self) -> int:
        return 2 * self.window

    def batches(self, corpus: np.ndarray, batch_size: int, seed: int = 0):
        """Whole-occurrence examples, static shapes: center [B], context
        bag [B, C] (C = 2·window), mask [B, C], negatives [B, K].

        Padding slots carry ``vocab_size`` — past the visible rows, so
        their (zero-masked) scatter lands in the table's invisible padded
        region instead of touching word 0's state under a non-linear
        updater."""
        rng = np.random.RandomState(seed)
        n = corpus.shape[0]
        C = self.bag_width
        cs, bags, masks = [], [], []
        for i in range(n):
            w = 1 + rng.randint(self.window)
            ctx = np.concatenate([corpus[max(0, i - w):i],
                                  corpus[i + 1:min(n, i + w + 1)]])
            bag = np.full(C, self.vocab_size, np.int32)
            m = np.zeros(C, bool)
            bag[:ctx.shape[0]] = ctx
            m[:ctx.shape[0]] = True
            cs.append(corpus[i]); bags.append(bag); masks.append(m)
            if len(cs) == batch_size:
                neg = rng.randint(self.vocab_size,
                                  size=(batch_size, self.negatives)
                                  ).astype(np.int32)
                yield (np.asarray(cs, np.int32), np.stack(bags),
                       np.stack(masks), neg)
                cs, bags, masks = [], [], []

    def _sense_rows(self, centers: np.ndarray) -> np.ndarray:
        """[B] word ids → [B·S] sense-row ids (w·S + s)."""
        return (centers.astype(np.int64)[:, None] * self.senses
                + np.arange(self.senses)).reshape(-1)

    # ------------------------------------------------ parity push-pull path
    def train_batch(self, centers: np.ndarray, bags: np.ndarray,
                    mask: np.ndarray, negatives: np.ndarray) -> None:
        """Reference loop body: Get rows → EM step → Add row deltas."""
        B, K = negatives.shape
        C = bags.shape[1]
        S, D = self.senses, self.dim
        sense_rows = self._sense_rows(centers)
        vs = jnp.asarray(self.table_sense.get_rows(sense_rows)
                         ).reshape(B, S, D)
        out_rows = np.concatenate([bags.reshape(-1), negatives.reshape(-1)])
        out_emb = self.table_out.get_rows(out_rows)
        uc = jnp.asarray(out_emb[:B * C]).reshape(B, C, D)
        un = jnp.asarray(out_emb[B * C:]).reshape(B, K, D)
        prior = jnp.asarray(self.table_prior.get_rows(centers))
        mask_j = jnp.asarray(mask)

        log_prior = jnp.log(prior / jnp.sum(prior, -1, keepdims=True))
        resp, _ = _mixture_stats(vs, uc, un, mask_j, log_prior)
        dvs, duc, dun = jax.grad(_weighted_sgns_loss, argnums=(0, 1, 2))(
            vs, uc, un, mask_j, resp)

        self.table_sense.add_rows(sense_rows,
                                  np.asarray(dvs).reshape(B * S, D),
                                  option=self.option)
        self.table_out.add_rows(
            out_rows,
            np.concatenate([np.asarray(duc).reshape(B * C, D),
                            np.asarray(dun).reshape(B * K, D)]),
            option=self.option)
        self.table_prior.add_rows(centers, np.asarray(resp))

    # ------------------------------------------------------ fused SPMD path
    def make_fused_step(self, batch_axis: str = "worker"):
        """One XLA program: gathers, E-step, weighted grads, scatter-apply.

        Returns ``step(ds, ss, do, so, dp, sp_, c, bags, mask, neg) ->
        (ds, ss, do, so, dp, sp_, loss)`` over (sense, out, prior) table
        raw values, and the index placer."""
        cached = self._fused_cache.get(batch_axis)
        if cached is not None:
            return cached
        ctx = core_context.get_context()
        from ..parallel.sharding import batch_placer
        _, place = batch_placer(ctx.mesh, batch_axis, dtype=jnp.int32)
        from ..updaters.base import scatter_apply

        upd_sense = self.table_sense.updater
        upd_out = self.table_out.updater
        upd_prior = self.table_prior.updater
        opt = self.option
        S, D = self.senses, self.dim

        @partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
        def step(ds, ss, do, so, dp, sp_, c, bags, mask, neg):
            B, K = neg.shape
            C = bags.shape[1]
            sense_rows = (c[:, None] * S + jnp.arange(S)).reshape(-1)
            vs = ds[sense_rows].reshape(B, S, D)
            uc = do[bags.reshape(-1)].reshape(B, C, D)
            un = do[neg.reshape(-1)].reshape(B, K, D)
            prior = dp[c]
            log_prior = jnp.log(prior / jnp.sum(prior, -1, keepdims=True))
            resp, _ = _mixture_stats(vs, uc, un, mask, log_prior)
            loss, grads = jax.value_and_grad(
                _weighted_sgns_loss, argnums=(0, 1, 2))(vs, uc, un, mask,
                                                        resp)
            dvs, duc, dun = grads
            ds, ss = scatter_apply(upd_sense, ds, ss, sense_rows,
                                   dvs.reshape(B * S, D), opt)
            out_rows = jnp.concatenate([bags.reshape(-1), neg.reshape(-1)])
            out_delta = jnp.concatenate([duc.reshape(B * C, D),
                                         dun.reshape(B * K, D)])
            do, so = scatter_apply(upd_out, do, so, out_rows, out_delta,
                                   opt)
            dp, sp_ = scatter_apply(upd_prior, dp, sp_, c, resp,
                                    self.table_prior.default_option)
            return ds, ss, do, so, dp, sp_, loss

        self._fused_cache[batch_axis] = (step, place)
        return step, place

    def train_epoch_fused(self, corpus: np.ndarray, batch_size: int,
                          seed: int = 0) -> Tuple[int, float]:
        step, place = self.make_fused_step()
        ds, ss = self.table_sense.raw_value()
        do, so = self.table_out.raw_value()
        dp, sp_ = self.table_prior.raw_value()
        loss = jnp.zeros(())
        steps = 0
        for c, bags, mask, neg in self.batches(corpus, batch_size,
                                               seed=seed):
            ds, ss, do, so, dp, sp_, loss = step(
                ds, ss, do, so, dp, sp_, place(c), place(bags),
                place(mask.astype(np.int32)).astype(bool), place(neg))
            steps += 1
        if steps == 0:
            raise ValueError(
                f"corpus of {corpus.shape[0]} tokens produced no full "
                f"batch of {batch_size} occurrences")
        self.table_sense.raw_assign(ds, ss)
        self.table_out.raw_assign(do, so)
        self.table_prior.raw_assign(dp, sp_)
        return steps, float(loss)

    # ------------------------------------------------------------- analysis
    def sense_priors(self, word: int) -> np.ndarray:
        """Normalized sense probabilities for ``word``."""
        counts = self.table_prior.get_rows(np.asarray([word]))[0]
        return counts / counts.sum()

    def sense_posterior(self, word: int, context: np.ndarray) -> np.ndarray:
        """P(sense | word, bag-of-context) — the E-step for one example."""
        context = np.asarray(context, np.int64)
        vs = self.table_sense.get_rows(self._sense_rows(
            np.asarray([word])))                       # [S, D]
        uc = self.table_out.get_rows(context)          # [C, D]
        nll = np.log1p(np.exp(-(vs @ uc.T))).sum(axis=1)  # -Σ log σ(s·c)
        logp = np.log(self.sense_priors(word) + 1e-12) - nll
        logp -= logp.max()
        p = np.exp(logp)
        return p / p.sum()

    def sense_vector(self, word: int, sense: int) -> np.ndarray:
        return self.table_sense.get_rows(
            np.asarray([word * self.senses + sense]))[0]
