"""Serve-tier fan-in benchmark worker (bench.py ``bench_serve_fanin``;
``make fanin-demo`` drives it too).

Run as ``python fanin_bench_worker.py <machine_file> <rank> [nclients]
[inflight_max] [chaos] [mode] [engine]``: two of these form a native
reactor fleet (``engine`` defaults to epoll; ``uring`` runs the same
protocol through the io_uring engine); rank 1 then drives ``nclients``
ANONYMOUS raw sockets (the serve wire protocol, ``serve/wire.py``)
against rank 0's reactor:

- **latency phase** — every client sends one header-only version probe,
  paced 8-outstanding so the p50/p99 measure the service path, not the
  self-inflicted queue;
- **overload phase** — every client fires a shard Get simultaneously;
  with ``-server_inflight_max=<inflight_max>`` the backlog trips the
  shed gate and the busy fraction is the measured shed rate.

``chaos=1`` (the demo mode) additionally has rank 0 run blocking adds
under injected send faults WHILE the herd hammers it — the PR 2 retry
harness must land every add exactly once (zero lost adds), asserted
against the final table value.

``mode=ops`` (bench.py ``bench_ops``, docs/observability.md) runs the
latency phase TWICE — plain, then with a concurrent anonymous scraper
polling in-band ``OpsQuery(metrics)`` as fast as replies return — and
reports ``ops_scrape_p50_ms``/``ops_scrape_p99_ms`` (scrape latency
under the fan-in load) plus ``ops_overhead_pct``: the serve-probe QPS
the live scrape path cost, proving introspection is effectively free.

``mode=audit`` (bench.py ``bench_audit``, docs/observability.md "audit
plane") re-runs the probe herd twice — delivery auditing armed (the
default) then disarmed via MV_SetAudit — and reports
``audit_overhead_pct`` (the serve-probe QPS the always-on audit plane
cost; acceptance: < 1%) plus ``audit_add_overhead_pct`` (the same A/B
over an async add stream, the path the seq stamps actually ride) and
``audit_detect_ms``: one injected duplicate send → the wall time until
rank 0's in-band ``"audit"`` scrape names it.

``mode=health`` (bench.py ``bench_health``, docs/observability.md
"health plane") A/Bs the timed serve probe stream with the health
plane armed (default rule pack evaluating each flush + the watchdog
bump + the alerts push) vs disarmed → ``health_overhead_pct``
(acceptance: < 1%), then arms the demo-tightened burn-rate rule,
kv-signals rank 0 to seed a 25 ms apply delay, and reports
``health_alert_detect_ms``: the fault-to-FIRING wall time through the
real flush loop (plus ``health_alert_fired``, which must be 1).

``mode=latency`` (bench.py ``bench_latency``, docs/observability.md
"latency plane") runs the probe phase THREE times over the same herd —
untimed baseline, wire-stamped (per-stage p50/p99 breakdown from the
reply trails + ``timing_overhead_pct``), then wire-stamped WITH both
sampling profilers armed in the herd process (``profiler_overhead_pct``
— the "always-on" bar, < 1%).  ``stage_sum_ratio`` checks the
offset-corrected stages telescope back to the end-to-end latency.

``mode=tail`` (bench.py ``bench_tail``, docs/serving.md "tail") is the
tail-at-scale acceptance: a 10k-socket bulk Get storm (paced by the
ReplyBusy backoff contract) against per-class weighted admission
(``-qos_inflight_max=32``, ``bulk:1,gold:8``) while a gold prober runs
in its OWN child process (``gold_probe`` entry — client-side GIL
isolation, the scraper-child discipline) measuring both e2e and SERVER
RESIDENCY per probe; plus the seeded-straggler hedge phase, the
1 ns-budget deadline-shed phase, and the pre-packed stamp-overhead
A/B.  The RLIMIT_NOFILE guard degrades the herd with a logged reason
instead of dying with EMFILE.

Rank 1 prints the measured keys; both ranks print ``FANIN_BENCH_OK``.
"""

import os
import selectors
import socket
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from multiverso_tpu import native as nat  # noqa: E402
from multiverso_tpu.serve.wire import (AnonServeClient,  # noqa: E402
                                       FrameDecoder, MSG, pack_frame,
                                       unpack_frame)

SIZE = 1024
CHAOS_ADDS = 5
# mode=tail's hedged-read matrix table (docs/serving.md "tail"): hot
# rows live in rank 0's shard (the contacted endpoint).
MROWS = 64
MCOLS = 8


class _Scraper:
    """Anonymous in-band metrics scraper hammering OpsQuery while the
    herd runs — its reply latencies are the measured scrape p50/p99.

    Runs as a child PROCESS (``fanin_bench_worker.py scrape <ep>``), not
    a thread: the herd's selector loop owns this process's GIL, and a
    threaded scraper would measure Python scheduling jitter on the
    CLIENT, not the server's in-band service path."""

    def __init__(self, endpoint: str):
        import subprocess

        self._proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "scrape",
             endpoint],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
        self.latencies = []
        # Wait for the child to finish importing and CONNECT before the
        # herd starts — otherwise a fast herd outruns the scraper and
        # the "under load" latencies never get measured.
        ready = self._proc.stdout.readline()
        assert "SCRAPER_READY" in ready, ready

    def stop(self) -> None:
        self._proc.stdin.write("\n")
        self._proc.stdin.flush()
        out = self._proc.communicate(timeout=60)[0]
        for tok in out.split():
            self.latencies.append(float(tok))


def _scrape_child(endpoint: str) -> int:
    """Child body: scrape OpsQuery(metrics) continuously (1 ms pacing)
    until a line arrives on stdin, then print the latencies (seconds)."""
    import select

    client = AnonServeClient(endpoint, timeout=30)
    client.ops_report("health")       # connection warm before READY
    print("SCRAPER_READY", flush=True)
    lat = []
    while not select.select([sys.stdin], [], [], 0.001)[0]:
        t0 = time.perf_counter()
        text = client.ops_report("metrics")
        lat.append(time.perf_counter() - t0)
        assert text, "empty ops reply"
    client.close()
    print(" ".join(f"{v:.9f}" for v in lat), flush=True)
    return 0


def _latency_herd(endpoint: str, nclients: int, rt) -> dict:
    """mode=latency body: three probe sweeps over one socket herd.

    Sweep A (untimed) is the baseline QPS; sweep B stamps timing trails
    and aggregates the reply-side stage breakdown; sweep C repeats B
    with the native SIGPROF sampler AND the Python sampler thread armed
    in THIS (busy) process — the profiler_overhead_pct A/B."""
    import numpy as np

    from multiverso_tpu import profiler as pyprof
    from multiverso_tpu.serve.wire import (OffsetEstimator, ntp_sample,
                                           stage_durations)

    host, port = endpoint.rsplit(":", 1)
    _raise_fd_limit(nclients + 256)
    sel = selectors.DefaultSelector()
    socks = []
    for i in range(nclients):
        s = socket.socket()
        s.connect((host, int(port)))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.setblocking(False)
        sel.register(s, selectors.EVENT_READ,
                     {"dec": FrameDecoder(), "id": i})
        socks.append(s)
    est = OffsetEstimator()

    def sweep(timing: bool, stages_out=None):
        done = 0
        t0 = time.perf_counter()
        window = 8
        mid = [0]
        for base in range(0, nclients, window):
            batch = socks[base:base + window]
            for s in batch:
                mid[0] += 1
                # Deadline propagation rides every probe (MV016):
                # the stamp matches the 60 s collect deadline below.
                s.sendall(pack_frame(MSG["RequestVersion"], 0, mid[0],
                                     timing=timing,
                                     qos=(0, 60_000_000_000)))
            deadline = time.time() + 60
            got = 0
            while got < len(batch) and time.time() < deadline:
                for key, _ in sel.select(timeout=1.0):
                    data = key.data
                    try:
                        chunk = key.fileobj.recv(65536)
                    except BlockingIOError:
                        continue
                    if not chunk:
                        raise RuntimeError(f"conn {data['id']} died")
                    data["dec"].feed(chunk)
                    while True:
                        body = data["dec"].next_frame()
                        if body is None:
                            break
                        reply = unpack_frame(body)
                        got += 1
                        trail = reply.get("timing")
                        if trail and stages_out is not None:
                            now = time.monotonic_ns()
                            sample = ntp_sample(trail, now)
                            if sample is not None:
                                est.update(*sample)
                            stages_out.append(stage_durations(
                                trail, now, est.offset_ns))
            if got < len(batch):
                raise RuntimeError(f"only {got}/{len(batch)} replies")
            done += got
        return done / (time.perf_counter() - t0)

    out = {"clients": float(nclients)}
    qps_plain = sweep(timing=False)
    stages = []
    qps_timed = sweep(timing=True, stages_out=stages)
    out["timing_overhead_pct"] = (
        max(0.0, (qps_plain - qps_timed) / qps_plain * 100.0)
        if qps_plain else 0.0)

    rt.set_profiler(97)
    sampler = pyprof.start(97)
    try:
        qps_profiled = sweep(timing=True, stages_out=[])
    finally:
        pyprof.stop(to_trace=False)
        rt.set_profiler(0)
    out["profiler_overhead_pct"] = (
        max(0.0, (qps_timed - qps_profiled) / qps_timed * 100.0)
        if qps_timed else 0.0)
    out["profiler_samples"] = float(sampler.samples)

    totals = np.asarray([s.get("total", 0.0) for s in stages]) * 1e3
    out["e2e_p50_ms"] = float(np.percentile(totals, 50))
    out["e2e_p99_ms"] = float(np.percentile(totals, 99))
    sums = np.asarray([sum(v for k, v in s.items() if k != "total")
                       for s in stages]) * 1e3
    ratios = sums[totals > 0] / totals[totals > 0]
    out["stage_sum_ratio"] = float(np.mean(ratios)) if len(ratios) else 0.0
    for name in ("queue", "wire_out", "mailbox", "apply", "reactor",
                 "wire_back"):
        vals = np.asarray([s.get(name, 0.0) for s in stages]) * 1e3
        out[f"stage_{name}_p50_ms"] = float(np.percentile(vals, 50))
        out[f"stage_{name}_p99_ms"] = float(np.percentile(vals, 99))
    for s in socks:
        sel.unregister(s)
        s.close()
    return out


def _audit_bench(endpoint: str, nclients: int, rt, h) -> dict:
    """mode=audit body (docs/observability.md "audit plane").

    Phase A re-runs the fan-in probe herd with auditing armed vs
    disarmed (MV_SetAudit): ``audit_overhead_pct`` is what the plane
    costs the serve tier.  Phase B A/Bs an async add stream — the path
    the seq stamps, ledger writes, and server books actually ride.
    Phase C injects ONE duplicate send and polls rank 0's in-band
    ``"audit"`` scrape until the dup is named: ``audit_detect_ms``."""
    import json

    out = {}
    # ONE persistent socket herd, interleaved probe sweeps: separate
    # 1000-connection herds swing several-fold run to run (connect
    # storms, TIME_WAIT pressure), which would drown the <1% bar the
    # A/B exists to measure.  Same discipline as mode=latency.
    host, port = endpoint.rsplit(":", 1)
    _raise_fd_limit(nclients + 256)
    sel = selectors.DefaultSelector()
    socks = []
    for i in range(nclients):
        s = socket.socket()
        s.connect((host, int(port)))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.setblocking(False)
        sel.register(s, selectors.EVENT_READ,
                     {"dec": FrameDecoder(), "id": i})
        socks.append(s)
    mid = [0]

    def sweep():
        done = 0
        t0 = time.perf_counter()
        window = 8
        for base in range(0, nclients, window):
            batch = socks[base:base + window]
            for s in batch:
                mid[0] += 1
                s.sendall(pack_frame(MSG["RequestVersion"], 0, mid[0],
                                     qos=(0, 60_000_000_000)))
            deadline = time.time() + 60
            got = 0
            while got < len(batch) and time.time() < deadline:
                for key, _ in sel.select(timeout=1.0):
                    data = key.data
                    try:
                        chunk = key.fileobj.recv(65536)
                    except BlockingIOError:
                        continue
                    if not chunk:
                        raise RuntimeError(f"conn {data['id']} died")
                    data["dec"].feed(chunk)
                    while data["dec"].next_frame() is not None:
                        got += 1
            if got < len(batch):
                raise RuntimeError(f"only {got}/{len(batch)} replies")
            done += got
        return done / (time.perf_counter() - t0)

    sweep()                                   # warm sweep: discarded
    armed_sweeps, disarmed_sweeps = [], []
    for _ in range(3):
        rt.set_audit(False)
        disarmed_sweeps.append(sweep())
        rt.set_audit(True)
        armed_sweeps.append(sweep())
    for s in socks:
        sel.unregister(s)
        s.close()
    base = max(disarmed_sweeps)
    out["audit_overhead_pct"] = (
        max(0.0, (base - max(armed_sweeps)) / base * 100.0)
        if base else 0.0)
    out["audit_probe_qps"] = max(armed_sweeps)

    delta = np.ones(SIZE, np.float32)

    def add_stream(n=256):
        t0 = time.perf_counter()
        for _ in range(n - 1):
            rt.array_add(h, delta, sync=False)
        rt.array_add(h, delta, sync=True)   # the ack closes the window
        return n / (time.perf_counter() - t0)

    add_stream()                             # full warm sweep: the
    add_stream()                             # first streams pay the
    # post-herd backlog drain, not the audit plane — discard them.
    # Interleaved best-of-3 per arm: loopback add throughput swings
    # ~2x run to run (PERF.md), and slowdown noise is one-sided.
    armed_runs, disarmed_runs = [], []
    for _ in range(3):
        rt.set_audit(False)
        disarmed_runs.append(add_stream())
        rt.set_audit(True)
        armed_runs.append(add_stream())
    qps_armed = max(armed_runs)
    qps_disarmed = max(disarmed_runs)
    out["audit_add_overhead_pct"] = (
        max(0.0, (qps_disarmed - qps_armed) / qps_disarmed * 100.0)
        if qps_disarmed else 0.0)
    out["audit_add_qps"] = qps_armed

    def total_dups(rep) -> int:
        return sum(o.get("dups", 0)
                   for t in rep.get("tables", [])
                   if isinstance(t.get("server"), dict)
                   for o in t["server"].get("origins", []))

    with AnonServeClient(endpoint, timeout=30) as client:
        dups0 = total_dups(json.loads(client.ops_report("audit")))
        rt.set_fault_n("dup", 1)
        t0 = time.perf_counter()
        rt.array_add(h, delta)               # blocking: on the wire now
        detect = -1.0
        deadline = time.time() + 30
        while time.time() < deadline:
            rep = json.loads(client.ops_report("audit"))
            if total_dups(rep) > dups0:
                detect = (time.perf_counter() - t0) * 1e3
                break
            time.sleep(0.002)
        rt.clear_faults()
    out["audit_detect_ms"] = detect
    out["audit_dup_named"] = 1.0 if detect >= 0 else 0.0
    return out


def _health_bench(endpoint: str, nclients: int, rt, h, hk) -> dict:
    """mode=health body (docs/observability.md "health plane").

    Phase A re-runs the serve probe stream with the health plane armed
    (rule pack + flush-loop evaluation + the watchdog bump + the alerts
    push) vs disarmed, interleaved best-of-3:
    ``health_overhead_pct`` is what closed-loop watching costs the
    serve tier.  Phase B arms a demo-tightened latency burn-rate rule,
    kv-signals rank 0 to seed a 25 ms ``apply_delay`` fault, and drives
    timed probes until the alert FIRES: ``health_alert_detect_ms`` is
    the fault-to-firing wall time through the real flush loop."""
    from multiverso_tpu import config, health, latency, metrics

    out = {}
    flush_ms = 100
    config.set_flag("health_latency_slo_ms", 10.0)
    metrics.reset()
    metrics.start_flush(flush_ms)

    def probes(n=64):
        t0 = time.perf_counter()
        with latency.attach_metrics(
                AnonServeClient(endpoint, timeout=30,
                                timing=True)) as client:
            for _ in range(n):
                client.get_shard(h)
        return n / (time.perf_counter() - t0)

    probes()                                  # warm: connect + JIT
    armed_runs, disarmed_runs = [], []
    for _ in range(3):
        health.disarm(rt)
        disarmed_runs.append(probes())
        health.arm(rules=health.default_rules(), runtime=rt)
        armed_runs.append(probes())
    base = max(disarmed_runs)
    out["health_overhead_pct"] = (
        max(0.0, (base - max(armed_runs)) / base * 100.0)
        if base else 0.0)
    out["health_probe_qps"] = max(armed_runs)

    # Phase B: demo-scale burn windows (the doctor-demo rule) so the
    # detection measures the flush loop, not a 300 s production window.
    health.arm(rules=[health.Rule(
        name="lat-slo-burn", metric="lat.slo.breach",
        op="burn_rate_gt", total_metric="lat.slo.total",
        threshold=2.0, objective=0.99, window_s=8.0,
        short_window_s=4.0, for_s=0.0, severity="critical")],
        runtime=rt)
    rt.kv_add(hk, "arm_delay", 1.0)
    while rt.kv_get(hk, "delay_armed") < 1.0:
        time.sleep(0.005)
    detect = -1.0
    t0 = time.perf_counter()
    deadline = time.time() + 30
    with latency.attach_metrics(
            AnonServeClient(endpoint, timeout=30,
                            timing=True)) as client:
        while time.time() < deadline:
            for _ in range(4):
                client.get_shard(h)           # ~25 ms each, all breaches
            doc = health.alerts_doc()
            if any(a["state"] == "firing" for a in doc["alerts"]):
                detect = (time.perf_counter() - t0) * 1e3
                break
    rt.kv_add(hk, "disarm_delay", 1.0)
    out["health_alert_detect_ms"] = detect
    out["health_alert_fired"] = 1.0 if detect >= 0 else 0.0
    health.disarm(rt)
    metrics.stop_flush()
    return out


def _raise_fd_limit(need: int) -> None:
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < need:
        resource.setrlimit(resource.RLIMIT_NOFILE,
                           (min(max(need, soft), hard), hard))


def _fd_budget(nclients: int, headroom: int = 256) -> int:
    """RLIMIT_NOFILE guard (docs/serving.md "tail"): raise the soft
    limit toward ``nclients + headroom``; when the hard limit cannot
    cover it, DEGRADE the herd to what fits (floor 64) with a logged
    reason instead of dying with EMFILE mid-connect — a low-ulimit
    host runs the 10k-socket phase at 1k, it does not die."""
    import resource

    need = nclients + headroom
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < need:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE,
                               (min(need, hard) if hard > 0 else need,
                                hard))
        except (ValueError, OSError) as exc:
            print(f"fd_limit: setrlimit({need}) failed: {exc}",
                  flush=True)
        soft = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    if soft < need:
        usable = max(64, soft - headroom)
        print(f"fd_limit: RLIMIT_NOFILE soft={soft} hard={hard} cannot "
              f"cover {nclients} sockets + {headroom} headroom — "
              f"degrading herd to {usable}", flush=True)
        return usable
    return nclients


class _GoldProber:
    """Paced gold-class prober running as a child PROCESS
    (``fanin_bench_worker.py gold_probe <ep> <socks>``) — the herd's
    selector loop owns this process's GIL, so an in-process gold
    prober would measure Python scheduling jitter on the CLIENT, not
    the server's per-class isolation (the same discipline as the
    bench_ops scraper child)."""

    def __init__(self, endpoint: str, socks: int = 64):
        import subprocess

        self._proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "gold_probe",
             endpoint, str(socks)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
        ready = self._proc.stdout.readline()
        assert "GOLD_READY" in ready, ready

    def stop(self):
        """(server_residency_ms, e2e_ms) arrays observed by the child.

        Residency = the trail's recv -> reply_send span, both stamps on
        the SERVER's clock — what the serve tier actually did to a gold
        read, immune to client-side scheduling on a shared host (the
        e2e numbers include the experiment's own CPU contention)."""
        self._proc.stdin.write("\n")
        self._proc.stdin.flush()
        out = self._proc.communicate(timeout=120)[0]
        res, e2e = [], []
        for line in out.splitlines():
            if line.startswith("RES "):
                res = [float(t) for t in line.split()[1:]]
            elif line.startswith("E2E "):
                e2e = [float(t) for t in line.split()[1:]]
        return np.asarray(res) * 1e3, np.asarray(e2e) * 1e3


def _gold_probe_child(endpoint: str, nsocks: int) -> int:
    """Child body: ``nsocks`` gold-class connections, paced
    8-outstanding version probes (each stamped class gold + a 30 s
    deadline budget) until a line arrives on stdin; prints the
    latencies (seconds)."""
    import select

    host, port = endpoint.rsplit(":", 1)
    _raise_fd_limit(nsocks + 64)
    sel = selectors.DefaultSelector()
    socks = []
    for i in range(nsocks):
        s = socket.socket()
        s.connect((host, int(port)))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.setblocking(False)
        sel.register(s, selectors.EVENT_READ,
                     {"dec": FrameDecoder(), "t0": 0.0})
        socks.append(s)
    print("GOLD_READY", flush=True)
    lat = []       # client-observed e2e (includes host contention)
    res = []       # server residency: trail recv -> reply_send
    mid = 0
    window = 8
    cap = 120_000            # bounded output; probing continues
    # PACED probing (a paid reader, not a herd): one window per 10 ms.
    # A max-rate prober would saturate its own CPU share on a shared
    # host and measure scheduler contention, not the server's per-class
    # isolation.
    base = 0
    while not select.select([sys.stdin], [], [], 0.01)[0]:
        batch = socks[base:base + window]
        base = (base + window) % nsocks
        for s in batch:
            mid += 1
            sel.get_key(s).data["t0"] = time.perf_counter()
            s.sendall(pack_frame(MSG["RequestVersion"], 0, mid,
                                 timing=True, qos=(1, 30_000_000_000)))
        got = 0
        deadline = time.time() + 60
        while got < len(batch) and time.time() < deadline:
            for key, _ in sel.select(timeout=1.0):
                data = key.data
                try:
                    chunk = key.fileobj.recv(65536)
                except BlockingIOError:
                    continue
                if not chunk:
                    raise RuntimeError("gold conn died")
                data["dec"].feed(chunk)
                while True:
                    body = data["dec"].next_frame()
                    if body is None:
                        break
                    reply = unpack_frame(body)
                    trail = reply.get("timing")
                    if len(lat) < cap:
                        lat.append(time.perf_counter() - data["t0"])
                        if trail and trail[2] and trail[5]:
                            res.append((trail[5] - trail[2]) * 1e-9)
                    got += 1
        if got < len(batch):
            raise RuntimeError(f"gold probes stalled ({got})")
    for s in socks:
        s.close()
    print("RES " + " ".join(f"{v:.9f}" for v in res), flush=True)
    print("E2E " + " ".join(f"{v:.9f}" for v in lat), flush=True)
    return 0


def _tail_bench(endpoint: str, nclients: int, rt, hk, hm) -> dict:
    """mode=tail body (docs/serving.md "tail"; bench.py ``bench_tail``).

    A mixed-tenant load against one epoll reactor with
    ``-qos_inflight_max`` armed — the GOLD tenant probes from a child
    process (client-side GIL isolation), the BULK herd storms from this
    one:

    - **gold-alone phase** — the gold child probes an idle reactor →
      baseline p50/p99/p99.9;
    - **herd phase** — a continuous bulk Get storm across the whole
      herd (one outstanding Get per socket, re-fired on every reply;
      sheds tallied) while the gold child re-probes →
      ``tail_qos_isolation`` = gold p99 under the herd / alone
      (acceptance: < 2x — the bulk herd must not starve gold);
    - **hedge phase** — a seeded ``apply_delay`` straggler on the
      server while a gold ``HedgedReader`` row-reads a hot row set →
      ``tail_hedge_win_rate`` (> 0 under the straggler);
    - **deadline phase** — gets stamped with a 1 ns budget must shed at
      dequeue (``tail_deadline_shed`` > 0, named by the in-band
      scrape);
    - **overhead phase** — interleaved best-of-5 paced probes stamped
      vs unstamped on a quiet reactor → ``tail_overhead_pct`` (the
      QoS/deadline stamp's cost on the unhedged fast path; < 1%).
    """
    import json

    from multiverso_tpu.serve.hedge import HedgedReader
    from multiverso_tpu.serve.wire import AnonServeClient

    host, port = endpoint.rsplit(":", 1)
    nclients = _fd_budget(nclients)
    bulk_n = max(16, nclients - 64)   # gold lives in the 64-sock child
    budget_ns = 30_000_000_000        # the storm's propagated deadline

    sel = selectors.DefaultSelector()
    bulk = []
    for i in range(bulk_n):
        s = socket.socket()
        s.connect((host, int(port)))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.setblocking(False)
        sel.register(s, selectors.EVENT_READ,
                     {"dec": FrameDecoder(), "id": i, "t0": 0.0})
        bulk.append(s)
    out = {"clients": float(bulk_n + 64), "bulk_clients": float(bulk_n),
           "gold_clients": 64.0}
    mid = [0]

    def fire(s):
        """One bulk Get, tolerant of a full send buffer (at 10k socks
        the kernel pushes back; a client that cannot send this round
        simply rejoins on its next reply)."""
        mid[0] += 1
        try:
            s.send(pack_frame(MSG["RequestGet"], 0, mid[0],
                              qos=(0, budget_ns)))
            return True
        except (BlockingIOError, InterruptedError):
            return False

    # The shed contract IS the pacing (docs/serving.md): a ReplyBusy
    # means "retry after backoff", so a shed bulk client re-fires after
    # a backoff window while a served one re-polls sooner.  A herd that
    # busy-looped on sheds instead would measure host-CPU starvation
    # (client and server share the machine), not admission isolation.
    BUSY_BACKOFF_S = 2.0
    SERVED_BACKOFF_S = 0.5

    def pct(arr, q):
        return float(np.percentile(arr, q)) if len(arr) else 0.0

    # --- phase A: gold alone -------------------------------------------
    gold = _GoldProber(endpoint)
    time.sleep(3.0)
    alone_res, alone_e2e = gold.stop()
    out["gold_p50_ms"] = pct(alone_res, 50)
    out["gold_alone_p99_ms"] = pct(alone_res, 99)
    out["gold_alone_p999_ms"] = pct(alone_res, 99.9)
    out["gold_alone_e2e_p99_ms"] = pct(alone_e2e, 99)

    # --- phase B: the bulk herd arrives --------------------------------
    import heapq

    gold = _GoldProber(endpoint)
    tally = {}
    bulk_lat = []
    due = []                      # (when, seq, sock) re-fire heap
    seq = [0]

    def schedule(s, delay):
        seq[0] += 1
        heapq.heappush(due, (time.perf_counter() + delay, seq[0], s))

    for s in bulk:
        sel.get_key(s).data["t0"] = time.perf_counter()
        fire(s)
    storm_stop = time.perf_counter() + 6.0
    refire = True
    while True:
        now = time.perf_counter()
        if refire and now >= storm_stop:
            refire = False
            herd_res, herd_e2e = gold.stop()  # gold sampled the storm
            drain_stop = now + 5.0
        if not refire and (time.perf_counter() >= drain_stop):
            break
        if refire:
            while due and due[0][0] <= now:
                _, _, s = heapq.heappop(due)
                sel.get_key(s).data["t0"] = time.perf_counter()
                fire(s)
        events = sel.select(timeout=0.05)
        if not events and not refire:
            break
        for key, _ in events:
            data = key.data
            try:
                chunk = key.fileobj.recv(65536)
            except BlockingIOError:
                continue
            if not chunk:
                raise RuntimeError(f"bulk conn {data['id']} died")
            data["dec"].feed(chunk)
            while True:
                body = data["dec"].next_frame()
                if body is None:
                    break
                reply = unpack_frame(body)
                tally[reply["type_name"]] = \
                    tally.get(reply["type_name"], 0) + 1
                served_reply = reply["type_name"] == "ReplyGet"
                if served_reply:
                    bulk_lat.append(time.perf_counter() - data["t0"])
                if refire:
                    schedule(key.fileobj, SERVED_BACKOFF_S if served_reply
                             else BUSY_BACKOFF_S)
    # Gated on SERVER RESIDENCY (the serve tier's contribution to a
    # gold read — mailbox wait + apply + reactor, one clock): on a
    # shared host the client-observed e2e includes the experiment's
    # own CPU contention, which no admission gate can remove.
    out["gold_p99_ms"] = pct(herd_res, 99)
    out["gold_p999_ms"] = pct(herd_res, 99.9)
    out["gold_e2e_p99_ms"] = pct(herd_e2e, 99)
    out["gold_e2e_p999_ms"] = pct(herd_e2e, 99.9)
    bulk_ms = np.asarray(bulk_lat) * 1e3
    out["bulk_p99_ms"] = pct(bulk_ms, 99)
    out["bulk_p999_ms"] = pct(bulk_ms, 99.9)
    served = tally.get("ReplyGet", 0)
    shed = tally.get("ReplyBusy", 0)
    out["bulk_served"] = float(served)
    out["bulk_shed"] = float(shed)
    out["bulk_shed_rate"] = shed / max(1.0, float(served + shed))
    out["qos_isolation"] = (out["gold_p99_ms"]
                            / max(out["gold_alone_p99_ms"], 1e-6))

    # --- phase C: hedged reads under a seeded straggler ----------------
    hot = list(range(8))  # rank 0's shard owns the low rows
    reader = HedgedReader(endpoint, hm, MCOLS, qos_class="gold",
                          hedge_min_us=2000, timeout=30.0)
    for _ in range(60):          # warm the SpaceSaving top-K + tracker
        reader.get_rows(hot)
    rt.kv_add(hk, "arm_delay", 1.0)      # rank 0 seeds apply_delay
    while rt.kv_get(hk, "delay_armed") < 1.0:
        time.sleep(0.02)
    for _ in range(240):
        reader.get_rows(hot)
    rt.kv_add(hk, "disarm_delay", 1.0)
    st = reader.stats()
    reader.close()
    out["hedge_issued"] = float(st["issued"])
    out["hedge_won"] = float(st["won"])
    out["hedge_wasted"] = float(st["wasted"])
    out["hedge_win_rate"] = st["win_rate"]

    # --- phase D: deadline sheds ---------------------------------------
    probe = AnonServeClient(endpoint, timeout=10.0)
    for i in range(20):
        # 1 ns budget: expired by the time the actor dequeues it — the
        # server must drop it, never burn an apply slot.  No reply
        # comes back; the probe socket stays healthy for the scrape.
        probe.send_raw(pack_frame(MSG["RequestGet"], 0,
                                  1_000_000 + i, qos=(0, 1)))
    deadline = time.time() + 10
    sheds = 0
    while time.time() < deadline:
        rep = json.loads(probe.ops_report("latency"))
        sheds = (rep.get("qos") or {}).get("deadline_shed", 0)
        if sheds >= 20:
            break
        time.sleep(0.05)
    out["deadline_shed"] = float(sheds)
    probe.close()

    # --- phase E: stamp overhead on the unhedged fast path -------------
    # Paced probes over 64 quiet sockets, interleaved best-of-5 per arm
    # (the bench_audit discipline: loopback QPS noise is one-sided, so
    # max-vs-max under interleaving is what can resolve a <1% bar).
    # Frames are PRE-PACKED outside the timed loop: the bar measures
    # what the stamp costs the WIRE + SERVER path, and on a shared host
    # every extra client-side pack cycle would also steal server time
    # (version probes ignore msg_id uniqueness, so one frame per arm
    # serves every probe).
    esocks = bulk[:64]
    frame_plain = pack_frame(MSG["RequestVersion"], 0, 1)  # mvlint: MV016-exempt(the unstamped A/B baseline arm)
    frame_qos = pack_frame(MSG["RequestVersion"], 0, 1,
                           qos=(0, budget_ns))

    def sweep(qos):
        frame = frame_qos if qos else frame_plain
        done = 0
        window = 8
        t0 = time.perf_counter()
        for _ in range(6):
            for base in range(0, len(esocks), window):
                batch = esocks[base:base + window]
                for s in batch:
                    s.sendall(frame)
                got = 0
                deadline = time.time() + 60
                while got < len(batch) and time.time() < deadline:
                    for key, _ in sel.select(timeout=1.0):
                        data = key.data
                        try:
                            chunk = key.fileobj.recv(65536)
                        except BlockingIOError:
                            continue
                        if not chunk:
                            raise RuntimeError("probe conn died")
                        data["dec"].feed(chunk)
                        while data["dec"].next_frame() is not None:
                            got += 1
                if got < len(batch):
                    raise RuntimeError("overhead probes stalled")
                done += got
        return done / (time.perf_counter() - t0)

    sweep(qos=False)                            # warm
    stamped_qps, plain_qps = [], []
    for _ in range(5):
        plain_qps.append(sweep(qos=False))
        stamped_qps.append(sweep(qos=True))
    base = max(plain_qps)
    out["overhead_pct"] = (max(0.0, (base - max(stamped_qps))
                           / base * 100.0) if base else 0.0)
    out["probe_qps"] = max(stamped_qps)

    for s in bulk:
        sel.unregister(s)
        s.close()
    return out


def _herd(endpoint: str, nclients: int, scrape: bool = False) -> dict:
    host, port = endpoint.rsplit(":", 1)
    _raise_fd_limit(nclients + 256)
    scraper = _Scraper(endpoint) if scrape else None
    sel = selectors.DefaultSelector()
    socks = []
    for i in range(nclients):
        s = socket.socket()
        s.connect((host, int(port)))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.setblocking(False)
        sel.register(s, selectors.EVENT_READ,
                     {"dec": FrameDecoder(), "id": i, "t0": 0.0})
        socks.append(s)

    def collect(expected, deadline_s, on_reply):
        got = 0
        deadline = time.time() + deadline_s
        while got < expected and time.time() < deadline:
            for key, _ in sel.select(timeout=1.0):
                data = key.data
                try:
                    chunk = key.fileobj.recv(65536)
                except BlockingIOError:
                    continue
                if not chunk:
                    raise RuntimeError(f"conn {data['id']} died")
                data["dec"].feed(chunk)
                while True:
                    body = data["dec"].next_frame()
                    if body is None:
                        break
                    on_reply(data, unpack_frame(body))
                    got += 1
        if got < expected:
            raise RuntimeError(f"only {got}/{expected} replies before "
                               f"the {deadline_s:.0f}s deadline")
        return got

    out = {"clients": float(nclients)}
    wall0 = time.perf_counter()

    # --- latency phase: 8-outstanding version probes --------------------
    lat = []
    window = 8
    for base in range(0, nclients, window):
        batch = socks[base:base + window]
        for j, s in enumerate(batch):
            sel.get_key(s).data["t0"] = time.perf_counter()
            s.sendall(pack_frame(MSG["RequestVersion"], 0, base + j,
                                 qos=(0, 60_000_000_000)))

        def note(data, reply):
            lat.append(time.perf_counter() - data["t0"])
        collect(len(batch), 60, note)
    lat_ms = np.asarray(lat) * 1e3
    out["p50_ms"] = float(np.percentile(lat_ms, 50))
    out["p99_ms"] = float(np.percentile(lat_ms, 99))
    # Pure latency-phase probe rate: the ops_overhead_pct numerator —
    # comparing it plain vs under a live scraper isolates what the
    # in-band introspection path costs the serve tier.
    out["probe_qps"] = len(lat) / (time.perf_counter() - wall0)
    if scraper is not None:
        # The scrape window is the FAN-IN load (1k-connection storm +
        # paced probes), not the deliberately pathological all-at-once
        # overload burst below — stop before it so ops_scrape_p99
        # measures scraping a busy-but-live server, the acceptance bar.
        scraper.stop()
        if scraper.latencies:
            sl = np.asarray(scraper.latencies) * 1e3
            out["ops_scrape_p50_ms"] = float(np.percentile(sl, 50))
            out["ops_scrape_p99_ms"] = float(np.percentile(sl, 99))
            out["ops_scrapes"] = float(len(sl))

    # --- overload phase: every client fires a Get at once ---------------
    counts = {"ReplyGet": 0, "ReplyBusy": 0}
    for i, s in enumerate(socks):
        s.sendall(pack_frame(MSG["RequestGet"], 0, 10000 + i,
                             qos=(0, 120_000_000_000)))

    def tally(_data, reply):
        counts[reply["type_name"]] = counts.get(reply["type_name"], 0) + 1
    replies = collect(nclients, 120, tally)
    wall = time.perf_counter() - wall0
    out["qps"] = (len(lat) + replies) / wall
    out["shed_rate"] = counts.get("ReplyBusy", 0) / float(replies)
    out["busy"] = float(counts.get("ReplyBusy", 0))
    for s in socks:
        sel.unregister(s)
        s.close()
    return out


def main() -> int:
    mf, rank = sys.argv[1], int(sys.argv[2])
    nclients = int(sys.argv[3]) if len(sys.argv) > 3 else 1000
    inflight_max = int(sys.argv[4]) if len(sys.argv) > 4 else 8
    chaos = int(sys.argv[5]) if len(sys.argv) > 5 else 0
    mode = sys.argv[6] if len(sys.argv) > 6 else ""
    engine = sys.argv[7] if len(sys.argv) > 7 else "epoll"
    args = [
        f"-machine_file={mf}", f"-rank={rank}", "-log_level=error",
        f"-net_engine={engine}",
        "-rpc_timeout_ms=60000", "-barrier_timeout_ms=120000",
        f"-server_inflight_max={inflight_max}",
        "-net_arena_bytes=8192", "-send_retries=3", "-send_backoff_ms=20"]
    if mode == "tail":
        # Tail plane (docs/serving.md "tail"): per-class weighted
        # admission armed — bulk owns ~1/9 of the read slots, gold the
        # rest, spare capacity borrowed in weight proportion.
        args += ["-qos_classes=bulk:1,gold:8", "-qos_inflight_max=32"]
    rt = nat.NativeRuntime(args=args)
    assert rt.net_engine() == engine, rt.net_engine()
    h = rt.new_array_table(SIZE)
    hk = rt.new_kv_table()
    hm = rt.new_matrix_table(MROWS, MCOLS)
    rt.barrier()
    if rank == 0:
        rt.array_add(h, np.ones(SIZE, np.float32))
        rt.matrix_add_rows(hm, list(range(MROWS)),
                           np.ones((MROWS, MCOLS), np.float32))
    rt.barrier()

    out = {}
    if rank == 0:
        rt.set_fault_seed(1234)
        if chaos:
            # PR 2 harness under live fan-in: every blocking add eats an
            # injected send failure and must still land EXACTLY once.
            for _ in range(CHAOS_ADDS):
                rt.set_fault_n("fail_send", 1)
                rt.array_add(h, np.ones(SIZE, np.float32))
            rt.clear_faults()
            assert rt.query_monitor("net.retries") >= CHAOS_ADDS
        # Hold the serve tier up until the herd reports done; mode=tail
        # additionally arms/disarms the seeded apply_delay straggler on
        # the herd's kv signal (the hedge phase's chaos ingredient).
        armed = False
        deadline = time.time() + 600
        while rt.kv_get(hk, "herd_done") < 1.0:
            if mode in ("tail", "health"):
                if not armed and rt.kv_get(hk, "arm_delay") > 0:
                    rt.set_fault_seed(1234)
                    if mode == "health":
                        # Every apply eats 25 ms: each timed probe is
                        # an SLO breach, so the burn rate saturates
                        # within one flush of traffic (doctor-demo's
                        # fault shape).
                        rt.set_fault("delay_ms", 25)
                        rt.set_fault("apply_delay", 1.0)
                    else:
                        rt.set_fault("apply_delay", 0.05)
                    armed = True
                    rt.kv_add(hk, "delay_armed", 1.0)
                elif armed and rt.kv_get(hk, "disarm_delay") > 0:
                    rt.clear_faults()
                    armed = False
            if time.time() > deadline:
                raise RuntimeError("herd never finished")
            time.sleep(0.05)
        if armed:
            rt.clear_faults()
    else:
        eps = [ln.strip() for ln in open(mf) if ln.strip()]
        if mode == "latency":
            out = _latency_herd(eps[0], nclients, rt)
        elif mode == "tail":
            out = _tail_bench(eps[0], nclients, rt, hk, hm)
        elif mode == "audit":
            out = _audit_bench(eps[0], nclients, rt, h)
        elif mode == "health":
            out = _health_bench(eps[0], nclients, rt, h, hk)
        elif mode == "ops":
            # A/B the latency phase: plain, then under a live in-band
            # scraper — the delta is what introspection costs serving.
            plain = _herd(eps[0], nclients)
            out = _herd(eps[0], nclients, scrape=True)
            base = plain.get("probe_qps", 0.0)
            scraped = out.get("probe_qps", base)
            out["ops_overhead_pct"] = (
                max(0.0, (base - scraped) / base * 100.0) if base else 0.0)
        else:
            out = _herd(eps[0], nclients)
        rt.kv_add(hk, "herd_done", 1.0)
    rt.barrier()

    # Zero lost adds: the exact final value, read through the fleet
    # (busy-shed retries until admitted — sheds are retryable by
    # contract, rc -6 means the server did no work).  mode=audit skips
    # the exact-value check: its add streams (and the deliberately
    # injected duplicate, which double-applies by design) change the
    # total — the audit books, not the value, are its assertion.
    want = 1.0 + (CHAOS_ADDS if chaos else 0)
    for attempt in range(60):
        try:
            got = rt.array_get(h, SIZE)
            break
        except nat.BusyError:
            time.sleep(0.05)
    else:
        raise RuntimeError("get shed 60 times in a row")
    if mode != "audit":
        np.testing.assert_allclose(got, want)

    if rank == 0:
        st = rt.fanin_stats()
        out["accepted"] = float(st["accepted_total"])
        out["client_shed"] = float(st["client_shed"])
        out["adds_ok"] = 1.0
    rt.barrier()
    rt.shutdown()
    kv = " ".join(f"{k}={v:.6f}" for k, v in sorted(out.items()))
    print(f"FANIN_BENCH_OK rank={rank} {kv}", flush=True)
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "scrape":
        sys.exit(_scrape_child(sys.argv[2]))
    if len(sys.argv) > 1 and sys.argv[1] == "gold_probe":
        sys.exit(_gold_probe_child(sys.argv[2], int(sys.argv[3])))
    sys.exit(main())
