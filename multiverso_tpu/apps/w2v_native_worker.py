"""N-process native-wire word2vec worker — the measured stand-in for
the reference's distributed word-embedding baseline.

``BASELINE.json`` frames the ≥8× north star as "LR + word2vec"; the LR
half got its 8-process native-wire denominator in round 4
(``lr_native_worker.py``), and this worker closes the word2vec half.
The reference app (SURVEY.md §2.36, ``Microsoft/distributed_word_embedding``
linking ``libmultiverso``) shards the embedding matrices across servers
as row-partitioned MatrixTables; each worker pulls only the rows its
batch touches (``GetMatrixTableByRows``), computes skip-gram
negative-sampling gradients locally, and pushes row deltas back
(``AddMatrixTableByRows``).  This worker reproduces that mechanism on
this repo's native runtime: worker+server rank over TcpNet, touched-row
pull → numpy SGNS gradient → row-delta push through the C API into the
C++ sgd updater.

Per batch of B (center, context) pairs with K negatives the touched set
is ``unique(centers)`` on the input table and ``unique(contexts ∪
negatives)`` on the output table — the sparse-access pattern that makes
a parameter server the right shape for this model (dense pulls of a
100k×128 table per batch would be ~100× more wire traffic).

Deltas go back through NON-blocking adds (``MV_AddAsyncMatrixTableByRows``
— the reference app's ASP push mode; the trailing barrier flushes the
pipeline so every delta lands inside the timed window), and with
``prefetch=True`` the next batch's rows are pulled through the async
Get handles (``MV_GetAsyncMatrixTableByRows``) issued right after this
batch's delta pushes — the reference's AsyncBuffer double-buffer idiom
(SURVEY.md §2.24) expressed over the wire.  The pushes go first so the
ordered connection applies them before the gets are served: prefetch-on
and prefetch-off then read under the SAME staleness regime and the A/B
isolates the overlap mechanism (both tables' gets pipelined behind the
in-flight adds) rather than overlap plus extra staleness.

Run: ``python w2v_native_worker.py <machine_file> <rank> <steps>
<batch> [prefetch]`` (spawned by ``bench.py``; stands alone for
debugging).
"""

import os
import sys
import time

# Before ANY multiverso/jax import: this process must not touch the TPU
# the spawning bench run holds (same seam as tests/mp_worker.py).
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

VOCAB = 100_000
DIM = 128
NEGATIVES = 5
LR = 0.025


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def make_batches(rng, steps, batch):
    """Pre-drawn (center, context, negatives) index batches plus the
    per-table unique row sets and local scatter indices — all the
    id-wrangling hoisted out of the timed loop, mirroring how the
    reference app's data pipeline pre-tokenizes outside the wire path."""
    batches = []
    for _ in range(steps):
        c = rng.integers(VOCAB, size=batch).astype(np.int32)
        o = rng.integers(VOCAB, size=batch).astype(np.int32)
        neg = rng.integers(VOCAB, size=(batch, NEGATIVES)).astype(np.int32)
        rows_in, c_loc = np.unique(c, return_inverse=True)
        out_ids = np.concatenate([o, neg.reshape(-1)])
        rows_out, out_loc = np.unique(out_ids, return_inverse=True)
        o_loc = out_loc[:batch].astype(np.int32)
        neg_loc = out_loc[batch:].reshape(batch, NEGATIVES).astype(np.int32)
        batches.append((rows_in.astype(np.int32), rows_out.astype(np.int32),
                        c_loc.astype(np.int32), o_loc, neg_loc))
    return batches


def sgns_row_grads(w_in, w_out, c_loc, o_loc, neg_loc):
    """Skip-gram negative-sampling gradients over the LOCAL row blocks.

    ``w_in``/``w_out`` hold only the batch's touched rows; ``*_loc``
    index into them.  Returns dense per-row delta blocks (scatter-added
    over duplicate tokens) ready for AddMatrixTableByRows."""
    v = w_in[c_loc]                          # [B, D] center vectors
    u_o = w_out[o_loc]                       # [B, D] positive context
    u_n = w_out[neg_loc]                     # [B, K, D] negatives
    g_o = _sigmoid(np.einsum("bd,bd->b", v, u_o)) - 1.0      # [B]
    g_n = _sigmoid(np.einsum("bd,bkd->bk", v, u_n))          # [B, K]
    d_v = g_o[:, None] * u_o + np.einsum("bk,bkd->bd", g_n, u_n)
    d_in = np.zeros_like(w_in)
    np.add.at(d_in, c_loc, d_v)
    d_out = np.zeros_like(w_out)
    np.add.at(d_out, o_loc, g_o[:, None] * v)
    np.add.at(d_out, neg_loc.reshape(-1),
              (g_n[:, :, None] * v[:, None, :]).reshape(-1, v.shape[1]))
    return d_in, d_out


def main(argv) -> None:
    mf, rank = argv[0], int(argv[1])
    steps, batch = int(argv[2]), int(argv[3])
    prefetch = len(argv) > 4 and argv[4] not in ("", "0", "false")

    from multiverso_tpu import native as nat

    rt = nat.NativeRuntime(args=[f"-machine_file={mf}", f"-rank={rank}",
                                 "-updater_type=sgd", "-log_level=error"])
    h_in = rt.new_matrix_table(VOCAB, DIM)
    h_out = rt.new_matrix_table(VOCAB, DIM)
    rt.set_add_option(learning_rate=LR)

    rng = np.random.default_rng(rank)
    batches = make_batches(rng, steps, batch)

    def fetch(i):
        rows_in, rows_out = batches[i][0], batches[i][1]
        if not prefetch:
            return (rt.matrix_get_rows(h_in, rows_in, DIM),
                    rt.matrix_get_rows(h_out, rows_out, DIM))
        return (rt.matrix_get_rows_async(h_in, rows_in, DIM),
                rt.matrix_get_rows_async(h_out, rows_out, DIM))

    def resolve(pair):
        return (pair[0].wait(), pair[1].wait()) if prefetch else pair

    rt.barrier()              # all ranks timed over the same window
    t0 = time.perf_counter()
    pending = fetch(0)
    for i in range(steps):
        w_in, w_out = resolve(pending)
        rows_in, rows_out, c_loc, o_loc, neg_loc = batches[i]
        d_in, d_out = sgns_row_grads(w_in, w_out, c_loc, o_loc, neg_loc)
        # Push THIS batch's deltas before issuing the next pull: the
        # async gets ride the same ordered connection as the async adds,
        # so batch i+1 reads post-add rows — the same staleness regime
        # the prefetch-off path sees — and the A/B isolates the overlap
        # mechanism itself (gets for both tables pipelined behind the
        # in-flight adds) rather than overlap + extra staleness.
        rt.matrix_add_rows(h_in, rows_in, d_in, sync=False)
        rt.matrix_add_rows(h_out, rows_out, d_out, sync=False)
        if i + 1 < steps:
            pending = fetch(i + 1)
    rt.barrier()              # every rank's adds applied
    dt = time.perf_counter() - t0

    print(f"NATIVE_W2V_OK rank={rank} dt={dt:.6f} steps={steps} "
          f"batch={batch} prefetch={int(prefetch)}", flush=True)
    rt.shutdown()


if __name__ == "__main__":
    main(sys.argv[1:])
