"""Fleet holder for the latency-attribution demo (``make latency-demo``).

Run as ``python latency_demo_worker.py <machine_file> <rank>
<trace_dir>``: two of these form a 2-rank native epoll fleet with
tracing, wire timing, heartbeats (a clock-offset channel), and the
native SIGPROF sampler armed, do cross-rank table traffic so every
stage histogram / offset estimator / exemplar has data, and print
``LATD_READY`` — then serve stdin commands:

- ``fault``   — arm a 100% 25 ms ``apply_delay`` fault on THIS rank's
  server apply path (the "slow apply" the doctor must name); print
  ``LATD_FAULT_ARMED``.
- ``traffic`` — 25 more cross-rank gets; print ``LATD_TRAFFIC_DONE``.
- ``quit``    — export native spans + the profiler's folded stacks to
  ``<trace_dir>/trace_rank<r>.json``, shut down, print
  ``LATD_OK <rank>``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np  # noqa: E402

from multiverso_tpu import profiler, tracing  # noqa: E402
from multiverso_tpu import native as nat  # noqa: E402

SIZE = 256
PROFILE_HZ = 97


def main() -> int:
    mf, rank, trace_dir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    rt = nat.NativeRuntime(args=[
        f"-machine_file={mf}", f"-rank={rank}", "-log_level=error",
        "-trace=true", f"-trace_dir={trace_dir}",
        f"-profile_hz={PROFILE_HZ}",
        "-heartbeat_ms=100", "-heartbeat_timeout_ms=5000",
        "-rpc_timeout_ms=30000", "-barrier_timeout_ms=60000"])
    assert rt.net_engine() == "epoll", rt.net_engine()
    h = rt.new_array_table(SIZE)
    rt.barrier()
    for _ in range(10):
        rt.array_add(h, np.full(SIZE, 0.5, np.float32))
        rt.array_get(h, SIZE)
    rt.barrier()
    print("LATD_READY", flush=True)

    for line in sys.stdin:
        cmd = line.strip()
        if cmd == "fault":
            rt.set_fault("delay_ms", 25)
            rt.set_fault("apply_delay", 1.0)
            print("LATD_FAULT_ARMED", flush=True)
        elif cmd == "traffic":
            for _ in range(25):
                rt.array_get(h, SIZE)
            print("LATD_TRAFFIC_DONE", flush=True)
        elif cmd == "quit":
            break
    rt.clear_faults()
    rt.barrier()

    # Trace export: native spans + the SIGPROF sampler's flame data on
    # one timeline (docs/observability.md "latency plane").
    tracing.enable(rank=rank)
    tracing.add_native_spans(rt)
    profiler.add_native_profile(rt, hz=PROFILE_HZ)
    tracing.save(os.path.join(trace_dir, f"trace_rank{rank}.json"))
    rt.shutdown()
    print(f"LATD_OK {rank}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
