"""DLRM-style sparse-embedding recommender — ROADMAP item 3's flagship
"millions of users" workload (docs/embedding.md).

Reference lineage: Multiverso's native habitat is huge sparse embedding
tables (PAPER.md §0 — word embedding, LightLDA); the modern shape of
that workload is recommender serving: a row-sharded embedding table with
O(10^7+) ids, zipf-skewed id traffic, training via sparse row adds and
serving via cached row reads.

This app is the JAX-plane driver of that shape:

- **the table** — one :class:`~multiverso_tpu.tables.MatrixTable`
  holding user AND item embeddings (items live at ``num_users + item``,
  one id space so a single sharded table serves both sides), trained
  with ``add_rows`` — only touched rows move;
- **training** — dot-product + sigmoid click prediction with binary
  cross-entropy; the per-row gradients come out of ONE jitted
  grad program over the gathered rows and push back as a batched
  ``add_rows`` (mvlint MV013 polices the row-at-a-time antipattern);
- **serving** — ``scores`` reads rows through the row-granular serve
  cache (docs/embedding.md): hot rows hit locally, misses fetch only
  the missing rows;
- **traffic** — :func:`zipf_ids` draws the standard zipf(s) id stream
  the bench/demo use, so hot-key sketches and the read replica see the
  skew the real workload has.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import numpy as np

from ..tables import MatrixTable
from ..updaters import AddOption

__all__ = ["DLRMRecommender", "zipf_ids", "synthetic_clicks"]


def zipf_ids(n: int, k: int, rng, s: float = 1.0) -> np.ndarray:
    """``n`` draws from zipf(``s``) over ``[0, k)`` — ``p(i) ∝ 1/(i+1)^s``.

    The distribution head (ids 0, 1, 2, …) is the planted hot set every
    embedding bench/demo in this repo asserts against."""
    p = 1.0 / np.arange(1, k + 1, dtype=np.float64) ** s
    p /= p.sum()
    return rng.choice(k, size=n, p=p).astype(np.int64)


def synthetic_clicks(batch: int, num_users: int, num_items: int,
                     rng, s: float = 1.0
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One zipf-skewed interaction batch: (user ids, item ids, labels).

    Labels follow a planted preference (hot users like hot items) so
    training has signal to descend."""
    users = zipf_ids(batch, num_users, rng, s)
    items = zipf_ids(batch, num_items, rng, s)
    labels = ((users + items) % 3 == 0).astype(np.float32)
    return users, items, labels


class DLRMRecommender:
    """Dot-product click model over one sharded embedding table.

    ``num_users + num_items`` rows of dimension ``dim``; row
    ``num_users + i`` is item ``i``.  The table shards over the table
    mesh like every MatrixTable — at recommender scale the row count is
    what makes it "the flagship": O(10^7) rows is just a bigger
    constructor argument (the bench runs shard-faithful scaled-down
    tables so CI stays fast).
    """

    def __init__(self, num_users: int, num_items: int, dim: int = 16,
                 learning_rate: float = 0.05, name: str = "dlrm",
                 seed: int = 0, serve_cache: Optional[int] = None,
                 max_staleness: Optional[int] = None):
        self.num_users = int(num_users)
        self.num_items = int(num_items)
        self.dim = int(dim)
        self.option = AddOption(learning_rate=learning_rate)
        rng = np.random.RandomState(seed)
        rows = self.num_users + self.num_items
        init = (0.05 * rng.randn(rows, self.dim)).astype(np.float32)
        kw = {}
        if serve_cache is not None:
            kw["serve_cache"] = serve_cache
        if max_staleness is not None:
            kw["max_staleness"] = max_staleness
        self.table = MatrixTable(rows, self.dim, init=init, name=name,
                                 updater_type="sgd",
                                 default_option=self.option, **kw)
        self._grad_fn = None

    # ------------------------------------------------------------- training
    def _grads(self, u_rows, v_rows, labels):
        """One jitted BCE grad over the gathered rows (built lazily so
        constructing the model costs no compile)."""
        import jax

        if self._grad_fn is None:
            def loss(u, v, y):
                import jax.numpy as jnp

                logits = jnp.sum(u * v, axis=-1)
                # Numerically-stable BCE-with-logits.
                return jnp.mean(jnp.maximum(logits, 0) - logits * y +
                                jnp.log1p(jnp.exp(-jnp.abs(logits))))

            self._grad_fn = jax.jit(jax.value_and_grad(loss,
                                                       argnums=(0, 1)))
        return self._grad_fn(u_rows, v_rows, labels)

    def train_step(self, user_ids, item_ids, labels) -> float:
        """Pull touched rows, one grad program, push sparse updates.

        The reference training-loop shape (§3.4) at row granularity:
        gather → grad → ``add_rows`` — ONE batched add per side, never a
        Python loop over ids (mvlint MV013)."""
        users = np.asarray(user_ids, np.int64)
        items = np.asarray(item_ids, np.int64) + self.num_users
        y = np.asarray(labels, np.float32)
        u_rows = self.table.get_rows(users)
        v_rows = self.table.get_rows(items)
        loss, (du, dv) = self._grads(u_rows, v_rows, y)
        self.table.add_rows(users, np.asarray(du, np.float32))
        self.table.add_rows(items, np.asarray(dv, np.float32))
        return float(loss)

    # -------------------------------------------------------------- serving
    def scores(self, user_id: int, item_ids) -> np.ndarray:
        """Serve scores for one user against candidate items — every
        row read rides the row-granular serve cache, so the zipf head
        stops paying fetches at all."""
        items = np.asarray(item_ids, np.int64) + self.num_users
        u = self.table.get_rows(np.asarray([user_id], np.int64))[0]
        v = self.table.get_rows(items)
        return (v @ u).astype(np.float32)

    def hot_report(self) -> dict:
        """The table's workload report (hot ids, skew) — what placement
        feeds on (docs/observability.md)."""
        return self.table.workload_report()

    def train_epoch(self, batches: int, batch: int, seed: int = 0,
                    s: float = 1.0) -> list:
        """Convenience loop for tests/demos: zipf traffic, returns the
        per-batch loss trajectory."""
        rng = np.random.RandomState(seed)
        make = partial(synthetic_clicks, batch, self.num_users,
                       self.num_items, rng, s)
        losses = []
        for _ in range(batches):
            users, items, y = make()
            losses.append(self.train_step(users, items, y))
        return losses

    def close(self) -> None:
        self.table.close()
