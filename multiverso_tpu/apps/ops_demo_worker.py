"""Fleet holder for the ops/introspection demo (``make ops-demo``).

Run as ``python ops_demo_worker.py <machine_file> <rank> <trace_dir>``:
two of these form a 2-rank native epoll fleet with tracing armed, do a
few cross-rank table ops (so monitors, spans, and bucket exemplars
exist), push the Python metrics registry into the native ops plane, and
print ``OPS_READY`` — then HOLD the fleet for the demo's anonymous
scraper until a line arrives on stdin.

On release, rank 0 runs an INJECTED BARRIER TIMEOUT: it enters a
barrier with ``-barrier_timeout_ms=1500`` while rank 1 sleeps 3 s before
arriving.  Rank 0's timeout is a flight-recorder trigger — the native
black box dumps ``<trace_dir>/blackbox_rank0.json`` — after which the
retry completes the rendezvous (PR 2 round semantics).  Both ranks then
export their span rings as ``trace_rank<r>.json`` (Chrome trace) so the
demo can prove the blackbox spans AND the scraped exemplars resolve in
the merged timeline, and exit with ``OPS_WORKER_OK <rank>``.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from multiverso_tpu import metrics, tracing  # noqa: E402
from multiverso_tpu import native as nat  # noqa: E402

SIZE = 256


def main() -> int:
    mf, rank, trace_dir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    barrier_ms = 1500 if rank == 0 else 60000
    rt = nat.NativeRuntime(args=[
        f"-machine_file={mf}", f"-rank={rank}", "-log_level=error",
        "-trace=true", f"-trace_dir={trace_dir}",
        "-rpc_timeout_ms=30000", f"-barrier_timeout_ms={barrier_ms}",
        "-server_inflight_max=64", "-heartbeat_ms=200"])
    assert rt.net_engine() == "epoll", rt.net_engine()
    h = rt.new_array_table(SIZE)
    hk = rt.new_kv_table()
    rt.barrier()
    # Cross-rank traffic: every op records monitors + spans + exemplars
    # (the worker Get on one rank correlates with the server apply on
    # the other by trace id — the ids a scraped exemplar must resolve).
    for step in range(5):
        rt.array_add(h, np.full(SIZE, 0.5, np.float32))
        rt.array_get(h, SIZE)
    rt.barrier()

    # Serve the FULL registry over the wire: bridge native monitors in,
    # then push the exemplar-annotated rendering into the ops plane.
    metrics.bridge_native(rt)
    rt.set_ops_host_metrics(metrics.render_prometheus(exemplars=True))

    print("OPS_READY", flush=True)
    sys.stdin.readline()          # held while the demo scrapes us

    # ---- injected barrier timeout (the flight-recorder trigger) ------
    if rank == 1:
        time.sleep(3.0)           # straggle PAST rank 0's deadline
        rt.barrier()              # late arrival: releases rank 0's retry
    else:
        try:
            rt.barrier()          # times out at 1.5s -> blackbox dump
            print("OPS_DEMO_UNEXPECTED: barrier did not time out",
                  flush=True)
            return 1
        except RuntimeError:
            box = os.path.join(trace_dir, "blackbox_rank0.json")
            assert os.path.exists(box), box
            print("BLACKBOX_DUMPED", flush=True)
        # Retry rounds until rank 1's late arrival completes the
        # rendezvous (each retry waits the 1.5s deadline again).
        for _ in range(20):
            try:
                rt.barrier()
                break
            except RuntimeError:
                continue
        else:
            raise RuntimeError("barrier retries never completed")
    rt.kv_add(hk, f"done{rank}", 1.0)
    rt.barrier()

    # Export the span ring as this rank's Chrome trace (the merge target
    # exemplars + blackbox spans resolve against).
    tracing.enable(rank=rank)
    tracing.add_native_spans(rt)
    tracing.save(os.path.join(trace_dir, f"trace_rank{rank}.json"))
    rt.barrier()
    rt.shutdown()
    print(f"OPS_WORKER_OK {rank}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
