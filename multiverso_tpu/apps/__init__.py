"""Bundled applications.

The reference ships its flagship apps as binding examples / sibling repos
(SURVEY.md §2.32, §2.36): Theano logistic regression, distributed word
embedding (word2vec), LightLDA.  Here they are first-class packages built on
the TPU-native tables, each with

- a *parity* training path using push-pull ``Get``/``Add`` (the literal
  reference training-loop shape, SURVEY.md §3.4), and
- a *fused* path where the whole data-parallel step — pull, compute, push,
  update — compiles into one XLA program over the device mesh (the
  TPU-native hot loop that the benchmarks run).
"""

from .dlrm import DLRMRecommender, synthetic_clicks, zipf_ids
from .lightlda import LightLDA, synthetic_documents
from .logistic_regression import LogisticRegression, synthetic_classification
from .skipgram_mixture import SkipGramMixture, synthetic_homonym_corpus
from .word2vec import SkipGram, synthetic_corpus

__all__ = [
    "LogisticRegression", "synthetic_classification",
    "SkipGram", "synthetic_corpus",
    "SkipGramMixture", "synthetic_homonym_corpus",
    "LightLDA", "synthetic_documents",
    "DLRMRecommender", "synthetic_clicks", "zipf_ids",
    # torch-dependent (import from .resnet directly): ResNet20DataParallel
]
