"""Error-budget / burn-rate math for the health plane
(docs/observability.md "health plane").

Pure functions over ``[(ts, value)]`` point lists — the exact shape
:mod:`multiverso_tpu.metrics` records into its bounded time-series ring
(one point per flush) — so every result here is hand-computable in a
test without a registry, a flusher, or a fleet.  ``health.py`` is the
stateful evaluator that feeds these from live rings each flush.

The model is the standard SRE error-budget one: an SLO objective (say
0.999 availability over the window) leaves a budget of ``1 - objective``
bad events per good+bad event; the **burn rate** is how many multiples
of that budget the observed bad fraction is consuming.  Burn rate 1.0
spends exactly the budget over the SLO window; burn rate 14 spends a
30-day budget in ~2 days.  Multiwindow alerting (a LONG window for
significance and a SHORT window for "still happening now") is what
keeps a burn-rate alert both fast and flap-free: the long window alone
keeps firing long after recovery, the short window alone fires on any
blip.

Every function returns ``None`` when the ring cannot answer yet (fewer
than two points in the window, zero elapsed, zero denominator) — the
same ``'-'`` discipline as ``metrics.rate()``: "no data" must never
read as "zero".
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

__all__ = [
    "budget", "window_points", "window_delta", "window_rate",
    "error_fraction", "burn_rate", "multiwindow_burn",
]

Point = Tuple[float, float]


def budget(objective: float) -> float:
    """The error budget an SLO objective leaves: ``1 - objective``
    (objective 0.999 -> 0.001).  Raises on a non-sensical objective —
    a rule with objective >= 1.0 has no budget to burn and would
    divide by zero quietly forever."""
    if not 0.0 < objective < 1.0:
        raise ValueError(
            f"SLO objective must be in (0, 1), got {objective}")
    return 1.0 - objective


def window_points(points: Sequence[Point], window_s: float,
                  now: Optional[float] = None) -> List[Point]:
    """The suffix of ``points`` whose timestamps fall within
    ``window_s`` of ``now`` (default: the last point's timestamp).
    Points are assumed time-ordered, as the metrics ring records them."""
    if not points:
        return []
    end = points[-1][0] if now is None else float(now)
    lo = end - float(window_s)
    return [p for p in points if lo <= p[0] <= end]


def window_delta(points: Sequence[Point], window_s: float,
                 now: Optional[float] = None) -> Optional[float]:
    """Counter increase over the window: last - first of the in-window
    points, clamped at 0 (a restarted rank's counter reset reads as no
    events, not negative events).  ``None`` with fewer than two
    in-window points — one sample is a value, never a delta."""
    pts = window_points(points, window_s, now)
    if len(pts) < 2:
        return None
    return max(0.0, pts[-1][1] - pts[0][1])


def window_rate(points: Sequence[Point], window_s: float,
                now: Optional[float] = None) -> Optional[float]:
    """Per-second rate over the window (``window_delta`` / elapsed);
    ``None`` when the delta is undefined or no time elapsed."""
    pts = window_points(points, window_s, now)
    if len(pts) < 2:
        return None
    elapsed = pts[-1][0] - pts[0][0]
    if elapsed <= 0:
        return None
    return max(0.0, pts[-1][1] - pts[0][1]) / elapsed


def error_fraction(bad: Sequence[Point], total: Sequence[Point],
                   window_s: float,
                   now: Optional[float] = None) -> Optional[float]:
    """Fraction of events in the window that were bad:
    ``delta(bad) / delta(total)``.  ``None`` when either delta is
    undefined or no events happened — zero traffic is "no data", not
    "perfect availability" (an idle rank must not mask a broken one by
    averaging, nor look healthy just because nobody asked)."""
    db = window_delta(bad, window_s, now)
    dt = window_delta(total, window_s, now)
    if db is None or dt is None or dt <= 0:
        return None
    return min(1.0, db / dt)


def burn_rate(bad: Sequence[Point], total: Sequence[Point],
              objective: float, window_s: float,
              now: Optional[float] = None) -> Optional[float]:
    """How many multiples of the error budget the window consumed:
    ``error_fraction / (1 - objective)``.  1.0 = spending exactly the
    budget; ``None`` under the no-data rules of
    :func:`error_fraction`."""
    frac = error_fraction(bad, total, window_s, now)
    if frac is None:
        return None
    return frac / budget(objective)


def multiwindow_burn(bad: Sequence[Point], total: Sequence[Point],
                     objective: float, threshold: float,
                     long_s: float, short_s: float,
                     now: Optional[float] = None
                     ) -> Tuple[Optional[float], Optional[float], bool]:
    """Multiwindow burn-rate check (the SRE-workbook alert shape):
    returns ``(long_burn, short_burn, firing)`` where ``firing`` is
    True only when BOTH windows burn past ``threshold`` — the long
    window proves the spend is significant, the short window proves it
    is still happening (so the alert resolves promptly after the fault
    clears instead of dragging the long window's tail).  A ``short_s``
    of 0 degenerates to single-window.  Either burn being ``None``
    (no data) means not firing."""
    long_burn = burn_rate(bad, total, objective, long_s, now)
    if short_s <= 0:
        firing = long_burn is not None and long_burn > threshold
        return long_burn, long_burn, firing
    short_burn = burn_rate(bad, total, objective, short_s, now)
    firing = (long_burn is not None and long_burn > threshold and
              short_burn is not None and short_burn > threshold)
    return long_burn, short_burn, firing
