"""Dashboard: named timing monitors — now a shim over the metrics
registry (docs/observability.md).

Parity with the reference's ``dashboard.h`` / ``src/dashboard.cpp``
(``Dashboard``, ``Monitor``, ``MONITOR(...)`` macro; SURVEY.md §2.26):
named accumulating timers around hot paths, aggregated and dumped at
shutdown through the logger.  The ``monitor()`` / ``get_monitor()`` /
``report()`` surface is unchanged, but every monitor is now backed by a
:class:`multiverso_tpu.metrics.Histogram` (fixed log2 latency buckets),
so ``report()`` prints p50/p95/p99 and ``metrics.snapshot()`` exposes
every monitor alongside the counters/gauges of the rest of the system.

When tracing is armed (``-trace_dir`` / ``tracing.enable()``), each
monitored section also records a span into ``multiverso_tpu.tracing``
— table ops, barriers, and jitted steps show up on the merged timeline
without new call sites.

TPU-native additions: monitors can also wrap jitted calls (timing includes
``block_until_ready``), and ``jax.profiler`` trace capture can be toggled
for a deeper look (SURVEY.md §5 "Tracing/profiling").
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator

from . import metrics, tracing
from .log import Log

__all__ = ["Monitor", "monitor", "get_monitor", "report", "reset",
           "start_trace", "stop_trace"]


class Monitor:
    """Accumulating named timer over a registry histogram.

    Keeps the legacy surface (``count`` / ``total_s`` / ``max_s`` /
    ``mean_ms``) and adds bucket percentiles (``p50_ms`` ...).
    """

    def __init__(self, name: str):
        self.name = name
        self._hist = metrics.histogram(name)

    def begin(self) -> float:
        return time.perf_counter()

    def end(self, t0: float) -> None:
        dt = time.perf_counter() - t0
        self._hist.observe(dt)
        if tracing.enabled():
            tracing.record_span(self.name,
                                int((time.time() - dt) * 1e6),
                                int(dt * 1e6),
                                trace_id=tracing.current_trace_id()
                                or tracing.new_trace_id())

    @property
    def count(self) -> int:
        return self._hist.count

    @property
    def total_s(self) -> float:
        return self._hist.sum

    @property
    def max_s(self) -> float:
        return self._hist.max

    @property
    def mean_ms(self) -> float:
        return self._hist.mean * 1e3

    def quantile_ms(self, q: float) -> float:
        return self._hist.quantile(q) * 1e3

    @property
    def p50_ms(self) -> float:
        return self.quantile_ms(0.50)

    @property
    def p95_ms(self) -> float:
        return self.quantile_ms(0.95)

    @property
    def p99_ms(self) -> float:
        return self.quantile_ms(0.99)

    def __str__(self) -> str:
        return (f"{self.name}: count={self.count} total={self.total_s:.3f}s "
                f"mean={self.mean_ms:.3f}ms p50={self.p50_ms:.3f}ms "
                f"p95={self.p95_ms:.3f}ms p99={self.p99_ms:.3f}ms "
                f"max={self.max_s * 1e3:.3f}ms")


_LOCK = threading.Lock()
_MONITORS: Dict[str, Monitor] = {}


def get_monitor(name: str) -> Monitor:
    with _LOCK:
        m = _MONITORS.get(name)
        if m is None:
            m = _MONITORS[name] = Monitor(name)
        return m


@contextmanager
def monitor(name: str) -> Iterator[Monitor]:
    """``with dashboard.monitor("Worker::Get"):`` — the MONITOR macro.

    With tracing armed the section runs under a span context too, so
    nested monitors (and native calls the caller stamps via
    ``NativeRuntime.set_trace_id``) share its trace id.
    """
    m = get_monitor(name)
    if not tracing.enabled():
        t0 = m.begin()
        try:
            yield m
        finally:
            m.end(t0)
        return
    with tracing.span(name):
        t0 = time.perf_counter()
        try:
            yield m
        finally:
            m._hist.observe(time.perf_counter() - t0)


def report(log: bool = True) -> Dict[str, Monitor]:
    """Aggregate table; dumped at shutdown like the reference Dashboard
    (now with percentiles)."""
    with _LOCK:
        monitors = dict(_MONITORS)
    if log and monitors:
        Log.info("---------------- Dashboard ----------------")
        for name in sorted(monitors):
            Log.info("  %s", monitors[name])
        Log.info("--------------------------------------------")
    return monitors


def reset() -> None:
    with _LOCK:
        for name in _MONITORS:
            metrics.REGISTRY.remove(name)
        _MONITORS.clear()


_trace_active = False


def start_trace(log_dir: str) -> None:
    """Start a jax.profiler trace (TPU-native deep profiling path)."""
    global _trace_active
    import jax

    if not _trace_active:
        jax.profiler.start_trace(log_dir)
        _trace_active = True


def stop_trace() -> None:
    global _trace_active
    import jax

    if _trace_active:
        jax.profiler.stop_trace()
        _trace_active = False
