"""Dashboard: named timing monitors.

Parity with the reference's ``dashboard.h`` / ``src/dashboard.cpp``
(``Dashboard``, ``Monitor``, ``MONITOR(...)`` macro; SURVEY.md §2.26):
named accumulating timers around hot paths, aggregated and dumped at
shutdown through the logger.

TPU-native additions: monitors can also wrap jitted calls (timing includes
``block_until_ready``), and ``jax.profiler`` trace capture can be toggled
for a deeper look (SURVEY.md §5 "Tracing/profiling").
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Iterator

from .log import Log

__all__ = ["Monitor", "monitor", "get_monitor", "report", "reset", "start_trace", "stop_trace"]


@dataclass
class Monitor:
    """Accumulating named timer (count, total seconds, max seconds)."""

    name: str
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def begin(self) -> float:
        return time.perf_counter()

    def end(self, t0: float) -> None:
        dt = time.perf_counter() - t0
        with self._lock:
            self.count += 1
            self.total_s += dt
            self.max_s = max(self.max_s, dt)

    @property
    def mean_ms(self) -> float:
        return (self.total_s / self.count * 1e3) if self.count else 0.0

    def __str__(self) -> str:
        return (f"{self.name}: count={self.count} total={self.total_s:.3f}s "
                f"mean={self.mean_ms:.3f}ms max={self.max_s * 1e3:.3f}ms")


_LOCK = threading.Lock()
_MONITORS: Dict[str, Monitor] = {}


def get_monitor(name: str) -> Monitor:
    with _LOCK:
        m = _MONITORS.get(name)
        if m is None:
            m = _MONITORS[name] = Monitor(name)
        return m


@contextmanager
def monitor(name: str) -> Iterator[Monitor]:
    """``with dashboard.monitor("Worker::Get"):`` — the MONITOR macro."""
    m = get_monitor(name)
    t0 = m.begin()
    try:
        yield m
    finally:
        m.end(t0)


def report(log: bool = True) -> Dict[str, Monitor]:
    """Aggregate table; dumped at shutdown like the reference Dashboard."""
    with _LOCK:
        monitors = dict(_MONITORS)
    if log and monitors:
        Log.info("---------------- Dashboard ----------------")
        for name in sorted(monitors):
            Log.info("  %s", monitors[name])
        Log.info("--------------------------------------------")
    return monitors


def reset() -> None:
    with _LOCK:
        _MONITORS.clear()


_trace_active = False


def start_trace(log_dir: str) -> None:
    """Start a jax.profiler trace (TPU-native deep profiling path)."""
    global _trace_active
    import jax

    if not _trace_active:
        jax.profiler.start_trace(log_dir)
        _trace_active = True


def stop_trace() -> None:
    global _trace_active
    import jax

    if _trace_active:
        jax.profiler.stop_trace()
        _trace_active = False
