"""Spans & trace export (docs/observability.md).

Cross-rank, cross-plane tracing for the push-pull path: a worker-side
``Get()``/``Add()``, the wire hop that carried it, and the server-side
apply all share one **trace id**, so a merged timeline answers *where
time went* across the Python/native/wire boundaries.

Three pieces:

- **Python spans** — :func:`span` is a context manager recording a
  wall-clock span into a bounded in-process buffer; ``dashboard``
  monitors emit spans automatically when tracing is on, so every table
  op / barrier / jitted step shows up without new call sites.  Trace
  ids are thread-local: nested spans share the outermost id (mirroring
  the native ``Monitor`` contract in ``mvtpu/dashboard.h``).
- **Native spans** — the C runtime records the same span shape
  (``MV_DumpSpans``; ids propagate through message headers across
  ranks).  :func:`add_native_spans` folds a dump into this buffer so
  one export holds both planes.
- **Export** — :func:`save` writes Chrome trace-event JSON (load it in
  Perfetto / ``chrome://tracing``); :func:`merge_dir` merges per-rank
  files into one timeline (timestamps are wall-clock µs, so same-host
  ranks line up).  ``jax.profiler`` capture stays available through
  ``dashboard.start_trace`` for XLA-level depth — this layer is the
  cheap always-on complement.

Enable with the ``-trace_dir=<dir>`` flag (``init()`` arms it and
``shutdown()`` writes ``trace_rank<r>.json``), or programmatically with
:func:`enable`.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from .log import Log

__all__ = [
    "SpanEvent", "enabled", "enable", "disable", "span", "record_span",
    "current_trace_id", "set_trace_id", "new_trace_id", "events",
    "trace_ids",
    "clear", "to_chrome", "save", "merge_dir", "add_native_spans",
    "parse_native_spans", "default_trace_path",
]

# Bounded buffer: a long run must not grow without limit; newest win.
_MAX_EVENTS = 100_000

_LOCK = threading.Lock()
_EVENTS: "collections.deque[SpanEvent]" = collections.deque(
    maxlen=_MAX_EVENTS)
_ENABLED = False
_RANK = 0
_SEQ = 0
_TLS = threading.local()


@dataclass
class SpanEvent:
    """One complete ('X'-phase) span."""

    name: str
    trace_id: int
    ts_us: int            # wall-clock start, µs (merges across ranks)
    dur_us: int
    pid: int              # rank
    tid: int              # thread id (hash for native threads)
    args: Dict[str, Any] = field(default_factory=dict)


def enabled() -> bool:
    return _ENABLED


def enable(rank: Optional[int] = None) -> None:
    """Arm span recording (idempotent).  ``rank`` salts trace ids so two
    ranks never mint the same id and labels the pid lane of exports."""
    global _ENABLED, _RANK
    with _LOCK:
        if rank is not None:
            _RANK = int(rank)
        _ENABLED = True


def disable() -> None:
    global _ENABLED
    with _LOCK:
        _ENABLED = False


def clear() -> None:
    with _LOCK:
        _EVENTS.clear()


def new_trace_id() -> int:
    """Fresh id with the rank salt in the high bits (the same layout the
    native plane uses, so merged traces cannot collide)."""
    global _SEQ
    with _LOCK:
        _SEQ += 1
        return ((_RANK + 1) << 40) | _SEQ


def current_trace_id() -> int:
    """This thread's active trace id (0 = none)."""
    return getattr(_TLS, "trace_id", 0)


def set_trace_id(trace_id: int) -> None:
    _TLS.trace_id = int(trace_id)


def record_span(name: str, ts_us: int, dur_us: int,
                trace_id: Optional[int] = None,
                args: Optional[Dict[str, Any]] = None) -> None:
    """Append one finished span (no-op when tracing is off)."""
    if not _ENABLED:
        return
    tid = trace_id if trace_id is not None else current_trace_id()
    ev = SpanEvent(name=name, trace_id=int(tid), ts_us=int(ts_us),
                   dur_us=int(dur_us), pid=_RANK,
                   tid=threading.get_ident() & 0xFFFF,
                   args=dict(args or {}))
    with _LOCK:
        _EVENTS.append(ev)


@contextmanager
def span(name: str, trace_id: Optional[int] = None,
         **args: Any) -> Iterator[int]:
    """``with tracing.span("Worker::Get", table="w"):`` — times the body
    and records a span.  Yields the trace id in effect (0 when tracing
    is off) so callers can stamp it into native calls
    (``NativeRuntime.set_trace_id``) or log lines.  Nested spans share
    the outermost id; an explicit ``trace_id`` pins it.
    """
    if not _ENABLED:
        yield 0
        return
    prev = current_trace_id()
    tid = int(trace_id) if trace_id else (prev or new_trace_id())
    set_trace_id(tid)
    ts = time.time()
    t0 = time.perf_counter()
    try:
        yield tid
    finally:
        dur = time.perf_counter() - t0
        set_trace_id(prev)
        record_span(name, int(ts * 1e6), int(dur * 1e6), trace_id=tid,
                    args=args)


def events() -> List[SpanEvent]:
    with _LOCK:
        return list(_EVENTS)


def trace_ids() -> set:
    """Every distinct trace id in the buffer — the resolution set an
    exemplar (docs/observability.md) must land in to be explainable."""
    with _LOCK:
        return {e.trace_id for e in _EVENTS if e.trace_id}


# ---------------------------------------------------------------------------
# Native span import (MV_DumpSpans wire format; see c_api.h).
# ---------------------------------------------------------------------------

def parse_native_spans(text: str) -> List[SpanEvent]:
    """``name\\ttrace_id\\tts_us\\tdur_us\\trank\\ttid`` lines → events."""
    out = []
    for line in text.splitlines():
        if not line.strip():
            continue
        name, trace_id, ts_us, dur_us, rank, tid = line.split("\t")
        out.append(SpanEvent(
            name=name, trace_id=int(trace_id), ts_us=int(ts_us),
            dur_us=int(dur_us), pid=int(rank), tid=int(tid) & 0xFFFF,
            args={"plane": "native"}))
    return out


def add_native_spans(runtime: Any) -> int:
    """Fold a ``NativeRuntime``'s recorded spans into this buffer (so one
    :func:`save` exports both planes).  Returns the span count."""
    spans = parse_native_spans(runtime.dump_spans())
    with _LOCK:
        _EVENTS.extend(spans)
    return len(spans)


# ---------------------------------------------------------------------------
# Chrome trace-event export.
# ---------------------------------------------------------------------------

def to_chrome(evts: Optional[List[SpanEvent]] = None) -> Dict[str, Any]:
    """Chrome trace-event JSON object (Perfetto / chrome://tracing)."""
    if evts is None:
        evts = events()
    trace_events = []
    for e in evts:
        args = dict(e.args)
        if e.trace_id:
            args["trace_id"] = f"{e.trace_id:#x}"
        trace_events.append({
            "name": e.name,
            "ph": "X",
            "ts": e.ts_us,
            "dur": e.dur_us,
            "pid": e.pid,
            "tid": e.tid,
            "args": args,
        })
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def default_trace_path(trace_dir: str, rank: Optional[int] = None) -> str:
    return os.path.join(trace_dir,
                        f"trace_rank{_RANK if rank is None else rank}.json")


def save(path: str, evts: Optional[List[SpanEvent]] = None) -> int:
    """Write the buffer (or ``evts``) as Chrome trace JSON; returns the
    event count.  Atomic replace so a crash mid-write never leaves a
    truncated file where a merge step expects JSON."""
    from .io.stream import LocalStream

    doc = to_chrome(evts)
    with LocalStream(path, "wb", atomic=True) as s:
        s.write(json.dumps(doc).encode())
    Log.debug("tracing: wrote %d span(s) to %s",
              len(doc["traceEvents"]), path)
    return len(doc["traceEvents"])


def merge_dir(trace_dir: str, out_name: str = "trace_merged.json") -> str:
    """Merge every ``trace_rank*.json`` (and any other ``*.json`` trace
    except a previous merge) in ``trace_dir`` into one Chrome trace;
    returns the merged file path.

    A truncated / mid-write / otherwise unparseable per-rank file is
    SKIPPED with a warning (and a synthetic ``trace_merge_skipped``
    metadata event naming it in the merged output) instead of raising:
    the flight recorder dumps while ranks are being SIGKILLed, and one
    corpse's half-written JSON must not cost the post-mortem every
    surviving rank's timeline."""
    merged: List[Dict[str, Any]] = []
    skipped: List[str] = []
    for name in sorted(os.listdir(trace_dir)):
        if not name.endswith(".json") or name == out_name:
            continue
        try:
            with open(os.path.join(trace_dir, name), "rb") as f:
                doc = json.load(f)
            events = doc.get("traceEvents", [])
            if not isinstance(events, list):
                raise ValueError("traceEvents is not a list")
        except (OSError, ValueError) as exc:
            # json.JSONDecodeError is a ValueError: truncated file,
            # interleaved partial write, or non-trace JSON all land here.
            Log.error("tracing.merge_dir: skipping unreadable %s (%s)",
                      name, exc)
            skipped.append(name)
            continue
        merged.extend(events)
    for name in skipped:
        merged.append({"name": "trace_merge_skipped", "ph": "i",
                       "ts": 0, "pid": -1, "tid": 0, "s": "g",
                       "args": {"file": name,
                                "why": "unparseable (truncated or "
                                       "mid-write)"}})
    merged.sort(key=lambda e: e.get("ts", 0))
    out_path = os.path.join(trace_dir, out_name)
    from .io.stream import LocalStream

    with LocalStream(out_path, "wb", atomic=True) as s:
        s.write(json.dumps({"traceEvents": merged,
                            "displayTimeUnit": "ms"}).encode())
    return out_path
