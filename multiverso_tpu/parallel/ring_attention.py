"""Ring attention — sequence/context parallelism over a mesh axis.

The reference framework predates long-context models and has nothing here
(SURVEY.md §5 "long-context: does not exist"), but this framework treats
long-context as first-class: sequences shard over a mesh axis (``sp``) and
attention runs blockwise, rotating K/V shards around the ring with
``ppermute`` over ICI while each device accumulates its queries' output
with an online (streaming) softmax.  Peak memory per device is O(T_local²)
instead of O(T_global²), and the K/V transfer overlaps compute around the
ring — the standard TPU recipe for million-token contexts.

Implementation: ``shard_map`` + ``lax.fori_loop`` + ``ppermute`` with
static shapes; each ring step computes a normalized ``(o, lse)`` piece —
on TPU via the differentiable Pallas flash kernel
(``ops/flash_attention.py``), elsewhere via the fused jnp streaming
path — and pieces combine with the logsumexp identity.  XLA overlaps the
collective-permute with the block compute on TPU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from multiverso_tpu.parallel._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["ring_attention", "blockwise_attention_local"]

_NEG = -1e30  # finite mask sentinel: exp(_NEG - m) underflows to exactly 0


def _online_block(q, k_blk, v_blk, o, m, l, q_pos, k_pos, scale, causal):
    """One streaming-softmax accumulation step over a K/V block.

    q [B,H,T,D]; k_blk/v_blk [B,H,Tb,D]; o [B,H,T,D] f32; m,l [B,H,T,1]
    f32; q_pos [T], k_pos [Tb] are GLOBAL positions for causal masking.
    The block matmul runs in the compute dtype (MXU); the softmax
    statistics and the output accumulate in float32 — bf16 accumulation
    across ring steps would compound rounding error.
    """
    s = jnp.einsum("bhtd,bhsd->bhts", q, k_blk).astype(jnp.float32) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]                # [T,Tb]
        s = jnp.where(mask[None, None], s, _NEG)
    blk_max = jnp.max(s, axis=-1, keepdims=True)               # [B,H,T,1]
    new_m = jnp.maximum(m, blk_max)
    # exp(_NEG - new_m) == 0 for every masked entry once any real score
    # has been seen; before that the correction factor zeroes the garbage.
    p = jnp.exp(s - new_m)
    corr = jnp.exp(m - new_m)
    l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    o = o * corr + jnp.einsum("bhts,bhsd->bhtd",
                              p.astype(v_blk.dtype), v_blk
                              ).astype(jnp.float32)
    return o, new_m, l


def _flash_block(t: int, cap: int, head_dim: int) -> int:
    """Block for the flash dispatch: the kernel's own fit policy
    (``ops.flash_attention.fit_block``) gated at ≥64 — below that the
    non-pallas scan path wins (0 = don't dispatch flash).

    Caps are the measured v5e sweet spot at D=128: q blocks 512, k
    blocks 1024 (``ops/flash_attention.py`` docstring).  The kernel's
    VMEM footprint scales with block·head_dim (k/v tiles) — larger head
    dims shrink the cap proportionally so D=256 keeps the D=128 budget
    instead of risking Mosaic VMEM exhaustion."""
    from ..ops.flash_attention import fit_block, scale_cap_for_head_dim

    b = fit_block(scale_cap_for_head_dim(cap, head_dim), t)
    return b if b >= 64 else 0


def blockwise_attention_local(q, k, v, scale: float, causal: bool = True,
                              q_offset: int = 0, k_offset: int = 0):
    """Single-device attention (the ring's degenerate case).

    On TPU backends with aligned shapes this dispatches to the Pallas
    flash kernel (``ops/flash_attention.py``) — O(T) memory, causal-block
    skipping, differentiable via its custom_vjp; elsewhere (CPU tests,
    odd shapes, offset blocks) the jnp streaming-softmax path runs and
    XLA fuses it.  Setting ``MVTPU_FORCE_FLASH`` (any non-empty value)
    forces the kernel on any backend — in interpret mode off-TPU, so CI
    covers this exact dispatch; ``MVTPU_NO_FLASH`` disables it.
    """
    import os

    B, H, T, D = q.shape
    bq = _flash_block(T, cap=512, head_dim=D)
    bk = _flash_block(T, cap=1024, head_dim=D)
    on_tpu = jax.default_backend() == "tpu"
    force = os.environ.get("MVTPU_FORCE_FLASH", "")
    use_flash = (q_offset == 0 and k_offset == 0 and T == k.shape[2]
                 and bq and bk and not os.environ.get("MVTPU_NO_FLASH")
                 and (on_tpu or force))
    if use_flash:
        from ..ops import flash_attention

        return flash_attention(q, k, v, scale=scale, causal=causal,
                               block_q=bq, block_k=bk,
                               interpret=not on_tpu)
    o = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full((B, H, T, 1), _NEG, jnp.float32)
    l = jnp.zeros((B, H, T, 1), jnp.float32)
    q_pos = q_offset + jnp.arange(T)
    k_pos = k_offset + jnp.arange(k.shape[2])
    o, m, l = _online_block(q, k, v, o, m, l, q_pos, k_pos, scale, causal)
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def _attn_piece(q, k, v, scale, causal: bool):
    """Normalized attention over one K/V block, plus row logsumexp.

    Returns ``(o [B,H,Tq,D] in q.dtype, lse [B,H,Tq] float32)``.  Pieces
    compose across ring steps: ``lse' = logaddexp(lse1, lse2); o' =
    o1·e^{lse1-lse'} + o2·e^{lse2-lse'}`` — so each ring step can run the
    Pallas flash kernel at full kernel speed and the combination stays
    pure jnp (fused by XLA).  On non-TPU backends (unless
    ``MVTPU_FORCE_FLASH``) the jnp streaming path computes the same pair.
    ``causal=True`` requires Tq == Tk (aligned diagonal), matching the
    kernel's contract.
    """
    import os

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    bq = _flash_block(Tq, cap=512, head_dim=D)
    bk = _flash_block(Tk, cap=1024, head_dim=D)
    on_tpu = jax.default_backend() == "tpu"
    force = os.environ.get("MVTPU_FORCE_FLASH", "")
    if (bq and bk and not os.environ.get("MVTPU_NO_FLASH")
            and (on_tpu or force)):
        from ..ops import flash_attention

        return flash_attention(q, k, v, scale=scale, causal=causal,
                               block_q=bq, block_k=bk,
                               interpret=not on_tpu, return_lse=True)
    o = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full((B, H, Tq, 1), _NEG, jnp.float32)
    l = jnp.zeros((B, H, Tq, 1), jnp.float32)
    o, m, l = _online_block(q, k, v, o, m, l, jnp.arange(Tq),
                            jnp.arange(Tk), scale, causal)
    lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype), lse


def _combine_pieces(o_acc, lse_acc, o_i, lse_i):
    """Fold one (o, lse) piece into the float32 accumulators."""
    new_lse = jnp.logaddexp(lse_acc, lse_i)
    o_acc = (o_acc * jnp.exp(lse_acc - new_lse)[..., None]
             + o_i.astype(jnp.float32) * jnp.exp(lse_i - new_lse)[..., None])
    return o_acc, new_lse


def _empty_piece(q):
    """A contributes-nothing piece (fully masked ring step)."""
    return (jnp.zeros(q.shape, q.dtype),
            jnp.full(q.shape[:3], _NEG, jnp.float32))


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                   causal: bool = True,
                   batch_axis: Optional[str] = "dp",
                   head_axis: Optional[str] = "tp",
                   scale: Optional[float] = None,
                   layout: str = "auto"):
    """Causal self-attention with sequences sharded over ``axis_name``.

    ``q``/``k``/``v``: [B, H, T_global, D] jax.Arrays (sharded or not —
    shard_map re-lays them: batch over ``batch_axis``, heads over
    ``head_axis``, sequence over ``axis_name``).  Returns [B, H, T, D]
    with the same layout.  The streaming softmax accumulates statistics and
    output in float32 regardless of the compute dtype, so bf16 inputs see
    only the block-matmul rounding, not compounded per-ring-step error.

    ``layout``: ``"contiguous"`` gives each device one contiguous sequence
    block — simple, but under causal masking low-rank devices burn most
    ring steps on fully-masked blocks.  ``"zigzag"`` gives each device the
    chunk pair (d, 2*sp-1-d), which balances causal work exactly: every
    non-self ring step computes two fully-unmasked c x c sub-blocks — half
    the FLOPs of the contiguous schedule — at the cost of one global
    sequence permutation on the way in and out.  ``"auto"`` picks zigzag
    for causal attention whenever 2*sp divides T.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    axes = dict(mesh.shape)
    sp = int(axes.get(axis_name, 1))
    b_ax = batch_axis if (batch_axis and batch_axis in axes) else None
    h_ax = head_axis if (head_axis and head_axis in axes) else None
    spec = P(b_ax, h_ax, axis_name if sp > 1 else None, None)

    if sp == 1 and b_ax is None and h_ax is None:
        return blockwise_attention_local(q, k, v, scale, causal)

    if layout not in ("auto", "zigzag", "contiguous"):
        raise ValueError(
            f"unknown layout '{layout}'; expected auto|zigzag|contiguous")
    T_global = q.shape[2]
    use_zigzag = (sp > 1 and causal and T_global % (2 * sp) == 0
                  and layout in ("auto", "zigzag"))
    if layout == "zigzag" and not use_zigzag:
        raise ValueError(
            f"zigzag layout needs sp > 1 (got {sp}), causal=True (got "
            f"{causal}), and T ({T_global}) divisible by 2*sp ({2 * sp})")

    if use_zigzag:
        c = T_global // (2 * sp)
        perm = np.concatenate(
            [np.r_[d * c:(d + 1) * c,
                   (2 * sp - 1 - d) * c:(2 * sp - d) * c]
             for d in range(sp)])
        inv_perm = np.argsort(perm)
        q = jnp.take(q, perm, axis=2)
        k = jnp.take(k, perm, axis=2)
        v = jnp.take(v, perm, axis=2)

    def local_contiguous(q_l, k_l, v_l):
        B, H, T, D = q_l.shape
        if sp == 1:
            return blockwise_attention_local(q_l, k_l, v_l, scale, causal)
        idx = jax.lax.axis_index(axis_name)
        o_acc = jnp.zeros(q_l.shape, jnp.float32)
        lse_acc = jnp.full((B, H, T), _NEG, jnp.float32)
        ring = [(j, (j + 1) % sp) for j in range(sp)]

        def body(i, carry):
            o_acc, lse_acc, k_blk, v_blk = carry
            src = (idx - i) % sp          # owner of the current K/V block
            if causal:
                # src == idx: aligned diagonal (causal kernel); src < idx:
                # every position valid (full kernel); src > idx: fully
                # masked — skip the matmuls entirely.
                o_i, lse_i = jax.lax.cond(
                    src == idx,
                    lambda kv: _attn_piece(q_l, kv[0], kv[1], scale, True),
                    lambda kv: jax.lax.cond(
                        src < idx,
                        lambda kv2: _attn_piece(q_l, kv2[0], kv2[1],
                                                scale, False),
                        lambda kv2: _empty_piece(q_l),
                        kv),
                    (k_blk, v_blk))
            else:
                o_i, lse_i = _attn_piece(q_l, k_blk, v_blk, scale, False)
            o_acc, lse_acc = _combine_pieces(o_acc, lse_acc, o_i, lse_i)
            # rotate AFTER consuming; the last rotation is harmless and
            # keeps the loop body uniform (XLA overlaps it with compute)
            k_blk = jax.lax.ppermute(k_blk, axis_name, ring)
            v_blk = jax.lax.ppermute(v_blk, axis_name, ring)
            return o_acc, lse_acc, k_blk, v_blk

        o_acc, lse_acc, _, _ = jax.lax.fori_loop(
            0, sp, body, (o_acc, lse_acc, k_l, v_l))
        return o_acc.astype(q_l.dtype)

    def local_zigzag(q_l, k_l, v_l):
        B, H, T, D = q_l.shape                      # T == 2c
        idx = jax.lax.axis_index(axis_name)
        o_acc = jnp.zeros(q_l.shape, jnp.float32)
        lse_acc = jnp.full((B, H, T), _NEG, jnp.float32)
        ring = [(j, (j + 1) % sp) for j in range(sp)]

        def self_step(k_blk, v_blk):
            # Own chunk pair (low, high): low attends k_low causally;
            # high attends k_low fully and k_high causally — three
            # aligned kernel pieces, no bespoke mask.
            ql, qh = q_l[:, :, :c], q_l[:, :, c:]
            kl, kh = k_blk[:, :, :c], k_blk[:, :, c:]
            vl, vh = v_blk[:, :, :c], v_blk[:, :, c:]
            o_lo, lse_lo = _attn_piece(ql, kl, vl, scale, True)
            o_h1, lse_h1 = _attn_piece(qh, kl, vl, scale, False)
            o_h2, lse_h2 = _attn_piece(qh, kh, vh, scale, True)
            o_hi, lse_hi = _combine_pieces(o_h1.astype(jnp.float32),
                                           lse_h1, o_h2, lse_h2)
            return (jnp.concatenate([o_lo.astype(jnp.float32), o_hi], 2)
                    .astype(q_l.dtype),
                    jnp.concatenate([lse_lo, lse_hi], axis=2))

        def low_step(k_blk, v_blk):
            # src < idx: BOTH local chunks attend to src's LOW chunk only;
            # every score is valid — no mask, half the block FLOPs.
            return _attn_piece(q_l, k_blk[:, :, :c], v_blk[:, :, :c],
                               scale, False)

        def high_step(k_blk, v_blk):
            # src > idx: only the local HIGH chunk attends, to BOTH of
            # src's chunks; every score is valid — no mask.
            o_hi, lse_hi = _attn_piece(q_l[:, :, c:], k_blk, v_blk,
                                       scale, False)
            o_lo, lse_lo = _empty_piece(q_l[:, :, :c])
            return (jnp.concatenate([o_lo, o_hi], axis=2),
                    jnp.concatenate([lse_lo, lse_hi], axis=2))

        def body(i, carry):
            o_acc, lse_acc, k_blk, v_blk = carry
            src = (idx - i) % sp
            o_i, lse_i = jax.lax.cond(
                i == 0,
                lambda kv: self_step(*kv),
                lambda kv: jax.lax.cond(
                    src < idx,
                    lambda kv2: low_step(*kv2),
                    lambda kv2: high_step(*kv2),
                    kv),
                (k_blk, v_blk))
            o_acc, lse_acc = _combine_pieces(o_acc, lse_acc, o_i, lse_i)
            k_blk = jax.lax.ppermute(k_blk, axis_name, ring)
            v_blk = jax.lax.ppermute(v_blk, axis_name, ring)
            return o_acc, lse_acc, k_blk, v_blk

        o_acc, lse_acc, _, _ = jax.lax.fori_loop(
            0, sp, body, (o_acc, lse_acc, k_l, v_l))
        return o_acc.astype(q_l.dtype)

    local = local_zigzag if use_zigzag else local_contiguous
    out = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check_vma=False)(q, k, v)
    if use_zigzag:
        out = jnp.take(out, inv_perm, axis=2)
    return out
