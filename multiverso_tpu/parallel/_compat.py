"""jax version compatibility for the parallel layer.

``shard_map`` moved from ``jax.experimental.shard_map`` to the ``jax``
top level, and its replication-check kwarg was renamed ``check_rep`` →
``check_vma`` along the way.  Callers here use the modern spelling;
this shim maps it back on older jax.
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax keeps it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = inspect.signature(_shard_map).parameters
_HAS_CHECK_VMA = "check_vma" in _PARAMS


def shard_map(f, **kwargs):
    if not _HAS_CHECK_VMA and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs)
