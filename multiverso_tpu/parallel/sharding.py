"""Mesh + sharding helpers.

This is where the reference's server-shard placement logic
(``WorkerTable::Partition`` splitting requests across server processes;
SURVEY.md §2.10) becomes declarative: a table picks a ``NamedSharding`` and
XLA materializes the partitioning and the collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "table_mesh", "replicated", "shard_along",
           "host_to_global", "batch_placer"]

_SHARD_AXIS = "shard"


def make_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str],
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a named device mesh, e.g. ``make_mesh((2, 4), ("dp", "tp"))``."""
    if devices is None:
        devices = jax.devices()
    n = int(np.prod(axis_sizes))
    if n != len(devices):
        raise ValueError(
            f"mesh {tuple(axis_sizes)} needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices).reshape(tuple(axis_sizes))
    return Mesh(arr, tuple(axis_names))


def table_mesh(mesh: Optional[Mesh] = None) -> Mesh:
    """1-D mesh over *all* devices used for table sharding.

    Tables always shard over the flattened device list — the analog of the
    reference sharding every table across every server process regardless of
    app topology.  Independent of whatever multi-axis mesh the app uses for
    its compute step.
    """
    if mesh is not None:
        devices = mesh.devices.flatten()
    else:
        devices = np.asarray(jax.devices())
    return Mesh(devices, (_SHARD_AXIS,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_along(mesh: Mesh, ndim: int, dim: int = 0,
                axis: str = _SHARD_AXIS) -> NamedSharding:
    """Shard dimension ``dim`` of an ndim-array along ``axis``; rest replicated."""
    spec = [None] * ndim
    spec[dim] = axis
    return NamedSharding(mesh, P(*spec))


def host_to_global(x: np.ndarray, sharding: NamedSharding) -> jax.Array:
    """Place a host array onto devices with the given sharding."""
    return jax.device_put(x, sharding)


def batch_placer(mesh: Mesh, batch_axis: str = "worker", dtype=None):
    """Resolve the data-parallel axis and build a batch-placing closure.

    Shared by the apps' fused steps: dim 0 of each input shards over the
    mesh's ``batch_axis`` (falling back to the mesh's first axis); a batch
    whose leading dim isn't divisible by the axis size is replicated instead
    (correct, just unsharded).  Returns ``(axis_name, place)``.
    """
    import jax.numpy as jnp

    axis = batch_axis if batch_axis in mesh.shape else list(mesh.shape)[0]
    n = int(mesh.shape[axis])
    rep = replicated(mesh)

    def place(a):
        a = jnp.asarray(a) if dtype is None else jnp.asarray(a, dtype)
        if a.shape[0] % n:
            return jax.device_put(a, rep)
        return jax.device_put(a, shard_along(mesh, a.ndim, 0, axis))

    return axis, place
