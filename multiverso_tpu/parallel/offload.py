"""OffloadedState — double-buffered async host bridge for ZeRO-style
offload (docs/host_bridge.md).

A flat float32 state vector lives on a native array table under the
``assign`` updater (``-updater_type=assign``): ``push()`` overwrites the
remote copy with the caller's bits verbatim, ``wait()`` returns the bits
exactly as pushed — the bridge is a bit-exact remote store, which is
what lets an offloaded trainer's loss trajectory match the in-memory
baseline bit for bit (``make bridge-demo``).

The overlap protocol (per step ``i``)::

    state = off.wait()        # arena buffer filled by step i-1's prefetch
    new   = compute(state)    # device/host compute
    off.push(new)             # ASYNC assign-add: wire overlaps compute
    off.prefetch()            # async get into the OTHER buffer

All four buffers (two get destinations, two push stagings) come from
the runtime's :class:`~multiverso_tpu.native.HostArena`, so pushes ship
zero-copy into the scatter-gather send path and gets land replies
straight into the buffer ``wait()`` hands back.  Correct reuse is
guaranteed by wire FIFO: a prefetch issued after a push completes only
after the push was applied (Get flushes and rides behind Adds on the
same connection), so by the time ``wait()`` returns, the previous
push's borrow has drained and its staging buffer is reusable.

``backend="local"`` swaps the native runtime for an in-process numpy
dict performing the IDENTICAL float32 arithmetic — the control arm of
the bit-exactness demo and a dependency-free fallback for tests.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import numpy as np

from .. import metrics, tracing

__all__ = ["OffloadedState"]


class _LocalStore:
    """In-process stand-in for the native assign table: the same
    float32 store semantics with zero wire — the demo's control arm."""

    def __init__(self, size: int):
        self._data = np.zeros(size, np.float32)

    def assign(self, vec: np.ndarray) -> None:
        self._data[:] = vec

    def fetch(self, out: np.ndarray) -> np.ndarray:
        np.copyto(out, self._data)
        return out


class OffloadedState:
    """Double-buffered bridge to a remote (or local) flat f32 store.

    ``rt``: a :class:`~multiverso_tpu.native.NativeRuntime` whose fleet
    runs ``-updater_type=assign`` (the bridge asserts this on the first
    roundtrip by construction: a non-assign updater would fail the
    read-back check in ``init()``).  ``backend="local"`` needs no
    runtime at all.
    """

    def __init__(self, rt: Optional[Any], size: int, *,
                 backend: str = "native"):
        self.size = int(size)
        self.backend = backend
        self._pending = None          # in-flight AsyncGet (or None)
        self._step = 0
        if backend == "local":
            self._store = _LocalStore(self.size)
            self._get_bufs = [np.zeros(self.size, np.float32)
                              for _ in range(2)]
            self._push_bufs = [np.zeros(self.size, np.float32)
                               for _ in range(2)]
            self._rt = None
            self._arena = None
            self.handle = -1
        elif backend == "native":
            if rt is None:
                raise ValueError("backend='native' needs a NativeRuntime")
            self._rt = rt
            self._arena = rt.arena()
            self.handle = rt.new_array_table(self.size)
            self._get_bufs = [self._arena.alloc(self.size)
                              for _ in range(2)]
            self._push_bufs = [self._arena.alloc(self.size)
                               for _ in range(2)]
        else:
            raise ValueError(f"unknown backend '{backend}'")
        self._get_slot = 0

    # ------------------------------------------------------------ seeding
    def init(self, vec) -> None:
        """Blocking seed: store ``vec`` and verify the read-back is
        bit-identical — which also fails fast when the runtime's
        updater is not ``assign`` (an accumulate would double on the
        probe)."""
        v = np.ascontiguousarray(vec, np.float32).ravel()
        if v.size != self.size:
            raise ValueError(f"init vector has {v.size} elements, "
                             f"expected {self.size}")
        if self._pending is not None:
            self.wait()  # drain a pre-init prefetch: it predates `vec`
        self.push(v, blocking=True)
        self.push(v, blocking=True)  # idempotence probe: assign, not add
        got = self.wait()
        if got.tobytes() != v.tobytes():
            raise RuntimeError(
                "offload store round-trip is not bit-exact — is the "
                "native fleet running -updater_type=assign? "
                "(docs/host_bridge.md)")

    # ------------------------------------------------------------- bridge
    def push(self, vec, blocking: bool = False) -> None:
        """Ship ``vec`` (any f32 array-like of the right size) to the
        store.  Async by default: the copy into the arena staging
        buffer is the only host work; the wire rides behind the
        caller's next compute."""
        with tracing.span("bridge::push", n=self.size):
            staging = self._push_bufs[self._step % 2]
            self._step += 1
            src = np.asarray(vec, np.float32).reshape(-1)
            if src.size != self.size:
                raise ValueError(f"push vector has {src.size} elements, "
                                 f"expected {self.size}")
            np.copyto(staging, src)
            t0 = time.perf_counter()
            if self.backend == "local":
                self._store.assign(staging)
            else:
                self._rt.array_add(self.handle, staging, sync=blocking,
                                   borrowed=True)
            metrics.counter("bridge.push").inc()
            metrics.histogram("bridge.push_s").observe(
                time.perf_counter() - t0)

    def prefetch(self) -> None:
        """Start the async get for the NEXT ``wait()`` into the idle
        buffer.  FIFO on the table's connection orders it behind every
        push issued before it."""
        if self._pending is not None:
            return  # one outstanding prefetch at a time
        if self.backend == "local":
            self._pending = "local"
            return
        buf = self._get_bufs[self._get_slot]
        self._pending = self._rt.array_get_async(
            self.handle, self.size, out=buf, arena=self._arena)

    def wait(self) -> np.ndarray:
        """The current state vector — from the outstanding prefetch
        when one is in flight, else via a blocking fetch.  The returned
        array is the bridge's OWN buffer: treat it read-only and
        consume it before the next ``wait()`` reuses the slot."""
        with tracing.span("bridge::wait", n=self.size):
            t0 = time.perf_counter()
            buf = self._get_bufs[self._get_slot]
            if self.backend == "local":
                self._store.fetch(buf)
                self._pending = None
            elif self._pending is not None:
                got = self._pending.wait()
                self._pending = None
                # The reply landed in OUR buffer (out=buf) — same bytes,
                # possibly a distinct view object.
                assert (got.__array_interface__["data"][0]
                        == buf.__array_interface__["data"][0])
            else:
                self._rt.array_get(self.handle, self.size, out=buf)
            self._get_slot ^= 1  # next prefetch targets the other buffer
            metrics.histogram("bridge.wait_s").observe(
                time.perf_counter() - t0)
            return buf

    # ------------------------------------------------------------- admin
    def close(self) -> None:
        """Drop the in-flight prefetch (withdrawing its ticket) and
        release the arena buffers back to the pool."""
        if self._pending is not None and self.backend == "native":
            pending, self._pending = self._pending, None
            del pending  # __del__ cancels the ticket + frees the hold
        if self._arena is not None:
            for b in self._get_bufs + self._push_bufs:
                try:
                    self._arena.release(b)
                except Exception:
                    pass  # already released / interpreter teardown
            self._get_bufs = []
            self._push_bufs = []
