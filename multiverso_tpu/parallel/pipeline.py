"""Pipeline parallelism — GPipe over a mesh axis.

Not in the reference (a 2016 parameter server predates pipeline-parallel
training); included because PP completes this framework's parallelism
matrix (dp / tp / sp / ep / pp).

TPU-first design: the classic GPipe schedule expressed as pure SPMD —
``shard_map`` over the ``pp`` axis, stage weights stacked [pp, ...] and
sharded on the leading dim, and ONE ``lax.scan`` over
``num_micro + pp - 1`` ticks.  Every tick each stage applies its layers
to the activation it holds, then the activations rotate one stage
forward via ``ppermute`` (ICI neighbor exchange).  Stage 0 injects a
fresh microbatch per tick; the last stage banks its finished
microbatches.  Idle ticks (the pipeline bubble, (pp-1)/(M+pp-1) of the
work) compute on garbage and are masked out — the standard SPMD trade:
uniform code, no data-dependent control flow, XLA overlaps the permute
with compute.  Everything is differentiable: ``ppermute`` transposes to
the reverse rotation, so ``jax.grad`` yields exactly the backward
pipeline schedule.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from multiverso_tpu.parallel._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["gpipe", "stage_pspec"]


def stage_pspec(ndim: int, axis_name: str = "pp"):
    """PartitionSpec for stacked stage params: [pp, ...] over ``axis_name``."""
    return P(axis_name, *([None] * (ndim - 1)))


def gpipe(stage_fn: Callable[[Any, jax.Array], jax.Array],
          stage_params: Any, x: jax.Array, mesh: Mesh,
          axis_name: str = "pp",
          batch_axis: str | None = "dp",
          param_specs: Any = None,
          remat_stages: bool = False) -> jax.Array:
    """Run ``x`` through ``pp`` pipeline stages, microbatched.

    - ``stage_fn(params_slice, h) -> h``: one stage's compute (e.g. a
      scan over its layer block); must preserve ``h``'s shape/dtype.
    - ``stage_params``: pytree whose leaves lead with the stage dim
      [pp, ...] (sharded over ``axis_name`` — use :func:`stage_pspec`).
    - ``x``: [M, Bm, ...] microbatched input.  Returns [M, Bm, ...]
      outputs — microbatch m's activations after ALL pp stages.
    - ``batch_axis``: mesh axis the microbatch dim Bm is sharded over
      (data parallel inside each stage), or None.
    - ``param_specs``: optional pytree of per-leaf ``PartitionSpec``s for
      the *trailing* weight dims (e.g. tensor-parallel layouts like
      ``P(None, "tp")`` per layer); ``gpipe`` prepends the stage axis
      and pads unnamed middle dims.  With tp-sharded weights the stage
      body is manual SPMD over that axis too — ``stage_fn`` must psum
      its row-parallel matmul outputs (see
      ``models/transformer.py`` pp×tp).  Default: weights replicated on
      every non-stage axis; pp then composes with dp only.
    - ``remat_stages``: wrap each stage tick in ``jax.checkpoint``.
      Under ``jax.grad`` this gives the 1F1B *memory* profile without
      1F1B's manual fwd/bwd interleaving: plain GPipe-as-scan saves
      every stage's internal activations for all M microbatches
      (O(M·layers_per_stage) per device); with remat only each tick's
      stage INPUT survives to the backward sweep — and that is the
      rotation buffer the scan carries anyway — so live memory drops to
      the microbatched input [M, Bm, d] plus one in-flight activation,
      the same O(pp)-in-flight bound 1F1B schedules target.  The cost is
      one extra forward per stage in the backward sweep, which is the
      standard remat trade everywhere else in this framework.  (1F1B's
      remaining advantage, bubble shape under interleaved virtual
      stages, needs per-tick fwd/bwd mixing that fights ``jax.grad``'s
      reverse-of-forward schedule — documented as out of scope.)  Must
      run under ``jax.jit`` (``jax.checkpoint`` inside ``shard_map`` has
      no eager path).
    """
    pp = int(mesh.shape[axis_name])
    M = int(x.shape[0])
    b_ax = batch_axis if (batch_axis and batch_axis in mesh.shape) else None
    x_spec = P(None, b_ax, *([None] * (x.ndim - 2)))
    if param_specs is None:
        p_spec = jax.tree_util.tree_map(
            lambda l: stage_pspec(l.ndim, axis_name), stage_params)
    else:
        p_spec = jax.tree_util.tree_map(
            lambda l, spec: P(axis_name,
                              *([None] * (l.ndim - 1 - len(spec))),
                              *spec),
            stage_params, param_specs,
            is_leaf=lambda t: isinstance(t, P))
    ring = [(s, (s + 1) % pp) for s in range(pp)]
    tick_fn = jax.checkpoint(stage_fn) if remat_stages else stage_fn

    def local(params_s, x_all):
        # params_s leaves: [1, ...] (this stage's slice); drop the dim.
        params_s = jax.tree_util.tree_map(lambda l: l[0], params_s)
        idx = jax.lax.axis_index(axis_name)
        buf = jnp.zeros_like(x_all[0])          # activation held right now
        outs = jnp.zeros_like(x_all)            # last stage's bank

        def tick(carry, t):
            buf, outs = carry
            # Stage 0 starts microbatch t (while t < M); other stages
            # work on what the previous tick's rotation handed them.
            inject = x_all[jnp.minimum(t, M - 1)]
            h = jnp.where(idx == 0, inject, buf)
            h = tick_fn(params_s, h)
            m = t - idx                         # microbatch this stage did
            bank = (idx == pp - 1) & (m >= 0) & (m < M)
            # Mask the ROW, not the whole bank — a full-buffer where()
            # would copy [M, Bm, d] every tick and defeat aliasing.
            pos = jnp.clip(m, 0, M - 1)
            outs = outs.at[pos].set(jnp.where(bank, h, outs[pos]))
            buf = jax.lax.ppermute(h, axis_name, ring)
            return (buf, outs), None

        (_, outs), _ = jax.lax.scan(tick, (buf, outs),
                                    jnp.arange(M + pp - 1))
        # Only the last stage holds real outputs; replicate over pp so
        # the caller sees one logical array (psum of one-hot banks).
        outs = jax.lax.psum(
            jnp.where(idx == pp - 1, outs, jnp.zeros_like(outs)),
            axis_name)
        return outs

    return shard_map(local, mesh=mesh, in_specs=(p_spec, x_spec),
                     out_specs=x_spec, check_vma=False)(stage_params, x)
