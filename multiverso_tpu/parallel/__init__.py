from .offload import OffloadedState
from .pipeline import gpipe, stage_pspec
from .sharding import (
    make_mesh,
    table_mesh,
    replicated,
    shard_along,
    host_to_global,
)

__all__ = [
    "make_mesh",
    "table_mesh",
    "replicated",
    "shard_along",
    "host_to_global",
    "gpipe",
    "stage_pspec",
    "OffloadedState",
]
