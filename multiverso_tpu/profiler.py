"""Always-on sampling profiler — the Python half
(docs/observability.md "latency plane").

Two samplers, one output shape:

- :class:`SamplingProfiler` — a daemon thread that samples EVERY Python
  thread's stack via ``sys._current_frames()`` at a fixed rate (no
  ``sys.setprofile``: tracing hooks tax every function call everywhere;
  a sampler taxes nothing between samples, which is what makes
  always-on viable).  Aggregates folded stacks
  (``outer;...;leaf count``).
- :func:`add_native_profile` — folds the NATIVE SIGPROF sampler's dump
  (``NativeRuntime.profiler_dump()``, same folded convention) in.

Both land in the Chrome trace via :func:`profile_to_spans`: each
distinct stack becomes one synthetic span whose duration is
``samples x period`` on a dedicated ``profile`` lane, so flame data
sits beside the request spans in ``trace_rank<r>.json`` and survives
``tracing.merge_dir`` like any other event.  Armed at ``init()`` by the
``-profile_hz`` flag; the overhead bar (``bench_latency``'s
``profiler_overhead_pct < 1``) is measured, not assumed.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter
from typing import Any, Dict, Optional

from . import tracing
from .log import Log

__all__ = ["SamplingProfiler", "parse_folded", "add_native_profile",
           "profile_to_spans", "start", "stop", "active"]

# Synthetic-span lane: keeps flame rows visually apart from real spans
# in Perfetto (tid is only a lane label in the Chrome trace format).
PROFILE_TID = 0xFADE


class SamplingProfiler:
    """Sampler thread over ``sys._current_frames()``.

    ``hz`` bounds the sampling cost: each tick walks every live
    thread's stack once (a few µs per thread) and bumps one Counter
    entry — there is no per-call hook anywhere.  The sampler SKIPS its
    own thread (it would otherwise be the hottest stack in an idle
    process)."""

    def __init__(self, hz: int = 97, max_depth: int = 48):
        self.period_s = 1.0 / max(1, int(hz))
        self.hz = max(1, int(hz))
        self.max_depth = int(max_depth)
        self._folded: Counter = Counter()
        self._samples = 0
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ control
    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="mvtpu-profiler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        t, self._thread = self._thread, None
        if t is None:
            return
        self._stop_evt.set()
        t.join(timeout=5.0)
        if t.is_alive():
            Log.error("profiler: sampler thread did not stop within 5s")

    @property
    def running(self) -> bool:
        return self._thread is not None

    # ----------------------------------------------------------- sampling
    def _run(self) -> None:
        me = threading.get_ident()
        while not self._stop_evt.wait(self.period_s):
            try:
                frames = sys._current_frames()
            except Exception:  # interpreter shutting down
                return
            with self._lock:
                for tid, frame in frames.items():
                    if tid == me:
                        continue
                    stack = []
                    depth = 0
                    while frame is not None and depth < self.max_depth:
                        code = frame.f_code
                        stack.append(f"{code.co_name} "
                                     f"({code.co_filename.rsplit('/', 1)[-1]}"
                                     f":{frame.f_lineno})")
                        frame = frame.f_back
                        depth += 1
                    # Innermost-first walk -> outermost-first folded key.
                    self._folded[";".join(reversed(stack))] += 1
                    self._samples += 1

    # ------------------------------------------------------------ results
    @property
    def samples(self) -> int:
        with self._lock:
            return self._samples

    def folded(self) -> Dict[str, int]:
        """``{"outer;...;leaf": samples}`` — the flamegraph folded
        shape, identical to the native ``MV_ProfilerDump`` lines."""
        with self._lock:
            return dict(self._folded)

    def clear(self) -> None:
        with self._lock:
            self._folded.clear()
            self._samples = 0


def parse_folded(text: str) -> Dict[str, int]:
    """Parse folded-stack lines (``stack count``) into a dict — the
    native ``MV_ProfilerDump`` wire shape."""
    out: Dict[str, int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count = line.rpartition(" ")
        if not stack:
            continue
        try:
            out[stack] = out.get(stack, 0) + int(count)
        except ValueError:
            continue
    return out


def profile_to_spans(folded: Dict[str, int], period_s: float,
                     plane: str = "python") -> int:
    """Land flame data in the trace buffer beside the spans: each
    distinct stack becomes one synthetic ``profile:<leaf>`` span whose
    duration is ``samples x period`` (the CPU time it represents), on
    the dedicated profile lane.  Returns the span count recorded (0
    when tracing is disarmed — same contract as every span source)."""
    if not tracing.enabled():
        return 0
    ts_us = int(time.time() * 1e6)
    n = 0
    for stack, count in sorted(folded.items(),
                               key=lambda kv: -kv[1]):
        leaf = stack.rsplit(";", 1)[-1]
        tracing.record_span(
            f"profile:{leaf}", ts_us,
            int(count * period_s * 1e6), trace_id=0,
            args={"stack": stack, "samples": count,
                  "plane": f"profiler/{plane}"})
        n += 1
    return n


def add_native_profile(runtime: Any, hz: int = 97) -> int:
    """Fold the native SIGPROF sampler's dump into the trace buffer
    (``profile:*`` spans, ``plane=profiler/native``).  ``hz`` must
    match the rate the sampler ran at — it scales samples back into
    CPU time.  Returns the span count."""
    folded = parse_folded(runtime.profiler_dump())
    return profile_to_spans(folded, 1.0 / max(1, hz), plane="native")


# ---------------------------------------------------------------------------
# Module-level singleton, armed by init() via the -profile_hz flag.
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_ACTIVE: Optional[SamplingProfiler] = None


def start(hz: int = 97) -> SamplingProfiler:
    """Start (or return) the process-wide sampler at ``hz``."""
    global _ACTIVE
    with _LOCK:
        if _ACTIVE is None:
            _ACTIVE = SamplingProfiler(hz=hz).start()
        return _ACTIVE


def stop(to_trace: bool = True) -> Optional[SamplingProfiler]:
    """Stop the process-wide sampler; with ``to_trace`` (default) its
    folded stacks land in the trace buffer first, so the shutdown
    trace export carries the flame data."""
    global _ACTIVE
    with _LOCK:
        p, _ACTIVE = _ACTIVE, None
    if p is None:
        return None
    p.stop()
    if to_trace:
        profile_to_spans(p.folded(), p.period_s)
    return p


def active() -> Optional[SamplingProfiler]:
    return _ACTIVE
