--- multiverso_tpu Lua binding (LuaJIT FFI over the C API).
--
-- Capability parity with the reference's binding/lua/ Lua module
-- (SURVEY.md §2.33): init/shutdown/barrier, ids, and Array/Matrix table
-- handlers, loaded straight over libmvtpu.so's flat C surface
-- (native/include/mvtpu/c_api.h).  Usage:
--
--   package.path  = package.path .. ";<repo>/multiverso_tpu/binding/lua/?.lua"
--   local mv = require("multiverso")
--   mv.init({"-updater_type=sgd"})
--   local t = mv.ArrayTableHandler:new(100)
--   t:add(delta)                  -- delta: Lua array or FFI float[]
--   local w = t:get()             -- FFI float[size]
--   mv.barrier()
--   mv.shutdown()
--
-- Error convention: C rc < 0 raises a Lua error naming the call and rc
-- (rc=-3 means an unreachable peer / expired deadline — see c_api.h).
--
-- Contract-checked: tools/mvcontract.py (`make contract`) diffs every
-- prototype in the cdef block below against c_api.h (a deliberate
-- subset, but each cdef'd signature must match exactly).

local ffi = require("ffi")

ffi.cdef[[
int MV_Init(int argc, const char* const* argv);
int MV_ShutDown();
int MV_Barrier();
int MV_Clock();
int MV_NumWorkers();
int MV_WorkerId();
int MV_ServerId();
int MV_SetFlag(const char* name, const char* value);
int MV_NewArrayTable(int64_t size, int32_t* handle);
int MV_GetArrayTable(int32_t handle, float* data, int64_t size);
int MV_AddArrayTable(int32_t handle, const float* delta, int64_t size);
int MV_AddAsyncArrayTable(int32_t handle, const float* delta, int64_t size);
int MV_NewMatrixTable(int64_t rows, int64_t cols, int32_t* handle);
int MV_NewSparseMatrixTable(int64_t rows, int64_t cols, int32_t* handle);
int MV_GetMatrixTableAll(int32_t handle, float* data, int64_t size);
int MV_AddMatrixTableAll(int32_t handle, const float* delta, int64_t size);
int MV_AddAsyncMatrixTableAll(int32_t handle, const float* delta, int64_t size);
int MV_GetMatrixTableByRows(int32_t handle, float* data, const int32_t* row_ids,
                            int64_t num_rows, int64_t cols);
int MV_AddMatrixTableByRows(int32_t handle, const float* delta,
                            const int32_t* row_ids, int64_t num_rows,
                            int64_t cols);
int MV_AddAsyncMatrixTableByRows(int32_t handle, const float* delta,
                                 const int32_t* row_ids, int64_t num_rows,
                                 int64_t cols);
int MV_GetAsyncArrayTable(int32_t handle, float* data, int64_t size,
                          int32_t* wait_handle);
int MV_GetAsyncMatrixTableByRows(int32_t handle, float* data,
                                 const int32_t* row_ids, int64_t num_rows,
                                 int64_t cols, int32_t* wait_handle);
int MV_WaitGet(int32_t wait_handle);
int MV_CancelGet(int32_t wait_handle);
int MV_ArenaAcquire(int64_t bytes, void** ptr);
int MV_ArenaRelease(void* ptr);
int MV_ArenaStats(long long* buffers, long long* free_buffers,
                  long long* bytes, long long* in_flight,
                  long long* deferred, long long* recycled,
                  long long* pinned);
int MV_AddArrayTableBorrowed(int32_t handle, const float* delta,
                             int64_t size);
int MV_AddAsyncArrayTableBorrowed(int32_t handle, const float* delta,
                                  int64_t size);
int MV_GetArrayTableBorrowed(int32_t handle, float* data, int64_t size);
int MV_GetAsyncArrayTableBorrowed(int32_t handle, float* data,
                                  int64_t size, int32_t* wait_handle);
int MV_AddMatrixTableAllBorrowed(int32_t handle, const float* delta,
                                 int64_t size);
int MV_AddAsyncMatrixTableAllBorrowed(int32_t handle, const float* delta,
                                      int64_t size);
int MV_AddMatrixTableByRowsBorrowed(int32_t handle, const float* delta,
                                    const int32_t* row_ids,
                                    int64_t num_rows, int64_t cols);
int MV_AddAsyncMatrixTableByRowsBorrowed(int32_t handle,
                                         const float* delta,
                                         const int32_t* row_ids,
                                         int64_t num_rows, int64_t cols);
int MV_GetAsyncMatrixTableByRowsBorrowed(int32_t handle, float* data,
                                         const int32_t* row_ids,
                                         int64_t num_rows, int64_t cols,
                                         int32_t* wait_handle);
int MV_NewKVTable(int32_t* handle);
int MV_GetKV(int32_t handle, const char* key, float* value);
int MV_AddKV(int32_t handle, const char* key, float delta);
int MV_AddAsyncKV(int32_t handle, const char* key, float delta);
int MV_GetKVBatch(int32_t handle, const char* keys, const int32_t* key_lens,
                  int64_t num_keys, float* values);
int MV_AddKVBatch(int32_t handle, const char* keys, const int32_t* key_lens,
                  int64_t num_keys, const float* deltas);
int MV_SetAddOption(float learning_rate, float momentum, float rho, float eps);
int MV_StoreTable(int32_t handle, const char* path);
int MV_LoadTable(int32_t handle, const char* path);
int MV_QueryMonitor(const char* name, long long* count);
int MV_TableVersion(int32_t handle, long long* version);
int MV_LastVersion(int32_t handle, long long* version);
int MV_CacheStats(long long* hits, long long* misses);
int MV_ServeQueueDepth(void);
int MV_SetTraceEnabled(int on);
int MV_SetTraceId(long long trace_id);
int MV_ClearSpans(void);
int MV_SetFault(const char* kind, double rate);
int MV_SetFaultN(const char* kind, long long n);
int MV_SetFaultSeed(long long seed);
int MV_ClearFaults(void);
int MV_DeadPeerCount(void);
int MV_SetReplication(int on);
long long MV_RoutingEpoch(void);
int MV_ShardOwner(int shard_idx);
int MV_BackupShard(void);
int MV_PromoteBackup(int dead_rank);
int MV_ReplJoin(int shard_idx);
int MV_ReplicationStats(long long* forwards, long long* acks,
                        long long* applied, long long* outstanding,
                        long long* promotions, long long* epoch_flips,
                        long long* dup_skips, long long* catchups);
int MV_SetTableCodec(int32_t handle, const char* codec);
int MV_FlushAdds(int32_t handle);
int MV_WireStats(long long* sent_bytes, long long* recv_bytes,
                 long long* sent_msgs, long long* recv_msgs);
char* MV_NetEngine(void);
int MV_UringSupported(void);
void MV_FreeString(char* s);
int MV_FanInStats(long long* accepted_total, long long* active_clients,
                  long long* client_shed);
char* MV_OpsReport(const char* kind);
int MV_SetOpsHostMetrics(const char* prom_text);
int MV_BlackboxEvent(const char* kind, const char* detail);
int MV_BlackboxTrigger(const char* reason);
char* MV_HotKeys(int32_t handle);
int MV_TableLoadStats(int32_t handle, long long* gets, long long* adds,
                      double* skew_ratio, double* add_l2,
                      double* add_linf, long long* nan_count,
                      long long* inf_count);
int MV_SetHotKeyTracking(int on);
char* MV_CapacityReport(void);
int MV_SetCapacityTracking(int on);
int MV_SetHotKeyReplica(int on);
int MV_ReplicaRefresh(int32_t handle);
int MV_ReplicaStats(int32_t handle, long long* hits, long long* misses,
                    long long* rows, long long* refreshes,
                    long long* pushes);
char* MV_OpsFleetReport(const char* kind);
int MV_SetWireTiming(int on);
int MV_SetAudit(int on);
int MV_ClockOffset(int rank, long long* offset_ns, long long* rtt_ns);
int MV_SetProfiler(int hz);
char* MV_ProfilerDump(void);
int MV_ProfilerClear(void);
int MV_SetOpsHostAlerts(const char* alerts_json);
int MV_SetWatchdog(int stall_ms);
int MV_WatchdogBump(const char* loop);
int MV_WatchdogBusy(const char* loop, long long queued);
char* MV_WatchdogStats(void);
]]

-- libmvtpu.so sits two directories up from this file (native/build/).
local function lib_path()
  local src = debug.getinfo(1, "S").source:sub(2)
  local here = src:match("(.*)/") or "."
  return here .. "/../../native/build/libmvtpu.so"
end

local C = ffi.load(os.getenv("MVTPU_NATIVE_LIB") or lib_path())

local mv = {}

local function check(rc, what)
  if rc < 0 then
    error(string.format("%s failed with rc=%d", what, rc))
  end
  return rc
end

--- Convert a Lua array (or pass through an FFI array) to float[n].
local function to_floats(data, n)
  if type(data) == "cdata" then return data end
  local buf = ffi.new("float[?]", n)
  for i = 1, n do buf[i - 1] = data[i] end
  return buf
end

local function to_ints(data, n)
  if type(data) == "cdata" then return data end
  local buf = ffi.new("int32_t[?]", n)
  for i = 1, n do buf[i - 1] = data[i] end
  return buf
end

--- init(args): args is an optional Lua array of "-flag=value" strings.
function mv.init(args)
  args = args or {}
  local argv = ffi.new("const char*[?]", #args)
  for i = 1, #args do argv[i - 1] = args[i] end
  check(C.MV_Init(#args, argv), "MV_Init")
end

function mv.shutdown() check(C.MV_ShutDown(), "MV_ShutDown") end
function mv.barrier() check(C.MV_Barrier(), "MV_Barrier") end
--- SSP clock tick (see c_api.h MV_Clock / the -staleness flag).
function mv.clock() check(C.MV_Clock(), "MV_Clock") end
function mv.num_workers() return C.MV_NumWorkers() end
function mv.worker_id() return C.MV_WorkerId() end
function mv.server_id() return C.MV_ServerId() end

function mv.set_flag(name, value)
  check(C.MV_SetFlag(name, tostring(value)), "MV_SetFlag")
end

function mv.set_add_option(lr, momentum, rho, eps)
  check(C.MV_SetAddOption(lr or 0.1, momentum or 0.9, rho or 0.9,
                          eps or 1e-8), "MV_SetAddOption")
end

--- Hit count of a Dashboard monitor (0 when it never fired) — e.g.
--- "net.retries" / "net.dropped" / "hb.missed" (docs/fault_tolerance.md).
function mv.query_monitor(name)
  local c = ffi.new("long long[1]")
  check(C.MV_QueryMonitor(name, c), "MV_QueryMonitor")
  return tonumber(c[0])
end

--- Serve layer (docs/serving.md): version probe — the table's current
--- max server-side version in ONE header-only round trip (the cheap
--- cache-validation alternative to a full fetch).  rc -6 = the server
--- shed the probe under -server_inflight_max backpressure (retryable).
function mv.table_version(handle)
  local v = ffi.new("long long[1]")
  check(C.MV_TableVersion(handle, v), "MV_TableVersion")
  return tonumber(v[0])
end

--- Highest version stamp observed in any reply to this process — a
--- free local lower bound on the server version (no wire traffic).
function mv.last_version(handle)
  local v = ffi.new("long long[1]")
  check(C.MV_LastVersion(handle, v), "MV_LastVersion")
  return tonumber(v[0])
end

--- Native worker-side row-cache counters: returns hits, misses.
function mv.cache_stats()
  local h = ffi.new("long long[1]")
  local m = ffi.new("long long[1]")
  check(C.MV_CacheStats(h, m), "MV_CacheStats")
  return tonumber(h[0]), tonumber(m[0])
end

--- Server-actor mailbox backlog (the -server_inflight_max gauge).
function mv.serve_queue_depth()
  return check(C.MV_ServeQueueDepth(), "MV_ServeQueueDepth")
end

--- Span tracing (docs/observability.md): arm native span recording
--- (worker ops, server applies, wire sends share cross-rank trace ids;
--- dump via the C API's MV_DumpSpans from the host-side tooling).
function mv.set_trace_enabled(on)
  check(C.MV_SetTraceEnabled(on and 1 or 0), "MV_SetTraceEnabled")
end

--- Pin this thread's trace id for subsequent ops (0 = auto per-op ids).
function mv.set_trace_id(id)
  check(C.MV_SetTraceId(id), "MV_SetTraceId")
end

function mv.clear_spans() check(C.MV_ClearSpans(), "MV_ClearSpans") end

--- Fault injection (chaos testing; docs/fault_tolerance.md): kind is
--- drop|delay|dup|fail_send with a per-op probability, or delay_ms to
--- set the injected delay length; set_fault_n fires on exactly the
--- next n ops.  Deterministic under set_fault_seed.
function mv.set_fault(kind, rate)
  check(C.MV_SetFault(kind, rate), "MV_SetFault")
end

function mv.set_fault_n(kind, n)
  check(C.MV_SetFaultN(kind, n), "MV_SetFaultN")
end

function mv.set_fault_seed(seed)
  check(C.MV_SetFaultSeed(seed), "MV_SetFaultSeed")
end

function mv.clear_faults() check(C.MV_ClearFaults(), "MV_ClearFaults") end

--- Peers with expired heartbeat leases on THIS rank (-heartbeat_ms;
--- lease watching is symmetric — docs/replication.md).
function mv.dead_peer_count() return C.MV_DeadPeerCount() end

--- Shard replication + failover (docs/replication.md).
function mv.set_replication(on)
  check(C.MV_SetReplication(on and 1 or 0), "MV_SetReplication")
end

function mv.routing_epoch() return tonumber(C.MV_RoutingEpoch()) end

--- Rank currently serving shard `idx` per the routed map (-1 = bad).
function mv.shard_owner(idx) return C.MV_ShardOwner(idx) end

--- The shard index this rank backs (-1 = none).
function mv.backup_shard() return C.MV_BackupShard() end

--- Operator-driven promotion of this rank's backup shard(s) for a
--- dead rank; returns the number of shards promoted.
function mv.promote_backup(dead_rank)
  return C.MV_PromoteBackup(dead_rank)
end

--- Elastic join: become shard `idx`'s backup (announce + catch-up).
function mv.repl_join(idx) check(C.MV_ReplJoin(idx), "MV_ReplJoin") end

--- Replication ledger: {forwards, acks, applied, outstanding,
--- promotions, epoch_flips, dup_skips, catchups}.
function mv.replication_stats()
  local f = ffi.new("long long[1]")
  local a = ffi.new("long long[1]")
  local ap = ffi.new("long long[1]")
  local o = ffi.new("long long[1]")
  local p = ffi.new("long long[1]")
  local e = ffi.new("long long[1]")
  local d = ffi.new("long long[1]")
  local c = ffi.new("long long[1]")
  check(C.MV_ReplicationStats(f, a, ap, o, p, e, d, c),
        "MV_ReplicationStats")
  return {forwards = tonumber(f[0]), acks = tonumber(a[0]),
          applied = tonumber(ap[0]), outstanding = tonumber(o[0]),
          promotions = tonumber(p[0]), epoch_flips = tonumber(e[0]),
          dup_skips = tonumber(d[0]), catchups = tonumber(c[0])}
end

--- Wire data plane (docs/wire_compression.md): retarget one table's
--- payload codec — "raw" | "1bit" (sign bits + scales with worker-side
--- error feedback) | "sparse" (lossless nonzero pairs).  Tables start
--- on the -wire_codec flag's value.
function mv.set_table_codec(handle, codec)
  check(C.MV_SetTableCodec(handle, codec), "MV_SetTableCodec")
end

--- Drain the add-aggregation buffer (-add_agg_ms/-add_agg_bytes) of one
--- table, or of every table when handle is nil/negative.
function mv.flush_adds(handle)
  check(C.MV_FlushAdds(handle or -1), "MV_FlushAdds")
end

--- Host-bridge arena (docs/host_bridge.md): acquire a recycled,
--- 64-byte-aligned, best-effort-pinned host buffer of `bytes` bytes as
--- an FFI void*.  Caller-held until mv.arena_release(ptr); borrowed
--- sends started from it defer the recycle past their in-flight window.
function mv.arena_acquire(bytes)
  local p = ffi.new("void*[1]")
  check(C.MV_ArenaAcquire(bytes, p), "MV_ArenaAcquire")
  return p[0]
end

--- Release an arena buffer (safe mid-flight: recycling defers behind
--- in-flight borrows; rc -2 on a double release raises).
function mv.arena_release(ptr)
  check(C.MV_ArenaRelease(ptr), "MV_ArenaRelease")
end

--- Arena counters: buffers, free_buffers, bytes, in_flight, deferred,
--- recycled, pinned (see MV_ArenaStats).
function mv.arena_stats()
  local v = {}
  for i = 1, 7 do v[i] = ffi.new("long long[1]") end
  check(C.MV_ArenaStats(v[1], v[2], v[3], v[4], v[5], v[6], v[7]),
        "MV_ArenaStats")
  local out = {}
  for i = 1, 7 do out[i] = tonumber(v[i][0]) end
  return unpack(out)
end

--- Borrowed fast-path siblings (docs/host_bridge.md): `data` must lie
--- inside a live arena buffer (rc -7 raises otherwise) — adds ship the
--- bytes zero-copy into the scatter-gather send path; async gets hold
--- the buffer until the ticket is consumed.
function mv.add_array_borrowed(handle, data, size, async)
  if async then
    check(C.MV_AddAsyncArrayTableBorrowed(handle, data, size),
          "MV_AddAsyncArrayTableBorrowed")
  else
    check(C.MV_AddArrayTableBorrowed(handle, data, size),
          "MV_AddArrayTableBorrowed")
  end
end

function mv.get_array_borrowed(handle, data, size)
  check(C.MV_GetArrayTableBorrowed(handle, data, size),
        "MV_GetArrayTableBorrowed")
  return data
end


function mv.add_matrix_all_borrowed(handle, data, size, async)
  if async then
    check(C.MV_AddAsyncMatrixTableAllBorrowed(handle, data, size),
          "MV_AddAsyncMatrixTableAllBorrowed")
  else
    check(C.MV_AddMatrixTableAllBorrowed(handle, data, size),
          "MV_AddMatrixTableAllBorrowed")
  end
end

function mv.add_matrix_rows_borrowed(handle, data, row_ids, k, cols,
                                     async)
  if async then
    check(C.MV_AddAsyncMatrixTableByRowsBorrowed(handle, data, row_ids,
                                                 k, cols),
          "MV_AddAsyncMatrixTableByRowsBorrowed")
  else
    check(C.MV_AddMatrixTableByRowsBorrowed(handle, data, row_ids, k,
                                            cols),
          "MV_AddMatrixTableByRowsBorrowed")
  end
end


--- Transport byte/frame ledger: returns sent_bytes, recv_bytes,
--- sent_msgs, recv_msgs over the native wire (headers included).
function mv.wire_stats()
  local sb = ffi.new("long long[1]")
  local rb = ffi.new("long long[1]")
  local sm = ffi.new("long long[1]")
  local rm = ffi.new("long long[1]")
  check(C.MV_WireStats(sb, rb, sm, rm), "MV_WireStats")
  return tonumber(sb[0]), tonumber(rb[0]), tonumber(sm[0]), tonumber(rm[0])
end

--- Active (effective) wire engine (docs/transport.md): "tcp" |
--- "epoll" | "mpi" | "uring", or "local" for a single process with no
--- transport.  A -net_engine=uring request on a kernel without
--- io_uring degrades to epoll and reports "epoll" here.
function mv.net_engine()
  local p = C.MV_NetEngine()
  local name = ffi.string(p)
  C.MV_FreeString(p)
  return name
end

--- True when this kernel can run the io_uring engine.  Probes the
--- kernel, not the session — callable before mv.init.
function mv.uring_supported()
  return C.MV_UringSupported() ~= 0
end

--- Anonymous serve-tier fan-in counters (epoll engine only): returns
--- accepted_total, active_clients, client_shed — non-rank client
--- connections accepted, currently connected, and requests shed by
--- the per-client admission gate (-client_inflight_max).
function mv.fanin_stats()
  local a = ffi.new("long long[1]")
  local c = ffi.new("long long[1]")
  local s = ffi.new("long long[1]")
  check(C.MV_FanInStats(a, c, s), "MV_FanInStats")
  return tonumber(a[0]), tonumber(c[0]), tonumber(s[0])
end

--- Live introspection (docs/observability.md): this rank's ops report —
--- "metrics" (Prometheus text with exemplar trace ids), "health"
--- (JSON verdict) or "tables" (JSON per-table stats); the same payload
--- the in-band wire scrape (MsgType::OpsQuery) serves.
function mv.ops_report(kind)
  local p = C.MV_OpsReport(kind or "health")
  local text = ffi.string(p)
  C.MV_FreeString(p)
  return text
end

--- Push a host-rendered Prometheus document so in-band scrapes serve it
--- instead of the native-only fallback (empty string clears).
function mv.set_ops_host_metrics(text)
  check(C.MV_SetOpsHostMetrics(text or ""), "MV_SetOpsHostMetrics")
end

--- Flight recorder ("black box"): record one lifecycle event into the
--- bounded in-memory ring / dump ring + spans + monitor totals to
--- <trace_dir>/blackbox_rank<r>.json (native failure triggers — barrier
--- timeout, dead peer, shed storm — dump automatically).
function mv.blackbox_event(kind, detail)
  check(C.MV_BlackboxEvent(kind, detail or ""), "MV_BlackboxEvent")
end

function mv.blackbox_trigger(reason)
  check(C.MV_BlackboxTrigger(reason), "MV_BlackboxTrigger")
end

--- Workload plane (docs/observability.md): per-table hot-key / load
--- report as a JSON string (the in-band "hotkeys" OpsQuery payload).
--- handle >= 0 restricts to one table; nil/-1 reports every table.
function mv.hot_keys(handle)
  local p = C.MV_HotKeys(handle or -1)
  local text = ffi.string(p)
  C.MV_FreeString(p)
  return text
end

--- Numeric workload slice for one table: gets, adds, skew_ratio,
--- add_l2, add_linf, nan_count, inf_count.
function mv.table_load_stats(handle)
  local g = ffi.new("long long[1]")
  local a = ffi.new("long long[1]")
  local sk = ffi.new("double[1]")
  local l2 = ffi.new("double[1]")
  local li = ffi.new("double[1]")
  local nn = ffi.new("long long[1]")
  local inf = ffi.new("long long[1]")
  check(C.MV_TableLoadStats(handle, g, a, sk, l2, li, nn, inf),
        "MV_TableLoadStats")
  return tonumber(g[0]), tonumber(a[0]), tonumber(sk[0]),
         tonumber(l2[0]), tonumber(li[0]), tonumber(nn[0]),
         tonumber(inf[0])
end

--- Toggle the workload accounting live (boot value: -hotkey_enabled).
function mv.set_hotkey_tracking(on)
  check(C.MV_SetHotKeyTracking(on and 1 or 0), "MV_SetHotKeyTracking")
end

--- Capacity plane (docs/observability.md "capacity plane"): this
--- rank's capacity report as a JSON string — proc stats, arena /
--- write-queue / registered byte gauges, per-table resident bytes per
--- bucket and the bounded load-history ring (the in-band "capacity"
--- OpsQuery payload; tools/mvplan.py plans over the fleet scrape).
function mv.capacity_report()
  local p = C.MV_CapacityReport()
  local text = ffi.string(p)
  C.MV_FreeString(p)
  return text
end

--- Toggle the byte accounting live (boot value: -capacity_enabled);
--- re-arming resyncs every shard's counters with an exact walk.
function mv.set_capacity_tracking(on)
  check(C.MV_SetCapacityTracking(on and 1 or 0), "MV_SetCapacityTracking")
end

--- Toggle the hot-key read replica live (docs/embedding.md; boot
--- value: -hotkey_replica): matrix row gets consult the servers'
--- pushed top-K rows before the wire.
function mv.set_hotkey_replica(on)
  check(C.MV_SetHotKeyReplica(on and 1 or 0), "MV_SetHotKeyReplica")
end

--- Force one replica refresh round trip for a matrix table.
function mv.replica_refresh(handle)
  check(C.MV_ReplicaRefresh(handle), "MV_ReplicaRefresh")
end

--- Replica ledger for a matrix table: hits, misses, rows held,
--- refresh round trips, server-side pushes.
function mv.replica_stats(handle)
  local h = ffi.new("long long[1]")
  local m = ffi.new("long long[1]")
  local r = ffi.new("long long[1]")
  local f = ffi.new("long long[1]")
  local p = ffi.new("long long[1]")
  check(C.MV_ReplicaStats(handle, h, m, r, f, p), "MV_ReplicaStats")
  return tonumber(h[0]), tonumber(m[0]), tonumber(r[0]),
         tonumber(f[0]), tonumber(p[0])
end

--- Toggle wire-header timing trails live (latency attribution;
--- boot value: -wire_timing, docs/observability.md "latency plane").
function mv.set_wire_timing(on)
  check(C.MV_SetWireTiming(on and 1 or 0), "MV_SetWireTiming")
end

--- Toggle the delivery-audit plane live (acked-add ledgers, applied
--- watermarks, dup/reorder/gap anomaly rings; boot value: -audit,
--- docs/observability.md "audit plane").  mv.ops_report("audit")
--- serves the JSON books.
function mv.set_audit(on)
  check(C.MV_SetAudit(on and 1 or 0), "MV_SetAudit")
end

--- Best NTP-style clock-offset estimate for a peer rank: returns
--- offset_ns (peer clock ahead of ours), rtt_ns — or nil when no
--- timed round trip to that rank completed yet.
function mv.clock_offset(rank)
  local off = ffi.new("long long[1]")
  local rtt = ffi.new("long long[1]")
  local rc = C.MV_ClockOffset(rank, off, rtt)
  if rc == -2 then return nil end
  check(rc, "MV_ClockOffset")
  return tonumber(off[0]), tonumber(rtt[0])
end

--- (Re)arm the SIGPROF sampling profiler at hz (CPU-time sampling);
--- hz <= 0 stops it.  Boot value: the -profile_hz flag.
function mv.set_profiler(hz)
  check(C.MV_SetProfiler(hz or 97), "MV_SetProfiler")
end

--- Folded-stack aggregation of everything sampled so far (one
--- "outer;...;leaf count" line per distinct stack).
function mv.profiler_dump()
  local p = C.MV_ProfilerDump()
  local text = ffi.string(p)
  C.MV_FreeString(p)
  return text
end

--- Drop recorded profiler samples (per-phase A/B runs).
function mv.profiler_clear()
  check(C.MV_ProfilerClear(), "MV_ProfilerClear")
end

--- Push this host's health-plane alert document (JSON from the rule
--- evaluator) so the in-band "alerts" ops scrape serves it alongside
--- the native watchdog stats (empty/nil clears).
function mv.set_ops_host_alerts(text)
  check(C.MV_SetOpsHostAlerts(text or ""), "MV_SetOpsHostAlerts")
end

--- Arm the native stall watchdog (docs/observability.md): a loop that
--- reports queued work but makes no progress for stall_ms dumps folded
--- stacks into the blackbox.  0 disarms.
function mv.set_watchdog(stall_ms)
  check(C.MV_SetWatchdog(stall_ms or 0), "MV_SetWatchdog")
end

--- Record forward progress on a named host-side loop.
function mv.watchdog_bump(loop)
  check(C.MV_WatchdogBump(loop), "MV_WatchdogBump")
end

--- Report how much work a named loop currently has queued (0 = idle;
--- idle loops are never flagged as stalled).
function mv.watchdog_busy(loop, queued)
  check(C.MV_WatchdogBusy(loop, queued or 0), "MV_WatchdogBusy")
end

--- Per-loop watchdog stats as a JSON array (progress, queued, stalls,
--- stalled flag, seconds since last progress).
function mv.watchdog_stats()
  local p = C.MV_WatchdogStats()
  local text = ffi.string(p)
  C.MV_FreeString(p)
  return text
end

--- Fleet-scope ops report assembled by THIS rank over the rank wire
--- (works on every engine, anonymous ingress or not).
function mv.ops_fleet_report(kind)
  local p = C.MV_OpsFleetReport(kind or "health")
  local text = ffi.string(p)
  C.MV_FreeString(p)
  return text
end

-- Shared async-get handle (MV_GetAsync* wait tickets): wait() joins the
-- pull and returns the filled buffer; a FAILED wait replays its error
-- on retry (MV_WaitGet consumes the ticket either way, so re-calling
-- it would report a bogus rc=-2).  cancel() withdraws an un-waited
-- pull; wait() after cancel() raises instead of returning the unfilled
-- buffer.  The buffer carries an ffi.gc finalizer so a handle dropped
-- without wait()/cancel() withdraws its ticket BEFORE LuaJIT frees the
-- buffer a late shard reply would scatter into (the c_api.h buffer-
-- lifetime contract; mirrors the ctypes binding's __del__).
local function make_async_get(ticket, buf)
  local h = { _ticket = ticket, _done = false, _cancelled = false }
  h._buf = ffi.gc(buf, function()
    if not h._done and not h._cancelled then C.MV_CancelGet(ticket) end
  end)
  function h.wait()
    if h._cancelled then error("async get was cancelled", 2) end
    if not h._done then
      h._done = true
      local ok, err = pcall(check, C.MV_WaitGet(h._ticket), "MV_WaitGet")
      if not ok then h._err = err end
    end
    if h._err then error(h._err, 0) end
    return h._buf
  end
  function h.cancel()
    if not h._done and not h._cancelled then
      h._cancelled = true
      C.MV_CancelGet(h._ticket)
    end
  end
  return h
end

-- ---------------------------------------------------------------- Array

--- Async borrowed gets (docs/host_bridge.md): defined after
--- make_async_get so the wrappers close over the local.
function mv.get_array_async_borrowed(handle, data, size)
  local t = ffi.new("int32_t[1]")
  check(C.MV_GetAsyncArrayTableBorrowed(handle, data, size, t),
        "MV_GetAsyncArrayTableBorrowed")
  return make_async_get(t[0], data)
end

function mv.get_matrix_rows_async_borrowed(handle, data, row_ids, k,
                                           cols)
  local t = ffi.new("int32_t[1]")
  check(C.MV_GetAsyncMatrixTableByRowsBorrowed(handle, data, row_ids, k,
                                               cols, t),
        "MV_GetAsyncMatrixTableByRowsBorrowed")
  return make_async_get(t[0], data)
end

mv.ArrayTableHandler = {}
mv.ArrayTableHandler.__index = mv.ArrayTableHandler

function mv.ArrayTableHandler:new(size)
  local h = ffi.new("int32_t[1]")
  check(C.MV_NewArrayTable(size, h), "MV_NewArrayTable")
  return setmetatable({ handle = h[0], size = size }, self)
end

function mv.ArrayTableHandler:get()
  local buf = ffi.new("float[?]", self.size)
  check(C.MV_GetArrayTable(self.handle, buf, self.size), "MV_GetArrayTable")
  return buf
end

function mv.ArrayTableHandler:add(delta, opts)
  local buf = to_floats(delta, self.size)
  if opts and opts.async then
    check(C.MV_AddAsyncArrayTable(self.handle, buf, self.size),
          "MV_AddAsyncArrayTable")
  else
    check(C.MV_AddArrayTable(self.handle, buf, self.size),
          "MV_AddArrayTable")
  end
end

--- Non-blocking get: returns a handle whose wait() blocks for the
--- replies and returns the buffer (async pull in flight meanwhile —
--- see c_api.h MV_GetAsync*).  The buffer is owned by the handle; call
--- cancel() instead of dropping an un-waited handle.
function mv.ArrayTableHandler:get_async()
  local buf = ffi.new("float[?]", self.size)
  local w = ffi.new("int32_t[1]")
  check(C.MV_GetAsyncArrayTable(self.handle, buf, self.size, w),
        "MV_GetAsyncArrayTable")
  return make_async_get(w[0], buf)
end

function mv.ArrayTableHandler:store(path)
  check(C.MV_StoreTable(self.handle, path), "MV_StoreTable")
end

function mv.ArrayTableHandler:load(path)
  check(C.MV_LoadTable(self.handle, path), "MV_LoadTable")
end

-- --------------------------------------------------------------- Matrix

mv.MatrixTableHandler = {}
mv.MatrixTableHandler.__index = mv.MatrixTableHandler

function mv.MatrixTableHandler:new(rows, cols)
  local h = ffi.new("int32_t[1]")
  check(C.MV_NewMatrixTable(rows, cols, h), "MV_NewMatrixTable")
  return setmetatable({ handle = h[0], rows = rows, cols = cols }, self)
end

function mv.MatrixTableHandler:get()
  local n = self.rows * self.cols
  local buf = ffi.new("float[?]", n)
  check(C.MV_GetMatrixTableAll(self.handle, buf, n), "MV_GetMatrixTableAll")
  return buf
end

function mv.MatrixTableHandler:add(delta, opts)
  local n = self.rows * self.cols
  local buf = to_floats(delta, n)
  if opts and opts.async then
    check(C.MV_AddAsyncMatrixTableAll(self.handle, buf, n),
          "MV_AddAsyncMatrixTableAll")
  else
    check(C.MV_AddMatrixTableAll(self.handle, buf, n),
          "MV_AddMatrixTableAll")
  end
end

-- Sparse variant: worker-side row cache, same handler methods.
mv.SparseMatrixTableHandler = {}

function mv.SparseMatrixTableHandler:new(rows, cols)
  local h = ffi.new("int32_t[1]")
  check(C.MV_NewSparseMatrixTable(rows, cols, h), "MV_NewSparseMatrixTable")
  return setmetatable({ handle = h[0], rows = rows, cols = cols },
                      mv.MatrixTableHandler)
end

--- #x raises on cdata, so FFI-array callers must pass the count.
local function row_count(row_ids, k)
  if k then return k end
  assert(type(row_ids) ~= "cdata",
         "pass the row count when row_ids is an FFI array")
  return #row_ids
end

function mv.MatrixTableHandler:get_rows(row_ids, k)
  k = row_count(row_ids, k)
  local ids = to_ints(row_ids, k)
  local buf = ffi.new("float[?]", k * self.cols)
  check(C.MV_GetMatrixTableByRows(self.handle, buf, ids, k, self.cols),
        "MV_GetMatrixTableByRows")
  return buf
end

--- Non-blocking row pull; see ArrayTableHandler:get_async.
function mv.MatrixTableHandler:get_rows_async(row_ids, k)
  k = row_count(row_ids, k)
  local ids = to_ints(row_ids, k)
  local buf = ffi.new("float[?]", k * self.cols)
  local w = ffi.new("int32_t[1]")
  check(C.MV_GetAsyncMatrixTableByRows(self.handle, buf, ids, k,
                                       self.cols, w),
        "MV_GetAsyncMatrixTableByRows")
  return make_async_get(w[0], buf)
end

function mv.MatrixTableHandler:add_rows(row_ids, delta, opts, k)
  k = row_count(row_ids, k)
  local ids = to_ints(row_ids, k)
  local buf = to_floats(delta, k * self.cols)
  if opts and opts.async then
    check(C.MV_AddAsyncMatrixTableByRows(self.handle, buf, ids, k,
                                         self.cols),
          "MV_AddAsyncMatrixTableByRows")
  else
    check(C.MV_AddMatrixTableByRows(self.handle, buf, ids, k, self.cols),
          "MV_AddMatrixTableByRows")
  end
end

-- ------------------------------------------------------------------- KV

mv.KVTableHandler = {}
mv.KVTableHandler.__index = mv.KVTableHandler

function mv.KVTableHandler:new()
  local h = ffi.new("int32_t[1]")
  check(C.MV_NewKVTable(h), "MV_NewKVTable")
  return setmetatable({ handle = h[0] }, self)
end

--- get("key") -> number; absent keys read 0.
function mv.KVTableHandler:get(key)
  local v = ffi.new("float[1]")
  check(C.MV_GetKV(self.handle, key, v), "MV_GetKV")
  return v[0]
end

--- add("key", delta [, {async=true}])
function mv.KVTableHandler:add(key, delta, opts)
  if opts and opts.async then
    check(C.MV_AddAsyncKV(self.handle, key, delta), "MV_AddAsyncKV")
  else
    check(C.MV_AddKV(self.handle, key, delta), "MV_AddKV")
  end
end

--- Pack a Lua array of strings into (concatenated bytes, int32 lens).
local function pack_keys(keys)
  local blob = table.concat(keys)
  local lens = ffi.new("int32_t[?]", #keys)
  for i = 1, #keys do lens[i - 1] = #keys[i] end
  return blob, lens
end

--- get_batch({"k1", "k2", ...}) -> float[n] (absent keys read 0).
function mv.KVTableHandler:get_batch(keys)
  local blob, lens = pack_keys(keys)
  local vals = ffi.new("float[?]", #keys)
  check(C.MV_GetKVBatch(self.handle, blob, lens, #keys, vals),
        "MV_GetKVBatch")
  return vals
end

--- add_batch({"k1", ...}, deltas): deltas is a Lua array or float[n].
function mv.KVTableHandler:add_batch(keys, deltas)
  local blob, lens = pack_keys(keys)
  local buf = to_floats(deltas, #keys)
  check(C.MV_AddKVBatch(self.handle, blob, lens, #keys, buf),
        "MV_AddKVBatch")
end

function mv.KVTableHandler:store(path)
  check(C.MV_StoreTable(self.handle, path), "MV_StoreTable")
end

function mv.KVTableHandler:load(path)
  check(C.MV_LoadTable(self.handle, path), "MV_LoadTable")
end

return mv
